"""Fused-vs-unfused bulk-pass CPU A/B at the recorded headline configs
(ISSUE 7 acceptance): run bench.py twice per config — identical pinned
knobs, `BENCH_BULK_FUSED` flipped — and write the four rows plus the
computed speedups to `artifacts/fused_ab_r07.json`.

Configs are the two CPU rows PERF.md has tracked across rounds:

- 8 lanes,   be=8 fb=1 bc=1  (the round-4 fused-pop A/B config)
- 256 lanes, be=8 fb=1 bc=1  (the round-4/5 contended-box config)

Knobs are PINNED (no self-calibration) so the pair differs in exactly
one bit; every row still stamps its full config + telemetry, so the
artifact is self-describing. CPU-pinned: this is the evidence A/B —
the on-chip confirmation slot is chip-session stage 13.

Usage: python scripts_fused_ab.py [--quick]
  --quick drops the 256-lane pair (each 256-lane bench run costs
  minutes on the 1-core box).
"""

from __future__ import annotations

import json
import os
import os.path as osp
import subprocess
import sys

REPO = osp.dirname(osp.abspath(__file__))

# reps: the 8-lane timed window is seconds long on this box and its
# single-run numbers swing ~±10% — interleave fused/unfused reps and
# take per-arm medians so the recorded speedup is not one draw of that
# noise; the 256-lane window is long enough that one rep is stable
CONFIGS = [
    # 16 chunks: the 8-lane default window is seconds long and swings
    # ±20% run-to-run on this box — a 4x window + median-of-3 makes
    # the recorded speedup a measurement, not a draw
    {"name": "8lane_be8_fb1_bc1", "BENCH_NUM_ENVS": "8",
     "BENCH_NUM_CHUNKS": "16", "reps": 3},
    {"name": "256lane_be8_fb1_bc1", "BENCH_NUM_ENVS": "256", "reps": 1},
]

PINNED = {
    "JAX_PLATFORMS": "cpu",
    "BENCH_BULK_EVENTS": "8",
    "BENCH_FULFILL_BULK": "1",
    "BENCH_BULK_CYCLES": "1",
    # telemetry on: the A/B rows double as phase-rank inputs
    "BENCH_TELEMETRY": "1",
    # the analysis/memory stamps cost minutes per row on this box and
    # are identical across the pair — stamp once via the normal bench
    # path instead of four times here
    "BENCH_ANALYSIS": "0",
    "BENCH_MEMFIT": "0",
}


def run_row(extra_env: dict) -> dict | None:
    env = os.environ | PINNED | extra_env
    r = subprocess.run(
        [sys.executable, osp.join(REPO, "bench.py")],
        env=env, capture_output=True, text=True, timeout=3600,
    )
    for line in r.stdout.splitlines():
        line = line.strip()
        if line.startswith("{"):
            return json.loads(line)
    print(
        f"# fused_ab: no row (rc={r.returncode}): "
        f"{r.stderr.strip().splitlines()[-1:] if r.stderr else ''}",
        file=sys.stderr, flush=True,
    )
    return None


def main() -> int:
    quick = "--quick" in sys.argv
    out = {"configs": {}}
    for cfg in CONFIGS[: 1 if quick else None]:
        name = cfg["name"]
        reps = int(cfg.get("reps", 1))
        envs = {
            k: v for k, v in cfg.items() if k not in ("name", "reps")
        }
        rows = {"fused": [], "unfused": []}
        for rep in range(reps):
            # interleave arms so slow machine-state drift (page cache,
            # the sibling service's bursts) hits both equally
            for fused in ("1", "0"):
                arm = "fused" if fused == "1" else "unfused"
                print(
                    f"# fused_ab: {name} {arm} rep {rep + 1}/{reps}",
                    file=sys.stderr, flush=True,
                )
                row = run_row(envs | {"BENCH_BULK_FUSED": fused})
                if row is None:
                    return 1
                rows[arm].append(row)

        def median(arm):
            vs = sorted(r["value"] for r in rows[arm])
            return vs[len(vs) // 2]

        v_f, v_u = median("fused"), median("unfused")
        out["configs"][name] = {
            # the rows whose value IS the reported median, plus every
            # rep's value so the spread is on record
            "fused": next(
                r for r in rows["fused"] if r["value"] == v_f
            ),
            "unfused": next(
                r for r in rows["unfused"] if r["value"] == v_u
            ),
            "fused_reps": [r["value"] for r in rows["fused"]],
            "unfused_reps": [r["value"] for r in rows["unfused"]],
            "speedup": round(v_f / v_u, 3) if v_u else None,
        }
        print(
            f"# fused_ab: {name}: fused {v_f} vs unfused {v_u} dec/s "
            f"({100 * (v_f / v_u - 1):+.1f}%, median of {reps})",
            file=sys.stderr, flush=True,
        )
    os.makedirs(osp.join(REPO, "artifacts"), exist_ok=True)
    # quick runs must not clobber the full two-config artifact
    path = osp.join(
        REPO, "artifacts",
        "fused_ab_r07_quick.json" if quick else "fused_ab_r07.json",
    )
    with open(path, "w") as fp:
        json.dump(out, fp, indent=1)
    print(f"# fused_ab: wrote {path}", file=sys.stderr, flush=True)
    for name, c in out["configs"].items():
        print(json.dumps({
            "metric": f"fused_ab_{name}",
            "speedup": c["speedup"],
            "fused": c["fused"]["value"],
            "unfused": c["unfused"]["value"],
            "unit": "steps/s",
        }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
