"""Trained-Decima vs fair-scheduler evaluation on held-out seeds.

Evaluates both schedulers on the SAME job sequences (seed-paired
episodes) at the trained checkpoint's scale and reports per-seed and mean
average job completion time — the reference's headline claim is that
Decima beats the fair scheduler on avg JCT (/root/reference/README.md:5-7,
examples.py:49-81). Writes EVAL.md.

Usage: python scripts_eval_decima.py [num_seeds] [ckpt|-] [out_md]
(ckpt "-" keeps the default multi-checkpoint comparison, e.g. to write
it to a non-default out_md.)
"""

from __future__ import annotations

import sys

import jax
import numpy as np

from sparksched_tpu import metrics
from sparksched_tpu.config import EnvParams
from sparksched_tpu.env import core
from sparksched_tpu.schedulers import DecimaScheduler, RoundRobinScheduler
from sparksched_tpu.trainers.rollout import collect_sync
from sparksched_tpu.workload import make_workload_bank

import os

# the checkpoint's training scale (scripts_train_session.py env cfg);
# EVAL_JOBS=50 reruns the table at the reference's demo setting
# (10 executors / 50 jobs, reference examples.py:15-23) with a
# proportionally larger decision cap
_JOBS = int(os.environ.get("EVAL_JOBS", 20))
# EVAL_EXECS=50 reruns the table at the flagship scale of
# config/decima_tpch.yaml (50 executors; reference decima_tpch.yaml)
_EXECS = int(os.environ.get("EVAL_EXECS", 10))
ENV = dict(num_executors=_EXECS, max_jobs=_JOBS, moving_delay=2000.0,
           warmup_delay=1000.0, job_arrival_rate=4.0e-5)
# padded decision cap per episode: decisions scale with both jobs and
# executors (every executor-availability event forces one); the default
# reproduces 600 at the 10-exec/20-job training scale
STEPS = int(os.environ.get("EVAL_STEPS", 3 * _JOBS * _EXECS))
HELD_OUT_BASE = 10_000  # disjoint from training seeds (iteration-indexed)


def episode_states(params, bank, seeds):
    return jax.vmap(
        lambda s: core.reset(params, bank, jax.random.PRNGKey(s))
    )(seeds)


def run_policy(params, bank, policy_fn, seeds):
    states = episode_states(params, bank, seeds)
    rngs = jax.vmap(
        lambda s: jax.random.PRNGKey(s + 1)
    )(seeds)

    @jax.jit
    def run(states, rngs):
        return jax.vmap(
            lambda r, s: collect_sync(params, bank, policy_fn, r, STEPS, s)
        )(rngs, states)

    import time

    t0 = time.perf_counter()
    ro = run(states, rngs)
    fs = ro.final_state
    done = np.asarray(jax.vmap(lambda s: s.all_jobs_complete)(fs))
    ajd = np.asarray(jax.vmap(metrics.avg_job_duration)(fs))
    print(f"  ({time.perf_counter() - t0:.0f}s)", flush=True)
    return ajd, done


def make_decima(params, ckpt):
    return DecimaScheduler(
        num_executors=params.num_executors,
        embed_dim=16,
        gnn_mlp_kwargs={
            "hid_dims": [32, 16],
            "act_cls": "LeakyReLU",
            "act_kwargs": {"negative_slope": 0.2},
        },
        policy_mlp_kwargs={"hid_dims": [64, 64], "act_cls": "Tanh"},
        state_dict_path=ckpt,
    )


CKPTS = {
    "decima (tpu-trained, no warm start)": "models/decima/model_tpu.msgpack",
    "decima (tpu fine-tuned)": "models/decima/model_ft.msgpack",
    "decima (reference ckpt, converted)": (
        "/root/reference/models/decima/model.pt"
    ),
}

# one provenance line per known checkpoint; the report only describes
# checkpoints it actually evaluated
PROVENANCE = {
    "decima (tpu-trained, no warm start)": (
        "from-scratch PPO in this framework: round-3 recipe through "
        "iteration 250 (scripts_scratch_train.py), then the round-4 "
        "plateau continuation with corrected late-training schedules "
        "(scripts_plateau_train.py); best-model checkpoint at curve "
        "iteration ~400, artifacts/decima_plateau/checkpoints/150"
    ),
    "decima (tpu fine-tuned)": (
        "PPO fine-tune in this framework warm-started from the "
        "converted reference weights (scripts_finetune_loop.py — the "
        "reference's own state_dict_path workflow, "
        "decima/scheduler.py:57-59; train state under "
        "artifacts/decima_ft)"
    ),
    "decima (reference ckpt, converted)": (
        "the reference's published models/decima/model.pt through the "
        "torch->flax converter, no training in this framework"
    ),
}


def main():
    num_seeds = int(sys.argv[1]) if len(sys.argv) > 1 else 32
    ckpts = dict(CKPTS)
    # EVAL_CKPTS: comma-separated substrings selecting which of the
    # default checkpoints to evaluate (long 50-job runs need not pay
    # for stale ones)
    sel = os.environ.get("EVAL_CKPTS")
    if sel:
        keys = [s.strip() for s in sel.split(",") if s.strip()]
        ckpts = {
            n: p for n, p in ckpts.items()
            if any(k in n for k in keys)
        }
        assert ckpts, f"EVAL_CKPTS={sel!r} matched nothing"
    if len(sys.argv) > 2 and sys.argv[2] != "-":
        ckpts = {"decima": sys.argv[2]}
    out_md = sys.argv[3] if len(sys.argv) > 3 else "EVAL.md"
    params = EnvParams(**ENV)
    bank = make_workload_bank(params.num_executors, params.max_stages)
    if bank.max_stages != params.max_stages:
        params = params.replace(
            max_stages=bank.max_stages, max_levels=bank.max_stages
        )
    seeds = jax.numpy.arange(
        HELD_OUT_BASE, HELD_OUT_BASE + num_seeds
    )

    fair = RoundRobinScheduler(
        params.num_executors, dynamic_partition=True
    )
    print("evaluating fair...", flush=True)
    ajd_fair, done_fair = run_policy(
        params, bank, lambda r, o: fair.policy(r, o), seeds
    )
    assert done_fair.all(), "unfinished fair episodes"

    results = {}
    for name, ckpt in ckpts.items():
        print(f"evaluating {name}...", flush=True)
        dec = make_decima(params, ckpt)
        ajd, done = run_policy(
            params, bank,
            lambda r, o: dec.policy(r, o, dec.params), seeds,
        )
        assert done.all(), f"unfinished {name} episodes"
        results[name] = ajd

    header = (
        "| seed | fair avg JCT (s) | "
        + " | ".join(f"{n} (s)" for n in results)
        + " |"
    )
    lines = [
        "# Decima vs fair scheduler — held-out evaluation",
        "",
        "Seed-paired episodes: every scheduler sees the identical job "
        "arrival sequence per seed (the reference's headline claim is "
        "Decima < fair on avg job completion time, "
        "/root/reference/README.md:5-7).",
        f"Env: {ENV['num_executors']} executors, {ENV['max_jobs']} "
        "TPC-H jobs (synthetic bank), held-out seeds "
        f"{HELD_OUT_BASE}..{HELD_OUT_BASE + num_seeds - 1}.",
        "",
        "Checkpoints: "
        + "; ".join(
            f"`{n}` = "
            + PROVENANCE.get(n, f"custom checkpoint {ckpts[n]}")
            for n in results
        )
        + ".",
        "",
        header,
        "|" + "---|" * (2 + len(results)),
    ]
    for i, s in enumerate(np.asarray(seeds)):
        row = f"| {int(s)} | {ajd_fair[i] * 1e-3:.1f} |"
        for ajd in results.values():
            row += f" {ajd[i] * 1e-3:.1f} |"
        lines.append(row)
    lines.append("")
    for name, ajd in results.items():
        wins = int((ajd < ajd_fair).sum())
        lines.append(
            f"**{name}: mean avg JCT {ajd.mean() * 1e-3:.1f}s vs fair "
            f"{ajd_fair.mean() * 1e-3:.1f}s "
            f"({(1 - ajd.mean() / ajd_fair.mean()) * 100:+.1f}%), wins "
            f"{wins}/{num_seeds} seeds.**"
        )
    lines.append("")
    out = "\n".join(lines)
    print(out)
    with open(out_md, "w") as fp:
        fp.write(out)


if __name__ == "__main__":
    from sparksched_tpu.config import honor_jax_platforms_env

    honor_jax_platforms_env()
    main()
