"""Decompose the micro-step cost on the real chip.

Variants timed (all 1024 lanes, 512-lane sub-batches, 256 micro-steps
per jit call):
  full        micro_step, auto_reset=True   (bench baseline)
  noreset     micro_step, auto_reset=False  (isolates reset cost;
              trajectories identical while no lane finishes)
  event       event_micro_step only, auto_reset=False (shared-tail cost
              without the DECIDE/FULFILL switch)
  pop         _pop_event + state replace only (lower bound on event cost)

Scratch diagnostic for the round-2 perf push (not part of the package).
"""

from __future__ import annotations

import time
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from sparksched_tpu.config import EnvParams
from sparksched_tpu.env import core
from sparksched_tpu.env.flat_loop import (
    LoopState,
    _pop_event,
    event_micro_step,
    init_loop_state,
    micro_step,
)
from sparksched_tpu.schedulers.heuristics import round_robin_policy
from sparksched_tpu.workload import make_workload_bank

NUM_ENVS = 1024
SUB = 512
CHUNK = 256


def main() -> None:
    params = EnvParams(
        num_executors=10, max_jobs=50, max_stages=20, max_levels=20,
        moving_delay=2000.0, warmup_delay=1000.0, job_arrival_rate=4e-5,
        mean_time_limit=None,
    )
    bank = make_workload_bank(params.num_executors, params.max_stages)
    if bank.max_stages != params.max_stages:
        params = params.replace(
            max_stages=bank.max_stages, max_levels=bank.max_stages
        )

    def pol(rng, obs):
        si, ne = round_robin_policy(obs, params.num_executors, True)
        return si, ne, {}

    def lane_full(ls, r, auto_reset):
        def body(carry, _):
            ls, k = carry
            k, sub = jax.random.split(k)
            ls = micro_step(
                params, bank, pol, ls, sub, auto_reset,
                compute_levels=False,
            )
            return (ls, k), None

        (ls, _), _ = lax.scan(body, (ls, r), None, length=CHUNK)
        return ls

    def lane_event(ls, r):
        def body(carry, _):
            ls, k = carry
            k, sub = jax.random.split(k)
            ls = event_micro_step(params, bank, ls, sub, False)
            return (ls, k), None

        (ls, _), _ = lax.scan(body, (ls, r), None, length=CHUNK)
        return ls

    def lane_pop(ls, r):
        def body(carry, _):
            ls, k = carry
            st, rk, rj, rs, arg, quirk = _pop_event(
                params, ls.env, ls.mode == 2
            )
            ls = ls.replace(env=st)
            return (ls, k), None

        (ls, _), _ = lax.scan(body, (ls, r), None, length=CHUNK)
        return ls

    @partial(jax.jit, static_argnums=(0,))
    def chunk(which, ls, rngs):
        fns = {
            "full": lambda l, r: lane_full(l, r, True),
            "noreset": lambda l, r: lane_full(l, r, False),
            "event": lane_event,
            "pop": lane_pop,
        }
        fn = fns[which]
        b = rngs.shape[0]
        grp = jax.tree_util.tree_map(
            lambda a: a.reshape(b // SUB, SUB, *a.shape[1:]), (ls, rngs)
        )
        ls2 = lax.map(lambda sr: jax.vmap(fn)(sr[0], sr[1]), grp)
        return jax.tree_util.tree_map(
            lambda a: a.reshape(b, *a.shape[2:]), ls2
        )

    rng = jax.random.PRNGKey(0)
    keys = jax.random.split(rng, NUM_ENVS)
    states = jax.vmap(lambda k: core.reset(params, bank, k))(keys)
    ls0 = jax.vmap(init_loop_state)(states)
    # warm into steady state with the full variant
    ls0 = chunk("full", ls0, jax.random.split(jax.random.PRNGKey(1),
                                              NUM_ENVS))
    jax.block_until_ready(ls0.decisions)

    for which in ("full", "noreset", "event", "pop"):
        ls = chunk(which, ls0,
                   jax.random.split(jax.random.PRNGKey(2), NUM_ENVS))
        jax.block_until_ready(ls.decisions)  # compile
        t0 = time.perf_counter()
        n_timed = 3
        ls = ls0
        for i in range(n_timed):
            ls = chunk(which, ls,
                       jax.random.split(jax.random.PRNGKey(3 + i),
                                        NUM_ENVS))
        jax.block_until_ready(ls.decisions)
        dt = time.perf_counter() - t0
        ms = n_timed * CHUNK * NUM_ENVS
        per = dt / (n_timed * CHUNK) * 1e3
        print(
            f"{which:8s}: {ms / dt:9.0f} micro-steps/s   "
            f"{per:6.2f} ms per 1024-lane micro-step   "
            f"decisions={int(ls.decisions.sum())}"
        )


if __name__ == "__main__":
    from sparksched_tpu.config import (
        enable_compilation_cache,
        honor_jax_platforms_env,
    )

    honor_jax_platforms_env()
    enable_compilation_cache()
    main()
