"""Measure decisions/s, micro-step mix and bulk efficiency of the flat
engine variants on the real chip.

Scratch diagnostic for the round-2 perf push (not part of the package).
"""

from __future__ import annotations

import time
from functools import partial

import jax
from jax import lax

from sparksched_tpu.config import EnvParams
from sparksched_tpu.env import core
from sparksched_tpu.env.flat_loop import init_loop_state, run_flat
from sparksched_tpu.schedulers.heuristics import round_robin_policy
from sparksched_tpu.workload import make_workload_bank

NUM_ENVS = 1024
SUB = 512
CHUNK = 256


def main() -> None:
    params = EnvParams(
        num_executors=10, max_jobs=50, max_stages=20, max_levels=20,
        moving_delay=2000.0, warmup_delay=1000.0, job_arrival_rate=4e-5,
        mean_time_limit=None,
    )
    bank = make_workload_bank(params.num_executors, params.max_stages)
    if bank.max_stages != params.max_stages:
        params = params.replace(
            max_stages=bank.max_stages, max_levels=bank.max_stages
        )

    def pol(rng, obs):
        si, ne = round_robin_policy(obs, params.num_executors, True)
        return si, ne, {}

    @partial(jax.jit, static_argnums=(0, 1))
    def chunk(bulk, reset, ls, rngs):
        def lane(l, r):
            return run_flat(
                params, bank, pol, r, CHUNK, auto_reset=reset,
                compute_levels=False, event_bulk=bulk, loop_state=l,
            )

        b = rngs.shape[0]
        grp = jax.tree_util.tree_map(
            lambda a: a.reshape(b // SUB, SUB, *a.shape[1:]), (ls, rngs)
        )
        ls2 = lax.map(lambda sr: jax.vmap(lane)(sr[0], sr[1]), grp)
        return jax.tree_util.tree_map(
            lambda a: a.reshape(b, *a.shape[2:]), ls2
        )

    rng = jax.random.PRNGKey(0)
    keys = jax.random.split(rng, NUM_ENVS)
    states = jax.vmap(lambda k: core.reset(params, bank, k))(keys)

    for bulk, reset in ((False, True), (True, True), (True, False)):
        ls = jax.vmap(init_loop_state)(states)
        ls = chunk(bulk, reset, ls,
                   jax.random.split(jax.random.PRNGKey(10), NUM_ENVS))
        jax.block_until_ready(ls.decisions)
        d0, b0 = int(ls.decisions.sum()), int(ls.bulked.sum())
        t0 = time.perf_counter()
        n_timed = 3
        for i in range(n_timed):
            ls = chunk(bulk, reset, ls,
                       jax.random.split(jax.random.PRNGKey(50 + i),
                                        NUM_ENVS))
        jax.block_until_ready(ls.decisions)
        dt = time.perf_counter() - t0
        d1, b1 = int(ls.decisions.sum()), int(ls.bulked.sum())
        msteps = n_timed * CHUNK * NUM_ENVS
        print(
            f"bulk={int(bulk)} reset={int(reset)}: "
            f"{(d1 - d0) / dt:8.0f} decisions/s  "
            f"{msteps / dt:9.0f} micro-steps/s  "
            f"dec/mstep={(d1 - d0) / msteps:.3f}  "
            f"bulked/mstep={(b1 - b0) / msteps:.2f}  "
            f"episodes={int(ls.episodes.sum())}"
        )


if __name__ == "__main__":
    from sparksched_tpu.config import (
        enable_compilation_cache,
        honor_jax_platforms_env,
    )

    honor_jax_platforms_env()
    enable_compilation_cache()
    main()
