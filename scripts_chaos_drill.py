"""Deterministic fault-injection drill (ISSUE 9 acceptance): exercise
every recovery path of the self-healing training runtime end-to-end on
CPU, asserting that each injected fault class is (a) DETECTED via a
runlog `health`/`recovery` record, (b) RECOVERED within the bounded
retry budget, and (c) leaves finite parameters behind.

Fault matrix (sparksched_tpu/chaos.py):

  nan_grad     NaN reward -> NaN loss/grads; the in-JIT PPO sentinel
               skips the minibatches, the trainer rolls back + retries
  bank_row     NaN observation-duration row (what a corrupted bank row
               produces downstream) -> same detection path; PLUS the
               state-level check: a genuinely corrupted bank driven
               through a health-threaded collector must trip
               H_NONFINITE_TIME in the telemetry mask
  corrupt_ckpt torn train-state write -> digest-verified load falls
               back to the previous generation and the resume completes
  sigkill      SIGKILL mid-iteration (subprocess) -> the atomic
               checkpoint_every write resumes the run, params finite
  straggler    inflated lane loop_iters -> straggler_ratio_max
               quarantine record, run continues (no retry)
  oom          simulated RESOURCE_EXHAUSTED between collect and update
               -> backoff + retry

Usage:
  python scripts_chaos_drill.py          # full matrix
  python scripts_chaos_drill.py --fast   # the tier-1 smoke subset
                                         # (nan_grad + corrupt_ckpt)

Exit code 0 iff every drilled scenario passed. Each scenario prints a
single `[drill] <name>: PASS|FAIL` line; artifacts land under a temp
dir unless DRILL_ARTIFACTS is set.
"""

from __future__ import annotations

import json
import os
import os.path as osp
import pathlib
import signal
import subprocess
import sys
import tempfile

from sparksched_tpu.config import honor_jax_platforms_env

honor_jax_platforms_env()

import jax  # noqa: E402
import numpy as np  # noqa: E402

from sparksched_tpu.obs.runlog import emit  # noqa: E402


def drill_cfg(artifacts: str, num_iterations: int = 3,
              health=None, chaos=None) -> dict:
    cfg = {
        "trainer": {
            "trainer_cls": "PPO",
            "num_iterations": num_iterations,
            "num_sequences": 1,
            "num_rollouts": 2,
            "seed": 0,
            "use_tensorboard": False,
            "num_epochs": 1,
            "num_batches": 2,
            "beta_discount": 5.0e-3,
            "opt_kwargs": {"lr": 3.0e-4},
            "max_grad_norm": 0.5,
            "rollout_steps": 30,
            "artifacts_dir": artifacts,
            "checkpointing_freq": 10**9,
        },
        "agent": {
            "agent_cls": "DecimaScheduler",
            "embed_dim": 8,
            "gnn_mlp_kwargs": {
                "hid_dims": [16, 8],
                "act_cls": "LeakyReLU",
                "act_kwargs": {"negative_slope": 0.2},
            },
            "policy_mlp_kwargs": {"hid_dims": [16, 16],
                                  "act_cls": "Tanh"},
        },
        "env": {
            "num_executors": 5,
            "job_arrival_cap": 3,
            "moving_delay": 2000.0,
            "mean_time_limit": 2.0e7,
            "job_arrival_rate": 4.0e-5,
            "warmup_delay": 1000.0,
        },
        "obs": {"runlog": True, "telemetry": True},
        "health": {
            "max_retries": 2,
            "backoff_seconds": 0.05,
            "checkpoint_every": 1,
        } | dict(health or {}),
    }
    if chaos is not None:
        cfg["chaos"] = chaos
    return cfg


def runlog_records(artifacts: str) -> list[dict]:
    recs = []
    for p in sorted(pathlib.Path(artifacts, "runlog").glob("*.jsonl")):
        recs.extend(json.loads(ln) for ln in open(p))
    return recs


def params_finite(state) -> bool:
    return all(
        np.isfinite(np.asarray(leaf)).all()
        for leaf in jax.tree_util.tree_leaves(state.params)
        if np.issubdtype(np.asarray(leaf).dtype, np.floating)
    )


def _train(cfg):
    from sparksched_tpu.trainers import make_trainer

    t = make_trainer(cfg)
    return t, t.train()


def drill_nan_grad(root: str) -> bool:
    """NaN gradient at iteration 1: detected (health record with the
    grad/loss bits), recovered (recovery record + run completes), and
    the final params are finite."""
    art = osp.join(root, "nan_grad")
    t, state = _train(drill_cfg(art, chaos={"nan_grad": [1], "seed": 7}))
    recs = runlog_records(art)
    health = [r for r in recs if r["ev"] == "health"]
    rec = [r for r in recs if r["ev"] == "recovery"
           and r.get("action") == "rollback_retry"]
    ok = (
        int(state.iteration) == 3
        and params_finite(state)
        and any("nonfinite_grad" in h.get("bits", ()) for h in health)
        and bool(rec)
    )
    return ok


def drill_bank_row(root: str) -> bool:
    """Corrupted-bank-row class, both halves: (1) the rollout-level
    injection recovers through the trainer; (2) a genuinely corrupted
    bank driven through a health-threaded flat collector trips the
    state-level H_NONFINITE_TIME sentinel in the telemetry mask."""
    art = osp.join(root, "bank_row")
    t, state = _train(drill_cfg(art, chaos={"bank_row": [1], "seed": 3}))
    recs = runlog_records(art)
    health = [r for r in recs if r["ev"] == "health"]
    trained_ok = (
        int(state.iteration) == 3 and params_finite(state) and health
        and any(r["ev"] == "recovery" for r in recs)
    )

    # state-level detection on a genuinely corrupt bank
    from sparksched_tpu.chaos import corrupt_bank
    from sparksched_tpu.env import core
    from sparksched_tpu.env.health import (
        H_EXEC_CONSERVE,
        H_NONFINITE_TIME,
    )
    from sparksched_tpu.obs.telemetry import summarize, telemetry_zeros
    from sparksched_tpu.schedulers.heuristics import round_robin_policy
    from sparksched_tpu.trainers.rollout import collect_flat_sync

    params, bank = t.params_env, corrupt_bank(t.bank, seed=5)

    def pol(rng, obs):
        si, ne = round_robin_policy(obs, params.num_executors, True)
        return si, ne, {}

    st = core.reset(params, bank, jax.random.PRNGKey(0))
    _, tm = collect_flat_sync(
        params, bank, pol, jax.random.PRNGKey(1), 30, st,
        telemetry_zeros(), micro_groups=400, health=True,
    )
    mask = summarize(tm)["health_mask"]
    # a NaN sampled duration first shows as an executing executor with
    # a non-finite finish time (exec-conservation), then as a NaN wall
    # clock once the event pops — either bit is a detection
    state_ok = bool(mask & (H_NONFINITE_TIME | H_EXEC_CONSERVE))
    return trained_ok and state_ok


def drill_corrupt_checkpoint(root: str) -> bool:
    """Torn train-state write: train 2 iterations (two checkpoint
    generations on disk), truncate the newest, and resume — the
    digest-verified loader must fall back to the previous generation
    and the resumed run must complete with finite params."""
    from sparksched_tpu.trainers import make_trainer

    art = osp.join(root, "corrupt_ckpt")
    cfg = drill_cfg(art, num_iterations=2)
    t = make_trainer(cfg)
    t.train()
    path = osp.join(art, "train_state.msgpack")
    data = open(path, "rb").read()
    with open(path, "wb") as fp:  # torn write: half the bytes
        fp.write(data[: len(data) // 2])

    cfg2 = drill_cfg(art, num_iterations=1)
    t2 = make_trainer(cfg2)
    state = t2.train(resume_from=path)
    recs = runlog_records(art)
    fell_back = any(
        r["ev"] == "recovery" and r.get("action") == "checkpoint_fallback"
        for r in recs
    )
    # the intact generation was written after iteration 1 or 2; resume
    # continues from whichever survived and completes one more
    return (
        fell_back and params_finite(state) and int(state.iteration) >= 2
    )


def drill_sigkill(root: str) -> bool:
    """SIGKILL mid-iteration in a subprocess; resume from the atomic
    per-iteration checkpoint and finish. The harder bit-exactness
    claim (resumed params == straight-run params) is test-pinned in
    tests/test_health.py; the drill asserts the operational story."""
    art = osp.join(root, "sigkill")
    code = (
        "import sys; sys.path.insert(0, {repo!r})\n"
        "import scripts_chaos_drill as d\n"
        "from sparksched_tpu.trainers import make_trainer\n"
        "cfg = d.drill_cfg({art!r}, num_iterations=3,\n"
        "                  chaos={{'sigkill': [1]}})\n"
        "make_trainer(cfg).train()\n"
    ).format(repo=osp.dirname(osp.abspath(__file__)), art=art)
    r = subprocess.run(
        [sys.executable, "-c", code], timeout=900,
        env=os.environ | {"JAX_PLATFORMS": "cpu"},
    )
    if r.returncode != -signal.SIGKILL:
        emit(f"[drill] sigkill: subprocess rc={r.returncode}, "
             f"expected {-signal.SIGKILL}")
        return False
    path = osp.join(art, "train_state.msgpack")
    if not osp.isfile(path):
        emit("[drill] sigkill: no checkpoint survived the kill")
        return False
    from sparksched_tpu.trainers import make_trainer

    t2 = make_trainer(drill_cfg(art, num_iterations=2))
    state = t2.train(resume_from=path)
    recs = runlog_records(art)
    resumed = any(r["ev"] == "resume" for r in recs)
    return resumed and params_finite(state) and int(state.iteration) == 3


def drill_straggler(root: str) -> bool:
    """Inflated straggler lane: quarantined via a `health` record with
    the straggler bit, NO retry (it is an observation, not corruption),
    and the run completes."""
    art = osp.join(root, "straggler")
    # with B lanes max/mean is bounded by B; at the drill's 2 lanes the
    # x100 inflation lands the ratio just under 2.0, so the threshold
    # sits below that bound but above any natural 2-lane imbalance
    t, state = _train(drill_cfg(
        art, health={"straggler_ratio_max": 1.9},
        chaos={"straggler": [1], "seed": 11},
    ))
    recs = runlog_records(art)
    health = [r for r in recs if r["ev"] == "health"]
    quarantined = any(
        "straggler" in h.get("bits", ())
        and h.get("action") == "quarantine"
        for h in health
    )
    no_retry = not any(r["ev"] == "recovery" for r in recs)
    return (
        quarantined and no_retry and int(state.iteration) == 3
        and params_finite(state)
    )


def drill_oom(root: str) -> bool:
    """Simulated RESOURCE_EXHAUSTED between collect and update:
    detected (health record with the oom bit), retried with backoff,
    run completes."""
    art = osp.join(root, "oom")
    t, state = _train(drill_cfg(art, chaos={"oom": [1]}))
    recs = runlog_records(art)
    health = [r for r in recs if r["ev"] == "health"]
    return (
        any("oom" in h.get("bits", ()) for h in health)
        and any(r["ev"] == "recovery"
                and r.get("action") == "rollback_retry" for r in recs)
        and int(state.iteration) == 3
        and params_finite(state)
    )


SCENARIOS = {
    "nan_grad": drill_nan_grad,
    "bank_row": drill_bank_row,
    "corrupt_ckpt": drill_corrupt_checkpoint,
    "sigkill": drill_sigkill,
    "straggler": drill_straggler,
    "oom": drill_oom,
}
FAST = ("nan_grad", "corrupt_ckpt")


def main(names=None) -> int:
    root = os.environ.get("DRILL_ARTIFACTS") or tempfile.mkdtemp(
        prefix="chaos_drill_"
    )
    names = tuple(names) if names else tuple(SCENARIOS)
    failed = []
    for name in names:
        try:
            ok = SCENARIOS[name](root)
        except Exception as e:  # a crashed drill is a failed drill
            emit(f"[drill] {name}: EXCEPTION {type(e).__name__}: {e}")
            ok = False
        emit(f"[drill] {name}: {'PASS' if ok else 'FAIL'}")
        if not ok:
            failed.append(name)
    emit(
        f"[drill] {len(names) - len(failed)}/{len(names)} scenarios "
        f"passed (artifacts: {root})"
    )
    return 1 if failed else 0


if __name__ == "__main__":
    picks = FAST if "--fast" in sys.argv[1:] else None
    sys.exit(main(picks))
