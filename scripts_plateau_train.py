"""Plateau continuation: hold the from-scratch policy's peak past
iteration 250.

The round-3 from-scratch curve peaked at iteration 250 (+15.6% vs fair
at the training setting, EVAL.md) and then decayed (+9.5% @300, +7.9%
@350). Two hyperparameter causes, both visible in the r3 recipe
(scripts_scratch_train.py):

- the lr anneal's 15000-step horizon assumed 3 epochs x 10 minibatches
  x 500 iterations, but CPU sessions run 1 epoch, so by iteration 450
  the lr was still ~2.2e-4 — barely annealed, far above the intended
  1e-4 floor for late training;
- the entropy bonus annealed through ~0.011 at iteration 250 and kept
  falling toward 0.005 — the decay window coincides with the
  coefficient dropping below ~0.01.

This runner warm-starts from the iteration-250 best-model checkpoint
(the curve's peak; the reference's own `state_dict_path` warm-start
workflow, reference schedulers/decima/scheduler.py:57-59) with fresh
optimizer state and corrected late-training hyperparameters:

- lr 9e-5 -> 3e-5 over ~250 iterations of actual optimizer steps
  (picks up smoothly below where the peak-era lr sat, ends at a real
  floor),
- entropy coefficient held constant at the 0.01 floor (no further
  decay below the collapse threshold),
- target_kl tightened 0.01 -> 0.007.

Iteration numbering restarts at 0; iteration i here corresponds to
250+i on the round-3 curve. Done-criterion (VERDICT round-3 #5): eval
checkpoints stay within noise of the 250 peak at both eval settings
(reference README.md:22-27 credits its tweaks for training stability —
this is the matching claim for ours).

Usage: python scripts_plateau_train.py [sessions] [iters_per_session]
Artifacts under artifacts/decima_plateau; latest params also written to
models/decima/model_plateau.msgpack.
"""

import sys

sys.path.insert(0, "/root/repo")
from sparksched_tpu.config import (  # noqa: E402
    enable_compilation_cache,
    honor_jax_platforms_env,
)

honor_jax_platforms_env()
enable_compilation_cache()

PEAK_CKPT = (
    "/root/repo/artifacts/decima_scratch_r3/checkpoints/250/model.msgpack"
)


def make_cfg(iters: int) -> dict:
    from scripts_scratch_train import make_cfg as scratch_cfg

    cfg = scratch_cfg("plateau", iters)
    cfg["trainer"] |= {
        "artifacts_dir": "/root/repo/artifacts/decima_plateau",
        "entropy_coeff": 0.01,
        "entropy_anneal": None,
        "target_kl": 0.007,
        "opt_kwargs": {"lr": 9.0e-5},
        "lr_anneal": {"final": 3.0e-5, "steps": 2500},
    }
    cfg["agent"]["state_dict_path"] = PEAK_CKPT
    return cfg


def run(sessions: int, iters: int) -> None:
    from scripts_scratch_train import run_sessions

    run_sessions(
        make_cfg(iters),
        "/root/repo/models/decima/model_plateau.msgpack",
        sessions,
        label="plateau session",
    )


if __name__ == "__main__":
    run(
        int(sys.argv[1]) if len(sys.argv) > 1 else 10,
        int(sys.argv[2]) if len(sys.argv) > 2 else 25,
    )
