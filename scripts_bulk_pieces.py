"""Isolate which construct inside _bulk_relaunch costs the time on the
real chip: scan 256 iterations of successively larger prefixes of the
bulk computation over 1024 lanes (512-lane sub-batches) and time each.

Scratch diagnostic for the round-2 perf push (not part of the package).
"""

from __future__ import annotations

import sys
import time
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from sparksched_tpu.config import (
    EnvParams,
    enable_compilation_cache,
    honor_jax_platforms_env,
)
from sparksched_tpu.env import core
from sparksched_tpu.env.state import BIG_SEQ, INF
from sparksched_tpu.workload import make_workload_bank
from sparksched_tpu.workload.sampling import sample_task_duration

NUM_ENVS, SUB, CHUNK = 1024, 512, 256
_i32 = jnp.int32


def bulk_upto(params, bank, state, level: int):
    """Prefixes of _bulk_relaunch's computation; returns a scalar that
    depends on everything computed so far (keeps XLA from DCE'ing)."""
    n = state.exec_finish_time.shape[0]
    j_cap, s_cap = state.stage_remaining.shape
    pos = jnp.arange(n)
    acc = state.wall_time

    if level >= 1:  # competitors + pairwise-rank permutation
        t_job = jnp.where(state.job_arrived, INF, state.job_arrival_time)
        jt = t_job.min()
        jseq = jnp.where(t_job == jt, state.job_arrival_seq, BIG_SEQ).min()
        at = state.exec_arrive_time.min()
        aseq = jnp.where(
            state.exec_arrive_time == at, state.exec_arrive_seq, BIG_SEQ
        ).min()
        t_star = jnp.minimum(jt, at)
        seq_star = jnp.minimum(
            jnp.where(jt == t_star, jseq, BIG_SEQ),
            jnp.where(at == t_star, aseq, BIG_SEQ),
        )
        tf = state.exec_finish_time
        sf = state.exec_finish_seq
        gt = (tf[:, None] > tf[None, :]) | (
            (tf[:, None] == tf[None, :]) & (sf[:, None] > sf[None, :])
        )
        rank = gt.sum(-1)
        perm = rank[None, :] == pos[:, None]

        def by_pos(x):
            return jnp.where(perm, x[None, :], 0).sum(-1)

        to = jnp.where(perm, tf[None, :], INF).min(-1)
        so = by_pos(sf)
        js = by_pos(state.exec_job)
        ss = by_pos(state.exec_task_stage)
        acc = acc + to.sum() + (so + js + ss).sum()
    if level >= 2:  # per-candidate gathers
        rem0 = state.stage_remaining[
            jnp.clip(js, 0, j_cap - 1), jnp.clip(ss, 0, s_cap - 1)
        ]
        num_local = (state.exec_job[None, :] == js[:, None]).sum(-1)
        tpl = state.job_template[jnp.clip(js, 0, j_cap - 1)]
        acc = acc + (rem0 + num_local + tpl).sum()
    if level >= 3:  # rng keys + vmapped sampler
        rng_next, sub = jax.random.split(state.rng)
        keys = jax.vmap(jax.random.fold_in, in_axes=(None, 0))(sub, pos)
        durs = jax.vmap(
            lambda key, tp, s_, nl: sample_task_duration(
                params, bank, key, tp, s_, nl,
                jnp.bool_(True), jnp.bool_(True),
            )
        )(keys, tpl, jnp.clip(ss, 0, s_cap - 1), num_local)
        acc = acc + durs.sum() + rng_next.sum()
    if level >= 4:  # prefix conditions
        new_fin = to + durs
        flat = js * s_cap + ss
        earlier = pos[None, :] < pos[:, None]
        cum_before = (earlier & (flat[None, :] == flat[:, None])).sum(-1)
        before_star = (to < t_star) | ((to == t_star) & (so < seq_star))
        gen_before = jnp.concatenate(
            [jnp.full((1,), INF), lax.cummin(new_fin)[:-1]]
        )
        ok = (
            jnp.isfinite(to) & before_star
            & (cum_before < rem0) & (to <= gen_before)
        )
        prefix = jnp.cumsum((~ok).astype(_i32)) == 0
        k = prefix.sum().astype(_i32)
        acc = acc + k
    if level >= 5:  # executor selects
        new_seq = state.seq_counter + pos
        sel = prefix[:, None] & perm
        upd_e = sel.any(0)
        fin_e = jnp.where(sel, new_fin[:, None], 0.0).sum(0)
        seq_e = jnp.where(sel, new_seq[:, None], 0).sum(0)
        acc = acc + jnp.where(upd_e, fin_e, 0.0).sum() + seq_e.sum()
    if level >= 6:  # [N,J,S] stage masks + payload reductions
        oh_j = js[:, None] == jnp.arange(j_cap)[None, :]
        oh_s = ss[:, None] == jnp.arange(s_cap)[None, :]
        m = oh_j[:, :, None] & oh_s[:, None, :] & prefix[:, None, None]
        cnt = m.sum(0).astype(_i32)
        aff = cnt > 0
        later_same = (
            (flat[None, :] == flat[:, None])
            & (pos[None, :] > pos[:, None])
            & prefix[None, :]
        )
        is_last = prefix & ~later_same.any(-1)
        dur_js = (m & is_last[:, None, None]).astype(durs.dtype)
        sd = jnp.where(aff, (dur_js * durs[:, None, None]).sum(0), 0.0)
        acc = acc + cnt.sum() + sd.sum()
    if level >= 7:  # sat refresh + candidate-row children update
        rem_new = state.stage_remaining - cnt
        demand = rem_new - state.moving_count - state.commit_count
        sat_new = demand <= 0
        jc = jnp.clip(js, 0, j_cap - 1)
        sc = jnp.clip(ss, 0, s_cap - 1)
        delta_i = jnp.where(
            is_last & state.stage_exists[jc, sc],
            sat_new[jc, sc].astype(_i32)
            - state.stage_sat[jc, sc].astype(_i32),
            0,
        )
        adj_row = state.adj[jc, sc]
        unsat = state.unsat_parent_count - (
            oh_j[:, :, None]
            * (delta_i[:, None] * adj_row.astype(_i32))[:, None, :]
        ).sum(0)
        acc = acc + unsat.sum() + sat_new.sum()
    return acc


def main(levels) -> None:
    params = EnvParams(
        num_executors=10, max_jobs=50, max_stages=20, max_levels=20,
        moving_delay=2000.0, warmup_delay=1000.0, job_arrival_rate=4e-5,
        mean_time_limit=None,
    )
    bank = make_workload_bank(params.num_executors, params.max_stages)
    if bank.max_stages != params.max_stages:
        params = params.replace(
            max_stages=bank.max_stages, max_levels=bank.max_stages
        )

    @partial(jax.jit, static_argnums=(0,))
    def chunk(level, states, accs):
        def lane(state, acc):
            def body(carry, _):
                st, a = carry
                a = a + bulk_upto(params, bank, st, level)
                # perturb the bulk's inputs so nothing is loop-invariant
                # (XLA hoists computations on constant carries out of
                # the scan, which zeroed out a first version of this
                # probe)
                st = st.replace(
                    exec_finish_time=st.exec_finish_time + (a * 0 + 1.0),
                    stage_remaining=st.stage_remaining
                    + (a * 0).astype(jnp.int32),
                    rng=st.rng + (a * 0).astype(st.rng.dtype),
                )
                return (st, a), None

            (st, out), _ = lax.scan(
                body, (state, acc), None, length=CHUNK
            )
            return out + st.wall_time * 0

        grp = jax.tree_util.tree_map(
            lambda a: a.reshape(NUM_ENVS // SUB, SUB, *a.shape[1:]),
            (states, accs),
        )
        return lax.map(
            lambda sr: jax.vmap(lane)(sr[0], sr[1]), grp
        ).reshape(NUM_ENVS)

    rng = jax.random.PRNGKey(0)
    states = jax.vmap(lambda k: core.reset(params, bank, k))(
        jax.random.split(rng, NUM_ENVS)
    )
    accs = jnp.zeros(NUM_ENVS)
    prev = 0.0
    for level in levels:
        out = chunk(level, states, accs)
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for _ in range(3):
            out = chunk(level, states, out)
        jax.block_until_ready(out)
        dt = time.perf_counter() - t0
        per = dt / (3 * CHUNK) * 1e3
        print(
            f"level={level}: {per:6.3f} ms per 1024-lane iter "
            f"(delta {per - prev:+6.3f})"
        )
        prev = per


if __name__ == "__main__":
    honor_jax_platforms_env()
    enable_compilation_cache()
    lv = [int(x) for x in sys.argv[1:]] or [0, 1, 2, 3, 4, 5, 6, 7]
    main(lv)
