"""From-scratch PPO training runner (no warm start) — the capability the
reference is named for (its models/decima/model.pt is the output of its
own trainers, README.md:5-7).

Round-3 recipe vs the round-2 run that failed to learn
(artifacts_train_log.txt: no trend over 100 iterations):
- reference-parity lane layout: 4 sequences x 4 rollouts (the round-2 run
  used 2x4; reference config/decima_tpch.yaml:11-18),
- entropy anneal 0.04 -> 0.005 (the fixed 0.04 bonus on a *normalized*
  entropy keeps the policy near-uniform at small scale),
- lr anneal 3e-4 -> 1e-4 over the optimizer steps of ~500 iterations,
- many more iterations (the reference trains 500; round 2 stopped at 100).

Resumable: sessions save/restore the full train state. Usage:
  python scripts_scratch_train.py [sessions] [iters_per_session] [tag]
Artifacts under artifacts/decima_scratch_<tag>; eval with
scripts_eval_decima.py against the written checkpoint.
"""

import os.path as osp
import sys

sys.path.insert(0, "/root/repo")
from sparksched_tpu.config import (  # noqa: E402
    enable_compilation_cache,
    honor_jax_platforms_env,
)

honor_jax_platforms_env()
enable_compilation_cache()

from flax import serialization  # noqa: E402
import jax  # noqa: E402

from sparksched_tpu.trainers import make_trainer  # noqa: E402


def make_cfg(tag: str, iters: int) -> dict:
    # 1 epoch on the 1-CPU-core box (the update's grad steps dominate
    # iteration wall time there; the KL early stop frequently skipped
    # the extra epochs anyway); reference-parity 3 epochs on the chip,
    # where the update is cheap — keyed on the backend so the
    # unattended chip-watcher launch gets the right value.
    num_epochs = 1 if jax.default_backend() == "cpu" else 3
    return {
        "trainer": {
            "trainer_cls": "PPO", "num_iterations": iters,
            "num_sequences": 4, "num_rollouts": 4, "seed": 42,
            "artifacts_dir": f"/root/repo/artifacts/decima_scratch_{tag}",
            "checkpointing_freq": 25, "use_tensorboard": False,
            "num_epochs": num_epochs, "num_batches": 10,
            "clip_range": 0.2,
            "target_kl": 0.01, "entropy_coeff": 0.04,
            "entropy_anneal": {"final": 0.005, "iterations": 400},
            "beta_discount": 5.0e-3,
            "opt_cls": "Adam", "opt_kwargs": {"lr": 3.0e-4},
            "lr_anneal": {"final": 1.0e-4, "steps": 15000},
            "max_grad_norm": 0.5, "rollout_steps": 600,
            "profiling": True,
        },
        "agent": {
            "agent_cls": "DecimaScheduler", "embed_dim": 16,
            "gnn_mlp_kwargs": {
                "hid_dims": [32, 16], "act_cls": "LeakyReLU",
                "act_kwargs": {"negative_slope": 0.2},
            },
            "policy_mlp_kwargs": {"hid_dims": [64, 64], "act_cls": "Tanh"},
        },
        "env": {
            "num_executors": 10, "job_arrival_cap": 20,
            "moving_delay": 2000.0, "mean_time_limit": 2.0e7,
            "job_arrival_rate": 4.0e-5, "warmup_delay": 1000.0,
        },
    }


def run_sessions(cfg: dict, out: str, sessions: int,
                 label: str = "session") -> None:
    """Shared bounded-session loop (also used by
    scripts_flagship_train.py): train `cfg` repeatedly, resuming from
    the artifacts dir's saved train state, writing the latest params to
    `out` after each session."""
    art = cfg["trainer"]["artifacts_dir"]
    resume = osp.join(art, "train_state.msgpack")
    for s in range(sessions):
        t = make_trainer(cfg)
        state = t.train(
            resume_from=resume if osp.isfile(resume) else None
        )
        with open(out, "wb") as fp:
            fp.write(serialization.to_bytes(jax.device_get(state.params)))
        print(
            f"{label} {s + 1}/{sessions} done at iteration "
            f"{int(state.iteration)} -> {out}",
            flush=True,
        )


def run(sessions: int, iters: int, tag: str = "r3") -> None:
    run_sessions(
        make_cfg(tag, iters),
        f"/root/repo/models/decima/model_scratch_{tag}.msgpack",
        sessions,
    )


if __name__ == "__main__":
    run(
        int(sys.argv[1]) if len(sys.argv) > 1 else 20,
        int(sys.argv[2]) if len(sys.argv) > 2 else 25,
        sys.argv[3] if len(sys.argv) > 3 else "r3",
    )
