"""Flagship-scale PPO training runner (config/decima_tpch.yaml: 50
executors, 200-job cap, 9600-step rollouts — the reference's headline
training configuration, reference config/decima_tpch.yaml:80-87).

Resumable sessions like scripts_scratch_train.py: the full train state
(params + optimizer + RNG + iteration) is saved between sessions, so
progress accumulates across bounded chip windows and survives tunnel
wedges. Adds the round-3 training-stability levers that made the
from-scratch small-scale run beat fair (entropy/lr anneal — see
scripts_scratch_train.py's recipe notes).

Usage: python scripts_flagship_train.py [sessions] [iters_per_session]
Artifacts under artifacts/decima_flagship; latest params also written to
models/decima/model_flagship.msgpack. Evaluate with
  EVAL_EXECS=50 EVAL_JOBS=50 python scripts_eval_decima.py 24 \
      models/decima/model_flagship.msgpack EVAL_FLAGSHIP.md
"""

import os.path as osp
import sys

sys.path.insert(0, "/root/repo")
from sparksched_tpu.config import (  # noqa: E402
    enable_compilation_cache,
    honor_jax_platforms_env,
)

honor_jax_platforms_env()
enable_compilation_cache()

import yaml  # noqa: E402
import jax  # noqa: E402

ART = "/root/repo/artifacts/decima_flagship"


def make_cfg(iters: int) -> dict:
    with open(osp.join(osp.dirname(__file__),
                       "config/decima_tpch.yaml")) as fp:
        cfg = yaml.safe_load(fp)
    num_epochs = 1 if jax.default_backend() == "cpu" else 3
    cfg["trainer"] |= {
        "num_iterations": iters,
        "artifacts_dir": ART,
        "checkpointing_freq": 5,
        "use_tensorboard": False,
        "num_epochs": num_epochs,
        # round-3 stability levers (scripts_scratch_train.py recipe),
        # with the entropy floor raised to 0.01: the r3 from-scratch
        # curve's post-peak decay window coincided with the coefficient
        # annealing below ~0.01 (scripts_plateau_train.py's diagnosis)
        "entropy_anneal": {"final": 0.01, "iterations": 400},
        "lr_anneal": {"final": 1.0e-4, "steps": 15000},
        "profiling": True,
    }
    return cfg


def run(sessions: int, iters: int) -> None:
    from scripts_scratch_train import run_sessions

    run_sessions(
        make_cfg(iters),
        "/root/repo/models/decima/model_flagship.msgpack",
        sessions,
        label="flagship session",
    )


if __name__ == "__main__":
    run(
        int(sys.argv[1]) if len(sys.argv) > 1 else 10,
        int(sys.argv[2]) if len(sys.argv) > 2 else 5,
    )
