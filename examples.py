"""Demo episodes (reference examples.py:15-106):

    python examples.py --sched fair
    python examples.py --sched decima [--state-dict PATH]
    python examples.py --sched random

Runs one 50-job / 10-executor TPC-H episode with the chosen scheduler,
prints the average job duration, and saves a Gantt chart to
`screenshot.png` (the reference renders live with pygame and saves the
same screenshot on close; here the chart is drawn headlessly)."""

from __future__ import annotations

from argparse import ArgumentParser

import jax
import jax.numpy as jnp

from sparksched_tpu import metrics
from sparksched_tpu.config import EnvParams
from sparksched_tpu.env import core
from sparksched_tpu.env.observe import observe
from sparksched_tpu.renderer import GanttRenderer
from sparksched_tpu.schedulers import (
    DecimaScheduler,
    RandomScheduler,
    RoundRobinScheduler,
)
from sparksched_tpu.workload import make_workload_bank

ENV_CFG = {
    "num_executors": 10,
    "max_jobs": 50,
    "moving_delay": 2000.0,
    "warmup_delay": 1000.0,
    "job_arrival_rate": 4e-5,
}


# shipped checkpoint, loaded when --state-dict is omitted: the
# reference demo auto-loads its published weights the same way
# (reference examples.py:69, models/decima/model.pt)
DEFAULT_DECIMA_CKPT = "models/decima/model_tpu.msgpack"


def make_scheduler(name: str, state_dict: str | None):
    n = ENV_CFG["num_executors"]
    if name == "fair":
        return RoundRobinScheduler(n, dynamic_partition=True)
    if name == "fifo":
        return RoundRobinScheduler(n, dynamic_partition=False)
    if name == "random":
        return RandomScheduler()
    if name == "decima":
        if state_dict is None:
            import os.path as osp

            state_dict = osp.join(
                osp.dirname(osp.abspath(__file__)), DEFAULT_DECIMA_CKPT
            )
            print(f"loading shipped checkpoint {DEFAULT_DECIMA_CKPT} "
                  "(override with --state-dict)")
        return DecimaScheduler(
            num_executors=n,
            embed_dim=16,
            gnn_mlp_kwargs={
                "hid_dims": [32, 16],
                "act_cls": "LeakyReLU",
                "act_kwargs": {"negative_slope": 0.2},
            },
            policy_mlp_kwargs={"hid_dims": [64, 64], "act_cls": "Tanh"},
            state_dict_path=state_dict,
        )
    raise ValueError(name)


def run_episode(scheduler, seed: int = 0, render: bool = True,
                max_steps: int = 20000, live: bool = False) -> float:
    params = EnvParams(**ENV_CFG)
    bank = make_workload_bank(params.num_executors, params.max_stages)
    if bank.max_stages != params.max_stages:
        params = params.replace(
            max_stages=bank.max_stages, max_levels=bank.max_stages
        )
    state = core.reset(params, bank, jax.random.PRNGKey(seed))
    renderer = GanttRenderer(
        params.num_executors,
        live_path="screenshot.png" if live else None,
    ) if render else None
    rng = jax.random.PRNGKey(seed + 1)
    policy = jax.jit(scheduler.policy)

    steps = 0
    while not bool(state.terminated | state.truncated) and steps < max_steps:
        obs = observe(params, state)
        rng, sub = jax.random.split(rng)
        stage_idx, num_exec, _ = policy(sub, obs)
        state, _, _, _ = core.step(
            params, bank, state, jnp.int32(stage_idx), jnp.int32(num_exec)
        )
        if renderer is not None:
            renderer.record(state)
        steps += 1

    avg = float(metrics.avg_job_duration(state))
    print(f"{scheduler.name}: avg job duration = {avg * 1e-3:.1f}s "
          f"({steps} decisions)")
    if renderer is not None:
        print("saved", renderer.render("screenshot.png"))
    return avg


if __name__ == "__main__":
    from sparksched_tpu.config import honor_jax_platforms_env

    honor_jax_platforms_env()
    p = ArgumentParser()
    p.add_argument("--sched", default="fair",
                   choices=["fair", "fifo", "random", "decima"])
    p.add_argument("--state-dict", default=None,
                   help="Decima weights (.pt torch or .msgpack); "
                        "default: the shipped "
                        f"{DEFAULT_DECIMA_CKPT}")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--no-render", action="store_true")
    p.add_argument("--live", action="store_true",
                   help="refresh screenshot.png during the episode "
                        "(reference render_frame analog)")
    args = p.parse_args()
    run_episode(
        make_scheduler(args.sched, args.state_dict),
        seed=args.seed,
        render=not args.no_render,
        live=args.live,
    )
