"""Perf probe: micro-step composition + per-piece timing on the real chip.

Not part of the package; a scratch diagnostic for the round-2 perf push.
"""

from __future__ import annotations

import time
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from sparksched_tpu.config import EnvParams
from sparksched_tpu.env import core
from sparksched_tpu.env.flat_loop import init_loop_state, micro_step
from sparksched_tpu.env.observe import observe
from sparksched_tpu.schedulers.heuristics import round_robin_policy
from sparksched_tpu.workload import make_workload_bank

NUM_ENVS = 1024
SUB = 512
CHUNK = 128


def timed(fn, *args, n=3):
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / n, out


def main():
    params = EnvParams(
        num_executors=10, max_jobs=50, max_stages=20, max_levels=20,
        moving_delay=2000.0, warmup_delay=1000.0, job_arrival_rate=4e-5,
        mean_time_limit=None,
    )
    bank = make_workload_bank(params.num_executors, params.max_stages)
    if bank.max_stages != params.max_stages:
        params = params.replace(
            max_stages=bank.max_stages, max_levels=bank.max_stages
        )
    print("caps:", params.max_jobs, params.max_stages,
          bank.num_templates, bank.max_stages)

    rng = jax.random.PRNGKey(0)
    keys = jax.random.split(rng, NUM_ENVS)
    states = jax.vmap(lambda k: core.reset(params, bank, k))(keys)
    ls = jax.vmap(init_loop_state)(states)

    def pol(rng, obs):
        si, ne = round_robin_policy(obs, params.num_executors, True)
        return si, ne, {}

    @partial(jax.jit, static_argnums=())
    def run_chunk(ls, rngs):
        def lane(l, r):
            def body(c, _):
                l, k = c
                k, s = jax.random.split(k)
                l = micro_step(params, bank, pol, l, s, True, False)
                return (l, k), None

            (l, _), _ = lax.scan(body, (l, r), None, length=CHUNK)
            return l

        b = rngs.shape[0]
        grp = jax.tree_util.tree_map(
            lambda a: a.reshape(b // SUB, SUB, *a.shape[1:]), (ls, rngs)
        )
        ls2 = lax.map(lambda sr: jax.vmap(lane)(sr[0], sr[1]), grp)
        return jax.tree_util.tree_map(
            lambda a: a.reshape(b, *a.shape[2:]), ls2
        )

    # mode histogram before/after to estimate decision fraction
    keys = jax.random.split(jax.random.PRNGKey(1), NUM_ENVS)
    ls1 = run_chunk(ls, keys)
    jax.block_until_ready(ls1.decisions)
    d0 = int(ls1.decisions.sum())
    t, ls2 = timed(run_chunk, ls1, jax.random.split(
        jax.random.PRNGKey(2), NUM_ENVS))
    d1 = int(ls2.decisions.sum())
    msteps = NUM_ENVS * CHUNK
    dec_per_chunk = (d1 - d0) / 3
    print(f"chunk: {t*1e3:.1f} ms for {msteps} micro-steps "
          f"({t/CHUNK*1e6:.0f} us per {NUM_ENVS}-lane micro-step)")
    print(f"decision fraction: {dec_per_chunk / msteps:.3f}")
    print(f"decisions/s: {dec_per_chunk / t:.0f}")
    print(f"micro-steps/s: {msteps / t:.0f}")
    print(f"episodes: {int(ls2.episodes.sum())}")

    # --- piece timings at 1024 lanes -------------------------------------
    st = ls2.env

    def f_observe(st):
        return jax.vmap(lambda s: observe(params, s, False))(st)

    def f_levels(st):
        return jax.vmap(lambda s: core.compute_node_levels(params, s))(st)

    def f_policy(st):
        obs = f_observe(st)
        return jax.vmap(
            lambda o: round_robin_policy(o, params.num_executors, True)
        )(obs)

    def f_next_event(st):
        return jax.vmap(lambda s: core._next_event(params, s))(st)

    def f_sched(st):
        return jax.vmap(
            lambda s: core.find_schedulable(params, s, s.source_job_id())
        )(st)

    def f_backup(st):
        return jax.vmap(
            lambda s: core._find_backup_stage(
                params, s, jnp.int32(0), s.source_job_id()
            )
        )(st)

    def f_apply(st):
        return jax.vmap(
            lambda s: core._apply_action(
                params, bank, s, jnp.int32(1), jnp.int32(0), jnp.int32(0),
                jnp.int32(0),
            )
        )(st)

    def f_fulfill_a(st):
        return jax.vmap(
            lambda s: core._fulfill_commitment_phase_a(
                s, jnp.int32(0), jnp.int32(0)
            )
        )(st)

    def f_handle_tf(st):
        return jax.vmap(
            lambda s: core._handle_task_finished(s, jnp.int32(0))
        )(st)

    def f_argsorts(st):
        def one(s):
            n = s.exec_job.shape[0]
            idle = s.source_pool_mask() & ~s.exec_executing
            eo = jnp.argsort(jnp.where(idle, jnp.arange(n), 10**9))
            so = jnp.argsort(
                jnp.where(s.cm_valid, s.cm_seq, 10**9), stable=True
            )
            return eo, so

        return jax.vmap(one)(st)

    for name, fn in [
        ("observe(no levels)", f_observe),
        ("node_levels", f_levels),
        ("observe+fair policy", f_policy),
        ("next_event", f_next_event),
        ("find_schedulable", f_sched),
        ("backup_stage", f_backup),
        ("apply_action", f_apply),
        ("fulfill_phase_a", f_fulfill_a),
        ("handle_task_finished", f_handle_tf),
        ("argsort pair", f_argsorts),
    ]:
        jf = jax.jit(fn)
        t, _ = timed(jf, st, n=10)
        print(f"{name:24s} {t*1e6:8.0f} us / call @1024")


if __name__ == "__main__":
    from sparksched_tpu.config import (
        enable_compilation_cache,
        honor_jax_platforms_env,
    )

    honor_jax_platforms_env()
    enable_compilation_cache()
    main()
