#!/bin/bash
# Poll the TPU tunnel GENTLY; whenever it answers, run the chip session
# (headline bench FIRST -- tunnel windows have been ~45 min, so the
# driver-gate number must land before anything else), then hand leftover
# chip time to FLAGSHIP-scale PPO training (config/decima_tpch.yaml: 50
# executors / 200-job arrivals -- the scale the reference's published
# model was trained at; VERDICT round-3 item 3). Touch
# /tmp/stop_chip_watch to make the watcher exit and leave the tunnel
# free (e.g. before the driver's round-end bench).
#
# Round-3 polling discipline (kept): the round-2 watcher probed every
# 4 min, each probe a timeout-killed client -- 12+ h of continuous
# wedge under that regime suggests aggressive polling may itself hold
# the grant. Poll every 20 min with a generous 300 s timeout.
#
# CPU-side training is the PLATEAU continuation (scripts_plateau_train:
# hold the from-scratch curve's iteration-250 peak - VERDICT round-3
# item 5); it trains at the 10-exec scale, cheap enough for the 1-core
# box. Flagship iterations are chip-only (CPU extrapolation from
# PERF.md stage-5: days per iteration).
cd /root/repo
rm -f /tmp/stop_chip_watch  # consume any stale stop request at launch

restart_cpu_trainer() {
  # plateau run complete (curve 250->500, EVAL.md); CPU now continues
  # the fine-tuned artifact under the corrected schedules
  if ! pgrep -f "scripts_ft_continue" > /dev/null; then
    JAX_PLATFORMS=cpu nohup nice -n 10 python scripts_ft_continue.py \
      4 25 >> /tmp/ft_continue.log 2>&1 &
    echo "cpu ft-continuation trainer restarted (pid $!) at $(date +%H:%M:%S)"
  fi
}

for i in $(seq 1 40); do
  [ -f /tmp/stop_chip_watch ] && { echo "stop file; exiting"; exit 0; }
  if timeout 300 python -c "
import jax
jax.config.update('jax_compilation_cache_dir', '/root/repo/.jax_cache')
import jax.numpy as jnp
jax.block_until_ready((jnp.ones((256,256)) @ jnp.ones((256,256))).sum())
print('ALIVE')
" 2>/dev/null | grep -q ALIVE; then
    echo "chip alive at $(date +%H:%M:%S); running session"
    # stop the CPU trainer for the chip window: compiles and host-side
    # scan glue need the single core
    pkill -f "scripts_plateau_train\|scripts_ft_continue" 2>/dev/null
    sleep 2
    timeout -k 60 3600 python scripts_chip_session.py 1 3
    echo "session rc=$? at $(date +%H:%M:%S)"
    [ -f /tmp/stop_chip_watch ] && { echo "stop file; exiting"; exit 0; }
    # flagship-scale training BEFORE the decima benches: VERDICT ranks
    # it higher, and round 3's tunnel window died inside a decima-bench
    # compile. Short resumable sessions (state saved every session; a
    # wedge mid-session loses at most iters_per_session iterations).
    timeout -k 60 7200 python scripts_flagship_train.py 20 2
    echo "flagship rc=$? at $(date +%H:%M:%S)"
    [ -f /tmp/stop_chip_watch ] && { echo "stop file; exiting"; exit 0; }
    timeout -k 60 2700 python scripts_chip_session.py 4
    echo "decima-bench rc=$? at $(date +%H:%M:%S)"
    [ -f /tmp/stop_chip_watch ] && { echo "stop file; exiting"; exit 0; }
    # fault-risk 1024-lane probe LAST in the chip episode: if it wedges
    # the tunnel, nothing else in this window is lost
    timeout -k 60 1900 python scripts_chip_session.py 7
    echo "probe1024 rc=$? at $(date +%H:%M:%S)"
  else
    echo "watch $i: wedged at $(date +%H:%M:%S)"
  fi
  # idempotent (pgrep-guarded): also revives a trainer that crashed
  # during a tunnel wedge, not just after a chip episode
  restart_cpu_trainer
  sleep 1200
done
restart_cpu_trainer
