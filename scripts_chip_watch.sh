#!/bin/bash
# Poll the TPU tunnel GENTLY; whenever it answers, run the chip session
# (headline bench FIRST -- tunnel windows have been ~45 min, so the
# driver-gate number must land before anything else), then hand leftover
# chip time to FLAGSHIP-scale PPO training (config/decima_tpch.yaml: 50
# executors / 200-job arrivals -- the scale the reference's published
# model was trained at; VERDICT round-3 item 3). Touch
# /tmp/stop_chip_watch to make the watcher exit and leave the tunnel
# free (e.g. before the driver's round-end bench).
#
# Round-3 polling discipline (kept): the round-2 watcher probed every
# 4 min, each probe a timeout-killed client -- 12+ h of continuous
# wedge under that regime suggests aggressive polling may itself hold
# the grant. Poll every 20 min with a generous 300 s timeout.
#
# CPU-side training (round 5) is the 50-executor in-distribution
# fine-tune (scripts_ft50_train.py — VERDICT round-4 item 2: stop
# gating flagship-executor-scale training on the chip). Sized for the
# 1-core box by the round-5 decision-count probes; full 200-job
# flagship iterations remain chip-preferred (scripts_flagship_train.py
# below).
cd /root/repo
rm -f /tmp/stop_chip_watch  # consume any stale stop request at launch
# true per-lifetime headline semantics (round-5 advisor): the re-measure
# marker must not survive watcher restarts, or a restarted watcher in
# the same round never re-measures after calibration changes
rm -f /tmp/headline_r05_remeasured
# same per-lifetime semantics for the on-chip memory capture (stage 11)
rm -f /tmp/memcap_done
# ... and for the sharded multichip bench (stage 12, ISSUE 6)
rm -f /tmp/multichip_done
# ... and for the fused-engine headline row (stage 13, ISSUE 7)
rm -f /tmp/fused_headline_done
# ... and for the serving-latency capture (stage 14, ISSUE 10)
rm -f /tmp/serve_latency_done
# ... and for the serve-scale open-loop capture (stage 15, ISSUE 11)
rm -f /tmp/serve_scale_done
# ... and for the continuous-batching A/B capture (stage 16, ISSUE 13)
rm -f /tmp/serve_cb_done
# ... and for the pipelined-serve A/B capture (stage 17, ISSUE 15)
rm -f /tmp/serve_pipe_done
# ... and for the network serving tier capture (stage 18, ISSUE 16)
rm -f /tmp/serve_net_done
# ... and for the ring record-path A/B capture (stage 19, ISSUE 18)
rm -f /tmp/serve_ring_done
# stage-completion ledger (ISSUE 9): per-LIFETIME like the markers
# above — a restarted watcher must re-run its multi-stage sessions, not
# inherit a previous lifetime's completions (the ledger's job is
# resuming a KILLED window, which the in-loop relaunches below cover)
rm -f artifacts/chip_session_ledger.json
# one-time legacy sweep: earlier-round trainers (tracked only by name,
# pre-PID-file) must not survive into this watcher's lifetime — they
# would contend the single core untracked and never be stopped for
# chip windows. Safe from self-match here: this script's own cmdline
# is "bash .../scripts_chip_watch.sh", which matches neither pattern.
pkill -f "scripts_ft_continue.py" 2>/dev/null
pkill -f "scripts_plateau_train.py" 2>/dev/null

# The CPU trainer is tracked by PID file, not pkill -f: pkill patterns
# self-match wrapper shells in this harness, and \|-alternation in a
# pkill ERE is a literal (round-4 advisor finding) — both made the old
# pattern kill either nothing or the caller.
# static-analysis gate once per watcher lifetime (PR 4): the bench rows
# stamp analysis_clean per process anyway, but the watcher log should
# say up front whether this tree is clean. CPU-pinned subprocess inside
# stage 10 — never touches the tunnel, so it runs before any polling.
timeout -k 30 1500 python scripts_chip_session.py 10 \
  | tee /tmp/analysis_last.log

# ISSUE 9: per-stage retry with backoff. A transient stage failure
# (rc != 0) gets ONE retry after a 60 s backoff; rc = 124 is the
# watcher's own budget kill — that is the TRUNCATION_EXPECTED case and
# is never retried (re-running a truncated stage would double-burn the
# window). The distinct RETRIED:/RETRY_FAILED: markers let artifact
# readers separate flakes (failed once, passed on retry) from real
# failures (failed twice) from truncations (rc=124, see the
# TRUNCATION_EXPECTED lines below).
run_with_retry() {  # run_with_retry <budget_secs> <label> <cmd...>
  local budget=$1 label=$2; shift 2
  timeout -k 60 "$budget" "$@"
  local rc=$?
  if [ "$rc" -ne 0 ] && [ "$rc" -ne 124 ]; then
    # honor a stop request BEFORE committing to another full stage
    # budget: the stop file exists to free the tunnel promptly, and a
    # retry can hold the grant for hours past it
    if [ -f /tmp/stop_chip_watch ]; then
      echo "RETRY_SKIPPED: $label rc=$rc; stop file present"
      return $rc
    fi
    echo "RETRIED: $label rc=$rc at $(date +%H:%M:%S); one retry after 60s backoff"
    sleep 60
    timeout -k 60 "$budget" "$@"
    rc=$?
    if [ "$rc" -ne 0 ] && [ "$rc" -ne 124 ]; then
      echo "RETRY_FAILED: $label rc=$rc (real failure, not a flake)"
    fi
  fi
  return $rc
}

CPU_TRAINER_PID=/tmp/cpu_trainer.pid

cpu_trainer_alive() {
  # identity-checked liveness: a recycled PID must not make the watcher
  # adopt (or later SIGTERM) an unrelated process
  [ -f "$CPU_TRAINER_PID" ] \
    && p="$(cat "$CPU_TRAINER_PID")" \
    && kill -0 "$p" 2>/dev/null \
    && tr '\0' ' ' < "/proc/$p/cmdline" 2>/dev/null \
       | grep -q "scripts_ft50_train"
}

stop_cpu_trainer() {
  if cpu_trainer_alive; then
    kill "$(cat "$CPU_TRAINER_PID")" 2>/dev/null
  fi
  # belt-and-braces: an ft50 instance NOT recorded in the PID file
  # (hand-launched, PID file lost) must still yield the core to a chip
  # window. Safe from self-match: this script's cmdline is
  # "bash .../scripts_chip_watch.sh".
  pkill -f "scripts_ft50_train.py" 2>/dev/null
  # settle delay for EITHER kill path: the SIGTERM'd JAX trainer needs
  # a moment to tear down before a chip session claims the core
  sleep 2
}

# stale-PID-file cleanup AFTER the liveness helper exists: a PID file
# whose process is a live ft50 trainer is ADOPTED (a watcher restart
# must not orphan its predecessor's trainer and spawn a duplicate);
# anything else is stale and removed so a recycled PID is never pinned.
cpu_trainer_alive || rm -f "$CPU_TRAINER_PID"

restart_cpu_trainer() {
  # round-5 CPU work: in-distribution fine-tune at the 50-executor
  # flagship scale (VERDICT round-4 item 2)
  if ! cpu_trainer_alive; then
    JAX_PLATFORMS=cpu nohup nice -n 10 python scripts_ft50_train.py \
      8 10 >> /tmp/ft50.log 2>&1 &
    echo "$!" > "$CPU_TRAINER_PID"
    echo "cpu ft50 trainer restarted (pid $!) at $(date +%H:%M:%S)"
  fi
}

for i in $(seq 1 40); do
  [ -f /tmp/stop_chip_watch ] && { echo "stop file; exiting"; exit 0; }
  if timeout 300 python -c "
import jax
jax.config.update('jax_compilation_cache_dir', '/root/repo/.jax_cache')
import jax.numpy as jnp
jax.block_until_ready((jnp.ones((256,256)) @ jnp.ones((256,256))).sum())
print('ALIVE')
" 2>/dev/null | grep -q ALIVE; then
    echo "chip alive at $(date +%H:%M:%S); running session"
    # stop the CPU trainer for the chip window: compiles and host-side
    # scan glue need the single core
    stop_cpu_trainer
    # headline bench at most ONCE per watcher lifetime (windows are
    # ~25 min; round-5 session 1 already committed an on-chip headline,
    # so later windows belong to the decima benches and flagship
    # training — one more stage-3 pass re-measures under the widened
    # be∈{4,8,16} calibration, then the marker stops repeats; the
    # marker is deleted at watcher launch, so "lifetime" really means
    # this watcher process, not until-reboot)
    HEADLINE_MARK=/tmp/headline_r05_remeasured
    if [ ! -f "$HEADLINE_MARK" ]; then
      timeout -k 60 3600 python scripts_chip_session.py 1 3 \
        | tee /tmp/stage3_last.log
      echo "session rc=${PIPESTATUS[0]} at $(date +%H:%M:%S)"
      grep -q '"backend": "tpu"' /tmp/stage3_last.log \
        && touch "$HEADLINE_MARK"
    else
      timeout -k 60 600 python scripts_chip_session.py 1
      echo "sanity rc=$? at $(date +%H:%M:%S)"
    fi
    [ -f /tmp/stop_chip_watch ] && { echo "stop file; exiting"; exit 0; }
    # round-5 reorder: decima benches BEFORE flagship training. The
    # round-5 session-1 window measured the headline then closed
    # ~25 min in, mid decima-compile — windows are too short to put a
    # 2 h training session ahead of the three short evidence rows the
    # VERDICT explicitly asks for (stage 4 is now per-row guarded, so
    # one dead compile no longer forfeits the stage).
    # stage-4 budget raised 2700 -> 3600 (round-5 advisor: 4 full-compile
    # rows against 2700 s in ~25-min tunnel windows meant the last row
    # was routinely truncated); rc=124 additionally logs an explicit
    # TRUNCATION_EXPECTED marker so artifact readers never misread a
    # missing trailing row as a per-row failure.
    run_with_retry 3600 "stage 4 (decima benches)" \
      python scripts_chip_session.py 4
    rc=$?
    echo "decima-bench rc=$rc at $(date +%H:%M:%S)"
    [ "$rc" -eq 124 ] && echo "TRUNCATION_EXPECTED: stage 4 hit its 3600s budget; trailing rows were cut by the watcher, not by row failures"
    [ -f /tmp/stop_chip_watch ] && { echo "stop file; exiting"; exit 0; }
    # round-6: decima_flat rows (flat-engine rollout collection — the
    # training fast path this round routed Decima through). Separate
    # stage so a truncated stage-4 window doesn't forfeit these rows.
    run_with_retry 2700 "stage 8 (decima flat benches)" \
      python scripts_chip_session.py 8
    rc=$?
    echo "decima-flat-bench rc=$rc at $(date +%H:%M:%S)"
    [ "$rc" -eq 124 ] && echo "TRUNCATION_EXPECTED: stage 8 hit its 2700s budget; trailing rows were cut by the watcher, not by row failures"
    [ -f /tmp/stop_chip_watch ] && { echo "stop file; exiting"; exit 0; }
    # one-time on-chip memory capture (ISSUE 5, stage 11): the
    # compiled.memory_analysis() bytes only the real backend can
    # produce — the ground truth the CPU-pinned memory pass's budgets
    # and lane-fit model are calibrated against. Once per watcher
    # lifetime so later windows keep going to benches + training.
    MEMCAP_MARK=/tmp/memcap_done
    if [ ! -f "$MEMCAP_MARK" ]; then
      timeout -k 60 1800 python scripts_chip_session.py 11 \
        | tee /tmp/memcap_last.log
      echo "memcap rc=${PIPESTATUS[0]} at $(date +%H:%M:%S)"
      grep -q "wrote artifacts/memory_chip.json" /tmp/memcap_last.log \
        && touch "$MEMCAP_MARK"
    fi
    [ -f /tmp/stop_chip_watch ] && { echo "stop file; exiting"; exit 0; }
    # one-time sharded multichip bench (ISSUE 6, stage 12): bench.py
    # with the lane axis sharded over every visible device. The gate
    # lives INSIDE the stage's subprocess (counting devices claims the
    # client); on today's single-chip tunnel it logs an explicit
    # "[multichip] UNAVAILABLE" marker and exits 0 — that marker (not
    # silence) is what tells the round reader no multi-chip window
    # opened. Marked done on EITHER outcome: a recorded UNAVAILABLE is
    # this lifetime's answer, and re-probing each window would burn
    # bench-sized time against an unchanged device count.
    MULTICHIP_MARK=/tmp/multichip_done
    if [ ! -f "$MULTICHIP_MARK" ]; then
      timeout -k 60 3600 python scripts_chip_session.py 12 \
        | tee /tmp/multichip_last.log
      echo "multichip rc=${PIPESTATUS[0]} at $(date +%H:%M:%S)"
      # done on ANY completed attempt — UNAVAILABLE, success, or
      # failure: the device count won't change within this lifetime,
      # so a deterministic failure would otherwise re-burn up to an
      # hour per loop and starve the flagship training stage below
      # (the log keeps the failing output for the round reader)
      touch "$MULTICHIP_MARK"
    fi
    [ -f /tmp/stop_chip_watch ] && { echo "stop file; exiting"; exit 0; }
    # one-time fused-engine headline row (ISSUE 7, stage 13): the
    # 1024-lane bench with the single fused bulk kernel plus its
    # unfused equal-config partner — the on-chip confirmation of the
    # CPU fusion A/B recorded in PERF.md round 11. Once per watcher
    # lifetime; marked done only when a TPU-backed row landed (an
    # UNAVAILABLE marker means no window yet — retry next loop).
    FUSED_MARK=/tmp/fused_headline_done
    if [ ! -f "$FUSED_MARK" ]; then
      timeout -k 60 5500 python scripts_chip_session.py 13 \
        | tee /tmp/fused_headline_last.log
      echo "fused-headline rc=${PIPESTATUS[0]} at $(date +%H:%M:%S)"
      grep -q '"backend": "tpu"' /tmp/fused_headline_last.log \
        && touch "$FUSED_MARK"
    fi
    [ -f /tmp/stop_chip_watch ] && { echo "stop file; exiting"; exit 0; }
    # one-time serving-latency capture (ISSUE 10, stage 14): the
    # 1024-session AOT store's batch=1 and batch=K p50/p99 rows — the
    # on-chip partner of the CPU latency table in PERF.md round 13.
    # Once per watcher lifetime; marked done only when a TPU-backed
    # row landed (an UNAVAILABLE marker means no window yet — retry
    # next loop, like the stage-13 slot).
    SERVE_MARK=/tmp/serve_latency_done
    if [ ! -f "$SERVE_MARK" ]; then
      timeout -k 60 2700 python scripts_chip_session.py 14 \
        | tee /tmp/serve_latency_last.log
      echo "serve-latency rc=${PIPESTATUS[0]} at $(date +%H:%M:%S)"
      grep -q '"backend": "tpu"' /tmp/serve_latency_last.log \
        && touch "$SERVE_MARK"
    fi
    [ -f /tmp/stop_chip_watch ] && { echo "stop file; exiting"; exit 0; }
    # one-time serve-scale open-loop capture (ISSUE 11, stage 15): the
    # offered-load sweep through the seeded load generator against the
    # chip-scale session store — goodput under the p99 SLO plus the
    # p99-vs-offered-load curve, the on-chip partner of the CPU sweep
    # in PERF.md round 14. Once per watcher lifetime; marked done only
    # when a TPU-backed row landed (an UNAVAILABLE marker means no
    # window yet — retry next loop, like the stage-13/14 slots).
    SERVE_SCALE_MARK=/tmp/serve_scale_done
    if [ ! -f "$SERVE_SCALE_MARK" ]; then
      timeout -k 60 2700 python scripts_chip_session.py 15 \
        | tee /tmp/serve_scale_last.log
      echo "serve-scale rc=${PIPESTATUS[0]} at $(date +%H:%M:%S)"
      grep -q '"backend": "tpu"' /tmp/serve_scale_last.log \
        && touch "$SERVE_SCALE_MARK"
    fi
    [ -f /tmp/stop_chip_watch ] && { echo "stop file; exiting"; exit 0; }
    # one-time continuous-batching A/B capture (ISSUE 13, stage 16):
    # the paired continuous-vs-linger offered-load sweep against the
    # chip-scale host-paged store — the on-chip partner of the CPU A/B
    # in artifacts/serve_scale_r13.json / PERF.md round 15. Once per
    # watcher lifetime; marked done only when a TPU-backed row landed
    # (an UNAVAILABLE marker means no window yet — retry next loop,
    # like the stage-13/14/15 slots).
    SERVE_CB_MARK=/tmp/serve_cb_done
    if [ ! -f "$SERVE_CB_MARK" ]; then
      timeout -k 60 3700 python scripts_chip_session.py 16 \
        | tee /tmp/serve_cb_last.log
      echo "serve-cb rc=${PIPESTATUS[0]} at $(date +%H:%M:%S)"
      grep -q '"backend": "tpu"' /tmp/serve_cb_last.log \
        && touch "$SERVE_CB_MARK"
    fi
    [ -f /tmp/stop_chip_watch ] && { echo "stop file; exiting"; exit 0; }
    # one-time pipelined-serve A/B capture (ISSUE 15, stage 17): the
    # paired sync-vs-pipelined offered-load sweep at chip scale —
    # continuous front on the r13 single-group store vs the pipelined
    # front on its own 4-group store (two serve architectures; see
    # the stage docstring) — the on-chip partner of the CPU A/B in
    # artifacts/serve_scale_r17.json / PERF.md round 17, queued behind
    # the 13-16 slots. Once per watcher lifetime; marked done only
    # when a TPU-backed row landed (an UNAVAILABLE marker means no
    # window yet — retry next loop, like the earlier slots).
    SERVE_PIPE_MARK=/tmp/serve_pipe_done
    if [ ! -f "$SERVE_PIPE_MARK" ]; then
      timeout -k 60 3700 python scripts_chip_session.py 17 \
        | tee /tmp/serve_pipe_last.log
      echo "serve-pipe rc=${PIPESTATUS[0]} at $(date +%H:%M:%S)"
      grep -q '"backend": "tpu"' /tmp/serve_pipe_last.log \
        && touch "$SERVE_PIPE_MARK"
    fi
    [ -f /tmp/stop_chip_watch ] && { echo "stop file; exiting"; exit 0; }
    # one-time network-serving-tier capture (ISSUE 16, stage 18): the
    # loopback HTTP A/B with the store on the chip + the replica-fleet
    # sweep behind the session-affinity router (fleet replicas on host
    # cores — one device client per chip; see the stage docstring) —
    # the on-chip partner of the CPU measurement in
    # artifacts/serve_scale_r18.json / PERF.md round 18, queued behind
    # the 13-17 slots. Once per watcher lifetime; marked done only
    # when a TPU-backed row landed (an UNAVAILABLE marker means no
    # window yet — retry next loop, like the earlier slots).
    SERVE_NET_MARK=/tmp/serve_net_done
    if [ ! -f "$SERVE_NET_MARK" ]; then
      timeout -k 60 3700 python scripts_chip_session.py 18 \
        | tee /tmp/serve_net_last.log
      echo "serve-net rc=${PIPESTATUS[0]} at $(date +%H:%M:%S)"
      grep -q '"backend": "tpu"' /tmp/serve_net_last.log \
        && touch "$SERVE_NET_MARK"
    fi
    [ -f /tmp/stop_chip_watch ] && { echo "stop file; exiting"; exit 0; }
    # one-time ring record-path A/B capture (ISSUE 18, stage 19): the
    # 1024-session store's batch=1 window record-off vs per-decision
    # record vs the device trajectory ring — the on-chip proof of the
    # blocked_host_wall_record_* family (the per-decision path pays a
    # device->host sync per decide on real silicon; the CPU A/B in
    # artifacts/serve_latency_r20.json / PERF.md round 20 bounds the
    # host-glue share only), queued behind the 13-18 slots. Once per
    # watcher lifetime; marked done only when a TPU-backed row landed
    # (an UNAVAILABLE marker means no window yet — retry next loop,
    # like the earlier slots).
    SERVE_RING_MARK=/tmp/serve_ring_done
    if [ ! -f "$SERVE_RING_MARK" ]; then
      timeout -k 60 2800 python scripts_chip_session.py 19 \
        | tee /tmp/serve_ring_last.log
      echo "serve-ring rc=${PIPESTATUS[0]} at $(date +%H:%M:%S)"
      grep -q '"backend": "tpu"' /tmp/serve_ring_last.log \
        && touch "$SERVE_RING_MARK"
    fi
    [ -f /tmp/stop_chip_watch ] && { echo "stop file; exiting"; exit 0; }
    # flagship-scale training with whatever window remains: resumable
    # sessions (state saved every session; a wedge mid-session loses at
    # most iters_per_session iterations). Retry-safe BECAUSE resumable:
    # the second attempt resumes from the atomic train-state write, so
    # a transient crash costs backoff, not the session's progress.
    run_with_retry 7200 "flagship training" \
      python scripts_flagship_train.py 20 2
    echo "flagship rc=$? at $(date +%H:%M:%S)"
    [ -f /tmp/stop_chip_watch ] && { echo "stop file; exiting"; exit 0; }
    # fault-risk 1024-lane probe LAST in the chip episode: if it wedges
    # the tunnel, nothing else in this window is lost
    timeout -k 60 1900 python scripts_chip_session.py 7
    echo "probe1024 rc=$? at $(date +%H:%M:%S)"
  else
    echo "watch $i: wedged at $(date +%H:%M:%S)"
  fi
  # idempotent (PID-file-guarded): also revives a trainer that crashed
  # during a tunnel wedge, not just after a chip episode
  restart_cpu_trainer
  sleep 1200
done
restart_cpu_trainer
