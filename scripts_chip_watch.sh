#!/bin/bash
# Poll the TPU tunnel GENTLY; the moment it answers, run the full chip
# session (benches incl. the new fulfill_bulk calibration) and then
# on-chip from-scratch PPO training. Output: /tmp/chip_watch.log
#
# Round-3 polling discipline: the round-2 watcher probed every 4 min,
# each probe a timeout-killed client — 12+ h of continuous wedge under
# that regime suggests aggressive polling may itself hold the grant.
# Poll every 20 min with a generous 300 s timeout instead, leaving long
# no-touch windows for the tunnel to clear.
cd /root/repo
for i in $(seq 1 40); do
  if timeout 300 python -c "
import jax
jax.config.update('jax_compilation_cache_dir', '/root/repo/.jax_cache')
import jax.numpy as jnp
jax.block_until_ready((jnp.ones((256,256)) @ jnp.ones((256,256))).sum())
print('ALIVE')
" 2>/dev/null | grep -q ALIVE; then
    echo "chip alive at $(date +%H:%M:%S); running session"
    timeout 4500 python scripts_chip_session.py 1 6 3 4 5
    echo "session rc=$? at $(date +%H:%M:%S)"
    # use remaining chip time for on-chip from-scratch PPO training.
    # The CPU session loop writes the same train state; stop it first
    # (it saves at each 25-iteration session boundary, so at most one
    # partial session is lost) and resume its progress on the chip.
    pkill -f "scripts_scratch_train" 2>/dev/null
    sleep 5
    timeout 9000 python scripts_scratch_train.py 40 25 r3
    echo "train rc=$? at $(date +%H:%M:%S)"
    exit 0
  fi
  echo "watch $i: wedged at $(date +%H:%M:%S)"
  sleep 1200
done
