#!/bin/bash
# Poll the TPU tunnel; the moment it answers, run the full chip session
# (benches + flagship check) in this same process slot and exit.
# Output: /tmp/chip_watch.log
cd /root/repo
for i in $(seq 1 200); do
  if timeout 120 python -c "
import jax
jax.config.update('jax_compilation_cache_dir', '/root/repo/.jax_cache')
import jax.numpy as jnp
jax.block_until_ready((jnp.ones((256,256)) @ jnp.ones((256,256))).sum())
print('ALIVE')
" 2>/dev/null | grep -q ALIVE; then
    echo "chip alive at $(date +%H:%M:%S); running session"
    timeout 4500 python scripts_chip_session.py 1 6 3 4 5
    echo "session rc=$? at $(date +%H:%M:%S)"
    # use remaining chip time for on-chip PPO training sessions
    # (resumable; scripts_train_loop honors the chip platform default)
    timeout 5400 python scripts_train_loop.py 20 3
    echo "train rc=$? at $(date +%H:%M:%S)"
    exit 0
  fi
  echo "watch $i: wedged at $(date +%H:%M:%S)"
  sleep 240
done
