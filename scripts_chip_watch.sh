#!/bin/bash
# Poll the TPU tunnel GENTLY; whenever it answers, run the chip session
# (headline bench FIRST -- tunnel windows have been ~45 min, so the
# driver-gate number must land before anything else), then hand leftover
# chip time to on-chip from-scratch PPO training. Loops: after a chip
# episode (or a wedge mid-session) the CPU trainer is restarted and
# polling resumes. Touch /tmp/stop_chip_watch to make the watcher exit
# and leave the tunnel free (e.g. before the driver's round-end bench).
#
# Round-3 polling discipline: the round-2 watcher probed every 4 min,
# each probe a timeout-killed client -- 12+ h of continuous wedge under
# that regime suggests aggressive polling may itself hold the grant.
# Poll every 20 min with a generous 300 s timeout instead.
cd /root/repo
rm -f /tmp/stop_chip_watch  # consume any stale stop request at launch

restart_cpu_trainer() {
  if ! pgrep -f "scripts_scratch_train" > /dev/null; then
    JAX_PLATFORMS=cpu nohup nice -n 10 python scripts_scratch_train.py \
      40 25 r3 >> /tmp/scratch_train_cpu.log 2>&1 &
    echo "cpu trainer restarted (pid $!) at $(date +%H:%M:%S)"
  fi
}

for i in $(seq 1 40); do
  [ -f /tmp/stop_chip_watch ] && { echo "stop file; exiting"; exit 0; }
  if timeout 300 python -c "
import jax
jax.config.update('jax_compilation_cache_dir', '/root/repo/.jax_cache')
import jax.numpy as jnp
jax.block_until_ready((jnp.ones((256,256)) @ jnp.ones((256,256))).sum())
print('ALIVE')
" 2>/dev/null | grep -q ALIVE; then
    echo "chip alive at $(date +%H:%M:%S); running session"
    timeout -k 60 4500 python scripts_chip_session.py 1 3 4 5
    echo "session rc=$? at $(date +%H:%M:%S)"
    [ -f /tmp/stop_chip_watch ] && { echo "stop file; exiting"; exit 0; }
    # use remaining chip time for on-chip from-scratch PPO training.
    # The CPU session loop writes the same train state; stop it first
    # (it saves at each 25-iteration session boundary, so at most one
    # partial session is lost) and resume its progress on the chip.
    pkill -f "scripts_scratch_train" 2>/dev/null
    sleep 5
    timeout -k 60 9000 python scripts_scratch_train.py 40 25 r3
    echo "train rc=$? at $(date +%H:%M:%S)"
    [ -f /tmp/stop_chip_watch ] && { echo "stop file; exiting"; exit 0; }
    # fault-risk 1024-lane probe LAST in the chip episode: if it wedges
    # the tunnel, nothing else in this window is lost
    timeout -k 60 1900 python scripts_chip_session.py 7
    echo "probe1024 rc=$? at $(date +%H:%M:%S)"
  else
    echo "watch $i: wedged at $(date +%H:%M:%S)"
  fi
  # idempotent (pgrep-guarded): also revives a trainer that crashed
  # during a tunnel wedge, not just after a chip episode
  restart_cpu_trainer
  sleep 1200
done
restart_cpu_trainer
