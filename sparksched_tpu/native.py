"""ctypes binding for the native host engine (native/sparksched_core.cpp).

The C++ engine is the framework's host runtime: a fast single-env
discrete-event simulator with the exact semantics of the vectorized XLA
core, used as a CPU fallback, as an independent cross-check of the TPU
program, and for single-episode tooling. Built lazily with g++ (no
pybind11 dependency — plain C ABI)."""

from __future__ import annotations

import ctypes as ct
import os
import os.path as osp
import subprocess

import numpy as np

from .config import EnvParams
from .workload.bank import EXEC_LEVEL_VALUES, WorkloadBank

_SRC = osp.join(osp.dirname(osp.dirname(osp.abspath(__file__))),
                "native", "sparksched_core.cpp")
_LIB = None


def _build_lib() -> str:
    out = osp.join(osp.dirname(_SRC), "libsparksched.so")
    if not osp.isfile(out) or os.path.getmtime(out) < os.path.getmtime(_SRC):
        subprocess.run(
            ["g++", "-O2", "-shared", "-fPIC", "-o", out, _SRC],
            check=True,
        )
    return out


def _lib() -> ct.CDLL:
    global _LIB
    if _LIB is None:
        lib = ct.CDLL(_build_lib())
        lib.ss_create.restype = ct.c_void_p
        lib.ss_create.argtypes = [
            ct.POINTER(ct.c_int32), ct.POINTER(ct.c_double),
            ct.c_int32, ct.c_int32, ct.c_int32, ct.c_int32,
            ct.POINTER(ct.c_int32), ct.POINTER(ct.c_int32),
            ct.POINTER(ct.c_uint8), ct.POINTER(ct.c_float),
            ct.POINTER(ct.c_int32), ct.POINTER(ct.c_int32),
            ct.POINTER(ct.c_float),
        ]
        lib.ss_destroy.argtypes = [ct.c_void_p]
        lib.ss_reset.argtypes = [
            ct.c_void_p, ct.POINTER(ct.c_double), ct.POINTER(ct.c_int32),
            ct.c_int32,
        ]
        lib.ss_step.restype = ct.c_double
        lib.ss_step.argtypes = [
            ct.c_void_p, ct.c_int32, ct.c_int32, ct.POINTER(ct.c_int32)
        ]
        lib.ss_wall_time.restype = ct.c_double
        lib.ss_wall_time.argtypes = [ct.c_void_p]
        lib.ss_observe.argtypes = [
            ct.c_void_p, ct.POINTER(ct.c_int32), ct.POINTER(ct.c_float),
            ct.POINTER(ct.c_uint8), ct.POINTER(ct.c_uint8),
            ct.POINTER(ct.c_int32), ct.POINTER(ct.c_int32),
            ct.POINTER(ct.c_int32), ct.POINTER(ct.c_uint8),
            ct.POINTER(ct.c_uint8),
        ]
        lib.ss_job_durations.restype = ct.c_int32
        lib.ss_job_durations.argtypes = [ct.c_void_p,
                                         ct.POINTER(ct.c_double)]
        _LIB = lib
    return _LIB


def _ptr(a: np.ndarray, dtype):
    return a.ctypes.data_as(ct.POINTER(dtype))


class NativeEnv:
    """Single-environment host engine with the `core.py` step contract
    (flat padded stage index, 1-based num_exec)."""

    def __init__(self, params: EnvParams, bank: WorkloadBank,
                 seed: int = 0) -> None:
        self.params = params
        lib = _lib()
        num_stages = np.ascontiguousarray(bank.num_stages, np.int32)
        num_tasks = np.ascontiguousarray(bank.num_tasks, np.int32)
        adj = np.ascontiguousarray(np.asarray(bank.adj), np.uint8)
        dur = np.ascontiguousarray(bank.dur, np.float32)
        cnt = np.ascontiguousarray(bank.cnt, np.int32)
        rough = np.ascontiguousarray(bank.rough_duration, np.float32)
        levels = np.ascontiguousarray(EXEC_LEVEL_VALUES, np.int32)
        t, s = num_tasks.shape
        _, _, _, L, K = dur.shape
        iparams = np.array(
            [params.num_executors, params.max_jobs, seed], np.int32
        )
        dparams = np.array(
            [params.moving_delay, params.warmup_delay], np.float64
        )
        assert s == params.max_stages, (s, params.max_stages)
        self._h = lib.ss_create(
            _ptr(iparams, ct.c_int32), _ptr(dparams, ct.c_double),
            t, s, L, K,
            _ptr(num_stages, ct.c_int32), _ptr(num_tasks, ct.c_int32),
            _ptr(adj, ct.c_uint8), _ptr(dur, ct.c_float),
            _ptr(cnt, ct.c_int32), _ptr(levels, ct.c_int32),
            _ptr(rough, ct.c_float),
        )
        self._lib = lib
        self.terminated = False

    def __del__(self) -> None:
        if getattr(self, "_h", None):
            self._lib.ss_destroy(self._h)
            self._h = None

    def reset(self, arrivals: np.ndarray, templates: np.ndarray) -> None:
        arrivals = np.ascontiguousarray(arrivals, np.float64)
        templates = np.ascontiguousarray(templates, np.int32)
        self._lib.ss_reset(
            self._h, _ptr(arrivals, ct.c_double),
            _ptr(templates, ct.c_int32), len(arrivals),
        )
        self.terminated = False

    def step(self, stage_idx: int, num_exec: int) -> tuple[float, bool]:
        term = ct.c_int32(0)
        r = self._lib.ss_step(self._h, int(stage_idx), int(num_exec),
                              ct.byref(term))
        self.terminated = bool(term.value)
        return float(r), self.terminated

    @property
    def wall_time(self) -> float:
        return float(self._lib.ss_wall_time(self._h))

    def observe(self) -> dict[str, np.ndarray]:
        p = self.params
        js = p.max_jobs * p.max_stages
        remaining = np.zeros(js, np.int32)
        duration = np.zeros(js, np.float32)
        schedulable = np.zeros(js, np.uint8)
        frontier = np.zeros(js, np.uint8)
        supplies = np.zeros(p.max_jobs, np.int32)
        job_mask = np.zeros(p.max_jobs, np.uint8)
        node_mask = np.zeros(js, np.uint8)
        committable = ct.c_int32(0)
        source_job = ct.c_int32(0)
        self._lib.ss_observe(
            self._h, _ptr(remaining, ct.c_int32), _ptr(duration, ct.c_float),
            _ptr(schedulable, ct.c_uint8), _ptr(frontier, ct.c_uint8),
            _ptr(supplies, ct.c_int32), ct.byref(committable),
            ct.byref(source_job), _ptr(job_mask, ct.c_uint8),
            _ptr(node_mask, ct.c_uint8),
        )
        shape = (p.max_jobs, p.max_stages)
        return {
            "remaining": remaining.reshape(shape),
            "duration": duration.reshape(shape),
            "schedulable": schedulable.reshape(shape).astype(bool),
            "frontier": frontier.reshape(shape).astype(bool),
            "exec_supplies": supplies,
            "job_mask": job_mask.astype(bool),
            "node_mask": node_mask.reshape(shape).astype(bool),
            "num_committable": int(committable.value),
            "source_job": int(source_job.value),
        }

    def job_durations(self) -> np.ndarray:
        out = np.zeros(self.params.max_jobs, np.float64)
        n = self._lib.ss_job_durations(self._h, _ptr(out, ct.c_double))
        return out[:n]
