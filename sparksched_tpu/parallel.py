"""Multi-chip scale-out over a `jax.sharding.Mesh`.

The reference's only parallelism is process-level fan-out of rollout
workers glued with `mp.Pipe` (reference trainers/trainer.py:264-296).
The TPU-native equivalent has two layers:

- on-chip: `jax.vmap` already runs thousands of env lanes per core — that
  alone replaces the reference's N worker processes;
- across chips: the lane axis is sharded over a 1-D `dp` mesh axis with
  `NamedSharding(P("dp"))`. Rollout collection is embarrassingly parallel
  along lanes; the PPO update's global minibatch permutation, advantage
  normalization and gradient reduction become XLA collectives (all-gather /
  psum) over ICI — no NCCL, no parameter scatter, no pickling. Multi-host
  works the same way: the mesh simply spans hosts and the same collectives
  ride DCN.
"""

from __future__ import annotations

import re
from typing import Any

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DP_AXIS = "dp"
HOST_AXIS = "host"


def make_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    """1-D data-parallel mesh over the first `n_devices` devices."""
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        assert len(devices) >= n_devices, (
            f"need {n_devices} devices, have {len(devices)}"
        )
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (DP_AXIS,))


def make_host_device_mesh(
    n_hosts: int | None = None, devices_per_host: int | None = None,
    devices=None,
) -> Mesh:
    """2-D ("host", "dp") mesh for multi-host runs.

    Lanes shard over BOTH axes (`lane_sharding` spans every mesh axis),
    so rollout collection stays embarrassingly parallel; the update's
    reductions become hierarchical collectives — XLA reduces along the
    fast "dp" (intra-host ICI) axis before the "host" (DCN) axis, which
    is exactly the hierarchy the reference's per-process workers + one
    learner lacked. Defaults follow jax's process topology
    (`jax.process_count()` x local device count); pass explicit factors
    to build a virtual multi-host mesh on a flat device list (tests)."""
    if devices is None:
        devices = jax.devices()
    if n_hosts is None:
        n_hosts = jax.process_count()
    if devices_per_host is None:
        devices_per_host = len(devices) // n_hosts
    need = n_hosts * devices_per_host
    assert len(devices) >= need, (
        f"need {need} devices, have {len(devices)}"
    )
    # jax.devices() order does not guarantee per-host contiguity on all
    # topologies; group by owning process first so each mesh row really
    # is one host's chips (otherwise "dp" reductions silently cross DCN
    # and the hierarchy claim above inverts)
    devices = sorted(devices, key=lambda d: (d.process_index, d.id))
    grid = np.array(devices[:need]).reshape(n_hosts, devices_per_host)
    if jax.process_count() > 1:
        for row in grid:
            assert len({d.process_index for d in row}) == 1, (
                "a host row mixes devices from different processes — "
                "pass explicit per-host `devices`"
            )
    return Mesh(grid, (HOST_AXIS, DP_AXIS))


def lane_sharding(mesh: Mesh) -> NamedSharding:
    """Shard the leading (env-lane) axis over every mesh axis (1-D dp
    meshes and 2-D host x device meshes alike)."""
    return NamedSharding(mesh, P(tuple(mesh.axis_names)))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_lanes(tree, mesh: Mesh):
    """Place a [B, ...] pytree with its lane axis sharded over the mesh."""
    return jax.device_put(tree, lane_sharding(mesh))


def constrain_lanes(tree, sharding: NamedSharding):
    """`with_sharding_constraint` every leaf's leading (lane) axis —
    applied to the collection scan's carry buffers so XLA's SPMD
    partitioner keeps them lane-sharded instead of falling back to a
    replicated layout mid-scan (every leaf must carry a leading [B]
    axis; scalars like the scan's PRNG key stay outside the tree)."""
    return jax.tree_util.tree_map(
        lambda a: jax.lax.with_sharding_constraint(a, sharding), tree
    )


# ---------------------------------------------------------------------------
# config wiring: the `parallel:` YAML block
# ---------------------------------------------------------------------------


def mesh_from_config(cfg: dict[str, Any] | None) -> Mesh | None:
    """Resolve the top-level `parallel:` config block to a mesh.

    Contract (config/decima_tpch_multichip.yaml documents the YAML
    side): `dp: auto` takes every visible device; `dp: N` demands
    exactly N and fails loudly when the host has fewer (a silent
    single-chip fallback would report sharded dec/s that never
    sharded). A resolved dp of 1 returns None — the unsharded jit path
    is the same program without the sharding plumbing, and a 1-device
    mesh would only add layout bookkeeping."""
    if not cfg:
        return None
    dp = cfg.get("dp", "auto")
    if dp in ("auto", None):
        dp = len(jax.devices())
    dp = int(dp)
    if dp <= 1:
        return None
    return make_mesh(dp)


# ---------------------------------------------------------------------------
# collective census: the HLO-level contract of the sharded update
# ---------------------------------------------------------------------------

COLLECTIVE_RE = re.compile(
    r"\b(all-reduce|all-gather|reduce-scatter|collective-permute|"
    r"all-to-all)\b"
)

# what the shard-aligned update is ALLOWED to lower to: the gradient /
# advantage-normalization reductions (all-reduce), their occasional
# reduce-scatter re-association, and the small gathers of per-shard
# scalars (KL early-stop predicate, loss means)
EXPECTED_UPDATE_COLLECTIVES = frozenset(
    {"all-reduce", "all-gather", "reduce-scatter"}
)
# what it must NEVER contain: resharding families. An all-to-all or
# collective-permute in the update means the minibatch permutation
# stopped being shard-aligned (e.g. someone reintroduced a global
# B*T shuffle) and every grad step now pays a full rollout reshuffle
# over ICI/DCN — the regression tests/test_parallel.py's census pins.
FORBIDDEN_UPDATE_COLLECTIVES = frozenset(
    {"all-to-all", "collective-permute"}
)


def compiled_flops(compiled) -> float:
    """Per-device FLOPs from an AOT-compiled program's cost analysis.
    `Compiled.cost_analysis()` returned a bare dict before jax 0.4.30ish
    and a one-element list of dicts after — accept both (the mesh
    accounting script and the dp-scaling test share this)."""
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    return float(ca.get("flops", 0.0))


def collective_census(hlo_text: str) -> dict[str, int]:
    """Count collective ops in an optimized-HLO dump, by family.
    Shared by the mesh-accounting script and the census test so the
    two cannot drift on what counts as a collective."""
    counts: dict[str, int] = {}
    for m in COLLECTIVE_RE.finditer(hlo_text):
        counts[m.group(1)] = counts.get(m.group(1), 0) + 1
    return counts
