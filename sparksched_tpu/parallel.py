"""Multi-chip scale-out over a `jax.sharding.Mesh`.

The reference's only parallelism is process-level fan-out of rollout
workers glued with `mp.Pipe` (reference trainers/trainer.py:264-296).
The TPU-native equivalent has two layers:

- on-chip: `jax.vmap` already runs thousands of env lanes per core — that
  alone replaces the reference's N worker processes;
- across chips: the lane axis is sharded over a 1-D `dp` mesh axis with
  `NamedSharding(P("dp"))`. Rollout collection is embarrassingly parallel
  along lanes; the PPO update's global minibatch permutation, advantage
  normalization and gradient reduction become XLA collectives (all-gather /
  psum) over ICI — no NCCL, no parameter scatter, no pickling. Multi-host
  works the same way: the mesh simply spans hosts and the same collectives
  ride DCN.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DP_AXIS = "dp"


def make_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    """1-D data-parallel mesh over the first `n_devices` devices."""
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        assert len(devices) >= n_devices, (
            f"need {n_devices} devices, have {len(devices)}"
        )
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (DP_AXIS,))


def lane_sharding(mesh: Mesh) -> NamedSharding:
    """Shard the leading (env-lane) axis over the dp mesh axis."""
    return NamedSharding(mesh, P(DP_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_lanes(tree, mesh: Mesh):
    """Place a [B, ...] pytree with its lane axis sharded over the mesh."""
    return jax.device_put(tree, lane_sharding(mesh))
