"""Multi-chip scale-out over a `jax.sharding.Mesh`.

The reference's only parallelism is process-level fan-out of rollout
workers glued with `mp.Pipe` (reference trainers/trainer.py:264-296).
The TPU-native equivalent has two layers:

- on-chip: `jax.vmap` already runs thousands of env lanes per core — that
  alone replaces the reference's N worker processes;
- across chips: the lane axis is sharded over a 1-D `dp` mesh axis with
  `NamedSharding(P("dp"))`. Rollout collection is embarrassingly parallel
  along lanes; the PPO update's global minibatch permutation, advantage
  normalization and gradient reduction become XLA collectives (all-gather /
  psum) over ICI — no NCCL, no parameter scatter, no pickling. Multi-host
  works the same way: the mesh simply spans hosts and the same collectives
  ride DCN.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DP_AXIS = "dp"
HOST_AXIS = "host"


def make_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    """1-D data-parallel mesh over the first `n_devices` devices."""
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        assert len(devices) >= n_devices, (
            f"need {n_devices} devices, have {len(devices)}"
        )
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (DP_AXIS,))


def make_host_device_mesh(
    n_hosts: int | None = None, devices_per_host: int | None = None,
    devices=None,
) -> Mesh:
    """2-D ("host", "dp") mesh for multi-host runs.

    Lanes shard over BOTH axes (`lane_sharding` spans every mesh axis),
    so rollout collection stays embarrassingly parallel; the update's
    reductions become hierarchical collectives — XLA reduces along the
    fast "dp" (intra-host ICI) axis before the "host" (DCN) axis, which
    is exactly the hierarchy the reference's per-process workers + one
    learner lacked. Defaults follow jax's process topology
    (`jax.process_count()` x local device count); pass explicit factors
    to build a virtual multi-host mesh on a flat device list (tests)."""
    if devices is None:
        devices = jax.devices()
    if n_hosts is None:
        n_hosts = jax.process_count()
    if devices_per_host is None:
        devices_per_host = len(devices) // n_hosts
    need = n_hosts * devices_per_host
    assert len(devices) >= need, (
        f"need {need} devices, have {len(devices)}"
    )
    # jax.devices() order does not guarantee per-host contiguity on all
    # topologies; group by owning process first so each mesh row really
    # is one host's chips (otherwise "dp" reductions silently cross DCN
    # and the hierarchy claim above inverts)
    devices = sorted(devices, key=lambda d: (d.process_index, d.id))
    grid = np.array(devices[:need]).reshape(n_hosts, devices_per_host)
    if jax.process_count() > 1:
        for row in grid:
            assert len({d.process_index for d in row}) == 1, (
                "a host row mixes devices from different processes — "
                "pass explicit per-host `devices`"
            )
    return Mesh(grid, (HOST_AXIS, DP_AXIS))


def lane_sharding(mesh: Mesh) -> NamedSharding:
    """Shard the leading (env-lane) axis over every mesh axis (1-D dp
    meshes and 2-D host x device meshes alike)."""
    return NamedSharding(mesh, P(tuple(mesh.axis_names)))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_lanes(tree, mesh: Mesh):
    """Place a [B, ...] pytree with its lane axis sharded over the mesh."""
    return jax.device_put(tree, lane_sharding(mesh))
