"""The vectorized Spark scheduling simulator: pure reset/step functions.

Semantics mirror the reference `SparkSchedSimEnv`
(spark_sched_sim/spark_sched_sim.py) exactly — commitment rounds, executor
pools, backup scheduling, moving delays, wave-based task durations — but the
implementation is a branch-free-per-lane state machine over the SoA
`EnvState`, so `jax.vmap(step)` advances thousands of simulations per TPU
core and `lax.while_loop` replaces the Python event loop.

Action encoding: `stage_idx` is a *flat padded node index* j * max_stages + s
(or -1 for "no selection"), unlike the reference's index into the compacted
list of schedulable stages (spark_sched_sim.py:284). Adapters convert.
`num_exec` is 1-based like the raw reference env (1..num_executors).

Invalid actions (unschedulable stage, out-of-range executor counts) are
handled by clamping — selecting an unschedulable stage behaves like -1 and
executor counts are clipped to [1, num_committable] — where the reference
raises ValueError (:275-295). Under jit there is no raising; policies are
expected to respect the masks.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from ..config import EnvParams
from ..obs.telemetry import add as _tm_add
from ..workload.bank import WorkloadBank
from ..workload.sampling import sample_job_sequence, sample_task_duration
from .state import (
    BIG_SEQ,
    EV_EXECUTOR_READY,
    EV_JOB_ARRIVAL,
    EV_TASK_FINISHED,
    INF,
    EnvState,
    empty_state,
    topo_levels,  # shared levels reduction (re-exported; observe/tests
    # and the golden property all use the single state.py copy)
)

_i32 = jnp.int32


def _onehot(n: int, e: jnp.ndarray) -> jnp.ndarray:
    return jnp.arange(n, dtype=_i32) == e


def _onehot2(j_cap: int, s_cap: int, j: jnp.ndarray, s: jnp.ndarray
             ) -> jnp.ndarray:
    """bool[j_cap, s_cap] mask selecting exactly (j, s); all-false when
    either index is out of range (e.g. -1 pool sentinels)."""
    return _onehot(j_cap, j)[:, None] & _onehot(s_cap, s)[None, :]


# --------------------------------------------------------------------------
# schedulable-stage computation (reference :505-555)
# --------------------------------------------------------------------------


def find_schedulable(
    params: EnvParams, state: EnvState, source_job_id: jnp.ndarray
) -> jnp.ndarray:
    """bool[J,S]. A stage is schedulable iff its job passes the saturation
    filter (source job exempt), it is ready (unsaturated with all parents
    saturated), and it was not selected this round."""
    j_idx = jnp.arange(params.max_jobs, dtype=_i32)
    job_ok = state.job_active & (
        (j_idx == source_job_id)
        | (state.job_supply < params.num_executors)
    )
    # incremental caches replace the [J,S,S] reduction the reference's
    # Python version implies (stage_sat / unsat_parent_count are updated at
    # every demand mutation; golden recomputations checked in tests)
    sat = state.stage_sat
    ready = state.stage_exists & ~sat & (state.unsat_parent_count == 0)
    return job_ok[:, None] & ready & ~state.stage_selected


def _refresh_sat(state: EnvState, j: jnp.ndarray, s: jnp.ndarray,
                 enable: jnp.ndarray = True) -> EnvState:
    """Recompute saturation of stage (j,s) after a demand mutation and
    propagate the flip to its children's unsaturated-parent counts.

    Written as masked whole-array selects rather than `.at[j, s]`
    scatters: under `jax.vmap` a batched scatter is a serialized kernel,
    while broadcast+select fuses with the surrounding elementwise work."""
    demand = (
        state.stage_remaining[j, s]
        - state.moving_count[j, s]
        - state.commit_count[j, s]
    )
    new = demand <= 0
    old = state.stage_sat[j, s]
    # only existing stages count as unsaturated parents
    delta = jnp.where(
        enable & state.stage_exists[j, s],
        new.astype(_i32) - old.astype(_i32),
        0,
    )
    j_cap, s_cap = state.stage_sat.shape
    oj = _onehot(j_cap, j)
    m2 = oj[:, None] & _onehot(s_cap, s)[None, :]
    return state.replace(
        stage_sat=jnp.where(m2 & enable, new, state.stage_sat),
        unsat_parent_count=state.unsat_parent_count
        - delta * (oj[:, None] & state.adj[j, s][None, :]).astype(_i32),
    )


# --------------------------------------------------------------------------
# executor pool moves (reference executor_tracker + spark_sched_sim helpers)
# --------------------------------------------------------------------------


def _move_idle_from_pool(
    state: EnvState, pj: jnp.ndarray, ps: jnp.ndarray, mask: jnp.ndarray
) -> EnvState:
    """_move_idle_executors (reference :745-782): no-op for the common pool
    and for unsaturated job pools; otherwise masked executors move to the
    common pool (job saturated — detaching them) or to the job pool (task
    reference intentionally retained, matching the reference's
    move_executor_to_pool which does not clear `executor.task`)."""
    sat = state.job_saturated[jnp.maximum(pj, 0)]
    noop = (pj < 0) | ((ps < 0) & ~sat)
    m = mask & ~noop
    to_common = m & sat
    return state.replace(
        exec_at_common=jnp.where(to_common, True, state.exec_at_common),
        exec_job=jnp.where(to_common, -1, state.exec_job),
        exec_stage=jnp.where(m, -1, state.exec_stage),
        exec_task_valid=jnp.where(
            to_common, False, state.exec_task_valid
        ),
    )


def _exec_location(state: EnvState, e: jnp.ndarray):
    """Pool key of executor e: (-1,-1) for common; (job, stage|-1) else."""
    pj = jnp.where(state.exec_at_common[e], -1, state.exec_job[e])
    ps = jnp.where(state.exec_at_common[e], -1, state.exec_stage[e])
    return pj, ps


# --------------------------------------------------------------------------
# task execution (reference :584-615)
#
# IMPORTANT STRUCTURAL CONSTRAINT: under `jax.vmap`, a `lax.cond`/`switch`
# with a lane-dependent predicate broadcasts EVERY operand — including
# closed-over constants like the workload bank's duration tables — across
# the batch (jax _cond_batching_rule: "we broadcast the input operands for
# simplicity"). At 1024+ lanes that materializes gigabytes. Therefore the
# event-loop machinery below is phase-split: conditional branches only
# touch `EnvState` and scalars, every event resolves to a small action
# descriptor (kind, executor, target stage), and the task-duration sample —
# the only bank access — happens UNCONDITIONALLY at loop-body top level,
# where it is an ordinary batched gather from the shared table.
# --------------------------------------------------------------------------

# move-request kinds produced by event phase-A handlers
RQ_NONE, RQ_START, RQ_MOVE = 0, 1, 2
# resolved action kinds consumed by _apply_action
A_NONE, A_START, A_SEND, A_IDLE, A_PARK = 0, 1, 2, 3, 4


# --------------------------------------------------------------------------
# backup scheduling (reference :784-845)
# --------------------------------------------------------------------------


def _find_backup_stage(params: EnvParams, state: EnvState, e: jnp.ndarray,
                       quirk_src: jnp.ndarray):
    """Greedy local-then-global search for a stage to absorb an executor
    that arrived somewhere it is no longer needed. Reproduces the
    reference's `if not source_job_id` falsiness quirk (:521-522): when the
    executor's job id is 0, the saturation-filter exemption falls back to
    the tracker's source job *as it was when the reference would run this
    search* (`quirk_src` — phase-A handlers may update the tracked source
    before the search runs here)."""
    own = state.exec_job[e]
    eff_src = jnp.where(own == 0, quirk_src, own)
    sched = find_schedulable(params, state, eff_src)
    j_cap, s_cap = sched.shape
    flat = sched.reshape(-1)
    pos = jnp.arange(j_cap * s_cap, dtype=_i32)
    job_of = pos // s_cap

    local = flat & (job_of == own)
    other = flat & (job_of != own)

    local_any = local.any()
    local_idx = jnp.argmax(local)
    other_any = other.any()
    other_idx = jnp.argmax(other)

    found = local_any | other_any
    idx = jnp.where(local_any, local_idx, other_idx)
    return found, idx // s_cap, idx % s_cap


# --------------------------------------------------------------------------
# executor -> stage movement resolution (reference :699-845)
# --------------------------------------------------------------------------


def _resolve_action(
    params: EnvParams, state: EnvState, req_kind: jnp.ndarray,
    e: jnp.ndarray, rj: jnp.ndarray, rs: jnp.ndarray,
    quirk_src: jnp.ndarray,
):
    """Resolve a phase-A move request into a concrete action. Pure mask
    arithmetic over the state; the reference's nested-branch version is
    _move_executor_to_stage (:784-845 saturated/backup layer) +
    _mets_inner send/start/park (:799-819)."""
    j = jnp.maximum(rj, 0)
    s = jnp.maximum(rs, 0)
    saturated = state.stage_remaining[j, s] == 0
    found, bj, bs = _find_backup_stage(params, state, e, quirk_src)
    use_backup = saturated & found
    tj = jnp.where(use_backup, bj, j)
    ts = jnp.where(use_backup, bs, s)
    dead = saturated & ~found
    send = state.exec_job[e] != tj
    start = state.frontier[tj, ts]
    ak_move = jnp.where(
        dead, A_IDLE,
        jnp.where(send, A_SEND, jnp.where(start, A_START, A_PARK)),
    )
    ak = jnp.where(
        req_kind == RQ_MOVE, ak_move,
        jnp.where(req_kind == RQ_START, A_START, A_NONE),
    )
    tj = jnp.where(req_kind == RQ_MOVE, tj, j)
    ts = jnp.where(req_kind == RQ_MOVE, ts, s)
    return ak.astype(_i32), tj.astype(_i32), ts.astype(_i32)


def _apply_action(
    params: EnvParams, bank: WorkloadBank, state: EnvState,
    ak: jnp.ndarray, e: jnp.ndarray, tj: jnp.ndarray, ts: jnp.ndarray
) -> EnvState:
    """Apply a resolved action. The duration is sampled unconditionally
    here — the only bank access — so no conditional branch closes over the
    bank tables (see structural note above). The rng is advanced once per
    call regardless of the action kind.

    This is the hottest function in the engine (every micro-step and every
    event-loop iteration ends here), so instead of a `lax.switch` over
    START/SEND/IDLE/PARK branches full of `.at[e].set` scatters — under
    vmap every branch executes anyway and batched scatters serialize — the
    five action semantics (reference `_execute_next_task` :584-615,
    `_send_executor` :617-637, `_move_idle_executors` :745-782, park) are
    fused into one straight-line pass of masked whole-array selects, at
    most one update per state field."""
    rng, sub = jax.random.split(state.rng)
    e = jnp.clip(e, 0, state.exec_job.shape[0] - 1)
    tpl = state.job_template[tj]
    num_local = (state.exec_job == tj).sum()
    dur = sample_task_duration(
        params, bank, jax.random.uniform(sub, (2,)), tpl, ts, num_local,
        state.exec_task_valid[e], state.exec_task_stage[e] == ts,
    )

    n = state.exec_job.shape[0]
    j_cap, s_cap = state.stage_remaining.shape
    one_e = _onehot(n, e)
    oj = _onehot(j_cap, tj)
    m2 = _onehot2(j_cap, s_cap, tj, ts)

    is_start = ak == A_START
    is_send = ak == A_SEND
    is_idle = ak == A_IDLE
    is_park = ak == A_PARK

    # IDLE = _move_idle_executors for the single executor e: no-op for the
    # common pool and unsaturated job pools; saturated job -> common pool
    pj, ps = _exec_location(state, e)
    pool_sat = state.job_saturated[jnp.maximum(pj, 0)]
    idle_eff = is_idle & ~((pj < 0) | ((ps < 0) & ~pool_sat))
    idle_common = idle_eff & pool_sat

    # START/SEND bookkeeping read before any mutation
    seq = state.seq_counter
    old_job = state.exec_job[e]
    newly_saturated = is_start & (state.stage_remaining[tj, ts] == 1)

    i32_ = lambda b: b.astype(_i32)  # noqa: E731
    m2_start = m2 & is_start

    state = state.replace(
        rng=rng,
        seq_counter=seq + i32_(is_start | is_send),
        # --- executor fields (single slot e) ---
        exec_stage=jnp.where(
            one_e & (is_start | is_send | idle_eff | is_park),
            jnp.where(is_start, ts, -1),
            state.exec_stage,
        ),
        exec_task_valid=jnp.where(
            one_e & (is_start | is_send | idle_common | is_park),
            is_start,
            state.exec_task_valid,
        ),
        exec_at_common=jnp.where(
            one_e & (is_send | idle_common),
            idle_common,
            state.exec_at_common,
        ),
        exec_job=jnp.where(
            one_e & (is_send | idle_common), -1, state.exec_job
        ),
        exec_moving=state.exec_moving | (one_e & is_send),
        exec_dst_job=jnp.where(one_e & is_send, tj, state.exec_dst_job),
        exec_dst_stage=jnp.where(
            one_e & is_send, ts, state.exec_dst_stage
        ),
        exec_arrive_time=jnp.where(
            one_e & is_send,
            state.wall_time + params.moving_delay,
            state.exec_arrive_time,
        ),
        exec_arrive_seq=jnp.where(
            one_e & is_send, seq, state.exec_arrive_seq
        ),
        exec_executing=state.exec_executing | (one_e & is_start),
        exec_task_stage=jnp.where(
            one_e & is_start, ts, state.exec_task_stage
        ),
        exec_finish_time=jnp.where(
            one_e & is_start,
            state.wall_time + dur,
            state.exec_finish_time,
        ),
        exec_finish_seq=jnp.where(
            one_e & is_start, seq, state.exec_finish_seq
        ),
        # --- job fields ---
        job_supply=state.job_supply
        + i32_(oj & is_send)
        - i32_(_onehot(j_cap, old_job) & is_send & (old_job >= 0)),
        job_saturated_stages=state.job_saturated_stages
        + i32_(oj & newly_saturated),
        # --- stage fields ---
        stage_remaining=state.stage_remaining - i32_(m2_start),
        stage_executing=state.stage_executing + i32_(m2_start),
        stage_duration=jnp.where(
            m2_start, dur, state.stage_duration
        ),
        moving_count=state.moving_count + i32_(m2 & is_send),
    )
    return _refresh_sat(state, tj, ts, enable=is_start | is_send)


# --------------------------------------------------------------------------
# commitments (reference executor_tracker.py:146-249)
# --------------------------------------------------------------------------


def _add_commitment(
    state: EnvState, n: jnp.ndarray, dj: jnp.ndarray, ds: jnp.ndarray
) -> EnvState:
    """Create n commitment slots from the current source pool to (dj, ds).
    Slots for an existing (src, dst) pair inherit its sequence number so
    `peek` preserves the reference's dict-insertion order."""
    src_j, src_s = state.source_job, state.source_stage
    match = (
        state.cm_valid
        & (state.cm_src_job == src_j)
        & (state.cm_src_stage == src_s)
        & (state.cm_dst_job == dj)
        & (state.cm_dst_stage == ds)
    )
    has_match = match.any()
    inherited = jnp.where(match, state.cm_seq, BIG_SEQ).min()
    seq = jnp.where(has_match, inherited, state.seq_counter)

    free = ~state.cm_valid
    take = free & (jnp.cumsum(free.astype(_i32)) <= n)

    j_cap, s_cap = state.commit_count.shape
    oj = _onehot(j_cap, dj)  # all-false when dj == -1
    supply = state.job_supply + n * (oj & (dj != src_j)).astype(_i32)
    cc = state.commit_count + n * _onehot2(j_cap, s_cap, dj, ds).astype(
        _i32
    )

    state = state.replace(
        seq_counter=state.seq_counter + jnp.where(has_match, 0, 1),
        job_supply=supply,
        commit_count=cc,
        cm_valid=state.cm_valid | take,
        cm_src_job=jnp.where(take, src_j, state.cm_src_job),
        cm_src_stage=jnp.where(take, src_s, state.cm_src_stage),
        cm_dst_job=jnp.where(take, dj, state.cm_dst_job),
        cm_dst_stage=jnp.where(take, ds, state.cm_dst_stage),
        cm_seq=jnp.where(take, seq, state.cm_seq),
    )
    return _refresh_sat(
        state, jnp.maximum(dj, 0), jnp.maximum(ds, 0), enable=dj >= 0
    )


def _commit_remaining(state: EnvState) -> EnvState:
    """reference :487-503 — commit uncommitted source executors to the
    common pool."""
    n = state.num_committable()
    return lax.cond(
        n > 0,
        lambda st: _add_commitment(st, n, _i32(-1), _i32(-1)),
        lambda st: st,
        state,
    )


def _peek_commitment(state: EnvState, pj: jnp.ndarray, ps: jnp.ndarray):
    """First outgoing commitment from pool (pj, ps) in insertion order
    (reference executor_tracker.py:175-181). Returns (exists, slot)."""
    match = (
        state.cm_valid
        & (state.cm_src_job == pj)
        & (state.cm_src_stage == ps)
    )
    key = jnp.where(match, state.cm_seq, BIG_SEQ)
    return match.any(), jnp.argmin(key)


def _fulfill_commitment_phase_a(
    state: EnvState, e: jnp.ndarray, slot: jnp.ndarray
):
    """reference :699-712 — consume one commitment slot with executor e.
    Pure bookkeeping + move request; the actual move is resolved/applied by
    the caller (see structural note above). Returns
    (state, req_kind, rj, rs)."""
    dj = state.cm_dst_job[slot]
    ds = state.cm_dst_stage[slot]
    sj = state.cm_src_job[slot]
    j_cap, s_cap = state.commit_count.shape
    oj = _onehot(j_cap, dj)  # all-false when dj == -1
    m2 = _onehot2(j_cap, s_cap, dj, ds)
    state = state.replace(
        cm_valid=state.cm_valid
        & ~_onehot(state.cm_valid.shape[0], slot),
        job_supply=state.job_supply - (oj & (dj != sj)).astype(_i32),
        commit_count=state.commit_count - m2.astype(_i32),
    )
    state = _refresh_sat(
        state, jnp.maximum(dj, 0), jnp.maximum(ds, 0), enable=dj >= 0
    )

    def to_common(st: EnvState):
        pj, ps = _exec_location(st, e)
        n = st.exec_job.shape[0]
        st = _move_idle_from_pool(st, pj, ps, _onehot(n, e))
        return st, _i32(RQ_NONE), _i32(-1), _i32(-1)

    def to_stage(st: EnvState):
        return st, _i32(RQ_MOVE), dj, ds

    return lax.cond(dj < 0, to_common, to_stage, state)


def _exec_scatter(sel):
    """Masked per-executor scatter helpers over a [candidate, executor]
    selection matrix in which every executor is selected at most once
    (shared by the bulk passes)."""

    def exset(base, cond, payload):
        msel = sel & cond[:, None]
        val = jnp.where(msel, payload[:, None], 0).sum(0)
        return jnp.where(msel.any(0), val.astype(base.dtype), base)

    def exflag(base, cond, value):
        return jnp.where((sel & cond[:, None]).any(0), value, base)

    return exset, exflag


def _bulk_fulfill(
    params: EnvParams, bank: WorkloadBank, state: EnvState,
    num_idle: jnp.ndarray, exec_order: jnp.ndarray,
    slot_order: jnp.ndarray,
):
    """Consume the maximal *simple* prefix of the fulfillment phase in
    one vectorized pass. Returns (state, m): candidates 0..m-1 of the
    (exec_order, slot_order) pairing are fully processed; the caller
    finishes the rest (backup-scheduling cases) on the one-at-a-time
    path.

    Each executor is fulfilled at most once per phase, so unlike the
    relaunch cascade there is no sequential generation structure: the
    only cross-candidate coupling is through per-stage/per-job counters
    (unlaunched-task counts, saturated-stage counts, executor-on-job
    counts), all reconstructible per candidate with N^2 prefix sums.
    A candidate is *simple* — its classification is static — iff its
    commitment targets the common pool (dj < 0) or its destination
    stage still has unlaunched tasks at its turn (rem0 minus earlier
    prefix starts > 0, the `_resolve_action` unsaturated case, which
    resolves to A_SEND / A_START / A_PARK by static facts: executor's
    job vs destination, destination frontier membership). The prefix
    stops at the first saturated-destination candidate, whose
    backup-stage search depends on the live saturation caches.

    Matches the sequential path bit-exactly except the rng stream
    (per-candidate pre-derived keys, as in `_bulk_relaunch`).
    """
    n = state.exec_job.shape[0]
    j_cap, s_cap = state.stage_remaining.shape
    pos = jnp.arange(n, dtype=_i32)

    e = exec_order
    slot = slot_order
    dj = state.cm_dst_job[slot]
    ds0 = state.cm_dst_stage[slot]
    sjs = state.cm_src_job[slot]
    ejob = state.exec_job[e]
    djc = jnp.clip(dj, 0, j_cap - 1)
    dsc = jnp.clip(ds0, 0, s_cap - 1)

    valid = pos < num_idle
    common_dst = dj < 0
    send0 = ~common_dst & (ejob != dj)
    frontier_k = state.frontier[djc, dsc]
    start0 = ~common_dst & ~send0 & frontier_k
    park0 = ~common_dst & ~send0 & ~frontier_k

    flat = djc * s_cap + dsc
    stage_pair = (
        (flat[None, :] == flat[:, None])
        & ~common_dst[None, :]
        & ~common_dst[:, None]
    )
    earlier = pos[None, :] < pos[:, None]
    cum_starts = (earlier & stage_pair & start0[None, :]).sum(-1)
    rem0 = state.stage_remaining[djc, dsc]
    saturated = ~common_dst & (rem0 - cum_starts == 0)
    ok = valid & ~saturated
    prefix = (jnp.cumsum((~ok).astype(_i32)) == 0) & valid
    m = prefix.sum().astype(_i32)

    send = send0 & prefix
    start = start0 & prefix
    park = park0 & prefix
    common_k = common_dst & prefix

    # source-pool saturation at each candidate's turn: starts that
    # launch a destination stage's last task bump the destination job's
    # saturated-stage count, which a later dj<0 candidate's
    # _move_idle_from_pool reads for the SOURCE job
    src_j = state.source_job
    src_s = state.source_stage
    newly_exh = start & (rem0 - cum_starts == 1)
    exh_src_before = (
        earlier & (newly_exh & (dj == src_j))[None, :]
    ).sum(-1)
    src_jc = jnp.maximum(src_j, 0)
    src_sat_k = (
        state.job_saturated_stages[src_jc] + exh_src_before
    ) >= state.job_num_stages[src_jc]
    noop_move = (src_j < 0) | ((src_s < 0) & ~src_sat_k)
    to_common = common_k & ~noop_move & src_sat_k
    moved_any = common_k & ~noop_move  # to common OR up to the job pool

    # executor-on-destination-job count at each candidate's turn (the
    # duration model's executor-level input): earlier sends/common
    # moves detach executors from the source job
    leaver = (send | to_common) & (ejob >= 0)
    leavers_before = (earlier & leaver[None, :]).sum(-1)
    base_nl = (state.exec_job[None, :] == dj[:, None]).sum(-1)
    nl = base_nl - jnp.where(dj == src_j, leavers_before, 0)

    rng_next, sub = jax.random.split(state.rng)
    # one batched draw for the whole pass (rows were independently
    # keyed via per-row fold_in before; independent uniforms now — see
    # sample_task_duration's docstring for the round-5 measurement)
    us = jax.random.uniform(sub, (pos.shape[0], 2))
    tpl = state.job_template[djc]
    tv = state.exec_task_valid[e]
    ss_same = state.exec_task_stage[e] == ds0
    durs = jax.vmap(
        lambda u2, tp, s_, nl_, tv_, sm_: sample_task_duration(
            params, bank, u2, tp, s_, nl_, tv_, sm_,
        )
    )(us, tpl, dsc, nl, tv, ss_same)

    inc = (start | send).astype(_i32)
    seq_k = state.seq_counter + (earlier & (inc[None, :] > 0)).sum(-1)
    n_inc = inc.sum()

    fin_k = state.wall_time + durs
    arr_k = jnp.full(
        (n,), state.wall_time + params.moving_delay, jnp.float32
    )

    # ---- per-executor scatters (each candidate's executor is unique)
    sel = prefix[:, None] & (e[:, None] == pos[None, :])  # [cand, exec]
    exset, exflag = _exec_scatter(sel)

    minus1 = jnp.full((n,), -1, _i32)
    exec_stage = exset(
        state.exec_stage, start | send | park | moved_any,
        jnp.where(start, ds0, minus1),
    )
    exec_task_valid = exflag(
        exflag(state.exec_task_valid, send | park | to_common, False),
        start, True,
    )
    exec_at_common = exflag(
        exflag(state.exec_at_common, send, False), to_common, True
    )
    exec_job = exset(state.exec_job, send | to_common, minus1)
    exec_moving = exflag(state.exec_moving, send, True)
    exec_dst_job = exset(state.exec_dst_job, send, dj)
    exec_dst_stage = exset(state.exec_dst_stage, send, ds0)
    exec_arrive_time = exset(state.exec_arrive_time, send, arr_k)
    exec_arrive_seq = exset(state.exec_arrive_seq, send, seq_k)
    exec_executing = exflag(state.exec_executing, start, True)
    exec_task_stage = exset(state.exec_task_stage, start, ds0)
    exec_finish_time = exset(state.exec_finish_time, start, fin_k)
    exec_finish_seq = exset(state.exec_finish_seq, start, seq_k)

    # ---- commitment slots (every prefix candidate consumes one)
    consumed = (
        prefix[:, None] & (slot[:, None] == pos[None, :])
    ).any(0)
    cm_valid = state.cm_valid & ~consumed

    # ---- per-stage counters (destination stages)
    oh_j = (
        (dj[:, None] == jnp.arange(j_cap, dtype=_i32)[None, :])
        & prefix[:, None]
        & ~common_dst[:, None]
    )  # [cand, J]
    oh_s = ds0[:, None] == jnp.arange(s_cap, dtype=_i32)[None, :]
    m3 = oh_j[:, :, None] & oh_s[:, None, :]  # [cand, J, S]
    cnt_start = (m3 & start[:, None, None]).sum(0).astype(_i32)
    cnt_send = (m3 & send[:, None, None]).sum(0).astype(_i32)
    cnt_slot = m3.sum(0).astype(_i32)
    stage_remaining = state.stage_remaining - cnt_start
    stage_executing = state.stage_executing + cnt_start
    moving_count = state.moving_count + cnt_send
    commit_count = state.commit_count - cnt_slot

    later = pos[None, :] > pos[:, None]
    is_last_start = start & ~(
        later & stage_pair & start[None, :]
    ).any(-1)
    dur_js = (
        (m3 & is_last_start[:, None, None]) * durs[:, None, None]
    ).sum(0)
    stage_duration = jnp.where(
        cnt_start > 0, dur_js, state.stage_duration
    )

    # ---- per-job counters
    job_supply = (
        state.job_supply
        - (oh_j & (dj != sjs)[:, None]).sum(0)  # slot consumption
        + (oh_j & send[:, None]).sum(0)  # arrivals in transit
        - _onehot(j_cap, src_jc).astype(_i32)
        * jnp.where(src_j >= 0, (send & (ejob >= 0)).sum(), 0)
    )
    job_saturated_stages = (
        state.job_saturated_stages
        + (oh_j & newly_exh[:, None]).sum(0).astype(_i32)
    )

    # ---- saturation-cache refresh for every touched destination stage
    aff = cnt_slot > 0
    demand = stage_remaining - moving_count - commit_count
    sat_new = demand <= 0
    is_rep = prefix & ~common_dst & ~(
        earlier & stage_pair
    ).any(-1)
    delta_k = jnp.where(
        is_rep & state.stage_exists[djc, dsc],
        sat_new[djc, dsc].astype(_i32)
        - state.stage_sat[djc, dsc].astype(_i32),
        0,
    )
    adj_row = state.adj[djc, dsc]  # [cand, S]
    unsat = state.unsat_parent_count - (
        oh_j[:, :, None]
        * (delta_k[:, None] * adj_row.astype(_i32))[:, None, :]
    ).sum(0)

    bulked = m > 0
    state = state.replace(
        rng=jnp.where(bulked, rng_next, state.rng),
        seq_counter=state.seq_counter + n_inc,
        exec_stage=exec_stage,
        exec_task_valid=exec_task_valid,
        exec_at_common=exec_at_common,
        exec_job=exec_job,
        exec_moving=exec_moving,
        exec_dst_job=exec_dst_job,
        exec_dst_stage=exec_dst_stage,
        exec_arrive_time=exec_arrive_time,
        exec_arrive_seq=exec_arrive_seq,
        exec_executing=exec_executing,
        exec_task_stage=exec_task_stage,
        exec_finish_time=exec_finish_time,
        exec_finish_seq=exec_finish_seq,
        cm_valid=cm_valid,
        stage_remaining=stage_remaining,
        stage_executing=stage_executing,
        moving_count=moving_count,
        commit_count=commit_count,
        stage_duration=stage_duration,
        job_supply=job_supply,
        job_saturated_stages=job_saturated_stages,
        stage_sat=jnp.where(aff, sat_new, state.stage_sat),
        unsat_parent_count=unsat,
    )
    return state, m


def _fulfill_from_source(
    params: EnvParams, bank: WorkloadBank, state: EnvState,
    active: jnp.ndarray, bulk: bool = True, telem=None
):
    """reference :730-743 — match the source pool's idle executors against
    its outstanding commitments, in commitment insertion order. `active`
    masks the whole call (used to fold the reference's round-finished
    branch into straight-line code). With `bulk`, the simple prefix of
    the phase is consumed in one `_bulk_fulfill` pass and only the
    backup-scheduling tail (usually empty) runs the per-candidate
    while-loop — under vmap the loop runs the batch-max LEFTOVER count
    instead of a fixed N iterations. With `telem` (an `obs.Telemetry`),
    returns `(state, telem)` with bulk hits and per-candidate
    fulfillments counted; the None path threads nothing."""
    track = telem is not None
    n = state.exec_job.shape[0]
    idle = state.source_pool_mask() & ~state.exec_executing
    num_idle = jnp.where(active, idle.sum(), 0)

    exec_order = _rank_order(
        jnp.where(idle, jnp.arange(n, dtype=_i32), BIG_SEQ)
    )
    match = (
        state.cm_valid
        & (state.cm_src_job == state.source_job)
        & (state.cm_src_stage == state.source_stage)
    )
    slot_order = _rank_order(jnp.where(match, state.cm_seq, BIG_SEQ))

    if bulk:
        state, k0 = _bulk_fulfill(
            params, bank, state, num_idle, exec_order, slot_order
        )
        if track:
            telem = _tm_add(telem, bulk_fulfill_hits=k0)
    else:
        k0 = _i32(0)

    def cond(carry):
        return carry[0] < num_idle

    def body(carry):
        if track:
            k, st, tm = carry
        else:
            k, st = carry
        e = exec_order[k]
        quirk_src = st.source_job_id()
        st, rk, rj, rs = _fulfill_commitment_phase_a(
            st, e, slot_order[k]
        )
        ak, tj, ts = _resolve_action(
            params, st, rk, e, rj, rs, quirk_src
        )
        st = _apply_action(params, bank, st, ak, e, tj, ts)
        if track:
            return k + 1, st, _tm_add(tm, fulfill_steps=1)
        return k + 1, st

    if track:
        _, state, telem = lax.while_loop(
            cond, body, (k0, state, telem)
        )
        return state, telem
    _, state = lax.while_loop(cond, body, (k0, state))
    return state


# --------------------------------------------------------------------------
# node levels for the GNN (active-subgraph topological generations)
# --------------------------------------------------------------------------


def _job_topo_levels(active_s: jnp.ndarray, adj_s: jnp.ndarray
                     ) -> jnp.ndarray:
    """i32[S] topological generation of one job's active nodes in the
    masked [S,S] subgraph; padding = S. Single-job form of `topo_levels`,
    used by the incremental `state.node_level` maintenance — an S-bounded
    pass over one job instead of the [J,S,S] all-jobs reduction."""
    s_cap = active_s.shape[0]

    def body(_, lvl):
        cand = jnp.where(adj_s, lvl[:, None] + 1, 0).max(axis=0)
        return jnp.maximum(lvl, cand)

    lvl = lax.fori_loop(0, s_cap, body, jnp.zeros(active_s.shape, _i32))
    return jnp.where(active_s, lvl, s_cap)


def compute_node_levels(params: EnvParams, state: EnvState) -> jnp.ndarray:
    """Active-subgraph topological generations (completed stages and
    inactive jobs excluded — the same node set as the observation's
    `node_mask`, so an Observation rebuilt from a stored rollout step is
    bit-identical to the live one). Since round 8 this full [J,S,S]
    recomputation is the GOLDEN reference only: `observe` reads the
    state-maintained `node_level` cache, updated per stage completion
    (`_handle_task_finished`) with a single-job `_job_topo_levels` pass."""
    active = (
        state.job_active[:, None]
        & state.stage_exists
        & ~state.stage_completed
    )
    adj_act = state.adj & active[:, :, None] & active[:, None, :]
    return topo_levels(active, adj_act)


# --------------------------------------------------------------------------
# event handlers (reference :426-483)
# --------------------------------------------------------------------------


def _handle_job_arrival(state: EnvState, j: jnp.ndarray):
    state = state.replace(
        job_arrived=state.job_arrived
        | _onehot(state.job_arrived.shape[0], j)
    )
    has_common = state.exec_at_common.any()
    state = state.replace(
        source_valid=state.source_valid | has_common,
        source_job=jnp.where(has_common, -1, state.source_job),
        source_stage=jnp.where(has_common, -1, state.source_stage),
    )
    return state, _i32(RQ_NONE), _i32(-1), _i32(-1)


def _handle_executor_ready(state: EnvState, e: jnp.ndarray):
    j = state.exec_dst_job[e]
    s = state.exec_dst_stage[e]
    n = state.exec_job.shape[0]
    j_cap, s_cap = state.moving_count.shape
    one_e = _onehot(n, e)
    m2 = _onehot2(j_cap, s_cap, j, s)
    state = state.replace(
        moving_count=state.moving_count - m2.astype(_i32),
        exec_moving=state.exec_moving & ~one_e,
        exec_arrive_time=jnp.where(one_e, INF, state.exec_arrive_time),
        exec_at_common=state.exec_at_common & ~one_e,
        exec_job=jnp.where(one_e, j, state.exec_job),
        exec_stage=jnp.where(one_e, -1, state.exec_stage),
    )
    state = _refresh_sat(state, j, s)
    return state, _i32(RQ_MOVE), j, s


def _handle_task_finished(state: EnvState, e: jnp.ndarray):
    j = state.exec_job[e]
    s = state.exec_task_stage[e]
    n = state.exec_job.shape[0]
    j_cap, s_cap = state.stage_executing.shape
    one_e = _onehot(n, e)
    oj = _onehot(j_cap, j)
    m2 = oj[:, None] & _onehot(s_cap, s)[None, :]
    frontier_before = state.frontier[j]

    state = state.replace(
        stage_executing=state.stage_executing - m2.astype(_i32),
        stage_completed_tasks=state.stage_completed_tasks
        + m2.astype(_i32),
        exec_executing=state.exec_executing & ~one_e,
        exec_finish_time=jnp.where(one_e, INF, state.exec_finish_time),
    )

    def more_tasks(st: EnvState):
        return st, _i32(RQ_START), j, s

    def released(st: EnvState):
        stage_done = st.stage_completed[j, s]
        # maintain the frontier cache: one fewer incomplete parent for
        # every child of a completed stage
        st = st.replace(
            incomplete_parent_count=st.incomplete_parent_count
            - (stage_done & oj[:, None] & st.adj[j, s][None, :]).astype(
                _i32
            )
        )
        # maintain the node-level cache: the completed stage leaves job
        # j's active subgraph, so recompute THAT job's row only (stage
        # completion is the sole mutation point — the bulk passes only
        # launch tasks and can never complete a stage)
        act_row = st.stage_exists[j] & ~st.stage_completed[j]
        adj_row = st.adj[j] & act_row[:, None] & act_row[None, :]
        lvl_row = _job_topo_levels(act_row, adj_row)
        st = st.replace(
            node_level=jnp.where(
                stage_done & oj[:, None], lvl_row[None, :],
                st.node_level,
            )
        )
        new_frontier = st.frontier[j] & ~frontier_before
        did_change = stage_done & new_frontier.any()
        job_done = st.job_completed[j]

        def complete_job(st: EnvState) -> EnvState:
            pool = st.pool_member_mask(j, _i32(-1)) & ~st.exec_executing
            st = _move_idle_from_pool(st, j, _i32(-1), pool)
            return st.replace(
                job_t_completed=jnp.where(
                    oj, st.wall_time, st.job_t_completed
                )
            )

        st = lax.cond(
            job_done & jnp.isinf(st.job_t_completed[j]),
            complete_job, lambda s2: s2, st,
        )

        has_cm, slot = _peek_commitment(st, j, s)

        def fulfill(st: EnvState):
            return _fulfill_commitment_phase_a(st, e, slot)

        def no_cm(st: EnvState):
            st = st.replace(
                exec_task_valid=st.exec_task_valid & ~one_e
            )
            st = lax.cond(
                did_change,
                lambda s2: _move_idle_from_pool(s2, j, s, _onehot(n, e)),
                lambda s2: s2,
                st,
            )
            return st, _i32(RQ_NONE), _i32(-1), _i32(-1)

        st, rk, rj, rs = lax.cond(has_cm, fulfill, no_cm, st)

        # _update_executor_source (reference :662-674)
        set_job_pool = did_change
        set_stage_pool = ~did_change & ~has_cm
        any_set = set_job_pool | set_stage_pool
        st = st.replace(
            source_valid=st.source_valid | any_set,
            source_job=jnp.where(any_set, j, st.source_job),
            source_stage=jnp.where(
                set_job_pool, -1,
                jnp.where(set_stage_pool, s, st.source_stage),
            ),
        )
        return st, rk, rj, rs

    return lax.cond(
        state.stage_remaining[j, s] > 0, more_tasks, released, state
    )


# --------------------------------------------------------------------------
# event selection + simulation loop (reference :320-343 + event.py)
# --------------------------------------------------------------------------


def _next_event(params: EnvParams, state: EnvState):
    """Lexicographic (time, seq) argmin over all pending events."""
    t_job = jnp.where(state.job_arrived, INF, state.job_arrival_time)
    times = jnp.concatenate(
        [t_job, state.exec_finish_time, state.exec_arrive_time]
    )
    seqs = jnp.concatenate(
        [state.job_arrival_seq, state.exec_finish_seq,
         state.exec_arrive_seq]
    )
    tmin = times.min()
    has = jnp.isfinite(tmin)
    cand = times == tmin
    idx = jnp.argmin(jnp.where(cand, seqs, BIG_SEQ))
    j_cap = params.max_jobs
    n = params.num_executors
    kind = jnp.where(
        idx < j_cap,
        EV_JOB_ARRIVAL,
        jnp.where(idx < j_cap + n, EV_TASK_FINISHED, EV_EXECUTOR_READY),
    )
    arg = jnp.where(
        idx < j_cap,
        idx,
        jnp.where(idx < j_cap + n, idx - j_cap, idx - j_cap - n),
    )
    return has, tmin, kind, arg


def _has_pending_event(state: EnvState) -> jnp.ndarray:
    """Cheap existence bit of `_next_event` — drain/resume loop conds
    need only "is anything pending", not the (kind, arg) argmin chain
    (the ISSUE-7 cheap-cond restructure)."""
    t = jnp.minimum(
        jnp.where(state.job_arrived, INF, state.job_arrival_time).min(),
        jnp.minimum(
            state.exec_finish_time.min(), state.exec_arrive_time.min()
        ),
    )
    return jnp.isfinite(t)


def _rank_order(key: jnp.ndarray) -> jnp.ndarray:
    """Stable ascending order of `key` as an index array — the
    `jnp.argsort(..., stable=True)` contract (ties break by index) —
    via an N x N pairwise rank matrix instead of a sort primitive: for
    the engine's N-sized keys a batched sort kernel costs far more than
    these few elementwise reduces."""
    n = key.shape[0]
    pos = jnp.arange(n, dtype=_i32)
    lt = (key[None, :] < key[:, None]) | (
        (key[None, :] == key[:, None]) & (pos[None, :] < pos[:, None])
    )
    rank = lt.sum(-1)
    perm = rank[None, :] == pos[:, None]
    return jnp.where(perm, pos[None, :], 0).sum(-1).astype(_i32)


def _bulk_relaunch(
    params: EnvParams, bank: WorkloadBank, state: EnvState,
    enabled: jnp.ndarray, stop_at_limit: bool = False,
    max_events: int = 8,
):
    """Pop up to `max_events` consecutive *task relaunch* events in one
    pass. Returns (state, k) with k the number of events consumed (0
    when the next event is not a relaunch, the queue is drained, or
    `enabled` is False — callers fall back to the single-event path).

    A relaunch is a TASK_FINISHED event on a stage that still has
    unlaunched tasks at processing time (`stage_remaining > 0`): the
    executor immediately launches the stage's next task
    (`_handle_task_finished`'s more_tasks path resolving to A_START).
    These are by far the most common events (one per task, 100s per
    stage). Two facts make a whole run of them processable in one
    micro-step:

    - the source pool is always empty while events are being popped
      (`clear_round`/`move_and_clear` precede every pop), so
      `num_committable() == 0` and `round_ready` cannot flip mid-run
      even when a relaunch saturates a parent stage and readies its
      children; relaunches touch no pools, commitments, sources or
      frontiers;
    - an executor only ever relaunches on its OWN stage, so the whole
      cascade's evolving state is N-sized: per-executor pending
      (time, seq), a shared per-stage remaining-task view, launch
      counts, and the per-stage last duration.

    The cascade is replayed in EXACT sequential order by a bounded
    `lax.scan`: each step picks the lexicographic (time, seq) minimum
    pending finish — the same tie-break as `_next_event` — checks the
    handler's relaunch condition against the live remaining view, and
    relaunches with a pre-sampled duration and the exact sequential
    seq-counter value. Newly generated events participate in later
    steps, so ordering (including ties against competitors and among
    generated events) is bit-identical to the one-event path; only the
    rng STREAM differs (each potential event has its own pre-derived
    key), which the engine does not promise for stochastic banks.

    The scan stops at the first event that is not a relaunch — a
    non-finish event with an earlier (time, seq), or a finish on a
    stage whose unlaunched tasks the run exhausted — leaving it
    pending for the single-event path. With `stop_at_limit` (the flat
    engine's per-micro-step episode-end check) it also stops right
    after the first event at or past the episode time limit, where
    that engine freezes/resets. A run longer than `max_events`
    resumes on the next micro-step: the cascade state is always
    consistent.
    """
    n = state.exec_finish_time.shape[0]
    j_cap, s_cap = state.stage_remaining.shape
    pos = jnp.arange(n, dtype=_i32)

    # earliest non-finish competitor, lexicographic (time, seq)
    t_job = jnp.where(state.job_arrived, INF, state.job_arrival_time)
    jt = t_job.min()
    jseq = jnp.where(t_job == jt, state.job_arrival_seq, BIG_SEQ).min()
    at = state.exec_arrive_time.min()
    aseq = jnp.where(
        state.exec_arrive_time == at, state.exec_arrive_seq, BIG_SEQ
    ).min()
    t_star = jnp.minimum(jt, at)
    seq_star = jnp.minimum(
        jnp.where(jt == t_star, jseq, BIG_SEQ),
        jnp.where(at == t_star, aseq, BIG_SEQ),
    )

    # static per-executor facts for the whole cascade: stage identity,
    # same-stage sharing, job-local executor count (for the duration
    # model's executor-level interpolation)
    je = state.exec_job
    se = state.exec_task_stage
    executing = jnp.isfinite(state.exec_finish_time)
    jc = jnp.clip(je, 0, j_cap - 1)
    sc = jnp.clip(se, 0, s_cap - 1)
    same = (
        (je[:, None] == je[None, :])
        & (se[:, None] == se[None, :])
        & executing[:, None]
        & executing[None, :]
    )
    num_local = (je[None, :] == je[:, None]).sum(-1)
    tpl = state.job_template[jc]

    # pre-sampled durations: dur_table[i, e] is the draw consumed if
    # the i-th processed event belongs to executor e. Each (i, e) key
    # is independent and the selection of e at step i depends only on
    # draws from earlier steps, so the consumed draws are i.i.d. from
    # the correct per-stage distribution; unconsumed draws are
    # discarded. Deterministic banks (the parity fixtures) are
    # unaffected. rng advances once iff the bulk fires.
    rng_next, sub = jax.random.split(state.rng)
    # one batched draw for the whole table (per-row fold_in keys
    # before; independent uniforms now — sample_task_duration docstring)
    us = jax.random.uniform(sub, (max_events * n, 2))
    e_rep = jnp.tile(pos, max_events)
    dur_table = jax.vmap(
        lambda u2, e: sample_task_duration(
            params, bank, u2, tpl[e], sc[e], num_local[e],
            jnp.bool_(True), jnp.bool_(True),
        )
    )(us, e_rep).reshape(max_events, n)

    def step_fn(carry, dur_row):
        t_e, sq_e, rem_e, k_e, ldur_e, counter, wall, active, crossed \
            = carry
        tmin = t_e.min()
        has = jnp.isfinite(tmin)
        cand = t_e == tmin
        smin = jnp.where(cand, sq_e, BIG_SEQ).min()
        e_oh = cand & (sq_e == smin)  # unique among pending finishes
        before = (tmin < t_star) | ((tmin == t_star) & (smin < seq_star))
        rem_i = jnp.where(e_oh, rem_e, 0).sum()
        ok = active & has & before & (rem_i > 0)
        if stop_at_limit:
            ok = ok & ~crossed
            crossed = crossed | (ok & (tmin >= state.time_limit))
        srow = (e_oh[:, None] & same).any(0)  # e*'s same-stage row
        dur_i = jnp.where(e_oh, dur_row, 0.0).sum()
        t_e = jnp.where(ok & e_oh, tmin + dur_i, t_e)
        sq_e = jnp.where(ok & e_oh, counter, sq_e)
        rem_e = rem_e - (ok & srow).astype(_i32)
        k_e = k_e + (ok & e_oh).astype(_i32)
        ldur_e = jnp.where(ok & srow, dur_i, ldur_e)
        counter = counter + ok.astype(_i32)
        wall = jnp.where(ok, tmin, wall)
        active = active & ok  # sequential order: first rejection stops
        return (
            t_e, sq_e, rem_e, k_e, ldur_e, counter, wall, active,
            crossed,
        ), None

    carry0 = (
        state.exec_finish_time,
        state.exec_finish_seq,
        state.stage_remaining[jc, sc],
        jnp.zeros(n, _i32),
        jnp.zeros(n, jnp.float32),
        state.seq_counter,
        state.wall_time,
        jnp.asarray(enabled, bool),
        jnp.bool_(False),
    )
    (t_e, sq_e, rem_e, k_e, ldur_e, counter, wall, _, _), _ = lax.scan(
        step_fn, carry0, dur_table
    )
    k = k_e.sum()
    bulked = k > 0
    touched = k_e > 0

    # one representative executor per touched stage (same-stage views
    # are kept consistent by the scan, so any member would do; pick the
    # minimal index to scatter each stage exactly once)
    first_touched = jnp.where(same & touched[None, :], pos[None, :], n
                              ).min(-1)
    rep = touched & (pos == first_touched)

    # per-representative stage quantities (all [N]-sized + gathers)
    cnt_i = ((same & touched[None, :]) * k_e[None, :]).sum(-1)
    exhausted_i = rep & (rem_e == 0)
    demand_i = (
        rem_e - state.moving_count[jc, sc] - state.commit_count[jc, sc]
    )
    sat_new_i = demand_i <= 0
    delta_i = jnp.where(
        rep & state.stage_exists[jc, sc],
        sat_new_i.astype(_i32) - state.stage_sat[jc, sc].astype(_i32),
        0,
    )
    adj_row = state.adj[jc, sc]  # [N, S] children of each rep's stage

    # scatter into [J,S] through rep-masked payload reduces
    oh_j = je[:, None] == jnp.arange(j_cap, dtype=_i32)[None, :]
    oh_s = se[:, None] == jnp.arange(s_cap, dtype=_i32)[None, :]
    m = oh_j[:, :, None] & oh_s[:, None, :] & rep[:, None, None]
    cnt = (m * cnt_i[:, None, None]).sum(0)
    aff = cnt > 0
    dur_js = (m * ldur_e[:, None, None]).sum(0)
    sat_js = (m & sat_new_i[:, None, None]).any(0)
    unsat = state.unsat_parent_count - (
        oh_j[:, :, None]
        * (delta_i[:, None] * adj_row.astype(_i32))[:, None, :]
    ).sum(0)

    return state.replace(
        rng=jnp.where(bulked, rng_next, state.rng),
        wall_time=wall,
        seq_counter=counter,
        exec_finish_time=jnp.where(touched, t_e, state.exec_finish_time),
        exec_finish_seq=jnp.where(touched, sq_e, state.exec_finish_seq),
        stage_remaining=state.stage_remaining - cnt,
        stage_completed_tasks=state.stage_completed_tasks + cnt,
        stage_duration=jnp.where(aff, dur_js, state.stage_duration),
        job_saturated_stages=state.job_saturated_stages
        + (oh_j & exhausted_i[:, None]).sum(0).astype(_i32),
        stage_sat=jnp.where(aff, sat_js, state.stage_sat),
        unsat_parent_count=unsat,
    ), k


def _bulk_ready(
    params: EnvParams, bank: WorkloadBank, state: EnvState,
    enabled: jnp.ndarray, stop_at_limit: bool = False,
):
    """Consume the maximal run of consecutive EXECUTOR_READY events in
    one vectorized pass. Returns (state, k); callers fall back to the
    single-event path when k == 0.

    After a send-heavy commitment round, every sent executor arrives at
    the same `wall + moving_delay` with consecutive seqs — a burst of
    ready events the one-at-a-time loop pays one iteration each for.
    An arrival is *simple* (statically classifiable) like a fulfillment
    candidate: its handler attaches the executor to its destination job
    and resolves RQ_MOVE locally, so with the destination unsaturated
    at its turn (rem0 minus earlier prefix starts > 0) it is A_START
    iff the destination is on the frontier (static — no completions
    happen mid-run) else A_PARK. The prefix stops at the first
    saturated-destination arrival (backup search), at any earlier
    non-ready event (job arrivals and task finishes are competitors —
    symmetrically, `_bulk_relaunch` treats arrival events as
    competitors, so the two passes alternate cleanly), at a finish
    event GENERATED by an earlier prefix start, and right AFTER any
    arrival that joins the live source pool — such an arrival can
    raise `num_committable` above 0, and the sequential per-event tail
    (round_ready / move_and_clear) must run before the next event,
    which the caller's tail does when the joiner ends the pass.

    Matches the sequential path bit-exactly except the rng stream.
    """
    n = state.exec_job.shape[0]
    j_cap, s_cap = state.stage_remaining.shape
    pos = jnp.arange(n, dtype=_i32)

    # earliest non-ready competitor, lexicographic (time, seq)
    t_job = jnp.where(state.job_arrived, INF, state.job_arrival_time)
    jt = t_job.min()
    jseq = jnp.where(t_job == jt, state.job_arrival_seq, BIG_SEQ).min()
    ft = state.exec_finish_time.min()
    fseq = jnp.where(
        state.exec_finish_time == ft, state.exec_finish_seq, BIG_SEQ
    ).min()
    t_star = jnp.minimum(jt, ft)
    seq_star = jnp.minimum(
        jnp.where(jt == t_star, jseq, BIG_SEQ),
        jnp.where(ft == t_star, fseq, BIG_SEQ),
    )

    # arrivals in processing order
    gt = (
        state.exec_arrive_time[:, None] > state.exec_arrive_time[None, :]
    ) | (
        (state.exec_arrive_time[:, None]
         == state.exec_arrive_time[None, :])
        & (state.exec_arrive_seq[:, None] > state.exec_arrive_seq[None, :])
    )
    rank = gt.sum(-1)
    perm = rank[None, :] == pos[:, None]

    def by_pos(x):
        return jnp.where(perm, x[None, :], 0).sum(-1)

    to = jnp.where(perm, state.exec_arrive_time[None, :], INF).min(-1)
    so = by_pos(state.exec_arrive_seq)
    e = by_pos(pos)
    dj = by_pos(state.exec_dst_job)
    ds0 = by_pos(state.exec_dst_stage)
    djc = jnp.clip(dj, 0, j_cap - 1)
    dsc = jnp.clip(ds0, 0, s_cap - 1)

    frontier_k = state.frontier[djc, dsc]
    flat = djc * s_cap + dsc
    earlier = pos[None, :] < pos[:, None]
    stage_pair = flat[None, :] == flat[:, None]
    # within a prefix nobody is saturated, so starts are static; the
    # per-candidate quantities below may count ALL earlier positions
    # rather than earlier prefix members — for an in-prefix candidate
    # the two coincide (the prefix is contiguous), and out-of-prefix
    # values are never consumed
    start0 = frontier_k
    cum_starts = (earlier & stage_pair & start0[None, :]).sum(-1)
    rem0 = state.stage_remaining[djc, dsc]
    saturated = rem0 - cum_starts == 0

    same_job = dj[None, :] == dj[:, None]
    base_nl = (state.exec_job[None, :] == dj[:, None]).sum(-1)
    # the arriving executor itself plus earlier arrivals to the same
    # job join the count the sequential `_apply_action` reads after
    # its handler ran
    nl = base_nl + (earlier & same_job).sum(-1) + 1

    rng_next, sub = jax.random.split(state.rng)
    # one batched draw for the whole pass (sample_task_duration
    # docstring has the round-5 measurement behind this form)
    us = jax.random.uniform(sub, (pos.shape[0], 2))
    tpl = state.job_template[djc]
    tv = state.exec_task_valid[jnp.clip(e, 0, n - 1)]
    ss_same = state.exec_task_stage[jnp.clip(e, 0, n - 1)] == ds0
    durs = jax.vmap(
        lambda u2, tp, s_, nl_, tv_, sm_: sample_task_duration(
            params, bank, u2, tp, s_, nl_, tv_, sm_,
        )
    )(us, tpl, dsc, nl, tv, ss_same)
    fin_k = to + durs

    before_star = (to < t_star) | ((to == t_star) & (so < seq_star))
    # an earlier prefix start GENERATES a finish event; the sequential
    # loop pops it before any later-timed arrival (ties go to the
    # arrival — generated seqs exceed all pending ones), so the run
    # must stop there
    gen = jnp.where(start0, fin_k, INF)
    gen_before = jnp.concatenate(
        [jnp.full((1,), INF, jnp.float32), lax.cummin(gen)[:-1]]
    )
    # an arrival that joins the LIVE source pool can raise
    # num_committable above 0; the sequential per-event tail reacts
    # (round_ready or move_and_clear) before the next event, so such
    # an arrival must be the LAST one this pass consumes — the
    # caller's tail then runs exactly where the sequential one would
    joins_source = (
        state.source_valid
        & (dj == state.source_job)
        & jnp.where(
            start0, ds0 == state.source_stage, state.source_stage == -1
        )
    )
    joined_before = (
        jnp.concatenate(
            [jnp.zeros(1, bool), joins_source[:-1]]
        ).cumsum() > 0
    )
    ok = (
        jnp.isfinite(to)
        & before_star
        & ~saturated
        & (to <= gen_before)
        & ~joined_before
    )
    if stop_at_limit:
        crossed_before = (
            jnp.concatenate(
                [jnp.zeros(1, bool), (to >= state.time_limit)[:-1]]
            ).cumsum() > 0
        )
        ok &= ~crossed_before
    prefix = (jnp.cumsum((~ok).astype(_i32)) == 0) & jnp.asarray(
        enabled, bool
    )
    k = prefix.sum().astype(_i32)

    start = start0 & prefix
    park = ~start0 & prefix
    newly_exh = start & (rem0 - cum_starts == 1)

    inc = start.astype(_i32)
    seq_k = state.seq_counter + (earlier & start0[None, :]).sum(-1)

    # ---- per-executor scatters
    sel = prefix[:, None] & perm
    exset, exflag = _exec_scatter(sel)

    minus1 = jnp.full((n,), -1, _i32)
    arrived = prefix
    exec_moving = exflag(state.exec_moving, arrived, False)
    exec_arrive_time = exset(
        state.exec_arrive_time, arrived, jnp.full((n,), INF, jnp.float32)
    )
    exec_at_common = exflag(state.exec_at_common, arrived, False)
    exec_job = exset(state.exec_job, arrived, dj)
    exec_stage = exset(
        state.exec_stage, arrived, jnp.where(start, ds0, minus1)
    )
    exec_task_valid = exflag(
        exflag(state.exec_task_valid, park, False), start, True
    )
    exec_executing = exflag(state.exec_executing, start, True)
    exec_task_stage = exset(state.exec_task_stage, start, ds0)
    exec_finish_time = exset(state.exec_finish_time, start, fin_k)
    exec_finish_seq = exset(state.exec_finish_seq, start, seq_k)

    # ---- per-stage counters (every prefix arrival was counted moving)
    oh_j = (dj[:, None] == jnp.arange(j_cap, dtype=_i32)[None, :]) \
        & prefix[:, None]
    oh_s = ds0[:, None] == jnp.arange(s_cap, dtype=_i32)[None, :]
    m3 = oh_j[:, :, None] & oh_s[:, None, :]
    cnt_arr = m3.sum(0).astype(_i32)
    cnt_start = (m3 & start[:, None, None]).sum(0).astype(_i32)
    moving_count = state.moving_count - cnt_arr
    stage_remaining = state.stage_remaining - cnt_start
    stage_executing = state.stage_executing + cnt_start

    later = pos[None, :] > pos[:, None]
    is_last_start = start & ~(later & stage_pair & start[None, :]).any(-1)
    dur_js = (
        (m3 & is_last_start[:, None, None]) * durs[:, None, None]
    ).sum(0)
    stage_duration = jnp.where(
        cnt_start > 0, dur_js, state.stage_duration
    )
    job_saturated_stages = (
        state.job_saturated_stages
        + (oh_j & newly_exh[:, None]).sum(0).astype(_i32)
    )

    # ---- saturation-cache refresh for touched destination stages
    aff = cnt_arr > 0
    demand = stage_remaining - moving_count - state.commit_count
    sat_new = demand <= 0
    is_rep = prefix & ~(earlier & stage_pair).any(-1)
    delta_k = jnp.where(
        is_rep & state.stage_exists[djc, dsc],
        sat_new[djc, dsc].astype(_i32)
        - state.stage_sat[djc, dsc].astype(_i32),
        0,
    )
    adj_row = state.adj[djc, dsc]
    unsat = state.unsat_parent_count - (
        oh_j[:, :, None]
        * (delta_k[:, None] * adj_row.astype(_i32))[:, None, :]
    ).sum(0)

    bulked = k > 0
    wall = jnp.where(
        bulked, jnp.where(prefix, to, -INF).max(), state.wall_time
    )
    state = state.replace(
        rng=jnp.where(bulked, rng_next, state.rng),
        wall_time=wall,
        seq_counter=state.seq_counter + inc.sum(),
        exec_moving=exec_moving,
        exec_arrive_time=exec_arrive_time,
        exec_at_common=exec_at_common,
        exec_job=exec_job,
        exec_stage=exec_stage,
        exec_task_valid=exec_task_valid,
        exec_executing=exec_executing,
        exec_task_stage=exec_task_stage,
        exec_finish_time=exec_finish_time,
        exec_finish_seq=exec_finish_seq,
        moving_count=moving_count,
        stage_remaining=stage_remaining,
        stage_executing=stage_executing,
        stage_duration=stage_duration,
        job_saturated_stages=job_saturated_stages,
        stage_sat=jnp.where(aff, sat_new, state.stage_sat),
        unsat_parent_count=unsat,
    )
    return state, k


def _bulk_events_fused(
    params: EnvParams, bank: WorkloadBank, state: EnvState,
    enabled: jnp.ndarray, stop_at_limit: bool = False,
    max_events: int = 8,
):
    """Consume one maximal run of *simple* events — task relaunches AND
    executor arrivals, interleaved in exact (time, seq) order — in a
    SINGLE bounded scan. Returns (state, k_rel, k_rdy): events consumed
    by kind (both 0 when the next event is not simple, the queue is
    drained, or `enabled` is False).

    This fuses `_bulk_relaunch` + `_bulk_ready` into one kernel (ISSUE
    7): instead of a fixed relaunch-pass / arrival-pass order — which
    pays one micro-step per event-kind switch and two full pass-sized
    op chains per micro-step — every scan step picks the lexicographic
    (time, seq) minimum over ALL pending finishes and arrivals,
    classifies it against the live remaining-task view, and applies it.
    One rng split, one duration-sampling chain per consumed event, one
    merged `state.replace` at the end. Because events are processed in
    true queue order, the separate passes' cross-kind stop conditions
    (`_bulk_ready`'s generated-finish cutoff, `_bulk_relaunch` treating
    arrivals as competitors) dissolve: a finish event generated by an
    in-run arrival start simply participates in later steps, and mixed
    relaunch/arrival runs that previously cost one micro-step per kind
    switch are consumed in one pass.

    An event is *simple* iff its target stage still has unlaunched
    tasks at its turn (`rem > 0` on the live view):

    - a TASK_FINISHED on a stage with `rem > 0` relaunches (the
      `_handle_task_finished` more_tasks path resolving to A_START);
      `rem == 0` means the released-stage handler must run — stop;
    - an EXECUTOR_READY whose destination has `rem > 0` resolves
      locally to A_START (destination on the frontier — static during
      the run, no stage ever completes here) or A_PARK; `rem == 0`
      triggers the backup-stage search — stop.

    The run also stops before any job-arrival competitor, right after
    an arrival that joins the live source pool (it can raise
    `num_committable` above 0, and the sequential per-event tail must
    run before the next event), and — with `stop_at_limit` — right
    after the first event at or past the episode time limit.

    The fulfillment-phase bulk (`_bulk_fulfill`) stays a separate pass
    in the shared micro-step tail: fulfillment work only exists on
    DECIDE-mode lanes and event work only on EVENT-mode lanes, so the
    two passes are mode-exclusive per micro-step and fusing them would
    add op count without removing a dispatch.

    Cross-event coupling is tracked in the scan carry: the live
    per-stage remaining view `rem[J,S]` (launches of either kind
    decrement it), the live executors-per-job count (`jcnt[J]` — the
    duration model's executor-level input; arrivals attach mid-run),
    and each executor's CURRENT finish-event stage (`fj`/`fs` — an
    arrival start re-targets the executor's next finish to its
    destination stage, and that finish may itself relaunch within the
    same pass). The scan length is `max_events + N`: the budget of one
    full relaunch cascade plus a worst-case arrival burst, so a fused
    pass can always consume at least what the unfused pass pair could.

    Matches the sequential path bit-exactly except the rng stream
    (one batched uniform table, as in the unfused passes)."""
    n = state.exec_job.shape[0]
    j_cap, s_cap = state.stage_remaining.shape
    pos = jnp.arange(n, dtype=_i32)
    length = max_events + n

    # job arrivals: the only competitor kind (never consumed here)
    t_job = jnp.where(state.job_arrived, INF, state.job_arrival_time)
    jt = t_job.min()
    jseq = jnp.where(t_job == jt, state.job_arrival_seq, BIG_SEQ).min()

    # static per-executor arrival facts (an arrival's destination and
    # wave inputs cannot change before it fires — the executor is
    # moving, so no other event touches it first)
    dj = state.exec_dst_job
    ds0 = state.exec_dst_stage
    djc = jnp.clip(dj, 0, j_cap - 1)
    dsc = jnp.clip(ds0, 0, s_cap - 1)
    frontier_a = state.frontier[djc, dsc]
    tv_a = state.exec_task_valid
    ss_a = state.exec_task_stage == ds0
    sq_a = state.exec_arrive_seq
    joins_a = (
        state.source_valid
        & (dj == state.source_job)
        & jnp.where(
            frontier_a, ds0 == state.source_stage,
            state.source_stage == -1,
        )
    )

    rng_next, sub = jax.random.split(state.rng)
    # one batched draw for the whole pass; us[i, e] is consumed iff the
    # i-th processed event belongs to executor e (selection at step i
    # depends only on earlier draws, so consumed draws are i.i.d.)
    us = jax.random.uniform(sub, (length, n, 2))

    jcnt0 = (
        state.exec_job[None, :] == jnp.arange(j_cap, dtype=_i32)[:, None]
    ).sum(-1).astype(_i32)

    def pick_i(oh, x):
        return jnp.where(oh, x, 0).sum().astype(x.dtype)

    def step_fn(carry, u_row):
        (t_f, sq_f, t_a, fj, fs, rem, jcnt, launch_t, dur_js, relc,
         arr_done, started, counter, wall, active, crossed) = carry

        # lexicographic (time, seq) minimum over finishes and arrivals
        ftmin = t_f.min()
        fcand = t_f == ftmin
        fsmin = jnp.where(fcand, sq_f, BIG_SEQ).min()
        atmin = t_a.min()
        acand = t_a == atmin
        asmin = jnp.where(acand, sq_a, BIG_SEQ).min()
        is_fin = (ftmin < atmin) | ((ftmin == atmin) & (fsmin < asmin))
        tmin = jnp.minimum(ftmin, atmin)
        smin = jnp.where(is_fin, fsmin, asmin)
        has = jnp.isfinite(tmin)
        before_job = (tmin < jt) | ((tmin == jt) & (smin < jseq))
        e_oh = jnp.where(
            is_fin, fcand & (sq_f == fsmin), acand & (sq_a == asmin)
        )

        # the winner's target stage on the LIVE views
        tj = jnp.where(is_fin, pick_i(e_oh, fj), pick_i(e_oh, djc))
        ts = jnp.where(is_fin, pick_i(e_oh, fs), pick_i(e_oh, dsc))
        rem_t = rem[tj, ts]
        ok = active & has & before_job & (rem_t > 0)
        if stop_at_limit:
            ok = ok & ~crossed
            crossed = crossed | (ok & (tmin >= state.time_limit))
        start_a = (e_oh & frontier_a).any()  # arrival-start vs park
        is_rel = ok & is_fin
        is_arr = ok & ~is_fin
        launch = is_rel | (is_arr & start_a)
        # an arrival that joins the live source pool ends the run
        # AFTER being consumed (the caller's tail then runs exactly
        # where the sequential loop's would)
        joins = is_arr & (e_oh & joins_a).any()

        # duration for the launched task (relaunch: same-stage
        # continuation; arrival: the sequential wave inputs)
        u2 = jnp.where(e_oh[:, None], u_row, 0.0).sum(0)
        nl = jcnt[tj] + is_arr.astype(_i32)  # arrival counts itself
        tv = jnp.where(is_fin, True, (e_oh & tv_a).any())
        ss = jnp.where(is_fin, True, (e_oh & ss_a).any())
        dur = sample_task_duration(
            params, bank, u2, state.job_template[tj], ts, nl, tv, ss
        )

        oh2 = _onehot2(j_cap, s_cap, tj, ts)
        t_f = jnp.where(launch & e_oh, tmin + dur, t_f)
        sq_f = jnp.where(launch & e_oh, counter, sq_f)
        t_a = jnp.where(is_arr & e_oh, INF, t_a)
        fj = jnp.where(is_arr & start_a & e_oh, tj, fj)
        fs = jnp.where(is_arr & start_a & e_oh, ts, fs)
        rem = rem - (launch & oh2).astype(_i32)
        jcnt = jcnt + (is_arr & _onehot(j_cap, tj)).astype(_i32)
        launch_t = launch_t | (launch & oh2)
        dur_js = jnp.where(launch & oh2, dur, dur_js)
        relc = relc + (is_rel & oh2).astype(_i32)
        arr_done = arr_done | (is_arr & e_oh)
        started = started | (is_arr & start_a & e_oh)
        counter = counter + launch.astype(_i32)
        wall = jnp.where(ok, tmin, wall)
        active = active & ok & ~joins
        return (
            t_f, sq_f, t_a, fj, fs, rem, jcnt, launch_t, dur_js, relc,
            arr_done, started, counter, wall, active, crossed,
        ), None

    jc = jnp.clip(state.exec_job, 0, j_cap - 1)
    sc = jnp.clip(state.exec_task_stage, 0, s_cap - 1)
    carry0 = (
        state.exec_finish_time,
        state.exec_finish_seq,
        state.exec_arrive_time,
        jc,
        sc,
        state.stage_remaining,
        jcnt0,
        jnp.zeros((j_cap, s_cap), bool),
        jnp.zeros((j_cap, s_cap), jnp.float32),
        jnp.zeros((j_cap, s_cap), _i32),
        jnp.zeros(n, bool),
        jnp.zeros(n, bool),
        state.seq_counter,
        state.wall_time,
        jnp.asarray(enabled, bool),
        jnp.bool_(False),
    )
    (t_f, sq_f, t_a, _, _, rem, _, launch_t, dur_js, relc, arr_done,
     started, counter, wall, _, _), _ = lax.scan(step_fn, carry0, us)

    k_rel = relc.sum()
    k_rdy = arr_done.sum().astype(_i32)
    bulked = (k_rel + k_rdy) > 0

    # [J,S] scatters for the consumed arrivals (static destinations)
    oh_j = (dj[:, None] == jnp.arange(j_cap, dtype=_i32)[None, :]) \
        & arr_done[:, None]
    oh_s = ds0[:, None] == jnp.arange(s_cap, dtype=_i32)[None, :]
    m3 = oh_j[:, :, None] & oh_s[:, None, :]
    cnt_arr = m3.sum(0).astype(_i32)
    cnt_start = (m3 & started[:, None, None]).sum(0).astype(_i32)
    moving_count = state.moving_count - cnt_arr
    stage_executing = state.stage_executing + cnt_start

    # stages that launched down to zero transitioned to fully-launched
    # (launches are the only in-run decrements and require rem > 0)
    newly_exh = launch_t & (rem == 0)
    job_saturated_stages = (
        state.job_saturated_stages + newly_exh.sum(-1).astype(_i32)
    )

    # saturation-cache refresh over every touched stage, full-array
    # form: demand moved wherever a launch or an arrival landed
    touched = launch_t | (cnt_arr > 0)
    demand = rem - moving_count - state.commit_count
    sat_new = demand <= 0
    delta = jnp.where(
        touched & state.stage_exists,
        sat_new.astype(_i32) - state.stage_sat.astype(_i32),
        0,
    )
    unsat = state.unsat_parent_count - jnp.einsum(
        "jp,jpc->jc", delta, state.adj.astype(_i32)
    )

    state = state.replace(
        rng=jnp.where(bulked, rng_next, state.rng),
        wall_time=wall,
        seq_counter=counter,
        exec_finish_time=t_f,
        exec_finish_seq=sq_f,
        exec_arrive_time=t_a,
        exec_moving=state.exec_moving & ~arr_done,
        exec_at_common=state.exec_at_common & ~arr_done,
        exec_job=jnp.where(arr_done, dj, state.exec_job),
        exec_stage=jnp.where(
            arr_done, jnp.where(started, ds0, -1), state.exec_stage
        ),
        exec_task_valid=jnp.where(
            arr_done, started, state.exec_task_valid
        ),
        exec_executing=state.exec_executing | started,
        exec_task_stage=jnp.where(
            started, ds0, state.exec_task_stage
        ),
        stage_remaining=rem,
        stage_completed_tasks=state.stage_completed_tasks + relc,
        stage_executing=stage_executing,
        moving_count=moving_count,
        stage_duration=jnp.where(launch_t, dur_js, state.stage_duration),
        job_saturated_stages=job_saturated_stages,
        stage_sat=jnp.where(touched, sat_new, state.stage_sat),
        unsat_parent_count=unsat,
    )
    return state, k_rel, k_rdy


def _resume_simulation(
    params: EnvParams, bank: WorkloadBank, state: EnvState,
    active: jnp.ndarray, bulk: bool = True, bulk_events: int = 8,
    telem=None,
):
    """Pop events until there are new scheduling decisions to make or the
    queue drains (reference :320-343). `active` masks the whole loop.
    With `bulk`, each iteration first consumes a whole run of relaunch
    events via `_bulk_relaunch` plus the arrival-burst prefix, and then
    — fused pop, mirroring the flat engine — still pops the run-cutting
    event in the SAME iteration whenever the skipped between-event tail
    is provably a no-op: `num_committable() == 0` (the tail's
    round-ready flip and move_and_clear are both gated on
    committable > 0, and `_bulk_ready` ends its prefix at any arrival
    that could raise it). Under vmap the while loop costs the batch-max
    iteration count, so consuming bulk + cutter per iteration cuts the
    straggler tax for every lane.

    With `telem` (an `obs.Telemetry`), returns `(state, telem)` counting
    each lane's own iteration count (`loop_iters` — the while batching
    rule masks the carry for false-cond lanes, so the count is per-lane
    exact and max/mean over lanes IS the straggler tax), single pops by
    event kind, and bulk-pass consumption. None threads nothing."""
    track = telem is not None

    def cond(carry):
        st = carry[0] if track else carry
        has, _, _, _ = _next_event(params, st)
        return active & has & ~st.round_ready

    def body(carry):
        if track:
            st, tm = carry
        else:
            st, tm = carry, None
        if bulk:
            st, nb1 = _bulk_relaunch(
                params, bank, st, jnp.bool_(True),
                max_events=bulk_events,
            )
            st, nb2 = _bulk_ready(params, bank, st, jnp.bool_(True))
            single = ((nb1 + nb2) == 0) | (st.num_committable() == 0)
            if track:
                tm = _tm_add(
                    tm, bulk_relaunch_events=nb1, bulk_ready_events=nb2,
                    bulk_passes=(nb1 + nb2) > 0,
                )
        else:
            single = jnp.bool_(True)
        # `has` must re-gate the fused pop: the bulk passes above may
        # have consumed the queue's last events (e.g. a parked arrival)
        has, t, kind, arg = _next_event(params, st)
        if track:
            did_pop = single & has
            tm = _tm_add(
                tm,
                loop_iters=1,
                drain_iters=1,
                event_steps=did_pop,
                ev_job_arrival=did_pop & (kind == EV_JOB_ARRIVAL),
                ev_task_finished=did_pop & (kind == EV_TASK_FINISHED),
                ev_exec_ready=did_pop & (kind == EV_EXECUTOR_READY),
            )

        def pop(st: EnvState):
            st = st.replace(wall_time=t)
            quirk_src = st.source_job_id()
            st, rk, rj, rs = lax.switch(
                kind,
                [
                    lambda st, a: _handle_job_arrival(st, a),
                    lambda st, a: _handle_task_finished(st, a),
                    lambda st, a: _handle_executor_ready(st, a),
                ],
                st,
                arg,
            )
            return st, rk, rj, rs, quirk_src

        def nopop(st: EnvState):
            return st, _i32(RQ_NONE), _i32(-1), _i32(-1), _i32(-1)

        st, rk, rj, rs, quirk_src = lax.cond(single & has, pop, nopop, st)
        ak, tj, ts = _resolve_action(params, st, rk, arg, rj, rs, quirk_src)
        st = _apply_action(params, bank, st, ak, arg, tj, ts)
        committable = st.num_committable()
        sched = find_schedulable(params, st, st.source_job_id())
        ready = (committable > 0) & sched.any()

        def set_ready(st: EnvState) -> EnvState:
            return st.replace(
                round_ready=jnp.bool_(True), schedulable=sched
            )

        def not_ready(st: EnvState) -> EnvState:
            def move_and_clear(st: EnvState) -> EnvState:
                idle = st.source_pool_mask() & ~st.exec_executing
                st = _move_idle_from_pool(
                    st, st.source_job, st.source_stage, idle
                )
                return st.replace(
                    source_valid=jnp.bool_(False),
                    source_job=_i32(-1),
                    source_stage=_i32(-1),
                )

            return lax.cond(
                committable > 0, move_and_clear, lambda s2: s2, st
            )

        st = lax.cond(ready, set_ready, not_ready, st)
        return (st, tm) if track else st

    if track:
        return lax.while_loop(cond, body, (state, telem))
    return lax.while_loop(cond, body, state)


# --------------------------------------------------------------------------
# reward (reference :847-874)
# --------------------------------------------------------------------------


def _compute_jobtime(
    params: EnvParams, state: EnvState, t_old: jnp.ndarray,
    active_old: jnp.ndarray, t_ref: jnp.ndarray | None = None
) -> jnp.ndarray:
    """Total (optionally beta-discounted) job-time over [t_old, wall_time].

    `t_ref` is the discount reference point; it defaults to `t_old` (the
    per-decision-step form `step` uses). The flat engine's trajectory
    recording accumulates job-time one micro-step at a time and passes the
    wall time of the round-finishing decision as `t_ref`, so the partial
    contributions telescope to exactly the single-span quantity `step`
    would have computed (exp(-b(x - t_ref)) factors cancel at interior
    interval boundaries; for beta == 0 the sum is plainly additive)."""
    t_new = state.wall_time
    m = active_old | state.job_active
    start = jnp.maximum(state.job_arrival_time, t_old)
    end = jnp.minimum(state.job_t_completed, t_new)
    if params.beta == 0.0:
        per = end - start
    else:
        ref = t_old if t_ref is None else t_ref
        b = params.beta * 1e-3
        per = jnp.exp(-b * (start - ref)) - jnp.exp(-b * (end - ref))
    total = jnp.where(m, per, 0.0).sum()
    if params.beta > 0.0:
        total = total / params.beta
    return jnp.where(t_new == t_old, 0.0, total)


# --------------------------------------------------------------------------
# public API: reset / step
# --------------------------------------------------------------------------


@partial(jax.jit, static_argnums=0)
def reset(params: EnvParams, bank: WorkloadBank, rng: jax.Array) -> EnvState:
    """Sample a fresh episode (reference :127-186 + StochasticTimeLimit)."""
    return reset_pair(params, bank, rng, jax.random.fold_in(rng, 1))


@partial(jax.jit, static_argnums=0)
def reset_pair(
    params: EnvParams, bank: WorkloadBank, seq_rng: jax.Array,
    lane_rng: jax.Array
) -> EnvState:
    """Reset with separate keys for the job sequence / time limit
    (`seq_rng`) and the per-lane stochastic stream (`lane_rng`). Lanes that
    share `seq_rng` replay the same arrival sequence — the TPU analogue of
    the reference's `num_sequences x num_rollouts` worker seed layout
    (trainers/trainer.py:268-271), which the critic-free baseline relies
    on (trainers/utils/baselines.py:12-18)."""
    k_limit, k_seq = jax.random.split(seq_rng)
    k_state = lane_rng

    if params.mean_time_limit is None:
        time_limit = INF
    else:
        time_limit = (
            jax.random.exponential(k_limit) * params.mean_time_limit
        ).astype(jnp.float32)

    arrivals, templates, num_jobs, mask = sample_job_sequence(
        params, bank, k_seq, time_limit
    )
    return reset_from_sequence(
        params, bank, k_state, time_limit, arrivals, templates, num_jobs,
        mask,
    )


@partial(jax.jit, static_argnums=0)
def reset_from_sequence(
    params: EnvParams, bank: WorkloadBank, rng: jax.Array,
    time_limit: jnp.ndarray, arrivals: jnp.ndarray, templates: jnp.ndarray,
    num_jobs: jnp.ndarray, mask: jnp.ndarray
) -> EnvState:
    """Reset with an explicitly provided job sequence (for parity tests and
    replay; the reference takes its sequence from DataSampler.job_sequence
    at reset, spark_sched_sim.py:149-156)."""
    state = empty_state(params, rng)
    s_cap = params.max_stages
    ns = jnp.where(mask, bank.num_stages[templates], 0)
    exists = (jnp.arange(s_cap, dtype=_i32)[None, :] < ns[:, None])
    ntasks = jnp.where(exists, bank.num_tasks[templates], 0)
    rough = jnp.where(exists, bank.rough_duration[templates], 0.0)
    adj = bank.adj[templates] & exists[:, :, None] & exists[:, None, :]

    sat0 = ntasks <= 0  # padding rows and empty stages start saturated
    unsat0 = (
        (adj & (~sat0 & exists)[:, :, None]).sum(axis=1)
    ).astype(jnp.int32)
    ipc0 = adj.sum(axis=1).astype(jnp.int32)
    state = state.replace(
        stage_sat=sat0,
        unsat_parent_count=unsat0,
        incomplete_parent_count=ipc0,
        node_level=topo_levels(exists, adj),
        time_limit=time_limit,
        seq_counter=num_jobs,
        job_template=templates,
        job_arrival_time=arrivals,
        job_arrival_seq=jnp.arange(params.max_jobs, dtype=_i32),
        job_num_stages=ns,
        num_jobs=num_jobs,
        stage_exists=exists,
        stage_num_tasks=ntasks,
        stage_remaining=ntasks,
        stage_duration=rough,
        adj=adj,
    )

    # _load_initial_jobs (reference :260-273): pop all t=0 arrivals
    t0 = mask & (arrivals == 0.0)
    state = state.replace(
        job_arrived=t0,
        # common pool holds all executors -> source = common pool
        source_valid=jnp.bool_(True),
        source_job=_i32(-1),
        source_stage=_i32(-1),
    )
    sched = find_schedulable(params, state, state.source_job_id())
    return state.replace(schedulable=sched, round_ready=jnp.bool_(True))


@partial(
    jax.jit, static_argnums=0, static_argnames=("bulk", "bulk_events")
)
def step(
    params: EnvParams, bank: WorkloadBank, state: EnvState,
    stage_idx: jnp.ndarray, num_exec: jnp.ndarray, *, bulk: bool = True,
    bulk_events: int = 8, telemetry=None
):
    """One decision step (reference :188-221). Returns
    (state, reward, terminated, truncated). `bulk=False` forces BOTH
    vectorized fast paths off — relaunch runs pop one event per
    iteration (`_bulk_relaunch`) and the fulfillment phase runs one
    candidate at a time (`_bulk_fulfill`) — for equivalence testing;
    the rng streams of the two modes differ (per-candidate pre-derived
    keys vs the sequential chain).

    With `telemetry` (an `obs.Telemetry`), returns a 5-tuple with the
    counters advanced — decisions/rounds on live lanes, event-loop
    iterations and event kinds (see `obs.telemetry` for semantics).
    The default None path is bit-identical to the pre-telemetry step
    and threads no extra carry."""
    track = telemetry is not None
    s_cap = params.max_stages
    j = stage_idx // s_cap
    s = stage_idx % s_cap
    valid = (
        (stage_idx >= 0)
        & (stage_idx < params.num_nodes)
        & state.schedulable[j, s]
    )
    if track:
        live = ~(state.terminated | state.truncated)

    def do_commit(st: EnvState) -> EnvState:
        committable = st.num_committable()
        n = jnp.clip(num_exec, 1, committable)
        n = jnp.minimum(n, st.exec_demand[j, s])  # _adjust_num_executors
        st = _add_commitment(st, n, j, s)
        j_cap, s_cap2 = st.stage_selected.shape
        sel = _onehot2(j_cap, s_cap2, j, s)
        st = st.replace(stage_selected=st.stage_selected | sel)
        sched = find_schedulable(params, st, st.source_job_id())
        return st.replace(schedulable=sched)

    state = lax.cond(valid, do_commit, _commit_remaining, state)

    round_continues = (state.num_committable() > 0) & state.schedulable.any()

    # The round-finished path below runs straight-line, masked by `active`,
    # instead of under lax.cond: its body reaches the workload bank (task
    # durations, via the event loop), and a lane-dependent cond would
    # broadcast the bank across the vmap batch (see structural note above).
    active = ~round_continues

    def commit_rest(st: EnvState) -> EnvState:
        return _commit_remaining(st)

    state = lax.cond(active, commit_rest, lambda st: st, state)
    if track:
        telemetry = _tm_add(
            telemetry,
            decide_steps=live,
            commit_rounds=active & live,
        )
        state, telemetry = _fulfill_from_source(
            params, bank, state, active, bulk=bulk, telem=telemetry
        )
    else:
        state = _fulfill_from_source(
            params, bank, state, active, bulk=bulk
        )

    def clear_round(st: EnvState) -> EnvState:
        return st.replace(
            source_valid=jnp.bool_(False),
            source_job=_i32(-1),
            source_stage=_i32(-1),
            stage_selected=jnp.zeros_like(st.stage_selected),
            round_ready=jnp.bool_(False),
            schedulable=jnp.zeros_like(st.schedulable),
        )

    state = lax.cond(active, clear_round, lambda st: st, state)
    t_old = state.wall_time
    active_old = state.job_active
    if track:
        state, telemetry = _resume_simulation(
            params, bank, state, active, bulk=bulk,
            bulk_events=bulk_events, telem=telemetry,
        )
    else:
        state = _resume_simulation(
            params, bank, state, active, bulk=bulk,
            bulk_events=bulk_events,
        )
    reward = jnp.where(
        active, -_compute_jobtime(params, state, t_old, active_old), 0.0
    )

    terminated = state.all_jobs_complete
    truncated = state.wall_time >= state.time_limit
    state = state.replace(terminated=terminated, truncated=truncated)
    if track:
        return state, reward, terminated, truncated, telemetry
    return state, reward, terminated, truncated
