"""In-JIT health sentinels (ISSUE 9 tentpole).

A health mask is a compact i32 bitmask of invariant violations,
computed as pure reductions inside jit — no host callbacks — so the
training program can both *report* a fault (the mask rides the
`obs.Telemetry` carry as `health_mask`) and *act* on it on-device (the
PPO update skips a minibatch whose gradients tripped a sentinel,
trainers/ppo.py). The checks are opt-in behind the top-level `health:`
config block: with it off, no sentinel op exists in any traced program
(the jaxpr/byte budgets pin this — see analysis/jaxpr_audit.py).

Two families:

- `state_health(state, prev, resetting)` — environment invariants on an
  `EnvState` after a step/micro-step: finite wall clock and stage
  durations, the incremental commitment/moving counters agree with
  their slot-table golden reductions (the conservation law a corrupted
  bank row or a bad scatter breaks first), executor residence flags
  consistent (never common *and* moving; executing implies a valid
  task and a finite finish time), and task-count sanity (completed
  never exceeds the stage's task count, never decreases across a step
  unless the lane auto-reset).
- `grad_health(loss, grads, params)` — update invariants: finite loss,
  finite gradients, finite parameters. Each argument is optional so
  the PPO minibatch body can check loss+grads per step and params once
  after the scan.

Host-detected conditions (a straggler ratio above the configured
threshold, a caught RESOURCE_EXHAUSTED) reuse bits from the same table
so one runlog `health` record schema covers everything; those bits are
never set inside jit.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .state import EnvState

_i32 = jnp.int32

# --- bit table (single source of truth; runlog `health` records carry
# both the raw mask and the decoded names from this table) ---------------
H_NONFINITE_TIME = 1  # wall_time / an existing stage's duration non-finite
H_COMMIT_CONSERVE = 2  # incremental commit/moving counts != slot golden
H_EXEC_CONSERVE = 4  # executor residence flags inconsistent
H_TASK_MONOTONIC = 8  # completed-task counters decreased / exceeded caps
H_NONFINITE_REWARD = 16  # a recorded reward was non-finite (collectors)
H_NONFINITE_LOSS = 32  # PPO minibatch loss non-finite
H_NONFINITE_GRAD = 64  # PPO minibatch gradients non-finite
H_NONFINITE_PARAM = 128  # post-update parameters non-finite
# host-detected (never set in-JIT):
H_STRAGGLER = 256  # per-lane loop_iters max/mean above health threshold
H_OOM = 512  # RESOURCE_EXHAUSTED caught around collect/update

HEALTH_BITS: dict[str, int] = {
    "nonfinite_time": H_NONFINITE_TIME,
    "commit_conservation": H_COMMIT_CONSERVE,
    "exec_conservation": H_EXEC_CONSERVE,
    "task_monotonicity": H_TASK_MONOTONIC,
    "nonfinite_reward": H_NONFINITE_REWARD,
    "nonfinite_loss": H_NONFINITE_LOSS,
    "nonfinite_grad": H_NONFINITE_GRAD,
    "nonfinite_param": H_NONFINITE_PARAM,
    "straggler": H_STRAGGLER,
    "oom": H_OOM,
}

# bits worth a rollback+retry (trainers/trainer.py recovery policy); a
# straggler is a performance observation, not state corruption — it is
# recorded and quarantined but never triggers a rollback
RETRYABLE_MASK = (
    H_NONFINITE_TIME | H_COMMIT_CONSERVE | H_EXEC_CONSERVE
    | H_TASK_MONOTONIC | H_NONFINITE_REWARD | H_NONFINITE_LOSS
    | H_NONFINITE_GRAD | H_NONFINITE_PARAM | H_OOM
)


def describe_mask(mask: int) -> list[str]:
    """Decoded bit names of a host-side mask int (runlog records carry
    these next to the raw mask so greps don't need the bit table).
    Host boundary by contract — callers pass concrete ints/scalars."""
    m = int(mask)  # analysis: allow(host-scalar)
    return [name for name, bit in HEALTH_BITS.items() if m & bit]


def _bit(pred: jnp.ndarray, bit: int) -> jnp.ndarray:
    return jnp.where(pred, _i32(bit), _i32(0))


def tree_nonfinite(tree) -> jnp.ndarray:
    """bool []: any leaf of a float pytree contains a non-finite value.
    Integer/bool leaves are skipped (isfinite is undefined there and
    they cannot go non-finite)."""
    flags = [
        ~jnp.isfinite(leaf).all()
        for leaf in jax.tree_util.tree_leaves(tree)
        if jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.floating)
    ]
    if not flags:
        return jnp.bool_(False)
    out = flags[0]
    for f in flags[1:]:
        out = out | f
    return out


def state_health(
    state: EnvState,
    prev: EnvState | None = None,
    resetting: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """i32 [] violation bitmask over one (unbatched) `EnvState` — vmap
    for lane batches. `prev` enables the cross-step monotonicity check;
    `resetting` (bool []) disables it for lanes that auto-reset inside
    the step (a fresh episode's counters legitimately restart at 0)."""
    # finite wall clock + finite durations on existing stages (padding
    # slots are 0; job_arrival_time/exec_finish_time use inf as the
    # no-event sentinel, so they are deliberately NOT checked)
    bad_time = ~jnp.isfinite(state.wall_time) | (
        state.stage_exists & ~jnp.isfinite(state.stage_duration)
    ).any() | jnp.isnan(state.job_t_completed).any()

    # conservation: the incrementally-maintained executor-flow counters
    # must equal their slot/executor-table golden reductions — the
    # first invariant a corrupted row or a misrouted scatter breaks
    bad_commit = (
        (state.commit_count != state.commit_count_to_stage).any()
        | (state.moving_count != state.moving_count_to_stage).any()
    )

    # executor residence: common and moving are exclusive; a moving
    # executor has a finite arrival, an executing one a valid task and
    # a finite finish time
    bad_exec = (
        (state.exec_at_common & state.exec_moving).any()
        | (state.exec_moving & ~jnp.isfinite(state.exec_arrive_time)).any()
        | (state.exec_executing & ~state.exec_task_valid).any()
        | (state.exec_executing & ~jnp.isfinite(state.exec_finish_time)).any()
    )

    # task-count sanity: completed <= total, remaining/executing >= 0
    bad_tasks = (
        (state.stage_completed_tasks > state.stage_num_tasks).any()
        | (state.stage_remaining < 0).any()
        | (state.stage_executing < 0).any()
    )
    if prev is not None:
        decreased = (
            state.stage_completed_tasks < prev.stage_completed_tasks
        ).any() | (state.num_jobs < prev.num_jobs)
        if resetting is not None:
            decreased = decreased & ~resetting
        bad_tasks = bad_tasks | decreased

    return (
        _bit(bad_time, H_NONFINITE_TIME)
        | _bit(bad_commit, H_COMMIT_CONSERVE)
        | _bit(bad_exec, H_EXEC_CONSERVE)
        | _bit(bad_tasks, H_TASK_MONOTONIC)
    )


def reward_health(reward: jnp.ndarray) -> jnp.ndarray:
    """i32 bitmask (same shape as `reward`): the non-finite-reward bit
    wherever a recorded reward is not finite."""
    return _bit(~jnp.isfinite(reward), H_NONFINITE_REWARD)


def grad_health(
    loss: jnp.ndarray | None = None,
    grads=None,
    params=None,
) -> jnp.ndarray:
    """i32 [] bitmask over the update-side quantities; every argument
    optional (None contributes nothing)."""
    mask = _i32(0)
    if loss is not None:
        mask = mask | _bit(~jnp.isfinite(loss), H_NONFINITE_LOSS)
    if grads is not None:
        mask = mask | _bit(tree_nonfinite(grads), H_NONFINITE_GRAD)
    if params is not None:
        mask = mask | _bit(tree_nonfinite(params), H_NONFINITE_PARAM)
    return mask
