"""Struct-of-arrays environment state.

Replaces the reference's Python object graph (Job/Stage/Task/Executor +
ExecutorTracker dicts + heapq event queue; reference spark_sched_sim/
components/) with fixed-shape arrays so `jax.vmap` can run thousands of
environments and `lax.while_loop` can drive the event loop on-device.

Encoding conventions
--------------------
Pool keys (reference components/executor_tracker.py:4-10) become integer
pairs: job == -1 means the common pool ("general pool"); stage == -1 means a
job pool; (job >= 0, stage >= 0) is a stage pool. A separate validity flag
stands in for the `None` placeholder pool.

Events (reference components/event.py): instead of a heap, every pending
event lives in the array that naturally owns it — job arrival times [J],
per-executor task finish times [N], per-executor move arrival times [N] —
each with the sequence number it was "pushed" with. The next event is the
lexicographic argmin of (time, seq), which reproduces the reference heap's
exact FIFO tie-breaking (event.py:34-35).

Commitments (reference executor_tracker dict-of-dicts): a slot table of at
most `num_executors` rows. This bound is exact: the tracker enforces
supply >= demand per pool (executor_tracker.py:234-236) and pools partition
the executors, so the total outstanding commitment count never exceeds N.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from ..config import EnvParams

# event kinds, dispatch order matches reference handler registration
# (spark_sched_sim.py:68-72)
EV_JOB_ARRIVAL, EV_TASK_FINISHED, EV_EXECUTOR_READY = 0, 1, 2

# numpy scalars, not jnp: creating a jax array at import time would
# initialize the backend (and claim the TPU) on `import sparksched_tpu`;
# numpy dtypes carry through jnp ops identically
INF = np.float32(np.inf)
BIG_SEQ = np.int32(2**30)


def topo_levels(active: jnp.ndarray, adj_act: jnp.ndarray) -> jnp.ndarray:
    """i32[J,S] topological generation of each active node in the masked
    subgraph; padding = S. Matches nx.topological_generations on the
    observed dag batch (reference decima/utils.py:238-267). Lives here —
    the leaf module — so the env core, the observation path and the
    golden `node_level_golden` property all share ONE copy of the
    reduction (core re-exports it)."""
    from jax import lax

    s_cap = active.shape[1]

    def body(_, lvl):
        cand = jnp.where(adj_act, lvl[:, :, None] + 1, 0).max(axis=1)
        return jnp.maximum(lvl, cand)

    lvl = lax.fori_loop(
        0, s_cap, body, jnp.zeros(active.shape, jnp.int32)
    )
    return jnp.where(active, lvl, s_cap)


class EnvState(struct.PyTreeNode):
    # --- rng / time ---
    rng: jnp.ndarray
    wall_time: jnp.ndarray  # f32 []
    time_limit: jnp.ndarray  # f32 []; inf if no time limit
    seq_counter: jnp.ndarray  # i32 []; next event/commitment sequence number

    # --- episode flags ---
    round_ready: jnp.ndarray  # bool []; a scheduling round is in progress
    terminated: jnp.ndarray  # bool []
    truncated: jnp.ndarray  # bool []

    # --- jobs [J] ---
    job_template: jnp.ndarray  # i32[J]
    job_arrival_time: jnp.ndarray  # f32[J]; inf for padding slots
    job_arrival_seq: jnp.ndarray  # i32[J]
    job_arrived: jnp.ndarray  # bool[J]
    job_t_completed: jnp.ndarray  # f32[J]; inf until completed
    job_num_stages: jnp.ndarray  # i32[J]
    job_saturated_stages: jnp.ndarray  # i32[J] (reference job.py:41)
    job_supply: jnp.ndarray  # i32[J]; _total_executor_count, maintained with
    # the reference's exact increments (executor_tracker.py:146-231) —
    # including its staleness for saturated jobs whose idle executors moved
    # to the common pool without a decrement
    num_jobs: jnp.ndarray  # i32 []; actual arrivals this episode

    # --- stages [J,S] ---
    stage_exists: jnp.ndarray  # bool[J,S]
    stage_num_tasks: jnp.ndarray  # i32[J,S]
    stage_remaining: jnp.ndarray  # i32[J,S]
    stage_executing: jnp.ndarray  # i32[J,S]
    stage_completed_tasks: jnp.ndarray  # i32[J,S]
    stage_duration: jnp.ndarray  # f32[J,S]; most_recent_duration
    stage_selected: jnp.ndarray  # bool[J,S]; selected this scheduling round
    schedulable: jnp.ndarray  # bool[J,S]; saved schedulable set for round
    adj: jnp.ndarray  # bool[J,S,S]; adj[j,p,c] == True iff edge p->c

    # --- executors [N] ---
    exec_at_common: jnp.ndarray  # bool[N]
    exec_job: jnp.ndarray  # i32[N]; attached job, -1 = none (common/moving)
    exec_stage: jnp.ndarray  # i32[N]; stage pool residence, -1 = none
    exec_moving: jnp.ndarray  # bool[N]
    exec_dst_job: jnp.ndarray  # i32[N]
    exec_dst_stage: jnp.ndarray  # i32[N]
    exec_arrive_time: jnp.ndarray  # f32[N]; inf if not moving
    exec_arrive_seq: jnp.ndarray  # i32[N]
    exec_executing: jnp.ndarray  # bool[N]
    exec_task_valid: jnp.ndarray  # bool[N]; executor.task is not None
    exec_task_stage: jnp.ndarray  # i32[N]; stage of current/last task
    exec_finish_time: jnp.ndarray  # f32[N]; inf if not executing
    exec_finish_seq: jnp.ndarray  # i32[N]

    # --- incremental scheduling caches [J,S] ---
    # stage saturation and per-stage parent counts, maintained at the few
    # mutation points instead of recomputed via [J,S,S] reductions on every
    # find_schedulable/frontier access inside the event loop (the dominant
    # TPU cost before this; the golden recomputations remain as properties
    # for invariant tests)
    stage_sat: jnp.ndarray  # bool[J,S]; exec_demand <= 0
    unsat_parent_count: jnp.ndarray  # i32[J,S]; parents with ~sat & exists
    incomplete_parent_count: jnp.ndarray  # i32[J,S]; parents not completed

    # --- incremental node-level cache [J,S] ---
    # per-job topological generations over the job's existing, incomplete
    # stages (padding = max_stages), maintained at the ONLY mutation point
    # that changes a job's active subgraph — stage completion in
    # `_handle_task_finished` (bulk passes never complete a stage) — by a
    # depth-bounded single-job [S,S] pass. Replaces the per-observation
    # S-deep [J,S,S] reduction (`compute_node_levels`, the documented most
    # expensive part of `observe`); job arrival/termination need no
    # recompute because the cache ignores `job_active` and the observation
    # masks with `node_mask`. Golden recomputation: `node_level_golden`.
    node_level: jnp.ndarray  # i32[J,S]

    # --- incremental executor-flow counters [J,S] ---
    # the reference maintains these as dicts (_num_commitments_to_stage /
    # _num_moving_to_stage, executor_tracker.py); recomputing them by
    # scatter on every find_schedulable call dominated the event loop on
    # TPU (scatters serialize), so they are first-class state updated at
    # the four mutation points (commit add/consume, send, arrival)
    commit_count: jnp.ndarray  # i32[J,S]
    moving_count: jnp.ndarray  # i32[J,S]

    # --- commitment slots [N] ---
    cm_valid: jnp.ndarray  # bool[N]
    cm_src_job: jnp.ndarray  # i32[N]
    cm_src_stage: jnp.ndarray  # i32[N]
    cm_dst_job: jnp.ndarray  # i32[N]; -1 = common pool destination
    cm_dst_stage: jnp.ndarray  # i32[N]
    cm_seq: jnp.ndarray  # i32[N]

    # --- executor source (reference executor_tracker _curr_source) ---
    source_valid: jnp.ndarray  # bool []
    source_job: jnp.ndarray  # i32 []; -1 = common pool
    source_stage: jnp.ndarray  # i32 []

    # ---------------- derived quantities ----------------

    @property
    def stage_completed(self) -> jnp.ndarray:
        """bool[J,S]; a stage is completed when all its tasks completed
        (reference components/stage.py:40)."""
        return self.stage_exists & (
            self.stage_completed_tasks >= self.stage_num_tasks
        )

    @property
    def job_completed(self) -> jnp.ndarray:
        """bool[J]; no incomplete stages remain (reference job.py:49-50)."""
        done = jnp.where(self.stage_exists, self.stage_completed, True)
        return self.job_arrived & done.all(axis=1)

    @property
    def job_active(self) -> jnp.ndarray:
        """bool[J]; arrived and not completed == membership of
        active_job_ids, which stays sorted by arrival order == job id."""
        return self.job_arrived & ~self.job_completed

    @property
    def job_saturated(self) -> jnp.ndarray:
        """bool[J] (reference job.py:53-54)."""
        return self.job_saturated_stages >= self.job_num_stages

    @property
    def frontier(self) -> jnp.ndarray:
        """bool[J,S]; incomplete stages whose parents all completed
        (reference job.py:24-26, maintained incrementally there AND here,
        via `incomplete_parent_count`). Identical to "no incoming edges in
        the active subgraph" computed by heuristic preprocessing
        (schedulers/heuristics/utils.py:5-14)."""
        return (
            self.stage_exists
            & ~self.stage_completed
            & (self.incomplete_parent_count == 0)
        )

    @property
    def frontier_golden(self) -> jnp.ndarray:
        """Recomputed frontier for invariant tests."""
        incomplete_parent = self.adj & ~self.stage_completed[:, :, None]
        blocked = incomplete_parent.any(axis=1)
        return self.stage_exists & ~self.stage_completed & ~blocked

    @property
    def node_level_golden(self) -> jnp.ndarray:
        """Recomputed per-job topological generations over existing,
        incomplete stages — the golden version of the incremental
        `node_level` field (the shared `topo_levels` reduction above)."""
        active = self.stage_exists & ~self.stage_completed
        adj_act = self.adj & active[:, :, None] & active[:, None, :]
        return topo_levels(active, adj_act)

    @property
    def commit_count_to_stage(self) -> jnp.ndarray:
        """i32[J,S]; slot-derived commitment counts — the slow golden
        version of the incremental `commit_count` field, kept for
        invariant checks in tests."""
        j_cap, s_cap = self.stage_exists.shape
        flat = jnp.zeros(j_cap * s_cap + 1, dtype=jnp.int32)
        idx = jnp.where(
            self.cm_valid & (self.cm_dst_job >= 0),
            self.cm_dst_job * s_cap + self.cm_dst_stage,
            j_cap * s_cap,
        )
        flat = flat.at[idx].add(1)
        return flat[:-1].reshape(j_cap, s_cap)

    @property
    def moving_count_to_stage(self) -> jnp.ndarray:
        """i32[J,S]; executor-derived moving counts — golden version of
        the incremental `moving_count` field, for invariant checks."""
        j_cap, s_cap = self.stage_exists.shape
        flat = jnp.zeros(j_cap * s_cap + 1, dtype=jnp.int32)
        idx = jnp.where(
            self.exec_moving,
            self.exec_dst_job * s_cap + self.exec_dst_stage,
            j_cap * s_cap,
        )
        flat = flat.at[idx].add(1)
        return flat[:-1].reshape(j_cap, s_cap)

    @property
    def exec_demand(self) -> jnp.ndarray:
        """i32[J,S]; remaining tasks minus (moving + committed) executors
        (reference spark_sched_sim.py:566-578). Can be negative."""
        return self.stage_remaining - (
            self.moving_count + self.commit_count
        )

    @property
    def stage_saturated(self) -> jnp.ndarray:
        """bool[J,S] (reference :580-582). Golden recomputation of the
        incremental `stage_sat` field."""
        return self.exec_demand <= 0

    @property
    def all_jobs_complete(self) -> jnp.ndarray:
        j = jnp.arange(self.job_arrived.shape[0], dtype=jnp.int32)
        return jnp.where(j < self.num_jobs, self.job_completed, True).all()

    # --- pools ---

    def pool_member_mask(self, job: jnp.ndarray, stage: jnp.ndarray
                         ) -> jnp.ndarray:
        """bool[N]; executors residing in pool (job, stage)."""
        common = self.exec_at_common
        at_job_pool = (self.exec_job == job) & (self.exec_stage == -1) & \
            ~self.exec_at_common & ~self.exec_moving
        at_stage_pool = (self.exec_job == job) & (self.exec_stage == stage)
        return jnp.where(
            job < 0, common, jnp.where(stage < 0, at_job_pool, at_stage_pool)
        )

    def source_pool_mask(self) -> jnp.ndarray:
        mask = self.pool_member_mask(self.source_job, self.source_stage)
        return jnp.where(self.source_valid, mask, False)

    def commitments_from_source(self) -> jnp.ndarray:
        """i32 []; total outgoing commitments from the source pool."""
        match = (
            self.cm_valid
            & (self.cm_src_job == self.source_job)
            & (self.cm_src_stage == self.source_stage)
        )
        return jnp.where(self.source_valid, match.sum(), 0).astype(jnp.int32)

    def num_committable(self) -> jnp.ndarray:
        """i32 []; source pool size minus its outgoing commitments
        (reference executor_tracker.py:105-111)."""
        return (
            self.source_pool_mask().sum().astype(jnp.int32)
            - self.commitments_from_source()
        )

    def source_job_id(self) -> jnp.ndarray:
        """i32 []; -1 when source is the common pool or cleared (the
        reference returns None in both cases, executor_tracker.py:98-102)."""
        return jnp.where(self.source_valid, self.source_job, -1)


def empty_state(params: EnvParams, rng: jax.Array) -> EnvState:
    """All-zero template state with the right shapes/dtypes."""
    j, s, n = params.max_jobs, params.max_stages, params.num_executors
    f32 = jnp.float32
    i32 = jnp.int32
    return EnvState(
        rng=rng,
        wall_time=f32(0),
        time_limit=INF,
        seq_counter=i32(0),
        round_ready=jnp.bool_(False),
        terminated=jnp.bool_(False),
        truncated=jnp.bool_(False),
        job_template=jnp.zeros(j, i32),
        job_arrival_time=jnp.full(j, INF, f32),
        job_arrival_seq=jnp.zeros(j, i32),
        job_arrived=jnp.zeros(j, bool),
        job_t_completed=jnp.full(j, INF, f32),
        job_num_stages=jnp.zeros(j, i32),
        job_saturated_stages=jnp.zeros(j, i32),
        job_supply=jnp.zeros(j, i32),
        num_jobs=i32(0),
        stage_exists=jnp.zeros((j, s), bool),
        stage_num_tasks=jnp.zeros((j, s), i32),
        stage_remaining=jnp.zeros((j, s), i32),
        stage_executing=jnp.zeros((j, s), i32),
        stage_completed_tasks=jnp.zeros((j, s), i32),
        stage_duration=jnp.zeros((j, s), f32),
        stage_selected=jnp.zeros((j, s), bool),
        schedulable=jnp.zeros((j, s), bool),
        adj=jnp.zeros((j, s, s), bool),
        exec_at_common=jnp.ones(n, bool),
        exec_job=jnp.full(n, -1, i32),
        exec_stage=jnp.full(n, -1, i32),
        exec_moving=jnp.zeros(n, bool),
        exec_dst_job=jnp.full(n, -1, i32),
        exec_dst_stage=jnp.full(n, -1, i32),
        exec_arrive_time=jnp.full(n, INF, f32),
        exec_arrive_seq=jnp.zeros(n, i32),
        exec_executing=jnp.zeros(n, bool),
        exec_task_valid=jnp.zeros(n, bool),
        exec_task_stage=jnp.full(n, -1, i32),
        exec_finish_time=jnp.full(n, INF, f32),
        exec_finish_seq=jnp.zeros(n, i32),
        stage_sat=jnp.ones((j, s), bool),
        unsat_parent_count=jnp.zeros((j, s), i32),
        incomplete_parent_count=jnp.zeros((j, s), i32),
        node_level=jnp.full((j, s), s, i32),
        commit_count=jnp.zeros((j, s), i32),
        moving_count=jnp.zeros((j, s), i32),
        cm_valid=jnp.zeros(n, bool),
        cm_src_job=jnp.full(n, -1, i32),
        cm_src_stage=jnp.full(n, -1, i32),
        cm_dst_job=jnp.full(n, -1, i32),
        cm_dst_stage=jnp.full(n, -1, i32),
        cm_seq=jnp.zeros(n, i32),
        source_valid=jnp.bool_(False),
        source_job=i32(-1),
        source_stage=i32(-1),
    )
