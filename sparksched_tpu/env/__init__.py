from .core import find_schedulable, reset, step  # noqa: F401
from .observe import NUM_NODE_FEATURES, Observation, observe  # noqa: F401
from .state import EnvState, empty_state  # noqa: F401
