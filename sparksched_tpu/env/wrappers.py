"""Wrapper-compatible shims (reference spark_sched_sim/wrappers/).

The reference composes Gymnasium wrappers around its env; here their
semantics live in the core (fixed shapes demand it), and these shims keep
the reference's wrapper API for drop-in use:

- StochasticTimeLimit (reference wrappers/stochastic_time_limit.py:5-31):
  the per-episode Exponential(mean_time_limit) horizon is sampled inside
  `core.reset` — this wrapper just configures it on a gym-compat env.
- DecimaObsWrapper's feature pipeline (reference schedulers/decima/
  env_wrapper.py) is `schedulers.decima.build_features`, applied inside
  the policy so rollouts stay on device.
"""

from __future__ import annotations

from typing import Any

from .gym_compat import SparkSchedSimGymEnv


class StochasticTimeLimit:
    """Configures the exponential episode horizon on a gym-compat env
    (reference wrappers/stochastic_time_limit.py:5-31). Usage:

        env = StochasticTimeLimit(env, mean_time_limit=2e7)
    """

    def __init__(self, env: SparkSchedSimGymEnv,
                 mean_time_limit: float) -> None:
        self.env = env
        env.params = env.params.replace(mean_time_limit=mean_time_limit)

    def __getattr__(self, name: str) -> Any:
        return getattr(self.env, name)
