"""Gymnasium-compatible single-environment adapter.

Exposes the vectorized core through the reference's exact observation /
action dict contract (spark_sched_sim.py:85-125), so code written against
`ArchieGertsman/gym-sparksched` — heuristic schedulers, metrics, episode
loops — runs unchanged on top of the TPU core. Also the bridge used by the
golden parity tests.
"""

from __future__ import annotations

from functools import partial
from typing import Any

import numpy as np

try:
    import gymnasium as gym
    import gymnasium.spaces as sp

    _GYM = True
except ImportError:  # pragma: no cover
    _GYM = False

import jax
import jax.numpy as jnp

from ..config import EnvParams, env_params_from_cfg
from ..workload import WorkloadBank, make_workload_bank
from . import core
from .observe import NUM_NODE_FEATURES, Observation, observe


def compact_obs(params: EnvParams, obs: Observation) -> dict[str, Any]:
    """Convert a padded Observation into the reference's ragged obs dict."""
    node_mask = np.asarray(obs.node_mask)
    job_mask = np.asarray(obs.job_mask)
    # f32 at the host boundary: the reference obs dict is float32, and
    # a bf16 observation bank (params.obs_dtype) must not leak an
    # ml_dtypes array into gym consumers
    nodes_padded = np.asarray(obs.nodes, dtype=np.float32)
    adj = np.asarray(obs.adj)
    supplies = np.asarray(obs.exec_supplies)

    active_jobs = np.flatnonzero(job_mask)
    nodes_list = []
    dag_ptr = [0]
    edge_links = []
    exec_supplies = []
    # flat padded index -> compact node index
    compact_of: dict[int, int] = {}
    s_cap = params.max_stages

    for j in active_jobs:
        stages = np.flatnonzero(node_mask[j])
        for s in stages:
            compact_of[int(j) * s_cap + int(s)] = len(nodes_list)
            nodes_list.append(nodes_padded[j, s])
        for p in stages:
            for c in np.flatnonzero(adj[j, p] & node_mask[j]):
                edge_links.append(
                    [compact_of[int(j) * s_cap + int(p)],
                     compact_of[int(j) * s_cap + int(c)]]
                )
        dag_ptr.append(len(nodes_list))
        exec_supplies.append(int(supplies[j]))

    nodes_arr = (
        np.vstack(nodes_list).astype(np.float32)
        if nodes_list
        else np.zeros((0, NUM_NODE_FEATURES), dtype=np.float32)
    )
    edge_arr = (
        np.array(sorted(edge_links), dtype=np.int64)
        if edge_links
        else np.zeros((0, 2), dtype=np.int64)
    )

    source_job = int(obs.source_job)
    source_job_idx = len(active_jobs)
    if source_job >= 0:
        pos = np.flatnonzero(active_jobs == source_job)
        if pos.size:
            source_job_idx = int(pos[0])

    return {
        "dag_batch": _graph_instance(nodes_arr, edge_arr),
        "dag_ptr": list(dag_ptr),
        "num_committable_execs": int(obs.num_committable),
        "source_job_idx": source_job_idx,
        "exec_supplies": exec_supplies,
        # extras used by adapters (not part of the reference dict)
        "_active_jobs": active_jobs,
        "_compact_of": compact_of,
    }


def _graph_instance(nodes: np.ndarray, edge_links: np.ndarray):
    if _GYM:
        return sp.GraphInstance(
            nodes, np.zeros(len(edge_links), dtype=np.int64), edge_links
        )
    return {"nodes": nodes, "edge_links": edge_links}


def schedulable_flat_indices(
    params: EnvParams, obs: Observation
) -> np.ndarray:
    """Flat padded node indices of schedulable stages, in the reference's
    enumeration order (active jobs by id, stages by id) — index k here
    corresponds to reference action stage_idx == k
    (spark_sched_sim.py:354-355)."""
    sched = np.asarray(obs.schedulable)
    return np.flatnonzero(sched.reshape(-1))


class SparkSchedSimGymEnv(gym.Env if _GYM else object):
    """Reference-compatible Gymnasium env backed by the jitted TPU core.

    Action dict: {"stage_idx": index into the current schedulable list
    (-1 = none), "num_exec": executors to commit} — the reference contract
    (spark_sched_sim.py:85-94)."""

    metadata = {"render_modes": ["human"]}

    def __init__(self, env_cfg: dict[str, Any],
                 bank: WorkloadBank | None = None) -> None:
        self.params = env_params_from_cfg(env_cfg)
        self.bank = bank if bank is not None else make_workload_bank(
            self.params.num_executors, self.params.max_stages,
            **{k: v for k, v in env_cfg.items()
               if k in ("data_dir", "seed", "bucket_size",
                        "bank_dtype")},
        )
        if self.bank.max_stages != self.params.max_stages:
            # real traces may exceed the configured cap; the bank widens and
            # the env params must follow (all shapes key off max_stages)
            self.params = self.params.replace(
                max_stages=self.bank.max_stages,
                max_levels=max(self.params.max_levels,
                               self.bank.max_stages),
            )
        self.state = None
        self._obs: Observation | None = None
        self._auto_seed = np.random.default_rng().integers(2**31)

    @property
    def wall_time(self) -> float:
        return float(self.state.wall_time)

    def reset(self, seed: int | None = None,
              options: dict[str, Any] | None = None):
        if _GYM:
            super().reset(seed=seed)
        if seed is None:
            # gymnasium convention: fresh entropy on unseeded resets
            self._auto_seed += 1
            seed = int(self._auto_seed)
        rng = jax.random.PRNGKey(seed)
        self.state = core.reset(self.params, self.bank, rng)
        self._obs = observe(self.params, self.state)
        return compact_obs(self.params, self._obs), self._info()

    def step(self, action: dict[str, Any]):
        stage_idx = int(action["stage_idx"])
        if stage_idx >= 0:
            flat = schedulable_flat_indices(self.params, self._obs)
            flat_idx = int(flat[stage_idx])
        else:
            flat_idx = -1
        self.state, reward, term, trunc = core.step(
            self.params, self.bank, self.state,
            jnp.int32(flat_idx), jnp.int32(int(action["num_exec"])),
        )
        self._obs = observe(self.params, self.state)
        return (
            compact_obs(self.params, self._obs),
            float(reward),
            bool(term),
            bool(trunc),
            self._info(),
        )

    def _info(self) -> dict[str, Any]:
        return {"wall_time": float(self.state.wall_time)}


class SparkSchedSimVectorEnv:
    """Vectorized batch of environments — the TPU-native counterpart of
    `gym.vector.VectorEnv`. Observations are the padded `Observation`
    pytree with a leading [B] axis; actions are flat padded stage indices
    and 1-based executor counts, [B] each. Episodes auto-reset.

    This is the thin host-facing layer over exactly the machinery the
    trainers use internally (vmapped reset/step + masked auto-reset)."""

    def __init__(self, num_envs: int, env_cfg: dict[str, Any],
                 bank: WorkloadBank | None = None) -> None:
        self.num_envs = num_envs
        self.params = env_params_from_cfg(env_cfg)
        self.bank = bank if bank is not None else make_workload_bank(
            self.params.num_executors, self.params.max_stages,
            **{k: v for k, v in env_cfg.items()
               if k in ("data_dir", "seed", "bucket_size",
                        "bank_dtype")},
        )
        if self.bank.max_stages != self.params.max_stages:
            self.params = self.params.replace(
                max_stages=self.bank.max_stages,
                max_levels=max(self.params.max_levels,
                               self.bank.max_stages),
            )
        params, bank_ = self.params, self.bank

        def _reset(rngs):
            return jax.vmap(lambda k: core.reset(params, bank_, k))(rngs)

        def _step(states, stage_idx, num_exec, reset_rngs):
            def one(st, si, ne, rk):
                nxt, r, term, trunc = core.step(params, bank_, st, si, ne)
                done = term | trunc
                fresh = core.reset(params, bank_, rk)
                nxt = jax.tree_util.tree_map(
                    lambda a, b: jnp.where(done, a, b), fresh, nxt
                )
                return nxt, r, term, trunc

            states, r, term, trunc = jax.vmap(one)(
                states, stage_idx, num_exec, reset_rngs
            )
            return states, observe_batch(params, states), r, term, trunc

        self._reset_jit = jax.jit(_reset)
        self._step_jit = jax.jit(_step)
        self.states = None
        self._rng = jax.random.PRNGKey(0)

    def reset(self, seed: int = 0):
        self._rng = jax.random.PRNGKey(seed)
        self._rng, sub = jax.random.split(self._rng)
        self.states = self._reset_jit(
            jax.random.split(sub, self.num_envs)
        )
        return observe_batch(self.params, self.states)

    def step(self, stage_idx, num_exec):
        self._rng, sub = jax.random.split(self._rng)
        self.states, obs, r, term, trunc = self._step_jit(
            self.states, jnp.asarray(stage_idx, jnp.int32),
            jnp.asarray(num_exec, jnp.int32),
            jax.random.split(sub, self.num_envs),
        )
        return obs, r, term, trunc


@partial(jax.jit, static_argnums=0)
def observe_batch(params: EnvParams, states) -> Observation:
    return jax.vmap(lambda s: observe(params, s))(states)
