"""Padded observations from the environment state.

The reference builds a ragged observation per step — variable-size node
array, dag_ptr, dynamic gym spaces (spark_sched_sim.py:345-406). Here the
observation is fixed-shape [max_jobs, max_stages] with masks, which is what
lets the whole rollout stay on device. Adapters (env/gym_compat.py) compact
it back to the reference layout for drop-in use."""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from flax import struct

from ..config import EnvParams
from .state import EnvState

NUM_NODE_FEATURES = 3  # reference spark_sched_sim.py:25


class Observation(struct.PyTreeNode):
    """Raw env observation (reference obs dict, spark_sched_sim.py:393-399),
    padded. `nodes[..., :]` = (num_remaining_tasks, most_recent_duration,
    is_schedulable) exactly as the reference's 3 node features."""

    nodes: jnp.ndarray  # f32[J,S,3] (bf16 under params.obs_dtype)
    node_mask: jnp.ndarray  # bool[J,S]; active stages of active jobs
    job_mask: jnp.ndarray  # bool[J]; active jobs
    schedulable: jnp.ndarray  # bool[J,S]
    frontier: jnp.ndarray  # bool[J,S]; no incoming edges in active subgraph
    adj: jnp.ndarray  # bool[J,S,S]; template adjacency (mask with node_mask)
    node_level: jnp.ndarray  # i32[J,S]; active-subgraph topo generation
    exec_supplies: jnp.ndarray  # i32[J]
    num_committable: jnp.ndarray  # i32 []
    source_job: jnp.ndarray  # i32 []; job id, -1 = common pool or no source
    wall_time: jnp.ndarray  # f32 []

    @property
    def num_active_jobs(self) -> jnp.ndarray:
        return self.job_mask.sum().astype(jnp.int32)

    @property
    def num_active_nodes(self) -> jnp.ndarray:
        return self.node_mask.sum().astype(jnp.int32)


@partial(jax.jit, static_argnums=(0, 2))
def observe(
    params: EnvParams, state: EnvState, compute_levels: bool = True
) -> Observation:
    """`node_level` comes from the state-maintained incremental cache
    (`state.node_level`, updated per stage completion), masked to the
    active jobs — a gather+select instead of the S-deep [J,S,S]
    topological-generation fori_loop that used to be by far the most
    expensive part of an observation (`core.compute_node_levels` remains
    as the golden recomputation, parity-pinned in
    tests/test_incremental_caches.py). `compute_levels=False` fills the
    padding value instead; only the Decima GNN reads `node_level`.

    `params.obs_dtype = "bfloat16"` (ISSUE 7 low-precision observation
    layout) narrows the feature bank `nodes` — and therefore the
    recorded per-decision `StoredObs.duration` buffers that inherit its
    dtype — to bf16; every consumer (`build_features`, the stored-obs
    rebuild) upcasts to f32 at its read site, so accumulations stay
    f32 and the drift is bounded by one bf16 rounding of each raw
    feature (pinned by the observe-path epsilon test)."""
    job_mask = state.job_active
    node_mask = (
        job_mask[:, None] & state.stage_exists & ~state.stage_completed
    )
    nodes = jnp.stack(
        [
            state.stage_remaining.astype(jnp.float32),
            state.stage_duration,
            state.schedulable.astype(jnp.float32),
        ],
        axis=-1,
    )
    nodes = jnp.where(node_mask[:, :, None], nodes, 0.0)
    if params.obs_dtype == "bfloat16":
        nodes = nodes.astype(jnp.bfloat16)
    if compute_levels:
        node_level = jnp.where(
            node_mask, state.node_level, node_mask.shape[1]
        )
    else:
        node_level = jnp.full(
            node_mask.shape, node_mask.shape[1], jnp.int32
        )
    return Observation(
        nodes=nodes,
        node_mask=node_mask,
        job_mask=job_mask,
        schedulable=state.schedulable & node_mask,
        frontier=state.frontier & node_mask,
        adj=state.adj,
        node_level=node_level,
        exec_supplies=jnp.where(job_mask, state.job_supply, 0),
        num_committable=state.num_committable(),
        source_job=state.source_job_id(),
        wall_time=state.wall_time,
    )
