"""Flat micro-step execution engine.

`core.step` drives one decision at a time: the event loop between
decisions is a `lax.while_loop`, and under `jax.vmap` every lane pays the
*maximum* event count over the batch per decision (measured ~6x the mean
at 64 lanes — the straggler tax of lockstep scanning). This engine
flattens the whole simulation into identical micro-steps —

    DECIDE   one policy commitment (or round finish)
    FULFILL  one source-pool commitment fulfillment
    EVENT    one event pop + handling

— so every lane advances by one unit of work on every iteration and no
lane ever idles waiting for a straggler. Semantics are identical to the
`core.step` loop (same phase-split helpers, same ordering); the flat-vs-
step equivalence is asserted by tests/test_flat_loop.py.

Used by bench/eval paths where only final states and decision counts
matter, and — since round 6 — by the trainers' fast rollout collectors
(`trainers/rollout.py:collect_flat_sync/_async`): with `record=True` a
micro-step additionally reports the DECIDE branch's observation/action/
log-prob plus the micro-step's reward and wall-clock advance, which the
collectors scatter into fixed-offset per-decision buffers (the DECIDE
mask keeps non-decision micro-steps out of the PPO batch).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from flax import struct
from jax import lax

from ..config import EnvParams
from ..obs.telemetry import add as _tm_add
from ..obs.tracing import annotate
from ..workload.bank import WorkloadBank
from .core import (
    RQ_NONE,
    _compute_jobtime,
    _rank_order,
    _onehot2,
    _add_commitment,
    _apply_action,
    _bulk_events_fused,
    _bulk_fulfill,
    _bulk_ready,
    _bulk_relaunch,
    _commit_remaining,
    _fulfill_commitment_phase_a,
    _handle_executor_ready,
    _handle_job_arrival,
    _handle_task_finished,
    _has_pending_event,
    _move_idle_from_pool,
    _next_event,
    _resolve_action,
    find_schedulable,
)
from .observe import observe
from .state import (
    BIG_SEQ,
    EV_EXECUTOR_READY,
    EV_JOB_ARRIVAL,
    EV_TASK_FINISHED,
    EnvState,
)

_i32 = jnp.int32

M_DECIDE, M_FULFILL, M_EVENT = 0, 1, 2


class LoopState(struct.PyTreeNode):
    env: EnvState
    mode: jnp.ndarray  # i32 []
    fulfill_k: jnp.ndarray  # i32 []
    num_idle: jnp.ndarray  # i32 []
    exec_order: jnp.ndarray  # i32[N]
    slot_order: jnp.ndarray  # i32[N]
    decisions: jnp.ndarray  # i32 []; decision micro-steps taken
    episodes: jnp.ndarray  # i32 []; completed episodes
    bulked: jnp.ndarray  # i32 []; events consumed by bulk relaunches


def aux_action_fields(aux: dict, stage_idx: jnp.ndarray,
                      num_exec: jnp.ndarray, max_stages: int):
    """(lgprob, job_idx, num_exec_k) from a policy's aux dict, with the
    derivation fallbacks for policies that omit keys (heuristics report
    no job_idx; it derives from the flat padded node index
    stage_idx = job * max_stages + stage). Single source of truth for
    BOTH collection paths — `trainers/rollout.py` (core.step scan) and
    `micro_step(record=True)` below — so their recorded actions cannot
    drift apart."""
    lgprob = aux.get("lgprob", jnp.float32(0.0))
    job = aux.get(
        "job_idx", jnp.where(stage_idx >= 0, stage_idx // max_stages, 0)
    )
    k = aux.get("num_exec_k", num_exec - 1)
    return lgprob, job, k


class MicroRec(struct.PyTreeNode):
    """One micro-step's trajectory record (`micro_step(record=True)`).

    `obs` and the action fields are meaningful only where `decide` is set
    (the micro-step ran the DECIDE branch on a live lane); `reward` is the
    micro-step's negative job-time contribution (discount-referenced to
    the caller-carried `t_ref`, see `_compute_jobtime`), `dt` its
    wall-clock advance (pre-reset), and `reset` whether the episode ended
    during the micro-step."""

    obs: Any  # Observation at the micro-step's start
    stage_idx: jnp.ndarray  # i32 []; raw policy output
    job_idx: jnp.ndarray  # i32 []
    num_exec_k: jnp.ndarray  # i32 []; 0-based exec choice
    lgprob: jnp.ndarray  # f32 []
    decide: jnp.ndarray  # bool []
    reward: jnp.ndarray  # f32 []
    dt: jnp.ndarray  # f32 []
    reset: jnp.ndarray  # bool []


def take_slot(store, i):
    """One session's `LoopState` gathered from a [C]-stacked store at a
    (possibly traced) slot index — the serve programs' gather
    (`serve/aot.py`) and the session pager's host-side page-out
    (`serve/session.py`) share this one definition, so the paged copy
    of a slot is by construction the same view the compiled program
    serves."""
    return jax.tree_util.tree_map(lambda a: a[i], store)


def write_slot(store, i, ls, drop: bool = False):
    """`take_slot`'s scatter partner: write one session's `LoopState`
    (or, with a vector index and [K]-stacked values, K sessions) back
    into a [C]-stacked store at slot index `i`. With `drop`,
    out-of-range indices drop instead of clamping (the batched serve
    program's padding-lane discipline). One definition shared by the
    serve programs' scatter-back (`serve/aot.py`) and the session
    store's slot writer / pager page-in (`serve/session.py`), so a
    paged or group-routed write is by construction the same update the
    compiled program performs."""
    kw = {"mode": "drop"} if drop else {}
    return jax.tree_util.tree_map(
        lambda s, v: s.at[i].set(v, **kw), store, ls
    )


class TrajRing(struct.PyTreeNode):
    """Device-resident trajectory ring (ISSUE 18): a [R]-stacked record
    pytree plus a monotone append cursor, living next to the session
    store and donated through the record-on serve programs.

    `cursor` counts TOTAL records ever appended (not the wrapped
    position): the host drains span `[drained, cursor)` and recovers the
    wrapped indices itself (`i % R`), so an overrun (more than R appends
    between drains) is detectable as `cursor - drained > R` instead of
    silently aliasing. `rec` is any [R, ...]-stacked record pytree — the
    serve layer stacks `RingRec` (serve/aot.py), but the append below is
    schema-agnostic."""

    cursor: jnp.ndarray  # i32 []; total records appended since init
    rec: Any  # [R, ...] record pytree


def ring_append(ring: TrajRing, recs, mask) -> TrajRing:
    """Masked in-JIT append into the ring: scalar `mask` appends one
    record, a [K] `mask` appends the masked subset of [K]-stacked
    records in order (exclusive-cumsum compaction), both via a single
    `mode="drop"` scatter — masked-off lanes target index R (out of
    range) and drop, so the traced program is branch-free and the
    donated ring updates in place. The wrap (`% R`) happens here, in
    the compiled program; the cursor advances by the number of records
    actually appended."""
    R = jax.tree_util.tree_leaves(ring.rec)[0].shape[0]
    if jnp.ndim(mask) == 0:
        n = mask.astype(_i32)
        idx = jnp.where(mask, ring.cursor % R, R)
    else:
        mi = mask.astype(_i32)
        n = mi.sum()
        offs = jnp.cumsum(mi) - mi  # exclusive cumsum: append order
        idx = jnp.where(mask, (ring.cursor + offs) % R, R)
    rec2 = jax.tree_util.tree_map(
        lambda s, v: s.at[idx].set(v, mode="drop"), ring.rec, recs
    )
    return TrajRing(cursor=ring.cursor + n, rec=rec2)


def init_loop_state(state: EnvState) -> LoopState:
    n = state.exec_job.shape[0]
    return LoopState(
        env=state,
        mode=_i32(M_DECIDE),
        fulfill_k=_i32(0),
        num_idle=_i32(0),
        exec_order=jnp.zeros(n, _i32),
        slot_order=jnp.zeros(n, _i32),
        decisions=_i32(0),
        episodes=_i32(0),
        bulked=_i32(0),
    )


def _pop_event(params: EnvParams, st: EnvState, enabled):
    """Pop + handle one event (core._resume_simulation body). Shared by
    the full micro-step's EVENT branch and `event_micro_step` so the two
    can never drift. Returns
    (state, req_kind, rj, rs, event_arg, quirk, popped, kind);
    a no-op (RQ_NONE, popped=False) when `enabled` is False or the
    queue is drained. `popped`/`kind` feed the telemetry counters."""
    has, t, kind, arg = _next_event(params, st)

    def pop(st: EnvState):
        st = st.replace(wall_time=t)
        quirk = st.source_job_id()
        st, rk, rj, rs = lax.switch(
            kind,
            [
                lambda st, a: _handle_job_arrival(st, a),
                lambda st, a: _handle_task_finished(st, a),
                lambda st, a: _handle_executor_ready(st, a),
            ],
            st,
            arg,
        )
        return st, rk, rj, rs, quirk

    def drained(st: EnvState):
        return st, _i32(RQ_NONE), _i32(-1), _i32(-1), _i32(-1)

    popped = enabled & has
    st, rk, rj, rs, quirk = lax.cond(popped, pop, drained, st)
    return st, rk, rj, rs, arg, quirk, popped, kind


def _bulk_cycle_chain(
    params: EnvParams,
    bank: WorkloadBank,
    env: EnvState,
    is_event: jnp.ndarray,
    bulk_events: int,
    bulk_cycles: int,
    bulk_fused: bool = True,
):
    """`bulk_cycles` chained bulk passes. With `bulk_fused` (the ISSUE-7
    default) each cycle is ONE `core._bulk_events_fused` kernel that
    consumes a mixed relaunch/arrival run in exact (time, seq) order —
    one scan, one rng split, one merged state update per cycle; without
    it, each cycle is the round-3/4 (relaunch cascade + arrival burst)
    pass pair. The first cycle runs whenever the lane is in EVENT mode;
    each further cycle runs only while the sequential between-event
    tail would be a no-op — `num_committable() == 0` (round-ready flip
    and move_and_clear are gated on committable > 0) and the wall clock
    inside the episode limit (the freeze point) — so chaining is
    exactly the next micro-step's bulk phase minus its provably-no-op
    tail. Returns (env, events_consumed, relaunch_events, ready_events)
    — the last two split the count by event kind for the telemetry
    counters."""
    nb = _i32(0)
    nb_rel = _i32(0)
    nb_rdy = _i32(0)
    for i in range(bulk_cycles):
        on = is_event if i == 0 else (
            is_event
            & (env.num_committable() == 0)
            & (env.wall_time < env.time_limit)
        )
        if bulk_fused:
            env, nbi1, nbi2 = _bulk_events_fused(
                params, bank, env, on,
                stop_at_limit=True, max_events=bulk_events,
            )
        else:
            env, nbi1 = _bulk_relaunch(
                params, bank, env, on,
                stop_at_limit=True, max_events=bulk_events,
            )
            # chain the arrival-burst pass; never past an episode-limit
            # crossing the cascade just committed (the freeze point)
            env, nbi2 = _bulk_ready(
                params, bank, env,
                on & (env.wall_time < env.time_limit),
                stop_at_limit=True,
            )
        nb = nb + nbi1 + nbi2
        nb_rel = nb_rel + nbi1
        nb_rdy = nb_rdy + nbi2
    return env, nb, nb_rel, nb_rdy


def _lane_done(env: EnvState) -> jnp.ndarray:
    """Episode over: all jobs complete or the time limit was crossed."""
    return env.all_jobs_complete | (env.wall_time >= env.time_limit)


def _fused_pop_gate(env: EnvState, nb: jnp.ndarray) -> jnp.ndarray:
    """May this micro-step still pop the run-cutting event after its
    bulk passes consumed `nb` events? Always when nothing was bulked
    (the classic single-pop path — the previous micro-step's tail ran
    for real); after a bulk only when the skipped between-event tail is
    provably a no-op (see `_bulk_cycle_chain`)."""
    return (nb == 0) | (
        (env.num_committable() == 0)
        & (env.wall_time < env.time_limit)
    )


def _clear_round(st: EnvState) -> EnvState:
    return st.replace(
        source_valid=jnp.bool_(False),
        source_job=_i32(-1),
        source_stage=_i32(-1),
        stage_selected=jnp.zeros_like(st.stage_selected),
        round_ready=jnp.bool_(False),
        schedulable=jnp.zeros_like(st.schedulable),
    )


def _apply_decision(
    params: EnvParams, ls: LoopState, stage_idx: jnp.ndarray,
    num_exec: jnp.ndarray, fulfill_bulk: bool,
) -> LoopState:
    """core.step's front half for ONE precomputed policy decision on
    `ls.env`: commit (or round finish), fulfillment-phase setup, mode
    bookkeeping. Shared by `micro_step`'s DECIDE branch and the
    single-eval `decide_micro_step` so the two can never drift. The
    caller runs the shared `_finish_micro_step` tail."""
    st = ls.env
    n = st.exec_job.shape[0]
    s_cap = params.max_stages
    j, s = stage_idx // s_cap, stage_idx % s_cap
    valid = (
        (stage_idx >= 0)
        & (stage_idx < params.num_nodes)
        & st.schedulable[j, s]
    )

    def do_commit(stt: EnvState) -> EnvState:
        committable = stt.num_committable()
        nn = jnp.clip(num_exec, 1, committable)
        nn = jnp.minimum(nn, stt.exec_demand[j, s])
        stt = _add_commitment(stt, nn, j, s)
        j_cap, s_cap2 = stt.stage_selected.shape
        sel = _onehot2(j_cap, s_cap2, j, s)
        stt = stt.replace(stage_selected=stt.stage_selected | sel)
        return stt.replace(
            schedulable=find_schedulable(
                params, stt, stt.source_job_id()
            )
        )

    st = lax.cond(valid, do_commit, _commit_remaining, st)
    round_continues = (
        (st.num_committable() > 0) & st.schedulable.any()
    )

    def finish(st: EnvState):
        st = _commit_remaining(st)
        idle = st.source_pool_mask() & ~st.exec_executing
        num_idle = idle.sum().astype(_i32)
        exec_order = _rank_order(
            jnp.where(idle, jnp.arange(n, dtype=_i32), BIG_SEQ)
        )
        match = (
            st.cm_valid
            & (st.cm_src_job == st.source_job)
            & (st.cm_src_stage == st.source_stage)
        )
        slot_order = _rank_order(
            jnp.where(match, st.cm_seq, BIG_SEQ)
        )
        if fulfill_bulk:
            # the bulk pass samples durations, and bank accesses
            # must stay OUT of lane-dependent branches: batching a
            # cond instantiates branch constants as broadcast
            # outputs, materializing a per-lane copy of the bank's
            # [T,S,3,L,K] duration table (a 19 GB HBM allocation at
            # 512 lanes on the v5e). The pass runs unconditionally
            # in the shared tail (_finish_micro_step), gated by
            # mode — exactly like the relaunch cascade above the
            # switch — along with the complete/clear/mode step.
            return st, _i32(M_FULFILL), num_idle, exec_order, \
                slot_order, _i32(0)
        k0 = _i32(0)
        # phase already complete (empty): clear and go straight to
        # events — matching core.step, which clears only after
        # _fulfill_from_source returns (no leftover backup search
        # remains to observe stage_selected)
        complete = k0 >= num_idle
        st = lax.cond(complete, _clear_round, lambda x: x, st)
        mode = jnp.where(complete, M_EVENT, M_FULFILL)
        return st, mode.astype(_i32), num_idle, exec_order, \
            slot_order, k0

    def stay(st: EnvState):
        return (
            st, _i32(M_DECIDE), _i32(0), ls.exec_order,
            ls.slot_order, _i32(0),
        )

    st, mode, num_idle, eo, so, k0 = lax.cond(
        round_continues, stay, finish, st
    )
    return ls.replace(
        env=st,
        mode=mode,
        fulfill_k=k0,
        num_idle=num_idle,
        exec_order=eo,
        slot_order=so,
        decisions=ls.decisions + 1,
    )


def _fulfill_branch(ls: LoopState):
    """One commitment fulfillment (core._fulfill_from_source body, one k
    per micro-step). Returns (ls, rk, rj, rs, e, quirk, popped, kind) —
    the shared-tail argument tuple."""
    st = ls.env
    k = ls.fulfill_k
    e = ls.exec_order[k]
    quirk = st.source_job_id()

    def do(st: EnvState):
        return _fulfill_commitment_phase_a(st, e, ls.slot_order[k])

    def skip(st: EnvState):
        return st, _i32(RQ_NONE), _i32(-1), _i32(-1)

    st, rk, rj, rs = lax.cond(k < ls.num_idle, do, skip, st)
    last = k + 1 >= ls.num_idle
    # round clearing is deferred to the shared tail (after this
    # fulfillment's resolve/apply), matching core.step which clears
    # only after _fulfill_from_source returns — the final executor's
    # backup-stage search must still see stage_selected
    mode = jnp.where(last, M_EVENT, M_FULFILL).astype(_i32)
    return ls.replace(env=st, mode=mode, fulfill_k=k + 1), rk, rj, rs, \
        e, quirk, jnp.bool_(False), _i32(0)


def _event_branch(params: EnvParams, ls: LoopState, nb: jnp.ndarray):
    """One event pop + handling (core._resume_simulation body) with the
    fused-pop gate over the `nb` events the bulk passes just consumed.
    Returns the shared-tail argument tuple."""
    st, rk, rj, rs, arg, quirk, popped, kind = _pop_event(
        params, ls.env, _fused_pop_gate(ls.env, nb)
    )
    return ls.replace(env=st), rk, rj, rs, arg, quirk, popped, kind


def micro_step(
    params: EnvParams,
    bank: WorkloadBank,
    policy_fn: Callable,
    ls: LoopState,
    rng: jax.Array,
    auto_reset: bool = True,
    compute_levels: bool = True,
    event_bulk: bool = True,
    bulk_events: int = 8,
    fulfill_bulk: bool = False,
    bulk_cycles: int = 1,
    record: bool = False,
    reset_fn: Callable | None = None,
    t_ref: jnp.ndarray | None = None,
    telemetry=None,
    bulk_fused: bool = True,
) -> LoopState | tuple:
    """One unit of work for one lane (vmap over lanes). With
    `event_bulk`, an EVENT micro-step consumes a whole run of relaunch
    events via `core._bulk_relaunch` (hoisted above the mode switch —
    it samples task durations, and bank accesses must stay out of
    lane-dependent branches; see core's structural note), chains the
    arrival-burst pass, and then — new in round 4 — still pops the
    run-cutting event in the SAME micro-step ("fused pop") whenever the
    sequential engine's between-event tail is provably a no-op:
    `num_committable() == 0` (the tail's round-ready flip and
    move_and_clear are both gated on committable > 0, and the bulk
    passes stop BEFORE any point where they could raise it — a
    source-joining arrival ends `_bulk_ready`'s prefix) and the wall
    clock is inside the episode limit (the freeze point). `bulk_cycles`
    extra (relaunch + ready) pass pairs run first under the same gate,
    consuming alternating run/burst patterns that previously cost one
    micro-step per kind switch.

    With `fulfill_bulk`, a DECIDE micro-step that finishes a commitment
    round consumes the fulfillment phase's simple prefix in one
    `core._bulk_fulfill` pass (exactly `core.step`'s bulk path) and only
    the backup-scheduling leftovers take FULFILL micro-steps — removing
    the ~1 FULFILL step per decision the flat loop otherwise pays. Like
    the relaunch cascade, the pass's op count is charged to every lane
    on every micro-step under vmap (a batched `lax.switch` executes all
    branches), so the flag is calibration-gated in bench.py rather than
    assumed to win.

    With `record` (static), returns `(LoopState, MicroRec)` instead of
    just the state: the DECIDE branch's observation/policy outputs are
    hoisted above the mode switch (identical cost under vmap, where a
    batched switch executes every branch anyway) so the trainers' flat
    collectors can scatter them into per-decision buffers. `reset_fn`,
    when given, replaces the auto-reset draw: called as
    `reset_fn(key, episodes)` with the lane's completed-episode count,
    which the async collector maps to the group-shared reset ordinal.
    `t_ref` is the discount reference wall time for the recorded reward
    (the wall time of the round-finishing decision; only read when
    `params.beta > 0`).

    With `bulk_fused` (the ISSUE-7 default), the bulk phase is the
    single fused `core._bulk_events_fused` kernel — mixed
    relaunch/arrival runs in exact queue order, one pass — instead of
    the (relaunch cascade + arrival burst) pass pair; step-exact
    either way (tests/test_flat_loop.py pins fused vs unfused).

    With `telemetry` (an `obs.Telemetry`, static None check), the
    counters are advanced on live lanes — micro-step composition by
    entry mode, events consumed (`loop_iters`), pops by kind, bulk-pass
    consumption — and the return gains a trailing telemetry element:
    `(ls[, rec], telemetry)`. The None path threads nothing."""
    track = telemetry is not None
    k_pol, k_reset = jax.random.split(rng)
    ls0 = ls  # pre-bulk state: the freeze path must restore exactly this
    if event_bulk:
        env_b, nb, nb_rel, nb_rdy = _bulk_cycle_chain(
            params, bank, ls.env, ls.mode == M_EVENT, bulk_events,
            bulk_cycles, bulk_fused,
        )
        ls = ls.replace(env=env_b, bulked=ls.bulked + nb)
    else:
        nb = _i32(0)
        nb_rel = nb_rdy = nb
    st = ls.env
    s_cap = params.max_stages

    if record:
        # bulk passes never touch DECIDE-mode lanes, so the post-bulk env
        # equals the pre-bulk env wherever the decide branch runs and the
        # hoisted observation is exactly what the branch would compute
        r_obs = observe(params, st, compute_levels)
        r_stage, r_nexec, r_aux = policy_fn(k_pol, r_obs)
        r_lgprob, r_job, r_k = aux_action_fields(
            r_aux, r_stage, r_nexec, s_cap
        )

    # ---- DECIDE: one commitment from the policy (core.step's front
    # half; the commit/round logic lives in the shared `_apply_decision`)
    def decide(ls: LoopState):
        if record:
            stage_idx, num_exec = r_stage, r_nexec
        else:
            obs = observe(params, ls.env, compute_levels)
            stage_idx, num_exec, _ = policy_fn(k_pol, obs)
        ls2 = _apply_decision(params, ls, stage_idx, num_exec, fulfill_bulk)
        return ls2, _i32(RQ_NONE), _i32(-1), _i32(-1), _i32(0), \
            ls2.env.source_job_id(), jnp.bool_(False), _i32(0)

    # ---- FULFILL: one commitment fulfillment (core._fulfill_from_source
    # body, one k per micro-step)
    def fulfill(ls: LoopState):
        return _fulfill_branch(ls)

    # ---- EVENT: one event pop + handling (core._resume_simulation
    # body). Fused pop: even after the bulk passes consumed events, the
    # run-cutting event they stopped at is popped in the same micro-step
    # when the skipped between-event tail is provably a no-op
    def event(ls: LoopState):
        return _event_branch(params, ls, nb)

    with annotate("env/micro_step"):
        ls2, rk, rj, rs, e, quirk, popped, ev_kind = lax.switch(
            ls.mode, [decide, fulfill, event], ls
        )
        out = _finish_micro_step(
            params, bank, ls0, ls2, rk, rj, rs, e, quirk, k_reset,
            auto_reset, fulfill_bulk=fulfill_bulk, record=record,
            reset_fn=reset_fn, t_ref=t_ref, telem=telemetry,
        )
    if track:
        *out, telemetry = out
        out = out[0] if len(out) == 1 else tuple(out)
    # frozen lanes (auto_reset=False, episode already over at entry) must
    # not report a decision — the tail rolls their state/counters back
    was_done = _lane_done(ls0.env)
    if track:
        live = ~was_done
        pop_live = popped & live
        telemetry = _tm_add(
            telemetry,
            decide_steps=(ls0.mode == M_DECIDE) & live,
            fulfill_steps=(ls0.mode == M_FULFILL) & live,
            event_steps=(ls0.mode == M_EVENT) & live,
            commit_rounds=(ls0.mode == M_DECIDE) & live
            & (ls2.mode != M_DECIDE),
            loop_iters=jnp.where(live, nb + popped.astype(_i32), 0),
            bulk_relaunch_events=jnp.where(live, nb_rel, 0),
            bulk_ready_events=jnp.where(live, nb_rdy, 0),
            bulk_passes=(nb > 0) & live,
            ev_job_arrival=pop_live & (ev_kind == EV_JOB_ARRIVAL),
            ev_task_finished=pop_live & (ev_kind == EV_TASK_FINISHED),
            ev_exec_ready=pop_live & (ev_kind == EV_EXECUTOR_READY),
        )
    if not record:
        return (out, telemetry) if track else out
    ls_f, (r_reward, r_dt, r_reset) = out
    rec = MicroRec(
        obs=r_obs,
        stage_idx=r_stage,
        job_idx=r_job,
        num_exec_k=r_k,
        lgprob=r_lgprob,
        decide=(ls0.mode == M_DECIDE) & ~was_done,
        reward=r_reward,
        dt=r_dt,
        reset=r_reset,
    )
    return (ls_f, rec, telemetry) if track else (ls_f, rec)


def _finish_micro_step(
    params: EnvParams,
    bank: WorkloadBank,
    ls: LoopState,
    ls2: LoopState,
    rk: jnp.ndarray,
    rj: jnp.ndarray,
    rs: jnp.ndarray,
    e: jnp.ndarray,
    quirk: jnp.ndarray,
    k_reset: jax.Array,
    auto_reset: bool,
    fulfill_bulk: bool = False,
    record: bool = False,
    reset_fn: Callable | None = None,
    t_ref: jnp.ndarray | None = None,
    telem=None,
) -> LoopState | tuple:
    """Shared micro-step tail: move resolution/application, round clearing
    and readiness, episode end. `ls` is the pre-step state, `ls2` the
    state after the mode branch ran. With `record`, also returns the
    micro-step's `(reward, dt, reset)` triple, measured on the pre-reset
    state and zeroed for frozen lanes (see `MicroRec`). With `telem`,
    the bulk-fulfillment hit count is added (live lanes only) and the
    telemetry is returned as the trailing element.

    With `fulfill_bulk`, a DECIDE micro-step that just finished a
    commitment round (mode went DECIDE -> FULFILL) consumes the
    fulfillment phase's simple prefix here via `core._bulk_fulfill`,
    hoisted out of the decide branch so the duration table is never a
    lane-dependent cond operand (see the branch comment in
    `micro_step.decide.finish`). The pass is a strict state no-op
    (rng included) for lanes where the gate is off: every scatter in
    `_bulk_fulfill` is masked by its candidate prefix, which is empty
    at num_idle=0."""
    st = ls2.env

    if fulfill_bulk:
        want = (ls.mode == M_DECIDE) & (ls2.mode == M_FULFILL)
        ni = jnp.where(want, ls2.num_idle, 0)
        st, k0 = _bulk_fulfill(
            params, bank, st, ni, ls2.exec_order, ls2.slot_order
        )
        if telem is not None:
            live = ~_lane_done(ls.env)
            telem = _tm_add(
                telem, bulk_fulfill_hits=jnp.where(live, k0, 0)
            )
        # phase complete (empty, or fully consumed by the pass): clear
        # and go straight to events — matching core.step, which clears
        # only after _fulfill_from_source returns (no leftover backup
        # search remains to observe stage_selected)
        complete = want & (k0 >= ls2.num_idle)
        st = lax.cond(complete, _clear_round, lambda x: x, st)
        ls2 = ls2.replace(
            fulfill_k=jnp.where(want, k0, ls2.fulfill_k).astype(_i32),
            mode=jnp.where(complete, M_EVENT, ls2.mode).astype(_i32),
        )

    # shared move resolution + application (the only bank access)
    ak, tj, ts = _resolve_action(params, st, rk, e, rj, rs, quirk)
    st = _apply_action(params, bank, st, ak, e, tj, ts)

    # a FULFILL micro-step that consumed the round's last idle executor
    # clears the round here, after its resolve/apply (core.step ordering)
    fulfill_done = (ls.mode == M_FULFILL) & (
        ls2.fulfill_k >= ls2.num_idle
    )
    st = lax.cond(fulfill_done, _clear_round, lambda x: x, st)

    # post-event round-ready check (core._resume_simulation :tail), only
    # meaningful after EVENT micro-steps
    is_event = ls.mode == M_EVENT
    committable = st.num_committable()
    sched = find_schedulable(params, st, st.source_job_id())
    ready = is_event & (committable > 0) & sched.any()

    def set_ready(st: EnvState) -> EnvState:
        return st.replace(round_ready=jnp.bool_(True), schedulable=sched)

    def not_ready(st: EnvState) -> EnvState:
        def move_and_clear(st: EnvState) -> EnvState:
            idle = st.source_pool_mask() & ~st.exec_executing
            st = _move_idle_from_pool(
                st, st.source_job, st.source_stage, idle
            )
            return st.replace(
                source_valid=jnp.bool_(False),
                source_job=_i32(-1),
                source_stage=_i32(-1),
            )

        return lax.cond(
            is_event & (committable > 0), move_and_clear,
            lambda x: x, st,
        )

    st = lax.cond(ready, set_ready, not_ready, st)
    mode = jnp.where(ready, M_DECIDE, ls2.mode).astype(_i32)

    # episode end: auto-reset (unconditional reset + select keeps the
    # workload bank out of lane-dependent conditionals); with
    # auto_reset=False finished lanes freeze instead (tests, evals)
    done = _lane_done(st)
    was_done = _lane_done(ls.env)
    if record:
        # reward/dt on the PRE-reset state (the reset select below would
        # lose the episode's final span); frozen lanes report zeros
        t_old = ls.env.wall_time
        jt = _compute_jobtime(
            params, st, t_old, ls.env.job_active, t_ref
        )
        rec_tail = (
            jnp.where(was_done, 0.0, -jt),
            jnp.where(was_done, 0.0, st.wall_time - t_old),
            done & ~was_done,
        )
    if auto_reset:
        from . import core as _core

        if reset_fn is None:
            fresh = _core.reset(params, bank, k_reset)
        else:
            # ls2.episodes is the pre-increment completed-episode count:
            # the async collector's group-shared reset-ordinal hook
            fresh = reset_fn(k_reset, ls2.episodes)
        st = jax.tree_util.tree_map(
            lambda a, b: jnp.where(done, a, b), fresh, st
        )
        mode = jnp.where(done, M_DECIDE, mode).astype(_i32)
    else:
        st = jax.tree_util.tree_map(
            lambda a, b: jnp.where(was_done, a, b), ls.env, st
        )
        ls2 = ls2.replace(
            decisions=jnp.where(
                was_done, ls.decisions, ls2.decisions
            ).astype(_i32),
            bulked=jnp.where(
                was_done, ls.bulked, ls2.bulked
            ).astype(_i32),
        )
    out = ls2.replace(
        env=st,
        mode=mode,
        episodes=ls2.episodes + (done & ~was_done).astype(_i32),
    )
    ret = (out, rec_tail) if record else (out,)
    if telem is not None:
        ret = ret + (telem,)
    return ret[0] if len(ret) == 1 else ret


def event_micro_step(
    params: EnvParams,
    bank: WorkloadBank,
    ls: LoopState,
    rng: jax.Array,
    auto_reset: bool = True,
    event_bulk: bool = True,
    bulk_events: int = 8,
    bulk_cycles: int = 1,
    record: bool = False,
    reset_fn: Callable | None = None,
    t_ref: jnp.ndarray | None = None,
    telemetry=None,
    bulk_fused: bool = True,
) -> LoopState | tuple:
    """One EVENT-only micro-step: lanes in M_EVENT mode pop + handle one
    event (with the full shared tail); other lanes no-op. With `record`,
    also returns the `(reward, dt, reset)` triple (zeroed for non-event
    lanes, which are untouched). With `telemetry`, counters advance for
    live event-mode lanes only and the return gains a trailing
    telemetry element.

    The point is cost amortization under vmap: a full `micro_step` pays
    for all three mode branches on every lane (batched `lax.switch`
    executes every branch), but in steady state >90% of micro-steps are
    events — the policy/observe/argsort work of the DECIDE branch is
    wasted 10x over. Interleaving K-1 of these between full micro-steps
    ("event burst") advances event-heavy lanes at a fraction of the cost;
    per-lane semantics are unchanged because event processing is exactly
    the M_EVENT path and non-event lanes are untouched."""
    track = telemetry is not None
    is_event = ls.mode == M_EVENT
    _, k_reset = jax.random.split(rng)

    ls0 = ls.replace(mode=_i32(M_EVENT))  # pre-bulk state for the tail
    if event_bulk:
        env_b, nb, nb_rel, nb_rdy = _bulk_cycle_chain(
            params, bank, ls.env, is_event, bulk_events, bulk_cycles,
            bulk_fused,
        )
        ls = ls.replace(env=env_b, bulked=ls.bulked + nb)
        pop_on = is_event & _fused_pop_gate(env_b, nb)
    else:
        nb = _i32(0)
        nb_rel = nb_rdy = nb
        pop_on = is_event
    st, rk, rj, rs, arg, quirk, popped, ev_kind = _pop_event(
        params, ls.env, pop_on
    )
    ls_ev = ls.replace(mode=_i32(M_EVENT), env=st)
    out = _finish_micro_step(
        params, bank, ls0, ls_ev,
        rk, rj, rs, arg, quirk, k_reset, auto_reset,
        record=record, reset_fn=reset_fn, t_ref=t_ref,
    )
    if record:
        out, (rw, dt, rs_) = out
    if track:
        was_done = _lane_done(ls0.env)
        gate = is_event & ~was_done
        pop_live = popped & gate
        telemetry = _tm_add(
            telemetry,
            event_steps=gate,
            loop_iters=jnp.where(gate, nb + popped.astype(_i32), 0),
            bulk_relaunch_events=jnp.where(gate, nb_rel, 0),
            bulk_ready_events=jnp.where(gate, nb_rdy, 0),
            bulk_passes=(nb > 0) & gate,
            ev_job_arrival=pop_live & (ev_kind == EV_JOB_ARRIVAL),
            ev_task_finished=pop_live & (ev_kind == EV_TASK_FINISHED),
            ev_exec_ready=pop_live & (ev_kind == EV_EXECUTOR_READY),
        )
    # non-event lanes are untouched (their rng/state must not advance)
    final = jax.tree_util.tree_map(
        lambda a, b: jnp.where(is_event, a, b), out, ls
    )
    if record:
        zero = jnp.float32(0.0)
        rec_tail = (
            jnp.where(is_event, rw, zero),
            jnp.where(is_event, dt, zero),
            is_event & rs_,
        )
        return (final, rec_tail, telemetry) if track else (final, rec_tail)
    return (final, telemetry) if track else final


def decide_micro_step(
    params: EnvParams,
    bank: WorkloadBank,
    ls: LoopState,
    stage_idx: jnp.ndarray,
    num_exec: jnp.ndarray,
    rng: jax.Array,
    auto_reset: bool = True,
    fulfill_bulk: bool = False,
    reset_fn: Callable | None = None,
    t_ref: jnp.ndarray | None = None,
    telemetry=None,
) -> tuple:
    """One DECIDE-only micro-step driven by a PRECOMPUTED policy decision:
    lanes in M_DECIDE mode commit (or round-finish) via the shared
    `_apply_decision` + `_finish_micro_step` pair; other lanes no-op
    bit-exactly (their rng/state must not advance). The single-eval flat
    collectors (`trainers/rollout.py:collect_flat_*_batch`) evaluate the
    policy ONCE per decision row at batch level and feed the outputs
    here, so the GNN appears exactly once per recorded decision instead
    of once per micro-step group. Returns
    `(ls, (decided, reward, dt, reset)[, telemetry])`; `decided` marks
    lanes that recorded a decision (live and in DECIDE mode at entry)."""
    track = telemetry is not None
    is_dec = ls.mode == M_DECIDE
    _, k_reset = jax.random.split(rng)
    # force the tail's mode-keyed logic to the DECIDE shape for every
    # lane (the event_micro_step pattern): non-decide lanes' branch
    # results are discarded by the final select below
    ls0 = ls.replace(mode=_i32(M_DECIDE))
    ls2 = _apply_decision(params, ls0, stage_idx, num_exec, fulfill_bulk)
    mode2 = ls2.mode  # pre-tail mode: DECIDE -> non-DECIDE == round done
    out = _finish_micro_step(
        params, bank, ls0, ls2, _i32(RQ_NONE), _i32(-1), _i32(-1),
        _i32(0), ls2.env.source_job_id(), k_reset, auto_reset,
        fulfill_bulk=fulfill_bulk, record=True, reset_fn=reset_fn,
        t_ref=t_ref, telem=telemetry,
    )
    if track:
        out_ls, (rw, dt, rs_), telemetry = out
    else:
        out_ls, (rw, dt, rs_) = out
    was_done = _lane_done(ls.env)
    decided = is_dec & ~was_done
    if track:
        telemetry = _tm_add(
            telemetry,
            decide_steps=decided,
            commit_rounds=decided & (mode2 != M_DECIDE),
        )
    final = jax.tree_util.tree_map(
        lambda a, b: jnp.where(is_dec, a, b), out_ls, ls
    )
    zero = jnp.float32(0.0)
    rec = (
        decided,
        jnp.where(is_dec, rw, zero),
        jnp.where(is_dec, dt, zero),
        is_dec & rs_,
    )
    return (final, rec, telemetry) if track else (final, rec)


def drain_micro_step(
    params: EnvParams,
    bank: WorkloadBank,
    ls: LoopState,
    rng: jax.Array,
    auto_reset: bool = True,
    event_bulk: bool = True,
    bulk_events: int = 8,
    bulk_cycles: int = 1,
    reset_fn: Callable | None = None,
    t_ref: jnp.ndarray | None = None,
    telemetry=None,
    bulk_fused: bool = True,
    masked: bool = True,
) -> tuple:
    """One NON-POLICY micro-step: FULFILL and EVENT lanes advance exactly
    as `micro_step`'s branches (bulk passes + fused pop included); DECIDE
    lanes no-op bit-exactly. Contains no observe/policy ops at all — the
    point of the single-eval restructure is that this program, not the
    policy-bearing one, runs between decisions. Returns
    `(ls, (reward, dt, reset)[, telemetry])`.

    `masked=False` skips the final full-pytree select that rolls
    DECIDE-mode lanes back — legal ONLY when the caller already
    guarantees every lane that reaches this step is non-DECIDE, which
    is exactly `drain_to_decision`'s while body: the vmapped
    while-loop's batching rule selects the whole carry against each
    lane's own cond, so the per-iteration ~50-leaf select here (adj is
    [J,S,S] per lane) was pure duplicated bandwidth on the drain's hot
    path (ISSUE 7 drain restructure)."""
    track = telemetry is not None
    active = ls.mode != M_DECIDE
    _, k_reset = jax.random.split(rng)
    ls0 = ls
    if event_bulk:
        env_b, nb, nb_rel, nb_rdy = _bulk_cycle_chain(
            params, bank, ls.env, ls.mode == M_EVENT, bulk_events,
            bulk_cycles, bulk_fused,
        )
        ls = ls.replace(env=env_b, bulked=ls.bulked + nb)
    else:
        nb = _i32(0)
        nb_rel = nb_rdy = nb

    def noop(ls: LoopState):
        return ls, _i32(RQ_NONE), _i32(-1), _i32(-1), _i32(0), \
            ls.env.source_job_id(), jnp.bool_(False), _i32(0)

    ls2, rk, rj, rs, e, quirk, popped, ev_kind = lax.switch(
        ls.mode,
        [noop, _fulfill_branch, lambda l: _event_branch(params, l, nb)],
        ls,
    )
    out = _finish_micro_step(
        params, bank, ls0, ls2, rk, rj, rs, e, quirk, k_reset,
        auto_reset, record=True, reset_fn=reset_fn, t_ref=t_ref,
        telem=telemetry,
    )
    if track:
        out_ls, (rw, dt, rs_), telemetry = out
    else:
        out_ls, (rw, dt, rs_) = out
    was_done = _lane_done(ls0.env)
    gate = active & ~was_done
    if track:
        pop_live = popped & gate
        telemetry = _tm_add(
            telemetry,
            fulfill_steps=(ls0.mode == M_FULFILL) & ~was_done,
            event_steps=(ls0.mode == M_EVENT) & ~was_done,
            loop_iters=jnp.where(gate, nb + popped.astype(_i32), 0),
            bulk_relaunch_events=jnp.where(gate, nb_rel, 0),
            bulk_ready_events=jnp.where(gate, nb_rdy, 0),
            bulk_passes=(nb > 0) & gate,
            ev_job_arrival=pop_live & (ev_kind == EV_JOB_ARRIVAL),
            ev_task_finished=pop_live & (ev_kind == EV_TASK_FINISHED),
            ev_exec_ready=pop_live & (ev_kind == EV_EXECUTOR_READY),
        )
    if not masked:
        # drain-while body: the loop's own batched-cond carry select
        # already discards DECIDE lanes' outputs
        rec = (rw, dt, rs_)
        return (out_ls, rec, telemetry) if track else (out_ls, rec)
    final = jax.tree_util.tree_map(
        lambda a, b: jnp.where(active, a, b), out_ls, ls0
    )
    zero = jnp.float32(0.0)
    rec = (
        jnp.where(active, rw, zero),
        jnp.where(active, dt, zero),
        active & rs_,
    )
    return (final, rec, telemetry) if track else (final, rec)


def drain_to_decision(
    params: EnvParams,
    bank: WorkloadBank,
    ls: LoopState,
    rng: jax.Array,
    auto_reset: bool = True,
    event_bulk: bool = True,
    bulk_events: int = 8,
    bulk_cycles: int = 1,
    reset_fn: Callable | None = None,
    t_ref: jnp.ndarray | None = None,
    telemetry=None,
    bulk_fused: bool = True,
) -> tuple:
    """Drain one lane's non-decision work — FULFILL leftovers and the
    whole inter-decision event run — until it is ready to DECIDE again
    (or its episode is over / its event queue is drained), accumulating
    the span's reward/dt/reset with `t_ref` as the discount reference.

    The batch collectors vmap this; under vmap the while-loop costs the
    batch-max drain length per decision row — but every iteration is
    pure env machinery (bulk passes + single pops), so the straggler tax
    lands on the cheap slice while the GNN, the decision row's measured
    70-90% share, runs exactly once per decision outside this loop.
    The ISSUE-7 restructure keeps that slice cheap two ways: the cond
    reduces to the existence bit of the next event (`_has_pending_event`
    — no argmin/kind chain), and the body runs `drain_micro_step` with
    `masked=False`, relying on the batched while-loop's own per-lane
    carry select instead of re-selecting the ~50-leaf LoopState every
    iteration. The per-lane iteration count is measured directly
    (`drain_iters` — its max/mean over lanes IS the drain's batch-max
    while tax). Returns `(ls, (reward, dt, reset)[, telemetry])`."""
    track = telemetry is not None
    zero = jnp.float32(0.0)

    def cond(c):
        ls = c[0]
        has = _has_pending_event(ls.env)
        # a drained queue with the episode still open cannot progress
        # without a new decision round — hand such a lane back to the
        # caller instead of spinning forever
        stuck = (ls.mode == M_EVENT) & ~has & ~ls.env.round_ready
        return (ls.mode != M_DECIDE) & ~_lane_done(ls.env) & ~stuck

    def body(c):
        if track:
            ls, k, rw, dt, rs, tm = c
            tm = _tm_add(tm, drain_iters=1)
        else:
            (ls, k, rw, dt, rs), tm = c, None
        k, sub = jax.random.split(k)
        out = drain_micro_step(
            params, bank, ls, sub, auto_reset, event_bulk, bulk_events,
            bulk_cycles, reset_fn, t_ref, telemetry=tm,
            bulk_fused=bulk_fused, masked=False,
        )
        if track:
            ls, (r, d, re), tm = out
        else:
            ls, (r, d, re) = out
        c2 = (ls, k, rw + r, dt + d, rs | re)
        return c2 + (tm,) if track else c2

    c0 = (ls, rng, zero, zero, jnp.bool_(False))
    if track:
        c0 = c0 + (telemetry,)
    c = lax.while_loop(cond, body, c0)
    ls, rw, dt, rs = c[0], c[2], c[3], c[4]
    if track:
        return ls, (rw, dt, rs), c[5]
    return ls, (rw, dt, rs)


def apply_and_drain(
    params: EnvParams,
    bank: WorkloadBank,
    ls: LoopState,
    stage_idx: jnp.ndarray,
    num_exec: jnp.ndarray,
    rng: jax.Array,
    auto_reset: bool = False,
    event_bulk: bool = True,
    bulk_events: int = 8,
    fulfill_bulk: bool = True,
    bulk_cycles: int = 1,
    bulk_fused: bool = True,
    telemetry=None,
) -> tuple:
    """One PRECOMPUTED decision applied and drained to the next decision
    point, for ONE lane: `decide_micro_step` (commit or round-finish)
    followed by `drain_to_decision` (FULFILL leftovers + the whole
    inter-decision event run) — the serving-shaped unit of work the
    AOT decision service compiles (`sparksched_tpu/serve/`). It drives
    the same two primitives as the single-eval collectors' scan body
    (`trainers/rollout.py:_flat_collect_single_eval`), but is NOT that
    body: the collectors carry their discount reference across rows
    (an undecided lane keeps the previous decision's `t_ref`), while
    a served request always references the lane's wall time at entry —
    per-request accounting, there is no previous row to carry. The
    engine-level decision semantics shared with training are pinned by
    the decide/drain step-exactness tests, not by this wrapper.
    Returns `(ls, (decided, reward, dt, reset)[, telemetry])` —
    `reward`/`dt` accumulate over the decide step and the whole
    drain."""
    track = telemetry is not None
    k_dec, k_drain = jax.random.split(rng)
    t_ref = ls.env.wall_time
    out = decide_micro_step(
        params, bank, ls, stage_idx, num_exec, k_dec, auto_reset,
        fulfill_bulk, t_ref=t_ref, telemetry=telemetry,
    )
    if track:
        ls2, (decided, rw1, dt1, rs1), telemetry = out
    else:
        ls2, (decided, rw1, dt1, rs1) = out
    out = drain_to_decision(
        params, bank, ls2, k_drain, auto_reset, event_bulk,
        bulk_events, bulk_cycles, t_ref=t_ref, telemetry=telemetry,
        bulk_fused=bulk_fused,
    )
    if track:
        ls3, (rw2, dt2, rs2), telemetry = out
    else:
        ls3, (rw2, dt2, rs2) = out
    rec = (decided, rw1 + rw2, dt1 + dt2, rs1 | rs2)
    return (ls3, rec, telemetry) if track else (ls3, rec)


def run_flat(
    params: EnvParams,
    bank: WorkloadBank,
    policy_fn: Callable,
    rng: jax.Array,
    num_groups: int,
    state: EnvState | None = None,
    auto_reset: bool = True,
    compute_levels: bool = True,
    event_burst: int = 1,
    event_bulk: bool = True,
    bulk_events: int = 8,
    fulfill_bulk: bool = False,
    bulk_cycles: int = 1,
    loop_state: LoopState | None = None,
    telemetry=None,
    bulk_fused: bool = True,
) -> LoopState | tuple:
    """Scan `num_groups` micro-step groups for one lane (vmap over
    lanes). Each group is one full micro-step plus `event_burst - 1`
    event-only sub-steps (see `event_micro_step`), i.e.
    `num_groups * event_burst` micro-steps in total. Pass `loop_state`
    (instead of a freshly-reset `state`) to continue a previous run —
    bench chunks resume this way. With `telemetry` (an
    `obs.Telemetry`), the counters ride the scan carry and the call
    returns `(LoopState, Telemetry)`."""
    ls = init_loop_state(state) if loop_state is None else loop_state
    track = telemetry is not None

    def body(carry, _):
        if track:
            ls, k, tm = carry
        else:
            (ls, k), tm = carry, None
        k, sub = jax.random.split(k)
        out = micro_step(
            params, bank, policy_fn, ls, sub, auto_reset,
            compute_levels, event_bulk, bulk_events, fulfill_bulk,
            bulk_cycles, telemetry=tm, bulk_fused=bulk_fused,
        )
        ls, tm = out if track else (out, None)
        for _ in range(event_burst - 1):
            k, sub = jax.random.split(k)
            out = event_micro_step(
                params, bank, ls, sub, auto_reset, event_bulk,
                bulk_events, bulk_cycles, telemetry=tm,
                bulk_fused=bulk_fused,
            )
            ls, tm = out if track else (out, None)
        return ((ls, k, tm) if track else (ls, k)), None

    if track:
        (ls, _, telemetry), _ = lax.scan(
            body, (ls, rng, telemetry), None, length=num_groups
        )
        return ls, telemetry
    (ls, _), _ = lax.scan(body, (ls, rng), None, length=num_groups)
    return ls
