"""Static configuration for the vectorized simulator.

The reference drives everything from a YAML file with three sections
(`trainer`/`agent`/`env`; reference: config/decima_tpch.yaml, cfg_loader.py).
We keep that YAML shape for drop-in familiarity, but the environment's shape
caps must be static so XLA sees fixed shapes: `EnvParams` is a frozen,
hashable dataclass that is passed as a `static_argnum` to jitted functions.
"""

from __future__ import annotations

import dataclasses
from argparse import ArgumentDefaultsHelpFormatter, ArgumentParser
from typing import Any

import yaml


@dataclasses.dataclass(frozen=True)
class EnvParams:
    """Static environment parameters (all shape-determining fields).

    Mirrors the reference env config (spark_sched_sim/spark_sched_sim.py:34-57)
    plus the padding caps the reference does not need because it uses dynamic
    Python object graphs.
    """

    # number of simulated executors (reference spark_sched_sim.py:37)
    num_executors: int = 10

    # hard cap on job arrivals == padded job axis. The reference allows a
    # time-limit-only episode (spark_sched_sim.py:48); with fixed shapes a
    # cap is always required.
    max_jobs: int = 50

    # padded per-job stage axis (TPC-H DAGs have <= ~20 stages)
    max_stages: int = 20

    # cap on DAG depth for the level-wise GNN scan; topological depth of a
    # DAG with max_stages nodes is at most max_stages.
    max_levels: int = 20

    # time in ms for an executor to move between jobs (reference :40)
    moving_delay: float = 2000.0

    # warmup delay in ms added to some first-wave task durations
    # (reference data_samplers/tpch.py:38-43)
    warmup_delay: float = 1000.0

    # continuous discount factor for rewards (reference :42-44)
    beta: float = 0.0

    # Poisson job arrival rate (1/ms); inverse is mean inter-arrival time
    # (reference data_samplers/tpch.py:29-32)
    job_arrival_rate: float = 4.0e-5

    # mean of the exponential per-episode time limit (ms). None => no time
    # limit (episode ends when all `max_jobs` jobs complete).
    # (reference wrappers/stochastic_time_limit.py)
    mean_time_limit: float | None = None

    # track per-executor release history on-device for Gantt rendering
    # (reference components/executor.py:20-26). 0 disables.
    history_cap: int = 0

    # dtype of the observation feature bank (`Observation.nodes` and
    # the recorded per-decision `StoredObs.duration` buffers):
    # "float32" (default) or "bfloat16" (ISSUE 7 low-precision
    # observation layout — halves the lane-scaled rollout-obs bytes;
    # consumers accumulate in f32, drift pinned by the observe-path
    # epsilon test). Env dynamics and rewards are f32 either way.
    # Aliases f32/bf16 normalize; anything else raises — the layout
    # checks compare the exact canonical string, and a misspelled
    # value silently running f32 would stamp mislabeled bench rows.
    obs_dtype: str = "float32"

    def __post_init__(self) -> None:
        canon = {
            "float32": "float32", "f32": "float32",
            "bfloat16": "bfloat16", "bf16": "bfloat16",
        }.get(self.obs_dtype)
        if canon is None:
            raise ValueError(
                f"obs_dtype {self.obs_dtype!r} is not one of "
                "float32/f32/bfloat16/bf16"
            )
        object.__setattr__(self, "obs_dtype", canon)

    @property
    def num_nodes(self) -> int:
        return self.max_jobs * self.max_stages

    def replace(self, **kw: Any) -> "EnvParams":
        return dataclasses.replace(self, **kw)


def env_params_from_cfg(env_cfg: dict[str, Any]) -> EnvParams:
    """Build EnvParams from a reference-style `env:` config section.

    Field values are coerced to the declared int/float types: PyYAML 1.1
    parses exponent literals without a sign (``2.0e7``) as *strings*, and
    a string smuggled into a jitted computation fails deep inside XLA."""
    types = {f.name: f.type for f in dataclasses.fields(EnvParams)}
    kw: dict[str, Any] = {}
    for k, v in env_cfg.items():
        if k not in types:
            continue
        if v is not None and types[k] != "str":
            v = int(float(v)) if types[k] == "int" else float(v)
        kw[k] = v
    if "max_jobs" not in kw and "job_arrival_cap" in env_cfg:
        kw["max_jobs"] = int(env_cfg["job_arrival_cap"])
    if "mean_time_limit" in env_cfg and "job_arrival_cap" not in env_cfg:
        # time-limit-only episodes still need a padding cap
        kw.setdefault("max_jobs", 200)
    return EnvParams(**kw)


# ---------------------------------------------------------------------------
# runtime robustness blocks (ISSUE 9): the known key sets of the
# top-level `health:` and `chaos:` YAML sections. Declarative data here
# — the single source of truth for the YAML surface — consumed by the
# trainer (health recovery policy) and sparksched_tpu/chaos.py (fault
# injection), both of which fail loudly on an unknown key: a typo'd
# sentinel knob silently disabling recovery is exactly the class of
# quiet failure the health subsystem exists to remove.
# ---------------------------------------------------------------------------

HEALTH_KEYS = frozenset({
    "enabled",  # default True when the block is present
    "max_retries",  # rollback+retry budget per iteration (default 2)
    "backoff_seconds",  # exponential-backoff base (default 1.0)
    "checkpoint_every",  # atomic train-state write cadence (0 = end only)
    "keep",  # checkpoint generations kept for corrupt-file fallback
    "straggler_ratio_max",  # quarantine threshold (no retry)
})

SERVE_KEYS = frozenset({
    # ISSUE 10: the top-level `serve:` block — the AOT decision
    # service's surface (sparksched_tpu/serve/session.py:
    # store_from_config), validated with the same fail-loud contract
    "capacity",  # sessions the store admits (one live cluster per tenant)
    "max_batch",  # micro-batch width K (the batched AOT program's shape)
    "linger_ms",  # bounded linger window (the `front: linger` A/B partner)
    "deterministic",  # greedy serving (default True)
    "donate",  # donate the store buffer to the serve programs
    "seed",  # base key for session resets / sampling
    # ISSUE 11 instrumentation (default off, zero-cost off):
    "trace",  # per-request span stamps + runlog `trace` records
    "metrics",  # attach an obs.metrics.MetricsRegistry to the store
    # ISSUE 13: continuous batching + the sharded, host-paged store
    "front",  # batching front: continuous (default) | linger
    "hot_capacity",  # device slots; < capacity pages idle sessions to host
    "shard_dp",  # shard the device store over a dp mesh (N | "auto")
    # ISSUE 14: the online learning loop's serve-side knobs
    "record",  # compile the record-on programs (per-decision StoredObs)
    "pager_aware",  # continuous front: prefer hot sessions in batches
    # ISSUE 18: the device-resident trajectory ring (record-on only)
    "ring",  # ring depth R (records); 0 = per-decision record path
    "ring_drain",  # drain cadence in decisions (default: ring // 2)
    # ISSUE 15: pipelined serve execution
    "groups",  # independently-donated slot groups (in-flight width)
    "depth",  # `front: pipelined` in-flight window depth (default: groups)
    "harvester",  # background harvester thread for output materialization
    "prefetch",  # pipelined front: page predicted-next sessions ahead
    # ISSUE 16: the network serving tier (serve/server.py HTTP front +
    # serve/router.py replica fleet) — consumed by `server_from_config`,
    # ignored by `store_from_config` exactly like the `front:` knobs.
    # All default OFF: no `replicas`/`port` keys => the in-process
    # store, byte-identical to the r15 path (zero-cost-off).
    "host",  # HTTP front bind address (default 127.0.0.1)
    "port",  # HTTP front port (0 = OS-assigned ephemeral, reported back)
    "replicas",  # serve-fleet width (0/absent = in-process, no fleet)
    "quota_sessions",  # per-tenant live-session quota (0 = unlimited)
    "quota_inflight",  # per-tenant outstanding-decide quota (0 = unlimited)
    # ISSUE 17: the fleet observability plane (obs/fleet.py collector +
    # obs/slo.py burn-rate monitor) — consumed by `server_from_config`,
    # stripped before the store like the other network-layer keys.
    # Default OFF: no `collect` key => no collector, no scrape loop,
    # `/fleet` 404s (zero-cost-off).
    "collect",  # attach the fleet collector (scrapes ride the pump)
    "collect_period_s",  # scrape period (default 1.0 s)
    "slo",  # nested declarative SLO block (obs.slo.SLO_CONFIG_KEYS:
    #   p99_ms, goodput_floor_rps, quarantine_rate_max, max_staleness,
    #   windows, rollback_on, cooldown_s, min_events)
    # ISSUE 20: the tail-latency attribution plane — a front-level
    # knob like `front:`/`linger_ms` (consumed by `front_from_config`,
    # ignored by `store_from_config`). Defaults to the `trace` value:
    # traced serving gets attribution unless explicitly disabled.
    "attribution",  # critical-path analyzer + tail exemplars on the front
    "hostprof",  # role-attributed sampling profiler over the serve threads
})

ONLINE_KEYS = frozenset({
    # ISSUE 14: the top-level `online:` block — the serve->learn->serve
    # loop's surface (sparksched_tpu/online/: TrajectoryBuffer +
    # OnlineLearner + ParamBus, built by `online.online_from_config`),
    # validated with the same fail-loud contract as health:/serve:
    "enabled",  # default True when the block is present
    "max_trajectories",  # completed-trajectory buffer bound (FIFO evict)
    "max_steps",  # decisions per trajectory segment (the padded T)
    "batch_trajectories",  # trajectories per ppo_update (the padded B)
    "max_param_lag",  # off-policy guard: skip trajectories whose
    #   params-version lag exceeds this (PPO's ratio clip covers the rest)
    "min_decisions",  # drop segments shorter than this many decisions
    "swap_every",  # publish params every N accepted learner updates
    "probation_decisions",  # post-swap decisions watched before a swap
    #   is marked good (the rollback window)
    "max_quarantine_rate",  # rollback when the post-swap quarantine
    #   rate over the probation window exceeds this
    "learner",  # nested PPO-hyperparameter overrides for the learner's
    #   trainer (lr, num_epochs, num_batches, entropy_coeff, ...)
    "seed",
})

OBS_KEYS = frozenset({
    # the top-level `obs:` block (ISSUE 2; consumed by the trainer) —
    # validated since ISSUE 11 with the same fail-loud contract as
    # health:/chaos:/serve: (a typo'd observability knob silently
    # running blind is the quiet failure this subsystem removes)
    "runlog",  # true|false|path — the JSONL event-stream sink
    "telemetry",  # thread on-device engine counters per iteration
    "memory",  # per-iteration device-allocator sample (default True)
    "trace_iteration",  # capture a labeled device trace of iteration N
    "trace_dir",  # where that trace lands
    "runlog_max_bytes",  # size-cap + numbered-suffix runlog rotation
    "slo",  # declarative SLO block for non-serving loops (same nested
    #   surface as serve: slo — obs.slo.SLO_CONFIG_KEYS)
})

CHAOS_KEYS = frozenset({
    "seed",  # injection-index derivation seed
    "nan_grad",  # iterations: poison one recorded reward with NaN
    "bank_row",  # iterations: poison one recorded obs duration row
    "straggler",  # iterations: inflate one lane's loop_iters counter
    "oom",  # iterations: raise a simulated RESOURCE_EXHAUSTED
    "sigkill",  # iterations: SIGKILL the process mid-iteration
    "straggler_factor",  # loop_iters inflation factor (default 100)
})


def honor_jax_platforms_env() -> None:
    """Re-assert the user's ``JAX_PLATFORMS`` choice via jax.config.

    Normally a no-op (jax reads the env var itself), but platform
    plugins preloaded at interpreter startup can override the selection
    before user code runs; calling this from a CLI entry point before
    any computation restores the standard env-var semantics (e.g.
    ``JAX_PLATFORMS=cpu python train.py ...``)."""
    import os

    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        import jax

        cur = jax.config.jax_platforms or ""
        # leave richer selections alone when they already honor the env
        # choice as primary (e.g. env "axon" vs plugin's "axon,cpu")
        if not cur.startswith(plat):
            jax.config.update("jax_platforms", plat)


def use_fast_prng() -> None:
    """Switch jax's default PRNG to the TPU-friendly ``rbg`` impl.

    The default threefry generator unrolls to ~60 scalar-heavy HLO ops
    per draw; the simulator's hot loop draws several keys per
    micro-step (reset keys, task-duration samples), so on an op-count
    bound engine the RNG alone is a measurable slice of every step
    (jaxpr census: sample_task_duration is ~200 eqns, ~180 of them
    threefry). ``rbg`` lowers to a single XLA RngBitGenerator op.

    Trade-off: rbg's split/fold_in are statistically weaker than
    threefry's, which is irrelevant for workload sampling. Keys from
    the two impls are incompatible (uint32[4] vs uint32[2]), so a
    checkpointed rng resumes only under the impl that wrote it. Tests
    keep the default threefry."""
    import jax

    jax.config.update("jax_default_prng_impl", "rbg")


def enable_compilation_cache(path: str | None = None) -> None:
    """Persist XLA compilations across processes.

    Chip compiles through the tunnel take 20-40s+ per program and were
    the direct cause of timed-out (then killed, then tunnel-wedging)
    benchmark runs; with the cache, repeat invocations of bench/train
    scripts skip straight to execution. Default cache location: a
    `.jax_cache` directory next to this package (override with `path`
    or the JAX_COMPILATION_CACHE_DIR env var jax honors natively)."""
    import os.path as osp

    import jax

    if path is None:
        path = osp.join(
            osp.dirname(osp.dirname(osp.abspath(__file__))),
            ".jax_cache",
        )
    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)


def load(filename: str | None = None) -> dict[str, Any]:
    """Load a YAML experiment config (reference cfg_loader.py:5-13)."""
    if not filename:
        args = make_parser().parse_args()
        filename = args.filename
    with open(filename, "r") as stream:
        return yaml.safe_load(stream)


def make_parser() -> ArgumentParser:
    parser = ArgumentParser(
        description="sparksched_tpu experiment runner",
        formatter_class=ArgumentDefaultsHelpFormatter,
    )
    parser.add_argument(
        "-f",
        "--file",
        dest="filename",
        help="experiment definition file",
        metavar="FILE",
        required=True,
    )
    return parser
