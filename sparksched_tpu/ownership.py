"""Runtime thread-ownership assertions (ISSUE 19).

The static half lives in `analysis/concurrency.py`: an AST pass that
maps every mutable attribute of the serve/online host classes to an
owning thread role (or a guarding lock) and fails CI when code
reachable from a non-owner role writes one. This module is the dynamic
half: `assert_owner(obj, role)` calls at the hot entry points verify,
under real threads, that each single-owner structure really is driven
by one thread. The two halves are cross-validated —
`analysis.concurrency.runtime_assert_expectations()` is compared
against the `assert_owner` call sites found in the package source
(tests/test_static_analysis.py), so the model and the code cannot
drift apart.

Semantics mirror the static pass's `main` exemption: the main thread
is ownership-polymorphic (it constructs everything and drives the
whole stack in single-threaded benches), so `assert_owner` no-ops on
`MainThread`. For any other thread:

- if the thread's NAME is a known role (the spawn sites name their
  threads `serve-pump`, `serve-harvester`, `online-learner`,
  `fleet-collector`, `serve-client-<i>`), the asserted role must
  match — an `online-learner` thread calling a `serve-pump` entry
  point is flagged immediately, no second thread needed;
- independently, the first non-main thread through an entry point
  binds `(object, role)`; a DIFFERENT live non-main thread hitting
  the same entry point later is a violation.

Cost: the env-var gate is read once at import; with
`SPARKSCHED_DEBUG_OWNERSHIP` unset every call is one module-global
load + compare + return (measured ~53ns — see PERF.md round 21,
<0.01% of a serve decide). No locks are taken on the fast path.
"""

from __future__ import annotations

import os
import threading
from typing import Any

ENV_FLAG = "SPARKSCHED_DEBUG_OWNERSHIP"

_enabled: bool = os.environ.get(ENV_FLAG, "") == "1"

# Role vocabulary — must match analysis.concurrency.KNOWN_ROLES.
# `serve-client` matches by prefix (workers are `serve-client-<i>`).
ROLE_NAMES = (
    "serve-pump",
    "serve-http",
    "serve-harvester",
    "serve-client",
    "online-learner",
    "fleet-collector",
    "host-profiler",
)

_guard = threading.Lock()
# (id(obj), role) -> (thread_object, thread_name, class_name). The
# Thread OBJECT, not its ident: the OS reuses idents, so a fresh
# thread can inherit a dead owner's ident and silently impersonate it.
_bindings: dict[tuple[int, str], tuple[threading.Thread, str, str]] = {}
# every violation ever recorded (also raised); tests assert this
# stays empty across a clean threaded run
violations: list[dict[str, Any]] = []


class OwnershipViolation(AssertionError):
    """A single-owner structure was driven by the wrong thread."""


def debug_enabled() -> bool:
    return _enabled


def set_debug(on: bool) -> None:
    """Flip the runtime checks (tests; production uses the env var)."""
    global _enabled
    _enabled = bool(on)


def reset() -> None:
    """Drop all bindings and recorded violations (test isolation)."""
    with _guard:
        _bindings.clear()
        violations.clear()


def _role_of_thread(name: str) -> str | None:
    for r in ROLE_NAMES:
        if name == r or name.startswith(r + "-"):
            return r
    return None


def _violate(obj: Any, roles: tuple[str, ...], t: threading.Thread,
             why: str, bound_to: str | None = None) -> None:
    rec = {
        "class": type(obj).__name__,
        "roles": roles,
        "thread": t.name,
        "why": why,
        "bound_to": bound_to,
    }
    with _guard:
        violations.append(rec)
    raise OwnershipViolation(
        f"{type(obj).__name__} entry point owned by role(s) "
        f"{'/'.join(roles)} driven from thread {t.name!r}: {why}"
    )


def assert_owner(obj: Any, *roles: str) -> None:
    """Assert the calling thread owns `obj` in one of `roles`.

    No-op unless SPARKSCHED_DEBUG_OWNERSHIP=1 (or `set_debug(True)`).
    The main thread always passes (see module docstring). Bindings
    are per (object, primary role); a binding whose thread has since
    exited is released, so sequential handoff (stop one driver, start
    another) never trips.
    """
    if not _enabled:
        return
    t = threading.current_thread()
    if t.name == "MainThread":
        return
    named = _role_of_thread(t.name)
    if named is not None and named not in roles:
        _violate(obj, roles, t,
                 f"thread is the {named!r} role, not an owner")
    key = (id(obj), roles[0])
    bound = _bindings.get(key)
    if bound is None:
        with _guard:
            bound = _bindings.setdefault(
                key, (t, t.name, type(obj).__name__)
            )
    if bound[0] is t:
        return
    # a dead previous owner releases the binding (sequential handoff)
    if bound[0].is_alive():
        _violate(obj, roles, t,
                 "second live thread entered a single-owner "
                 "entry point", bound_to=bound[1])
    with _guard:
        _bindings[key] = (t, t.name, type(obj).__name__)


def owner_snapshot() -> dict[tuple[str, str], set[str]]:
    """(class_name, role) -> set of thread names observed owning it."""
    out: dict[tuple[str, str], set[str]] = {}
    with _guard:
        for (_oid, role), (_thread, name, cls) in _bindings.items():
            out.setdefault((cls, role), set()).add(name)
    return out
