"""Training orchestration (reference trainers/trainer.py:25-329).

The reference Trainer spawns `num_sequences x num_rollouts` worker
processes, scatters `state_dict`s over pipes, gathers pickled rollout
buffers, and trains on them with torch. Here the whole iteration —
vmapped env resets, scanned policy-in-the-loop rollouts, returns,
baselines and the policy update — is jitted XLA code; the host loop only
carries seeds, logging, best-model tracking and checkpoints.

Config surface mirrors the reference YAML (config/decima_tpch.yaml):
`trainer:` (num_iterations, num_sequences, num_rollouts, seed,
artifacts_dir, checkpointing_freq, use_tensorboard, beta_discount |
reward_buff_cap, rollout_duration -> async mode, opt_kwargs,
max_grad_norm, + PPO keys), `agent:`, `env:`. One new required cap:
`rollout_steps` — the static scan length (the reference's dynamic episode
lengths become masked fixed-shape rollouts).

Rollout-engine keys (all optional): `rollout_engine: core|flat` selects
the per-decision `core.step` scan or the flat micro-step engine
(env/flat_loop.py; see trainers/rollout.py:collect_flat_*), and
`flat_micro_per_decision` / `flat_event_burst` / `flat_event_bulk` /
`flat_bulk_events` / `flat_fulfill_bulk` / `flat_bulk_cycles` expose the
flat engine's calibration surface (bench.py documents the per-backend
winners).

Multi-chip: a top-level `parallel:` YAML block (`dp: auto|N`) builds a
1-D dp mesh (parallel.py) and runs the whole iteration SPMD — rollout
lanes sharded over the mesh, parameters replicated, the update's
gradient/advantage reductions lowered to one all-reduce family per step
(the minibatch permutation is shard-aligned by construction, see
trainers/ppo.py). `num_sequences * num_rollouts` must divide evenly
over the mesh.
"""

from __future__ import annotations

import abc
import hashlib
import json
import os
import os.path as osp
import pathlib
import shutil
import time
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import serialization, struct

from .. import metrics
from ..config import (
    HEALTH_KEYS,
    OBS_KEYS,
    EnvParams,
    env_params_from_cfg,
)
from ..env import core
from ..env.health import (
    H_OOM,
    H_STRAGGLER,
    RETRYABLE_MASK,
    describe_mask,
)
from ..obs import RunLog, emit
from ..obs.memory import device_memory_stats
from ..obs.telemetry import summarize, telemetry_zeros_like
from ..schedulers import TrainableScheduler, make_scheduler
from ..workload import make_workload_bank
from .baselines import group_baselines
from .profiler import Profiler
from .returns import (
    AvgNumJobsBuffer,
    differential_returns,
    discounted_returns,
    step_dts,
)
from ..env.flat_loop import init_loop_state
from .rollout import (
    Rollout,
    collect_async,
    collect_flat_async,
    collect_flat_async_batch,
    collect_flat_sync,
    collect_flat_sync_batch,
    collect_sync,
    flat_micro_group_budget,
)

CfgType = dict[str, Any]


class TrainState(struct.PyTreeNode):
    params: Any
    opt_state: Any
    rng: jax.Array
    buf: AvgNumJobsBuffer | None  # differential-returns window, or None
    iteration: jnp.ndarray  # i32 []


def make_optimizer(train_cfg: CfgType) -> optax.GradientTransformation:
    """Adam + global-norm clipping (reference scheduler.py:37-54,
    decima_tpch.yaml:60-63). Optional `lr_anneal: {final, steps}`
    geometrically decays the learning rate over optimizer steps — a
    training-stability lever beyond the reference's fixed lr."""
    opt_cls = train_cfg.get("opt_cls", "Adam").lower()
    kwargs = dict(train_cfg.get("opt_kwargs") or {})
    lr = float(kwargs.pop("lr", 3e-4))
    anneal = train_cfg.get("lr_anneal")
    if anneal:
        final = float(anneal["final"])
        steps = int(anneal["steps"])
        lr = optax.exponential_decay(
            init_value=lr, transition_steps=steps,
            decay_rate=final / lr, end_value=final,
        )
    makers = {
        "adam": optax.adam,
        "adamw": optax.adamw,
        "sgd": optax.sgd,
        "rmsprop": optax.rmsprop,
    }
    if opt_cls not in makers:
        raise ValueError(f"unsupported optimizer {opt_cls!r}")
    tx = makers[opt_cls](lr, **kwargs)
    max_grad_norm = train_cfg.get("max_grad_norm")
    if max_grad_norm:
        tx = optax.chain(optax.clip_by_global_norm(max_grad_norm), tx)
    return tx


class Trainer(abc.ABC):
    """Base trainer; subclasses implement the jitted `_update`."""

    def __init__(self, agent_cfg: CfgType, env_cfg: CfgType,
                 train_cfg: CfgType, mesh=None,
                 obs_cfg: CfgType | None = None,
                 health_cfg: CfgType | None = None,
                 chaos_cfg: CfgType | None = None) -> None:
        # TPU-friendly rbg PRNG for the whole training program (the env
        # hot loop draws several keys per micro-step; see
        # config.use_fast_prng). Must run before any key is created.
        # An rng checkpointed under one impl resumes only under the
        # same impl (uint32[4] vs uint32[2] keys).
        if train_cfg.get("fast_prng", False):
            from ..config import use_fast_prng

            use_fast_prng()
        self.seed: int = train_cfg.get("seed", 42)
        self.num_iterations: int = train_cfg["num_iterations"]
        self.num_sequences: int = train_cfg["num_sequences"]
        self.num_rollouts: int = int(train_cfg["num_rollouts"])
        self.num_envs = self.num_sequences * self.num_rollouts

        self.artifacts_dir: str = train_cfg.get("artifacts_dir", "artifacts")
        self.use_tensorboard: bool = train_cfg.get("use_tensorboard", False)
        self.checkpointing_freq: int = train_cfg.get(
            "checkpointing_freq", 50
        )
        rd = train_cfg.get("rollout_duration")
        # YAML exponent literals without a sign ("2.0e7") arrive as strings
        self.rollout_duration = float(rd) if rd is not None else None

        # training-stability levers beyond the reference's fixed
        # hyperparameters (its README credits tuning for stability;
        # these make the schedule explicit and checkpoint-resumable):
        # entropy_anneal: {final, iterations} — geometric decay of the
        # entropy bonus from `entropy_coeff` to `final`;
        # fixed_sequences: true — train every iteration on the same
        # `num_sequences` job sequences instead of resampling (lower
        # gradient variance early in training).
        self.entropy_anneal = train_cfg.get("entropy_anneal")
        if self.entropy_anneal and "final" not in self.entropy_anneal:
            raise ValueError("entropy_anneal requires a 'final' value")
        if self.entropy_anneal and "iterations" not in self.entropy_anneal:
            # `num_iterations` counts iterations *per session* while
            # state.iteration is absolute across resumed sessions, so an
            # implicit horizon would silently pin the coefficient at
            # `final` for every session after the first
            raise ValueError(
                "entropy_anneal requires an explicit 'iterations' horizon "
                "(absolute iteration count, spanning resumed sessions)"
            )
        self.fixed_sequences = bool(train_cfg.get("fixed_sequences", False))
        if self.fixed_sequences and self.rollout_duration:
            # async lanes draw each mid-scan episode from
            # fold_in(seq_base, reset_count); only the initial reset
            # would be pinned, so the flag's guarantee cannot hold
            raise ValueError(
                "fixed_sequences is only supported in sync mode "
                "(remove rollout_duration)"
            )

        # per-iteration wall-time reporting + optional device trace of the
        # first iteration (the reference wraps every rollout in cProfile,
        # rollout_worker.py:103; host profiles are meaningless for jitted
        # programs, so this uses the jax.profiler-backed Profiler)
        self.profiling: bool = bool(train_cfg.get("profiling", False))
        self.profile_trace_dir = train_cfg.get("profile_trace_dir")

        # observability block (top-level `obs:` YAML section):
        #   runlog: true|false|path — JSONL event stream (spans, stats,
        #     telemetry summaries, JIT recompiles) under artifacts/
        #     (the default sink; TensorBoard stays a mirror)
        #   telemetry: true — thread engine counters through the rollout
        #     collectors and summarize once per iteration
        #   memory: true (default) — sample the device allocator
        #     (`obs.memory.device_memory_stats`) once per iteration and
        #     emit a `memory` runlog record + mem_* scalars; a no-op on
        #     backends without allocator stats (CPU), so the default
        #     costs nothing off-chip
        #   trace_iteration: N — capture a labeled jax.profiler device
        #     trace of (absolute) iteration N's collect+update
        #   trace_dir: where that trace lands (default
        #     artifacts/trace)
        #   runlog_max_bytes: N — size-cap + numbered-suffix rotation
        #     of the runlog file (ISSUE 11; 0/absent = unbounded)
        oc = dict(obs_cfg or {})
        if set(oc) - OBS_KEYS:
            raise ValueError(
                "unknown obs: config key(s) "
                f"{sorted(set(oc) - OBS_KEYS)} — known keys: "
                f"{sorted(OBS_KEYS)}"
            )
        self.obs_runlog = oc.get("runlog", True)
        rmb = oc.get("runlog_max_bytes")
        self.obs_runlog_max_bytes = int(rmb) if rmb else None
        self.obs_telemetry: bool = bool(oc.get("telemetry", False))
        self.obs_memory: bool = bool(oc.get("memory", True))
        ti = oc.get("trace_iteration")
        self.obs_trace_iteration = None if ti is None else int(ti)
        self.obs_trace_dir: str = oc.get(
            "trace_dir", osp.join(self.artifacts_dir, "trace")
        )
        self._runlog: RunLog | None = None

        # self-healing block (top-level `health:` YAML section, ISSUE 9):
        #   enabled: true (default when the block is present) — thread
        #     the in-JIT health sentinels through the rollout collectors
        #     and the PPO update, and turn on automatic recovery (skip
        #     the poisoned update in-JIT; on a tripped sentinel roll
        #     back to the last-good state, reseed the iteration rng, and
        #     retry with exponential backoff)
        #   max_retries: 2 — rollback+retry budget per iteration; an
        #     iteration still unhealthy past it raises (poisoned params
        #     must never train on)
        #   backoff_seconds: 1.0 — base of the exponential backoff
        #   checkpoint_every: N — atomically save the full train state
        #     every N iterations (0 = session end only), the preemption
        #     half: a SIGKILLed window resumes from the last write
        #   keep: 2 — checkpoint generations retained for the
        #     corrupt-file fallback in `load_train_state`
        #   straggler_ratio_max: float — quarantine (runlog `health`
        #     record, no retry) iterations whose measured while-loop
        #     straggler ratio exceeds this
        # Enabling health forces telemetry threading (the mask rides the
        # Telemetry carry) and disables the async-carry donation so a
        # rolled-back iteration can re-collect from the pre-iteration
        # lanes (one extra resident LoopState copy — the price of
        # rollback).
        hc = dict(health_cfg or {})
        if set(hc) - HEALTH_KEYS:
            raise ValueError(
                "unknown health: config key(s) "
                f"{sorted(set(hc) - HEALTH_KEYS)} — known keys: "
                f"{sorted(HEALTH_KEYS)}"
            )
        self.health_enabled: bool = bool(
            hc.get("enabled", health_cfg is not None)
        )
        self.health_max_retries: int = int(hc.get("max_retries", 2))
        self.health_backoff: float = float(hc.get("backoff_seconds", 1.0))
        self.health_checkpoint_every: int = int(
            hc.get("checkpoint_every", 0)
        )
        self.checkpoint_keep: int = int(hc.get("keep", 2))
        srm = hc.get("straggler_ratio_max")
        self.health_straggler_max = None if srm is None else float(srm)
        if self.health_enabled:
            self.obs_telemetry = True

        # deterministic fault injection (top-level `chaos:` YAML block;
        # sparksched_tpu/chaos.py) — drills the recovery paths above
        self._chaos = None
        if chaos_cfg:
            from ..chaos import ChaosMonkey

            self._chaos = ChaosMonkey(chaos_cfg)
            if self._chaos.any_scheduled() and not self.health_enabled:
                emit(
                    "[chaos] warning: chaos: faults scheduled without a "
                    "health: block — injections will NOT be detected or "
                    "recovered (this is only useful for negative tests)"
                )

        # exactly one returns mode (reference trainer.py:63-74)
        assert ("reward_buff_cap" in train_cfg) ^ (
            "beta_discount" in train_cfg
        ), "provide exactly one of reward_buff_cap / beta_discount"
        self.beta: float = float(train_cfg.get("beta_discount", 0.0))
        self.reward_buff_cap: int = int(
            train_cfg.get("reward_buff_cap", 0)
        )
        if self.beta:
            env_cfg = env_cfg | {"beta": self.beta}

        self.params_env: EnvParams = env_params_from_cfg(env_cfg)
        self.bank = make_workload_bank(
            self.params_env.num_executors, self.params_env.max_stages,
            **{k: v for k, v in env_cfg.items()
               if k in ("data_dir", "bucket_size", "data_sampler_cls",
                        "bank_dtype")},
        )
        if self.bank.max_stages != self.params_env.max_stages:
            self.params_env = self.params_env.replace(
                max_stages=self.bank.max_stages,
                max_levels=max(self.params_env.max_levels,
                               self.bank.max_stages),
            )

        # static rollout scan length
        self.rollout_steps: int = train_cfg.get(
            "rollout_steps", 48 * self.params_env.max_jobs
        )

        # rollout engine: "core" drives the per-decision core.step scan
        # (a vmapped while_loop between decisions — pays the batch-max
        # straggler tax); "flat" drives the flat micro-step engine
        # (env/flat_loop.py) and scatters DECIDE micro-steps into the
        # same Rollout (trainers/rollout.py:collect_flat_*). Knobs
        # mirror bench.py's calibration surface.
        self.rollout_engine: str = str(
            train_cfg.get("rollout_engine", "core")
        )
        if self.rollout_engine not in ("core", "flat"):
            raise ValueError(
                f"rollout_engine must be 'core' or 'flat', got "
                f"{self.rollout_engine!r}"
            )
        # single-eval flat collection (round 8, default on): the scan is
        # decision-synchronous — ONE batched policy evaluation per
        # decision row (vs ~2 per decision measured on the per-lane
        # micro-step-group collectors), with the Decima job-compaction
        # cond at batch level. Requires a scheduler exposing
        # `flat_batch_policy`; set `flat_single_eval: false` to fall
        # back to the round-6 per-lane group collectors.
        self.flat_single_eval: bool = bool(
            train_cfg.get("flat_single_eval", True)
        )
        # micro-step-group budget per decision: the scan runs
        # rollout_steps * this many groups (PERF.md mode census: ~3
        # micro-steps per decision in steady state; 4 adds headroom)
        self.flat_micro_per_decision: float = float(
            train_cfg.get("flat_micro_per_decision", 4.0)
        )
        # the flat knob dicts are built AFTER the scheduler exists: the
        # single-eval capability check may downgrade flat_single_eval,
        # and fulfill_bulk's default follows the final mode

        # bound the Decima level scan by the bank's true max DAG depth
        # (bit-identical — deeper levels are no-op updates — and the
        # dominant GNN cost scales with it; the synthetic bank is 6 deep
        # vs a 20-stage cap). An explicit agent num_levels wins.
        bank_depth = int(
            np.max(
                np.where(
                    np.asarray(self.bank.node_level)
                    < self.bank.max_stages,
                    np.asarray(self.bank.node_level),
                    -1,
                )
            )
        ) + 1
        scheduler = make_scheduler(
            {"num_levels": bank_depth}
            | agent_cfg
            | {"num_executors": self.params_env.num_executors}
        )
        assert isinstance(scheduler, TrainableScheduler), (
            "scheduler must be trainable"
        )
        self.scheduler: TrainableScheduler = scheduler
        # single-eval collection calls scheduler.batch_policy (one
        # batched evaluation per decision row); schedulers without it
        # fall back to the per-lane group collectors
        self.flat_single_eval = self.flat_single_eval and hasattr(
            scheduler, "batch_policy"
        )
        self.flat_knobs = {
            "event_burst": int(train_cfg.get("flat_event_burst", 1)),
            "event_bulk": bool(train_cfg.get("flat_event_bulk", True)),
            "bulk_events": int(train_cfg.get("flat_bulk_events", 8)),
            # single-eval mode defaults fulfill bulking ON: leftovers
            # otherwise cost one drain iteration each, and the pass's
            # op count rides the already-GNN-dominated decision row.
            # The default follows the FINAL mode (post capability
            # check), so a per-lane fallback keeps its round-6 False.
            "fulfill_bulk": bool(
                train_cfg.get("flat_fulfill_bulk",
                              self.flat_single_eval)
            ),
            "bulk_cycles": int(train_cfg.get("flat_bulk_cycles", 1)),
            # ISSUE 7: single fused bulk kernel (mixed relaunch/arrival
            # runs in one pass) vs the round-3/4 pass pair; step-exact
            # either way, so this is purely a dispatch-count knob
            "bulk_fused": bool(train_cfg.get("flat_bulk_fused", True)),
        }
        # the batch (single-eval) collectors take no event_burst —
        # bursts amortized the policy eval the restructure removed
        self.flat_batch_knobs = {
            k: v for k, v in self.flat_knobs.items()
            if k != "event_burst"
        }
        self.flat_micro_groups: int = flat_micro_group_budget(
            self.rollout_steps, self.flat_micro_per_decision,
            self.flat_knobs["event_burst"],
        )
        self.tx = make_optimizer(train_cfg)
        self.train_cfg = train_cfg
        self._env_states = None  # async mode: persistent lanes

        # SPMD over a device mesh: rollout lanes sharded along the dp axis,
        # parameters replicated; the update's cross-lane reductions lower to
        # XLA collectives (see parallel.py). The persistent async carry
        # (env_states, arg 3) is donated on both paths: the host never
        # reads it between iterations, and donation lets XLA alias the
        # lane-sharded LoopState buffers across iterations instead of
        # holding two copies of the largest resident state per device.
        self.mesh = mesh
        self._lane_sharding = None
        # health rollback needs the pre-iteration async carry to stay
        # valid after a (possibly poisoned) collect, so donation is off
        # under the health block (see the health: comment above)
        donate = () if self.health_enabled else (3,)
        if mesh is not None:
            from ..parallel import lane_sharding

            lanes = lane_sharding(mesh)
            assert self.num_envs % mesh.size == 0, (
                f"num_sequences*num_rollouts={self.num_envs} must divide "
                f"evenly over {mesh.size} devices"
            )
            self._lane_sharding = lanes
            # every _collect output is lane-leading: the Rollout, the
            # async (LoopState, reset_counts) carry, and the per-lane
            # Telemetry — shard them all, or the carry round-trips
            # through a replicated layout every iteration
            self._collect_jit = jax.jit(
                self._collect, out_shardings=(lanes, lanes, lanes),
                donate_argnums=donate,
            )
            self._update_jit = jax.jit(
                self._update, in_shardings=(None, lanes),
                out_shardings=None,
            )
        else:
            self._collect_jit = jax.jit(
                self._collect, donate_argnums=donate
            )
            self._update_jit = jax.jit(self._update)

    # ------------------------------------------------------------------
    # device-side pieces
    # ------------------------------------------------------------------

    def init_state(self) -> TrainState:
        params = self.scheduler.params
        return TrainState(
            params=params,
            opt_state=self.tx.init(params),
            rng=jax.random.PRNGKey(self.seed),
            buf=(AvgNumJobsBuffer.create(self.reward_buff_cap)
                 if self.reward_buff_cap else None),
            iteration=jnp.zeros((), jnp.int32),
        )

    def _entropy_coeff_at(self, base: float, iteration: jnp.ndarray):
        """Entropy coefficient at `iteration` under the optional
        geometric anneal (jit-traceable)."""
        if not self.entropy_anneal or not base:
            return base
        final = float(self.entropy_anneal["final"])
        n = float(self.entropy_anneal["iterations"])
        frac = jnp.clip(iteration.astype(jnp.float32) / n, 0.0, 1.0)
        return base * (final / base) ** frac

    def _collect(self, model_params, iteration: jnp.ndarray,
                 rng: jax.Array, env_states) -> tuple[Rollout, Any, Any]:
        """One iteration's rollouts: [B]-vmapped scans. Seed layout mirrors
        the reference (trainer.py:268-271): lanes in the same sequence
        group share the job-sequence key, refreshed per reset. Returns
        `(rollout, env_states, telemetry)` — telemetry is a per-lane
        `obs.Telemetry` when `obs: telemetry` is on, else None."""
        p, bank = self.params_env, self.bank
        G, R = self.num_sequences, self.num_rollouts
        master = jax.random.PRNGKey(self.seed)
        telem0 = (
            telemetry_zeros_like((G * R,)) if self.obs_telemetry else None
        )
        if self.fixed_sequences:
            iteration = jnp.zeros_like(iteration)

        def seq_key(g, reset_count):
            return jax.random.fold_in(
                jax.random.fold_in(master, g), reset_count
            )

        g_ids = jnp.repeat(jnp.arange(G), R)
        r_ids = jnp.tile(jnp.arange(R), G)
        seq_rngs = jax.vmap(lambda g: seq_key(g, iteration))(g_ids)
        lane_rngs = jax.vmap(
            lambda s, r: jax.random.fold_in(s, 1000 + r)
        )(seq_rngs, r_ids)
        pol_rngs = jax.vmap(
            lambda r: jax.random.fold_in(jax.random.fold_in(rng, r), 7)
        )(jnp.arange(G * R))

        def policy_fn(k, obs):
            return self.scheduler.policy(k, obs, model_params)

        flat = self.rollout_engine == "flat"
        single = flat and self.flat_single_eval
        if single:
            def batch_policy_fn(k, obs):
                return self.scheduler.batch_policy(k, obs, model_params)
        if self.rollout_duration:  # async mode
            if env_states is None:
                states = jax.vmap(
                    lambda s, l: core.reset_pair(p, bank, s, l)
                )(seq_rngs, lane_rngs)
                if flat:
                    states = jax.vmap(init_loop_state)(states)
                # the initial reset consumed ordinal `iteration`; the
                # next (mid-scan) reset of any lane is ordinal + 1
                reset_counts = jnp.full(
                    (G * R,), iteration + 1, jnp.int32
                )
            else:
                states, reset_counts = env_states
            seq_bases = jax.vmap(
                lambda g: jax.random.fold_in(master, g)
            )(g_ids)
            lane_salts = (1000 + r_ids).astype(jnp.int32)
            # telem0 is None or a per-lane Telemetry; vmap treats None
            # as an empty pytree, so ONE vmapped call covers both modes
            # (the collector's return shape switches on the Python-level
            # None check at trace time)
            track = telem0 is not None
            if single:
                out = collect_flat_async_batch(
                    p, bank, batch_policy_fn,
                    jax.random.fold_in(rng, 7), self.rollout_steps,
                    states, self.rollout_duration, seq_bases,
                    lane_salts, reset_counts, telem0,
                    lane_shard=self._lane_sharding,
                    health=self.health_enabled,
                    **self.flat_batch_knobs,
                )
                ro, loop_states, telem = (
                    out if track else (out + (None,))
                )
                return ro, (loop_states, ro.final_reset_count), telem
            if flat:
                out = jax.vmap(
                    lambda k, s, sb, salt, rc, tm: collect_flat_async(
                        p, bank, policy_fn, k, self.rollout_steps, s,
                        self.rollout_duration, sb, salt, rc, tm,
                        micro_groups=self.flat_micro_groups,
                        health=self.health_enabled,
                        **self.flat_knobs,
                    )
                )(pol_rngs, states, seq_bases, lane_salts,
                  reset_counts, telem0)
                ro, loop_states, telem = (
                    out if track else (out + (None,))
                )
                return ro, (loop_states, ro.final_reset_count), telem
            out = jax.vmap(
                lambda k, s, sb, salt, rc, tm: collect_async(
                    p, bank, policy_fn, k, self.rollout_steps, s,
                    self.rollout_duration, sb, salt, rc, tm,
                    health=self.health_enabled,
                )
            )(pol_rngs, states, seq_bases, lane_salts, reset_counts,
              telem0)
            ro, telem = out if track else (out, None)
            return ro, (ro.final_state, ro.final_reset_count), telem
        else:  # sync: fresh episode per iteration
            states = jax.vmap(
                lambda s, l: core.reset_pair(p, bank, s, l)
            )(seq_rngs, lane_rngs)
            track = telem0 is not None
            if single:
                out = collect_flat_sync_batch(
                    p, bank, batch_policy_fn,
                    jax.random.fold_in(rng, 7), self.rollout_steps,
                    states, telem0,
                    lane_shard=self._lane_sharding,
                    health=self.health_enabled,
                    **self.flat_batch_knobs,
                )
            elif flat:
                out = jax.vmap(
                    lambda k, s, tm: collect_flat_sync(
                        p, bank, policy_fn, k, self.rollout_steps, s, tm,
                        micro_groups=self.flat_micro_groups,
                        health=self.health_enabled,
                        **self.flat_knobs,
                    )
                )(pol_rngs, states, telem0)
            else:
                out = jax.vmap(
                    lambda k, s, tm: collect_sync(
                        p, bank, policy_fn, k, self.rollout_steps, s, tm,
                        health=self.health_enabled,
                    )
                )(pol_rngs, states, telem0)
            ro, telem = out if track else (out, None)
            return ro, None, telem

    def _returns_and_baselines(self, state: TrainState, ro: Rollout):
        """Shared preprocessing (reference trainer.py:172-212)."""
        T = self.rollout_steps
        dts = step_dts(ro.wall_times)  # [B,T]
        if self.beta:
            returns = discounted_returns(ro.reward, dts, self.beta)
            buf = state.buf
            avg_num_jobs = None
        else:
            buf = state.buf.extend(dts, ro.reward, ro.valid)
            avg_num_jobs = buf.avg_num_jobs()
            returns = differential_returns(ro.reward, dts, avg_num_jobs)
        G, R = self.num_sequences, self.num_rollouts
        obs_times = ro.wall_times[:, :T]
        baselines = group_baselines(
            obs_times.reshape(G, R, T),
            returns.reshape(G, R, T),
            ro.valid.reshape(G, R, T),
        ).reshape(G * R, T)
        return returns, baselines, buf, avg_num_jobs

    @abc.abstractmethod
    def _update(self, state: TrainState, ro: Rollout):
        """One policy update from an iteration's rollouts. Returns
        (new TrainState, stats dict of scalars)."""

    # ------------------------------------------------------------------
    # host loop
    # ------------------------------------------------------------------

    def train(self, resume_from: str | None = None) -> TrainState:
        """Run `num_iterations` more iterations, optionally resuming a
        saved full train state (params + optimizer + returns window + RNG +
        iteration counter) — the resume capability the reference lacks
        (its checkpoints are model weights only, trainer.py:256-262)."""
        self._setup(fresh=resume_from is None)
        if resume_from:
            state = self.load_train_state(resume_from)
            emit(f"Resumed from {resume_from} at iteration "
                 f"{int(state.iteration)}.")
            if self._runlog is not None:
                self._runlog.write(
                    "resume", path=resume_from,
                    iteration=int(state.iteration),
                )
        else:
            state = self.init_state()
        best: dict[str, Any] | None = None
        start = int(state.iteration)
        sink = (
            self._runlog.span_event if self._runlog is not None else None
        )

        for i in range(start, start + self.num_iterations):
            # device trace: the obs-block iteration (absolute) wins; the
            # legacy profile_trace_dir traces the session's first
            # iteration's collect as before
            if i == self.obs_trace_iteration:
                trace_dir = self.obs_trace_dir
            elif i == start and self.profile_trace_dir:
                trace_dir = self.profile_trace_dir
            else:
                trace_dir = None
            trace_upd = (
                self.obs_trace_dir if i == self.obs_trace_iteration
                else None
            )
            # recovery loop (ISSUE 9): with `health:` off this runs the
            # iteration exactly once with the pre-health rng derivation;
            # with it on, a tripped sentinel rolls back to `last_good`
            # (the pre-iteration TrainState and async carry — donation
            # is off under health, so the carry stays valid), reseeds
            # the iteration rng, and retries under exponential backoff.
            last_good = state
            prev_env_states = self._env_states
            attempt = 0
            while True:
                rng_i = jax.random.fold_in(
                    jax.random.PRNGKey(self.seed), i
                )
                if attempt:
                    # reseeded retry: a fresh minibatch permutation and
                    # policy-sampling stream for the re-run
                    rng_i = jax.random.fold_in(rng_i, 90_000 + attempt)
                state = last_good.replace(rng=rng_i)
                try:
                    with Profiler(trace_dir, f"iter {i + 1} collect",
                                  quiet=not self.profiling,
                                  sink=sink) as p_col:
                        ro, env_states_new, telem = self._collect_jit(
                            state.params, state.iteration, state.rng,
                            prev_env_states,
                        )
                        jax.block_until_ready(ro.reward)
                    if self._chaos is not None:
                        ro, injected = self._chaos.poison_rollout(
                            ro, i, attempt
                        )
                        telem, inj2 = self._chaos.inflate_straggler(
                            telem, i, attempt
                        )
                        injected += inj2
                        if injected and self._runlog is not None:
                            self._runlog.write(
                                "chaos", iteration=i, attempt=attempt,
                                injected=injected,
                            )
                        self._chaos.maybe_sigkill(i)
                        self._chaos.maybe_raise_oom(i, attempt)
                    prev_params = state.params
                    with Profiler(trace_upd, f"iter {i + 1} update",
                                  quiet=not self.profiling,
                                  sink=sink) as p_upd:
                        state, stats = self._update_jit(state, ro)
                        jax.block_until_ready(state.params)
                except Exception as e:
                    if not (self.health_enabled
                            and "RESOURCE_EXHAUSTED" in str(e)):
                        raise
                    if not self._record_health_and_retry(
                        i, attempt, H_OOM, detail=str(e)[:300]
                    ):
                        raise
                    attempt += 1
                    continue
                tsum = summarize(telem) if telem is not None else None
                health_mask = 0
                if self.health_enabled:
                    if tsum is not None:
                        health_mask |= int(tsum.get("health_mask", 0))
                    hm_stat = stats.get("health_mask")
                    if hm_stat is not None:
                        health_mask |= int(hm_stat)
                    if (self.health_straggler_max is not None
                            and tsum is not None
                            and tsum["straggler_ratio"]
                            > self.health_straggler_max):
                        health_mask |= H_STRAGGLER
                if health_mask & RETRYABLE_MASK:
                    if not self._record_health_and_retry(
                        i, attempt, health_mask
                    ):
                        raise RuntimeError(
                            f"iteration {i + 1} still unhealthy "
                            f"({describe_mask(health_mask)}) after "
                            f"{attempt} retr"
                            f"{'y' if attempt == 1 else 'ies'} — "
                            "refusing to train on a poisoned state"
                        )
                    attempt += 1
                    continue
                if health_mask:  # non-retryable bits (straggler):
                    # quarantine the observation, keep the iteration
                    self._record_health(i, attempt, health_mask,
                                        action="quarantine")
                break
            self._env_states = env_states_new
            state = state.replace(iteration=state.iteration + 1)

            roll_stats = self._rollout_stats(ro)
            avg_num_jobs = float(
                stats.get("avg_num_jobs_est") or roll_stats["avg_num_jobs"]
            )

            if best is None or avg_num_jobs < best["avg_num_jobs"]:
                best = {
                    "iteration": i,
                    "avg_num_jobs": round(avg_num_jobs, 3),
                    "params": jax.device_get(prev_params),
                    "completed_job_count": int(
                        roll_stats["num_completed_jobs"]
                    ),
                }
            if (i + 1) % self.checkpointing_freq == 0:
                self._checkpoint(i, best, state)
                best = None

            host_stats = {
                k: float(v) for k, v in stats.items()
                if v is not None
                and k not in ("avg_num_jobs_est", "health_mask")
            }
            host_stats["collect_seconds"] = p_col.elapsed
            host_stats["update_seconds"] = p_upd.elapsed
            if self.health_enabled:
                host_stats["health_mask"] = float(health_mask)
                host_stats["health_retries"] = float(attempt)
            if tsum is not None:
                if self._runlog is not None:
                    self._runlog.telemetry(tsum, iteration=i)
                host_stats["straggler_ratio"] = tsum["straggler_ratio"]
                host_stats["micro_per_decision"] = tsum[
                    "micro_per_decision"
                ]
                host_stats["events_per_decision"] = tsum[
                    "events_per_decision"
                ]
            if self.obs_memory:
                # one host call per iteration, after the update sync —
                # outside the timed collect/update spans, so the sample
                # reads the iteration's peak without riding its clock
                mem = device_memory_stats()
                if mem is not None:
                    if self._runlog is not None:
                        self._runlog.memory(mem, iteration=i)
                    for src, dst in (
                        ("bytes_in_use", "mem_bytes_in_use"),
                        ("peak_bytes_in_use", "mem_peak_bytes"),
                    ):
                        if mem.get(src) is not None:
                            host_stats[dst] = mem[src]
            self._write_stats(i, host_stats | roll_stats)
            # preemption safety (ISSUE 9): an atomic full-train-state
            # write every N iterations, so a SIGKILLed window resumes
            # from the last completed iteration instead of the session
            # start (the end-of-session save in _cleanup never runs
            # under SIGKILL)
            if (self.health_enabled and self.health_checkpoint_every
                    and (i + 1) % self.health_checkpoint_every == 0):
                self.save_train_state(
                    state,
                    osp.join(self.artifacts_dir, "train_state.msgpack"),
                )
            emit(
                f"Iteration {i + 1} complete. Avg. # jobs: "
                f"{avg_num_jobs:.3f}"
            )
        self._cleanup(state)
        return state

    # ------------------------------------------------------------------
    # health recording / recovery policy (ISSUE 9)
    # ------------------------------------------------------------------

    def _record_health(self, i: int, attempt: int, mask: int,
                       action: str, **fields: Any) -> None:
        """One runlog `health` record (the quarantine marker): the raw
        bitmask, its decoded bit names, and what the trainer did about
        it."""
        bits = describe_mask(mask)
        if self._runlog is not None:
            self._runlog.health(
                mask, iteration=i, attempt=attempt, action=action,
                **fields,
            )
        emit(
            f"[health] iteration {i + 1} attempt {attempt}: "
            f"{bits or [hex(mask)]} -> {action}"
        )

    def _record_health_and_retry(self, i: int, attempt: int, mask: int,
                                 **fields: Any) -> bool:
        """Record a tripped sentinel and decide the retry: True means
        "rolled back, backoff slept, caller should re-run the
        iteration"; False means the retry budget is exhausted."""
        if attempt >= self.health_max_retries:
            self._record_health(i, attempt, mask, action="gave_up",
                                **fields)
            if self._runlog is not None:
                self._runlog.write(
                    "recovery", iteration=i, attempt=attempt,
                    action="gave_up", mask=int(mask),
                    bits=describe_mask(mask),
                )
            return False
        delay = self.health_backoff * (2.0 ** attempt)
        self._record_health(i, attempt, mask, action="rollback_retry",
                            backoff_seconds=round(delay, 3), **fields)
        if self._runlog is not None:
            self._runlog.write(
                "recovery", iteration=i, attempt=attempt,
                action="rollback_retry", mask=int(mask),
                bits=describe_mask(mask),
                backoff_seconds=round(delay, 3),
            )
        time.sleep(delay)
        return True

    # ------------------------------------------------------------------
    # stats / io
    # ------------------------------------------------------------------

    def _rollout_stats(self, ro: Rollout) -> dict[str, float]:
        fs = ro.final_state
        d, m = jax.vmap(metrics.job_durations)(fs)
        pcts = metrics.masked_percentiles(d, m)  # pooled across lanes
        pct_stats = {
            f"job_duration_p{q}": float(v)
            for q, v in zip(metrics.PERCENTILE_QS, pcts)
        }
        return pct_stats | {
            "avg_job_duration": float(
                jax.vmap(metrics.avg_job_duration)(fs).mean()
            ),
            "avg_num_jobs": float(
                jax.vmap(metrics.avg_num_jobs)(fs).mean()
            ),
            "num_completed_jobs": float(
                jax.vmap(metrics.num_completed_jobs)(fs).mean()
            ),
            "num_job_arrivals": float(
                jax.vmap(metrics.num_job_arrivals)(fs).mean()
            ),
            "episode_length": float(ro.valid.sum(-1).mean()),
        }

    def _setup(self, fresh: bool = True) -> None:
        pathlib.Path(self.artifacts_dir).mkdir(parents=True, exist_ok=True)
        self.checkpointing_dir = osp.join(self.artifacts_dir, "checkpoints")
        if fresh:
            shutil.rmtree(self.checkpointing_dir, ignore_errors=True)
        os.makedirs(self.checkpointing_dir, exist_ok=True)
        if self.obs_runlog and self._runlog is None:
            if isinstance(self.obs_runlog, str):
                self._runlog = RunLog(
                    self.obs_runlog,
                    max_bytes=self.obs_runlog_max_bytes,
                )
            else:
                self._runlog = RunLog.create(
                    self.artifacts_dir,
                    max_bytes=self.obs_runlog_max_bytes,
                )
            self._runlog.install_jit_hooks()
            self._runlog.write(
                "run_start",
                trainer=type(self).__name__,
                num_iterations=self.num_iterations,
                num_envs=self.num_envs,
                rollout_steps=self.rollout_steps,
                rollout_engine=self.rollout_engine,
                telemetry=self.obs_telemetry,
                memory=self.obs_memory,
                seed=self.seed,
            )
        self._tb = None
        if self.use_tensorboard:
            # a heavy torch dependency in a JAX repo: degrade to the
            # JSONL runlog (the default sink) instead of crashing when
            # torch/tensorboard is absent
            try:
                from torch.utils.tensorboard import SummaryWriter
            except ImportError as e:
                emit(
                    "use_tensorboard: torch.utils.tensorboard is "
                    f"unavailable ({e}); stats go to the JSONL runlog "
                    "instead"
                    + (
                        f" ({self._runlog.path})"
                        if self._runlog is not None
                        else " (enable it via the obs: config block)"
                    )
                )
            else:
                self._tb = SummaryWriter(
                    osp.join(self.artifacts_dir, "tb")
                )

    def _cleanup(self, state: TrainState) -> None:
        if self._tb is not None:
            self._tb.close()
        # always leave a resumable final state behind (the reference cannot
        # resume: it only saves model weights, trainer.py:256-262)
        self.save_train_state(
            state, osp.join(self.artifacts_dir, "train_state.msgpack")
        )
        if self._runlog is not None:
            self._runlog.close(iteration=int(state.iteration))
            self._runlog = None
        emit("\nTraining complete.")

    def _checkpoint(self, i: int, best: dict[str, Any],
                    state: TrainState) -> None:
        d = osp.join(self.checkpointing_dir, f"{i + 1}")
        os.makedirs(d, exist_ok=True)
        with open(osp.join(d, "model.msgpack"), "wb") as fp:
            fp.write(serialization.to_bytes(best["params"]))
        meta = {k: v for k, v in best.items() if k != "params"}
        with open(osp.join(d, "state.json"), "w") as fp:
            json.dump(meta, fp)

    def save_train_state(self, state: TrainState, path: str,
                         keep: int | None = None) -> None:
        """Atomic, digest-stamped, keep-last-K train-state write
        (ISSUE 9 satellite): serialize, fsync a tmp file, rotate the
        previous generations (`path.1` = previous, `path.2` = the one
        before, up to `keep - 1` — state and meta move together), then
        `os.replace` into place. A kill at ANY point leaves either the
        old complete generation set or the new one; a torn write can
        only ever hit the tmp file, never a named generation."""
        keep = self.checkpoint_keep if keep is None else int(keep)
        data = serialization.to_bytes(jax.device_get(state))
        # the checkpointed rng key's layout depends on the PRNG impl
        # (threefry uint32[2] vs rbg uint32[4], config.use_fast_prng);
        # stamp the impl so a resume under the wrong `fast_prng` setting
        # fails with an error naming the flag instead of an opaque flax
        # shape mismatch. sha256 is the torn-write detector: a load
        # whose bytes don't match falls back to the previous generation.
        meta = {
            "prng_impl": str(jax.config.jax_default_prng_impl),
            "sha256": hashlib.sha256(data).hexdigest(),
            "iteration": int(state.iteration),
        }

        def fsync_write(target: str, payload: bytes | str,
                        mode: str) -> None:
            tmp = target + ".tmp"
            with open(tmp, mode) as fp:
                fp.write(payload)
                fp.flush()
                os.fsync(fp.fileno())
            os.replace(tmp, target)

        def intact(gen: str) -> bool:
            """Digest check of one on-disk generation; generations
            without a digest (legacy) pass."""
            meta_p = gen + ".meta.json"
            if not osp.exists(meta_p):
                return True
            try:
                with open(meta_p) as fp:
                    want = json.load(fp).get("sha256")
                if want is None:
                    return True
                with open(gen, "rb") as fp:
                    return hashlib.sha256(
                        fp.read()
                    ).hexdigest() == want
            except (OSError, ValueError):
                return False

        # rotate existing generations oldest-first (gen g -> g+1) —
        # but NEVER promote a torn generation over an intact one: after
        # a crash-recovery resume, `path` may be the very corrupt file
        # the loader fell back past, and rotating it onto `path.1`
        # would destroy the only good copy right before the (killable)
        # write below
        for g in range(keep - 1, 0, -1):
            src = path if g == 1 else f"{path}.{g - 1}"
            if not osp.exists(src):
                continue
            if not intact(src):
                emit(
                    f"[checkpoint] discarding torn generation {src} "
                    "instead of rotating it over an intact one"
                )
                os.remove(src)
                if osp.exists(src + ".meta.json"):
                    os.remove(src + ".meta.json")
                continue
            os.replace(src, f"{path}.{g}")
            if osp.exists(src + ".meta.json"):
                os.replace(
                    src + ".meta.json", f"{path}.{g}.meta.json"
                )
        fsync_write(path, data, "wb")
        fsync_write(path + ".meta.json", json.dumps(meta), "w")

    def load_train_state(self, path: str) -> TrainState:
        """Verified load with corrupt-file fallback (ISSUE 9): check
        the meta digest, deserialize, and on a torn/corrupt generation
        fall back to the previous one (`path.1`, `path.2`, ...),
        emitting + runlogging what was skipped. A PRNG-impl mismatch
        raises immediately — that is a config error on THIS process,
        not file corruption, and every generation shares it."""
        current = str(jax.config.jax_default_prng_impl)
        template = self.init_state()
        candidates = [path] + [
            f"{path}.{g}" for g in range(1, max(self.checkpoint_keep, 2))
        ]
        errors: list[str] = []
        for cand in candidates:
            if not osp.exists(cand):
                continue
            meta_path = cand + ".meta.json"
            digest = None
            if osp.exists(meta_path):
                with open(meta_path) as fp:
                    meta = json.load(fp)
                saved = meta.get("prng_impl", current)
                if saved != current:
                    raise ValueError(
                        f"train state {cand} was saved under PRNG impl "
                        f"{saved!r} but this process uses {current!r} — "
                        f"set `fast_prng: {saved == 'rbg'}` in the "
                        "trainer config (config.use_fast_prng switches "
                        "the impl) before resuming"
                    )
                digest = meta.get("sha256")
            with open(cand, "rb") as fp:
                data = fp.read()
            if digest is not None and (
                hashlib.sha256(data).hexdigest() != digest
            ):
                errors.append(f"{cand}: sha256 mismatch (torn write?)")
                continue
            try:
                restored = serialization.from_bytes(template, data)
            except (ValueError, KeyError) as e:
                errors.append(f"{cand}: {e}")
                continue
            if errors:
                emit(
                    f"[checkpoint] fell back to {cand} — skipped: "
                    + "; ".join(errors)
                )
                if self._runlog is not None:
                    self._runlog.write(
                        "recovery", action="checkpoint_fallback",
                        loaded=cand, skipped=errors,
                    )
            return restored
        raise ValueError(
            f"could not restore {path}: no intact generation among "
            f"{candidates} ({'; '.join(errors) or 'none found'}) — if "
            "the error is a shape mismatch on `rng`, the state was "
            "saved under a different PRNG impl (trainer config "
            "`fast_prng`)"
        )

    def _write_stats(self, i: int, stats: dict[str, float]) -> None:
        """Per-iteration scalars: runlog JSONL (default sink) + the
        TensorBoard mirror when enabled — identical keys/values."""
        if self._runlog is not None:
            self._runlog.scalars(i, stats)
        if self._tb is None:
            return
        for k, v in stats.items():
            self._tb.add_scalar(k, v, i)


def make_trainer(cfg: CfgType) -> Trainer:
    """String-keyed factory (reference trainers/__init__.py:7-13); the
    optional top-level `obs:` YAML section configures the observability
    block (runlog / telemetry / trace capture) and the optional
    `parallel:` section (`dp: auto|N`) shards rollout lanes over a
    device mesh — params replicated, `EnvState`/`Rollout`/`Telemetry`
    batch-sharded, the PPO update's reductions lowered to XLA
    collectives (parallel.py; config/decima_tpch_multichip.yaml is the
    worked example)."""
    from ..parallel import mesh_from_config
    from .ppo import PPO
    from .vpg import VPG

    registry = {"PPO": PPO, "VPG": VPG}
    name = cfg["trainer"]["trainer_cls"]
    if name not in registry:
        raise ValueError(f"'{name}' is not a valid trainer.")
    return registry[name](
        cfg["agent"], cfg["env"], cfg["trainer"],
        mesh=mesh_from_config(cfg.get("parallel")),
        obs_cfg=cfg.get("obs"),
        health_cfg=cfg.get("health"),
        chaos_cfg=cfg.get("chaos"),
    )
