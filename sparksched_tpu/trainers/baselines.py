"""Critic-free baseline (reference trainers/utils/baselines.py:4-53).

Rollout lanes are laid out [num_sequences, num_rollouts]; lanes within a
group replay the same job arrival sequence. Each lane's returns curve is
linearly interpolated onto the union of the group's wall-time points, the
baseline is the cross-lane mean at each point, and each lane reads the
baseline back at its own times — all as vmapped `jnp.interp`s instead of
the reference's per-group Python/np.interp loops.

Padded (invalid) steps are sent to far-future sentinel times with their
return forward-filled from the last valid step, which reproduces
np.interp's constant right-extension (`fp[-1]`) for lanes that ended
before others."""

from __future__ import annotations

import jax
import jax.numpy as jnp

_SENTINEL = 1e12


def _lane_curves(ts, ys, valid):
    """Per lane: sentinel times for padding, forward-filled returns."""
    t_cap = ts.shape[-1]
    n_valid = valid.sum(-1, keepdims=True)
    last_idx = jnp.maximum(n_valid - 1, 0)
    last_val = jnp.take_along_axis(ys, last_idx, axis=-1)
    ys_f = jnp.where(valid, ys, last_val)
    ts_f = jnp.where(
        valid, ts, _SENTINEL + jnp.arange(t_cap, dtype=ts.dtype)
    )
    return ts_f, ys_f


def group_baselines(
    wall_times: jnp.ndarray,  # f32[G,R,T] obs times (not the final time)
    returns: jnp.ndarray,  # f32[G,R,T]
    valid: jnp.ndarray,  # bool[G,R,T]
) -> jnp.ndarray:
    """f32[G,R,T] baselines (reference Baseline._average:20-37)."""

    def per_group(ts, ys, vm):
        ts_f, ys_f = _lane_curves(ts, ys, vm)
        union = jnp.sort(ts_f.reshape(-1))
        y_hats = jax.vmap(lambda t, y: jnp.interp(union, t, y))(ts_f, ys_f)
        mean = y_hats.mean(axis=0)
        return jax.vmap(lambda t: jnp.interp(t, union, mean))(ts_f)

    return jax.vmap(per_group)(wall_times, returns, valid)
