"""Profiling utilities (reference trainers/utils/profiler.py:7-30).

The reference wraps every rollout in a cProfile context manager printing
top-N cumulative stats. Host-side Python profiling is meaningless for a
jitted program, so `Profiler` keeps the same context-manager interface but
reports wall time and, when a trace directory is given, captures a
`jax.profiler` device trace viewable in TensorBoard / Perfetto."""

from __future__ import annotations

import time


class Profiler:
    """Context manager timing a block (and optionally tracing the devices).

    >>> with Profiler() as p:
    ...     rollout = collect(...)
    >>> p.elapsed  # seconds
    """

    def __init__(self, trace_dir: str | None = None,
                 label: str = "block", quiet: bool = False) -> None:
        self.trace_dir = trace_dir
        self.label = label
        self.quiet = quiet
        self.elapsed = 0.0

    def __enter__(self) -> "Profiler":
        if self.trace_dir:
            import jax

            jax.profiler.start_trace(self.trace_dir)
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc_val, exc_tb) -> None:
        self.elapsed = time.perf_counter() - self._t0
        if self.trace_dir:
            import jax

            jax.profiler.stop_trace()
        if not self.quiet:
            print(
                f"[profiler] {self.label}: {self.elapsed:.3f}s",
                flush=True,
            )
