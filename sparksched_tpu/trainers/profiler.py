"""Profiling utilities (reference trainers/utils/profiler.py:7-30).

The reference wraps every rollout in a cProfile context manager printing
top-N cumulative stats. Host-side Python profiling is meaningless for a
jitted program, so `Profiler` keeps the same context-manager interface but
reports wall time and, when a trace directory is given, captures a
`jax.profiler` device trace viewable in TensorBoard / Perfetto (phases
are labeled via `obs.tracing.annotate` scopes — see PERF.md "Reading a
run")."""

from __future__ import annotations

import time

from ..obs.runlog import emit


class Profiler:
    """Context manager timing a block (and optionally tracing the devices).

    >>> with Profiler() as p:
    ...     rollout = collect(...)
    >>> p.elapsed  # seconds

    `sink(label, elapsed)` replaces the default stdout report — the
    trainer routes it into the JSONL runlog. The device trace is stopped
    in a try/finally: an exception inside a traced block (or inside the
    report itself) must not leave jax's process-global tracer running,
    which would poison the next capture with a "profiler already active"
    error."""

    def __init__(self, trace_dir: str | None = None,
                 label: str = "block", quiet: bool = False,
                 sink=None) -> None:
        self.trace_dir = trace_dir
        self.label = label
        self.quiet = quiet
        self.sink = sink
        self.elapsed = 0.0
        self._tracing = False

    def __enter__(self) -> "Profiler":
        if self.trace_dir:
            import jax

            jax.profiler.start_trace(self.trace_dir)
            self._tracing = True
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc_val, exc_tb) -> None:
        try:
            self.elapsed = time.perf_counter() - self._t0
            # the sink (runlog) always receives the span; `quiet` only
            # silences the console echo
            if self.sink is not None:
                self.sink(self.label, self.elapsed)
            if not self.quiet:
                emit(f"[profiler] {self.label}: {self.elapsed:.3f}s")
        finally:
            if self._tracing:
                self._tracing = False
                import jax

                jax.profiler.stop_trace()
