"""On-device rollout collection.

The reference collects rollouts in `num_sequences x num_rollouts` separate
OS processes, each running a Python env + torch policy episode loop and
shipping pickled buffers over pipes (trainers/rollout_worker.py:49-206,
trainer.py:264-296). Here a rollout is one `lax.scan` of
policy∘env-step over T decision steps, vmapped over B environment lanes on
one chip (and sharded over the device mesh for more) — parameter scatter
and buffer gather disappear because learner and actors are one XLA program.

Both reference modes exist:
- sync (RolloutWorkerSync:132-157): one episode per lane per iteration;
  steps after episode end are masked out (`valid=False`).
- async (RolloutWorkerAsync:160-206): fixed sim-time budget per iteration;
  lanes persist across iterations and auto-reset mid-scan, recording reset
  steps.
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
from flax import struct
from jax import lax

from ..config import EnvParams
from ..env import core
from ..env.observe import Observation, observe
from ..env.state import EnvState
from ..workload.bank import WorkloadBank

_i32 = jnp.int32


class StoredObs(struct.PyTreeNode):
    """Minimal per-step observation record from which `Observation` (and so
    Decima features) can be rebuilt — the padded equivalent of the obs dicts
    the reference keeps in RolloutBuffer.obsns (rollout_worker.py:27-39).
    The [S,S] adjacency is *not* stored: it is reconstructed from the job's
    template id, which shrinks the rollout memory footprint by ~10x."""

    remaining: jnp.ndarray  # i32[J,S]
    duration: jnp.ndarray  # f32[J,S]
    schedulable: jnp.ndarray  # bool[J,S]
    node_mask: jnp.ndarray  # bool[J,S]
    job_mask: jnp.ndarray  # bool[J]
    job_template: jnp.ndarray  # i32[J]
    exec_supplies: jnp.ndarray  # i32[J]
    num_committable: jnp.ndarray  # i32 []
    source_job: jnp.ndarray  # i32 []


def store_obs(obs: Observation, state: EnvState) -> StoredObs:
    return StoredObs(
        remaining=obs.nodes[..., 0].astype(_i32),
        duration=obs.nodes[..., 1],
        schedulable=obs.schedulable,
        node_mask=obs.node_mask,
        job_mask=obs.job_mask,
        job_template=state.job_template,
        exec_supplies=obs.exec_supplies,
        num_committable=obs.num_committable,
        source_job=obs.source_job,
    )


def stored_to_observation(bank: WorkloadBank, so: StoredObs) -> Observation:
    """Rebuild the padded Observation a stored step was taken from.

    `node_level` is recomputed from the reconstructed active-subgraph
    adjacency rather than stored: an i32[J,S] per step was ~30% of the
    rollout buffer at the flagship 200-job scale, and the S-deep level
    recursion is a small fraction of the GNN work the observation feeds."""
    adj = (
        bank.adj[so.job_template]
        & so.node_mask[:, :, None]
        & so.node_mask[:, None, :]
    )
    nodes = jnp.stack(
        [
            so.remaining.astype(jnp.float32),
            so.duration,
            so.schedulable.astype(jnp.float32),
        ],
        axis=-1,
    )
    return Observation(
        nodes=nodes,
        node_mask=so.node_mask,
        job_mask=so.job_mask,
        schedulable=so.schedulable,
        frontier=jnp.zeros_like(so.schedulable),  # not needed by any model
        adj=adj,
        node_level=core.topo_levels(so.node_mask, adj),
        exec_supplies=so.exec_supplies,
        num_committable=so.num_committable,
        source_job=so.source_job,
        wall_time=jnp.float32(0.0),
    )


class Rollout(struct.PyTreeNode):
    """One lane's fixed-length rollout (leading [T] axis on per-step
    fields; vmapped collection adds a [B] axis in front)."""

    obs: StoredObs  # [T, ...]
    stage_idx: jnp.ndarray  # i32[T] flat padded node index (-1 = none)
    job_idx: jnp.ndarray  # i32[T]
    num_exec_k: jnp.ndarray  # i32[T] 0-based exec choice (Decima) or n-1
    lgprob: jnp.ndarray  # f32[T]
    reward: jnp.ndarray  # f32[T]
    # wall_times[k] = time of obs k; wall_times[T] = final time
    # (reference rollout_worker.py:154-156 appends the last wall time)
    wall_times: jnp.ndarray  # f32[T+1]
    valid: jnp.ndarray  # bool[T]; step actually happened
    resets: jnp.ndarray  # bool[T]; async: env was reset after this step
    final_state: EnvState
    # async: the next reset ordinal for this lane (drives the group-shared
    # job-sequence key; reference rollout_worker.py:119-120). 0 for sync.
    final_reset_count: jnp.ndarray  # i32 []

    @property
    def num_steps(self) -> jnp.ndarray:
        return self.valid.sum()


# policy_fn(rng, obs) -> (stage_idx, num_exec_1based, aux) where aux is a
# dict containing at least {"lgprob", "job_idx", "num_exec_k"} for
# trainable policies; heuristics may return {}.
PolicyFn = Callable[[jax.Array, Observation], tuple]


def _aux_fields(aux: dict, stage_idx: jnp.ndarray, num_exec: jnp.ndarray,
                max_stages: int):
    lgprob = aux.get("lgprob", jnp.float32(0.0))
    # heuristic policies don't report job_idx; derive it from the flat
    # padded node index (stage_idx = job * max_stages + stage)
    job = aux.get(
        "job_idx", jnp.where(stage_idx >= 0, stage_idx // max_stages, 0)
    )
    k = aux.get("num_exec_k", num_exec - 1)
    return lgprob, job, k


@partial(jax.jit, static_argnums=(0, 2, 4))
def collect_sync(
    params: EnvParams,
    bank: WorkloadBank,
    policy_fn: PolicyFn,
    rng: jax.Array,
    num_steps: int,
    state: EnvState,
) -> Rollout:
    """One episode (from the given freshly-reset state), padded to
    `num_steps` decisions (reference RolloutWorkerSync.collect_rollout)."""

    def body(carry, _):
        st, k = carry
        k, k_pol = jax.random.split(k)
        obs = observe(params, st)
        done = st.terminated | st.truncated
        stage_idx, num_exec, aux = policy_fn(k_pol, obs)
        nxt, reward, _, _ = core.step(params, bank, st, stage_idx, num_exec)
        nxt = jax.tree_util.tree_map(
            lambda a, b: jnp.where(done, a, b), st, nxt
        )
        lgprob, job, kk = _aux_fields(
            aux, stage_idx, num_exec, params.max_stages
        )
        rec = (
            store_obs(obs, st),
            jnp.where(done, -1, stage_idx),
            job,
            kk,
            jnp.where(done, 0.0, lgprob),
            jnp.where(done, 0.0, reward),
            st.wall_time,
            ~done,
        )
        return (nxt, k), rec

    (final, _), (obs, stage_idx, job, kk, lgprob, reward, wt, valid) = (
        lax.scan(body, (state, rng), None, length=num_steps)
    )
    wall_times = jnp.concatenate([wt, final.wall_time[None]])
    return Rollout(
        obs=obs,
        stage_idx=stage_idx,
        job_idx=job,
        num_exec_k=kk,
        lgprob=lgprob,
        reward=reward,
        wall_times=wall_times,
        valid=valid,
        resets=jnp.zeros_like(valid),
        final_state=final,
        final_reset_count=jnp.int32(0),
    )


@partial(jax.jit, static_argnums=(0, 2, 4))
def collect_async(
    params: EnvParams,
    bank: WorkloadBank,
    policy_fn: PolicyFn,
    rng: jax.Array,
    num_steps: int,
    state: EnvState,
    rollout_duration: jnp.ndarray | float = jnp.inf,
    seq_base: jax.Array | None = None,
    lane_salt: jnp.ndarray | int = 0,
    reset_count: jnp.ndarray | int = 0,
) -> Rollout:
    """Fixed sim-time budget with persistent envs and auto-reset (reference
    RolloutWorkerAsync.collect_rollout:171-206). `wall_times` are *elapsed*
    times within the iteration, continuing across resets. Steps after the
    budget is exhausted are masked.

    Mid-scan resets draw the new episode from
    ``fold_in(seq_base, reset_count)`` — so lanes that share `seq_base`
    (a sequence group) replay identical job-arrival sequences at equal
    reset ordinals, which the grouped critic-free baseline relies on
    (reference ``base_seed + seed_step * reset_count``,
    rollout_worker.py:119-120, trainer.py:268-271). `lane_salt`
    de-correlates the per-lane stochastic stream within a group
    (core.reset_pair's seq/lane split). When `seq_base` is None (ad-hoc
    use outside a trainer), `rng` stands in for it."""
    rollout_duration = jnp.float32(rollout_duration)
    if seq_base is None:
        seq_base = rng
    lane_salt = jnp.asarray(lane_salt, _i32)
    reset_count = jnp.asarray(reset_count, _i32)

    def body(carry, _):
        st, k, elapsed, rc = carry
        k, k_pol = jax.random.split(k)
        obs = observe(params, st)
        over = elapsed >= rollout_duration
        stage_idx, num_exec, aux = policy_fn(k_pol, obs)
        nxt, reward, term, trunc = core.step(
            params, bank, st, stage_idx, num_exec
        )
        new_elapsed = elapsed + (nxt.wall_time - st.wall_time)
        done = term | trunc

        # unconditional reset + tree-select rather than lax.cond: a
        # lane-dependent cond broadcasts the closed-over workload bank
        # across the vmap batch (see env/core.py structural note)
        seq_rng = jax.random.fold_in(seq_base, rc)
        fresh = core.reset_pair(
            params, bank, seq_rng, jax.random.fold_in(seq_rng, lane_salt)
        )
        did_reset = done & ~over
        nxt2 = jax.tree_util.tree_map(
            lambda a, b: jnp.where(did_reset, a, b), fresh, nxt
        )
        # budget exhausted: freeze the lane
        nxt2 = jax.tree_util.tree_map(
            lambda a, b: jnp.where(over, a, b), st, nxt2
        )
        new_elapsed = jnp.where(over, elapsed, new_elapsed)
        new_rc = rc + did_reset.astype(_i32)
        lgprob, job, kk = _aux_fields(
            aux, stage_idx, num_exec, params.max_stages
        )
        rec = (
            store_obs(obs, st),
            jnp.where(over, -1, stage_idx),
            job,
            kk,
            jnp.where(over, 0.0, lgprob),
            jnp.where(over, 0.0, reward),
            elapsed,
            ~over,
            did_reset,
        )
        return (nxt2, k, new_elapsed, new_rc), rec

    (final, _, elapsed, final_rc), (
        obs, stage_idx, job, kk, lgprob, reward, wt, valid, resets
    ) = lax.scan(
        body, (state, rng, jnp.float32(0.0), reset_count), None,
        length=num_steps,
    )
    wall_times = jnp.concatenate([wt, elapsed[None]])
    return Rollout(
        obs=obs,
        stage_idx=stage_idx,
        job_idx=job,
        num_exec_k=kk,
        lgprob=lgprob,
        reward=reward,
        wall_times=wall_times,
        valid=valid,
        resets=resets,
        final_state=final,
        final_reset_count=final_rc,
    )


def vmap_collect(collect_fn, params, bank, policy_fn, rngs, num_steps,
                 states, *args):
    """Collect B rollouts in parallel: `rngs` [B,2] and `states` with a
    leading [B] axis (the TPU replacement for the reference's B worker
    processes)."""
    return jax.vmap(
        lambda r, s: collect_fn(
            params, bank, policy_fn, r, num_steps, s, *args
        )
    )(rngs, states)
