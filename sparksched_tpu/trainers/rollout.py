"""On-device rollout collection.

The reference collects rollouts in `num_sequences x num_rollouts` separate
OS processes, each running a Python env + torch policy episode loop and
shipping pickled buffers over pipes (trainers/rollout_worker.py:49-206,
trainer.py:264-296). Here a rollout is one `lax.scan` of
policy∘env-step over T decision steps, vmapped over B environment lanes on
one chip (and sharded over the device mesh for more) — parameter scatter
and buffer gather disappear because learner and actors are one XLA program.

Both reference modes exist:
- sync (RolloutWorkerSync:132-157): one episode per lane per iteration;
  steps after episode end are masked out (`valid=False`).
- async (RolloutWorkerAsync:160-206): fixed sim-time budget per iteration;
  lanes persist across iterations and auto-reset mid-scan, recording reset
  steps.
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
from flax import struct
from jax import lax

from ..config import EnvParams
from ..env import core
from ..env.flat_loop import (
    M_DECIDE,
    LoopState,
    aux_action_fields,
    decide_micro_step,
    drain_to_decision,
    event_micro_step,
    init_loop_state,
    micro_step,
)
from ..env.health import reward_health, state_health
from ..env.observe import Observation, observe
from ..env.state import EnvState
from ..obs.telemetry import orr as _tm_orr
from ..obs.tracing import annotate
from ..workload.bank import WorkloadBank

_i32 = jnp.int32


class StoredObs(struct.PyTreeNode):
    """Minimal per-step observation record from which `Observation` (and so
    Decima features) can be rebuilt — the padded equivalent of the obs dicts
    the reference keeps in RolloutBuffer.obsns (rollout_worker.py:27-39).
    The [S,S] adjacency is *not* stored: it is reconstructed from the job's
    template id, which shrinks the rollout memory footprint by ~10x."""

    remaining: jnp.ndarray  # i32[J,S]
    duration: jnp.ndarray  # f32[J,S]
    schedulable: jnp.ndarray  # bool[J,S]
    node_mask: jnp.ndarray  # bool[J,S]
    job_mask: jnp.ndarray  # bool[J]
    job_template: jnp.ndarray  # i32[J]
    exec_supplies: jnp.ndarray  # i32[J]
    num_committable: jnp.ndarray  # i32 []
    source_job: jnp.ndarray  # i32 []


def store_obs(obs: Observation, state: EnvState) -> StoredObs:
    # `remaining` comes from the state, not `nodes[..., 0]`: the count
    # must stay exactly i32 even when `params.obs_dtype` narrows the
    # feature bank to bf16 (whose 8-bit mantissa rounds counts > 256);
    # `duration` deliberately inherits the bank's (possibly narrow)
    # dtype — it is the lane-scaled buffer the layout exists to halve
    return StoredObs(
        remaining=jnp.where(obs.node_mask, state.stage_remaining, 0),
        duration=obs.nodes[..., 1],
        schedulable=obs.schedulable,
        node_mask=obs.node_mask,
        job_mask=obs.job_mask,
        job_template=state.job_template,
        exec_supplies=obs.exec_supplies,
        num_committable=obs.num_committable,
        source_job=obs.source_job,
    )


def stored_to_observation(bank: WorkloadBank, so: StoredObs) -> Observation:
    """Rebuild the padded Observation a stored step was taken from.

    `node_level` is recomputed from the reconstructed active-subgraph
    adjacency rather than stored: an i32[J,S] per step was ~30% of the
    rollout buffer at the flagship 200-job scale, and the S-deep level
    recursion is a small fraction of the GNN work the observation feeds."""
    adj = (
        bank.adj[so.job_template]
        & so.node_mask[:, :, None]
        & so.node_mask[:, None, :]
    )
    nodes = jnp.stack(
        [
            so.remaining.astype(jnp.float32),
            # f32 accumulation at the use site: a bf16-recorded
            # duration upcasts losslessly here
            so.duration.astype(jnp.float32),
            so.schedulable.astype(jnp.float32),
        ],
        axis=-1,
    )
    return Observation(
        nodes=nodes,
        node_mask=so.node_mask,
        job_mask=so.job_mask,
        schedulable=so.schedulable,
        frontier=jnp.zeros_like(so.schedulable),  # not needed by any model
        adj=adj,
        node_level=core.topo_levels(so.node_mask, adj),
        exec_supplies=so.exec_supplies,
        num_committable=so.num_committable,
        source_job=so.source_job,
        wall_time=jnp.float32(0.0),
    )


class Rollout(struct.PyTreeNode):
    """One lane's fixed-length rollout (leading [T] axis on per-step
    fields; vmapped collection adds a [B] axis in front)."""

    obs: StoredObs  # [T, ...]
    stage_idx: jnp.ndarray  # i32[T] flat padded node index (-1 = none)
    job_idx: jnp.ndarray  # i32[T]
    num_exec_k: jnp.ndarray  # i32[T] 0-based exec choice (Decima) or n-1
    lgprob: jnp.ndarray  # f32[T]
    reward: jnp.ndarray  # f32[T]
    # wall_times[k] = time of obs k; wall_times[T] = final time
    # (reference rollout_worker.py:154-156 appends the last wall time)
    wall_times: jnp.ndarray  # f32[T+1]
    valid: jnp.ndarray  # bool[T]; step actually happened
    resets: jnp.ndarray  # bool[T]; async: env was reset after this step
    final_state: EnvState
    # async: the next reset ordinal for this lane (drives the group-shared
    # job-sequence key; reference rollout_worker.py:119-120). 0 for sync.
    final_reset_count: jnp.ndarray  # i32 []

    @property
    def num_steps(self) -> jnp.ndarray:
        return self.valid.sum()


# policy_fn(rng, obs) -> (stage_idx, num_exec_1based, aux) where aux is a
# dict containing at least {"lgprob", "job_idx", "num_exec_k"} for
# trainable policies; heuristics may return {}.
PolicyFn = Callable[[jax.Array, Observation], tuple]


def _aux_fields(aux: dict, stage_idx: jnp.ndarray, num_exec: jnp.ndarray,
                max_stages: int):
    # single source of truth shared with the flat engine's record path
    # (env/flat_loop.py:aux_action_fields) so the two collection paths'
    # recorded actions cannot drift apart
    return aux_action_fields(aux, stage_idx, num_exec, max_stages)


@partial(
    jax.jit, static_argnums=(0, 2, 4), static_argnames=("health",)
)
def collect_sync(
    params: EnvParams,
    bank: WorkloadBank,
    policy_fn: PolicyFn,
    rng: jax.Array,
    num_steps: int,
    state: EnvState,
    telemetry=None,
    health: bool = False,
) -> Rollout | tuple:
    """One episode (from the given freshly-reset state), padded to
    `num_steps` decisions (reference RolloutWorkerSync.collect_rollout).
    With `telemetry` (an `obs.Telemetry`), engine counters ride the scan
    carry — rolled back on frozen (done) lanes — and the call returns
    `(Rollout, Telemetry)`. With `health` (static; requires telemetry),
    each live step additionally ORs the `env/health.py` sentinel mask
    over the post-step state + reward into `telemetry.health_mask`."""
    track = telemetry is not None
    if health and not track:
        raise ValueError("health=True requires a telemetry carry")

    def body(carry, _):
        if track:
            st, k, tm = carry
        else:
            (st, k), tm = carry, None
        k, k_pol = jax.random.split(k)
        obs = observe(params, st)
        done = st.terminated | st.truncated
        stage_idx, num_exec, aux = policy_fn(k_pol, obs)
        if track:
            nxt, reward, _, _, tm2 = core.step(
                params, bank, st, stage_idx, num_exec, telemetry=tm
            )
            tm = jax.tree_util.tree_map(
                lambda a, b: jnp.where(done, a, b), tm, tm2
            )
        else:
            nxt, reward, _, _ = core.step(
                params, bank, st, stage_idx, num_exec
            )
        if health:
            hm = state_health(nxt, prev=st) | reward_health(reward)
            tm = _tm_orr(tm, health_mask=jnp.where(done, 0, hm))
        nxt = jax.tree_util.tree_map(
            lambda a, b: jnp.where(done, a, b), st, nxt
        )
        lgprob, job, kk = _aux_fields(
            aux, stage_idx, num_exec, params.max_stages
        )
        rec = (
            store_obs(obs, st),
            jnp.where(done, -1, stage_idx),
            job,
            kk,
            jnp.where(done, 0.0, lgprob),
            jnp.where(done, 0.0, reward),
            st.wall_time,
            ~done,
        )
        return ((nxt, k, tm) if track else (nxt, k)), rec

    carry0 = (state, rng, telemetry) if track else (state, rng)
    carry, (obs, stage_idx, job, kk, lgprob, reward, wt, valid) = (
        lax.scan(body, carry0, None, length=num_steps)
    )
    final = carry[0]
    wall_times = jnp.concatenate([wt, final.wall_time[None]])
    ro = Rollout(
        obs=obs,
        stage_idx=stage_idx,
        job_idx=job,
        num_exec_k=kk,
        lgprob=lgprob,
        reward=reward,
        wall_times=wall_times,
        valid=valid,
        resets=jnp.zeros_like(valid),
        final_state=final,
        final_reset_count=jnp.int32(0),
    )
    return (ro, carry[2]) if track else ro


@partial(
    jax.jit, static_argnums=(0, 2, 4), static_argnames=("health",)
)
def collect_async(
    params: EnvParams,
    bank: WorkloadBank,
    policy_fn: PolicyFn,
    rng: jax.Array,
    num_steps: int,
    state: EnvState,
    rollout_duration: jnp.ndarray | float = jnp.inf,
    seq_base: jax.Array | None = None,
    lane_salt: jnp.ndarray | int = 0,
    reset_count: jnp.ndarray | int = 0,
    telemetry=None,
    health: bool = False,
) -> Rollout | tuple:
    """Fixed sim-time budget with persistent envs and auto-reset (reference
    RolloutWorkerAsync.collect_rollout:171-206). `wall_times` are *elapsed*
    times within the iteration, continuing across resets. Steps after the
    budget is exhausted are masked. With `telemetry`, counters ride the
    scan carry (rolled back on budget-frozen lanes) and the call returns
    `(Rollout, Telemetry)`.

    Mid-scan resets draw the new episode from
    ``fold_in(seq_base, reset_count)`` — so lanes that share `seq_base`
    (a sequence group) replay identical job-arrival sequences at equal
    reset ordinals, which the grouped critic-free baseline relies on
    (reference ``base_seed + seed_step * reset_count``,
    rollout_worker.py:119-120, trainer.py:268-271). `lane_salt`
    de-correlates the per-lane stochastic stream within a group
    (core.reset_pair's seq/lane split). When `seq_base` is None (ad-hoc
    use outside a trainer), `rng` stands in for it."""
    track = telemetry is not None
    if health and not track:
        raise ValueError("health=True requires a telemetry carry")
    rollout_duration = jnp.float32(rollout_duration)
    if seq_base is None:
        seq_base = rng
    lane_salt = jnp.asarray(lane_salt, _i32)
    reset_count = jnp.asarray(reset_count, _i32)

    def body(carry, _):
        if track:
            st, k, elapsed, rc, tm = carry
        else:
            (st, k, elapsed, rc), tm = carry, None
        k, k_pol = jax.random.split(k)
        obs = observe(params, st)
        over = elapsed >= rollout_duration
        stage_idx, num_exec, aux = policy_fn(k_pol, obs)
        if track:
            nxt, reward, term, trunc, tm2 = core.step(
                params, bank, st, stage_idx, num_exec, telemetry=tm
            )
            tm = jax.tree_util.tree_map(
                lambda a, b: jnp.where(over, a, b), tm, tm2
            )
        else:
            nxt, reward, term, trunc = core.step(
                params, bank, st, stage_idx, num_exec
            )
        if health:
            # on the post-step, PRE-reset state (the reset select below
            # swaps in a fresh episode for done lanes)
            hm = state_health(nxt, prev=st) | reward_health(reward)
            tm = _tm_orr(tm, health_mask=jnp.where(over, 0, hm))
        new_elapsed = elapsed + (nxt.wall_time - st.wall_time)
        done = term | trunc

        # unconditional reset + tree-select rather than lax.cond: a
        # lane-dependent cond broadcasts the closed-over workload bank
        # across the vmap batch (see env/core.py structural note)
        seq_rng = jax.random.fold_in(seq_base, rc)
        fresh = core.reset_pair(
            params, bank, seq_rng, jax.random.fold_in(seq_rng, lane_salt)
        )
        did_reset = done & ~over
        nxt2 = jax.tree_util.tree_map(
            lambda a, b: jnp.where(did_reset, a, b), fresh, nxt
        )
        # budget exhausted: freeze the lane
        nxt2 = jax.tree_util.tree_map(
            lambda a, b: jnp.where(over, a, b), st, nxt2
        )
        new_elapsed = jnp.where(over, elapsed, new_elapsed)
        new_rc = rc + did_reset.astype(_i32)
        lgprob, job, kk = _aux_fields(
            aux, stage_idx, num_exec, params.max_stages
        )
        rec = (
            store_obs(obs, st),
            jnp.where(over, -1, stage_idx),
            job,
            kk,
            jnp.where(over, 0.0, lgprob),
            jnp.where(over, 0.0, reward),
            elapsed,
            ~over,
            did_reset,
        )
        carry = (
            (nxt2, k, new_elapsed, new_rc, tm)
            if track
            else (nxt2, k, new_elapsed, new_rc)
        )
        return carry, rec

    carry0 = (state, rng, jnp.float32(0.0), reset_count)
    if track:
        carry0 = carry0 + (telemetry,)
    carry, (
        obs, stage_idx, job, kk, lgprob, reward, wt, valid, resets
    ) = lax.scan(body, carry0, None, length=num_steps)
    final, elapsed, final_rc = carry[0], carry[2], carry[3]
    wall_times = jnp.concatenate([wt, elapsed[None]])
    ro = Rollout(
        obs=obs,
        stage_idx=stage_idx,
        job_idx=job,
        num_exec_k=kk,
        lgprob=lgprob,
        reward=reward,
        wall_times=wall_times,
        valid=valid,
        resets=resets,
        final_state=final,
        final_reset_count=final_rc,
    )
    return (ro, carry[4]) if track else ro


def vmap_collect(collect_fn, params, bank, policy_fn, rngs, num_steps,
                 states, *args):
    """Collect B rollouts in parallel: `rngs` [B,2] and `states` with a
    leading [B] axis (the TPU replacement for the reference's B worker
    processes)."""
    return jax.vmap(
        lambda r, s: collect_fn(
            params, bank, policy_fn, r, num_steps, s, *args
        )
    )(rngs, states)


# ---------------------------------------------------------------------------
# flat micro-step collection (env/flat_loop.py engine)
#
# The per-decision `core.step` scan above pays the straggler tax of a
# vmapped `lax.while_loop` between decisions (batch-max event count per
# decision, measured ~6x the mean at 64 lanes). The collectors below drive
# the flat micro-step engine instead — every lane advances by one unit of
# work per iteration — and scatter the DECIDE micro-steps' records into
# the same fixed-shape `Rollout` the trainers already consume, so only
# decision steps enter the PPO batch. Collected quantities are step-exact
# vs the `core.step` path (tests/test_flat_loop.py parity test): actions,
# log-probs, the DECIDE mask, per-decision wall times and rewards (the
# micro-step reward deltas telescope to `core.step`'s per-decision span
# quantity — see `core._compute_jobtime`'s `t_ref` note).
# ---------------------------------------------------------------------------


def flat_micro_group_budget(
    num_steps: int, micro_per_decision: float, event_burst: int
) -> int:
    """Scan length (micro-step groups) for the flat collectors:
    ceil(num_steps * micro_per_decision / event_burst). Shared by the
    trainer and bench_decima so the two cannot drift on rounding."""
    import math

    return max(
        1, math.ceil(num_steps * micro_per_decision / event_burst)
    )


def _zero_stored(params: EnvParams) -> StoredObs:
    j, s = params.max_jobs, params.max_stages
    # duration mirrors the observation bank's dtype (params.obs_dtype):
    # the scan carry's buffer and the per-step `store_obs` record must
    # agree or the collection scan fails its carry dtype check
    dur_dt = (
        jnp.bfloat16 if params.obs_dtype == "bfloat16" else jnp.float32
    )
    return StoredObs(
        remaining=jnp.zeros((j, s), _i32),
        duration=jnp.zeros((j, s), dur_dt),
        schedulable=jnp.zeros((j, s), bool),
        node_mask=jnp.zeros((j, s), bool),
        job_mask=jnp.zeros((j,), bool),
        job_template=jnp.zeros((j,), _i32),
        exec_supplies=jnp.zeros((j,), _i32),
        num_committable=_i32(0),
        source_job=_i32(-1),
    )


class _FlatBuf(struct.PyTreeNode):
    """Fixed-offset per-decision buffers the micro-step scan scatters
    into (carried through the scan — per-micro-step stacking would
    multiply rollout memory by the micro-steps-per-decision factor)."""

    obs: StoredObs  # [T, ...]
    stage_idx: jnp.ndarray  # i32[T]
    job_idx: jnp.ndarray  # i32[T]
    num_exec_k: jnp.ndarray  # i32[T]
    lgprob: jnp.ndarray  # f32[T]
    reward: jnp.ndarray  # f32[T]
    walls: jnp.ndarray  # f32[T]
    resets: jnp.ndarray  # i32[T]


def _flat_collect(
    params: EnvParams,
    bank: WorkloadBank,
    policy_fn: PolicyFn,
    rng: jax.Array,
    num_steps: int,
    ls: LoopState,
    micro_groups: int,
    auto_reset: bool,
    event_burst: int,
    event_bulk: bool,
    bulk_events: int,
    fulfill_bulk: bool,
    bulk_cycles: int,
    reset_fn,
    rollout_duration,
    use_elapsed: bool,
    telemetry=None,
    bulk_fused: bool = True,
    health: bool = False,
):
    """Shared flat-engine collection scan for one lane (vmap over lanes).

    Scans `micro_groups` micro-step groups (one full micro-step plus
    `event_burst - 1` event-only sub-steps, the `run_flat` grouping).
    Each group's DECIDE record lands in per-decision slot `ndec` and its
    micro-rewards/resets accumulate into the slot of the most recent
    decision, so decision k's reward is exactly the job-time of the span
    (decision k, decision k+1]. A lane freezes when its decision buffer
    is full AND it is about to decide again (so the last slot still
    receives its full trailing span, matching `collect_sync`'s T-step
    truncation), or — async — when `rollout_duration` sim-time elapsed.
    Micro-rewards before a chunk's first decision (async lanes resuming
    mid-phase) belong to the previous chunk's final decision, which was
    already consumed; they are dropped together with their `dt`, which
    keeps the (reward, dt) pairing the returns/average-job estimators
    rely on consistent.

    With `telemetry`, engine counters ride the scan carry (rolled back
    on frozen lanes) and the returned tuple gains a trailing
    Telemetry. With `health` (static; requires telemetry), each live
    micro-step group ORs the `env/health.py` sentinel mask over the
    group's post-state + accumulated reward into
    `telemetry.health_mask` (monotonicity checks are suppressed across
    in-group auto-resets)."""
    track = telemetry is not None
    if health and not track:
        raise ValueError("health=True requires a telemetry carry")
    T = num_steps
    zs = _zero_stored(params)
    buf0 = _FlatBuf(
        obs=jax.tree_util.tree_map(
            lambda a: jnp.zeros((T,) + a.shape, a.dtype), zs
        ),
        stage_idx=jnp.zeros(T, _i32),
        job_idx=jnp.zeros(T, _i32),
        num_exec_k=jnp.zeros(T, _i32),
        lgprob=jnp.zeros(T, jnp.float32),
        reward=jnp.zeros(T, jnp.float32),
        walls=jnp.zeros(T, jnp.float32),
        resets=jnp.zeros(T, _i32),
    )

    def body(carry, _):
        if track:
            ls, k, t_ref, elapsed, ndec, buf, tm = carry
        else:
            (ls, k, t_ref, elapsed, ndec, buf), tm = carry, None
        tm_frozen = tm
        k, sub = jax.random.split(k)
        env0 = ls.env
        wall0 = env0.wall_time
        # pre-step freeze: full decision buffer about to decide again,
        # or (async) sim-time budget exhausted
        over = (ls.mode == M_DECIDE) & (ndec >= T)
        if rollout_duration is not None:
            over = over | (elapsed >= rollout_duration)

        out = micro_step(
            params, bank, policy_fn, ls, sub, auto_reset, True,
            event_bulk, bulk_events, fulfill_bulk, bulk_cycles,
            record=True, reset_fn=reset_fn, t_ref=t_ref,
            telemetry=tm, bulk_fused=bulk_fused,
        )
        (ls2, rec, tm) = out if track else (out + (None,))
        # advance the discount reference BEFORE the burst sub-steps: with
        # fulfill_bulk a round-finishing DECIDE micro-step jumps straight
        # to M_EVENT, so this group's own sub-steps already advance time
        # within the NEW decision's span
        t_ref = jnp.where(rec.decide & ~over, wall0, t_ref)
        reward, dt, reset = rec.reward, rec.dt, rec.reset
        for _ in range(event_burst - 1):
            k, sub = jax.random.split(k)
            out = event_micro_step(
                params, bank, ls2, sub, auto_reset, event_bulk,
                bulk_events, bulk_cycles,
                record=True, reset_fn=reset_fn, t_ref=t_ref,
                telemetry=tm, bulk_fused=bulk_fused,
            )
            (ls2, (rw, dd, rr), tm) = (
                out if track else (out + (None,))
            )
            reward = reward + rw
            dt = dt + dd
            reset = reset | rr
        if health:
            hm = state_health(
                ls2.env, prev=env0, resetting=reset
            ) | reward_health(reward)

        # frozen lanes: state untouched, nothing recorded
        ls2 = jax.tree_util.tree_map(
            lambda a, b: jnp.where(over, a, b), ls, ls2
        )
        if track:
            tm = jax.tree_util.tree_map(
                lambda a, b: jnp.where(over, a, b), tm_frozen, tm
            )
        if health:
            tm = _tm_orr(tm, health_mask=jnp.where(over, 0, hm))
        zero = jnp.float32(0.0)
        reward = jnp.where(over, zero, reward)
        dt = jnp.where(over, zero, dt)
        reset = reset & ~over
        dec = rec.decide & ~over

        # decision-slot scatter (mode="drop" discards non-decide steps
        # and buffer overflow alike)
        with annotate("collect/scatter"):
            slot = jnp.where(dec & (ndec < T), ndec, T)
            stored = store_obs(rec.obs, env0)
            buf = buf.replace(
                obs=jax.tree_util.tree_map(
                    lambda b, v: b.at[slot].set(v, mode="drop"),
                    buf.obs, stored,
                ),
                stage_idx=buf.stage_idx.at[slot].set(
                    rec.stage_idx, mode="drop"
                ),
                job_idx=buf.job_idx.at[slot].set(
                    rec.job_idx, mode="drop"
                ),
                num_exec_k=buf.num_exec_k.at[slot].set(
                    rec.num_exec_k, mode="drop"
                ),
                lgprob=buf.lgprob.at[slot].set(rec.lgprob, mode="drop"),
                walls=buf.walls.at[slot].set(
                    elapsed if use_elapsed else wall0, mode="drop"
                ),
            )
            ndec2 = ndec + dec.astype(_i32)
            # micro-rewards belong to the most recent decision's span
            rslot = jnp.where((ndec2 > 0) & (ndec2 <= T), ndec2 - 1, T)
            buf = buf.replace(
                reward=buf.reward.at[rslot].add(reward, mode="drop"),
                resets=buf.resets.at[rslot].max(
                    reset.astype(_i32), mode="drop"
                ),
            )
        carry = (ls2, k, t_ref, elapsed + dt, ndec2, buf)
        return (carry + (tm,) if track else carry), None

    carry0 = (
        ls, rng, ls.env.wall_time, jnp.float32(0.0), _i32(0), buf0
    )
    if track:
        carry0 = carry0 + (telemetry,)
    carry, _ = lax.scan(body, carry0, None, length=micro_groups)
    ls, elapsed, ndec, buf = carry[0], carry[3], carry[4], carry[5]
    if track:
        telemetry = carry[6]

    valid = jnp.arange(T) < jnp.minimum(ndec, T)
    final_t = elapsed if use_elapsed else ls.env.wall_time
    walls = jnp.where(valid, buf.walls, final_t)
    ro = Rollout(
        obs=buf.obs,
        stage_idx=jnp.where(valid, buf.stage_idx, -1),
        job_idx=buf.job_idx,
        num_exec_k=buf.num_exec_k,
        lgprob=buf.lgprob,
        reward=buf.reward,
        wall_times=jnp.concatenate([walls, final_t[None]]),
        valid=valid,
        resets=buf.resets > 0,
        final_state=ls.env,
        final_reset_count=ls.episodes,
    )
    return (ro, ls, telemetry) if track else (ro, ls)


@partial(
    jax.jit, static_argnums=(0, 2, 4),
    static_argnames=(
        "micro_groups", "event_burst", "event_bulk", "bulk_events",
        "fulfill_bulk", "bulk_cycles", "bulk_fused", "health",
    ),
)
def collect_flat_sync(
    params: EnvParams,
    bank: WorkloadBank,
    policy_fn: PolicyFn,
    rng: jax.Array,
    num_steps: int,
    state: EnvState,
    telemetry=None,
    *,
    micro_groups: int,
    event_burst: int = 1,
    event_bulk: bool = True,
    bulk_events: int = 8,
    fulfill_bulk: bool = False,
    bulk_cycles: int = 1,
    bulk_fused: bool = True,
    health: bool = False,
) -> Rollout | tuple:
    """Flat-engine equivalent of `collect_sync`: one episode from the
    given freshly-reset state, micro-stepped with frozen lanes at episode
    end, padded to `num_steps` decisions. `micro_groups` bounds the scan
    (size it at ~3-4 micro-step groups per expected decision; a too-small
    value truncates the episode exactly like a too-small `num_steps`).
    With `telemetry`, returns `(Rollout, Telemetry)`; `health` (static)
    additionally ORs the in-JIT sentinel mask into
    `telemetry.health_mask` per live group."""
    out = _flat_collect(
        params, bank, policy_fn, rng, num_steps,
        init_loop_state(state), micro_groups,
        auto_reset=False, event_burst=event_burst, event_bulk=event_bulk,
        bulk_events=bulk_events, fulfill_bulk=fulfill_bulk,
        bulk_cycles=bulk_cycles, reset_fn=None, rollout_duration=None,
        use_elapsed=False, telemetry=telemetry, bulk_fused=bulk_fused,
        health=health,
    )
    return (out[0], out[2]) if telemetry is not None else out[0]


# ---------------------------------------------------------------------------
# single-eval flat collection (round 8)
#
# The per-lane collectors above run `micro_step(record=True)`, which
# evaluates observe+policy on EVERY full micro-step group — at the
# round-6 calibrations that measured ~2 GNN evaluations per recorded
# decision (the DECIDE group's eval plus the wasted eval of each group
# that lands on a FULFILL/EVENT lane). The collectors below restructure
# the scan so ONE policy evaluation is both acted on and recorded per
# decision row:
#
#   scan iteration k == decision k:
#     observe -> batch_policy (ONE eval over the [B] lane stack, with
#     the Decima job-compaction cond at batch level) ->
#     vmap(decide_micro_step) (acts on + records the same outputs) ->
#     vmap(drain_to_decision) (non-policy micro-steps until every lane
#     is at its next decision)
#
# The drain reintroduces a batch-max while-loop between decisions — but
# only over the cheap env machinery (bulk passes + pops); the GNN, the
# measured 70-90% of the Decima decision row, runs exactly once per
# decision (test-pinned by a counting-policy test in
# tests/test_flat_loop.py). Collected quantities remain step-exact vs
# the `core.step` path.
# ---------------------------------------------------------------------------


# batch policy: policy_fn(rng, obs_with_leading_B_axis) -> per-lane
# (stage_idx[B], num_exec[B], aux-of-[B]) from ONE evaluation — see
# DecimaScheduler.batch_policy / flat_batch_policy.
BatchPolicyFn = Callable[[jax.Array, Observation], tuple]


def _flat_collect_single_eval(
    params: EnvParams,
    bank: WorkloadBank,
    batch_policy_fn: BatchPolicyFn,
    rng: jax.Array,
    num_steps: int,
    ls: LoopState,  # [B]-batched
    auto_reset: bool,
    event_bulk: bool,
    bulk_events: int,
    fulfill_bulk: bool,
    bulk_cycles: int,
    reset_fns,  # None, or a per-lane factory: lane_idx -> reset_fn
    rollout_duration,
    use_elapsed: bool,
    telemetry=None,
    lane_shard=None,
    bulk_fused: bool = True,
    health: bool = False,
):
    """Shared single-eval collection scan over the WHOLE lane batch
    (`ls` carries a leading [B] axis; no outer vmap). Exactly
    `num_steps` scan iterations, each producing at most one decision
    per lane; see the section comment above for the shape.

    `lane_shard` (a `NamedSharding` over the lane axis, parallel.py:
    `lane_sharding`) pins the scan's carry — the [B] `LoopState`, the
    [B,T] decision buffers and the per-lane telemetry — to the dp mesh
    via `with_sharding_constraint`, so the whole collection runs SPMD
    with the lane axis sharded end-to-end instead of leaving the carry
    layout to the partitioner's fallback (which can silently replicate
    the largest resident buffers of the program).

    With `health` (static; requires telemetry), each decision row ORs
    the per-lane `env/health.py` sentinel mask over the post-drain
    state + the row's accumulated reward into
    `telemetry.health_mask`."""
    track = telemetry is not None
    if health and not track:
        raise ValueError("health=True requires a telemetry carry")
    T = num_steps
    B = ls.mode.shape[0]
    s_cap = params.max_stages
    zs = _zero_stored(params)
    buf0 = _FlatBuf(
        obs=jax.tree_util.tree_map(
            lambda a: jnp.zeros((B, T) + a.shape, a.dtype), zs
        ),
        stage_idx=jnp.zeros((B, T), _i32),
        job_idx=jnp.zeros((B, T), _i32),
        num_exec_k=jnp.zeros((B, T), _i32),
        lgprob=jnp.zeros((B, T), jnp.float32),
        reward=jnp.zeros((B, T), jnp.float32),
        walls=jnp.zeros((B, T), jnp.float32),
        resets=jnp.zeros((B, T), _i32),
    )
    if lane_shard is not None:
        from ..parallel import constrain_lanes

        ls = constrain_lanes(ls, lane_shard)
        buf0 = constrain_lanes(buf0, lane_shard)
        if track:
            telemetry = constrain_lanes(telemetry, lane_shard)
    lane_idx = jnp.arange(B)

    def v_decide(ls, si, ne, keys, li, tm):
        def one(l, s_, n_, k_, i_, t_):
            rf = None if reset_fns is None else reset_fns(i_)
            return decide_micro_step(
                params, bank, l, s_, n_, k_, auto_reset, fulfill_bulk,
                reset_fn=rf, telemetry=t_,
            )

        return jax.vmap(one)(ls, si, ne, keys, li, tm)

    def v_drain(ls, keys, li, t_ref, tm):
        def one(l, k_, i_, tr, t_):
            rf = None if reset_fns is None else reset_fns(i_)
            return drain_to_decision(
                params, bank, l, k_, auto_reset, event_bulk,
                bulk_events, bulk_cycles, reset_fn=rf, t_ref=tr,
                telemetry=t_, bulk_fused=bulk_fused,
            )

        return jax.vmap(one)(ls, keys, li, t_ref, tm)

    def body(carry, _):
        if track:
            ls, k, t_ref, elapsed, ndec, buf, tm = carry
        else:
            (ls, k, t_ref, elapsed, ndec, buf), tm = carry, None
        tm_frozen = tm
        k, k_pol, k_dec, k_drain = jax.random.split(k, 4)
        env0 = ls.env
        wall0 = env0.wall_time  # [B]
        if rollout_duration is not None:
            over = elapsed >= rollout_duration
        else:
            over = jnp.zeros((B,), bool)

        # THE policy evaluation of this decision row (batch-level: one
        # net application, compaction cond on a scalar predicate)
        obs = jax.vmap(lambda e: observe(params, e))(env0)
        stage_idx, num_exec, aux = batch_policy_fn(k_pol, obs)
        lgprob, job, kk = aux_action_fields(
            aux, stage_idx, num_exec, s_cap
        )
        # heuristic batch policies may omit lgprob (scalar default);
        # the per-lane buffer scatters need a [B] leading axis
        lgprob = jnp.broadcast_to(
            jnp.asarray(lgprob, jnp.float32), stage_idx.shape
        )

        out = v_decide(
            ls, stage_idx, num_exec, jax.random.split(k_dec, B),
            lane_idx, tm,
        )
        if track:
            ls2, (decided, rw1, dt1, rs1), tm = out
        else:
            ls2, (decided, rw1, dt1, rs1) = out
        # discount reference for the span this decision opens (the
        # decide micro-step itself never advances the wall clock)
        t_ref2 = jnp.where(decided & ~over, wall0, t_ref)

        out = v_drain(
            ls2, jax.random.split(k_drain, B), lane_idx, t_ref2, tm
        )
        if track:
            ls3, (rw2, dt2, rs2), tm = out
        else:
            ls3, (rw2, dt2, rs2) = out
        reward = rw1 + rw2
        dt = dt1 + dt2
        reset = rs1 | rs2
        if health:
            hm = jax.vmap(state_health)(
                ls3.env, env0, reset
            ) | reward_health(reward)

        # frozen lanes (async budget exhausted): state untouched,
        # nothing recorded
        ls3 = jax.tree_util.tree_map(
            lambda a, b: jnp.where(
                over.reshape(over.shape + (1,) * (a.ndim - 1)), a, b
            ),
            ls, ls3,
        )
        if track:
            tm = jax.tree_util.tree_map(
                lambda a, b: jnp.where(over, a, b), tm_frozen, tm
            )
        if health:
            tm = _tm_orr(tm, health_mask=jnp.where(over, 0, hm))
        zero = jnp.float32(0.0)
        reward = jnp.where(over, zero, reward)
        dt = jnp.where(over, zero, dt)
        reset = reset & ~over
        dec = decided & ~over

        with annotate("collect/scatter"):
            slot = jnp.where(dec & (ndec < T), ndec, T)
            stored = jax.vmap(store_obs)(obs, env0)
            set_at = lambda b, s, v: b.at[s].set(v, mode="drop")  # noqa: E731
            buf = buf.replace(
                obs=jax.tree_util.tree_map(
                    lambda b, v: jax.vmap(set_at)(b, slot, v),
                    buf.obs, stored,
                ),
                stage_idx=jax.vmap(set_at)(buf.stage_idx, slot, stage_idx),
                job_idx=jax.vmap(set_at)(buf.job_idx, slot, job),
                num_exec_k=jax.vmap(set_at)(buf.num_exec_k, slot, kk),
                lgprob=jax.vmap(set_at)(buf.lgprob, slot, lgprob),
                walls=jax.vmap(set_at)(
                    buf.walls, slot, elapsed if use_elapsed else wall0
                ),
            )
            ndec2 = ndec + dec.astype(_i32)
            # span rewards belong to the most recent decision's slot;
            # spans before a resumed lane's first decision drop
            rslot = jnp.where((ndec2 > 0) & (ndec2 <= T), ndec2 - 1, T)
            buf = buf.replace(
                reward=jax.vmap(
                    lambda b, s, v: b.at[s].add(v, mode="drop")
                )(buf.reward, rslot, reward),
                resets=jax.vmap(
                    lambda b, s, v: b.at[s].max(v, mode="drop")
                )(buf.resets, rslot, reset.astype(_i32)),
            )
        carry = (ls3, k, t_ref2, elapsed + dt, ndec2, buf)
        return (carry + (tm,) if track else carry), None

    carry0 = (
        ls, rng, ls.env.wall_time, jnp.zeros((B,), jnp.float32),
        jnp.zeros((B,), _i32), buf0,
    )
    if track:
        carry0 = carry0 + (telemetry,)
    carry, _ = lax.scan(body, carry0, None, length=T)
    ls, elapsed, ndec, buf = carry[0], carry[3], carry[4], carry[5]
    if track:
        telemetry = carry[6]

    valid = jnp.arange(T)[None, :] < jnp.minimum(ndec, T)[:, None]
    final_t = elapsed if use_elapsed else ls.env.wall_time
    walls = jnp.where(valid, buf.walls, final_t[:, None])
    ro = Rollout(
        obs=buf.obs,
        stage_idx=jnp.where(valid, buf.stage_idx, -1),
        job_idx=buf.job_idx,
        num_exec_k=buf.num_exec_k,
        lgprob=buf.lgprob,
        reward=buf.reward,
        wall_times=jnp.concatenate([walls, final_t[:, None]], axis=1),
        valid=valid,
        resets=buf.resets > 0,
        final_state=ls.env,
        final_reset_count=ls.episodes,
    )
    return (ro, ls, telemetry) if track else (ro, ls)


@partial(
    jax.jit, static_argnums=(0, 2, 4),
    static_argnames=(
        "event_bulk", "bulk_events", "fulfill_bulk", "bulk_cycles",
        "lane_shard", "bulk_fused", "health",
    ),
)
def collect_flat_sync_batch(
    params: EnvParams,
    bank: WorkloadBank,
    batch_policy_fn: BatchPolicyFn,
    rng: jax.Array,
    num_steps: int,
    states: EnvState,  # [B]-batched, freshly reset
    telemetry=None,
    *,
    event_bulk: bool = True,
    bulk_events: int = 8,
    fulfill_bulk: bool = True,
    bulk_cycles: int = 1,
    lane_shard=None,
    bulk_fused: bool = True,
    health: bool = False,
) -> Rollout | tuple:
    """Single-eval flat equivalent of `vmap(collect_sync)`: one episode
    per lane from the given freshly-reset [B] states, exactly one policy
    evaluation per decision row (no `micro_groups` sizing — the scan
    length IS `num_steps`). With `telemetry` ([B]-leading), returns
    `(Rollout, Telemetry)`. `lane_shard` (static; a lane-axis
    `NamedSharding`) runs the collection SPMD over a dp mesh — see
    `_flat_collect_single_eval`. `health` (static) ORs the in-JIT
    sentinel mask into `telemetry.health_mask` per decision row."""
    ls = jax.vmap(init_loop_state)(states)
    out = _flat_collect_single_eval(
        params, bank, batch_policy_fn, rng, num_steps, ls,
        auto_reset=False, event_bulk=event_bulk,
        bulk_events=bulk_events, fulfill_bulk=fulfill_bulk,
        bulk_cycles=bulk_cycles, reset_fns=None, rollout_duration=None,
        use_elapsed=False, telemetry=telemetry, lane_shard=lane_shard,
        bulk_fused=bulk_fused, health=health,
    )
    return (out[0], out[2]) if telemetry is not None else out[0]


@partial(
    jax.jit, static_argnums=(0, 2, 4),
    static_argnames=(
        "event_bulk", "bulk_events", "fulfill_bulk", "bulk_cycles",
        "lane_shard", "bulk_fused", "health",
    ),
)
def collect_flat_async_batch(
    params: EnvParams,
    bank: WorkloadBank,
    batch_policy_fn: BatchPolicyFn,
    rng: jax.Array,
    num_steps: int,
    loop_states: LoopState,  # [B]-batched
    rollout_duration: jnp.ndarray | float = jnp.inf,
    seq_bases: jax.Array | None = None,  # [B] keys
    lane_salts: jnp.ndarray | int = 0,  # [B]
    reset_counts: jnp.ndarray | int = 0,  # [B]
    telemetry=None,
    *,
    event_bulk: bool = True,
    bulk_events: int = 8,
    fulfill_bulk: bool = True,
    bulk_cycles: int = 1,
    lane_shard=None,
    bulk_fused: bool = True,
    health: bool = False,
) -> tuple:
    """Single-eval flat equivalent of `vmap(collect_flat_async)`:
    persistent [B] lanes, fixed sim-time budget, group-shared mid-scan
    reset sequences from `fold_in(seq_bases[i], reset_counts[i] +
    completed_episodes)`. Budget granularity is the decision row (the
    same as `collect_async`). Returns `(Rollout, LoopState[,
    Telemetry])`. `lane_shard` (static) runs the collection SPMD over
    a dp mesh — see `_flat_collect_single_eval`; the returned
    `LoopState` carry stays lane-sharded, so the next iteration's
    collection starts from shards already resident on their devices."""
    rollout_duration = jnp.float32(rollout_duration)
    B = loop_states.mode.shape[0]
    if seq_bases is None:
        seq_bases = jax.random.split(rng, B)
    lane_salts = jnp.broadcast_to(
        jnp.asarray(lane_salts, _i32), (B,)
    )
    reset_counts = jnp.broadcast_to(
        jnp.asarray(reset_counts, _i32), (B,)
    )
    loop_states = loop_states.replace(episodes=jnp.zeros((B,), _i32))

    def reset_fns(lane_idx):
        def reset_fn(key, episodes):
            seq_rng = jax.random.fold_in(
                seq_bases[lane_idx], reset_counts[lane_idx] + episodes
            )
            return core.reset_pair(
                params, bank, seq_rng,
                jax.random.fold_in(seq_rng, lane_salts[lane_idx]),
            )

        return reset_fn

    out = _flat_collect_single_eval(
        params, bank, batch_policy_fn, rng, num_steps, loop_states,
        auto_reset=True, event_bulk=event_bulk, bulk_events=bulk_events,
        fulfill_bulk=fulfill_bulk, bulk_cycles=bulk_cycles,
        reset_fns=reset_fns, rollout_duration=rollout_duration,
        use_elapsed=True, telemetry=telemetry, lane_shard=lane_shard,
        bulk_fused=bulk_fused, health=health,
    )
    ro, ls = out[0], out[1]
    ro = ro.replace(final_reset_count=reset_counts + ls.episodes)
    if telemetry is not None:
        return ro, ls, out[2]
    return ro, ls


@partial(
    jax.jit, static_argnums=(0, 2, 4),
    static_argnames=(
        "micro_groups", "event_burst", "event_bulk", "bulk_events",
        "fulfill_bulk", "bulk_cycles", "bulk_fused", "health",
    ),
)
def collect_flat_async(
    params: EnvParams,
    bank: WorkloadBank,
    policy_fn: PolicyFn,
    rng: jax.Array,
    num_steps: int,
    loop_state: LoopState,
    rollout_duration: jnp.ndarray | float = jnp.inf,
    seq_base: jax.Array | None = None,
    lane_salt: jnp.ndarray | int = 0,
    reset_count: jnp.ndarray | int = 0,
    telemetry=None,
    *,
    micro_groups: int,
    event_burst: int = 1,
    event_bulk: bool = True,
    bulk_events: int = 8,
    fulfill_bulk: bool = False,
    bulk_cycles: int = 1,
    bulk_fused: bool = True,
    health: bool = False,
) -> tuple:
    """Flat-engine equivalent of `collect_async`: persistent lanes with a
    fixed sim-time budget per iteration and mid-scan auto-resets drawn
    from `fold_in(seq_base, reset_count + completed_episodes)` — the same
    group-shared job-sequence scheme as `collect_async` (lanes sharing
    `seq_base` replay identical sequences at equal reset ordinals).

    Takes and returns the full `LoopState` (a budget-frozen lane may be
    mid-FULFILL/EVENT phase, which `EnvState` alone cannot represent);
    the returned rollout's `final_reset_count` is the next reset ordinal,
    as in `collect_async`. The budget check runs at micro-step-group
    granularity rather than `collect_async`'s decision granularity, and
    micro-rewards a resumed lane accrues before its first decision of the
    chunk are dropped (see `_flat_collect`). With `telemetry`, returns
    `(Rollout, LoopState, Telemetry)`."""
    rollout_duration = jnp.float32(rollout_duration)
    if seq_base is None:
        seq_base = rng
    lane_salt = jnp.asarray(lane_salt, _i32)
    reset_count = jnp.asarray(reset_count, _i32)
    # episodes doubles as the chunk's reset ordinal offset; zero it so
    # `reset_count + episodes` counts from this chunk's start
    loop_state = loop_state.replace(episodes=jnp.zeros((), _i32))

    def reset_fn(key, episodes):
        seq_rng = jax.random.fold_in(seq_base, reset_count + episodes)
        return core.reset_pair(
            params, bank, seq_rng, jax.random.fold_in(seq_rng, lane_salt)
        )

    out = _flat_collect(
        params, bank, policy_fn, rng, num_steps, loop_state, micro_groups,
        auto_reset=True, event_burst=event_burst, event_bulk=event_bulk,
        bulk_events=bulk_events, fulfill_bulk=fulfill_bulk,
        bulk_cycles=bulk_cycles, reset_fn=reset_fn,
        rollout_duration=rollout_duration, use_elapsed=True,
        telemetry=telemetry, bulk_fused=bulk_fused, health=health,
    )
    ro, ls = out[0], out[1]
    ro = ro.replace(final_reset_count=reset_count + ls.episodes)
    if telemetry is not None:
        return ro, ls, out[2]
    return ro, ls
