"""Vanilla Policy Gradient / REINFORCE with the critic-free baseline
(reference trainers/vpg.py:11-50).

Per-lane advantage standardization and per-lane losses, summed and applied
in one optimizer step — the functional equivalent of the reference's
per-rollout `loss.backward()` accumulation followed by a single
`update_parameters()`. (The reference's rollout loop contains a latent
bug — `zip(data.values())` instead of `zip(*data.values())`, vpg.py:25 —
this implements the evident intent.)"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax

from ..schedulers.decima import DecimaAction
from .rollout import Rollout, stored_to_observation
from .trainer import CfgType, Trainer, TrainState

EPS = 1e-8


class VPG(Trainer):
    def __init__(self, agent_cfg: CfgType, env_cfg: CfgType,
                 train_cfg: CfgType, mesh=None,
                 obs_cfg: CfgType | None = None,
                 health_cfg: CfgType | None = None,
                 chaos_cfg: CfgType | None = None) -> None:
        super().__init__(agent_cfg, env_cfg, train_cfg, mesh=mesh,
                         obs_cfg=obs_cfg, health_cfg=health_cfg,
                         chaos_cfg=chaos_cfg)
        self.entropy_coeff = train_cfg.get("entropy_coeff", 0.0)

    def _update(self, state: TrainState, ro: Rollout):
        returns, baselines, buf, avg_num_jobs = (
            self._returns_and_baselines(state, ro)
        )
        B, T = ro.reward.shape
        adv = returns - baselines  # [B,T]
        w = (ro.valid & (ro.stage_idx >= 0)).astype(jnp.float32)
        n = jnp.maximum(w.sum(-1, keepdims=True), 1.0)
        mean = (adv * w).sum(-1, keepdims=True) / n
        var = ((adv - mean) ** 2 * w).sum(-1, keepdims=True) / jnp.maximum(
            n - 1, 1.0
        )
        adv = (adv - mean) / (jnp.sqrt(var) + EPS)

        actions = DecimaAction(
            stage_idx=ro.stage_idx, job_idx=ro.job_idx,
            num_exec=ro.num_exec_k,
        )

        def loss_fn(params):
            def lane(so, acts):
                feats = jax.vmap(
                    lambda s: self.scheduler.features(
                        stored_to_observation(self.bank, s)
                    )
                )(so)
                return self.scheduler.evaluate_actions(params, feats, acts)

            lgprobs, entropies = jax.vmap(lane)(ro.obs, actions)
            policy_losses = -(lgprobs * adv * w).sum(-1) / n[:, 0]
            entropy_losses = -(entropies * w).sum(-1) / n[:, 0]
            ent_coeff = self._entropy_coeff_at(
                self.entropy_coeff, state.iteration
            )
            losses = policy_losses + ent_coeff * entropy_losses
            return losses.sum(), {
                "policy_loss": policy_losses.mean(),
                "entropy_loss": entropy_losses.mean(),
            }

        (_, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            state.params
        )
        updates, opt_state = self.tx.update(
            grads, state.opt_state, state.params
        )
        params = optax.apply_updates(state.params, updates)
        stats = {
            "policy_loss": aux["policy_loss"],
            "entropy": aux["entropy_loss"],
            "avg_num_jobs_est": avg_num_jobs,
        }
        return state.replace(
            params=params, opt_state=opt_state, buf=buf
        ), stats
