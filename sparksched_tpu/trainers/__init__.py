"""RL training layer (reference trainers/): on-device rollouts, returns,
critic-free baselines, PPO and VPG."""

from .baselines import group_baselines  # noqa: F401
from .returns import (  # noqa: F401
    AvgNumJobsBuffer,
    differential_returns,
    discounted_returns,
    step_dts,
)
from .rollout import (  # noqa: F401
    Rollout,
    StoredObs,
    collect_async,
    collect_flat_async,
    collect_flat_sync,
    collect_sync,
    store_obs,
    stored_to_observation,
)
from .trainer import TrainState, Trainer, make_optimizer, make_trainer  # noqa: F401,E501
from .ppo import PPO  # noqa: F401
from .vpg import VPG  # noqa: F401
