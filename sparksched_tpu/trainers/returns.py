"""Return computation (reference trainers/utils/returns_calculator.py).

Both reference modes, as pure jnp functions over padded [B,T] rollouts:

- continuously discounted returns  R_k = r_k + e^{-beta*1e-3*dt_k} R_{k+1}
  (reference :67-76) — a reverse `lax.scan`;
- differential (average-reward) returns
  R_k = -(jobtime_k - dt_k * avg_num_jobs) + R_{k+1} (reference :52-65),
  with `avg_num_jobs` estimated from a moving window over the last
  `buff_cap` steps (reference CircularArray :6-21), kept here as a
  fixed-shape ring buffer that is part of the train state.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct
from jax import lax

_i32 = jnp.int32


def step_dts(wall_times: jnp.ndarray) -> jnp.ndarray:
    """dt[k] = wall_times[k+1] - wall_times[k] (reference :46)."""
    return wall_times[..., 1:] - wall_times[..., :-1]


def discounted_returns(
    rewards: jnp.ndarray, dts: jnp.ndarray, beta: float
) -> jnp.ndarray:
    """[B,T] continuously discounted returns (reference :67-76). Invalid
    (padded) steps must carry r=0; dt=0 there keeps the chain intact."""

    def body(R, x):
        r, dt = x
        R = r + jnp.exp(-beta * 1e-3 * dt) * R
        return R, R

    def one(rs, ds):
        _, out = lax.scan(
            body, jnp.float32(0.0), (rs, ds), reverse=True
        )
        return out

    return jax.vmap(one)(rewards, dts)


def differential_returns(
    rewards: jnp.ndarray, dts: jnp.ndarray, avg_num_jobs: jnp.ndarray
) -> jnp.ndarray:
    """[B,T] differential returns (reference :52-65):
    R_k = r_k + dt_k*avg_num_jobs + R_{k+1} (jobtime_k = -r_k)."""

    def body(R, x):
        r, dt = x
        R = r + dt * avg_num_jobs + R
        return R, R

    def one(rs, ds):
        _, out = lax.scan(body, jnp.float32(0.0), (rs, ds), reverse=True)
        return out

    return jax.vmap(one)(rewards, dts)


class AvgNumJobsBuffer(struct.PyTreeNode):
    """Ring buffer over the last `cap` (dt, reward) step records
    (reference CircularArray :6-21). Unfilled slots are zero and contribute
    nothing to either sum, exactly like the reference's zero-initialized
    array."""

    dt: jnp.ndarray  # f32[cap]
    r: jnp.ndarray  # f32[cap]
    ptr: jnp.ndarray  # i32 []

    @classmethod
    def create(cls, cap: int) -> "AvgNumJobsBuffer":
        return cls(
            dt=jnp.zeros(cap, jnp.float32),
            r=jnp.zeros(cap, jnp.float32),
            ptr=jnp.zeros((), _i32),
        )

    @property
    def cap(self) -> int:
        return self.dt.shape[0]

    def extend(self, dts: jnp.ndarray, rewards: jnp.ndarray,
               valid: jnp.ndarray) -> "AvgNumJobsBuffer":
        """Append flat step records, dropping dt<=0 steps (reference
        :81-84) and keeping only the newest `cap` if more arrive at once."""
        cap = self.cap
        dts, rewards, valid = (
            dts.reshape(-1), rewards.reshape(-1), valid.reshape(-1)
        )
        keep = valid & (dts > 0)
        m = dts.shape[0]
        # compact kept entries to the front, preserving order
        order = jnp.argsort(~keep, stable=True)
        dt_c, r_c = dts[order], rewards[order]
        n = keep.sum()
        drop = jnp.maximum(n - cap, 0)  # ref keeps new_data[-cap:]
        idx = jnp.arange(m)
        take = (idx >= drop) & (idx < n)
        pos = (self.ptr + idx - drop) % cap
        pos = jnp.where(take, pos, cap)  # out-of-bounds -> dropped
        return self.replace(
            dt=self.dt.at[pos].set(dt_c, mode="drop"),
            r=self.r.at[pos].set(r_c, mode="drop"),
            ptr=(self.ptr + n - drop) % cap,
        )

    def avg_num_jobs(self) -> jnp.ndarray:
        """-sum(rewards)/sum(dt) = total job-time per unit time
        (reference :86-89)."""
        return -self.r.sum() / jnp.maximum(self.dt.sum(), 1e-9)
