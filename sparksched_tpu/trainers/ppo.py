"""Proximal Policy Optimization (reference trainers/ppo.py:39-138).

The epochs x shuffled-minibatches loop, clipped surrogate loss, per-batch
advantage standardization, entropy bonus and approx-KL early stop are all
inside one jitted `lax.scan` over minibatches, so the whole update is a
single XLA program. Two deliberate deviations from the reference, both
forced by static shapes:

- minibatches are fixed-size slices of a padded permutation, so a batch's
  *effective* size varies slightly (masked means) instead of
  `len(dataset)//num_batches + 1`;
- the KL early stop zeroes out all subsequent updates in the scan instead
  of Python `break` — identical parameter trajectory, same wasted-compute
  tradeoff the reference makes when it keeps collecting after stopping.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax

from ..schedulers.decima import DecimaAction
from .rollout import Rollout, stored_to_observation
from .trainer import CfgType, Trainer, TrainState

EPS = 1e-8


def _masked_mean(x, w, n):
    return (x * w).sum() / n


class PPO(Trainer):
    def __init__(self, agent_cfg: CfgType, env_cfg: CfgType,
                 train_cfg: CfgType, mesh=None) -> None:
        super().__init__(agent_cfg, env_cfg, train_cfg, mesh=mesh)
        self.entropy_coeff = train_cfg.get("entropy_coeff", 0.0)
        self.clip_range = train_cfg.get("clip_range", 0.2)
        self.target_kl = train_cfg.get("target_kl", 0.01)
        self.num_epochs = train_cfg.get("num_epochs", 10)
        self.num_batches = train_cfg.get("num_batches", 3)

    def _features(self, so):
        return jax.vmap(
            lambda s: self.scheduler.features(
                stored_to_observation(self.bank, s)
            )
        )(so)

    def _update(self, state: TrainState, ro: Rollout):
        returns, baselines, buf, avg_num_jobs = (
            self._returns_and_baselines(state, ro)
        )
        B, T = ro.reward.shape
        bt = B * T
        ent_coeff = self._entropy_coeff_at(
            self.entropy_coeff, state.iteration
        )
        flat = jax.tree_util.tree_map(
            lambda a: a.reshape(bt, *a.shape[2:]), ro.obs
        )
        actions = DecimaAction(
            stage_idx=ro.stage_idx.reshape(bt),
            job_idx=ro.job_idx.reshape(bt),
            num_exec=ro.num_exec_k.reshape(bt),
        )
        advantages = (returns - baselines).reshape(bt)
        old_lgprobs = ro.lgprob.reshape(bt)
        valid = (ro.valid.reshape(bt)) & (actions.stage_idx >= 0)

        # shuffled fixed-size minibatches (reference ppo.py:64-71)
        nb = self.num_batches
        mbs = -(-bt // nb)
        rng = jax.random.fold_in(state.rng, 13)
        perms = jax.vmap(
            lambda k: jax.random.permutation(k, bt)
        )(jax.random.split(rng, self.num_epochs))
        pad = nb * mbs - bt
        perms = jnp.concatenate(
            [perms, jnp.zeros((self.num_epochs, pad), jnp.int32)], axis=1
        )
        in_range = jnp.concatenate(
            [jnp.ones((self.num_epochs, bt), bool),
             jnp.zeros((self.num_epochs, pad), bool)],
            axis=1,
        )
        mb_idx = perms.reshape(self.num_epochs * nb, mbs)
        mb_ok = in_range.reshape(self.num_epochs * nb, mbs)

        def loss_fn(params, idx, ok):
            so = jax.tree_util.tree_map(lambda a: a[idx], flat)
            feats = self._features(so)
            acts = jax.tree_util.tree_map(lambda a: a[idx], actions)
            lgprobs, entropies = self.scheduler.evaluate_actions(
                params, feats, acts
            )
            w = (valid[idx] & ok).astype(jnp.float32)
            n = jnp.maximum(w.sum(), 1.0)

            adv = advantages[idx]
            mean = _masked_mean(adv, w, n)
            var = ((adv - mean) ** 2 * w).sum() / jnp.maximum(n - 1, 1.0)
            adv = (adv - mean) / (jnp.sqrt(var) + EPS)

            log_ratio = lgprobs - old_lgprobs[idx]
            ratio = jnp.exp(log_ratio)
            pl1 = adv * ratio
            pl2 = adv * jnp.clip(
                ratio, 1 - self.clip_range, 1 + self.clip_range
            )
            policy_loss = -_masked_mean(jnp.minimum(pl1, pl2), w, n)
            entropy_loss = -_masked_mean(entropies, w, n)
            loss = policy_loss + ent_coeff * entropy_loss
            kl = _masked_mean((ratio - 1) - log_ratio, w, n)
            return loss, {
                "policy_loss": policy_loss,
                "entropy_loss": entropy_loss,
                "kl": jax.lax.stop_gradient(kl),
            }

        grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

        def body(carry, x):
            params, opt_state, stop, sums = carry
            idx, ok = x
            (_, aux), grads = grad_fn(params, idx, ok)
            kl_bad = (
                (aux["kl"] > 1.5 * self.target_kl)
                if self.target_kl is not None
                else jnp.bool_(False)
            )
            do_update = ~stop & ~kl_bad
            updates, new_opt = self.tx.update(grads, opt_state, params)
            new_params = optax.apply_updates(params, updates)
            sel = lambda a, b: jnp.where(do_update, a, b)  # noqa: E731
            params = jax.tree_util.tree_map(sel, new_params, params)
            opt_state = jax.tree_util.tree_map(sel, new_opt, opt_state)
            computed = (~stop).astype(jnp.float32)
            sums = {
                "policy_loss": sums["policy_loss"]
                + computed * aux["policy_loss"],
                "entropy_loss": sums["entropy_loss"]
                + computed * aux["entropy_loss"],
                "kl": sums["kl"] + computed * aux["kl"],
                "count": sums["count"] + computed,
            }
            return (params, opt_state, stop | kl_bad, sums), None

        zero = jnp.float32(0.0)
        sums0 = {"policy_loss": zero, "entropy_loss": zero, "kl": zero,
                 "count": zero}
        (params, opt_state, _, sums), _ = jax.lax.scan(
            body,
            (state.params, state.opt_state, jnp.bool_(False), sums0),
            (mb_idx, mb_ok),
        )
        n = jnp.maximum(sums["count"], 1.0)
        stats = {
            "policy_loss": jnp.abs(sums["policy_loss"] / n),
            "entropy": jnp.abs(sums["entropy_loss"] / n),
            "approx_kl_div": jnp.abs(sums["kl"] / n),
            "avg_num_jobs_est": avg_num_jobs,
        }
        return state.replace(
            params=params, opt_state=opt_state, buf=buf
        ), stats
