"""Proximal Policy Optimization (reference trainers/ppo.py:39-138).

The epochs x shuffled-minibatches loop, clipped surrogate loss, per-batch
advantage standardization, entropy bonus and approx-KL early stop are all
inside one jitted `lax.scan` over minibatches, so the whole update is a
single XLA program. Three deliberate deviations from the reference, the
first two forced by static shapes, the third by SPMD sharding:

- minibatches are fixed-size slices of a padded permutation, so a batch's
  *effective* size varies slightly (masked means) instead of
  `len(dataset)//num_batches + 1`;
- the KL early stop zeroes out all subsequent updates in the scan instead
  of Python `break` — identical parameter trajectory, same wasted-compute
  tradeoff the reference makes when it keeps collecting after stopping;
- minibatches are drawn as per-lane permutations of the TIME axis (every
  minibatch contains all B lanes x a random T-slice) instead of one
  global permutation of the flattened B*T dataset. A global shuffle
  forces XLA to all-gather the whole rollout onto every device of a dp
  mesh (measured: per-device update FLOPs flat in dp); keeping the lane
  axis intact lets the minibatch gather, the GNN recompute and the
  gradient all shard 1/dp, with one psum per grad step — the same
  reduction structure as the loss means. Identical on a single device
  modulo minibatch composition (every step still appears exactly once
  per epoch; advantage standardization stays per-minibatch and global
  across lanes).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax

from ..env.health import grad_health
from ..obs.tracing import annotate
from ..schedulers.decima import DecimaAction
from .rollout import Rollout, stored_to_observation
from .trainer import CfgType, Trainer, TrainState

EPS = 1e-8


def _masked_mean(x, w, n):
    return (x * w).sum() / n


class PPO(Trainer):
    def __init__(self, agent_cfg: CfgType, env_cfg: CfgType,
                 train_cfg: CfgType, mesh=None,
                 obs_cfg: CfgType | None = None,
                 health_cfg: CfgType | None = None,
                 chaos_cfg: CfgType | None = None) -> None:
        super().__init__(agent_cfg, env_cfg, train_cfg, mesh=mesh,
                         obs_cfg=obs_cfg, health_cfg=health_cfg,
                         chaos_cfg=chaos_cfg)
        self.entropy_coeff = train_cfg.get("entropy_coeff", 0.0)
        self.clip_range = train_cfg.get("clip_range", 0.2)
        self.target_kl = train_cfg.get("target_kl", 0.01)
        self.num_epochs = train_cfg.get("num_epochs", 10)
        self.num_batches = train_cfg.get("num_batches", 3)

    def _features(self, so):
        return jax.vmap(
            lambda s: self.scheduler.features(
                stored_to_observation(self.bank, s)
            )
        )(so)

    def _update(self, state: TrainState, ro: Rollout):
        returns, baselines, buf, avg_num_jobs = (
            self._returns_and_baselines(state, ro)
        )
        B, T = ro.reward.shape
        ent_coeff = self._entropy_coeff_at(
            self.entropy_coeff, state.iteration
        )
        actions = DecimaAction(
            stage_idx=ro.stage_idx,
            job_idx=ro.job_idx,
            num_exec=ro.num_exec_k,
        )  # [B,T]
        advantages = returns - baselines  # [B,T]
        old_lgprobs = ro.lgprob
        valid = ro.valid & (actions.stage_idx >= 0)

        # shuffled fixed-size minibatches (reference ppo.py:64-71),
        # shard-aligned: per-lane permutations of the time axis (see
        # module docstring). mb_idx[k] is i32[B, mbs] — lane b of
        # minibatch k takes steps mb_idx[k, b, :].
        nb = self.num_batches
        mbs = -(-T // nb)
        rng = jax.random.fold_in(state.rng, 13)
        # per-(epoch, lane) permutation keys via fold_in over a lane
        # iota — elementwise in the lane index, so each dp shard derives
        # its local lanes' keys from the replicated rng. The previous
        # vmap(split) derivation materialized one global [E*B] key strip
        # whose distribution onto lane shards lowered to
        # collective-permute chains (the resharding family the census
        # test forbids); fold_in keeps the update's collective set to
        # the reduction families alone.
        ep_keys = jax.random.split(rng, self.num_epochs)  # [E, 2]
        lane_keys = jax.vmap(
            lambda ek: jax.vmap(
                lambda b: jax.random.fold_in(ek, b)
            )(jnp.arange(B))
        )(ep_keys)  # [E, B]
        perms = jax.vmap(jax.vmap(lambda k: jax.random.permutation(k, T)))(
            lane_keys
        )  # [E, B, T]
        pad = nb * mbs - T
        perms = jnp.concatenate(
            [perms, jnp.zeros((self.num_epochs, B, pad), jnp.int32)],
            axis=-1,
        )
        # [E, B, nb, mbs] -> [E*nb, B, mbs]; ok masks by slot position
        # (identical across lanes and epochs: slots past T are padding)
        mb_idx = (
            perms.reshape(self.num_epochs, B, nb, mbs)
            .transpose(0, 2, 1, 3)
            .reshape(self.num_epochs * nb, B, mbs)
        )
        in_range = jnp.arange(nb * mbs) < T  # [nb*mbs]
        mb_ok = jnp.tile(
            in_range.reshape(nb, mbs), (self.num_epochs, 1)
        )

        def gather_t(a, idx):
            """a: [B, T, ...], idx: i32[B, m] -> [B, m, ...]."""
            return jax.vmap(lambda row, ii: row[ii])(a, idx)

        def loss_fn(params, idx, ok):
            so = jax.tree_util.tree_map(
                lambda a: gather_t(a, idx).reshape(
                    B * idx.shape[1], *a.shape[2:]
                ),
                ro.obs,
            )
            feats = self._features(so)
            acts = jax.tree_util.tree_map(
                lambda a: gather_t(a, idx).reshape(-1), actions
            )
            lgprobs, entropies = self.scheduler.evaluate_actions(
                params, feats, acts
            )
            w = (
                gather_t(valid, idx).reshape(-1)
                & jnp.tile(ok, (B,))
            ).astype(jnp.float32)
            n = jnp.maximum(w.sum(), 1.0)

            adv = gather_t(advantages, idx).reshape(-1)
            mean = _masked_mean(adv, w, n)
            var = ((adv - mean) ** 2 * w).sum() / jnp.maximum(n - 1, 1.0)
            adv = (adv - mean) / (jnp.sqrt(var) + EPS)

            log_ratio = lgprobs - gather_t(old_lgprobs, idx).reshape(-1)
            ratio = jnp.exp(log_ratio)
            pl1 = adv * ratio
            pl2 = adv * jnp.clip(
                ratio, 1 - self.clip_range, 1 + self.clip_range
            )
            policy_loss = -_masked_mean(jnp.minimum(pl1, pl2), w, n)
            entropy_loss = -_masked_mean(entropies, w, n)
            loss = policy_loss + ent_coeff * entropy_loss
            kl = _masked_mean((ratio - 1) - log_ratio, w, n)
            return loss, {
                "policy_loss": policy_loss,
                "entropy_loss": entropy_loss,
                "kl": jax.lax.stop_gradient(kl),
            }

        grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

        # in-JIT health sentinel (ISSUE 9, opt-in via the `health:`
        # block): a minibatch whose loss or gradients go non-finite is
        # SKIPPED on-device — exactly the KL-stop select pattern, so a
        # single NaN gradient can never reach the optimizer — and the
        # violation bits accumulate into a `health_mask` stat the
        # trainer's recovery loop reads. With health off the traced
        # program is bit-identical to the pre-health update (the
        # ppo_update budget pin).
        health = bool(getattr(self, "health_enabled", False))

        def body(carry, x):
            params, opt_state, stop, sums = carry
            idx, ok = x
            (loss_val, aux), grads = grad_fn(params, idx, ok)
            kl_bad = (
                (aux["kl"] > 1.5 * self.target_kl)
                if self.target_kl is not None
                else jnp.bool_(False)
            )
            do_update = ~stop & ~kl_bad
            if health:
                mb_mask = grad_health(loss=loss_val, grads=grads)
                do_update = do_update & (mb_mask == 0)
            updates, new_opt = self.tx.update(grads, opt_state, params)
            new_params = optax.apply_updates(params, updates)
            sel = lambda a, b: jnp.where(do_update, a, b)  # noqa: E731
            params = jax.tree_util.tree_map(sel, new_params, params)
            opt_state = jax.tree_util.tree_map(sel, new_opt, opt_state)
            computed = (~stop).astype(jnp.float32)
            new_sums = {
                "policy_loss": sums["policy_loss"]
                + computed * aux["policy_loss"],
                "entropy_loss": sums["entropy_loss"]
                + computed * aux["entropy_loss"],
                "kl": sums["kl"] + computed * aux["kl"],
                "count": sums["count"] + computed,
            }
            if health:
                new_sums["health"] = sums["health"] | mb_mask
            return (params, opt_state, stop | kl_bad, new_sums), None

        zero = jnp.float32(0.0)
        sums0 = {"policy_loss": zero, "entropy_loss": zero, "kl": zero,
                 "count": zero}
        if health:
            sums0["health"] = jnp.int32(0)
        with annotate("train/ppo_update"):
            (params, opt_state, _, sums), _ = jax.lax.scan(
                body,
                (state.params, state.opt_state, jnp.bool_(False), sums0),
                (mb_idx, mb_ok),
            )
        n = jnp.maximum(sums["count"], 1.0)
        stats = {
            "policy_loss": jnp.abs(sums["policy_loss"] / n),
            "entropy": jnp.abs(sums["entropy_loss"] / n),
            "approx_kl_div": jnp.abs(sums["kl"] / n),
            "avg_num_jobs_est": avg_num_jobs,
        }
        if health:
            # post-update params check: the skip gate should make this
            # unreachable, but a pre-existing non-finite parameter (a
            # corrupt resume that slipped the digest) must still trip
            stats["health_mask"] = sums["health"] | grad_health(
                params=params
            )
        return state.replace(
            params=params, opt_state=opt_state, buf=buf
        ), stats
