"""Concurrency ownership pass (ISSUE 19): thread-ownership +
lock-discipline static analysis over the serve/online host stack.

The traced-code passes (lint/contracts/jaxpr/memory) police what runs
ON device. This pass polices the host threads AROUND the device: the
serve pump, the HTTP handler pool, the optional harvester, the online
learner, the fleet collector. Its model has two declarative halves:

1. a **thread-role call graph** — roles are seeded at every
   `threading.Thread(target=...)` spawn site (the spawn's `name=` is
   the role: the shipped sites all name their threads `serve-pump`,
   `serve-harvester`, `online-learner`, `fleet-collector`,
   `serve-client-<i>`) plus `DECLARED_ENTRY_POINTS` for threads the
   stdlib spawns for us (the HTTP handler pool), then propagated
   through method calls (self-calls, dispatch-table references, and
   cross-class calls typed by `ATTR_TYPES`);
2. an **OWNERSHIP table** mapping each mutable attribute of the host
   classes to its owning role(s), its guarding lock, or a sanctioned
   `handoff` object (Queue / Event / Condition transfer — internally
   synchronized).

Rules (ids are what `# analysis: allow(<rule>)` pragmas and the JSON
report use):

- ``concurrency-nonowner-write``: a write to a role-owned attribute
  in a method reachable from a role other than the owner(s).
- ``concurrency-unlocked-shared``: an access to a lock-guarded
  attribute outside a `with <lock>:` block (`__init__` is exempt —
  construction happens-before thread start; so are declared
  caller-holds-the-lock helpers, see `LOCKED_BODY_FUNCS` and the
  `*_locked` naming convention, whose call sites must themselves
  hold a class lock); also an UNDECLARED attribute of a checked
  class written and accessed from >= 2 distinct non-main roles with
  no lock held at every site — the table must grow with the code.
- ``concurrency-lock-order``: a cycle in the lock-acquisition graph
  (edges: lock A held while lock B is acquired, lexically or through
  the call graph). Includes re-acquiring a held non-reentrant lock.
- ``concurrency-blocking-under-lock``: a blocking call
  (`block_until_ready` / `device_get`, an unbounded `Queue.get` /
  `Event.wait` / `Thread.join`, socket/pipe receives) made while
  holding a lock. `Condition.wait` on the condition being held is
  the sanctioned CV pattern (wait releases it); locks in `IO_LOCKS`
  exist to serialize a blocking channel and are exempt.
- ``concurrency-pump-blocking``: a blocking call from a method
  reachable from the `serve-pump` role outside the harvest boundary
  (`SERVE_HARVEST_FUNCS` + the drain/lifecycle funcs) — the
  role-propagated generalization of lint's file-scoped
  ``serve-host-sync`` (ISSUE 15), which it absorbs: the same
  boundary set, applied wherever pump-reachable code lives.
- ``concurrency-stale-ownership`` / ``concurrency-assert-placement``
  (package scan only): the OWNERSHIP table and the runtime
  `assert_owner` placements (`sparksched_tpu/ownership.py`) must
  match the code — a table entry whose class/attribute no longer
  exists, or an `assert_owner` call site that differs from
  `RUNTIME_ASSERT_SITES`, is itself a violation, so the static
  model, the runtime checks, and the code cannot drift apart.

The `main` thread is ownership-polymorphic: it constructs everything
and drives the whole stack in single-threaded benches, so reachability
from `main` alone never violates ownership (the runtime half —
`SPARKSCHED_DEBUG_OWNERSHIP=1` — covers dynamic single-owner binding).

Like lint, scoping keys on paths RELATIVE to the scanned root, so a
fixture tree mirroring the package layout gets identical treatment,
and ownership can be declared inline for fixture/new classes with
``# owner: <role>[, <role>]`` / ``# lock: <attr>`` pragmas on the
attribute's assignment line.
"""

from __future__ import annotations

import ast
import pathlib
import re
from dataclasses import dataclass, field
from typing import Any

from . import Violation
from .lint import (
    SERVE_HARVEST_FUNCS,
    _dotted,
    _import_table,
    _pragmas,
    iter_package_files,
)

# --- declarative model ------------------------------------------------------

KNOWN_ROLES = (
    "main",
    "serve-pump",
    "serve-http",
    "serve-harvester",
    "serve-client",
    "online-learner",
    "fleet-collector",
    "host-profiler",
)

# Threads the stdlib spawns for us: ThreadingHTTPServer's handler pool
# enters the package through ServeServer._submit_op.
DECLARED_ENTRY_POINTS: dict[tuple[str, str], str] = {
    ("serve/server.py", "ServeServer._submit_op"): "serve-http",
}

# Cross-class call typing: (class, attribute) -> candidate classes the
# attribute may hold at runtime. `self.<attr>.<meth>(...)` adds a call
# edge to every candidate that defines <meth>. Duck-typed slots list
# every shipped implementation (ServeServer serves a SessionStore, a
# batcher front, or a whole Router fleet through the same protocol).
ATTR_TYPES: dict[tuple[str, str], tuple[str, ...]] = {
    ("ServeServer", "store"): ("SessionStore", "Router"),
    ("ServeServer", "front"): (
        "ContinuousBatcher", "MicroBatcher", "Router",
    ),
    ("ServeServer", "collector"): ("FleetCollector",),
    ("ServeServer", "metrics"): ("MetricsRegistry",),
    ("ServeServer", "runlog"): ("RunLog",),
    ("ContinuousBatcher", "store"): ("SessionStore",),
    ("ContinuousBatcher", "metrics"): ("MetricsRegistry",),
    ("ContinuousBatcher", "runlog"): ("RunLog",),
    ("MicroBatcher", "store"): ("SessionStore",),
    ("MicroBatcher", "metrics"): ("MetricsRegistry",),
    ("MicroBatcher", "runlog"): ("RunLog",),
    ("SessionStore", "collector"): ("TrajectoryBuffer",),
    ("SessionStore", "metrics"): ("MetricsRegistry",),
    ("SessionStore", "_runlog"): ("RunLog",),
    ("Router", "collector"): ("TrajectoryBuffer",),
    ("Router", "metrics"): ("MetricsRegistry",),
    ("Router", "runlog"): ("RunLog",),
    ("OnlineLearner", "buffer"): ("TrajectoryBuffer",),
    ("OnlineLearner", "bus"): ("ParamBus",),
    ("OnlineLearner", "metrics"): ("MetricsRegistry",),
    ("OnlineLearner", "runlog"): ("RunLog",),
    ("ParamBus", "store"): ("SessionStore", "Router"),
    ("ParamBus", "metrics"): ("MetricsRegistry",),
    ("ParamBus", "runlog"): ("RunLog",),
    ("TrajectoryBuffer", "metrics"): ("MetricsRegistry",),
    ("FleetCollector", "backend"): ("Router", "SessionStore"),
    ("FleetCollector", "runlog"): ("RunLog",),
    ("ServeClient", "metrics"): ("MetricsRegistry",),
    ("ServeClient", "runlog"): ("RunLog",),
    ("MicroBatcher", "critpath"): ("CritPathAnalyzer",),
    ("ContinuousBatcher", "critpath"): ("CritPathAnalyzer",),
    ("FleetCollector", "critpath"): ("CritPathAnalyzer",),
    ("ServeServer", "hostprof"): ("HostProfiler",),
    ("OnlineLearner", "hostprof"): ("HostProfiler",),
}

# Plain-callable attributes (bound methods injected by composition
# roots): calling `self.<attr>()` calls the listed targets.
CALLABLE_ATTRS: dict[tuple[str, str], tuple[tuple[str, str], ...]] = {
    # server_from_config wires on_poll=bus.pump onto the pump loop
    ("ServeServer", "on_poll"): (("ParamBus", "pump"),),
}

# spec forms:
#   ("role", ("<role>", ...)) - single-owner state; listed roles are
#       the sanctioned drivers (more than one ONLY when the modes are
#       mutually exclusive by contract and the runtime binding picks
#       the live one, e.g. FleetCollector's ride-the-pump vs own-thread
#       modes); writes from any other non-main role violate.
#   ("lock", "<attr>")        - every access outside __init__ must
#       hold the lock (Condition aliases resolve to their Lock).
#   ("handoff", "<why>")      - internally-synchronized transfer
#       object (Queue / Event); excluded from attribute checks.
OWNERSHIP: dict[str, dict[str, tuple[str, Any]]] = {
    "SessionStore": {
        # device state + session bookkeeping: the single serving thread
        "_stores": ("role", ("serve-pump",)),
        "_model_params": ("role", ("serve-pump",)),
        "params_version": ("role", ("serve-pump",)),
        "_last_good_params": ("role", ("serve-pump",)),
        "_last_good_version": ("role", ("serve-pump",)),
        "last_spans": ("role", ("serve-pump",)),
        "_calls": ("role", ("serve-pump",)),
        "_rings": ("role", ("serve-pump",)),
        "_ring_pot": ("role", ("serve-pump",)),
        "_ring_drained": ("role", ("serve-pump",)),
        "_ring_pending": ("role", ("serve-pump",)),
        "_ring_mute": ("role", ("serve-pump",)),
        "ring_sink": ("role", ("serve-pump",)),
        "_live": ("role", ("serve-pump",)),
        "_quarantined": ("role", ("serve-pump",)),
        "_slot_of": ("role", ("serve-pump",)),
        "_sid_of": ("role", ("serve-pump",)),
        "_group_of": ("role", ("serve-pump",)),
        "_gen": ("role", ("serve-pump",)),
        "_free_sids": ("role", ("serve-pump",)),
        "_free_slots": ("role", ("serve-pump",)),
        "_cold": ("role", ("serve-pump",)),
        "_wb_pending": ("role", ("serve-pump",)),
        "_last_use": ("role", ("serve-pump",)),
        "_tick": ("role", ("serve-pump",)),
        "wall_split": ("role", ("serve-pump",)),
        "stats": ("role", ("serve-pump",)),
        "_harvester": ("role", ("serve-pump",)),
        # the serving<->harvester handshake: deque + claim flags, all
        # touched under the condition only
        "_inflight": ("lock", "_harvest_cv"),
        "_harvester_stop": ("lock", "_harvest_cv"),
    },
    "ContinuousBatcher": {
        "_queues": ("role", ("serve-pump",)),
        "_rotation": ("role", ("serve-pump",)),
        "_skips": ("role", ("serve-pump",)),
    },
    "MicroBatcher": {
        "_pending": ("role", ("serve-pump",)),
    },
    "ServeServer": {
        # "no locks by construction": only the pump thread touches
        # tenancy/quota bookkeeping (handlers just enqueue ops)
        "_tenant_of": ("role", ("serve-pump",)),
        "_sessions_by_tenant": ("role", ("serve-pump",)),
        "_inflight_by_tenant": ("role", ("serve-pump",)),
        "_q": ("handoff", "queue.Queue is internally locked"),
        "_stop": ("handoff", "threading.Event"),
    },
    "Router": {
        # fleet bookkeeping rides whoever drives the router: the serve
        # pump in the server integration, or the collector thread in
        # FleetCollector.start() mode - mutually exclusive by the
        # collector's contract; the runtime binding enforces the live
        # single owner.
        "params_version": ("role", ("serve-pump", "fleet-collector")),
        "stats": ("role", ("serve-pump", "fleet-collector")),
        "_rid": ("role", ("serve-pump", "fleet-collector")),
        "_tickets": ("role", ("serve-pump", "fleet-collector")),
        "_replies": ("role", ("serve-pump", "fleet-collector")),
        "_reply_owner": ("role", ("serve-pump", "fleet-collector")),
        "_sid_map": ("role", ("serve-pump", "fleet-collector")),
        "_failed": ("role", ("serve-pump", "fleet-collector")),
        "_stopped": ("role", ("serve-pump", "fleet-collector")),
        "_replicas": ("role", ("serve-pump", "fleet-collector")),
        "_ring_next": ("role", ("serve-pump", "fleet-collector")),
    },
    "TrajectoryBuffer": {
        # producer (pump: add/ingest_chunk/on_close) vs consumer
        # (learner: drain/requeue) - the one genuinely two-role
        # structure; everything goes through the lock
        "_open": ("lock", "_lock"),
        "_done": ("lock", "_lock"),
        "stats": ("lock", "_lock"),
    },
    "ParamBus": {
        "_pending": ("lock", "_lock"),
        "stats": ("lock", "_lock"),
        # probation state is serving-side only (pump applies / judges)
        "_proven": ("role", ("serve-pump",)),
        "_probation": ("role", ("serve-pump",)),
    },
    "OnlineLearner": {
        "state": ("role", ("online-learner",)),
        "version": ("role", ("online-learner",)),
        "stats": ("role", ("online-learner",)),
        "history": ("role", ("online-learner",)),
    },
    "RunLog": {
        # "thread-safe by contract: the JIT hooks fire from whatever
        # thread compiles"
        "_fp": ("lock", "_lock"),
        "_closed": ("lock", "_lock"),
        "_rotations": ("lock", "_lock"),
    },
    "FleetCollector": {
        # ride-the-owner-loop (maybe_scrape on the pump) or own thread
        # (start(), poll-safe backends) - mutually exclusive modes
        "_prev": ("role", ("serve-pump", "fleet-collector")),
        "_last_scrape": ("role", ("serve-pump", "fleet-collector")),
        "last_status": ("role", ("serve-pump", "fleet-collector")),
        "stats": ("role", ("serve-pump", "fleet-collector")),
    },
    "CritPathAnalyzer": {
        # ingest path (observe/add) rides the serve pump that finishes
        # tickets; the exemplar-window flush path additionally rides
        # the fleet collector's scrape (idle-tail shipping) — the same
        # mutually-exclusive-drivers contract as FleetCollector
        "profile": ("role", ("serve-pump",)),
        "by_tenant": ("role", ("serve-pump",)),
        "by_replica": ("role", ("serve-pump",)),
        "_seq": ("role", ("serve-pump",)),
        "_exemplars": ("role", ("serve-pump", "fleet-collector")),
        "_window_start": ("role", ("serve-pump", "fleet-collector")),
        "stats": ("role", ("serve-pump", "fleet-collector")),
    },
    "HostProfiler": {
        # sample tables are sampler-thread-owned; start/stop (which
        # touch _started_at/_elapsed_s) run on the constructing main
        # thread before spawn / after join — happens-before ordered
        "_counts": ("role", ("host-profiler",)),
        "_samples": ("role", ("host-profiler",)),
        "_elapsed_s": ("role", ("host-profiler",)),
        "_started_at": ("role", ("host-profiler",)),
        "_stop": ("handoff", "threading.Event"),
    },
    "MetricsRegistry": {
        # shared by every role that instruments (pump, client workers,
        # learner, collector): the one registry-wide lock (ISSUE 19
        # race fix - see obs/metrics.py docstring for the cost math)
        "counters": ("lock", "_lock"),
        "gauges": ("lock", "_lock"),
        "hists": ("lock", "_lock"),
    },
}

# Helpers whose body runs with a lock held without a lexical `with`:
# either caller-holds-the-lock contracts (TrajectoryBuffer._count, the
# `*_locked` suffix convention) or self-managed non-blocking acquires
# (RunLog._teardown - signal context, see its docstring).
LOCKED_BODY_FUNCS: dict[tuple[str, str], str] = {
    ("TrajectoryBuffer", "_count"): "_lock",
    ("RunLog", "_teardown"): "_lock",
}

# Locks whose purpose is serializing a blocking channel - holding them
# across the blocking call IS the design (ServeClient's sync HTTP
# connection), so concurrency-blocking-under-lock exempts them.
IO_LOCKS: frozenset[tuple[str, str]] = frozenset({
    ("ServeClient", "_sync_lock"),
})

# Files whose pump-reachable blocking calls are the product: the
# router's pipe round-trips ARE the replica transport (mirrors lint's
# HOST_FILES rationale for the generic host-sync rule).
PUMP_BLOCKING_EXEMPT_FILES = frozenset({"serve/router.py"})

# The harvest boundary for concurrency-pump-blocking: lint's
# SERVE_HARVEST_FUNCS (the sanctioned sync stage of the pipelined
# front) plus the drain/lifecycle methods that block by contract.
PUMP_BOUNDARY_FUNCS = frozenset(SERVE_HARVEST_FUNCS) | {
    "flush", "stop", "stop_harvester", "close_all", "drain_ring",
    "warmup",
}

# Runtime assert_owner placements (sparksched_tpu/ownership.py): the
# hot entry points of every role-owned structure. The package scan
# fails (concurrency-assert-placement) when the assert_owner calls
# found in source differ from this table, and the tests cross-validate
# the roles against OWNERSHIP - the three layers cannot drift apart.
RUNTIME_ASSERT_SITES: dict[tuple[str, str], tuple[str, ...]] = {
    ("serve/session.py", "SessionStore.create"): ("serve-pump",),
    ("serve/session.py", "SessionStore.close"): ("serve-pump",),
    ("serve/session.py", "SessionStore.decide"): ("serve-pump",),
    ("serve/session.py", "SessionStore.decide_batch"):
        ("serve-pump",),
    ("serve/session.py", "SessionStore.dispatch_batch"):
        ("serve-pump",),
    ("serve/session.py", "SessionStore.set_params"): ("serve-pump",),
    ("serve/session.py", "ContinuousBatcher.submit"): ("serve-pump",),
    ("serve/session.py", "ContinuousBatcher.pump"): ("serve-pump",),
    ("serve/session.py", "MicroBatcher.submit"): ("serve-pump",),
    ("serve/session.py", "MicroBatcher.flush"): ("serve-pump",),
    ("serve/server.py", "ServeServer._handle_op"): ("serve-pump",),
    ("serve/router.py", "Router.submit"):
        ("serve-pump", "fleet-collector"),
    ("serve/router.py", "Router.poll"):
        ("serve-pump", "fleet-collector"),
    ("online/bus.py", "ParamBus.publish"): ("online-learner",),
    ("online/bus.py", "ParamBus.pump"): ("serve-pump",),
    ("online/learner.py", "OnlineLearner.step"): ("online-learner",),
    ("obs/fleet.py", "FleetCollector.scrape"):
        ("serve-pump", "fleet-collector"),
    ("obs/critpath.py", "CritPathAnalyzer.add"): ("serve-pump",),
    ("obs/hostprof.py", "HostProfiler._sample"): ("host-profiler",),
}

# mutating container methods: a call `self.<attr>.<m>(...)` with m in
# this set is a WRITE to <attr>
_MUTATORS = frozenset({
    "append", "appendleft", "extend", "extendleft", "insert", "add",
    "remove", "discard", "pop", "popleft", "popitem", "clear",
    "update", "setdefault", "sort", "reverse", "put", "put_nowait",
})

_SOCKET_BLOCKING = frozenset({"accept", "recv", "recvfrom",
                              "getresponse"})

_OWNER_PRAGMA_RE = re.compile(
    r"#\s*owner:\s*([a-z\-]+(?:\s*,\s*[a-z\-]+)*)")
_LOCK_PRAGMA_RE = re.compile(r"#\s*lock:\s*([A-Za-z_]\w*)")

_last_scan_count = 0


def last_scan_count() -> int:
    return _last_scan_count


def runtime_assert_expectations() -> dict[tuple[str, str],
                                          tuple[str, ...]]:
    """The declared assert_owner placements, for cross-validation in
    tests (static table <-> runtime checks <-> code)."""
    return dict(RUNTIME_ASSERT_SITES)


def _spec_pragmas(source: str) -> dict[int, tuple[str, Any]]:
    """lineno -> ownership spec declared inline on that line."""
    out: dict[int, tuple[str, Any]] = {}
    for i, line in enumerate(source.splitlines(), start=1):
        m = _OWNER_PRAGMA_RE.search(line)
        if m:
            roles = tuple(r.strip() for r in m.group(1).split(","))
            out[i] = ("role", roles)
            continue
        m = _LOCK_PRAGMA_RE.search(line)
        if m:
            out[i] = ("lock", m.group(1))
    return out


# --- per-file collection ----------------------------------------------------


@dataclass
class _Access:
    attr: str
    write: bool
    lineno: int
    held: frozenset[str]
    in_init: bool


@dataclass
class _FuncInfo:
    cls: str
    name: str  # "meth" or "meth.nested"
    relpath: str
    accesses: list[_Access] = field(default_factory=list)
    # (method-name referenced via self.<name>, lineno, held)
    self_refs: list[tuple[str, int, frozenset[str]]] = \
        field(default_factory=list)
    # bare-Name loads (resolve nested defs later)
    name_refs: list[tuple[str, int, frozenset[str]]] = \
        field(default_factory=list)
    # (attr, meth, lineno, held) for self.<attr>.<meth>(...)
    typed_calls: list[tuple[str, str, int, frozenset[str]]] = \
        field(default_factory=list)
    callable_refs: list[tuple[str, int, frozenset[str]]] = \
        field(default_factory=list)
    # (lock-attr acquired, lineno, locks held before)
    acquisitions: list[tuple[str, int, frozenset[str]]] = \
        field(default_factory=list)
    # (description, lineno, held, wait-on-lock-attr-or-None)
    blocking: list[tuple[str, int, frozenset[str], str | None]] = \
        field(default_factory=list)
    # (target key-in-class, role, lineno)
    spawns: list[tuple[str, str, int]] = field(default_factory=list)
    assert_roles: tuple[str, ...] | None = None
    assert_line: int = 0


@dataclass
class _ClassInfo:
    name: str
    relpath: str
    locks: dict[str, str] = field(default_factory=dict)  # attr->root
    events: set[str] = field(default_factory=set)
    queues: set[str] = field(default_factory=set)
    threads: set[str] = field(default_factory=set)
    method_names: set[str] = field(default_factory=set)
    funcs: dict[str, _FuncInfo] = field(default_factory=dict)
    pragma_specs: dict[str, tuple[str, Any]] = \
        field(default_factory=dict)
    assigned_attrs: set[str] = field(default_factory=set)
    spawns_threads: bool = False


def _canonical(imports: dict[str, str], node: ast.AST) -> str:
    name = _dotted(node)
    if not name:
        return ""
    head, _, rest = name.partition(".")
    head = imports.get(head, head)
    return f"{head}.{rest}" if rest else head


def _self_attr(node: ast.AST) -> str | None:
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _root_self_attr(node: ast.AST) -> str | None:
    """The `x` of any `self.x[...].y...` receiver chain."""
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        a = _self_attr(node)
        if a is not None:
            return a
        node = node.value
    return None


def _is_bounded(call: ast.Call) -> bool:
    if call.args:
        return True
    return any(kw.arg in ("timeout", "block") for kw in call.keywords)


class _FuncVisitor(ast.NodeVisitor):
    def __init__(self, cls: _ClassInfo, info: _FuncInfo,
                 imports: dict[str, str]) -> None:
        self.cls = cls
        self.info = info
        self.imports = imports
        self.held: list[str] = []  # resolved lock attrs, innermost last

    def _held(self) -> frozenset[str]:
        return frozenset(self.held)

    def _in_init(self) -> bool:
        return self.info.name == "__init__"

    # -- scoping ----------------------------------------------------------

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        # a nested def runs later, on whatever thread calls it - fresh
        # lock context, own node in the role graph
        nested = _FuncInfo(self.cls.name,
                           f"{self.info.name}.{node.name}",
                           self.info.relpath)
        self.cls.funcs[nested.name] = nested
        sub = _FuncVisitor(self.cls, nested, self.imports)
        for stmt in node.body:
            sub.visit(stmt)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_With(self, node: ast.With) -> None:
        got: list[str] = []
        for item in node.items:
            a = _self_attr(item.context_expr)
            if a is not None and a in self.cls.locks:
                root = self.cls.locks[a]
                self.info.acquisitions.append(
                    (root, node.lineno, self._held()))
                self.held.append(root)
                got.append(root)
        for item in node.items:
            if item.optional_vars is not None:
                self.visit(item.optional_vars)
        for stmt in node.body:
            self.visit(stmt)
        for _ in got:
            self.held.pop()

    visit_AsyncWith = visit_With

    # -- accesses ----------------------------------------------------------

    def visit_Attribute(self, node: ast.Attribute) -> None:
        a = _self_attr(node)
        if a is not None:
            write = isinstance(node.ctx, (ast.Store, ast.Del))
            self.info.accesses.append(_Access(
                a, write, node.lineno, self._held(), self._in_init()))
            if not write and a in self.cls.method_names:
                self.info.self_refs.append(
                    (a, node.lineno, self._held()))
        self.generic_visit(node)

    def visit_Subscript(self, node: ast.Subscript) -> None:
        if isinstance(node.ctx, (ast.Store, ast.Del)):
            a = _root_self_attr(node.value)
            if a is not None:
                self.info.accesses.append(_Access(
                    a, True, node.lineno, self._held(),
                    self._in_init()))
        self.generic_visit(node)

    def visit_Name(self, node: ast.Name) -> None:
        if isinstance(node.ctx, ast.Load):
            self.info.name_refs.append(
                (node.id, node.lineno, self._held()))

    def visit_Call(self, node: ast.Call) -> None:
        canon = _canonical(self.imports, node.func)
        f = node.func

        if canon == "threading.Thread":
            self._spawn(node)
        elif (canon.endswith("ownership.assert_owner")
                or canon == "assert_owner"):
            roles = tuple(
                a.value for a in node.args[1:]
                if isinstance(a, ast.Constant)
                and isinstance(a.value, str))
            if roles:
                self.info.assert_roles = roles
                self.info.assert_line = node.lineno
        elif canon in ("jax.block_until_ready", "jax.device_get"):
            self.info.blocking.append(
                (canon, node.lineno, self._held(), None))

        if isinstance(f, ast.Attribute):
            m = f.attr
            recv = f.value
            recv_attr = _self_attr(recv)
            if (m == "block_until_ready"
                    and canon != "jax.block_until_ready"):
                # method form `x.block_until_ready()`; the module
                # form was already recorded by the canonical match
                self.info.blocking.append(
                    ("block_until_ready", node.lineno, self._held(),
                     None))
            elif m in _SOCKET_BLOCKING:
                self.info.blocking.append(
                    (f"socket/pipe .{m}()", node.lineno, self._held(),
                     None))
            if recv_attr is not None:
                if (m == "get" and recv_attr in self.cls.queues
                        and not _is_bounded(node)):
                    self.info.blocking.append(
                        (f"unbounded {recv_attr}.get()", node.lineno,
                         self._held(), None))
                elif (m == "wait"
                        and (recv_attr in self.cls.events
                             or recv_attr in self.cls.locks)
                        and not _is_bounded(node)):
                    lock = self.cls.locks.get(recv_attr)
                    self.info.blocking.append(
                        (f"unbounded {recv_attr}.wait()", node.lineno,
                         self._held(), lock))
                elif (m == "join" and recv_attr in self.cls.threads
                        and not _is_bounded(node)):
                    self.info.blocking.append(
                        (f"unbounded {recv_attr}.join()", node.lineno,
                         self._held(), None))
            # typed cross-class call: self.<attr>.<meth>(...)
            r2 = _self_attr(recv)
            if r2 is not None and m not in _MUTATORS:
                self.info.typed_calls.append(
                    (r2, m, node.lineno, self._held()))
            # container mutation: self.<attr>.append(...) etc.
            root = _root_self_attr(recv)
            if root is not None and m in _MUTATORS:
                self.info.accesses.append(_Access(
                    root, True, node.lineno, self._held(),
                    self._in_init()))
            # callable attribute: self.on_poll(...)
            a = _self_attr(f)
            if a is not None and a not in self.cls.method_names:
                self.info.callable_refs.append(
                    (a, node.lineno, self._held()))
        self.generic_visit(node)

    def _spawn(self, node: ast.Call) -> None:
        self.cls.spawns_threads = True
        target = None
        name = None
        for kw in node.keywords:
            if kw.arg == "target":
                target = kw.value
            elif kw.arg == "name":
                name = kw.value
        if target is None:
            return
        key = None
        tname = None
        a = _self_attr(target)
        if a is not None:
            key, tname = a, a
        elif isinstance(target, ast.Name):
            key = f"{self.info.name}.{target.id}"
            tname = target.id
        if key is None:
            return
        role = tname or ""
        if isinstance(name, ast.Constant) and isinstance(name.value,
                                                         str):
            role = name.value
        elif isinstance(name, ast.JoinedStr):
            # f"serve-client-{i}" -> role "serve-client"
            parts = [v.value for v in name.values
                     if isinstance(v, ast.Constant)]
            role = "".join(parts).rstrip("-") or role
        self.info.spawns.append((key, role, node.lineno))


class _FileScan:
    def __init__(self, relpath: str, source: str,
                 tree: ast.AST) -> None:
        self.relpath = relpath
        self.pragmas = _pragmas(source)
        self.spec_pragmas = _spec_pragmas(source)
        self.imports = _import_table(tree)
        self.classes: dict[str, _ClassInfo] = {}
        for node in tree.body:
            if isinstance(node, ast.ClassDef):
                self._scan_class(node)

    def _scan_class(self, cnode: ast.ClassDef) -> None:
        cls = _ClassInfo(cnode.name, self.relpath)
        self.classes[cnode.name] = cls
        for stmt in cnode.body:
            if isinstance(stmt, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                cls.method_names.add(stmt.name)
        # pass 1: discover locks / events / queues / threads and
        # inline ownership pragmas from every `self.X = ...`
        for node in ast.walk(cnode):
            if isinstance(node, ast.Assign):
                targets = []
                for t in node.targets:
                    targets.extend(
                        t.elts if isinstance(t, (ast.Tuple, ast.List))
                        else [t])
            elif isinstance(node, ast.AnnAssign) and node.value:
                targets = [node.target]
            else:
                continue
            for tgt in targets:
                a = _self_attr(tgt)
                if a is None:
                    continue
                cls.assigned_attrs.add(a)
                spec = self.spec_pragmas.get(node.lineno)
                if spec is not None:
                    cls.pragma_specs[a] = spec
                if not isinstance(node.value, ast.Call):
                    continue
                canon = _canonical(self.imports, node.value.func)
                if canon in ("threading.Lock", "threading.RLock"):
                    cls.locks[a] = a
                elif canon == "threading.Condition":
                    arg = (node.value.args[0]
                           if node.value.args else None)
                    root = _self_attr(arg) if arg is not None else None
                    cls.locks[a] = cls.locks.get(root, root) if root \
                        else a
                elif canon == "threading.Event":
                    cls.events.add(a)
                elif canon in ("queue.Queue", "queue.SimpleQueue",
                               "queue.LifoQueue",
                               "queue.PriorityQueue"):
                    cls.queues.add(a)
                elif canon == "threading.Thread":
                    cls.threads.add(a)
        # pass 2: walk method bodies
        for stmt in cnode.body:
            if isinstance(stmt, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                info = _FuncInfo(cls.name, stmt.name, self.relpath)
                cls.funcs[stmt.name] = info
                v = _FuncVisitor(cls, info, self.imports)
                for s in stmt.body:
                    v.visit(s)


# --- package-wide analysis --------------------------------------------------


def _lockid(cls: _ClassInfo, attr: str) -> tuple[str, str]:
    return (cls.name, cls.locks.get(attr, attr))


def _class_spec(cls: _ClassInfo, attr: str) -> tuple[str, Any] | None:
    spec = OWNERSHIP.get(cls.name, {}).get(attr)
    if spec is None:
        spec = cls.pragma_specs.get(attr)
    return spec


def _locked_body_lock(cls: _ClassInfo, fname: str) -> str | None:
    """The lock a helper's body is contractually holding, if any."""
    base = fname.split(".")[-1]
    declared = LOCKED_BODY_FUNCS.get((cls.name, base))
    if declared is not None:
        return cls.locks.get(declared, declared)
    if base.endswith("_locked"):
        roots = set(cls.locks.values())
        if len(roots) == 1:
            return next(iter(roots))
        if roots:
            return sorted(roots)[0]
    return None


class _Analysis:
    def __init__(self, scans: list[_FileScan], strict: bool) -> None:
        self.scans = scans
        self.strict = strict
        self.found: list[Violation] = []
        # class name -> (_ClassInfo); later definition wins (fixture
        # trees are small; the shipped class names are unique)
        self.classes: dict[str, _ClassInfo] = {}
        for sc in scans:
            self.classes.update(sc.classes)
        self.pragmas: dict[str, dict[int, set[str]]] = {
            sc.relpath: sc.pragmas for sc in scans}
        # node key: (class name, func name)
        self.roles: dict[tuple[str, str], set[str]] = {}
        self.edges: dict[tuple[str, str],
                         list[tuple[tuple[str, str], int,
                                    frozenset[str], str]]] = {}

    # -- plumbing ----------------------------------------------------------

    def _emit(self, rule: str, relpath: str, lineno: int,
              detail: str) -> None:
        allowed = self.pragmas.get(relpath, {}).get(lineno, set())
        if rule in allowed:
            return
        self.found.append(Violation(
            "concurrency", rule, f"{relpath}:{lineno}", detail))

    def _nodes(self):
        for cls in self.classes.values():
            for info in cls.funcs.values():
                yield cls, info

    # -- role graph --------------------------------------------------------

    def _build_edges(self) -> None:
        for cls, info in self._nodes():
            key = (cls.name, info.name)
            out = self.edges.setdefault(key, [])
            for ref, ln, held in info.self_refs:
                if ref in cls.funcs:
                    out.append(((cls.name, ref), ln, held,
                                cls.relpath))
            for nm, ln, held in info.name_refs:
                nested = f"{info.name}.{nm}"
                if nested in cls.funcs:
                    out.append(((cls.name, nested), ln, held,
                                cls.relpath))
            for attr, meth, ln, held in info.typed_calls:
                for cand in ATTR_TYPES.get((cls.name, attr), ()):
                    tc = self.classes.get(cand)
                    if tc is not None and meth in tc.funcs:
                        out.append(((cand, meth), ln, held,
                                    cls.relpath))
            for attr, ln, held in info.callable_refs:
                for tgt in CALLABLE_ATTRS.get((cls.name, attr), ()):
                    tc = self.classes.get(tgt[0])
                    if tc is not None and tgt[1] in tc.funcs:
                        out.append((tgt, ln, held, cls.relpath))

    def _propagate_roles(self) -> None:
        work: list[tuple[str, str]] = []

        def seed(key: tuple[str, str], role: str) -> None:
            got = self.roles.setdefault(key, set())
            if role not in got:
                got.add(role)
                work.append(key)

        for cls, info in self._nodes():
            for tkey, role, _ln in info.spawns:
                if tkey in cls.funcs:
                    seed((cls.name, tkey), role)
        for (relpath, qual), role in DECLARED_ENTRY_POINTS.items():
            cname, _, fname = qual.partition(".")
            cls = self.classes.get(cname)
            if (cls is not None and cls.relpath == relpath
                    and fname in cls.funcs):
                seed((cname, fname), role)
        while work:
            key = work.pop()
            for callee, _ln, _held, _rp in self.edges.get(key, ()):
                for role in self.roles.get(key, ()):
                    seed(callee, role)

    def _node_roles(self, cls: _ClassInfo, fname: str) -> set[str]:
        return {r for r in self.roles.get((cls.name, fname), set())
                if r != "main"}

    # -- rule: ownership / locking -----------------------------------------

    def _checked(self, cls: _ClassInfo) -> bool:
        return (cls.name in OWNERSHIP or bool(cls.pragma_specs)
                or cls.spawns_threads)

    def _check_attrs(self) -> None:
        for cls in self.classes.values():
            if not self._checked(cls):
                continue
            per_attr: dict[str, list[tuple[_FuncInfo, _Access]]] = {}
            for info in cls.funcs.values():
                for acc in info.accesses:
                    per_attr.setdefault(acc.attr, []).append(
                        (info, acc))
            for attr, sites in per_attr.items():
                spec = _class_spec(cls, attr)
                if spec is None:
                    self._check_undeclared(cls, attr, sites)
                    continue
                kind, data = spec
                if kind == "handoff":
                    continue
                if kind == "role":
                    self._check_role_attr(cls, attr, data, sites)
                elif kind == "lock":
                    self._check_lock_attr(cls, attr, data, sites)

    def _check_role_attr(self, cls, attr, owners, sites) -> None:
        owners = set(owners)
        for info, acc in sites:
            if not acc.write or acc.in_init:
                continue
            extra = self._node_roles(cls, info.name) - owners
            if extra:
                self._emit(
                    "concurrency-nonowner-write", cls.relpath,
                    acc.lineno,
                    f"{cls.name}.{attr} is owned by role(s) "
                    f"{'/'.join(sorted(owners))} but this write is "
                    f"reachable from {'/'.join(sorted(extra))} "
                    f"(via {info.name})")

    def _check_lock_attr(self, cls, attr, lock, sites) -> None:
        root = cls.locks.get(lock, lock)
        for info, acc in sites:
            if acc.in_init:
                continue
            if root in acc.held:
                continue
            if _locked_body_lock(cls, info.name) == root:
                continue
            self._emit(
                "concurrency-unlocked-shared", cls.relpath,
                acc.lineno,
                f"{cls.name}.{attr} is guarded by {lock} but this "
                f"{'write' if acc.write else 'read'} (in {info.name}) "
                f"does not hold it")

    def _check_undeclared(self, cls, attr, sites) -> None:
        if attr in cls.locks or attr in cls.events \
                or attr in cls.queues or attr in cls.threads:
            return
        if attr in cls.method_names:
            return
        roles: set[str] = set()
        writes = []
        non_init = []
        for info, acc in sites:
            if acc.in_init:
                continue
            non_init.append((info, acc))
            roles |= self._node_roles(cls, info.name)
            if acc.write:
                writes.append((info, acc))
        if len(roles) < 2 or not writes:
            return
        # a common lock held at EVERY non-init site makes it safe
        common = None
        for i, (info, acc) in enumerate(non_init):
            held = set(acc.held)
            body = _locked_body_lock(cls, info.name)
            if body:
                held.add(body)
            common = held if common is None else (common & held)
        if common:
            return
        info, acc = writes[0]
        self._emit(
            "concurrency-unlocked-shared", cls.relpath, acc.lineno,
            f"{cls.name}.{attr} is accessed from roles "
            f"{'/'.join(sorted(roles))} with no common lock and no "
            f"OWNERSHIP declaration (declare an owner role, a "
            f"guarding lock, or a handoff)")

    # -- rule: lock order --------------------------------------------------

    def _check_lock_order(self) -> None:
        # transitively acquired locks per node (fixpoint over calls)
        acquired: dict[tuple[str, str], set[tuple[str, str]]] = {}
        for cls, info in self._nodes():
            key = (cls.name, info.name)
            acquired[key] = {(cls.name, a)
                             for a, _ln, _h in info.acquisitions}
        changed = True
        while changed:
            changed = False
            for key, outs in self.edges.items():
                mine = acquired.setdefault(key, set())
                for callee, _ln, _h, _rp in outs:
                    extra = acquired.get(callee, set()) - mine
                    if extra:
                        mine |= extra
                        changed = True
        # edges with a witness site each
        graph: dict[tuple[str, str], set[tuple[str, str]]] = {}
        sites: dict[tuple, tuple[str, int, str]] = {}

        def add(a, b, relpath, ln, why):
            graph.setdefault(a, set()).add(b)
            sites.setdefault((a, b), (relpath, ln, why))

        for cls, info in self._nodes():
            for lock, ln, held in info.acquisitions:
                b = (cls.name, lock)
                for h in held:
                    add((cls.name, h), b, cls.relpath, ln,
                        f"{info.name} acquires {lock} while holding "
                        f"{h}")
        for key, outs in self.edges.items():
            cname = key[0]
            for callee, ln, held, relpath in outs:
                if not held:
                    continue
                callee_cls = self.classes.get(callee[0])
                body = (_locked_body_lock(callee_cls, callee[1])
                        if callee_cls else None)
                for b in acquired.get(callee, set()):
                    if body is not None and b == (callee[0], body):
                        continue  # caller-holds contract, not a grab
                    for h in held:
                        add((cname, h), b, relpath, ln,
                            f"{key[1]} calls {callee[0]}.{callee[1]} "
                            f"(acquires {b[1]}) while holding {h}")
        # cycle detection (includes self-loops: non-reentrant locks)
        state: dict[tuple[str, str], int] = {}
        stack: list[tuple[str, str]] = []
        reported: set[tuple] = set()

        def dfs(n):
            state[n] = 1
            stack.append(n)
            for m in graph.get(n, ()):
                if m == n or state.get(m) == 1:
                    i = stack.index(m) if m in stack else len(stack)
                    cyc = stack[i:] + [m] if m != n else [n, n]
                    for a, b in zip(cyc, cyc[1:]):
                        if (a, b) in reported or (a, b) not in sites:
                            continue
                        reported.add((a, b))
                        rp, ln, why = sites[(a, b)]
                        names = " -> ".join(
                            f"{c}.{l}" for c, l in cyc)
                        self._emit("concurrency-lock-order", rp, ln,
                                   f"lock-order cycle {names}: {why}")
                elif m not in state:
                    dfs(m)
            stack.pop()
            state[n] = 2

        for n in list(graph):
            if n not in state:
                dfs(n)

    # -- rule: blocking ----------------------------------------------------

    def _check_blocking(self) -> None:
        for cls, info in self._nodes():
            fname = info.name.split(".")[-1]
            pump = "serve-pump" in self.roles.get(
                (cls.name, info.name), set())
            for desc, ln, held, waitlock in info.blocking:
                held_eff = set(held)
                if waitlock is not None and waitlock in held_eff:
                    # the CV pattern: wait() releases the condition
                    held_eff.discard(waitlock)
                held_eff -= {l for l in held_eff
                             if (cls.name, l) in IO_LOCKS}
                if held_eff:
                    self._emit(
                        "concurrency-blocking-under-lock",
                        cls.relpath, ln,
                        f"{desc} in {cls.name}.{info.name} while "
                        f"holding {'/'.join(sorted(held_eff))}")
                if (pump
                        and cls.relpath
                        not in PUMP_BLOCKING_EXEMPT_FILES
                        and fname not in PUMP_BOUNDARY_FUNCS):
                    self._emit(
                        "concurrency-pump-blocking", cls.relpath, ln,
                        f"{desc} in {cls.name}.{info.name} is "
                        f"reachable from the serve-pump role outside "
                        f"the harvest boundary")

    # -- rule: locked-helper call sites ------------------------------------

    def _check_locked_calls(self) -> None:
        for cls, info in self._nodes():
            for ref, ln, held in info.self_refs:
                base = ref.split(".")[-1]
                if not base.endswith("_locked"):
                    continue
                need = _locked_body_lock(cls, base)
                if need is None or need in held:
                    continue
                self._emit(
                    "concurrency-unlocked-shared", cls.relpath, ln,
                    f"{cls.name}.{info.name} calls {ref} (a "
                    f"caller-holds-{need} helper) without holding "
                    f"{need}")

    # -- strict (package) table/placement sync ------------------------------

    def _check_strict(self) -> None:
        for cname, attrs in OWNERSHIP.items():
            cls = self.classes.get(cname)
            if cls is None:
                self._emit("concurrency-stale-ownership", "OWNERSHIP",
                           0, f"class {cname} not found in package")
                continue
            touched = set(cls.assigned_attrs)
            for info in cls.funcs.values():
                touched |= {a.attr for a in info.accesses}
            for attr in attrs:
                if attr not in touched:
                    self._emit(
                        "concurrency-stale-ownership", cls.relpath, 0,
                        f"OWNERSHIP declares {cname}.{attr} but no "
                        f"method assigns it")
        found: dict[tuple[str, str], tuple[tuple[str, ...], int]] = {}
        for cls, info in self._nodes():
            if info.assert_roles is not None:
                found[(cls.relpath, f"{cls.name}.{info.name}")] = (
                    info.assert_roles, info.assert_line)
        for site, roles in RUNTIME_ASSERT_SITES.items():
            got = found.pop(site, None)
            if got is None:
                self._emit(
                    "concurrency-assert-placement", site[0], 0,
                    f"RUNTIME_ASSERT_SITES expects assert_owner in "
                    f"{site[1]} (roles {roles}) but none was found")
            elif got[0] != roles:
                self._emit(
                    "concurrency-assert-placement", site[0], got[1],
                    f"{site[1]} asserts roles {got[0]} but the table "
                    f"declares {roles}")
        for site, (roles, ln) in found.items():
            self._emit(
                "concurrency-assert-placement", site[0], ln,
                f"assert_owner in {site[1]} (roles {roles}) is not "
                f"declared in RUNTIME_ASSERT_SITES")

    # -- driver ------------------------------------------------------------

    def run(self) -> list[Violation]:
        self._build_edges()
        self._propagate_roles()
        self._check_attrs()
        self._check_locked_calls()
        self._check_lock_order()
        self._check_blocking()
        if self.strict:
            self._check_strict()
        return self.found


# --- entry points -----------------------------------------------------------


def check_paths(root: pathlib.Path,
                strict: bool = False) -> list[Violation]:
    """Analyze every .py under `root` (relative-path scoping, like
    `lint.lint_paths`). `strict` additionally verifies the OWNERSHIP
    table and assert_owner placements against the tree - package scans
    only (fixture trees don't carry the shipped classes)."""
    global _last_scan_count
    scans: list[_FileScan] = []
    found: list[Violation] = []
    n = 0
    for path, rel in iter_package_files(root):
        n += 1
        source = path.read_text()
        try:
            tree = ast.parse(source)
        except SyntaxError as e:
            found.append(
                Violation("concurrency", "syntax", rel, str(e)))
            continue
        scans.append(_FileScan(rel, source, tree))
    _last_scan_count = n
    found.extend(_Analysis(scans, strict).run())
    return found


def check_package() -> list[Violation]:
    import sparksched_tpu

    root = pathlib.Path(sparksched_tpu.__file__).parent
    return check_paths(root, strict=True)
