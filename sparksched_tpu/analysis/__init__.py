"""Static-analysis subsystem: the single source of truth for "is this
program still TPU-shaped".

The repo's hot loop must stay XLA-friendly (ROADMAP north star: as fast
as the hardware allows), but nothing in Python stops a stray host
callback, an f64 promotion, or a data-dependent while-loop from landing
in a hot program and surfacing rounds later as a bench slump. This
package makes TPU-hostility a CI failure, via three passes:

- `jaxpr_audit`: traces the registered hot programs (`observe`,
  `micro_step`, `decide_micro_step`, `drain_to_decision`,
  `DecimaScheduler.score`/`batch_policy`, `ppo_update`,
  `flat_collect_batch`, the `health:`-instrumented
  `ppo_update_health`/`flat_collect_batch_health` variants, plus the
  AOT serving programs `serve_decide`/`serve_decide_batch`) with
  audit-config shapes and checks each jaxpr rule-by-rule — no host
  callbacks outside an explicit allowlist, no f64/i64 anywhere,
  loop-free programs stay free of `while`/`scan`, and per-program
  eqn/gather/scatter budgets from ONE declarative table (migrated out
  of tests/test_jaxpr_budget.py).
- `lint`: AST rules over `sparksched_tpu/` source — host-scalar pulls
  (`.item()`/`float()`/`int()`/`np.asarray`) in traced modules, host
  syncs (`jax.device_get`/`block_until_ready`) outside the sanctioned
  host loop, implicit-dtype array constructors in hot modules,
  `time.*` reads in traced modules, and the generalized no-bare-print
  rule (moved here from tests/test_obs.py).
- `contracts`: declared dtype/shape schemas for `EnvState`,
  `Telemetry` and trajectory records, verified statically (the
  schemas are data the auditor reads via `jax.eval_shape`) plus a
  cheap runtime-assert mode tests use to pin that reset/step never
  drift structure, dtype, or shape (the recompile hazard).
- `coverage`: registry coverage — every `jax.jit`/AOT site in the
  package must map to a registered jaxpr-audit program or carry an
  explicit waiver (`coverage.COVERAGE`), closing the silent-gap
  failure mode as the program surface grows.
- `concurrency`: host-thread ownership + lock discipline over the
  serve/online stack — a thread-role call graph seeded at every
  `threading.Thread` spawn site, a declarative attribute OWNERSHIP
  table, non-owner-write / unlocked-shared / lock-order /
  blocking-under-lock / pump-blocking rules, and cross-validation of
  the runtime `assert_owner` placements (`sparksched_tpu.ownership`).
- `memory`: HBM-byte observability (ISSUE 5 tentpole) — per-program
  trace-time byte accounting under the TPU tiled-layout model, the
  `bank-broadcast` rule (no vmapped lane program may contain a
  lane-batched producer of a workload-bank-shaped array — the 19.4 GB
  round-5 OOM, checkable on CPU before backend folding), a
  declarative temp-bytes budget table, and the lane-fit advisor (max
  vmap lanes per program under a 17.2 GB HBM budget).

`python -m sparksched_tpu.analysis` runs all passes, prints a JSON
report, and exits non-zero on any violation. Budgets and rule scoping
are declarative data in the respective modules; see
`jaxpr_audit.BUDGETS` and `memory.MEM_BUDGETS` for the re-pin
procedures.
"""

from __future__ import annotations

import dataclasses
from typing import Any

__all__ = [
    "Violation",
    "run_all",
    "clean_in_subprocess",
    "analysis_clean_stamp",
]


@dataclasses.dataclass(frozen=True)
class Violation:
    """One rule violation. `passname` is the pass that found it
    (jaxpr | lint | contracts), `rule` the rule id, `where` the
    program/file/pytree location, `detail` a human-readable message."""

    passname: str
    rule: str
    where: str
    detail: str

    def to_dict(self) -> dict[str, str]:
        return dataclasses.asdict(self)

    def __str__(self) -> str:
        return f"[{self.passname}/{self.rule}] {self.where}: {self.detail}"


DEFAULT_PASSES = ("lint", "coverage", "concurrency", "contracts",
                  "jaxpr", "memory")


def run_all(passes: tuple[str, ...] = DEFAULT_PASSES,
            programs: tuple[str, ...] | None = None,
            ) -> dict[str, Any]:
    """Run the selected passes and return the JSON-able report dict.

    Pass order is cheap-first (lint is pure AST, contracts is
    `eval_shape`-only, the jaxpr audit traces every registered hot
    program, the memory pass additionally traces the VMAPPED lane
    programs — it reuses the jaxpr pass's unbatched traces via the
    registry cache, so running both costs one set of traces plus the
    vmapped ones) so a dirty tree fails fast. `programs` restricts the
    jaxpr/memory registries (the lint/contracts passes ignore it). The
    heavy imports happen here, not at module import, so `from
    sparksched_tpu import analysis` stays light for the bench stamp
    helper."""
    report: dict[str, Any] = {"passes": {}, "violations": []}
    all_violations: list[Violation] = []
    for p in passes:
        if p == "lint":
            from . import lint

            vs = lint.lint_package()
            extra: dict[str, Any] = {"files_scanned": lint.last_scan_count()}
        elif p == "coverage":
            from . import coverage

            vs = coverage.check_package()
            extra = {"files_scanned": coverage.last_scan_count(),
                     "sites_registered": len(coverage.COVERAGE)}
        elif p == "concurrency":
            from . import concurrency

            vs = concurrency.check_package()
            extra = {"files_scanned": concurrency.last_scan_count()}
        elif p == "contracts":
            from . import contracts

            vs = contracts.check_all()
            extra = {"schemas": contracts.SCHEMA_NAMES}
        elif p == "jaxpr":
            from . import jaxpr_audit

            vs, measured = jaxpr_audit.audit_all(names=programs)
            extra = {"measured": measured}
        elif p == "memory":
            from . import memory

            vs, measured = memory.audit_memory(names=programs)
            extra = {"measured": measured}
        else:
            raise ValueError(f"unknown pass {p!r}")
        report["passes"][p] = extra | {
            "violations": [v.to_dict() for v in vs],
        }
        all_violations.extend(vs)
    report["violations"] = [v.to_dict() for v in all_violations]
    report["violation_count"] = len(all_violations)
    report["clean"] = not all_violations
    return report


def run_cli_subprocess(timeout: float = 900.0, quiet: bool = True):
    """Spawn the full analyzer CLI in a CPU-pinned subprocess — THE
    shared runner for every out-of-process gate (the bench stamp and
    the chip-session stage), so invocation, env pinning and timeout
    semantics cannot diverge between them.

    A subprocess so the analyzer can never claim the accelerator the
    parent bench holds (one tunnel grant, PERF.md operational rules)
    and never pollutes the parent's jit caches; CPU-pinned because
    tracing is backend-independent. Returns the CompletedProcess, or
    None when the spawn failed or timed out."""
    import os
    import subprocess
    import sys

    env = os.environ | {"JAX_PLATFORMS": "cpu"}
    cmd = [sys.executable, "-m", "sparksched_tpu.analysis"]
    if quiet:
        cmd.append("--quiet")
    try:
        return subprocess.run(
            cmd, env=env, timeout=timeout, capture_output=True
        )
    except Exception:
        return None


def clean_in_subprocess(timeout: float = 900.0) -> bool:
    """True iff the tree is analysis-clean. Any failure — timeout,
    crash, violations — is False: a perf row that cannot prove the
    tree is clean must identify itself as dirty."""
    r = run_cli_subprocess(timeout)
    return r is not None and r.returncode == 0


_STAMP_CACHE: list = []


def analysis_clean_stamp() -> bool | None:
    """The bench-row `analysis_clean` value, memoized per process
    (bench_decima emits several rows per run; the tree cannot change
    between them). `BENCH_ANALYSIS=0` skips the run and stamps null —
    an explicit opt-out, distinct from False which means the analyzer
    found violations, crashed, or timed out."""
    import os

    if os.environ.get("BENCH_ANALYSIS", "1") != "1":
        return None
    if not _STAMP_CACHE:
        _STAMP_CACHE.append(clean_in_subprocess())
    return _STAMP_CACHE[0]
