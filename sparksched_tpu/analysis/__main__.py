"""`python -m sparksched_tpu.analysis` — run every static-analysis pass,
print a JSON report, exit non-zero on any violation.

Flags:
  --passes lint,coverage,concurrency,contracts,jaxpr,memory
                                  subset to run (default: all,
                                  cheap-first); `--passes memory` runs
                                  the HBM memory pass alone,
                                  `--passes concurrency` the host
                                  thread-ownership pass alone
  --quiet                         violations-only JSON (no measured
                                  counts) — the bench stamp subprocess
                                  uses this
  --programs observe,micro_step   registry subset for the jaxpr/memory
                                  passes (default: all 8; unknown names
                                  are an error)
  --mem-compile                   additionally AOT-compile every
                                  registry program on the current
                                  backend and report
                                  compiled.memory_analysis() (backend-
                                  true bytes; roughly doubles runtime)
Exit code 0 == analysis-clean tree.

JAX_PLATFORMS defaults to cpu (tracing is backend-independent, and the
audit must never claim an accelerator a bench session holds — PERF.md
operational rules); an explicit JAX_PLATFORMS in the environment wins.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m sparksched_tpu.analysis",
        description="TPU-hostility static analysis (jaxpr audit + AST "
        "lint + pytree contracts)",
    )
    ap.add_argument(
        "--passes",
        default="lint,coverage,concurrency,contracts,jaxpr,memory",
        help="comma-separated subset of lint,coverage,concurrency,"
        "contracts,jaxpr,memory",
    )
    ap.add_argument(
        "--quiet", action="store_true",
        help="violations-only JSON (omit measured counts)",
    )
    ap.add_argument(
        "--programs", default=None,
        help="comma-separated registry subset for the jaxpr/memory "
        "passes (default: every registered hot program)",
    )
    ap.add_argument(
        "--mem-compile", action="store_true",
        help="AOT-compile the registry and report backend-true "
        "memory_analysis() bytes (chip session stage 11 uses this "
        "on-device; the default stays trace-only and CPU-pinned)",
    )
    args = ap.parse_args(argv)

    # pin the backend BEFORE jax initializes (run_all imports it)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    from . import run_all

    passes = tuple(p for p in args.passes.split(",") if p)
    programs = (
        tuple(p for p in args.programs.split(",") if p)
        if args.programs else None
    )
    report = run_all(passes, programs=programs)
    if args.mem_compile:
        from .memory import program_memory_accounting

        report["mem_compile"] = program_memory_accounting(programs)
    if args.quiet:
        report = {
            "clean": report["clean"],
            "violation_count": report["violation_count"],
            "violations": report["violations"],
        }
    json.dump(report, sys.stdout, indent=1)
    sys.stdout.write("\n")
    return 0 if report["clean"] else 1


if __name__ == "__main__":
    sys.exit(main())
