"""AST lint over `sparksched_tpu/` source: repo rules that keep host
Python out of the traced hot path.

Rules (ids are what `# analysis: allow(<rule>)` pragmas and the JSON
report use):

- ``host-scalar``: no `.item()` / `np.asarray(...)` / `float(...)` /
  `int(...)` on non-constant values inside the fully-traced modules
  (`env/`, `schedulers/`). Each of these forces a device->host
  transfer when it touches a traced value — inside jit it is a trace
  error at best, a silent sync at worst.
- ``host-sync``: no `jax.device_get` / `block_until_ready` outside the
  sanctioned host-side code (`obs/`, the host adapters, and the
  trainer host loop — see `HOST_SYNC_EXEMPT_FUNCS`). Collection and
  update code must stay asynchronous; a stray sync serializes the
  dispatch pipeline.
- ``implicit-dtype``: `jnp.zeros/ones/full/arange` in the hot modules
  must pass an explicit dtype (keyword or the positional dtype slot).
  Implicit dtypes follow the x64 flag — the same constructor that
  builds i32/f32 on the shipped config silently builds i64/f64 under
  `JAX_ENABLE_X64`, and a single wide leaf recompiles every consumer.
- ``time-in-jit``: no `time.time()`-family reads in the fully-traced
  modules. A wall-clock read inside a jitted body is evaluated once at
  trace time and baked in as a constant — timing belongs to the host
  loop (`trainers/profiler.py`, `obs/runlog.py`).
- ``bare-print``: no bare `print(` anywhere in the package outside
  `renderer.py` (moved here from tests/test_obs.py) — host-loop output
  goes through `obs.runlog` (`emit` / the JSONL sink) so it stays
  machine-readable and console-consistent.
- ``serve-host-sync`` (ISSUE 15): in the serve pump hot path
  (`serve/session.py`), a blocking host sync — `jax.device_get`,
  `block_until_ready`, or an eager `np.asarray` on a device array —
  is a violation OUTSIDE the harvest/trace boundary
  (`SERVE_HARVEST_FUNCS`). The pipelined front exists because one
  stray sync in dispatch/admission serializes the whole in-flight
  window; this rule makes that regression a CI failure instead of a
  p99 surprise. The file is a HOST_FILE (the generic host-sync rule
  deliberately exempts it — handing back concrete decisions IS its
  product), so this rule is the narrow replacement: syncs may live in
  the harvest stage and the trace stamps, nowhere else. ISSUE 19
  generalizes this interprocedurally: `concurrency-pump-blocking`
  (analysis/concurrency.py) follows the serve-pump ROLE through the
  call graph package-wide, so a sync buried two calls deep or in a
  different module is caught too; this rule stays as the cheap
  file-scoped first line.

Scoping is declarative data below. Known-host-side code is exempted
there (visible in one place), and a line-level escape hatch exists for
deliberate boundary crossings: append ``# analysis: allow(<rule>)`` to
the offending line.
"""

from __future__ import annotations

import ast
import pathlib
import re
from typing import Iterator

from . import Violation

# --- rule scoping (declarative) -------------------------------------------

# fully-traced modules: everything in these package subdirs executes
# (or may execute) under jit/vmap/scan
HOT_DIRS = ("env", "schedulers")

# host adapters by contract — they exist to bridge device pytrees to
# host consumers, so host-scalar/host-sync/time rules do not apply.
# serve/session.py is the decision-serving request/response boundary
# (ISSUE 10): its device_get/block_until_ready ARE the product — the
# caller is handed a concrete decision — and its traced code lives in
# serve/aot.py + env/, which the jaxpr rules audit directly.
# serve/loadgen.py (ISSUE 11) is host-side by definition — an
# open-loop load generator IS a wall-clock consumer
# (time.perf_counter is its measurement instrument, not a trace
# hazard); obs/metrics.py needs no entry here because obs/ is already
# a sync-exempt host dir, but both are named so the scoping decision
# is visible in one place.
HOST_FILES = frozenset({
    "renderer.py", "env/gym_compat.py", "serve/session.py",
    "serve/loadgen.py",
    # ISSUE 14: the online loop's host-side modules — trajectory
    # assembly consumes concrete ServeResults (device_get is the
    # product, as in serve/session.py), the learner's host loop syncs
    # on update completion exactly like trainers/trainer.py's, and
    # the bus is pure host bookkeeping; their traced code is the
    # registry-audited serve/ppo programs, not these files
    "online/__init__.py", "online/trajectory.py",
    "online/learner.py", "online/bus.py",
    # ISSUE 16: the network tier's request/response boundary — the
    # HTTP front and the replica router are host bookkeeping end to
    # end (sockets, pipes, wall-clock timeouts ARE the product);
    # their traced code is the same registry-audited serve programs,
    # built per-replica through store_from_config. Jaxpr-exempt but
    # still AST-linted (bare-print etc. apply).
    "serve/server.py", "serve/router.py",
    # ISSUE 17: the fleet observability plane — scrape loops, burn-
    # rate window arithmetic, and artifact-JSON indexing are host
    # bookkeeping by definition (wall clocks and files ARE the
    # product); nothing in them traces. Already under the obs/
    # sync-exempt dir; named here so the host scoping is explicit.
    "obs/fleet.py", "obs/slo.py", "obs/ledger.py",
    # ISSUE 20: the tail-attribution plane — span arithmetic over
    # perf_counter stamps and a wall-clock sampling profiler are host
    # instruments by definition (the clock IS the measurement).
    # Already under the obs/ sync-exempt dir; named for visibility.
    "obs/critpath.py", "obs/hostprof.py",
})

# host-side entry points inside otherwise-hot modules, PATH-QUALIFIED
# (a bare-name exemption would let any function named `schedule` in a
# hot module disable the rules): constructor config coercion, the
# one-decision host API, torch checkpoint IO
HOST_BOUNDARY_FUNCS: dict[str, tuple[str, ...]] = {
    "__init__": ("schedulers/",),
    "schedule": ("schedulers/",),
    "load_torch_state_dict": ("schedulers/decima.py",),
}

# the sanctioned synchronous host loop, path-qualified like the above:
# the trainer's per-iteration timing fences and checkpoint
# serialization, and the scheduler's host-side single-decision API
HOST_SYNC_EXEMPT_DIRS = ("obs",)
HOST_SYNC_EXEMPT_FUNCS: dict[str, tuple[str, ...]] = {
    "train": ("trainers/trainer.py",),
    "save_train_state": ("trainers/trainer.py",),
    "_checkpoint": ("trainers/trainer.py",),
    "_cleanup": ("trainers/trainer.py",),
    "schedule": ("schedulers/",),
}

# serve-host-sync (ISSUE 15) scoping: the serve pump hot path, and the
# functions forming its sanctioned harvest/trace boundary — the ONLY
# places in those files where a blocking device sync
# (device_get / block_until_ready / eager np.asarray on device
# buffers) is allowed. Everything else in the file is
# dispatch/admission code the pipelined front needs sync-free.
SERVE_PUMP_FILES = frozenset({"serve/session.py"})
SERVE_HARVEST_FUNCS = frozenset({
    # the synchronous serve path's materialization (it IS a harvest)
    "_served",
    # the pipelined harvest stage (pop_ready = the device half,
    # finalize_call = the host half) + the background harvester
    "harvest", "pop_ready", "finalize_call", "_materialize",
    "_harvester_loop",
    # the deferred page-out drain (the non-blocking pager's tail)
    "_drain_writebacks",
})


def _func_exempt(relpath: str, func_stack: list[str],
                 table: dict[str, tuple[str, ...]]) -> bool:
    return any(
        f in table and any(relpath.startswith(p) for p in table[f])
        for f in func_stack
    )

_JNP_CTORS = {
    # constructor -> index of the positional dtype slot
    "zeros": 1,
    "ones": 1,
    "full": 2,
    "arange": 3,
}
_TIME_FNS = frozenset({
    "time", "perf_counter", "monotonic", "process_time", "time_ns",
    "perf_counter_ns", "monotonic_ns",
})

_PRAGMA_RE = re.compile(r"#\s*analysis:\s*allow\(([a-z\-_, ]+)\)")

_last_scan_count = 0


def last_scan_count() -> int:
    return _last_scan_count


def _pragmas(source: str) -> dict[int, set[str]]:
    """lineno -> set of rule ids allowed on that line."""
    out: dict[int, set[str]] = {}
    for i, line in enumerate(source.splitlines(), start=1):
        m = _PRAGMA_RE.search(line)
        if m:
            out[i] = {r.strip() for r in m.group(1).split(",")}
    return out


def _dotted(node: ast.AST) -> str:
    """Best-effort dotted name of a call target ('jax.device_get')."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return ".".join(reversed(parts))


def _import_table(tree: ast.AST) -> dict[str, str]:
    """Local name -> canonical dotted path, from every import form, so
    rules match on canonical names and cannot be bypassed by aliasing
    (`import time as t`, `from jax.numpy import zeros as z`,
    `import jax.numpy as J`, ...)."""
    table: dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.asname:
                    table[a.asname] = a.name
                else:
                    # `import jax.numpy` binds `jax`; dotted call
                    # sites resolve through the first segment
                    top = a.name.split(".")[0]
                    table[top] = top
        elif isinstance(node, ast.ImportFrom) and node.module:
            for a in node.names:
                if a.name != "*":
                    table[a.asname or a.name] = \
                        f"{node.module}.{a.name}"
    return table


class _Linter(ast.NodeVisitor):
    def __init__(self, relpath: str, source: str,
                 tree: ast.AST) -> None:
        self.relpath = relpath
        self.pragmas = _pragmas(source)
        self.func_stack: list[str] = []
        self.found: list[Violation] = []
        self.imports = _import_table(tree)

        top = relpath.split("/")[0]
        self.in_hot = top in HOT_DIRS and relpath not in HOST_FILES
        self.host_file = relpath in HOST_FILES
        self.sync_exempt_file = (
            top in HOST_SYNC_EXEMPT_DIRS or self.host_file
        )
        self.serve_pump = relpath in SERVE_PUMP_FILES
        self.print_exempt = relpath == "renderer.py"

    # -- helpers ------------------------------------------------------

    def _emit(self, rule: str, node: ast.AST, detail: str) -> None:
        line = getattr(node, "lineno", 0)
        if rule in self.pragmas.get(line, ()):  # line-level escape hatch
            return
        self.found.append(Violation(
            "lint", rule, f"{self.relpath}:{line}", detail
        ))

    def _in_host_boundary(self) -> bool:
        return _func_exempt(
            self.relpath, self.func_stack, HOST_BOUNDARY_FUNCS
        )

    def _sync_exempt(self) -> bool:
        return self.sync_exempt_file or _func_exempt(
            self.relpath, self.func_stack, HOST_SYNC_EXEMPT_FUNCS
        )

    def _canonical(self, fn: ast.AST) -> str:
        """Import-resolved dotted name of a call target: `t.time` under
        `import time as t` -> "time.time"; `z` under `from jax.numpy
        import zeros as z` -> "jax.numpy.zeros"."""
        name = _dotted(fn)
        if not name:
            return ""
        head, _, rest = name.partition(".")
        resolved = self.imports.get(head, head)
        return f"{resolved}.{rest}" if rest else resolved

    # -- traversal ----------------------------------------------------

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self.func_stack.append(node.name)
        self.generic_visit(node)
        self.func_stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef  # type: ignore[assignment]

    def visit_Call(self, node: ast.Call) -> None:
        self._check_call(node)
        self.generic_visit(node)

    def _check_call(self, node: ast.Call) -> None:
        fn = node.func
        name = self._canonical(fn)
        mod, _, leaf = name.rpartition(".")

        # bare-print (whole package minus renderer.py)
        if isinstance(fn, ast.Name) and fn.id == "print":
            if not self.print_exempt:
                self._emit(
                    "bare-print", node,
                    "bare print( call — use obs.runlog.emit or the "
                    "JSONL runlog",
                )
            return

        # host-sync (package-wide minus the sanctioned host loop)
        is_sync_call = (
            name in ("jax.device_get", "jax.block_until_ready")
            or (isinstance(fn, ast.Attribute)
                and fn.attr == "block_until_ready")
        )
        if is_sync_call and not self._sync_exempt():
            self._emit(
                "host-sync", node,
                f"{name}() outside obs//bench — a device sync in "
                "collection/update code serializes dispatch",
            )

        # serve-host-sync (ISSUE 15): blocking syncs in the serve pump
        # hot path are confined to the harvest/trace boundary — a
        # stray one in dispatch/admission code serializes the whole
        # in-flight window
        if self.serve_pump and (
            is_sync_call or name == "numpy.asarray"
        ) and not any(
            f in SERVE_HARVEST_FUNCS for f in self.func_stack
        ):
            self._emit(
                "serve-host-sync", node,
                f"{name or 'block_until_ready'}() in the serve pump "
                "hot path outside the harvest/trace boundary "
                "(SERVE_HARVEST_FUNCS) — a blocking sync here "
                "serializes the pipelined in-flight window",
            )

        if not self.in_hot:
            return

        # implicit-dtype (hot modules; jnp.* and any aliased or
        # from-imported form of the jax.numpy constructors)
        if mod == "jax.numpy" and leaf in _JNP_CTORS:
            has_kw = any(kw.arg == "dtype" for kw in node.keywords)
            has_pos = len(node.args) > _JNP_CTORS[leaf]
            if not (has_kw or has_pos):
                self._emit(
                    "implicit-dtype", node,
                    f"jnp.{leaf}(...) without an explicit dtype — "
                    "implicit dtypes follow the x64 flag",
                )

        # time-in-jit (hot modules; any import form of the clock fns)
        if mod == "time" and leaf in _TIME_FNS:
            self._emit(
                "time-in-jit", node,
                f"time.{leaf}() in a traced module — evaluated once "
                "at trace time, constant thereafter",
            )

        # host-scalar (hot modules, outside host-boundary functions)
        if self._in_host_boundary():
            return
        if isinstance(fn, ast.Attribute) and fn.attr == "item" \
                and not node.args:
            self._emit(
                "host-scalar", node,
                ".item() in a traced module forces a device->host "
                "transfer",
            )
        elif name == "numpy.asarray":
            self._emit(
                "host-scalar", node,
                "np.asarray() on a (possibly traced) value — use "
                "jnp.asarray or move to a host adapter",
            )
        elif (
            isinstance(fn, ast.Name)
            and fn.id in ("float", "int")
            and node.args
            and not isinstance(node.args[0], ast.Constant)
        ):
            self._emit(
                "host-scalar", node,
                f"{fn.id}(...) on a non-constant in a traced module — "
                "a silent sync on concrete values, a trace error under "
                "jit",
            )


def lint_file(path: pathlib.Path, relpath: str) -> list[Violation]:
    source = path.read_text()
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        return [Violation("lint", "syntax", relpath, str(e))]
    linter = _Linter(relpath, source, tree)
    linter.visit(tree)
    return linter.found


def iter_package_files(root: pathlib.Path) -> Iterator[
        tuple[pathlib.Path, str]]:
    for path in sorted(root.rglob("*.py")):
        if "__pycache__" in path.parts:
            continue
        rel = path.relative_to(root).as_posix()
        if rel.startswith("analysis/"):
            # the analyzer itself is host-side tooling
            continue
        yield path, rel


def lint_paths(root: pathlib.Path) -> list[Violation]:
    """Lint every .py under `root`, with rule scoping keyed on paths
    RELATIVE to `root` (so a fixture tree mirroring the package layout
    — env/..., schedulers/..., obs/... — gets the same treatment)."""
    global _last_scan_count
    found: list[Violation] = []
    n = 0
    for path, rel in iter_package_files(root):
        n += 1
        found.extend(lint_file(path, rel))
    _last_scan_count = n
    return found


def lint_package() -> list[Violation]:
    import sparksched_tpu

    return lint_paths(pathlib.Path(sparksched_tpu.__file__).parent)
