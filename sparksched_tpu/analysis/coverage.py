"""Registry-coverage pass (ISSUE 19 satellite): every `jax.jit` /
AOT-lowered callable in the package must be accounted for in the
jaxpr-audit program registry or carry an explicit waiver.

The jaxpr/memory passes only audit programs someone REGISTERED in
`jaxpr_audit.BUDGETS` — a new jit site that nobody registers is a
silent gap: it ships untraced, unbudgeted, and surfaces rounds later
as a bench slump. This pass closes the gap structurally: it finds
every jit/AOT site in the source (call forms `jax.jit(...)`,
decorator forms `@jax.jit` / `@partial(jax.jit, ...)`, and
`aot_compile(...)` lowering sites) and requires each to appear in the
declarative `COVERAGE` table below, mapped either to the audited
program(s) it produces or to a waiver with a reason.

Rules:

- ``coverage-unregistered-jit``: a jit/AOT site with no COVERAGE
  entry (and no `# analysis: allow(coverage-unregistered-jit)`
  pragma). Register the program in `jaxpr_audit.BUDGETS` + here, or
  waive it with the reason.
- ``coverage-stale-entry``: a COVERAGE entry whose site no longer
  exists — the table must shrink with the code (package scan only).
- ``coverage-unknown-program``: a COVERAGE entry naming a program
  that is not a `jaxpr_audit.BUDGETS` key — a typo'd or unregistered
  mapping is itself a gap.

Sites are keyed `(relative path, enclosing qualname)` — stable across
line churn, specific enough that a NEW jit site in an already-listed
function still needs a table touch only when it lands in a new scope.
"""

from __future__ import annotations

import ast
import pathlib
from typing import Any

from . import Violation
from .lint import _import_table, _pragmas, iter_package_files

# site -> ("program", (budget keys...)) or ("waiver", reason)
COVERAGE: dict[tuple[str, str], tuple[str, Any]] = {
    # the generic AOT lowering entry: every serve program goes through
    # it; the concrete programs are registered per budget key
    ("serve/aot.py", "aot_compile"): ("program", (
        "serve_decide", "serve_decide_batch",
        "serve_decide_batch_sharded", "serve_decide_batch_group",
        "serve_decide_record", "serve_decide_batch_record",
        "serve_decide_record_ring", "serve_decide_batch_record_ring",
    )),
    # session-store construction lowers the serve programs (the
    # aot_compile call sites) and jits the slot-copy helpers
    # (_reset1/_write_slot/_take1/_ring_take — pure dynamic-slice
    # plumbing, covered by the serve programs' scatter budgets)
    ("serve/session.py", "SessionStore.__init__"): ("program", (
        "serve_decide", "serve_decide_batch",
        "serve_decide_batch_sharded", "serve_decide_batch_group",
        "serve_decide_record", "serve_decide_batch_record",
        "serve_decide_record_ring", "serve_decide_batch_record_ring",
    )),
    # tooling, not a hot program: the memory pass's own compile probe
    ("obs/memory.py", "aot_memory"): ("waiver",
        "analysis tooling: compiles the PROBED program, is not one"),
    # host-API convenience wrapper; the underlying policy programs are
    # audited as decima_score/decima_batch_policy
    ("schedulers/decima.py", "DecimaScheduler.schedule"): ("waiver",
        "host convenience API; the policy it jits is audited as "
        "decima_score/decima_batch_policy"),
    # baseline heuristics: cold-path comparison schedulers, not part
    # of the training/serving hot loop
    ("schedulers/heuristics.py", "round_robin_policy"): ("waiver",
        "baseline comparison scheduler, cold path"),
    ("schedulers/heuristics.py", "random_policy"): ("waiver",
        "baseline comparison scheduler, cold path"),
    ("env/observe.py", "observe"): ("program", ("observe",)),
    # episode initialization: traced once per reset, audited inside
    # the collector programs that inline it
    ("env/core.py", "reset"): ("waiver",
        "episode init, cold path; inlined into the audited "
        "collectors"),
    ("env/core.py", "reset_pair"): ("waiver",
        "episode init, cold path; inlined into the audited "
        "collectors"),
    ("env/core.py", "reset_from_sequence"): ("waiver",
        "episode init, cold path; inlined into the audited "
        "collectors"),
    ("env/core.py", "step"): ("program", (
        "micro_step", "decide_micro_step", "drain_to_decision",
    )),
    # gym-API compatibility shim: external-interface path,
    # perf-audited only through the native collectors
    ("env/gym_compat.py", "SparkSchedSimVectorEnv.__init__"): (
        "waiver", "gym-API compatibility shim"),
    ("env/gym_compat.py", "observe_batch"): ("waiver",
        "gym-API compatibility shim (batched observe helper)"),
    # the production collector program (batch axis) and its health
    # variant
    ("trainers/rollout.py", "collect_flat_sync_batch"): ("program", (
        "flat_collect_batch", "flat_collect_batch_health",
    )),
    ("trainers/rollout.py", "collect_flat_async_batch"): ("program", (
        "flat_collect_batch",
    )),
    # legacy/single-lane collectors kept for parity tests; the
    # audited production program is flat_collect_batch
    ("trainers/rollout.py", "collect_sync"): ("waiver",
        "legacy per-lane collector, parity-test path"),
    ("trainers/rollout.py", "collect_async"): ("waiver",
        "legacy per-lane collector, parity-test path"),
    ("trainers/rollout.py", "collect_flat_sync"): ("waiver",
        "single-lane flat collector, parity-test path"),
    ("trainers/rollout.py", "collect_flat_async"): ("waiver",
        "single-lane flat collector, parity-test path"),
    # Trainer.__init__ jits the collect/update pair; the update is
    # audited as ppo_update (+_health), the collect as
    # flat_collect_batch through the rollout entries above
    ("trainers/trainer.py", "Trainer.__init__"): ("program", (
        "ppo_update", "ppo_update_health", "flat_collect_batch",
    )),
}

_last_scan_count = 0


def last_scan_count() -> int:
    return _last_scan_count


def _canonical(imports: dict[str, str], node: ast.AST) -> str:
    from .lint import _dotted

    name = _dotted(node)
    if not name:
        return ""
    head, _, rest = name.partition(".")
    head = imports.get(head, head)
    return f"{head}.{rest}" if rest else head


def _is_jit_expr(imports: dict[str, str], node: ast.AST) -> bool:
    """jax.jit referenced bare (decorator) or called."""
    if isinstance(node, ast.Call):
        node = node.func
    return _canonical(imports, node) == "jax.jit"


class _SiteFinder(ast.NodeVisitor):
    def __init__(self, relpath: str, imports: dict[str, str]) -> None:
        self.relpath = relpath
        self.imports = imports
        self.stack: list[str] = []
        self.sites: list[tuple[str, int, str]] = []  # qualname, line

    def _qual(self) -> str:
        return ".".join(self.stack) if self.stack else "<module>"

    def _record(self, lineno: int, what: str) -> None:
        self.sites.append((self._qual(), lineno, what))

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self.stack.append(node.name)
        self.generic_visit(node)
        self.stack.pop()

    def _visit_func(self, node) -> None:
        for dec in node.decorator_list:
            # site lineno is the DECORATOR's line, so an
            # `# analysis: allow(...)` pragma sits where the reader
            # sees the jit, not on the def below it
            if _is_jit_expr(self.imports, dec):
                self.stack.append(node.name)
                self._record(dec.lineno, "@jax.jit")
                self.stack.pop()
            elif (isinstance(dec, ast.Call)
                    and _canonical(self.imports, dec.func)
                    in ("functools.partial", "partial")
                    and dec.args
                    and _is_jit_expr(self.imports, dec.args[0])):
                self.stack.append(node.name)
                self._record(dec.lineno, "@partial(jax.jit, ...)")
                self.stack.pop()
        self.stack.append(node.name)
        for stmt in node.body:
            self.visit(stmt)
        self.stack.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    def visit_Call(self, node: ast.Call) -> None:
        canon = _canonical(self.imports, node.func)
        if canon == "jax.jit":
            self._record(node.lineno, "jax.jit(...)")
        elif (canon.endswith("aot_compile")
                and self.relpath != "serve/aot.py"):
            # lowering call sites outside the definition module
            self._record(node.lineno, "aot_compile(...)")
        self.generic_visit(node)


def _collapse_qual(qual: str) -> str:
    """Nested defs fold onto their outermost enclosing scope: the
    table keys on where the site LIVES, not closure depth."""
    parts = qual.split(".")
    return ".".join(parts[:2]) if len(parts) > 2 else qual


def check_paths(root: pathlib.Path,
                strict: bool = False) -> list[Violation]:
    global _last_scan_count
    found: list[Violation] = []
    seen: set[tuple[str, str]] = set()
    n = 0
    for path, rel in iter_package_files(root):
        n += 1
        source = path.read_text()
        try:
            tree = ast.parse(source)
        except SyntaxError as e:
            found.append(Violation("coverage", "syntax", rel, str(e)))
            continue
        pragmas = _pragmas(source)
        finder = _SiteFinder(rel, _import_table(tree))
        finder.visit(tree)
        for qual, lineno, what in finder.sites:
            key = (rel, _collapse_qual(qual))
            seen.add(key)
            if key in COVERAGE:
                continue
            if "coverage-unregistered-jit" in pragmas.get(lineno,
                                                          set()):
                continue
            found.append(Violation(
                "coverage", "coverage-unregistered-jit",
                f"{rel}:{lineno}",
                f"{what} in {qual} is not in the COVERAGE table: "
                f"register the program in jaxpr_audit.BUDGETS and map "
                f"it here, or add a waiver with the reason"))
    _last_scan_count = n
    if strict:
        from .jaxpr_audit import BUDGETS

        for key, (kind, data) in COVERAGE.items():
            if key not in seen:
                found.append(Violation(
                    "coverage", "coverage-stale-entry",
                    f"{key[0]}:{key[1]}",
                    f"COVERAGE lists this {kind} entry but no jit/AOT "
                    f"site exists there anymore"))
            if kind == "program":
                for name in data:
                    if name not in BUDGETS:
                        found.append(Violation(
                            "coverage", "coverage-unknown-program",
                            f"{key[0]}:{key[1]}",
                            f"mapped program {name!r} is not a "
                            f"jaxpr_audit.BUDGETS key"))
    return found


def check_package() -> list[Violation]:
    import sparksched_tpu

    root = pathlib.Path(sparksched_tpu.__file__).parent
    return check_paths(root, strict=True)
