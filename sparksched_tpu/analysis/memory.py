"""Memory pass: HBM-byte accounting, the bank-broadcast rule, and the
lane-fit advisor over the registered hot programs.

The round-5 flagship bench died in XLA allocation analysis with a
19.4 GB temp — a per-lane broadcast of the workload bank's duration
table (`f32[512,154,20,3,8,16]`) that XLA:CPU folds away, so no CPU
test, bench or calibration run could see it (PERF.md "Round-3 on-chip
session 1"; fixed by commit 81e77fb). This pass makes that class of
failure a CPU-checkable CI failure, with the same shape as the eqn
budgets in `jaxpr_audit`:

Rules (ids used in the JSON report and the fixture tests):

- ``bank-broadcast``: no vmapped lane program (`observe`,
  `micro_step`, `decide_micro_step`, `drain_to_decision` — the
  registry programs that run under a lane vmap in production) may
  contain a lane-batched producer of a workload-bank-shaped array
  (`dur[T,S,3,L,K]`, `cnt[T,S,3,L]`, `adj[T,S,S]` with a leading lane
  dim). jax's cond/switch batching broadcasts closed-over operands
  when the predicate is lane-dependent, so a bank access inside a
  lane-dependent branch materializes a per-lane table copy — the
  exact invariant 81e77fb restored, checked on the JAXPR (before
  backend folding) so CPU CI sees what the TPU would allocate.
- ``mem-budget``: per-program `temp_total_bytes` (the tile-padded sum
  over every intermediate buffer of the UNBATCHED program at audit
  shapes — no liveness model, but stable and monotone in program
  growth) within the declarative `MEM_BUDGETS` bands below.

The report additionally carries, per program: the full trace-time
byte accounting (`obs.memory.jaxpr_memory_estimate` — args / outputs /
consts / temp-total / peak lower bound and a top-K largest-buffer
attribution naming shape + producing op), and for the lane programs a
lane-fit table (max lanes under the `TPU_HBM_BUDGET_BYTES` budget,
default 17.2 GB = the v5-lite part in PERF.md).

Backend-true accounting (`compiled.memory_analysis()` after a real AOT
compile) is NOT part of the default pass — it is backend-dependent
(CPU folds, TPU pads) and compiling every registry program would roughly
double the gate's cost. `program_memory_accounting(compile=True)`
exposes it for the chip session (stage 11) and the CLI's
`--mem-compile` flag.

Re-pin procedure (same contract as jaxpr_audit.BUDGETS): run
`python -m sparksched_tpu.analysis` — the report's
`passes.memory.measured` block prints every program's measured
temp-total bytes. A deliberate change that moves a program's bytes
gets a new cap of ~1.35x the measured value IN THE SAME PR, with a
bench row justifying the growth (PERF.md "Memory"). Bands are loose:
byte totals drift a few percent across jax versions as fusion
boundaries move; a band breach means structural allocation growth
(a new lane-batched table, a widened buffer), not noise.

Pinned 2026-08 (jax 0.4.37, threefry, CPU trace, tile-padded audit
shapes) — measured temp-total MB: observe 2.3, decima_score 153.6,
decima_batch_policy 169.2, ppo_update 269.6. Re-pinned 2026-08-03
for the ISSUE-7 fused bulk kernel, which SHRANK the engine programs:
micro_step 22.1 -> 16.1, drain_to_decision 16.2 -> 9.7,
flat_collect_batch 357.7 -> 329.8; decide_micro_step unchanged at
9.9 (its bulk phase is the mode-exclusive fulfill pass, deliberately
unfused). (The decima/ppo programs
carry a 4-lane batch in their audited shapes, and tile padding
inflates narrow minor dims — these are model numbers for regression
detection, not literal HBM footprints; the lane-fit table is the
footprint story.)
"""

from __future__ import annotations

import dataclasses
from typing import Any

from . import Violation
from .jaxpr_audit import (
    AUDIT_COLLECT_BATCH,
    BATCH_LANE_PROGRAMS,
    LANE_PROGRAMS,
    audit_setup,
    build_programs,
    flat_collect_batch_callable,
    lane_callables,
    program_callables,
)

# batch-width-parameterized builders for BATCH_LANE_PROGRAMS — the
# lane-fit advisor re-traces these at a second width to fit its
# per-lane byte model (keep in one-to-one sync with the tuple)
BATCH_PROGRAM_BUILDERS = {
    "flat_collect_batch": flat_collect_batch_callable,
}
assert set(BATCH_PROGRAM_BUILDERS) == set(BATCH_LANE_PROGRAMS)
from ..obs.memory import (
    TPU_HBM_BUDGET_BYTES,
    _iter_eqns,
    _trace_vmapped,
    aot_memory,
    aval_bytes,
    gb,
    jaxpr_memory_estimate,
    lane_fit,
)


@dataclasses.dataclass(frozen=True)
class MemBudget:
    """Per-program byte budget: `temp_hi` bounds the tile-padded sum of
    intermediate buffer bytes of the unbatched program at audit shapes
    (`obs.memory.jaxpr_memory_estimate`'s `temp_total_bytes`)."""

    temp_hi: int


MB = 10**6

# ---------------------------------------------------------------------------
# THE bytes budget table (single source of truth; see the module
# docstring for the re-pin procedure). Caps are ~1.35x the measured
# value, matching the eqn-budget band policy.
# ---------------------------------------------------------------------------

MEM_BUDGETS: dict[str, MemBudget] = {
    "observe": MemBudget(temp_hi=4 * MB),
    "micro_step": MemBudget(temp_hi=22 * MB),
    "decide_micro_step": MemBudget(temp_hi=14 * MB),
    "drain_to_decision": MemBudget(temp_hi=14 * MB),
    "decima_score": MemBudget(temp_hi=210 * MB),
    "decima_batch_policy": MemBudget(temp_hi=230 * MB),
    "ppo_update": MemBudget(temp_hi=365 * MB),
    # ISSUE 6: the single-eval batch collector the dp mesh shards,
    # audited at its native 4-lane batch (audit shapes are per-REPLICA:
    # under a dp mesh each device holds a 1/dp shard of every
    # lane-batched buffer, which is what the lane-fit advisor's `mesh`
    # mode models — these bytes bound the unsharded audit program)
    "flat_collect_batch": MemBudget(temp_hi=445 * MB),
    # ISSUE 9 `health:`-on variants (pinned 2026-08-03): the sentinels
    # are scalar reductions, so bytes barely move — ppo_update_health
    # 269.8 MB (vs 269.6 off), flat_collect_batch_health 330.6 MB (vs
    # 329.8). The byte budget pins that the sentinels stay reductions:
    # a health check that starts materializing per-lane tables would
    # breach this long before it OOMs a chip.
    "ppo_update_health": MemBudget(temp_hi=365 * MB),
    "flat_collect_batch_health": MemBudget(temp_hi=450 * MB),
    # ISSUE 10 serving programs (pinned 2026-08-04): serve_decide
    # 59.0 MB, serve_decide_batch 325.5 MB at the audit store/batch
    # shapes. The byte budget is the serving-latency analog of the
    # round-5 OOM lesson: a serve-path change that starts
    # materializing store-sized temporaries (the donation exists so
    # steady-state decisions allocate nothing store-shaped) breaches
    # this band long before it shows up as a p99 regression on-chip.
    "serve_decide": MemBudget(temp_hi=80 * MB),
    "serve_decide_batch": MemBudget(temp_hi=440 * MB),
    # ISSUE 13 sharded-store variant (pinned 2026-08-04): 329.3 MB vs
    # 325.5 unsharded — the sharding constraints add layout ops, not
    # buffers. The band pins that sharding the [C] axis never starts
    # materializing a gathered (unsharded) store copy: that would
    # roughly double the temp bytes and breach here on CPU before a
    # multi-chip window ever compiles it.
    "serve_decide_batch_sharded": MemBudget(temp_hi=445 * MB),
    # ISSUE 14 record-on serve variants (pinned 2026-08-04): 59.3 MB
    # / 326.7 MB vs 59.0 / 325.5 record-off — the StoredObs record is
    # a handful of [J,S] masks/counters per decision, ~0.4% bytes.
    # The band pins that recording stays a byproduct of the decision
    # already computed: a record path that re-materializes
    # observation-sized temporaries (a second observe pass, an
    # unmasked [J,S,S] adjacency copy) breaches here first. The
    # record-off programs re-measured byte-identical in the same PR
    # (the hot-swap params-as-argument refactor moved no bytes).
    "serve_decide_record": MemBudget(temp_hi=81 * MB),
    "serve_decide_batch_record": MemBudget(temp_hi=442 * MB),
    # ISSUE 15 group-shaped store program (pinned 2026-08-04):
    # 324.6 MB vs 325.5 at the full audit store — the temp bytes are
    # batch-axis-dominated (the width-K policy eval), so halving the
    # STORE axis moves almost nothing. The band pins that a grouped
    # lowering never starts materializing cross-group state (a
    # concatenated all-groups view would double here immediately).
    "serve_decide_batch_group": MemBudget(temp_hi=440 * MB),
    # ISSUE 18 ring-record serve variants (pinned 2026-08-07): 59.9 MB
    # / 327.3 MB vs 59.3 / 326.7 for the per-decision record programs —
    # the trajectory ring rides in the donated ARGS (one [R,...] RingRec
    # pytree, ~0.5 MB at the audit R), and the append is a single
    # masked scatter per leaf into that donated buffer, so temp bytes
    # barely move. The band pins that the ring append never starts
    # materializing a ring-sized temporary: a lowering that copies the
    # [R,...] ring to stage the append (instead of scattering in place)
    # would add the full ring bytes here and breach on CPU before a
    # record-on serve deploy ever pages it.
    "serve_decide_record_ring": MemBudget(temp_hi=82 * MB),
    "serve_decide_batch_record_ring": MemBudget(temp_hi=443 * MB),
}

# lane counts the advisor sweeps (the bench's production range; 1024
# is the headline lane count, 512 the sub-batch the round-5 OOM hit)
LANE_FIT_CANDIDATES = (64, 128, 256, 512, 1024)
# lane counts the vmapped traces are built at: B=4 feeds the
# bank-broadcast scan, (2, 4) the advisor's linear model
AUDIT_LANES = (2, 4)


def bank_shapes(bank) -> dict[str, tuple[int, ...]]:
    """The workload-bank array shapes whose lane-batched materialization
    is the hazard (the same trio tests/test_vmap_memory.py greps for)."""
    return {
        "dur": tuple(bank.dur.shape),
        "cnt": tuple(bank.cnt.shape),
        "adj": tuple(bank.adj.shape),
    }


def check_bank_broadcast(name: str, closed, bank, lanes: int
                         ) -> list[Violation]:
    """Scan one VMAPPED program's jaxpr for equations producing a
    lane-batched bank-shaped array. Names the producing op and the
    would-be HBM cost at the headline lane count, so the report reads
    like the round-5 postmortem instead of a six-dim shape."""
    hazard = {
        (lanes,) + shape: table
        for table, shape in bank_shapes(bank).items()
    }
    found: list[Violation] = []
    seen: set[tuple] = set()
    for eqn in _iter_eqns(closed.jaxpr):
        for v in eqn.outvars:
            aval = getattr(v, "aval", None)
            shape = tuple(getattr(aval, "shape", ()))
            if shape not in hazard:
                continue
            import jax

            key = (eqn.primitive.name, shape, str(aval.dtype))
            if key in seen:
                continue
            seen.add(key)
            at_1024 = aval_bytes(
                jax.ShapeDtypeStruct((1024,) + shape[1:], aval.dtype)
            )
            found.append(Violation(
                "memory", "bank-broadcast", name,
                f"lane-batched producer of the bank's {hazard[shape]} "
                f"table: {eqn.primitive.name} -> {aval.dtype}"
                f"{list(shape)} under a {lanes}-lane vmap "
                f"(~{gb(at_1024)} GB tile-padded at 1024 lanes) — a "
                "bank access moved inside a lane-dependent cond/switch "
                "branch; hoist it to the shared micro-step tail "
                "(commit 81e77fb pattern)",
            ))
    return found


def _lane_traces(names: tuple[str, ...] | None = None
                 ) -> dict[str, dict[int, Any]]:
    """Vmapped ClosedJaxprs of the lane programs at AUDIT_LANES —
    built once and shared between the bank-broadcast rule and the
    lane-fit advisor (each heavy trace costs seconds)."""
    out: dict[str, dict[int, Any]] = {}
    for name, (fn, args) in lane_callables().items():
        if names is not None and name not in names:
            continue
        out[name] = {
            b: _trace_vmapped(fn, args, b) for b in AUDIT_LANES
        }
    return out


def audit_memory(
    names: tuple[str, ...] | None = None,
    budget_bytes: int = TPU_HBM_BUDGET_BYTES,
) -> tuple[list[Violation], dict[str, Any]]:
    """Run the memory pass over the registry (or the `names` subset).
    Returns (violations, measured dict for the report): per-program
    byte accounting + budget verdicts, bank-broadcast scan of the
    vmapped lane programs, and the lane-fit table."""
    if names is not None:
        unknown = set(names) - set(MEM_BUDGETS)
        if unknown:
            raise ValueError(
                f"unknown program name(s) {sorted(unknown)} — the "
                "registry is the MEM_BUDGETS table's key set"
            )
    _, bank, _ = audit_setup()
    found: list[Violation] = []
    measured: dict[str, Any] = {}
    programs = build_programs(names)

    # -- unbatched accounting + the bytes budget ------------------------
    for name, closed in programs.items():
        est = jaxpr_memory_estimate(closed, tile_pad=True, top_k=3)
        budget = MEM_BUDGETS.get(name)
        measured[name] = {
            "temp_total_bytes": est["temp_total_bytes"],
            "temp_total_mb": round(est["temp_total_bytes"] / MB, 1),
            "args_bytes": est["args_bytes"],
            "out_bytes": est["out_bytes"],
            "const_bytes": est["const_bytes"],
            "peak_lower_bound_bytes": est["peak_lower_bound_bytes"],
            "largest": est["largest"],
        }
        if budget is None:
            found.append(Violation(
                "memory", "mem-budget", name,
                "program has no entry in the MEM_BUDGETS table",
            ))
        elif est["temp_total_bytes"] > budget.temp_hi:
            top = est["largest"][0] if est["largest"] else {}
            found.append(Violation(
                "memory", "mem-budget", name,
                f"temp-total {round(est['temp_total_bytes'] / MB, 1)}"
                f" MB > cap {round(budget.temp_hi / MB, 1)} MB "
                f"(largest buffer: {top.get('op')} "
                f"{top.get('shape')} = "
                f"{round(top.get('bytes', 0) / MB, 2)} MB) — "
                "structural allocation growth (or a stale cap); "
                "re-measure and re-pin in the same PR with a bench "
                "row justifying it",
            ))

    # -- vmapped lane programs: bank-broadcast + lane-fit ---------------
    lane_names = tuple(
        n for n in LANE_PROGRAMS if names is None or n in names
    )
    if lane_names:
        traces = _lane_traces(lane_names)
        callables = lane_callables()
        b_scan = max(AUDIT_LANES)
        lane_report: dict[str, Any] = {}
        for name in lane_names:
            found.extend(check_bank_broadcast(
                name, traces[name][b_scan], bank, b_scan
            ))
            fn, args = callables[name]
            fit = lane_fit(
                fn, args, candidates=LANE_FIT_CANDIDATES,
                budget_bytes=budget_bytes, base_lanes=AUDIT_LANES,
                traced=traces[name],
            )
            lane_report[name] = fit
            measured[name]["lane_fit"] = {
                "budget_gb": gb(budget_bytes),
                "max_lanes_fit": fit["max_lanes_fit"],
                "at_1024_gb": next(
                    (gb(r["est_peak_bytes"])
                     for r in fit["candidates"] if r["lanes"] == 1024),
                    None,
                ),
            }

    # -- batch programs (native lane axis): the sharded collectors ------
    # The single-eval collectors take the lane stack directly, so the
    # registry trace ALREADY carries the batch axis: the bank-broadcast
    # rule scans it as-traced (a lane-batched bank table here is the
    # same 19.4 GB class — and under a dp mesh it would materialize
    # per SHARD, i.e. the rule must see one replicated bank per
    # device, not a per-lane broadcast), and the lane-fit advisor fits
    # its model by re-tracing at a second batch width instead of
    # vmapping.
    for name in BATCH_LANE_PROGRAMS:
        if names is not None and name not in names:
            continue
        found.extend(check_bank_broadcast(
            name, programs[name], bank, AUDIT_COLLECT_BATCH
        ))

        def _tracer(b, _builder=BATCH_PROGRAM_BUILDERS[name]):
            import jax

            fn, args = _builder(batch=b)
            return jax.make_jaxpr(fn)(*args)

        fit = lane_fit(
            candidates=LANE_FIT_CANDIDATES, budget_bytes=budget_bytes,
            base_lanes=(2, AUDIT_COLLECT_BATCH),
            traced={AUDIT_COLLECT_BATCH: programs[name]},
            tracer=_tracer,
        )
        measured[name]["lane_fit"] = {
            "budget_gb": gb(budget_bytes),
            "max_lanes_fit": fit["max_lanes_fit"],
            "at_1024_gb": next(
                (gb(r["est_peak_bytes"])
                 for r in fit["candidates"] if r["lanes"] == 1024),
                None,
            ),
        }

    # -- serving batch programs (ISSUE 10/13): the bank-broadcast rule
    # on their native micro-batch axis. `serve/aot.py` vmaps
    # apply_and_drain over the K gathered sessions, so a bank access
    # slipping into a lane-dependent cond/switch branch would
    # materialize one bank copy per in-flight request — the same
    # 19.4 GB hazard class, caught here on CPU before a serving deploy
    # ever sees it. The dp-sharded variant is scanned too: under the
    # mesh a broadcast bank would materialize per SHARD, so the rule
    # must see one replicated bank, not a per-request (or per-device)
    # copy. (No lane-fit: the serve batch width is a latency knob
    # bounded by max_batch, not a throughput axis swept to HBM
    # capacity — the hot-set axis has its own advisor,
    # obs.memory.hot_set_fit.)
    for sname in ("serve_decide_batch", "serve_decide_batch_sharded",
                  "serve_decide_batch_group",
                  "serve_decide_batch_record_ring"):
        if names is not None and sname not in names:
            continue
        from ..serve.aot import SERVE_AUDIT_BATCH

        found.extend(check_bank_broadcast(
            sname, programs[sname], bank, SERVE_AUDIT_BATCH,
        ))
    return found, measured


_REGISTRY_FIT_CACHE: dict = {}


def registry_lane_fit(
    names: tuple[str, ...] = ("micro_step",),
    budget_bytes: int = TPU_HBM_BUDGET_BYTES,
) -> dict[str, Any]:
    """Memoized compact lane-fit of registry lane programs — the stamp
    bench rows use when their own collection program has no per-lane
    form (the single-eval batch collectors, the trainer's PPO jit): the
    registry micro-step/decide programs are the HBM-dominant inner loop
    every engine shares, so their fit is the honest proxy. Memoized per
    process because each program costs two heavy vmapped traces."""
    from ..obs.memory import lane_fit_summary

    key = (tuple(names), int(budget_bytes))
    if key not in _REGISTRY_FIT_CACHE:
        callables = lane_callables()
        _REGISTRY_FIT_CACHE[key] = {
            name: lane_fit_summary(lane_fit(
                *callables[name], candidates=LANE_FIT_CANDIDATES,
                budget_bytes=budget_bytes, base_lanes=AUDIT_LANES,
            ))
            for name in names
        }
    return _REGISTRY_FIT_CACHE[key]


def program_memory_accounting(
    names: tuple[str, ...] | None = None,
) -> dict[str, Any]:
    """Backend-true accounting: AOT lower + compile every registry
    program on the CURRENT backend and extract
    `compiled.memory_analysis()` (argument/output/temp/generated-code
    bytes). This is what chip-session stage 11 captures on the real
    TPU; on CPU the numbers are real but post-folding (the broadcast
    hazard is invisible here — that is the jaxpr rules' job). A
    program that fails to compile records the error string instead of
    killing the capture."""
    import jax

    out: dict[str, Any] = {"backend": jax.default_backend()}
    for name, (fn, args) in program_callables(names).items():
        mem = aot_memory(fn, *args)
        if mem is None:
            out[name] = {"error": "lower/compile/memory_analysis failed"}
        else:
            out[name] = mem
    return out
