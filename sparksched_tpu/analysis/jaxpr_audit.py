"""Jaxpr auditor: trace the registered hot programs and check each
rule-by-rule.

The decision row's cost on op-count-bound backends tracks jaxpr
equation counts (PERF.md round-4 census), host callbacks serialize the
dispatch pipeline, f64/i64 leaves double memory traffic and poison
compile keys, and a data-dependent while-loop reappearing in a
pinned-loop-free program re-introduces the straggler tax the flat
engine exists to remove. Each of those is a silent, gradual failure —
this auditor makes them CI failures at the PR that introduces them.

Rules (ids used in the JSON report and the fixture tests):

- ``host-callback``: no callback primitives (`pure_callback`,
  `io_callback`, `debug_callback`, ...) anywhere in a hot program,
  outside the program's explicit `Budget.callback_allow` set (e.g. a
  telemetry io_callback, should one ever be threaded on-device).
- ``wide-dtype``: no f64/i64/u64/c128 avals anywhere — inputs,
  outputs, or any intermediate equation.
- ``loop-free``: programs pinned loop-free (`Budget.loop_free`)
  contain no `while`/`scan` primitives at any nesting depth.
- ``budget``: per-program equation/gather/scatter counts within the
  declarative `BUDGETS` table below.

Programs are traced with the AUDIT CONFIG shapes (10 executors,
20-job/20-stage caps — the same shapes tests/test_jaxpr_budget.py
pinned before the table moved here). Equation counts are
shape-independent, so small shapes trace fast and the budgets hold at
flagship scale; the Decima programs use the shipped agent architecture
(config/decima_tpch.yaml: embed 16, gnn [32,16], policy [64,64]) with
the compaction bucket scaled to the audit job cap so BOTH score
branches (compact + full-width fallback) are in the audited program.
Everything is traced via `jax.make_jaxpr`/`jax.eval_shape` over
ShapeDtypeStructs — nothing executes on a device except tiny parameter
init, so the audit is safe to run while a bench holds the accelerator
(the CLI pins JAX_PLATFORMS=cpu regardless).

Budgets were pinned under the default threefry PRNG (a key draw is
~60 eqns under threefry vs 1 under rbg, so the impl is part of the
measurement); the CLI never switches impls, and neither should a test
importing this module.
"""

from __future__ import annotations

import dataclasses
from collections import Counter
from typing import Any, Callable

from . import Violation

WIDE_DTYPES = frozenset({"float64", "int64", "uint64", "complex128"})
LOOP_PRIMS = frozenset({"while", "scan"})


@dataclasses.dataclass(frozen=True)
class Budget:
    """Per-program op budget. `eqn_*` bound total equations (including
    nested sub-jaxprs), `gather_hi`/`scatter_hi` bound the gather- and
    scatter-family primitive counts (they serialize on TPU, so growth
    there hurts more than its eqn share suggests). `loop_free` pins the
    program free of while/scan; `callback_allow` names callback
    primitives the program may legitimately contain (empty everywhere
    today — the telemetry counters are pure adds, not callbacks)."""

    eqn_lo: int
    eqn_hi: int
    gather_hi: int
    scatter_hi: int
    loop_free: bool = False
    callback_allow: frozenset = frozenset()


# ---------------------------------------------------------------------------
# THE budget table (single source of truth; tests/test_jaxpr_budget.py is
# a thin wrapper over this).
#
# Re-pin procedure: run `python -m sparksched_tpu.analysis` — the report's
# `passes.jaxpr.measured` block prints every program's measured eqn /
# gather / scatter counts. A deliberate change that moves a count gets a
# new cap of ~1.35x the measured value (gather/scatter: measured + max(2,
# 35%)) IN THE SAME PR, with a bench row justifying the growth
# (PERF.md "Static analysis"). Bands are deliberately loose: counts
# drift a few percent across jax versions; a band breach means
# structural growth, not noise.
#
# Pinned 2026-08 (jax 0.4.37, threefry, CPU trace) — measured eqns /
# gathers / scatters: observe 78/0/0 (identical before and after the
# implicit-dtype lint fixes, and to the tests/test_jaxpr_budget.py pin
# this table absorbed), decima_score 491/8/2, decima_batch_policy
# 733/13/2, ppo_update 2856/43/3 (re-measured 2860/43/3 after the
# ISSUE-6 fold_in minibatch-key derivation).
#
# Re-pinned 2026-08-03 for the ISSUE-7 fused bulk kernel
# (core._bulk_events_fused replaces the relaunch+ready pass pair;
# drain_to_decision additionally moved to the cheap existence-bit cond
# + unmasked body): the fusion SHRANK the audited programs —
# micro_step 4734/69/1 -> 4044/29/1, drain_to_decision 3374/45/1 ->
# 2539/5/1, flat_collect_batch 13407/216/18 -> 12513/190/18;
# decide_micro_step unchanged at 2729/28/1 (its bulk phase is the
# mode-exclusive fulfill pass, deliberately left unfused). Caps below
# tightened to ~1.35x the new measurements per the band policy; the
# fusion A/B bench rows live in PERF.md round 11.
# ---------------------------------------------------------------------------

BUDGETS: dict[str, Budget] = {
    # round 8 replaced observe's S-deep [J,S,S] fori_loop with the
    # state-maintained node_level cache: the program must stay loop-free
    # and within a small eqn band (migrated from test_jaxpr_budget.py)
    "observe": Budget(
        eqn_lo=20, eqn_hi=110, gather_hi=2, scatter_hi=2, loop_free=True,
    ),
    # one flat micro-step at the shipped bulk config (be=8,
    # fulfill_bulk, cycles=1, fused bulk kernel) — the engine's unit
    # of work (the scan is the fused event run, not a decision loop)
    "micro_step": Budget(
        eqn_lo=2000, eqn_hi=5500, gather_hi=40, scatter_hi=3,
    ),
    # the single-eval collectors' policy-bearing micro-step
    "decide_micro_step": Budget(
        eqn_lo=1000, eqn_hi=3700, gather_hi=40, scatter_hi=3,
    ),
    # the single-eval collectors' non-policy drain (while-loop by
    # design: it runs until the lane is ready to DECIDE again; the
    # ISSUE-7 restructure keeps its cond to the event existence bit
    # and drops the per-iteration full-pytree rollback select)
    "drain_to_decision": Budget(
        eqn_lo=1200, eqn_hi=3450, gather_hi=8, scatter_hi=3,
    ),
    # Decima stage/exec scores over a [B]-stacked feature set, both
    # compaction branches under the scalar cond (the scan is the
    # level-wise GNN message pass)
    "decima_score": Budget(
        eqn_lo=150, eqn_hi=670, gather_hi=12, scatter_hi=4,
    ),
    # score + per-lane masked sampling over a lane stack
    "decima_batch_policy": Budget(
        eqn_lo=250, eqn_hi=990, gather_hi=18, scatter_hi=4,
    ),
    # one PPO update (epochs x minibatches scan, remat'd GNN recompute)
    "ppo_update": Budget(
        eqn_lo=1000, eqn_hi=3900, gather_hi=60, scatter_hi=5,
    ),
    # the single-eval batch collector over a native [B] lane axis —
    # the program the dp mesh shards (ISSUE 6): decide + drain + ONE
    # Decima batch_policy per decision row inside a short scan, with
    # the per-decision buffer scatters. The jaxpr is dp-invariant
    # (sharding is applied at lowering, not tracing), which is exactly
    # what makes this CPU audit valid for the sharded configuration;
    # the HLO-level collective census lives in tests/test_parallel.py.
    "flat_collect_batch": Budget(
        eqn_lo=9000, eqn_hi=16900, gather_hi=257, scatter_hi=25,
    ),
    # ISSUE 9: the `health:`-on variants of the two production
    # programs. Pinned 2026-08-03 — ppo_update_health 3209/43/3 (the
    # grad sentinels + per-minibatch skip gate cost ~12% eqns, zero
    # extra gathers/scatters), flat_collect_batch_health 12734/190/20
    # (per-decision-row state sentinels ride the telemetry carry:
    # +1.8% eqns, +2 scatters from the conservation goldens). The
    # default-off programs above are byte-for-byte the PR-7 pins —
    # which is the acceptance bar: health off must change nothing.
    "ppo_update_health": Budget(
        eqn_lo=1000, eqn_hi=4350, gather_hi=60, scatter_hi=5,
    ),
    "flat_collect_batch_health": Budget(
        eqn_lo=9000, eqn_hi=17200, gather_hi=257, scatter_hi=27,
    ),
    # ISSUE 10: the AOT decision-serving programs (serve/aot.py),
    # pinned 2026-08-04 — serve_decide 6514/33/65, serve_decide_batch
    # 12853/251/65 (store capacity 8 / batch 4 at audit scale). The
    # high scatter count is structural: the store scatter-back writes
    # each of the ~50 LoopState leaves at the served slot(s) — one
    # dynamic-update per leaf, in-place under donation. The while is
    # `drain_to_decision` (the inter-decision drain, by design); the
    # scan is the GNN level pass + the bulk event kernel.
    "serve_decide": Budget(
        eqn_lo=3000, eqn_hi=8800, gather_hi=45, scatter_hi=88,
    ),
    "serve_decide_batch": Budget(
        eqn_lo=6000, eqn_hi=17400, gather_hi=339, scatter_hi=88,
    ),
    # ISSUE 13: the dp-sharded store variant (serve/aot.py
    # `serve_decide_batch_fn(..., shard=...)`), pinned 2026-08-04 —
    # 12975/251/65: exactly the unsharded batch program plus one
    # sharding_constraint eqn per store leaf at entry and exit. The
    # constraint count is MESH-SIZE-INVARIANT (the mesh is a lowering
    # parameter, not an equation — measured identical at 1 and 8
    # devices), so the pin holds on the 1-device analysis CLI and the
    # 8-virtual-device test mesh alike; the unsharded programs above
    # re-measured byte-identical, which is the acceptance bar (shard
    # off must change nothing).
    "serve_decide_batch_sharded": Budget(
        eqn_lo=6000, eqn_hi=17500, gather_hi=339, scatter_hi=88,
    ),
    # ISSUE 14: the record-on serve variants (serve/aot.py
    # `record=True` — the online trajectory path's programs), pinned
    # 2026-08-04 — serve_decide_record 6520/33/65,
    # serve_decide_batch_record 12860/251/65: +6/+7 eqns over the
    # record-off programs (the StoredObs assembly is masked selects
    # over already-computed observation pieces; zero extra
    # gathers/scatters). Two things were re-measured in the same PR:
    # (a) the record-off programs above are BYTE-IDENTICAL to the
    # PR-10/13 pins, and (b) moving the model params from closure
    # constants to runtime arguments (the hot-swap refactor) changed
    # NO count on any serve program — params enter as invars, the
    # traced computation is the same.
    "serve_decide_record": Budget(
        eqn_lo=3000, eqn_hi=8810, gather_hi=45, scatter_hi=88,
    ),
    "serve_decide_batch_record": Budget(
        eqn_lo=6000, eqn_hi=17410, gather_hi=339, scatter_hi=88,
    ),
    # ISSUE 15: the GROUP-shaped serve program (the pipelined store's
    # [hot_capacity/groups] lowering — serve/aot.py
    # `serve_decide_batch_group`), pinned 2026-08-04 at 12853/251/65:
    # byte-identical counts to `serve_decide_batch`, which is the
    # acceptance bar — slot groups are host-side call routing, and a
    # "grouped" program that started diverging structurally from the
    # ungrouped one (extra copies, a gather over groups) would breach
    # here first. All pre-ISSUE-15 serve programs re-measured
    # byte-identical in the same PR (the take_slot/write_slot
    # refactor moved code, not equations).
    "serve_decide_batch_group": Budget(
        eqn_lo=6000, eqn_hi=17400, gather_hi=339, scatter_hi=88,
    ),
    # ISSUE 18: the ring-recording serve programs (serve/aot.py
    # `serve_decide_ring_fn` / `serve_decide_batch_ring_fn` — the
    # device-resident trajectory path), pinned 2026-08-07 —
    # serve_decide_record_ring 6648/33/86,
    # serve_decide_batch_record_ring 12996/252/86. The +21 scatters
    # over the record programs are structural: ring_append writes
    # each of the 21 RingRec leaves (12 decision scalars + the
    # StoredObs pieces) at the masked cursor position — one
    # dynamic-update per leaf, in-place under ring donation, with the
    # drop-mode lane for masked-off appends. +~130 eqns are the
    # cursor/offset arithmetic and the record assembly. Every
    # record-OFF and record-on-ring-OFF serve program above
    # re-measured BYTE-IDENTICAL in the same PR — the zero-cost-off
    # acceptance bar.
    "serve_decide_record_ring": Budget(
        eqn_lo=3000, eqn_hi=8980, gather_hi=45, scatter_hi=117,
    ),
    "serve_decide_batch_record_ring": Budget(
        eqn_lo=6000, eqn_hi=17550, gather_hi=341, scatter_hi=117,
    ),
}


# ---------------------------------------------------------------------------
# jaxpr walking
# ---------------------------------------------------------------------------


def iter_eqns(jaxpr):
    """Yield every equation including nested sub-jaxprs (cond/scan/while
    branches, closed calls, custom_* wrappers)."""
    for eqn in jaxpr.eqns:
        yield eqn
        for v in eqn.params.values():
            for sub in v if isinstance(v, (list, tuple)) else [v]:
                if hasattr(sub, "jaxpr"):
                    yield from iter_eqns(sub.jaxpr)
                elif hasattr(sub, "eqns"):
                    yield from iter_eqns(sub)


def count_eqns(jaxpr) -> int:
    return sum(1 for _ in iter_eqns(jaxpr))


def primitive_counts(jaxpr) -> Counter:
    return Counter(e.primitive.name for e in iter_eqns(jaxpr))


def _gather_count(prims: Counter) -> int:
    return sum(n for p, n in prims.items() if p == "gather")


def _scatter_count(prims: Counter) -> int:
    return sum(n for p, n in prims.items() if p.startswith("scatter"))


def _iter_avals(jaxpr):
    for v in list(jaxpr.invars) + list(jaxpr.outvars) + list(
            jaxpr.constvars):
        yield getattr(v, "aval", None)
    for eqn in iter_eqns(jaxpr):
        for v in list(eqn.invars) + list(eqn.outvars):
            yield getattr(v, "aval", None)


def wide_dtype_avals(jaxpr) -> list[str]:
    found = []
    for aval in _iter_avals(jaxpr):
        dt = getattr(aval, "dtype", None)
        if dt is not None and str(dt) in WIDE_DTYPES:
            found.append(f"{dt}{tuple(getattr(aval, 'shape', ()))}")
    return found


def audit_closed_jaxpr(name: str, closed, budget: Budget
                       ) -> tuple[list[Violation], dict[str, Any]]:
    """Apply every jaxpr rule to one traced program. Returns the
    violations plus the measured counts (the re-pin surface)."""
    jaxpr = closed.jaxpr
    prims = primitive_counts(jaxpr)
    n_eqns = sum(prims.values())
    n_gather = _gather_count(prims)
    n_scatter = _scatter_count(prims)
    measured = {
        "eqns": n_eqns,
        "gathers": n_gather,
        "scatters": n_scatter,
        "loops": sorted(set(prims) & LOOP_PRIMS),
    }
    found: list[Violation] = []

    callbacks = {
        p for p in prims
        if "callback" in p or p in ("outside_call", "host_callback")
    }
    bad_cb = callbacks - set(budget.callback_allow)
    if bad_cb:
        found.append(Violation(
            "jaxpr", "host-callback", name,
            f"callback primitives {sorted(bad_cb)} present "
            f"({sum(prims[p] for p in bad_cb)} call sites) — host "
            "callbacks serialize the dispatch pipeline; allowlist "
            "explicitly in BUDGETS if deliberate",
        ))

    wide = wide_dtype_avals(jaxpr)
    if wide:
        found.append(Violation(
            "jaxpr", "wide-dtype", name,
            f"{len(wide)} f64/i64-family avals in the jaxpr (e.g. "
            f"{wide[:3]}) — a single wide leaf doubles memory traffic "
            "and recompiles every consumer",
        ))

    loops = set(prims) & LOOP_PRIMS
    if budget.loop_free and loops:
        found.append(Violation(
            "jaxpr", "loop-free", name,
            f"loop primitives {sorted(loops)} in a pinned-loop-free "
            "program — the data-dependent loop this pin exists to keep "
            "out came back",
        ))

    if not (budget.eqn_lo <= n_eqns <= budget.eqn_hi):
        found.append(Violation(
            "jaxpr", "budget", name,
            f"eqn count {n_eqns} outside [{budget.eqn_lo}, "
            f"{budget.eqn_hi}] — structural op growth (or a stale "
            "budget); re-measure and re-pin in the same PR with a "
            "bench row justifying it",
        ))
    if n_gather > budget.gather_hi:
        found.append(Violation(
            "jaxpr", "budget", name,
            f"gather count {n_gather} > {budget.gather_hi}",
        ))
    if n_scatter > budget.scatter_hi:
        found.append(Violation(
            "jaxpr", "budget", name,
            f"scatter count {n_scatter} > {budget.scatter_hi}",
        ))
    return found, measured


# ---------------------------------------------------------------------------
# audit config + program registry
# ---------------------------------------------------------------------------

_SETUP_CACHE: list = []


def audit_setup():
    """(params, bank, reset-state ShapeDtypeStruct pytree) under the
    audit config — shared with the contracts pass so both agree on
    shapes. The bank is real data (host numpy -> device constants);
    the state is abstract."""
    if _SETUP_CACHE:
        return _SETUP_CACHE[0]
    import jax

    from ..config import EnvParams
    from ..env import core
    from ..workload import make_workload_bank

    params = EnvParams(
        num_executors=10, max_jobs=20, max_stages=20, max_levels=20
    )
    bank = make_workload_bank(params.num_executors, params.max_stages)
    params = params.replace(
        max_stages=bank.max_stages, max_levels=bank.max_stages
    )
    key = jax.eval_shape(lambda: jax.random.PRNGKey(0))
    state = jax.eval_shape(lambda k: core.reset(params, bank, k), key)
    _SETUP_CACHE.append((params, bank, state))
    return _SETUP_CACHE[0]


def _shipped_agent_kwargs() -> dict[str, Any]:
    """The shipped Decima architecture (config/decima_tpch.yaml agent
    section). Hard-coded rather than YAML-loaded so the audit is
    self-contained; drift is caught by the budget band moving."""
    return {
        "embed_dim": 16,
        "gnn_mlp_kwargs": {
            "hid_dims": [32, 16],
            "act_cls": "LeakyReLU",
            "act_kwargs": {"negative_slope": 0.2},
        },
        "policy_mlp_kwargs": {"hid_dims": [64, 64], "act_cls": "Tanh"},
    }


def _batched(tree, b: int):
    import jax

    return jax.tree_util.tree_map(
        lambda l: jax.ShapeDtypeStruct((b,) + tuple(l.shape), l.dtype),
        tree,
    )


# per-lane programs of the registry: the ones that run under a lane
# vmap in production (bench.py / the flat collectors), and therefore
# the ones the memory pass lane-batches for the bank-broadcast rule
# and the lane-fit advisor
LANE_PROGRAMS = (
    "observe", "micro_step", "decide_micro_step", "drain_to_decision",
)

# batch programs: registry programs that take the lane axis NATIVELY
# (no outer vmap) — the single-eval collectors the dp mesh shards. The
# memory pass applies the bank-broadcast rule to their traced batch
# axis directly and drives the lane-fit advisor by re-tracing at each
# base batch width (`flat_collect_batch_callable(batch)`).
BATCH_LANE_PROGRAMS = ("flat_collect_batch",)

# lane/scan widths of the audited batch collector: 4 lanes x 3
# decision rows keeps the ~13k-eqn trace a few seconds while still
# containing every production phase (batch policy, decide, drain,
# scatter) — eqn counts are shape-independent, so the budgets hold at
# flagship scale
AUDIT_COLLECT_BATCH = 4
AUDIT_COLLECT_STEPS = 3


def flat_collect_batch_callable(
    batch: int = AUDIT_COLLECT_BATCH,
    health: bool = False,
) -> tuple[Callable, tuple]:
    """The single-eval flat sync collector over a native [batch] lane
    axis with the shipped Decima batch policy — the program
    `parallel:` mesh configs shard over dp
    (trainers/rollout.py:collect_flat_sync_batch; the async variant
    shares the same scan body). As (callable, abstract args); `batch`
    parameterizes the lane width so the memory pass can fit its
    per-lane byte model from two widths. With `health`, the in-JIT
    sentinels ride a telemetry carry — the `health:`-on production
    configuration, audited as `flat_collect_batch_health` so the
    sentinel cost stays inside its own eqn/byte budget."""
    import jax

    from ..obs.telemetry import telemetry_zeros_like
    from ..schedulers.decima import DecimaScheduler
    from ..trainers.rollout import collect_flat_sync_batch

    params, bank, state = audit_setup()
    # compaction bucket scaled to the audit job cap, as for the
    # decima_* programs, so BOTH score branches are in the audit
    sched = DecimaScheduler(
        num_executors=params.num_executors, job_bucket=8,
        **_shipped_agent_kwargs(),
    )
    key = jax.eval_shape(lambda: jax.random.PRNGKey(0))
    states_b = _batched(state, batch)
    telem = (
        jax.eval_shape(lambda: telemetry_zeros_like((batch,)))
        if health else None
    )

    def fn(s, r):
        return collect_flat_sync_batch(
            params, bank,
            lambda rr, oo: sched.batch_policy(rr, oo),
            r, AUDIT_COLLECT_STEPS, s, telem,
            event_bulk=True, bulk_events=8, fulfill_bulk=True,
            bulk_cycles=1, health=health,
        )

    return fn, (states_b, key)


def lane_callables() -> dict[str, tuple[Callable, tuple]]:
    """The per-lane registry programs as (callable, UNBATCHED abstract
    args) — shared by the unbatched jaxpr trace below and the memory
    pass's vmapped traces, so the two passes cannot audit different
    programs under the same name."""
    import jax
    import jax.numpy as jnp

    from ..env.flat_loop import (
        decide_micro_step,
        drain_to_decision,
        init_loop_state,
        micro_step,
    )
    from ..env.observe import observe
    from ..schedulers.heuristics import round_robin_policy

    params, bank, state = audit_setup()
    key = jax.eval_shape(lambda: jax.random.PRNGKey(0))
    ls = jax.eval_shape(init_loop_state, state)
    i32 = jax.ShapeDtypeStruct((), jnp.int32)

    def pol(rng, obs):
        si, ne = round_robin_policy(obs, params.num_executors, True)
        return si, ne, {}

    return {
        "observe": (lambda s: observe(params, s), (state,)),
        # the shipped bulk config: be=8, fulfill_bulk on, one cycle
        # (compute_levels=False as in bench.py's driving loop)
        "micro_step": (
            lambda l, r: micro_step(
                params, bank, pol, l, r, True, False, True, 8, True, 1
            ),
            (ls, key),
        ),
        "decide_micro_step": (
            lambda l, si, ne, r: decide_micro_step(
                params, bank, l, si, ne, r, True, True
            ),
            (ls, i32, i32, key),
        ),
        "drain_to_decision": (
            lambda l, r: drain_to_decision(
                params, bank, l, r, True, True, 8, 1
            ),
            (ls, key),
        ),
    }


_PROGRAMS_CACHE: dict = {}


def program_callables(names: tuple[str, ...] | None = None
                      ) -> dict[str, tuple[Callable, tuple]]:
    """Every registered hot program as (callable, abstract args) —
    the single registry behind the unbatched jaxpr traces (this pass),
    the memory pass's vmapped traces, and the chip session's on-device
    `memory_analysis()` capture."""
    import jax

    from ..env.observe import observe
    from ..schedulers.decima import DecimaScheduler

    params, bank, state = audit_setup()
    key = jax.eval_shape(lambda: jax.random.PRNGKey(0))
    want = set(names) if names is not None else None

    out: dict[str, tuple[Callable, tuple]] = {}
    for name, entry in lane_callables().items():
        if want is None or name in want:
            out[name] = entry

    if want is None or want & {"decima_score", "decima_batch_policy"}:
        # compaction bucket scaled to the audit job cap (flagship K=32
        # over a 200-job cap -> K=8 over 20) so the cond's BOTH
        # branches are in the audited program
        sched = DecimaScheduler(
            num_executors=params.num_executors, job_bucket=8,
            **_shipped_agent_kwargs(),
        )
        obs_b = jax.eval_shape(
            lambda s: jax.vmap(lambda x: observe(params, x))(s),
            _batched(state, 4),
        )
        feats_b = jax.eval_shape(
            lambda o: jax.vmap(sched.features)(o), obs_b
        )
        if want is None or "decima_score" in want:
            out["decima_score"] = (
                lambda f: sched.score(sched.params, f), (feats_b,)
            )
        if want is None or "decima_batch_policy" in want:
            out["decima_batch_policy"] = (
                lambda r, o: sched.batch_policy(r, o), (key, obs_b)
            )

    if want is None or want & {
        "serve_decide", "serve_decide_batch",
        "serve_decide_batch_sharded", "serve_decide_record",
        "serve_decide_batch_record", "serve_decide_batch_group",
        "serve_decide_record_ring", "serve_decide_batch_record_ring",
    }:
        # ISSUE 10/13: the AOT decision service's programs (serving
        # store capacity 8, micro-batch width 4 at audit scale; the
        # production programs differ only in buffer widths), plus the
        # dp-sharded store variant. Traced here exactly as
        # `serve/aot.py` lowers them, so the audited jaxpr IS the
        # compiled serving program.
        from ..serve.aot import serve_callables

        for name, entry in serve_callables().items():
            if want is None or name in want:
                out[name] = entry

    if want is None or "ppo_update" in want:
        out["ppo_update"] = ppo_update_callable()
    if want is None or "flat_collect_batch" in want:
        out["flat_collect_batch"] = flat_collect_batch_callable()
    # the `health:`-on variants (ISSUE 9): the sentinel-instrumented
    # production programs, budgeted separately so (a) the opt-in cost
    # is visible and capped, and (b) the default programs above prove
    # the off path is structurally unchanged
    if want is None or "ppo_update_health" in want:
        out["ppo_update_health"] = ppo_update_callable(health=True)
    if want is None or "flat_collect_batch_health" in want:
        out["flat_collect_batch_health"] = flat_collect_batch_callable(
            health=True
        )
    return out


def build_programs(names: tuple[str, ...] | None = None
                   ) -> dict[str, Any]:
    """Trace the registered hot programs; returns name -> ClosedJaxpr.
    Order is cheap-first. `names` restricts the registry (the thin
    test wrappers trace only what they pin). The full-registry result
    is memoized per process: the jaxpr and memory passes both consume
    it, and re-tracing ~15k equations for the second pass would double
    the gate's cost for identical jaxprs."""
    import jax

    if names is None and _PROGRAMS_CACHE:
        return dict(_PROGRAMS_CACHE)
    programs = {
        name: jax.make_jaxpr(fn)(*args)
        for name, (fn, args) in program_callables(names).items()
    }
    if names is None:
        _PROGRAMS_CACHE.update(programs)
    return programs


def _trace_ppo_update():
    import jax

    fn, args = ppo_update_callable()
    return jax.make_jaxpr(fn)(*args)


def ppo_update_callable(health: bool = False) -> tuple[Callable, tuple]:
    """One PPO update at a tiny audit scale (2 lanes, 16 decision
    steps), as (callable, abstract args). The rollout is abstract
    (`eval_shape` over `_collect`), so nothing episode-sized executes;
    tracing/lowering the callable then hits the real epochs x
    minibatches scan with the remat'd GNN recompute. With `health`,
    the update carries the in-JIT grad sentinels + minibatch skip gate
    (audited as `ppo_update_health`)."""
    import jax
    import jax.numpy as jnp

    from ..trainers.ppo import PPO

    agent_cfg = {"agent_cls": "DecimaScheduler"} | _shipped_agent_kwargs()
    env_cfg = {
        "num_executors": 5,
        "job_arrival_cap": 3,
        "moving_delay": 2000.0,
        "mean_time_limit": 2.0e7,
        "job_arrival_rate": 4.0e-5,
        "warmup_delay": 1000.0,
    }
    train_cfg = {
        "trainer_cls": "PPO",
        "num_iterations": 1,
        "num_sequences": 1,
        "num_rollouts": 2,
        "seed": 0,
        "use_tensorboard": False,
        "num_epochs": 1,
        "num_batches": 2,
        "beta_discount": 5.0e-3,
        "opt_kwargs": {"lr": 3.0e-4},
        "max_grad_norm": 0.5,
        "rollout_steps": 16,
        "checkpointing_freq": 10**9,
    }
    trainer = PPO(
        agent_cfg, env_cfg, train_cfg,
        health_cfg={"enabled": True} if health else None,
    )
    state = jax.eval_shape(trainer.init_state)
    it = jax.ShapeDtypeStruct((), jnp.int32)
    key = jax.eval_shape(lambda: jax.random.PRNGKey(0))
    ro, _, _ = jax.eval_shape(
        lambda p, i, r: trainer._collect(p, i, r, None),
        state.params, it, key,
    )
    return trainer._update, (state, ro)


def audit_all(names: tuple[str, ...] | None = None
              ) -> tuple[list[Violation], dict[str, Any]]:
    """Trace + audit every registered program (or the `names` subset).
    Returns (violations, measured-counts dict for the report)."""
    if names is not None:
        unknown = set(names) - set(BUDGETS)
        if unknown:
            raise ValueError(
                f"unknown program name(s) {sorted(unknown)} — the "
                "registry is the BUDGETS table's key set"
            )
    programs = build_programs(names)
    found: list[Violation] = []
    measured: dict[str, Any] = {}
    for name, closed in programs.items():
        if name not in BUDGETS:
            found.append(Violation(
                "jaxpr", "budget", name,
                "program has no entry in the BUDGETS table",
            ))
            continue
        vs, m = audit_closed_jaxpr(name, closed, BUDGETS[name])
        found.extend(vs)
        measured[name] = m
    return found, measured
