"""Pytree contract checker: declared dtype/shape schemas for the
pytrees that cross the jit boundary every iteration.

The recompile hazard this pass pins: XLA keys compiled executables on
the (structure, dtype, shape) signature of every input, so a field
that drifts — an f32 that becomes weak-f64 under a stray promotion, a
shape that silently follows a config change, a leaf added to
`EnvState` without a schema update — recompiles every consumer and
invalidates the budget table. Schemas here are *data*: the auditor
reads them (static verification via `jax.eval_shape` — nothing
executes), and tests run the cheap runtime-assert mode around real
episodes to pin that `reset`/`step`/`micro_step` never change a
field's structure, dtype, or shape mid-run.

Shape entries are dim tokens resolved against the `EnvParams` under
audit: ``J`` = max_jobs, ``S`` = max_stages, ``N`` = num_executors,
``*`` = any size (the rng key length is PRNG-impl-dependent:
threefry uint32[2] vs rbg uint32[4]).

Rules reported by `check_all` (all under pass "contracts"):

- ``env-state-schema``: `core.reset`'s output matches ENV_STATE_SCHEMA
  exactly — field set, dtypes, shapes (no unknown or missing leaves).
- ``telemetry-schema``: every `Telemetry` counter is an i32 scalar
  (vmapped engines prepend lane axes; the schema checks the trailing
  shape).
- ``trajectory-schema``: the flat engine's `MicroRec` action/reward
  leaves and the collectors' `StoredObs` record match their declared
  dtypes/shapes — an f64 smuggled into the rollout buffer doubles its
  footprint and poisons the update's compile key.
- ``step-invariance``: `core.step` and flat `micro_step` return an
  `EnvState` with the *identical* spec as their input (via eval_shape;
  the recompile hazard directly).
"""

from __future__ import annotations

from typing import Any

from . import Violation

SCHEMA_NAMES = (
    "EnvState", "Telemetry", "MicroRec", "StoredObs",
)

# --- schemas (declarative data) -------------------------------------------

ENV_STATE_SCHEMA: dict[str, tuple[str, tuple]] = {
    "rng": ("uint32", ("*",)),
    "wall_time": ("float32", ()),
    "time_limit": ("float32", ()),
    "seq_counter": ("int32", ()),
    "round_ready": ("bool", ()),
    "terminated": ("bool", ()),
    "truncated": ("bool", ()),
    "job_template": ("int32", ("J",)),
    "job_arrival_time": ("float32", ("J",)),
    "job_arrival_seq": ("int32", ("J",)),
    "job_arrived": ("bool", ("J",)),
    "job_t_completed": ("float32", ("J",)),
    "job_num_stages": ("int32", ("J",)),
    "job_saturated_stages": ("int32", ("J",)),
    "job_supply": ("int32", ("J",)),
    "num_jobs": ("int32", ()),
    "stage_exists": ("bool", ("J", "S")),
    "stage_num_tasks": ("int32", ("J", "S")),
    "stage_remaining": ("int32", ("J", "S")),
    "stage_executing": ("int32", ("J", "S")),
    "stage_completed_tasks": ("int32", ("J", "S")),
    "stage_duration": ("float32", ("J", "S")),
    "stage_selected": ("bool", ("J", "S")),
    "schedulable": ("bool", ("J", "S")),
    "adj": ("bool", ("J", "S", "S")),
    "exec_at_common": ("bool", ("N",)),
    "exec_job": ("int32", ("N",)),
    "exec_stage": ("int32", ("N",)),
    "exec_moving": ("bool", ("N",)),
    "exec_dst_job": ("int32", ("N",)),
    "exec_dst_stage": ("int32", ("N",)),
    "exec_arrive_time": ("float32", ("N",)),
    "exec_arrive_seq": ("int32", ("N",)),
    "exec_executing": ("bool", ("N",)),
    "exec_task_valid": ("bool", ("N",)),
    "exec_task_stage": ("int32", ("N",)),
    "exec_finish_time": ("float32", ("N",)),
    "exec_finish_seq": ("int32", ("N",)),
    "stage_sat": ("bool", ("J", "S")),
    "unsat_parent_count": ("int32", ("J", "S")),
    "incomplete_parent_count": ("int32", ("J", "S")),
    "node_level": ("int32", ("J", "S")),
    "commit_count": ("int32", ("J", "S")),
    "moving_count": ("int32", ("J", "S")),
    "cm_valid": ("bool", ("N",)),
    "cm_src_job": ("int32", ("N",)),
    "cm_src_stage": ("int32", ("N",)),
    "cm_dst_job": ("int32", ("N",)),
    "cm_dst_stage": ("int32", ("N",)),
    "cm_seq": ("int32", ("N",)),
    "source_valid": ("bool", ()),
    "source_job": ("int32", ()),
    "source_stage": ("int32", ()),
}

# every engine counter is an i32 scalar per lane (telemetry.py)
TELEMETRY_SCHEMA_DTYPE = "int32"

# MicroRec's non-obs leaves (obs is checked against the Observation the
# engine builds — its shapes follow EnvParams and need no extra pins)
MICRO_REC_SCHEMA: dict[str, tuple[str, tuple]] = {
    "stage_idx": ("int32", ()),
    "job_idx": ("int32", ()),
    "num_exec_k": ("int32", ()),
    "lgprob": ("float32", ()),
    "decide": ("bool", ()),
    "reward": ("float32", ()),
    "dt": ("float32", ()),
    "reset": ("bool", ()),
}

STORED_OBS_SCHEMA: dict[str, tuple[str, tuple]] = {
    "remaining": ("int32", ("J", "S")),
    # the audited layout; `env: {obs_dtype: bfloat16}` configs narrow
    # this leaf to bf16 (ISSUE 7) — the audit always runs the default
    # f32 params, so the pin holds for CI while the low-precision
    # layout stays an explicit per-config opt-in
    "duration": ("float32", ("J", "S")),
    "schedulable": ("bool", ("J", "S")),
    "node_mask": ("bool", ("J", "S")),
    "job_mask": ("bool", ("J",)),
    "job_template": ("int32", ("J",)),
    "exec_supplies": ("int32", ("J",)),
    "num_committable": ("int32", ()),
    "source_job": ("int32", ()),
}


# --- core machinery --------------------------------------------------------


def dims_from_params(params) -> dict[str, int]:
    return {
        "J": params.max_jobs,
        "S": params.max_stages,
        "N": params.num_executors,
    }


def _shape_matches(shape: tuple, spec: tuple, dims: dict[str, int]) -> bool:
    if len(shape) != len(spec):
        return False
    for got, want in zip(shape, spec):
        if want == "*":
            continue
        if got != dims.get(want, want):
            return False
    return True


def check_fields(
    obj: Any,
    schema: dict[str, tuple[str, tuple]],
    dims: dict[str, int],
    where: str,
    batch_ndim: int = 0,
) -> list[Violation]:
    """Check a dataclass-style pytree (concrete arrays OR
    ShapeDtypeStructs — anything with .dtype/.shape) against a schema.
    `batch_ndim` leading axes are ignored on every leaf (vmapped/
    scanned containers). Reports unknown fields too: a leaf added
    without a schema update is itself a contract violation."""
    found: list[Violation] = []
    if isinstance(obj, dict):
        names = set(obj)
        get = obj.__getitem__
    else:
        fields = getattr(obj, "__dataclass_fields__", None)
        names = set(fields) if fields is not None else set(vars(obj))
        get = lambda n: getattr(obj, n)  # noqa: E731
    for name in sorted(names - set(schema)):
        found.append(Violation(
            "contracts", "env-state-schema" if "EnvState" in where
            else "trajectory-schema",
            f"{where}.{name}",
            "field missing from the declared schema — declare its "
            "dtype/shape in analysis/contracts.py",
        ))
    for name, (dtype, shape) in schema.items():
        if name not in names:
            found.append(Violation(
                "contracts", "env-state-schema" if "EnvState" in where
                else "trajectory-schema",
                f"{where}.{name}", "declared field missing from pytree",
            ))
            continue
        leaf = get(name)
        got_dt = str(leaf.dtype)
        got_shape = tuple(leaf.shape)[batch_ndim:]
        if got_dt != dtype:
            found.append(Violation(
                "contracts", "env-state-schema" if "EnvState" in where
                else "trajectory-schema",
                f"{where}.{name}",
                f"dtype {got_dt}, schema says {dtype}",
            ))
        if not _shape_matches(got_shape, shape, dims):
            found.append(Violation(
                "contracts", "env-state-schema" if "EnvState" in where
                else "trajectory-schema",
                f"{where}.{name}",
                f"shape {got_shape}, schema says {shape} with {dims}",
            ))
    return found


def check_env_state(state, params, where: str = "EnvState",
                    batch_ndim: int = 0) -> list[Violation]:
    return check_fields(
        state, ENV_STATE_SCHEMA, dims_from_params(params), where,
        batch_ndim,
    )


def check_telemetry(tm, where: str = "Telemetry",
                    batch_ndim: int = 0) -> list[Violation]:
    """Every counter must be an i32 SCALAR past the `batch_ndim`
    leading lane axes a vmapped engine prepends — a counter silently
    widened to a vector changes the scan carry's compile key on every
    consumer."""
    found: list[Violation] = []
    for name in tm.__dataclass_fields__:
        leaf = getattr(tm, name)
        if str(leaf.dtype) != TELEMETRY_SCHEMA_DTYPE:
            found.append(Violation(
                "contracts", "telemetry-schema", f"{where}.{name}",
                f"dtype {leaf.dtype}, every counter must be "
                f"{TELEMETRY_SCHEMA_DTYPE}",
            ))
        trailing = tuple(leaf.shape)[batch_ndim:]
        if trailing != ():
            found.append(Violation(
                "contracts", "telemetry-schema", f"{where}.{name}",
                f"trailing shape {trailing}, every counter must be a "
                "scalar past the lane axes",
            ))
    return found


# --- runtime-assert mode ---------------------------------------------------


def spec_of(tree) -> list[tuple[str, str, tuple]]:
    """Flat (path, dtype, shape) signature of a pytree — the exact
    quantity XLA keys compiled executables on. Host-side and cheap
    (reads metadata only, no device sync)."""
    import jax

    leaves = jax.tree_util.tree_leaves_with_path(tree)
    return [
        (jax.tree_util.keystr(path), str(leaf.dtype), tuple(leaf.shape))
        for path, leaf in leaves
        if hasattr(leaf, "dtype")
    ]


def diff_spec(before, after, where: str = "pytree") -> list[Violation]:
    """Spec difference between two snapshots of the same logical pytree
    — the runtime-assert core: any entry here would force a recompile."""
    b = {p: (d, s) for p, d, s in before}
    a = {p: (d, s) for p, d, s in after}
    found: list[Violation] = []
    for p in sorted(set(b) - set(a)):
        found.append(Violation(
            "contracts", "step-invariance", f"{where}{p}",
            "leaf disappeared across a step",
        ))
    for p in sorted(set(a) - set(b)):
        found.append(Violation(
            "contracts", "step-invariance", f"{where}{p}",
            "leaf appeared across a step",
        ))
    for p in sorted(set(a) & set(b)):
        if a[p] != b[p]:
            found.append(Violation(
                "contracts", "step-invariance", f"{where}{p}",
                f"{b[p]} -> {a[p]} across a step (recompile hazard)",
            ))
    return found


def assert_env_state(state, params, where: str = "EnvState",
                     batch_ndim: int = 0) -> None:
    """Runtime-assert mode: raise AssertionError listing every schema
    violation on a concrete state. Cheap (metadata only) — tests wrap
    episodes with it."""
    vs = check_env_state(state, params, where, batch_ndim)
    assert not vs, "\n".join(map(str, vs))


def assert_same_spec(before, after, where: str = "pytree") -> None:
    vs = diff_spec(before, after, where)
    assert not vs, "\n".join(map(str, vs))


# --- static verification (the auditor's contracts pass) --------------------


def check_all() -> list[Violation]:
    """Static contract verification under `jax.eval_shape` — nothing
    executes, so this pass is cheap and backend-independent. Uses the
    shared audit config from `jaxpr_audit` so the two passes agree on
    shapes."""
    import jax
    import jax.numpy as jnp

    from ..env import core
    from ..env.flat_loop import init_loop_state, micro_step
    from ..obs.telemetry import telemetry_zeros
    from .jaxpr_audit import audit_setup

    params, bank, state_sds = audit_setup()
    dims = dims_from_params(params)
    found: list[Violation] = []

    # env-state-schema: reset's output
    found.extend(check_env_state(state_sds, params, "reset->EnvState"))

    # telemetry-schema
    found.extend(check_telemetry(telemetry_zeros()))

    # step-invariance: core.step output state spec == input spec
    def run_step(s, si, ne, tm):
        out = core.step(params, bank, s, si, ne, telemetry=tm)
        return out[0], out[4]

    si = jax.ShapeDtypeStruct((), jnp.int32)
    tm0 = telemetry_zeros()
    out_state, out_tm = jax.eval_shape(run_step, state_sds, si, si, tm0)
    found.extend(diff_spec(
        spec_of(state_sds), spec_of(out_state), "core.step(EnvState)"
    ))
    found.extend(diff_spec(
        spec_of(tm0), spec_of(out_tm), "core.step(Telemetry)"
    ))

    # step-invariance + trajectory-schema: flat micro_step
    def pol(rng, obs):
        from ..schedulers.heuristics import round_robin_policy

        s_idx, ne = round_robin_policy(obs, params.num_executors, True)
        return s_idx, ne, {}

    def run_micro(ls, r):
        return micro_step(
            params, bank, pol, ls, r, True, True, True, 8, True, 1,
            record=True,
        )

    ls0 = jax.eval_shape(init_loop_state, state_sds)
    key = jax.eval_shape(lambda: jax.random.PRNGKey(0))
    ls1, rec = jax.eval_shape(run_micro, ls0, key)
    found.extend(diff_spec(
        spec_of(ls0), spec_of(ls1), "micro_step(LoopState)"
    ))
    # every MicroRec field except obs goes through check_fields, so a
    # leaf added without a schema update (the f64-into-the-rollout-
    # buffer hazard) is reported as unknown, and a renamed/removed
    # field is reported as missing rather than crashing the pass
    rec_no_obs = {
        k: getattr(rec, k)
        for k in rec.__dataclass_fields__ if k != "obs"
    }
    found.extend(check_fields(
        rec_no_obs, MICRO_REC_SCHEMA, dims, "MicroRec"
    ))

    # trajectory-schema: the collectors' stored-observation record
    from ..env.observe import observe
    from ..trainers.rollout import store_obs

    so = jax.eval_shape(
        lambda s: store_obs(observe(params, s), s), state_sds
    )
    found.extend(check_fields(so, STORED_OBS_SCHEMA, dims, "StoredObs"))
    return found
