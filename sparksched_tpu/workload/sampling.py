"""On-device workload sampling: Poisson job sequences and task durations.

Replaces reference tpch.py:54-106 (host-side Python sampling of job arrivals
and per-task durations). Everything here is shape-static and traced into the
environment's jitted step/reset."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..config import EnvParams
from .bank import WAVE_FIRST, WAVE_FRESH, WAVE_REST, WorkloadBank


def sample_job_sequence(
    params: EnvParams, bank: WorkloadBank, rng: jax.Array,
    time_limit: jnp.ndarray
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Sample up to `max_jobs` Poisson arrivals (reference tpch.py:54-73):
    the first job arrives at t=0, subsequent inter-arrival gaps are
    Exponential(1/rate); arrivals stop at the time limit or the cap.

    Returns (arrival_times[J] with inf padding, templates[J], arrived_cap
    num_jobs scalar, mask[J])."""
    j_cap = params.max_jobs
    k_gap, k_tpl = jax.random.split(rng)
    mean_gap = 1.0 / params.job_arrival_rate
    gaps = jax.random.exponential(k_gap, (j_cap,)) * mean_gap
    arrivals = jnp.concatenate(
        [jnp.zeros(1), jnp.cumsum(gaps)[: j_cap - 1]]
    ).astype(jnp.float32)
    mask = arrivals < time_limit
    mask = mask.at[0].set(True)  # first job must arrive at t=0
    # arrivals must be a prefix: a job only exists if all earlier ones do
    mask = jnp.cumprod(mask.astype(jnp.int32)).astype(bool)
    templates = jax.random.randint(
        k_tpl, (j_cap,), 0, bank.num_templates, dtype=jnp.int32
    )
    num_jobs = mask.sum().astype(jnp.int32)
    arrivals = jnp.where(mask, arrivals, jnp.inf)
    return arrivals, templates, num_jobs, mask


def sample_executor_key(
    bank: WorkloadBank, u: jnp.ndarray, template: jnp.ndarray,
    stage: jnp.ndarray, num_local: jnp.ndarray
) -> jnp.ndarray:
    """Map the executor count to a trace executor-level index, randomly
    interpolating between the two bracketing levels and falling back to the
    max level present for this stage (reference tpch.py:216-235).

    `u` is a pre-drawn Uniform[0,1) scalar, NOT a PRNG key: the round-5
    CPU decomposition measured the per-call rng plumbing (fold_in +
    split + uniform + randint per sampled task) at ~31% of the whole
    flat micro-step, while the bank-table gathers were free. Callers
    draw ONE batched uniform array per bulk pass and hand each row's
    slice down (see `sample_task_duration`)."""
    left_v = bank.itv_left_val[num_local]
    right_v = bank.itv_right_val[num_local]
    left_i = bank.itv_left_idx[num_local]
    right_i = bank.itv_right_idx[num_local]
    rand_pt = 1 + (u * (right_v - left_v)).astype(jnp.int32)
    use_left = (left_v == right_v) | (rand_pt <= num_local - left_v)
    key_idx = jnp.where(use_left, left_i, right_i)
    key_val = jnp.where(use_left, left_v, right_v)
    # the reference's interval table leaves index num_executors zeroed when
    # num_executors > 100 (tpch.py:258-260 excludes it); a 0 "level" is not
    # a first_wave key there, so it falls through to the max present level
    present = bank.level_present[template, stage, key_idx] & (key_val > 0)
    return jnp.where(present, key_idx, bank.max_present[template, stage])


def sample_task_duration(
    params: EnvParams, bank: WorkloadBank, u2: jnp.ndarray,
    template: jnp.ndarray, stage: jnp.ndarray, num_local: jnp.ndarray,
    task_valid: jnp.ndarray, same_stage: jnp.ndarray
) -> jnp.ndarray:
    """Sample one task duration, reproducing the reference's wave logic and
    try/except fallback chains (tpch.py:75-106):

    - executor idle (`task_valid` False — it was just sitting or moving):
      fresh_durations, else first_wave + warmup_delay;
    - executor continuing the same stage: rest_wave, else first_wave, else
      fresh_durations;
    - executor new to this stage: first_wave, else fresh_durations.

    A final fallback to the stage's rough mean duration replaces the
    reference's uncaught exception when a bucket is entirely empty.

    `u2` is f32[2] of pre-drawn Uniform[0,1) variates (NOT a key):
    u2[0] drives the executor-level interpolation, u2[1] the
    within-bucket pick. Hot callers (`_apply_action` and the three bulk
    passes in env/core.py) draw one batched uniform per pass — the
    per-row key plumbing this replaces was ~31% of the flat micro-step
    on the CPU backend (round-5 ablation), with identical per-row
    distributions (rows were independently keyed before, independent
    uniforms now; `pick = floor(u*n)` matches randint's law)."""
    li = sample_executor_key(bank, u2[0], template, stage, num_local)

    cnt = bank.cnt[template, stage, :, li]  # i32[3]
    has = cnt > 0
    fresh_i, first_i, rest_i = WAVE_FRESH, WAVE_FIRST, WAVE_REST

    # wave choice + warmup flag per the chains above
    idle_wave = jnp.where(has[fresh_i], fresh_i, first_i)
    idle_warm = ~has[fresh_i]
    same_wave = jnp.where(
        has[rest_i], rest_i, jnp.where(has[first_i], first_i, fresh_i)
    )
    diff_wave = jnp.where(has[first_i], first_i, fresh_i)

    wave = jnp.where(
        ~task_valid, idle_wave, jnp.where(same_stage, same_wave, diff_wave)
    )
    warm = jnp.where(~task_valid, idle_warm, False)

    n = jnp.maximum(cnt[wave], 1)
    pick = jnp.minimum((u2[1] * n).astype(jnp.int32), n - 1)
    dur = bank.dur[template, stage, wave, li, pick]
    if dur.dtype != jnp.float32:
        # low-precision bank layout (ISSUE 7): the gather stays narrow,
        # everything downstream accumulates in f32. Integer banks carry
        # a per-template LOG-domain dequantization scale (relative
        # error ~dur_scale/2 uniformly across the heavy tail — see
        # workload.quantize_bank); bf16 banks just upcast.
        dur = dur.astype(jnp.float32)
        if bank.dur_scale is not None:
            dur = jnp.expm1(dur * bank.dur_scale[template])
    dur = jnp.where(
        cnt[wave] > 0, dur, bank.rough_duration[template, stage]
    )
    return dur + jnp.where(warm, params.warmup_delay, 0.0)
