"""Workload template bank: DAG-job traces packed into device arrays.

The reference samples jobs from 22 TPC-H queries x 7 input sizes, loading
`adj_mat_*.npy` / `task_duration_*.npy` trace files per job and sampling task
durations from per-(stage, wave, executor-count-level) empirical lists
(reference: spark_sched_sim/data_samplers/tpch.py). That design — Python
dicts of variable-length lists consulted inside the event loop — cannot run
on a TPU.

Here every job *template* is packed once into fixed-shape arrays shared by
all environments:

- structure: `adj[T,S,S]`, `num_tasks[T,S]`, `num_stages[T]`, topological
  `node_level[T,S]` (precomputed for the GNN's level-wise message passing,
  replacing the per-observation nx.topological_generations of reference
  schedulers/decima/utils.py:238-267);
- durations: `dur[T,S,3,L,K]` buckets of K empirical samples per
  (stage, wave, executor-level), with counts `cnt[T,S,3,L]` and presence
  masks driving the same fallback chain as the reference's
  try/except sampling (tpch.py:75-106).

Sampling a duration on-device is then two integer gathers and one
`jax.random.randint` — no host round trip.
"""

from __future__ import annotations

import os.path as osp
from typing import Any

import numpy as np
from flax import struct
import jax.numpy as jnp

# executor-count levels at which the TPC-H traces record durations
# (reference tpch.py:238)
EXEC_LEVEL_VALUES = (5, 10, 20, 40, 50, 60, 80, 100)
NUM_EXEC_LEVELS = len(EXEC_LEVEL_VALUES)

# wave indices into the duration buckets
WAVE_FRESH, WAVE_FIRST, WAVE_REST = 0, 1, 2

NUM_QUERIES = 22
QUERY_SIZES = ("2g", "5g", "10g", "20g", "50g", "80g", "100g")


class WorkloadBank(struct.PyTreeNode):
    """Packed template bank. T templates, S stage slots, L executor levels,
    K duration samples per bucket. All arrays live on device and are shared
    (broadcast) across every vmapped environment lane."""

    # --- structure ---
    num_stages: jnp.ndarray  # i32[T]
    num_tasks: jnp.ndarray  # i32[T,S]
    adj: jnp.ndarray  # bool[T,S,S]; adj[t,p,c] == True iff edge p->c
    node_level: jnp.ndarray  # i32[T,S]; topological generation, S = padding
    rough_duration: jnp.ndarray  # f32[T,S]; mean duration over all buckets

    # --- durations ---
    dur: jnp.ndarray  # f32[T,S,3,L,K]
    cnt: jnp.ndarray  # i32[T,S,3,L]
    level_present: jnp.ndarray  # bool[T,S,L]; key present in first_wave
    max_present: jnp.ndarray  # i32[T,S]; index of max present level

    # --- executor-count interpolation (depends on num_executors) ---
    # For each possible num_local_executors in [0, N]: the left/right level
    # VALUES bracketing it and their indices into EXEC_LEVEL_VALUES
    # (reference tpch.py:216-262).
    itv_left_val: jnp.ndarray  # i32[N+1]
    itv_right_val: jnp.ndarray  # i32[N+1]
    itv_left_idx: jnp.ndarray  # i32[N+1]
    itv_right_idx: jnp.ndarray  # i32[N+1]

    # --- low-precision layout (ISSUE 7) ---
    # When `dur` carries an integer dtype (int8/int16 via
    # `quantize_bank`), `dur_scale` is the per-template f32[T]
    # LOG-domain dequantization scale:
    # duration = expm1(dur.astype(f32) * dur_scale[t]),
    # applied at the single use site (`sampling.sample_task_duration`)
    # so every accumulation stays f32. None for f32/bf16 banks.
    dur_scale: jnp.ndarray | None = None

    @property
    def num_templates(self) -> int:
        return self.num_stages.shape[0]

    @property
    def max_stages(self) -> int:
        return self.num_tasks.shape[1]

    @property
    def bucket_size(self) -> int:
        return self.dur.shape[-1]


def topological_levels(adj: np.ndarray, num_stages: int) -> np.ndarray:
    """Kahn's algorithm returning the topological generation index of each
    node (same grouping as nx.topological_generations). Padding slots get
    level == S."""
    s_cap = adj.shape[0]
    level = np.full(s_cap, s_cap, dtype=np.int32)
    indeg = adj[:num_stages, :num_stages].sum(axis=0)
    frontier = [int(i) for i in np.flatnonzero(indeg == 0)]
    cur = 0
    while frontier:
        nxt = []
        for u in frontier:
            level[u] = cur
            for v in np.flatnonzero(adj[u, :num_stages]):
                indeg[v] -= 1
                if indeg[v] == 0:
                    nxt.append(int(v))
        frontier = nxt
        cur += 1
    assert (level[:num_stages] < s_cap).all(), "adjacency has a cycle"
    return level


def _executor_intervals(num_executors: int) -> np.ndarray:
    """Map num_local_executors -> (left, right) executor-level VALUES,
    reproducing the reference table exactly (tpch.py:237-262), including its
    behavior of leaving index `num_executors` zeroed when
    num_executors > max level (the presence fallback then kicks in)."""
    levels = list(EXEC_LEVEL_VALUES)
    cap = num_executors
    intervals = np.zeros((cap + 1, 2), dtype=np.int64)
    intervals[: levels[0] + 1] = levels[0]
    for i in range(len(levels) - 1):
        intervals[levels[i] + 1 : levels[i + 1]] = (levels[i], levels[i + 1])
        if levels[i + 1] > cap:
            break
        intervals[levels[i + 1]] = levels[i + 1]
    if cap > levels[-1]:
        intervals[levels[-1] + 1 : cap] = levels[-1]
    return intervals


def _value_to_index() -> dict[int, int]:
    return {v: i for i, v in enumerate(EXEC_LEVEL_VALUES)}


def pack_bank(
    templates: list[dict[str, Any]],
    num_executors: int,
    max_stages: int,
    bucket_size: int,
    seed: int = 0,
) -> WorkloadBank:
    """Pack a list of host-side template dicts into a WorkloadBank.

    Each template dict has:
      adj: bool [s, s] numpy, parent->child
      num_tasks: int [s]
      durations: {stage_id: {wave_name: {level_value: list[float]}}}
        with wave_name in ('fresh_durations', 'first_wave', 'rest_wave').
        Levels present in 'first_wave' define the presence mask
        (reference tpch.py:228-231).
    """
    rng = np.random.default_rng(seed)
    t_n = len(templates)
    s_cap = max_stages
    l_n = NUM_EXEC_LEVELS
    k = bucket_size

    num_stages = np.zeros(t_n, dtype=np.int32)
    num_tasks = np.zeros((t_n, s_cap), dtype=np.int32)
    adj = np.zeros((t_n, s_cap, s_cap), dtype=bool)
    node_level = np.full((t_n, s_cap), s_cap, dtype=np.int32)
    rough = np.zeros((t_n, s_cap), dtype=np.float32)
    dur = np.zeros((t_n, s_cap, 3, l_n, k), dtype=np.float32)
    cnt = np.zeros((t_n, s_cap, 3, l_n), dtype=np.int32)
    present = np.zeros((t_n, s_cap, l_n), dtype=bool)
    max_present = np.zeros((t_n, s_cap), dtype=np.int32)

    v2i = _value_to_index()
    wave_names = {"fresh_durations": WAVE_FRESH, "first_wave": WAVE_FIRST,
                  "rest_wave": WAVE_REST}

    for t, tpl in enumerate(templates):
        s_n = tpl["adj"].shape[0]
        assert s_n <= s_cap, f"template {t} has {s_n} stages > cap {s_cap}"
        num_stages[t] = s_n
        num_tasks[t, :s_n] = tpl["num_tasks"]
        adj[t, :s_n, :s_n] = tpl["adj"]
        node_level[t] = topological_levels(adj[t], s_n)

        for s in range(s_n):
            stage_data = tpl["durations"][s]
            all_durs: list[float] = []
            for wname, w in wave_names.items():
                for lv, samples in stage_data.get(wname, {}).items():
                    li = v2i[int(lv)]
                    samples = np.asarray(samples, dtype=np.float32)
                    all_durs.extend(samples.tolist())
                    if samples.size == 0:
                        continue
                    if samples.size > k:
                        samples = rng.choice(samples, size=k, replace=False)
                    n = samples.size
                    dur[t, s, w, li, :n] = samples
                    cnt[t, s, w, li] = n
            for lv in stage_data.get("first_wave", {}):
                present[t, s, v2i[int(lv)]] = True
            pres_idx = np.flatnonzero(present[t, s])
            max_present[t, s] = pres_idx.max() if pres_idx.size else 0
            rough[t, s] = float(np.mean(all_durs)) if all_durs else 1.0

    itv = _executor_intervals(num_executors)
    lv_arr = np.array(EXEC_LEVEL_VALUES, dtype=np.int64)

    def to_idx(vals: np.ndarray) -> np.ndarray:
        # map values to level indices; unknown values (e.g. the zeroed tail
        # entry of the reference table) map to index 0 — the presence
        # fallback replaces them anyway
        idx = np.zeros_like(vals)
        for i, v in enumerate(lv_arr):
            idx[vals == v] = i
        return idx

    return WorkloadBank(
        num_stages=jnp.asarray(num_stages),
        num_tasks=jnp.asarray(num_tasks),
        adj=jnp.asarray(adj),
        node_level=jnp.asarray(node_level),
        rough_duration=jnp.asarray(rough),
        dur=jnp.asarray(dur),
        cnt=jnp.asarray(cnt),
        level_present=jnp.asarray(present),
        max_present=jnp.asarray(max_present),
        itv_left_val=jnp.asarray(itv[:, 0], dtype=jnp.int32),
        itv_right_val=jnp.asarray(itv[:, 1], dtype=jnp.int32),
        itv_left_idx=jnp.asarray(to_idx(itv[:, 0]), dtype=jnp.int32),
        itv_right_idx=jnp.asarray(to_idx(itv[:, 1]), dtype=jnp.int32),
    )


BANK_DTYPES = ("f32", "float32", "bf16", "bfloat16", "int8", "int16")


def bank_dtype_label(bank: WorkloadBank) -> str:
    """Short dtype tag of a bank's `dur` table for bench-row stamps
    ("f32", "bf16", "int8", "int16")."""
    name = str(bank.dur.dtype)
    return {"float32": "f32", "bfloat16": "bf16"}.get(name, name)


def quantize_bank(bank: WorkloadBank, dtype: str = "int16"
                  ) -> WorkloadBank:
    """Re-encode the bank's `dur[T,S,3,L,K]` table — by far its largest
    array — in a narrow dtype (ISSUE 7 low-precision bank layout).

    int8/int16: LOG-domain quantization with a per-template f32 scale
    (`q = rint(log1p(dur) / dur_scale[t])`, `dur_scale[t] =
    log1p(max(dur[t])) / intmax`). TPC-H durations are heavy-tailed
    (per-template maxima in the millions of ms against typical tasks
    of hundreds), so a LINEAR step of max/intmax would put ~50 ms of
    absolute error on every short task; the log code makes the error
    RELATIVE instead — bounded by expm1(dur_scale[t]/2), i.e. ~1.2e-4
    for int16 and ~6e-2 for int8, uniformly across the tail. The
    observe-path drift this buys is pinned by
    tests/test_workload_ingest.py's epsilon test.
    bfloat16: a plain cast (8-bit mantissa, no scale needed).

    Dequantization to f32 (`expm1(q * dur_scale[t])`) happens at the
    single gather site (`sampling.sample_task_duration`), so the env
    state, rewards and every accumulation stay f32; only the resident
    table and its gathers narrow. `rough_duration` ([T,S], vanishingly
    small next to the K-sample buckets) stays f32 — it is the
    empty-bucket fallback and feeds observations directly."""
    if dtype in ("f32", "float32"):
        return bank
    if dtype in ("bf16", "bfloat16"):
        return bank.replace(
            dur=bank.dur.astype(jnp.bfloat16), dur_scale=None
        )
    if dtype not in ("int8", "int16"):
        raise ValueError(
            f"unknown bank dtype {dtype!r} (have: {BANK_DTYPES})"
        )
    imax = 127 if dtype == "int8" else 32767
    # quantize in f64 on the host: an f32 log/division can land a
    # value epsilon-across a .5 step boundary and round one step off,
    # which would break the half-step error bound the epsilon test pins
    ldur = np.log1p(np.asarray(bank.dur, dtype=np.float64))
    t_max = ldur.reshape(ldur.shape[0], -1).max(axis=1)
    scale = np.where(t_max > 0, t_max / imax, 1.0)
    q = np.rint(ldur / scale[:, None, None, None, None])
    q = np.clip(q, 0, imax).astype(dtype)
    scale = scale.astype(np.float32)
    return bank.replace(
        dur=jnp.asarray(q), dur_scale=jnp.asarray(scale)
    )


def load_tpch_templates(data_dir: str = "data/tpch") -> list[dict[str, Any]]:
    """Load the real TPC-H traces (if present on disk) into host template
    dicts, applying the same preprocessing as the reference: fresh durations
    are removed from first_wave, and empty first-wave lists borrow the
    nearest lower executor level's (tpch.py:135-162)."""
    templates = []
    for size in QUERY_SIZES:
        for q in range(1, NUM_QUERIES + 1):
            qdir = osp.join(data_dir, size)
            adj = np.load(osp.join(qdir, f"adj_mat_{q}.npy"), allow_pickle=True)
            tdd = np.load(
                osp.join(qdir, f"task_duration_{q}.npy"), allow_pickle=True
            ).item()
            s_n = adj.shape[0]
            durations = {}
            ntasks = np.zeros(s_n, dtype=np.int64)
            for s in range(s_n):
                data = {k: {lv: list(v) for lv, v in d.items()}
                        for k, d in tdd[s].items()}
                e0 = next(iter(data["first_wave"]))
                ntasks[s] = len(data["first_wave"][e0]) + len(
                    data["rest_wave"][e0]
                )
                _preprocess_first_wave(data)
                durations[s] = data
            templates.append(
                {"adj": adj.astype(bool), "num_tasks": ntasks,
                 "durations": durations, "query_num": q, "query_size": size}
            )
    return templates


def _preprocess_first_wave(data: dict[str, Any]) -> None:
    """Remove fresh durations from first_wave lists, then fill empty lists
    from the nearest lower level (reference tpch.py:135-162)."""
    clean: dict[int, list[float]] = {}
    for e in data["first_wave"]:
        clean[e] = []
        fresh: dict[float, int] = {}
        for d in data["fresh_durations"].get(e, []):
            fresh[d] = fresh.get(d, 0) + 1
        for d in data["first_wave"][e]:
            if fresh.get(d, 0) > 0:
                fresh[d] -= 1
            else:
                clean[e].append(d)
    last: list[float] = []
    for e in sorted(clean.keys()):
        if len(clean[e]) == 0:
            clean[e] = last
        last = clean[e]
    data["first_wave"] = clean
