"""Synthetic TPC-H-like workload generator.

The reference auto-downloads empirical TPC-H traces (tpch.py:109-115); this
environment has no network egress, so we generate a statistically similar
bank deterministically: 22 "queries" x 7 input sizes, layered DAGs of 2..20
stages, skewed task counts, lognormal task durations with wave structure
(fresh > first > rest, reflecting JVM warmup in the real traces) and a mild
slowdown at higher executor-count levels (stragglers/contention).

`make_templates` is pure in its seed; the same bank is reproduced across
processes and hosts. If real traces exist at `data/tpch`, prefer
`bank.load_tpch_templates`.
"""

from __future__ import annotations

from typing import Any

import numpy as np

from .bank import EXEC_LEVEL_VALUES, NUM_QUERIES, QUERY_SIZES

# work multiplier per query size (durations scale with input size)
SIZE_SCALE = {"2g": 1.0, "5g": 1.6, "10g": 2.4, "20g": 3.6, "50g": 6.0,
              "80g": 8.0, "100g": 9.5}


def _query_structure(q: int, rng: np.random.Generator):
    """DAG structure is a function of the query number only (like TPC-H,
    where query plans are fixed and sizes scale the data)."""
    num_stages = int(rng.integers(2, 21))
    num_layers = int(rng.integers(2, max(3, min(6, num_stages)) + 1))
    layer_of = np.sort(rng.integers(0, num_layers, size=num_stages))
    layer_of[0] = 0
    adj = np.zeros((num_stages, num_stages), dtype=bool)
    for c in range(num_stages):
        earlier = np.flatnonzero(layer_of[:c] < layer_of[c])
        if earlier.size == 0:
            continue
        # every non-root stage depends on 1-3 earlier-layer stages
        k = int(rng.integers(1, min(3, earlier.size) + 1))
        parents = rng.choice(earlier, size=k, replace=False)
        adj[parents, c] = True
    # skewed task counts: many small stages, a few wide ones
    num_tasks = np.maximum(
        1, np.round(rng.lognormal(mean=2.2, sigma=1.1, size=num_stages))
    ).astype(np.int64)
    num_tasks = np.minimum(num_tasks, 200)
    base_dur = rng.lognormal(mean=9.2, sigma=0.8, size=num_stages)  # ~10s
    return num_stages, adj, num_tasks, base_dur


def make_templates(seed: int = 2024, bucket_size: int = 16,
                   num_samples_per_bucket: int | None = None
                   ) -> list[dict[str, Any]]:
    num_samples = num_samples_per_bucket or bucket_size
    templates = []
    for q in range(1, NUM_QUERIES + 1):
        struct_rng = np.random.default_rng([seed, q])
        num_stages, adj, num_tasks, base_dur = _query_structure(q, struct_rng)
        for si, size in enumerate(QUERY_SIZES):
            # NOT hash(size): Python string hashing is salted per process
            # (PYTHONHASHSEED), which silently made every process build a
            # different bank — the index is the deterministic key
            rng = np.random.default_rng([seed, q, si])
            scale = SIZE_SCALE[size]
            durations = {}
            for s in range(num_stages):
                stage = {"fresh_durations": {}, "first_wave": {},
                         "rest_wave": {}}
                base = base_dur[s] * scale
                for lv in EXEC_LEVEL_VALUES:
                    # more executors -> mild per-task slowdown
                    lv_factor = 1.0 + 0.08 * np.log2(lv / EXEC_LEVEL_VALUES[0])
                    rest_mean = base * lv_factor
                    stage["rest_wave"][lv] = _ln_samples(
                        rng, rest_mean, 0.25, num_samples)
                    stage["first_wave"][lv] = _ln_samples(
                        rng, rest_mean * 1.5, 0.3, num_samples)
                    stage["fresh_durations"][lv] = _ln_samples(
                        rng, rest_mean * 2.0 + 1000.0, 0.3, num_samples)
                durations[s] = stage
            templates.append(
                {"adj": adj, "num_tasks": num_tasks, "durations": durations,
                 "query_num": q, "query_size": size}
            )
    return templates


def _ln_samples(rng: np.random.Generator, mean: float, sigma: float,
                n: int) -> list[float]:
    mu = np.log(mean) - sigma**2 / 2
    return [float(x) for x in rng.lognormal(mu, sigma, size=n)]
