"""Workload factory + data-sampler plugin boundary.

The reference exposes an overridable `DataSampler` ABC selected by the
`data_sampler_cls` config string through a globals() factory
(spark_sched_sim/data_samplers/__init__.py:9-15,
data_samplers/data_sampler.py:9-23). The TPU-native equivalent of "a
sampler object consulted inside the event loop" is a *template provider*:
a callable that produces host-side template dicts (DAG structure +
per-(stage, wave, executor-level) duration buckets) which `pack_bank`
turns into fixed-shape device arrays. Custom workloads plug in by
registering a provider under a name and selecting it by config string —
no package edits required.
"""

import os.path as osp
from typing import Any, Callable, Protocol

from .bank import (  # noqa: F401
    EXEC_LEVEL_VALUES,
    NUM_EXEC_LEVELS,
    WorkloadBank,
    bank_dtype_label,
    load_tpch_templates,
    pack_bank,
    quantize_bank,
)
from .synthetic import make_templates  # noqa: F401


class TemplateProvider(Protocol):
    """Plugin contract (replaces the reference DataSampler ABC,
    data_sampler.py:9-23): return a list of template dicts, each with
    `adj` (bool [s,s] parent->child), `num_tasks` (int [s]), and
    `durations` ({stage: {wave_name: {exec_level: list[float]}}})."""

    def __call__(
        self,
        *,
        num_executors: int,
        max_stages: int,
        bucket_size: int,
        data_dir: str,
        seed: int,
    ) -> list[dict[str, Any]]: ...


def _tpch_provider(
    *,
    num_executors: int,
    max_stages: int,
    bucket_size: int,
    data_dir: str,
    seed: int,
) -> list[dict[str, Any]]:
    """Default provider: real TPC-H traces when present on disk (the
    reference auto-downloads them, tpch.py:109-115 — impossible without
    egress), else the synthetic TPC-H-like bank."""
    if osp.isdir(data_dir):
        return load_tpch_templates(data_dir)
    return make_templates(seed=seed, bucket_size=bucket_size)


_DATA_SAMPLERS: dict[str, Callable[..., list[dict[str, Any]]]] = {
    # reference class name, for drop-in config compatibility
    "TPCHDataSampler": _tpch_provider,
}


def register_data_sampler(
    name: str, provider: Callable[..., list[dict[str, Any]]]
) -> None:
    """Register a custom workload provider selectable via the
    `data_sampler_cls` config string."""
    _DATA_SAMPLERS[name] = provider


def make_workload_bank(
    num_executors: int,
    max_stages: int = 20,
    bucket_size: int = 16,
    data_dir: str = "data/tpch",
    seed: int = 2024,
    data_sampler_cls: str | None = None,
    bank_dtype: str | None = None,
    **_: object,
) -> WorkloadBank:
    """Factory mirroring the reference `make_data_sampler`
    (spark_sched_sim/data_samplers/__init__.py:9-15): dispatches on the
    `data_sampler_cls` config string through the provider registry.
    `bank_dtype` (ISSUE 7; an `env:` config key — "int16", "int8" or
    "bf16", default f32) selects the low-precision duration-table
    layout via `quantize_bank`."""
    name = data_sampler_cls or "TPCHDataSampler"
    if name not in _DATA_SAMPLERS:
        raise ValueError(
            f"'{name}' is not a registered data sampler "
            f"(have: {sorted(_DATA_SAMPLERS)})"
        )
    templates = _DATA_SAMPLERS[name](
        num_executors=num_executors,
        max_stages=max_stages,
        bucket_size=bucket_size,
        data_dir=data_dir,
        seed=seed,
    )
    max_stages = max(
        max_stages, max(t["adj"].shape[0] for t in templates)
    )
    bank = pack_bank(templates, num_executors, max_stages, bucket_size)
    if bank_dtype is not None:
        bank = quantize_bank(bank, bank_dtype)
    return bank


# drop-in alias for the reference factory name
# (spark_sched_sim/data_samplers/__init__.py:9-15)
make_data_sampler = make_workload_bank
