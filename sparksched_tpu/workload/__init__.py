import os.path as osp

from .bank import (  # noqa: F401
    EXEC_LEVEL_VALUES,
    NUM_EXEC_LEVELS,
    WorkloadBank,
    load_tpch_templates,
    pack_bank,
)
from .synthetic import make_templates  # noqa: F401


def make_workload_bank(
    num_executors: int,
    max_stages: int = 20,
    bucket_size: int = 16,
    data_dir: str = "data/tpch",
    seed: int = 2024,
    data_sampler_cls: str | None = None,
    **_: object,
) -> WorkloadBank:
    """Factory mirroring the reference `make_data_sampler`
    (spark_sched_sim/data_samplers/__init__.py:9-15). Loads real TPC-H
    traces when present on disk (the reference auto-downloads them,
    tpch.py:109-115 — impossible without egress), else generates the
    synthetic TPC-H-like bank."""
    if osp.isdir(data_dir):
        templates = load_tpch_templates(data_dir)
        max_stages = max(max_stages, max(t["adj"].shape[0] for t in templates))
    else:
        templates = make_templates(seed=seed, bucket_size=bucket_size)
    return pack_bank(templates, num_executors, max_stages, bucket_size)


# drop-in alias for the reference factory name
# (spark_sched_sim/data_samplers/__init__.py:9-15)
make_data_sampler = make_workload_bank
