"""Decima GNN policy, TPU-native (flax + padded graphs).

Semantics mirror the reference implementation
(schedulers/decima/scheduler.py:16-385, env_wrapper.py:36-162,
decima/utils.py) — same 5 normalized node features, the same DAGNN-style
*asynchronous level-wise* message passing leaf→root, the same dag/global
summaries and two autoregressive policy heads — but the ragged PyG graphs
become fixed-shape [max_jobs, max_stages] arrays with masks:

- the per-level masked sparse matmul (reference scheduler.py:219-232)
  becomes a dense per-job `[S,S] @ [S,D]` einsum inside a `lax.scan` over
  topological generations — batched matmuls that tile onto the MXU instead
  of scatter/gather kernels;
- the edge-mask batches the reference caches per observation
  (env_wrapper.py:145-162) are replaced by the env-maintained per-node
  `node_level` array, so no host-side graph analysis happens at all;
- `collate_obsns` (decima/utils.py:118-231) disappears: training batches
  are plain `jnp.stack`s of identically-shaped observations.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn
from flax import struct

from ..env.observe import Observation
from .base import TrainableScheduler

NUM_NODE_FEATURES = 5  # reference env_wrapper.py:9
NUM_DAG_FEATURES = 3  # reference scheduler.py:34
# numpy scalar, not jnp: a jax array here would initialize the backend
# (and claim the TPU) on `import sparksched_tpu.schedulers` — see the
# matching note in env/state.py
NEG_INF = np.float32(-1e30)

_i32 = jnp.int32


# --------------------------------------------------------------------------
# features (reference DecimaObsWrapper, env_wrapper.py:69-143)
# --------------------------------------------------------------------------


class DecimaFeatures(struct.PyTreeNode):
    """Padded model inputs derived from a raw Observation."""

    x: jnp.ndarray  # f32[J,S,5] normalized node features
    node_mask: jnp.ndarray  # bool[J,S]
    job_mask: jnp.ndarray  # bool[J]
    stage_mask: jnp.ndarray  # bool[J,S]; schedulable stages
    exec_mask: jnp.ndarray  # bool[J,N]; allowed parallelism limits per job
    adj: jnp.ndarray  # bool[J,S,S] active-subgraph adjacency
    node_level: jnp.ndarray  # i32[J,S] topological generation


def build_features(
    obs: Observation,
    num_executors: int,
    num_tasks_scale: float = 200.0,
    work_scale: float = 1e5,
) -> DecimaFeatures:
    """The 5 normalized node features + masks (env_wrapper.py:110-143):
    commit-cap/N, ±1 source-job flag, exec-supply/N, tasks/200, work/1e5."""
    n = num_executors
    j_cap = obs.job_mask.shape[0]
    j_idx = jnp.arange(j_cap, dtype=_i32)

    supplies = obs.exec_supplies
    committable = obs.num_committable
    gap = jnp.maximum(n - supplies, 0)
    caps = jnp.minimum(gap, committable)
    is_src = (obs.source_job >= 0) & (j_idx == obs.source_job)
    caps = jnp.where(is_src, committable, caps)

    # f32 accumulation at the use site: under the low-precision
    # observation layout (params.obs_dtype = bf16) the feature bank
    # arrives narrow; the normalization arithmetic below must not run
    # in bf16, so each read upcasts first (lossless for bf16 inputs)
    remaining = obs.nodes[..., 0].astype(jnp.float32)
    duration = obs.nodes[..., 1].astype(jnp.float32)
    x = jnp.stack(
        [
            jnp.broadcast_to((caps / n)[:, None], remaining.shape),
            jnp.broadcast_to(
                jnp.where(is_src, 1.0, -1.0)[:, None], remaining.shape
            ),
            jnp.broadcast_to((supplies / n)[:, None], remaining.shape),
            remaining / num_tasks_scale,
            remaining * duration / work_scale,
        ],
        axis=-1,
    ).astype(jnp.float32)
    x = jnp.where(obs.node_mask[..., None], x, 0.0)

    exec_mask = (
        jnp.arange(n, dtype=_i32)[None, :] < caps[:, None]
    ) & obs.job_mask[
        :, None
    ]
    adj = obs.adj & obs.node_mask[:, :, None] & obs.node_mask[:, None, :]
    return DecimaFeatures(
        x=x,
        node_mask=obs.node_mask,
        job_mask=obs.job_mask,
        stage_mask=obs.schedulable,
        exec_mask=exec_mask,
        adj=adj,
        node_level=obs.node_level,
    )


# --------------------------------------------------------------------------
# active-job compaction (round-8 fast path)
#
# The reference only ever embeds the arrived, incomplete jobs (its PyG
# batch is built from live DAGs; scheduler.py:219-232), while the dense
# padded port pays the full [J,S,S]@[S,D] level einsum over every padded
# job slot. These helpers gather the <=K active jobs into a width-K view,
# run the (shape-polymorphic) net at width K, and scatter the per-job
# scores back to the padded [J] layout before masked softmax — cutting
# GNN FLOPs and memory traffic by ~J/K at flagship shapes (J=200 cap,
# typically a few dozen live jobs). All per-job computations are
# independent except the global summary, which sums over job_mask only,
# so compact and full-width scores agree on every active job.
# --------------------------------------------------------------------------


def compact_features(
    f: DecimaFeatures, k: int
) -> tuple[DecimaFeatures, jnp.ndarray]:
    """Gather the first `k` active jobs of an unbatched [J,...] feature
    set into a width-k view. Returns (compact features, ids) where
    `ids[i]` is the padded job id behind compact row i (== j_cap for
    empty rows). Only meaningful when the number of active jobs is <= k;
    callers guard with the overflow cond in `DecimaScheduler.score`."""
    j_cap = f.job_mask.shape[0]
    # active ids are the smallest entries of this ascending sort, so
    # rows 0..num_active-1 are exactly the active jobs in id order
    ids = jnp.sort(
        jnp.where(f.job_mask, jnp.arange(j_cap, dtype=_i32), j_cap)
    )[:k]
    valid = ids < j_cap
    idx = jnp.minimum(ids, j_cap - 1)  # clamp gathers for empty rows
    vm = valid[:, None]
    node_mask = f.node_mask[idx] & vm
    return DecimaFeatures(
        x=jnp.where(node_mask[..., None], f.x[idx], 0.0),
        node_mask=node_mask,
        job_mask=valid,
        stage_mask=f.stage_mask[idx] & vm,
        exec_mask=f.exec_mask[idx] & vm,
        adj=f.adj[idx] & vm[:, :, None],
        node_level=f.node_level[idx],
    ), ids


def scatter_job_scores(
    stage_k: jnp.ndarray, exec_k: jnp.ndarray, ids: jnp.ndarray,
    j_cap: int,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Scatter compact [k,S]/[k,N] scores back to the padded [J,S]/[J,N]
    layout (rows of inactive jobs are zero — the masked softmax never
    reads them). Empty compact rows carry ids == j_cap and drop."""
    stage = jnp.zeros(
        (j_cap,) + stage_k.shape[1:], stage_k.dtype
    ).at[ids].set(stage_k, mode="drop")
    execs = jnp.zeros(
        (j_cap,) + exec_k.shape[1:], exec_k.dtype
    ).at[ids].set(exec_k, mode="drop")
    return stage, execs


# --------------------------------------------------------------------------
# model (reference scheduler.py:142-385)
# --------------------------------------------------------------------------


def make_act(name: str, kwargs: Any = None) -> Callable:
    """Activation factory (reference utils.make_mlp's act_cls lookup).
    `kwargs` may be a dict or the hashable tuple-of-pairs form flax module
    fields require."""
    if isinstance(kwargs, tuple):
        kwargs = dict(kwargs)
    kwargs = kwargs or {}
    name = name.lower()
    if name in ("leakyrelu", "leaky_relu"):
        slope = kwargs.get("negative_slope", 0.01)
        return lambda x: jnp.where(x >= 0, x, slope * x)
    if name == "tanh":
        return jnp.tanh
    if name == "relu":
        return jax.nn.relu
    raise ValueError(f"unknown activation {name!r}")


class MLP(nn.Module):
    """Dense stack matching reference utils.make_mlp:45-64 (all biases
    start at zero per scheduler.py:66-69 `_reset_biases`).

    `dtype` is the *compute* dtype (params stay f32): bfloat16 keeps the
    matmuls on the MXU's native precision — the TPU analog of the
    reference's f32 torch path."""

    hid_dims: tuple[int, ...]
    out_dim: int
    act: Callable
    dtype: Any = None

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        for i, d in enumerate(self.hid_dims):
            x = self.act(
                nn.Dense(d, name=f"dense_{i}", dtype=self.dtype)(x)
            )
        return nn.Dense(
            self.out_dim, name=f"dense_{len(self.hid_dims)}",
            dtype=self.dtype,
        )(x)


class DecimaNet(nn.Module):
    """Encoder + both policy heads in one module.

    Returns masked stage scores [J,S] and exec scores for every job [J,N];
    the reference computes exec scores only for the selected job
    (scheduler.py:92), but computing all rows is one batched matmul here and
    removes the data-dependent gather from the autoregressive chain.
    """

    num_executors: int
    embed_dim: int = 16
    gnn_hid: tuple[int, ...] = (32, 16)
    policy_hid: tuple[int, ...] = (64, 64)
    gnn_act: str = "LeakyReLU"
    gnn_act_kwargs: Any = None
    policy_act: str = "Tanh"
    policy_act_kwargs: Any = None
    # compute dtype for all Dense layers + message aggregation; params
    # stay f32. "bfloat16" puts the matmuls on the MXU's native input
    # precision; scores are returned as f32 either way.
    compute_dtype: str | None = None
    # upper bound on topological depth (0 = all s_cap levels). Levels
    # >= the deepest active node are exact no-ops (the update mask is
    # all-false), so bounding the scan by the workload bank's true max
    # DAG depth (e.g. 6 for the synthetic TPC-H bank vs s_cap = 20) is
    # bit-identical and cuts the GNN's dominant cost proportionally.
    # The reference gets this for free from its per-observation edge
    # mask list (scheduler.py:219-232 iterates only realized levels).
    num_levels: int = 0

    def setup(self) -> None:
        # setup() (not @nn.compact) so the level loop can be an nn.scan
        # over a method; attribute names keep the param tree identical to
        # the round-1/2 checkpoints ("mlp_prep", "mlp_msg", ...).
        g_act = make_act(self.gnn_act, self.gnn_act_kwargs)
        self._p_act = make_act(self.policy_act, self.policy_act_kwargs)
        cdt = (
            jnp.dtype(self.compute_dtype) if self.compute_dtype else None
        )
        self._cdt = cdt
        d = self.embed_dim
        self.mlp_prep = MLP(self.gnn_hid, d, g_act, dtype=cdt)
        self.mlp_msg = MLP(self.gnn_hid, d, g_act, dtype=cdt)
        self.mlp_update = MLP(self.gnn_hid, d, g_act, dtype=cdt)
        self.mlp_dag = MLP(self.gnn_hid, d, g_act, dtype=cdt)
        self.mlp_glob = MLP(self.gnn_hid, d, g_act, dtype=cdt)
        self.mlp_stage = MLP(self.policy_hid, 1, self._p_act, dtype=cdt)
        self.mlp_exec = MLP(self.policy_hid, 1, self._p_act, dtype=cdt)

    def __call__(self, f: DecimaFeatures):
        d = self.embed_dim
        cdt = self._cdt

        # --- NodeEncoder (reference scheduler.py:173-241) ---
        # h[leaf] = update(prep(x)); h[p] = prep(x)[p] + update(sum_children
        # msg(h[c])), computed one topological generation at a time from the
        # deepest level up (reverse_flow=True, leaf-to-root).
        x = f.x.astype(cdt) if cdt is not None else f.x
        s_cap = x.shape[-2]
        h_init = self.mlp_prep(x)
        adj_f = f.adj.astype(h_init.dtype)
        has_child = f.adj.any(axis=-1)
        h0 = jnp.where(has_child[..., None], 0.0, self.mlp_update(h_init))

        # one `nn.scan` step per topological generation, deepest first.
        # Weights are broadcast across levels (the reference reuses the
        # same msg/update MLPs each level, scheduler.py:219-232); scanning
        # instead of statically unrolling keeps the compiled program one
        # body regardless of s_cap — at the flagship 200-job scale the
        # unrolled chain dominated XLA compile time.
        def level_step(mdl, h_node, lvl):
            agg = jnp.einsum(
                "...pc,...cd->...pd", adj_f, mdl.mlp_msg(h_node)
            )
            upd = (f.node_level == lvl) & has_child
            h_node = jnp.where(
                upd[..., None], h_init + mdl.mlp_update(agg), h_node
            )
            return h_node, None

        nl = min(self.num_levels, s_cap) if self.num_levels else s_cap
        levels = jnp.arange(nl - 1, -1, -1, dtype=_i32)
        h_node, _ = nn.scan(
            level_step,
            variable_broadcast="params",
            split_rngs={"params": False},
        )(self, h0, levels)
        # reference fast path for an observation with no edges
        # (scheduler.py:205-207,236-241): plain prep(x), no update().
        # Reduced per ITEM (last 3 axes), not over leading batch dims:
        # a vmapped per-lane policy traces the unbatched reduction, so
        # the genuinely-batched callers (batch_policy / the single-eval
        # collectors) must do the same per-lane or the two paths'
        # scores diverge on edgeless observations sharing a batch with
        # edged ones.
        edgeless = ~f.adj.any(axis=(-3, -2, -1))
        h_node = jnp.where(
            edgeless[..., None, None, None], h_init, h_node
        )
        h_node = jnp.where(f.node_mask[..., None], h_node, 0.0)

        # --- DagEncoder (reference scheduler.py:244-257) ---
        z = self.mlp_dag(jnp.concatenate([x, h_node], axis=-1))
        h_dag = jnp.where(f.node_mask[..., None], z, 0.0).sum(axis=-2)

        # --- GlobalEncoder (reference scheduler.py:260-276) ---
        zg = self.mlp_glob(h_dag)
        h_glob = jnp.where(f.job_mask[..., None], zg, 0.0).sum(axis=-2)

        # --- StagePolicyNetwork (reference scheduler.py:279-320) ---
        j_cap = x.shape[-3]
        h_dag_rpt = jnp.broadcast_to(
            h_dag[..., :, None, :], (*x.shape[:-1], d)
        )
        h_glob_rpt = jnp.broadcast_to(
            h_glob[..., None, None, :], (*x.shape[:-1], d)
        )
        stage_in = jnp.concatenate(
            [x, h_node, h_dag_rpt, h_glob_rpt], axis=-1
        )
        stage_scores = self.mlp_stage(stage_in)[..., 0].astype(jnp.float32)

        # --- ExecPolicyNetwork (reference scheduler.py:323-385) ---
        # x_dag = first NUM_DAG_FEATURES features of each dag's first node;
        # features 0..2 are per-job constants so any active node works.
        first = jnp.argmax(f.node_mask, axis=-1)
        x_dag = jnp.take_along_axis(
            x, first[..., None, None], axis=-2
        )[..., 0, :NUM_DAG_FEATURES]
        n = self.num_executors
        k_frac = (jnp.arange(n, dtype=_i32) / n).astype(x.dtype)
        per_job = jnp.concatenate([x_dag, h_dag], axis=-1)
        exec_in = jnp.concatenate(
            [
                jnp.broadcast_to(
                    per_job[..., :, None, :],
                    (*per_job.shape[:-1], n, per_job.shape[-1]),
                ),
                jnp.broadcast_to(
                    h_glob[..., None, None, :],
                    (*per_job.shape[:-1], n, d),
                ),
                jnp.broadcast_to(
                    k_frac[:, None], (*per_job.shape[:-1], n, 1)
                ),
            ],
            axis=-1,
        )
        exec_scores = self.mlp_exec(exec_in)[..., 0].astype(jnp.float32)

        return stage_scores, exec_scores


# --------------------------------------------------------------------------
# masked sampling / evaluation (reference decima/utils.py:19-42)
# --------------------------------------------------------------------------


def masked_log_softmax(scores: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    logits = jnp.where(mask, scores, NEG_INF)
    return jax.nn.log_softmax(logits, axis=-1)


def masked_entropy(logp: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    """-sum p·logp over masked entries (reference utils.evaluate:26-42)."""
    p = jnp.exp(logp)
    return -jnp.where(mask, p * logp, 0.0).sum(axis=-1)


class DecimaAction(struct.PyTreeNode):
    stage_idx: jnp.ndarray  # i32 flat padded node index (-1 = none)
    job_idx: jnp.ndarray  # i32 padded job id
    num_exec: jnp.ndarray  # i32 0-based parallelism choice k (env gets k+1)


def sample_action(
    rng: jax.Array,
    stage_scores: jnp.ndarray,
    exec_scores: jnp.ndarray,
    f: DecimaFeatures,
    deterministic: bool = False,
):
    """Autoregressive sample: stage via masked softmax over all schedulable
    nodes, then executor count conditioned on the stage's job (reference
    scheduler.py:81-99). Returns (DecimaAction, lgprob). With
    `deterministic` (static), both heads take the masked argmax instead of
    sampling (greedy eval / rng-free parity testing); `lgprob` is still the
    softmax log-probability of the chosen action."""
    j_cap, s_cap = f.stage_mask.shape
    k_stage, k_exec = jax.random.split(rng)

    flat_mask = f.stage_mask.reshape(-1)
    logp_stage = masked_log_softmax(stage_scores.reshape(-1), flat_mask)
    valid = flat_mask.any()
    stage_logits = jnp.where(flat_mask, stage_scores.reshape(-1), NEG_INF)
    stage_pick = (
        jnp.argmax(stage_logits)
        if deterministic
        else jax.random.categorical(k_stage, stage_logits)
    )
    stage_flat = jnp.where(valid, stage_pick, -1).astype(_i32)
    job = jnp.where(valid, stage_flat // s_cap, -1).astype(_i32)

    e_mask = f.exec_mask[jnp.maximum(job, 0)]
    logp_exec = masked_log_softmax(exec_scores[jnp.maximum(job, 0)], e_mask)
    exec_logits = jnp.where(
        e_mask, exec_scores[jnp.maximum(job, 0)], NEG_INF
    )
    exec_pick = (
        jnp.argmax(exec_logits)
        if deterministic
        else jax.random.categorical(k_exec, exec_logits)
    )
    k = jnp.where(e_mask.any(), exec_pick, 0).astype(_i32)

    lgprob = jnp.where(
        valid,
        logp_stage[jnp.maximum(stage_flat, 0)] + logp_exec[k],
        0.0,
    )
    return DecimaAction(stage_idx=stage_flat, job_idx=job, num_exec=k), lgprob


def evaluate_actions(
    stage_scores: jnp.ndarray,
    exec_scores: jnp.ndarray,
    f: DecimaFeatures,
    action: DecimaAction,
    num_executors: int,
):
    """Log-prob + normalized entropy of one stored action (reference
    scheduler.py:101-139). Batch by vmapping over leading axes."""
    s_cap = f.stage_mask.shape[-1]
    flat_mask = f.stage_mask.reshape(-1)
    logp_stage = masked_log_softmax(stage_scores.reshape(-1), flat_mask)
    e_mask = f.exec_mask[jnp.maximum(action.job_idx, 0)]
    logp_exec = masked_log_softmax(
        exec_scores[jnp.maximum(action.job_idx, 0)], e_mask
    )

    lgprob = (
        logp_stage[jnp.maximum(action.stage_idx, 0)]
        + logp_exec[action.num_exec]
    )
    ent = masked_entropy(logp_stage, flat_mask) + masked_entropy(
        logp_exec, e_mask
    )
    # entropy scale-normalization (reference scheduler.py:135-137)
    num_nodes = f.node_mask.sum()
    ent = ent / jnp.log(
        jnp.maximum(num_executors * num_nodes, 2).astype(jnp.float32)
    )
    valid = action.stage_idx >= 0
    return jnp.where(valid, lgprob, 0.0), jnp.where(valid, ent, 0.0)


# --------------------------------------------------------------------------
# scheduler plugin
# --------------------------------------------------------------------------


class DecimaScheduler(TrainableScheduler):
    """Trainable Decima scheduler (reference decima/scheduler.py:16-139).

    Holds the flax module and a parameter pytree; all heavy lifting is in
    the pure functions above so trainers can jit/vmap/grad them directly.
    """

    def __init__(
        self,
        num_executors: int,
        embed_dim: int = 16,
        gnn_mlp_kwargs: dict[str, Any] | None = None,
        policy_mlp_kwargs: dict[str, Any] | None = None,
        state_dict_path: str | None = None,
        seed: int = 42,
        num_tasks_scale: float = 200.0,
        work_scale: float = 1e5,
        compute_dtype: str | None = None,
        num_levels: int = 0,
        job_bucket: int = 0,
        **_: Any,
    ) -> None:
        self.name = "Decima"
        self.num_executors = int(num_executors)
        self.num_tasks_scale = num_tasks_scale
        self.work_scale = work_scale
        # active-job compaction bucket K (0 = off): `score` runs the GNN
        # at width K when every item has <= K active jobs, with a
        # scalar-predicate full-width fallback (see `score`'s docstring)
        self.job_bucket = int(job_bucket)
        gnn_mlp_kwargs = gnn_mlp_kwargs or {}
        policy_mlp_kwargs = policy_mlp_kwargs or {}
        self.net = DecimaNet(
            num_executors=self.num_executors,
            embed_dim=embed_dim,
            gnn_hid=tuple(gnn_mlp_kwargs.get("hid_dims", (32, 16))),
            policy_hid=tuple(policy_mlp_kwargs.get("hid_dims", (64, 64))),
            gnn_act=gnn_mlp_kwargs.get("act_cls", "LeakyReLU"),
            gnn_act_kwargs=_hashable(gnn_mlp_kwargs.get("act_kwargs")),
            policy_act=policy_mlp_kwargs.get("act_cls", "Tanh"),
            policy_act_kwargs=_hashable(policy_mlp_kwargs.get("act_kwargs")),
            compute_dtype=compute_dtype,
            num_levels=int(num_levels),
        )
        self.params = self.init_params(jax.random.PRNGKey(seed))
        if state_dict_path:
            self.name += f":{state_dict_path}"
            if state_dict_path.endswith(".pt"):
                self.params = load_torch_state_dict(
                    state_dict_path, self.params
                )
            else:  # flax msgpack checkpoint written by the Trainer
                from flax import serialization

                with open(state_dict_path, "rb") as fp:
                    self.params = serialization.from_bytes(
                        self.params, fp.read()
                    )
        self._rng = jax.random.PRNGKey(seed)

    # -- parameter init ---------------------------------------------------
    def init_params(self, rng: jax.Array):
        f = _dummy_features(self.num_executors)
        return self.net.init(rng, f)

    def features(self, obs: Observation) -> DecimaFeatures:
        return build_features(
            obs, self.num_executors, self.num_tasks_scale, self.work_scale
        )

    # -- scoring (compaction-aware) ----------------------------------------
    def score(self, params, f: DecimaFeatures):
        """Stage/exec scores for padded features `f` — unbatched [J,...]
        or with any number of leading batch axes. With `job_bucket` K > 0
        the <=K active jobs are gathered into a width-K view, the net
        runs at width K, and the scores scatter back to [J] (identical
        values on active jobs — per-job computations are independent and
        the global summary sums over job_mask only). The full-width
        fallback runs under a lax.cond whose predicate reduces over ALL
        leading axes to a scalar: batched callers (the single-eval flat
        collectors, bench) execute exactly one branch at runtime —
        unlike a per-lane cond, which jax's batching rule lowers to
        executing both branches for every lane."""
        k = self.job_bucket
        j_cap = f.job_mask.shape[-1]
        if not k or k >= j_cap:
            return self.net.apply(params, f)
        overflow = (f.job_mask.sum(-1) > k).any()

        def full(f):
            return self.net.apply(params, f)

        def compact(f):
            cf = partial(compact_features, k=k)
            sc = partial(scatter_job_scores, j_cap=j_cap)
            for _ in range(f.job_mask.ndim - 1):
                cf, sc = jax.vmap(cf), jax.vmap(sc)
            fk, ids = cf(f)
            ss, es = self.net.apply(params, fk)
            return sc(ss, es, ids)

        return jax.lax.cond(overflow, full, compact, f)

    # -- pure policy (vmap/scan-safe) -------------------------------------
    def policy(self, rng: jax.Array, obs: Observation, params=None,
               deterministic: bool = False):
        from ..obs.tracing import annotate

        params = self.params if params is None else params
        f = self.features(obs)
        with annotate("decima/gnn"):
            stage_scores, exec_scores = self.score(params, f)
        action, lgprob = sample_action(
            rng, stage_scores, exec_scores, f, deterministic
        )
        # env takes a 1-based executor count (reference env_wrapper.py:33-34)
        return action.stage_idx, action.num_exec + 1, {
            "lgprob": lgprob,
            "job_idx": action.job_idx,
            "num_exec_k": action.num_exec,
        }

    # -- batched policy (single GNN eval over a lane stack) ----------------
    def batch_policy(self, rng: jax.Array, obs: Observation, params=None,
                     deterministic: bool = False):
        """Policy over a [B]-leading Observation stack in ONE net
        evaluation, with the compaction cond at batch level (scalar
        predicate — one branch executes at runtime). `rng` is a single
        key, split per lane internally. Returns per-lane
        (stage_idx[B], num_exec_1based[B], aux-of-[B])."""
        from ..obs.tracing import annotate

        params = self.params if params is None else params
        f = jax.vmap(self.features)(obs)
        with annotate("decima/gnn"):
            stage_scores, exec_scores = self.score(params, f)
        keys = jax.random.split(rng, f.job_mask.shape[0])
        action, lgprob = jax.vmap(
            lambda r, ss, es, ff: sample_action(
                r, ss, es, ff, deterministic
            )
        )(keys, stage_scores, exec_scores, f)
        return action.stage_idx, action.num_exec + 1, {
            "lgprob": lgprob,
            "job_idx": action.job_idx,
            "num_exec_k": action.num_exec,
        }

    # -- flat micro-step engine adapter ------------------------------------
    def flat_policy(self, params=None, deterministic: bool = False):
        """Bind this scheduler into a `policy_fn(rng, obs)` for the flat
        micro-step engine (`env/flat_loop.py`): the dense per-job einsum
        GNN runs on the DECIDE branch's padded observation inside the
        micro-step scan, and the aux dict carries the log-prob/action
        decomposition the trajectory recorder stores. Pass explicit
        `params` (e.g. the live training parameters) to keep the returned
        closure jit/scan-safe across parameter updates."""
        p = self.params if params is None else params

        def policy_fn(rng, obs):
            return self.policy(rng, obs, p, deterministic)

        return policy_fn

    def flat_batch_policy(self, params=None, deterministic: bool = False):
        """Batched analog of `flat_policy` for the single-eval flat
        collectors (`trainers/rollout.py:collect_flat_sync_batch`): one
        `batch_policy` call per decision row over the whole lane stack,
        so the compaction cond stays scalar (see `score`)."""
        p = self.params if params is None else params

        def policy_fn(rng, obs):
            return self.batch_policy(rng, obs, p, deterministic)

        return policy_fn

    def serve_policies(self, params=None, deterministic: bool = True):
        """The `(policy_fn, batch_policy_fn)` pair with the parameters
        BOUND as closure constants — the pre-ISSUE-14 serving binding,
        kept for ad-hoc jit use. The AOT decision service compiles
        `serve_param_policies` instead (explicit-params signature), so
        weights stay a runtime argument and hot swap needs no
        recompile. Serving defaults to greedy (`deterministic=True`):
        a production decision is the argmax of both heads,
        rng-independent, so equal session states always serve equal
        decisions regardless of the request's batch placement."""
        p = self.params if params is None else params
        return (
            self.flat_policy(p, deterministic),
            self.flat_batch_policy(p, deterministic),
        )

    def serve_param_policies(self, deterministic: bool = True):
        """The `(policy_fn, batch_policy_fn)` pair the AOT decision
        service compiles since ISSUE 14, with the model parameters as
        the LEADING EXPLICIT ARGUMENT:
        `policy_fn(model_params, rng, obs)` /
        `batch_policy_fn(model_params, rng, obs)`. Both serve paths
        receive the same params value per call from the session store,
        so they cannot disagree on weights — and because params enter
        the compiled programs as ordinary arguments (not closure
        constants), a new parameter version swaps in with zero
        recompiles (the `ParamBus` hot-swap contract)."""
        return (
            lambda p, k, o: self.policy(k, o, p, deterministic),
            lambda p, k, o: self.batch_policy(k, o, p, deterministic),
        )

    # -- host-side single decision ----------------------------------------
    def schedule(self, obs: Observation):
        self._rng, sub = jax.random.split(self._rng)
        stage_idx, num_exec, info = jax.jit(self.policy)(sub, obs)
        return (
            {"stage_idx": int(stage_idx), "num_exec": int(num_exec)},
            {k: jax.device_get(v) for k, v in info.items()},
        )

    # -- training-time evaluation ------------------------------------------
    def evaluate_actions(self, params, feats: DecimaFeatures,
                         actions: DecimaAction):
        """Batched log-probs/entropies; `feats`/`actions` have leading batch
        axes (reference scheduler.py:101-139).

        The forward is rematerialized (`jax.checkpoint`): the unrolled
        S-level GNN would otherwise keep every level's activations alive
        for the backward pass across the whole minibatch — the memory
        wall at the flagship 200-job/20-stage scale. Remat trades one
        recomputed forward for ~S x less live activation memory."""

        from ..obs.tracing import annotate

        def one(f, a):
            with annotate("decima/gnn"):
                stage_scores, exec_scores = jax.checkpoint(
                    lambda p, ff: self.net.apply(p, ff)
                )(params, f)
            return evaluate_actions(
                stage_scores, exec_scores, f, a, self.num_executors
            )

        return jax.vmap(one)(feats, actions)


def _hashable(obj):
    if isinstance(obj, dict):
        return tuple(sorted(obj.items()))
    return obj


def _dummy_features(num_executors: int) -> DecimaFeatures:
    j, s = 2, 3
    return DecimaFeatures(
        x=jnp.zeros((j, s, NUM_NODE_FEATURES), jnp.float32),
        node_mask=jnp.ones((j, s), bool),
        job_mask=jnp.ones((j,), bool),
        stage_mask=jnp.ones((j, s), bool),
        exec_mask=jnp.ones((j, num_executors), bool),
        adj=jnp.zeros((j, s, s), bool),
        node_level=jnp.zeros((j, s), _i32),
    )


# --------------------------------------------------------------------------
# torch checkpoint conversion (reference models/decima/model.pt)
# --------------------------------------------------------------------------

_TORCH_TO_FLAX = {
    "encoder.node_encoder.mlp_prep": "mlp_prep",
    "encoder.node_encoder.mlp_msg": "mlp_msg",
    "encoder.node_encoder.mlp_update": "mlp_update",
    "encoder.dag_encoder.mlp": "mlp_dag",
    "encoder.global_encoder.mlp": "mlp_glob",
    "stage_policy_network.mlp_score": "mlp_stage",
    "exec_policy_network.mlp_score": "mlp_exec",
}


def load_torch_state_dict(path: str, params):
    """Convert a reference torch checkpoint (scheduler.py:57-59) into this
    module's parameter pytree. Torch `Sequential` indices map to dense
    layer indices (Linear layers sit at even indices)."""
    import torch

    sd = torch.load(path, map_location="cpu", weights_only=True)
    out = jax.tree_util.tree_map(lambda x: x, params)  # shallow copy
    flat = dict(out["params"])
    for tname, fname in _TORCH_TO_FLAX.items():
        dst = dict(flat[fname])
        seq_idxs = sorted(
            {
                int(k[len(tname) + 1:].split(".")[0])
                for k in sd
                if k.startswith(tname + ".")
            }
        )
        for li, si in enumerate(seq_idxs):
            w = np.asarray(sd[f"{tname}.{si}.weight"])
            b = np.asarray(sd[f"{tname}.{si}.bias"])
            dst[f"dense_{li}"] = {
                "kernel": jnp.asarray(w.T),
                "bias": jnp.asarray(b),
            }
        flat[fname] = dst
    return {"params": flat}
