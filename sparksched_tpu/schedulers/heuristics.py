"""Heuristic schedulers as branch-free jitted policies.

Semantics mirror the reference heuristics exactly
(schedulers/heuristics/round_robin.py:14-49, random_scheduler.py:16-32,
utils.py:17-37) but operate on the padded Observation: the Python loops over
jobs/stages become masked argmax selections, so thousands of scheduling
decisions run per TPU core under `jax.vmap`.
"""

from __future__ import annotations

from functools import partial
from typing import Any

import jax
import jax.numpy as jnp

from ..env.observe import Observation
from .base import Scheduler

_i32 = jnp.int32


def find_stage_per_job(obs: Observation):
    """Per-job stage selection, frontier-preferred (reference
    heuristics/utils.py:17-37): for each job, the first schedulable frontier
    stage, else the first schedulable stage. Returns (stage[J] with -1 for
    none, has[J])."""
    sched = obs.schedulable
    front = sched & obs.frontier
    s_cap = sched.shape[1]
    first_sched = jnp.argmax(sched, axis=1)
    first_front = jnp.argmax(front, axis=1)
    has_front = front.any(axis=1)
    has = sched.any(axis=1)
    sel = jnp.where(has_front, first_front, first_sched)
    return jnp.where(has, sel, -1).astype(_i32), has


@partial(jax.jit, static_argnames=("num_executors", "dynamic_partition"))
def round_robin_policy(
    obs: Observation, num_executors: int, dynamic_partition: bool = True
):
    """Fair (dynamic per-job executor cap) or FIFO scheduling (reference
    round_robin.py:14-49). Returns (flat stage_idx | -1, num_exec)."""
    s_cap = obs.schedulable.shape[1]
    j_cap = obs.schedulable.shape[0]
    n_active = obs.job_mask.sum()
    if dynamic_partition:
        cap = jnp.ceil(num_executors / jnp.maximum(1, n_active)).astype(_i32)
    else:
        cap = _i32(num_executors)

    sel, has = find_stage_per_job(obs)
    committable = obs.num_committable

    # branch 1: a stage in the job that is releasing executors (:22-30)
    src = obs.source_job
    src_ok = (src >= 0) & has[jnp.maximum(src, 0)]

    # branch 2: jobs in arrival order == job-id order (job ids are assigned
    # in arrival order both here and in the reference)
    j_idx = jnp.arange(j_cap, dtype=jnp.int32)
    supplies = obs.exec_supplies
    want = obs.job_mask & has & (supplies < cap) & (j_idx != src)
    any_want = want.any()
    j_pick = jnp.argmax(want)

    stage_src = src * s_cap + sel[jnp.maximum(src, 0)]
    stage_loop = j_pick.astype(_i32) * s_cap + sel[j_pick]
    n_loop = jnp.minimum(committable, cap - supplies[j_pick])

    stage_idx = jnp.where(
        src_ok, stage_src, jnp.where(any_want, stage_loop, -1)
    ).astype(_i32)
    num_exec = jnp.where(src_ok | ~any_want, committable, n_loop).astype(_i32)
    return stage_idx, num_exec


@jax.jit
def random_policy(rng: jax.Array, obs: Observation):
    """Uniform-random job with a schedulable stage, frontier-preferred stage
    within it, uniform executor count in [1, committable] (reference
    random_scheduler.py:16-32)."""
    s_cap = obs.schedulable.shape[1]
    sel, has = find_stage_per_job(obs)
    k_job, k_n = jax.random.split(rng)
    n_has = has.sum()
    p = jnp.where(has, 1.0, 0.0) / jnp.maximum(1, n_has)
    j = jax.random.choice(k_job, has.shape[0], p=p)
    stage_idx = jnp.where(n_has > 0, j.astype(_i32) * s_cap + sel[j], -1)
    num_exec = jax.random.randint(
        k_n, (), 1, jnp.maximum(obs.num_committable, 1) + 1, dtype=_i32
    )
    return stage_idx.astype(_i32), num_exec


class RoundRobinScheduler(Scheduler):
    """Fair/FIFO heuristic (reference round_robin.py:7-49)."""

    def __init__(self, num_executors: int, dynamic_partition: bool = True,
                 **_: Any) -> None:
        self.name = "Fair" if dynamic_partition else "FIFO"
        self.num_executors = int(num_executors)
        self.dynamic_partition = bool(dynamic_partition)

    def policy(self, rng: jax.Array, obs: Observation):
        stage_idx, num_exec = round_robin_policy(
            obs, self.num_executors, self.dynamic_partition
        )
        return stage_idx, num_exec, {}

    def schedule(self, obs: Observation):
        stage_idx, num_exec = round_robin_policy(
            obs, self.num_executors, self.dynamic_partition
        )
        return {"stage_idx": int(stage_idx), "num_exec": int(num_exec)}, {}


class RandomScheduler(Scheduler):
    """Uniform-random heuristic (reference random_scheduler.py:7-32)."""

    def __init__(self, seed: int = 42, **_: Any) -> None:
        self.name = "Random"
        self.set_seed(seed)

    def set_seed(self, seed: int) -> None:
        self._rng = jax.random.PRNGKey(seed)

    def policy(self, rng: jax.Array, obs: Observation):
        stage_idx, num_exec = random_policy(rng, obs)
        return stage_idx, num_exec, {}

    def schedule(self, obs: Observation):
        self._rng, sub = jax.random.split(self._rng)
        stage_idx, num_exec = random_policy(sub, obs)
        return {"stage_idx": int(stage_idx), "num_exec": int(num_exec)}, {}
