"""Scheduler interfaces (reference schedulers/scheduler.py:10-55).

Two calling conventions coexist:

- `schedule(obs) -> (action, info)`: host-side, one decision at a time —
  the reference's contract, kept for drop-in compatibility and debugging.
- `policy(rng, obs, ...) -> (stage_idx, num_exec, info)`: pure jittable
  function over the padded `Observation`, the TPU-native path used inside
  vmapped/scanned rollouts. `stage_idx` is a flat padded node index
  (job * max_stages + stage, or -1 for "no selection").
"""

from __future__ import annotations

import abc
from typing import Any

import jax


class Scheduler(abc.ABC):
    """Interface for all schedulers (reference scheduler.py:10-18)."""

    name: str

    @abc.abstractmethod
    def schedule(self, obs: Any) -> tuple[dict[str, Any], dict[str, Any]]:
        """One decision from a single padded Observation. Returns
        ({"stage_idx": flat padded index | -1, "num_exec": int}, info)."""

    @abc.abstractmethod
    def policy(self, rng: jax.Array, obs: Any):
        """Pure jittable single-decision function; vmap/scan-safe."""


class TrainableScheduler(Scheduler):
    """Interface for trainable schedulers (reference scheduler.py:21-55).

    The torch `nn.Module` + owned-optimizer design becomes functional:
    parameters are an explicit pytree, `evaluate_actions` is a pure function
    of (params, rollout arrays), and the optimizer lives with the trainer
    (optax), so `update_parameters` (reference :37-54) has no analogue here —
    gradient clipping and the update are part of the trainer's jitted step.
    """

    params: Any  # flax parameter pytree

    @abc.abstractmethod
    def evaluate_actions(self, params: Any, obsns: Any, actions: Any):
        """Log-probs and entropies of `actions` under `params`, batched over
        the rollout. Pure; differentiable wrt `params`."""
