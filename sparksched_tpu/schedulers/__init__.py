"""Scheduler plugin layer.

Mirrors the reference `schedulers/` package (scheduler.py:10-55,
__init__.py:17-21): a `Scheduler` interface, string-keyed factory, two
heuristics and the trainable Decima policy — but every policy here is a pure
jittable function over the padded `Observation`, so it can run inside
`jax.vmap`/`lax.scan` rollouts entirely on device.
"""

from .base import Scheduler, TrainableScheduler  # noqa: F401
from .heuristics import (  # noqa: F401
    RandomScheduler,
    RoundRobinScheduler,
    find_stage_per_job,
    random_policy,
    round_robin_policy,
)
from .decima import DecimaScheduler  # noqa: F401

_REGISTRY = {
    "RoundRobinScheduler": RoundRobinScheduler,
    "RandomScheduler": RandomScheduler,
    "DecimaScheduler": DecimaScheduler,
}


def make_scheduler(agent_cfg: dict) -> Scheduler:
    """String-keyed factory (reference schedulers/__init__.py:17-21)."""
    cls_name = agent_cfg["agent_cls"]
    if cls_name not in _REGISTRY:
        raise ValueError(f"'{cls_name}' is not a valid scheduler.")
    kwargs = {k: v for k, v in agent_cfg.items() if k != "agent_cls"}
    return _REGISTRY[cls_name](**kwargs)
