"""Episode metrics (reference spark_sched_sim/metrics.py:4-23), computed
on-device from the SoA EnvState so they can be vmapped across thousands of
environment lanes and logged from the host once per iteration."""

from __future__ import annotations

import jax.numpy as jnp

from .env.state import EnvState


def job_durations(state: EnvState) -> tuple[jnp.ndarray, jnp.ndarray]:
    """(durations[J], mask[J]) over arrived jobs: duration is
    min(t_completed, wall_time) - t_arrival (reference metrics.py:4-10)."""
    mask = state.job_arrived
    t_end = jnp.minimum(state.job_t_completed, state.wall_time)
    durations = jnp.where(mask, t_end - state.job_arrival_time, 0.0)
    return durations, mask


def avg_job_duration(state: EnvState) -> jnp.ndarray:
    d, m = job_durations(state)
    return d.sum() / jnp.maximum(m.sum(), 1)


def avg_num_jobs(state: EnvState) -> jnp.ndarray:
    """Time-average number of concurrent jobs = total job-time / wall time
    (reference metrics.py:17-18)."""
    d, _ = job_durations(state)
    return d.sum() / jnp.maximum(state.wall_time, 1e-9)


def num_completed_jobs(state: EnvState) -> jnp.ndarray:
    return (state.job_arrived & jnp.isfinite(state.job_t_completed)).sum()


def num_job_arrivals(state: EnvState) -> jnp.ndarray:
    return state.job_arrived.sum()


PERCENTILE_QS = (25, 50, 75, 100)


def masked_percentiles(durations, mask, qs=PERCENTILE_QS):
    """Host-side percentiles over masked durations; one shared policy for
    single states and pooled vmapped batches."""
    import numpy as np

    d, m = np.asarray(durations).ravel(), np.asarray(mask).ravel()
    return np.percentile(d[m], list(qs)) if m.any() else np.zeros(len(qs))


def job_duration_percentiles(state: EnvState, qs=PERCENTILE_QS):
    """Percentiles over arrived jobs (reference metrics.py:21-23)."""
    return masked_percentiles(*job_durations(state), qs)
