"""sparksched_tpu — a TPU-native (JAX/XLA) framework for DAG-job cluster
scheduling simulation and RL training.

Re-designed from scratch with the capabilities of
`ArchieGertsman/gym-sparksched` (the "spark-sched-sim" reference, mounted at
/root/reference), but built TPU-first:

- the discrete-event Spark simulator is a pure function over a
  struct-of-arrays, fixed-shape-padded environment state, so `jax.vmap` runs
  thousands of parallel environments per chip and `jax.lax.scan` collects
  whole trajectories on-device (reference: one Python object-graph env per
  OS process, spark_sched_sim/spark_sched_sim.py);
- the event heap (reference: components/event.py) becomes an argmin over
  candidate event times with exact FIFO tie-breaking via sequence numbers;
- the Decima GNN (reference: schedulers/decima/scheduler.py, PyTorch
  Geometric) is a flax module whose level-wise DAG message passing runs as
  batched dense matmuls on the MXU;
- rollout workers + mp.Pipe (reference: trainers/) collapse into a single
  jitted program: `vmap(policy . env_step)` under `lax.scan`, with PPO/VPG
  losses computed on-device and `shard_map` scaling lanes across a device
  mesh.
"""

__version__ = "0.1.0"

from . import config  # noqa: F401

# Gymnasium registration (reference spark_sched_sim/__init__.py:6), guarded
# so the core framework works without gymnasium installed.
try:
    from gymnasium.envs.registration import register as _register

    _register(
        id="SparkSchedSimEnv-v0",
        entry_point="sparksched_tpu.env.gym_compat:SparkSchedSimGymEnv",
    )
except Exception:  # pragma: no cover - gymnasium absent or double-register
    pass
