"""Gantt-chart renderer (reference spark_sched_sim/components/renderer.py).

The reference draws a live pygame window from per-executor task histories
accumulated inside the simulator objects (renderer.py:83-117,
executor.py:34-44) and saves `screenshot.png` on close. Device-side history
ring buffers would bloat the vmapped env state, so here the history is
recorded host-side by snapshotting the (tiny) executor arrays once per
decision step of a rendered episode, and the chart is drawn with
matplotlib: one row per executor, segments colored by job, red markers at
job completion times, and the same summary stats text."""

from __future__ import annotations

from typing import Any

import numpy as np

from . import metrics
from .env.state import EnvState


class GanttRenderer:
    """Post-hoc and live Gantt rendering.

    `live_path` + `live_every` approximate the reference's real-time
    render mode (reference components/renderer.py:45-81 `render_frame`,
    one pygame frame per decision): every `live_every` recorded
    decisions the chart is redrawn to `live_path`, so an episode in
    progress can be watched by any image viewer that follows the file.
    Headless boxes have no display server, so a refreshed file is the
    render target — the reference equally falls back to a saved
    `screenshot.png` artifact on close."""

    def __init__(self, num_executors: int, live_path: str | None = None,
                 live_every: int = 50) -> None:
        self.num_executors = num_executors
        self.times: list[float] = []
        self.exec_job: list[np.ndarray] = []
        self.exec_busy: list[np.ndarray] = []
        self.final_state: EnvState | None = None
        self.live_path = live_path
        self.live_every = max(int(live_every), 1)
        self._live_last = 0.0

    def record(self, state: EnvState) -> None:
        """Snapshot executor assignment after an env step; in live mode,
        refresh the on-disk frame every `live_every` snapshots — rate-
        limited to one redraw per second of wall clock, since each
        refresh redraws the full history (O(snapshots)) and an unlimited
        refresh cadence would make long episodes rendering-bound."""
        self.times.append(float(state.wall_time))
        self.exec_job.append(np.asarray(state.exec_job))
        self.exec_busy.append(np.asarray(state.exec_executing))
        self.final_state = state
        if (
            self.live_path is not None
            and len(self.times) % self.live_every == 0
        ):
            import time as _time

            now = _time.monotonic()
            if now - self._live_last >= 1.0:
                self._live_last = now
                self.render(self.live_path)

    def _segments(self):
        """Merge consecutive snapshots into (executor, job, t0, t1) bars."""
        segs: list[tuple[int, int, float, float]] = []
        open_seg: dict[int, tuple[int, float]] = {}
        for t, jobs, busy in zip(self.times, self.exec_job, self.exec_busy):
            for e in range(self.num_executors):
                j = int(jobs[e]) if busy[e] else -1
                cur = open_seg.get(e)
                if cur is not None and cur[0] != j:
                    segs.append((e, cur[0], cur[1], t))
                    open_seg.pop(e)
                    cur = None
                if cur is None and j >= 0:
                    open_seg[e] = (j, t)
        t_end = self.times[-1] if self.times else 0.0
        for e, (j, t0) in open_seg.items():
            segs.append((e, j, t0, t_end))
        return [s for s in segs if s[3] > s[2]]

    def render(self, path: str = "screenshot.png") -> str:
        import matplotlib

        matplotlib.use("Agg")
        import matplotlib.pyplot as plt

        segs = self._segments()
        state = self.final_state
        n_jobs = int(np.asarray(state.job_arrived).sum()) if state else 1
        cmap = plt.colormaps["tab20"].resampled(max(n_jobs, 1))

        fig, ax = plt.subplots(
            figsize=(12, 0.4 * self.num_executors + 2)
        )
        for e, j, t0, t1 in segs:
            ax.barh(e, t1 - t0, left=t0, height=0.8,
                    color=cmap(j % 20), edgecolor="none")
        if state is not None:
            t_done = np.asarray(state.job_t_completed)
            for j in np.flatnonzero(np.isfinite(t_done)):
                ax.axvline(t_done[j], color="red", lw=0.8, alpha=0.7)
            ajd = float(metrics.avg_job_duration(state))
            done = int(metrics.num_completed_jobs(state))
            ax.set_title(
                f"avg job duration: {ajd * 1e-3:.1f}s    "
                f"completed jobs: {done}"
            )
        ax.set_xlabel("wall time (ms)")
        ax.set_ylabel("executor")
        ax.set_ylim(-0.5, self.num_executors - 0.5)
        fig.tight_layout()
        fig.savefig(path, dpi=120)
        plt.close(fig)
        return path
