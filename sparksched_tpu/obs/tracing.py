"""Named trace scopes so captured device traces are legible.

`annotate(name)` combines the two annotation mechanisms a jitted JAX
program needs for one Perfetto-readable label:

- `jax.named_scope(name)`: active at TRACE time — prefixes the HLO
  metadata of every op created inside the block, so the XLA device
  timeline groups the phase's kernels under the name;
- `jax.profiler.TraceAnnotation(name)`: active at RUN time on the host
  thread — marks the dispatch span in the host track (useful around
  un-jitted host phases like the trainer's collect/update calls).

Entering both is cheap and safe in either context (a TraceAnnotation
with no profiler running is a no-op; a named_scope outside tracing only
touches a thread-local name stack), so call sites don't have to care
which side of the jit boundary they are on. The phases the codebase
labels: `decima/gnn` (GNN eval), `env/micro_step` (flat engine),
`collect/scatter` (decision-buffer scatter), `train/ppo_update`.
"""

from __future__ import annotations


class annotate:
    """Context manager: `with annotate("decima/gnn"): ...`"""

    def __init__(self, name: str) -> None:
        self.name = name
        self._ns = None
        self._ta = None

    def __enter__(self) -> "annotate":
        import jax

        self._ns = jax.named_scope(self.name)
        self._ns.__enter__()
        try:
            self._ta = jax.profiler.TraceAnnotation(self.name)
            self._ta.__enter__()
        except Exception:
            self._ta = None  # profiler backend unavailable: scope only
        return self

    def __exit__(self, exc_type, exc_val, exc_tb) -> None:
        try:
            if self._ta is not None:
                self._ta.__exit__(exc_type, exc_val, exc_tb)
        finally:
            self._ns.__exit__(exc_type, exc_val, exc_tb)
