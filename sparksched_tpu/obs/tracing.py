"""Named trace scopes so captured device traces are legible.

`annotate(name)` combines the two annotation mechanisms a jitted JAX
program needs for one Perfetto-readable label:

- `jax.named_scope(name)`: active at TRACE time — prefixes the HLO
  metadata of every op created inside the block, so the XLA device
  timeline groups the phase's kernels under the name;
- `jax.profiler.TraceAnnotation(name)`: active at RUN time on the host
  thread — marks the dispatch span in the host track (useful around
  un-jitted host phases like the trainer's collect/update calls).

Entering both is cheap and safe in either context (a TraceAnnotation
with no profiler running is a no-op; a named_scope outside tracing only
touches a thread-local name stack), so call sites don't have to care
which side of the jit boundary they are on. The phases the codebase
labels: `decima/gnn` (GNN eval), `env/micro_step` (flat engine),
`collect/scatter` (decision-buffer scatter), `train/ppo_update`.

Exception safety: a raise inside the annotated block (or inside one of
the two underlying exits) must still pop the named-scope stack — a
leaked scope prefixes every LATER trace's labels with a dead phase
name, corrupting the whole capture, not just the failing region. Both
context managers live on a `contextlib.ExitStack`, whose `__exit__`
guarantees LIFO unwinding even when an inner exit raises;
`tests/test_obs.py::test_annotate_exception_safe` pins it.
"""

from __future__ import annotations

import contextlib
import itertools
import os
import time


class annotate:
    """Context manager: `with annotate("decima/gnn"): ...`"""

    def __init__(self, name: str) -> None:
        self.name = name
        self._stack: contextlib.ExitStack | None = None

    def __enter__(self) -> "annotate":
        import jax

        stack = contextlib.ExitStack()
        stack.enter_context(jax.named_scope(self.name))
        try:
            stack.enter_context(jax.profiler.TraceAnnotation(self.name))
        except Exception:
            pass  # profiler backend unavailable: scope only
        self._stack = stack
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        stack, self._stack = self._stack, None
        if stack is not None:
            return stack.__exit__(exc_type, exc_val, exc_tb)
        return False


# ---------------------------------------------------------------------------
# per-request span traces (ISSUE 11)
#
# The serving path's Dapper-style walk: a trace id minted at `Ticket`
# creation, one perf_counter stamp per phase as the request moves
# submit -> batch_admit -> dispatch -> harvest -> device_compute ->
# scatter_back -> reply. `harvest` (ISSUE 15) is the instant the host
# STARTS materializing the call — immediately after dispatch on the
# synchronous front, one full in-flight residency later under the
# pipelined front (dispatch -> harvest is the pipeline overlap the
# span exists to show).
# Host-side only — the compiled serve programs are untouched
# (the analysis registry pins them byte-identical), and the host
# phases bracket the device work: `dispatch` is the instant the
# compiled call is issued, `device_compute` when its outputs are ready
# (block_until_ready), `scatter_back` when the host has the concrete
# ServeResults (device_get + un-batching). The instrumented
# MicroBatcher additionally enters `annotate("serve/flush")` around
# the dispatch, so a Perfetto capture carries the same phase label the
# trace records use.
#
# Across the wire (ISSUE 16): the network client brackets the walk
# with `wire_submit` (the instant the request leaves the client) and
# `wire_reply` (the instant the decoded reply is in the client's
# hands). The server's spans ride back in the reply as offsets and
# are re-anchored so the server-side `submit` coincides with the
# client's `wire_submit` — by construction, `reply -> wire_reply`
# is then the request's total NETWORK + serialization overhead (both
# directions plus server-side parse), while `dispatch ->
# device_compute` stays the device share and the harvest spans the
# host share. One clock never spans two machines: each side stamps
# only its own perf_counter, and only OFFSETS cross the wire. The
# runlog `trace` record shape is unchanged — the wire spans are just
# two more keys in `spans_ms`.
# ---------------------------------------------------------------------------

SPAN_ORDER = (
    "wire_submit", "submit", "batch_admit", "dispatch", "harvest",
    "device_compute", "scatter_back", "reply", "wire_reply",
)

_TRACE_SEQ = itertools.count()


class RequestTrace:
    """One request's spans: `stamp(name)` records a perf_counter time;
    `offsets_ms()` converts to ms offsets from submit (the runlog
    `trace` record payload). Trace ids are process-unique and ordered
    (`t<pid>-<seq>`), deterministic given submission order."""

    __slots__ = ("trace_id", "spans")

    def __init__(self, trace_id: str | None = None) -> None:
        self.trace_id = (
            trace_id
            if trace_id is not None
            else f"t{os.getpid():x}-{next(_TRACE_SEQ):08d}"
        )
        self.spans: dict[str, float] = {}

    def stamp(self, name: str, t: float | None = None) -> None:
        self.spans[name] = time.perf_counter() if t is None else t

    def offsets_ms(self) -> dict[str, float]:
        base = self.spans.get("submit")
        if base is None:
            # a wire-side trace that never reached a server (429 /
            # transport error) still has its client bracket
            base = self.spans.get("wire_submit")
        if base is None:
            return {}
        return {
            name: (self.spans[name] - base) * 1e3
            for name in SPAN_ORDER
            if name in self.spans
        }
