"""Named trace scopes so captured device traces are legible.

`annotate(name)` combines the two annotation mechanisms a jitted JAX
program needs for one Perfetto-readable label:

- `jax.named_scope(name)`: active at TRACE time — prefixes the HLO
  metadata of every op created inside the block, so the XLA device
  timeline groups the phase's kernels under the name;
- `jax.profiler.TraceAnnotation(name)`: active at RUN time on the host
  thread — marks the dispatch span in the host track (useful around
  un-jitted host phases like the trainer's collect/update calls).

Entering both is cheap and safe in either context (a TraceAnnotation
with no profiler running is a no-op; a named_scope outside tracing only
touches a thread-local name stack), so call sites don't have to care
which side of the jit boundary they are on. The phases the codebase
labels: `decima/gnn` (GNN eval), `env/micro_step` (flat engine),
`collect/scatter` (decision-buffer scatter), `train/ppo_update`.

Exception safety: a raise inside the annotated block (or inside one of
the two underlying exits) must still pop the named-scope stack — a
leaked scope prefixes every LATER trace's labels with a dead phase
name, corrupting the whole capture, not just the failing region. Both
context managers live on a `contextlib.ExitStack`, whose `__exit__`
guarantees LIFO unwinding even when an inner exit raises;
`tests/test_obs.py::test_annotate_exception_safe` pins it.
"""

from __future__ import annotations

import contextlib


class annotate:
    """Context manager: `with annotate("decima/gnn"): ...`"""

    def __init__(self, name: str) -> None:
        self.name = name
        self._stack: contextlib.ExitStack | None = None

    def __enter__(self) -> "annotate":
        import jax

        stack = contextlib.ExitStack()
        stack.enter_context(jax.named_scope(self.name))
        try:
            stack.enter_context(jax.profiler.TraceAnnotation(self.name))
        except Exception:
            pass  # profiler backend unavailable: scope only
        self._stack = stack
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        stack, self._stack = self._stack, None
        if stack is not None:
            return stack.__exit__(exc_type, exc_val, exc_tb)
        return False
