"""Per-request critical-path attribution (ISSUE 20, tentpole part 1).

The span walk (obs/tracing.py `SPAN_ORDER`) records WHEN each phase of
a served request happened; nothing decomposed WHY the wall latency was
what it was. This module closes the gap between "p99 breached" and
"which segment owns the tail":

- `decompose(spans)` turns any subset of a request's span stamps into
  ADDITIVE, NON-OVERLAPPING segments that sum to the measured wall
  latency EXACTLY (telescoping: each gap between adjacent present
  boundaries is attributed to the segment of the earlier boundary, so
  the sum is `last - first` by construction, for full traces, wire
  traces, quarantined requests, and 429-rejected requests alike).
  The segment model, from the boundary semantics the serving stack
  stamps (serve/session.py, serve/server.py):

      wire_submit    client bracket -> server submit. After the wire
                     re-anchor this gap is 0 on a served request, so
                     the segment is nonzero only for requests that
                     never reached a server (429 / transport error:
                     their whole wall lands here).
      queue_wait     submit -> batch_admit: time queued in the front.
      batch_form     batch_admit -> dispatch: admission-to-issue
                     (batch assembly + the compiled call's setup).
      dispatch       dispatch -> harvest: the issue itself PLUS the
                     in-flight residency under the pipelined front
                     (~0 on the synchronous front — the overlap the
                     pipeline buys shows up HERE, not in
                     device_compute).
      device_compute harvest -> device_compute: the host's
                     block_until_ready wait — the device share.
      harvest        device_compute -> scatter_back -> reply: host
                     materialization (device_get + un-batching) and
                     ticket resolution — the host share the pipelined
                     front exists to hide.
      wire_reply     reply -> wire_reply: total network +
                     serialization overhead, both directions (the
                     re-anchor folds the outbound leg in here — see
                     obs/tracing.py).

- `SegmentProfile` keeps the JOINT (wall bucket x segment) sums next
  to a wall-latency `StreamingHistogram`, so attribution is available
  AT A QUANTILE: the segment mix of requests NEAR p50 vs NEAR p99 —
  marginal per-segment histograms cannot answer that (the p99 of
  queue_wait is not the queue_wait of the p99 request).

- `CritPathAnalyzer` is the serving-side instrument: fed one trace
  per finished request (`serve/session.py _finish_ticket`), it
  maintains the global / per-tenant / per-replica profiles, feeds
  per-segment `serve_seg_<name>_ms` histograms into the shared
  `MetricsRegistry` (the fleet collector windows those per replica —
  obs/fleet.py), and keeps a bounded reservoir of the slowest-N full
  traces per window, emitted as `tail_exemplar` runlog records at
  each window flush — a p99 incident ships concrete traces, not a
  number.

Threading: the analyzer is single-owner state driven by the serve
pump (the fronts call `add` from `_finish_ticket`; the collector
reads `snapshot()`/`flush_window()` from the same pump thread in the
server integration). The wire client does NOT share an analyzer —
its worker threads use the pure `decompose` + the locked registry
(serve/server.py `ServeClient._resolve`).
"""

from __future__ import annotations

import heapq
import itertools
import time
from typing import Any, Callable

from .metrics import StreamingHistogram
from .tracing import SPAN_ORDER
from ..ownership import assert_owner

# attribution segments, in boundary order (the runlog / scoreboard /
# bench row vocabulary)
SEGMENTS = (
    "wire_submit", "queue_wait", "batch_form", "dispatch",
    "device_compute", "harvest", "wire_reply",
)

# the gap starting at span boundary <key> belongs to segment <value>;
# `scatter_back -> reply` merges into `harvest` (both are host
# materialization/resolution — splitting them adds a segment no
# operator decision distinguishes)
_SEG_OF_GAP = {
    "wire_submit": "wire_submit",
    "submit": "queue_wait",
    "batch_admit": "batch_form",
    "dispatch": "dispatch",
    "harvest": "device_compute",
    "device_compute": "harvest",
    "scatter_back": "harvest",
    "reply": "wire_reply",
}

# metric-registry histogram name per segment (what the fleet
# collector windows per replica)
SEG_HIST = {s: f"serve_seg_{s}_ms" for s in SEGMENTS}

_SPAN_RANK = {name: i for i, name in enumerate(SPAN_ORDER)}


def decompose(spans: dict[str, float], *,
              scale_ms: float = 1e3) -> dict[str, Any]:
    """Decompose one request's span stamps into additive segments.

    `spans` maps span name -> stamp, in ANY consistent unit: raw
    perf_counter seconds (`scale_ms=1e3`, the Ticket/WireTicket
    shape) or ms offsets (`scale_ms=1.0`, the runlog `trace` record /
    `RequestTrace.offsets_ms` shape). Unknown span names are ignored;
    the decomposition works on any subset of `SPAN_ORDER` with >= 2
    present boundaries (a single-boundary trace has zero wall and an
    empty decomposition).

    Returns `{"wall_ms", "segments": {segment: ms}, "first", "last"}`
    and GUARANTEES sum(segments.values()) == wall_ms to float
    round-off (test-pinned) — the invariant is checked here, so a
    trace whose stamps violate it (impossible by telescoping) raises
    rather than shipping books that don't balance.
    """
    present = sorted(
        (n for n in spans if n in _SPAN_RANK),
        key=_SPAN_RANK.__getitem__,
    )
    segments: dict[str, float] = {}
    if len(present) < 2:
        return {"wall_ms": 0.0, "segments": segments,
                "first": present[0] if present else None,
                "last": present[0] if present else None}
    wall = (spans[present[-1]] - spans[present[0]]) * scale_ms
    for a, b in itertools.pairwise(present):
        gap = (spans[b] - spans[a]) * scale_ms
        seg = _SEG_OF_GAP[a]
        segments[seg] = segments.get(seg, 0.0) + gap
    total = sum(segments.values())
    if abs(total - wall) > 1e-6 + 1e-9 * abs(wall):
        raise ValueError(
            f"segment decomposition does not sum to wall latency: "
            f"{total!r} != {wall!r} over spans {sorted(spans)}"
        )
    return {"wall_ms": wall, "segments": segments,
            "first": present[0], "last": present[-1]}


class SegmentProfile:
    """Joint (wall-latency bucket x segment) accounting: a wall
    `StreamingHistogram` plus, per wall bucket, the request count and
    per-segment ms sums of the requests that landed there. O(buckets)
    like the histogram itself; `attribution_at(q)` reads the segment
    mix of the requests NEAR quantile q."""

    __slots__ = ("wall", "_cells")

    def __init__(self) -> None:
        self.wall = StreamingHistogram()
        # bucket index -> [count, {segment: ms sum}]
        self._cells: dict[int, list] = {}

    def add(self, wall_ms: float, segments: dict[str, float]) -> None:
        idx = self.wall._index(max(0.0, float(wall_ms)))
        self.wall.add(wall_ms)
        cell = self._cells.get(idx)
        if cell is None:
            cell = self._cells[idx] = [0, {}]
        cell[0] += 1
        sums = cell[1]
        for seg, ms in segments.items():
            sums[seg] = sums.get(seg, 0.0) + ms

    def attribution_at(self, q: float,
                       min_requests: int = 8) -> dict[str, Any] | None:
        """Segment mix of the requests near quantile `q`: starting
        from the wall bucket holding the q-quantile, grow the bucket
        window symmetrically until it covers >= `min_requests`
        requests (or 5% of the population, whichever is larger, capped
        by the population). Returns `{"wall_ms", "n", "share", and
        "mean_ms" per segment}`, or None on an empty profile."""
        if self.wall.count == 0:
            return None
        target = self.wall.quantile(q)
        center = self.wall._index(target)
        want = min(self.wall.count,
                   max(int(min_requests), self.wall.count // 20))
        n = 0
        sums: dict[str, float] = {}
        lo = hi = center
        span_max = len(self.wall.counts)
        for radius in range(span_max + 1):
            for idx in ({center} if radius == 0
                        else {center - radius, center + radius}):
                cell = self._cells.get(idx)
                if cell is None:
                    continue
                n += cell[0]
                for seg, ms in cell[1].items():
                    sums[seg] = sums.get(seg, 0.0) + ms
                lo, hi = min(lo, idx), max(hi, idx)
            if n >= want:
                break
        total = sum(sums.values())
        return {
            "q": q,
            "wall_ms": round(target, 4),
            "n": n,
            "share": {
                seg: round(ms / total, 4) if total > 0 else 0.0
                for seg, ms in sorted(sums.items())
            },
            "mean_ms": {
                seg: round(ms / n, 4) if n else 0.0
                for seg, ms in sorted(sums.items())
            },
        }

    def dominant_segment(self, q: float = 0.99) -> str | None:
        att = self.attribution_at(q)
        if att is None or not att["share"]:
            return None
        return max(att["share"].items(), key=lambda kv: kv[1])[0]

    def summary(self) -> dict[str, Any]:
        out: dict[str, Any] = {"n": self.wall.count}
        for q, label in ((0.5, "at_p50"), (0.99, "at_p99")):
            att = self.attribution_at(q)
            if att is not None:
                out[label] = att
        dom = self.dominant_segment()
        if dom is not None:
            out["dominant_tail_segment"] = dom
        return out


class _Exemplar:
    """Heap entry: min-heap on wall so the reservoir keeps the
    slowest-N; `seq` breaks ties deterministically."""

    __slots__ = ("wall_ms", "seq", "record")

    def __init__(self, wall_ms: float, seq: int,
                 record: dict[str, Any]) -> None:
        self.wall_ms = wall_ms
        self.seq = seq
        self.record = record

    def __lt__(self, other: "_Exemplar") -> bool:
        return (self.wall_ms, self.seq) < (other.wall_ms, other.seq)


class CritPathAnalyzer:
    """The serving-side attribution instrument (module docstring).

    `add(trace, ...)` per finished request; `snapshot()` for the
    attribution block a scrape/bench row stamps; `flush_window()`
    emits the window's slowest-N traces as `tail_exemplar` runlog
    records (called from `add` when `window_s` elapses, and by the
    fleet collector's scrape so exemplars ship even on an idle
    tail)."""

    def __init__(self, *, metrics=None, runlog=None, top_n: int = 8,
                 window_s: float = 60.0, max_keys: int = 32,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.metrics = metrics
        self.runlog = runlog
        self.top_n = max(1, int(top_n))
        self.window_s = float(window_s)
        self.max_keys = max(1, int(max_keys))
        self._clock = clock
        self.profile = SegmentProfile()
        self.by_tenant: dict[str, SegmentProfile] = {}
        self.by_replica: dict[str, SegmentProfile] = {}
        self._exemplars: list[_Exemplar] = []
        self._seq = 0
        self._window_start = self._clock()
        self.stats = {
            "critpath_requests": 0,
            "critpath_errors": 0,
            "critpath_exemplar_windows": 0,
            "critpath_exemplars": 0,
        }

    # -- feed ----------------------------------------------------------

    def add(self, trace, *, tenant=None, replica=None,
            error: str | None = None) -> dict[str, Any]:
        """Ingest one finished request's `RequestTrace` (raw
        perf_counter stamps). Returns the decomposition (the caller
        may stamp it on a reply or a bench row)."""
        assert_owner(self, "serve-pump")
        return self.observe(
            trace.spans, trace_id=trace.trace_id, scale_ms=1e3,
            tenant=tenant, replica=replica, error=error,
        )

    def observe(self, spans: dict[str, float], *,
                trace_id: str | None = None, scale_ms: float = 1e3,
                tenant=None, replica=None,
                error: str | None = None) -> dict[str, Any]:
        """`add` for span dicts that aren't `RequestTrace`s (ms-offset
        records replayed from a runlog: pass `scale_ms=1.0`)."""
        dec = decompose(spans, scale_ms=scale_ms)
        wall, segments = dec["wall_ms"], dec["segments"]
        self.stats["critpath_requests"] += 1
        if error is not None:
            self.stats["critpath_errors"] += 1
        self.profile.add(wall, segments)
        if tenant is not None:
            self._keyed(self.by_tenant, str(tenant)).add(
                wall, segments)
        if replica is not None:
            self._keyed(self.by_replica, str(replica)).add(
                wall, segments)
        if self.metrics is not None:
            for seg, ms in segments.items():
                self.metrics.observe(SEG_HIST[seg], ms)
        self._seq += 1
        ex = _Exemplar(wall, self._seq, {
            "trace_id": trace_id,
            "wall_ms": round(wall, 4),
            "segments": {k: round(v, 4) for k, v in segments.items()},
            "tenant": None if tenant is None else str(tenant),
            "replica": None if replica is None else str(replica),
            "error": error,
        })
        if len(self._exemplars) < self.top_n:
            heapq.heappush(self._exemplars, ex)
        elif self._exemplars[0] < ex:
            heapq.heapreplace(self._exemplars, ex)
        self.maybe_flush_window()
        return dec

    def _keyed(self, table: dict[str, SegmentProfile],
               key: str) -> SegmentProfile:
        prof = table.get(key)
        if prof is None:
            if len(table) >= self.max_keys:
                # bounded cardinality: the long tail of keys shares
                # one overflow profile instead of growing the table
                key = "~other"
                prof = table.get(key)
                if prof is not None:
                    return prof
            prof = table[key] = SegmentProfile()
        return prof

    # -- exemplars -----------------------------------------------------

    def maybe_flush_window(self, now: float | None = None
                           ) -> list[dict[str, Any]]:
        """`flush_window` iff `window_s` has elapsed — the cadence
        guard shared by `observe` and the fleet collector's scrape
        (which flushes an IDLE tail: no new requests, the reservoir
        still ships)."""
        t = self._clock() if now is None else float(now)
        if t - self._window_start < self.window_s:
            return []
        return self.flush_window(now=t)

    def flush_window(self, now: float | None = None
                     ) -> list[dict[str, Any]]:
        """Emit the current window's slowest-N traces as
        `tail_exemplar` runlog records (slowest first) and reset the
        reservoir. No-op (empty list) on an empty window."""
        t = self._clock() if now is None else float(now)
        window_s = t - self._window_start
        self._window_start = t
        if not self._exemplars:
            return []
        out = [e.record for e in
               sorted(self._exemplars, reverse=True)]
        self._exemplars = []
        self.stats["critpath_exemplar_windows"] += 1
        self.stats["critpath_exemplars"] += len(out)
        if self.runlog is not None:
            for rank, rec in enumerate(out):
                self.runlog.tail_exemplar(
                    rank=rank, window_s=round(window_s, 3), **rec)
        return out

    # -- read ----------------------------------------------------------

    def dominant_tail_segment(self) -> str | None:
        return self.profile.dominant_segment()

    def snapshot(self) -> dict[str, Any]:
        """The attribution block: global p50/p99 segment mixes plus
        each tenant's and replica's dominant tail segment (full
        per-key profiles stay internal — the block must stay small
        enough to stamp on every bench row / fleet scrape)."""
        block = self.profile.summary()
        block["stats"] = dict(self.stats)
        for label, table in (("tenants", self.by_tenant),
                             ("replicas", self.by_replica)):
            if table:
                block[label] = {
                    key: {
                        "n": prof.wall.count,
                        "p99_wall_ms": round(
                            prof.wall.quantile(0.99), 4),
                        "dominant_tail_segment":
                            prof.dominant_segment(),
                    }
                    for key, prof in sorted(table.items())
                }
        return block
