"""Perf-regression ledger: an append-only index over the repo's bench
artifacts across rounds (ISSUE 17, tentpole part 3).

The bench series (`BENCH_rNN.json` at the repo root, `artifacts/*.json`
per subsystem) is the project's perf memory, but nothing reads it back:
a PR that regresses the serving headline ships silently unless a human
diffs JSON by hand. The ledger closes that loop:

- **Index**: schema-tolerant extraction over every `artifacts/*.json` +
  `BENCH_*.json`. Three extractors, in order: (1) any dict anywhere in
  the document carrying a string `metric` and numeric `value` is a row
  (the r10+ row dialect, BENCH `parsed` blocks, fused_ab config pairs,
  MULTICHIP measured rows); (2) `sustained_rps_slo`-style headline
  dicts ({front: rps}) become synthetic `sustained_rps_slo_<front>`
  entries; (3) files yielding nothing (protocol-only artifacts like
  `online_loop_r16.json`) fall back to shallow numeric leaves named by
  their dotted path, so *every* parseable file contributes entries and
  "full parse coverage" is checkable (files_failed == 0 and every file
  indexed).
- **Rounds**: inferred from the `_rNN` filename stamp; a file without
  one gets round -1 (indexed, excluded from trends).
- **Noise bands**: each entry's band comes from its own artifact — the
  paired-rep lists the A/B protocol stamps (`ab.goodput_rps_reps`,
  `*_reps`) give (min, max) of reps; entries without reps get a
  DEFAULT_REL_BAND half-width. Bands travel with the entry, so the
  verdict never invents a tolerance the measurement didn't earn.
- **Verdicts**: for each metric family observed in >= 2 rounds, compare
  the latest entry against the previous round's. Direction comes from
  the unit (rates are higher-better, latencies lower-better; unknown
  units are trend-only). REGRESSION only when the bands are DISJOINT in
  the bad direction (latest's most favorable edge worse than previous'
  least favorable edge) — i.e. outside the noise band, the PERF.md
  operational-rule standard. IMPROVEMENT is the mirror; else STABLE.

CLI (`python -m sparksched_tpu.obs.ledger`): prints the trend report,
checks `--pin metric=value` headline assertions, and exits nonzero on
parse-coverage failure (rc 2), pin mismatch (rc 3), or a regression
verdict (rc 4) — the tier-1 gate wires exactly this.
"""

from __future__ import annotations

import argparse
import glob
import json
import math
import os
import re
from typing import Any

# default relative half-width when an entry carries no paired reps:
# generous enough to absorb single-run jitter on a noisy box, tight
# enough that a real headline drop (the r13 100 -> 125 scale) is
# orders beyond it
DEFAULT_REL_BAND = 0.05
# floor on any band's half-width: 3-rep lists rounded to 2 decimals
# can collapse to zero width, and a zero-width band turns sub-percent
# jitter into a REGRESSION verdict
MIN_REL_BAND = 0.01
# committed waiver file: {"waivers": {metric: reason}} acknowledges a
# verdict-visible drop that is a protocol change, not a perf loss
# (e.g. r18 re-measured sustained rps WITH the network tier's wire
# cost on the 1-core box — ROADMAP item 2)
WAIVERS_FILE = "ledger_waivers.json"

ROUND_RE = re.compile(r"_r(\d+)")

# unit direction: which way is "worse". Rates up = good, latencies
# up = bad; anything unrecognized is indexed but never judged.
_HIGHER_BETTER = ("steps/s", "rps", "decisions/s", "dec/s", "req/s",
                  "sessions/s", "/s")
_LOWER_BETTER = ("ms", "us", "s", "bytes", "mb", "gb")


def unit_direction(unit: str) -> int:
    """+1 higher-better, -1 lower-better, 0 unknown."""
    u = (unit or "").strip().lower()
    if not u:
        return 0
    for suf in _HIGHER_BETTER:
        if u.endswith(suf):
            return 1
    if u in _LOWER_BETTER:
        return -1
    return 0


class Entry:
    """One indexed measurement: (round, file, metric, value, unit,
    noise band). `band` is the (lo, hi) envelope of the measurement's
    own paired reps, or a DEFAULT_REL_BAND half-width."""

    __slots__ = ("round", "file", "metric", "value", "unit", "band",
                 "band_source", "path")

    def __init__(self, rnd: int, file: str, metric: str, value: float,
                 unit: str = "", band: tuple[float, float] | None = None,
                 band_source: str = "default", path: str = "") -> None:
        self.round = rnd
        self.file = file
        self.metric = metric
        self.value = float(value)
        self.unit = unit
        if band is None:
            half = abs(self.value) * DEFAULT_REL_BAND
            band = (self.value - half, self.value + half)
            band_source = "default"
        floor = abs(self.value) * MIN_REL_BAND
        band = (min(band[0], self.value - floor),
                max(band[1], self.value + floor))
        self.band = (float(band[0]), float(band[1]))
        self.band_source = band_source
        self.path = path

    def to_json(self) -> dict[str, Any]:
        return {
            "round": self.round, "file": self.file,
            "metric": self.metric, "value": self.value,
            "unit": self.unit, "band": list(self.band),
            "band_source": self.band_source, "path": self.path,
        }


def _is_num(v: Any) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool) \
        and math.isfinite(v)


def _rep_lists(obj: Any, depth: int = 0) -> dict[str, list[float]]:
    """All `*_reps` numeric lists reachable within a row (shallow)."""
    out: dict[str, list[float]] = {}
    if depth > 3 or not isinstance(obj, dict):
        return out
    for k, v in obj.items():
        if (k.endswith("_reps") and isinstance(v, list) and v
                and all(_is_num(x) for x in v)):
            out[k] = [float(x) for x in v]
        elif isinstance(v, dict):
            out.update(_rep_lists(v, depth + 1))
    return out


def _band_from_row(row: dict[str, Any], value: float
                   ) -> tuple[tuple[float, float], str] | None:
    """The row's own noise band: the `*_reps` list whose envelope
    contains (or whose median equals) the row value — the paired-rep
    A/B protocol's rep vector. None when the row carries no reps."""
    for name, reps in _rep_lists(row).items():
        lo, hi = min(reps), max(reps)
        med = sorted(reps)[len(reps) // 2]
        if lo - 1e-9 <= value <= hi + 1e-9 or \
                math.isclose(med, value, rel_tol=1e-6):
            return (lo, hi), name
    return None


def _walk_rows(obj: Any, path: str, out: list[tuple[str, dict]],
               depth: int = 0) -> None:
    """Collect every dict with a string `metric` + numeric `value`."""
    if depth > 8:
        return
    if isinstance(obj, dict):
        if isinstance(obj.get("metric"), str) and _is_num(obj.get("value")):
            out.append((path, obj))
        for k, v in obj.items():
            _walk_rows(v, f"{path}.{k}" if path else str(k), out,
                       depth + 1)
    elif isinstance(obj, list):
        for i, v in enumerate(obj):
            _walk_rows(v, f"{path}[{i}]", out, depth + 1)


def _walk_headlines(obj: Any, path: str,
                    out: list[tuple[str, str, float]],
                    depth: int = 0) -> None:
    """`sustained_rps_slo`-style headline dicts: {label: number} under
    a known headline key become synthetic `<key>_<label>` entries."""
    if depth > 6 or not isinstance(obj, dict):
        return
    for k, v in obj.items():
        if k == "sustained_rps_slo" and isinstance(v, dict):
            for label, num in v.items():
                if _is_num(num):
                    out.append((f"{path}.{k}" if path else k,
                                f"{k}_{label}", float(num)))
        elif isinstance(v, dict):
            _walk_headlines(v, f"{path}.{k}" if path else str(k), out,
                            depth + 1)


def _numeric_leaves(obj: Any, path: str = "", depth: int = 0
                    ) -> list[tuple[str, float]]:
    """Shallow numeric leaves (the zero-row fallback). Depth-limited so
    protocol-only artifacts still contribute a handful of entries."""
    out: list[tuple[str, float]] = []
    if depth > 2:
        return out
    if isinstance(obj, dict):
        for k, v in obj.items():
            p = f"{path}.{k}" if path else str(k)
            if _is_num(v):
                out.append((p, float(v)))
            elif isinstance(v, dict):
                out.extend(_numeric_leaves(v, p, depth + 1))
    return out


def round_of(path: str) -> int:
    m = None
    for m in ROUND_RE.finditer(os.path.basename(path)):
        pass
    return int(m.group(1)) if m else -1


def extract_file(path: str) -> list[Entry]:
    """Index one artifact. Raises on unparseable JSON (the coverage
    gate counts those); returns >= 1 entry for any parseable dict."""
    with open(path) as fp:
        doc = json.load(fp)
    rnd = round_of(path)
    fname = os.path.relpath(path)
    entries: list[Entry] = []

    rows: list[tuple[str, dict]] = []
    _walk_rows(doc, "", rows)
    for rpath, row in rows:
        value = float(row["value"])
        band = _band_from_row(row, value)
        entries.append(Entry(
            rnd, fname, str(row["metric"]), value,
            unit=str(row.get("unit", "")),
            band=band[0] if band else None,
            band_source=band[1] if band else "default",
            path=rpath,
        ))
        # ISSUE 20: a row stamped with an `attribution` block also
        # indexes its windowed per-segment p99s — a PR that shifts the
        # tail from device_compute into queue_wait now regresses a
        # TRACKED metric even when the headline survives
        att = row.get("attribution")
        seg_p99 = (att.get("seg_p99_ms")
                   if isinstance(att, dict) else None)
        if isinstance(seg_p99, dict):
            for seg, sv in sorted(seg_p99.items()):
                if _is_num(sv):
                    entries.append(Entry(
                        rnd, fname,
                        f"{row['metric']}_seg_{seg}_p99_ms",
                        float(sv), unit="ms",
                        path=f"{rpath}.attribution.seg_p99_ms.{seg}",
                    ))

    heads: list[tuple[str, str, float]] = []
    _walk_headlines(doc, "", heads)
    seen = {e.metric for e in entries}
    for hpath, metric, value in heads:
        if metric not in seen:
            entries.append(Entry(rnd, fname, metric, value,
                                 unit="rps", path=hpath))
            seen.add(metric)

    if not entries and isinstance(doc, dict):
        for lpath, value in _numeric_leaves(doc)[:16]:
            entries.append(Entry(rnd, fname, lpath, value, unit="",
                                 path=lpath))
    return entries


class Ledger:
    """The full index plus coverage accounting."""

    def __init__(self) -> None:
        self.entries: list[Entry] = []
        self.files_ok: list[str] = []
        self.files_failed: list[tuple[str, str]] = []
        self.waivers: dict[str, str] = {}

    @classmethod
    def scan(cls, artifacts_dir: str = "artifacts",
             bench_glob: str = "BENCH_*.json",
             root: str = ".") -> "Ledger":
        led = cls()
        wpath = os.path.join(root, artifacts_dir, WAIVERS_FILE)
        if os.path.exists(wpath):
            with open(wpath) as fp:
                led.waivers = dict(json.load(fp).get("waivers", {}))
        paths = sorted(glob.glob(os.path.join(root, artifacts_dir,
                                              "*.json")))
        paths += sorted(glob.glob(os.path.join(root, bench_glob)))
        paths = [p for p in paths
                 if os.path.basename(p) != WAIVERS_FILE]
        for p in paths:
            try:
                got = led.extend(p)
            except Exception as exc:  # noqa: BLE001 — coverage report
                led.files_failed.append((p, f"{type(exc).__name__}: {exc}"))
                continue
            if not got:
                led.files_failed.append((p, "no entries extracted"))
        return led

    def extend(self, path: str) -> int:
        es = extract_file(path)
        if es:
            self.entries.extend(es)
            self.files_ok.append(path)
        return len(es)

    # -- reads ---------------------------------------------------------

    def families(self) -> dict[str, list[Entry]]:
        """metric -> entries sorted by round (stable within a round)."""
        fams: dict[str, list[Entry]] = {}
        for e in self.entries:
            fams.setdefault(e.metric, []).append(e)
        for es in fams.values():
            es.sort(key=lambda e: e.round)
        return fams

    def verdicts(self) -> list[dict[str, Any]]:
        """Latest-vs-previous-round comparison per multi-round family.
        Outside-the-noise-band means the two bands are disjoint in the
        bad direction."""
        out: list[dict[str, Any]] = []
        for metric, es in sorted(self.families().items()):
            rounds = sorted({e.round for e in es if e.round >= 0})
            if len(rounds) < 2:
                continue
            cur = [e for e in es if e.round == rounds[-1]][-1]
            prev = [e for e in es if e.round == rounds[-2]][-1]
            direction = unit_direction(cur.unit) or \
                unit_direction(prev.unit)
            if direction == 0:
                continue
            if direction > 0:
                regressed = cur.band[1] < prev.band[0]
                improved = cur.band[0] > prev.band[1]
            else:
                regressed = cur.band[0] > prev.band[1]
                improved = cur.band[1] < prev.band[0]
            verdict = ("REGRESSION" if regressed
                       else "IMPROVEMENT" if improved else "STABLE")
            if verdict == "REGRESSION" and metric in self.waivers:
                verdict = "WAIVED"
            out.append({
                "metric": metric, "verdict": verdict,
                "direction": "higher" if direction > 0 else "lower",
                "prev_round": prev.round, "prev_value": prev.value,
                "prev_band": list(prev.band),
                "round": cur.round, "value": cur.value,
                "band": list(cur.band),
                "prev_file": prev.file, "file": cur.file,
                "waived": self.waivers.get(metric),
            })
        return out

    def trend_report(self) -> str:
        lines = ["# Perf ledger trend report",
                 f"files indexed: {len(self.files_ok)}  "
                 f"failed: {len(self.files_failed)}  "
                 f"entries: {len(self.entries)}", ""]
        for p, why in self.files_failed:
            lines.append(f"PARSE FAIL  {p}: {why}")
        if self.files_failed:
            lines.append("")
        fams = self.families()
        multi = {m: es for m, es in fams.items()
                 if len({e.round for e in es if e.round >= 0}) > 1}
        lines.append(f"## Trends ({len(multi)} multi-round metric "
                     f"families of {len(fams)})")
        for metric in sorted(multi):
            es = multi[metric]
            pts = " -> ".join(
                f"r{e.round:02d}:{e.value:g}" for e in es
                if e.round >= 0
            )
            unit = next((e.unit for e in es if e.unit), "")
            lines.append(f"  {metric} [{unit}]: {pts}")
        lines.append("")
        vs = self.verdicts()
        bad = [v for v in vs if v["verdict"] == "REGRESSION"]
        lines.append(f"## Verdicts ({len(vs)} judged, "
                     f"{len(bad)} regressions)")
        for v in vs:
            if v["verdict"] == "STABLE":
                continue
            lines.append(
                f"  {v['verdict']:<11} {v['metric']}: "
                f"r{v['prev_round']:02d} {v['prev_value']:g} "
                f"(band {v['prev_band'][0]:g}..{v['prev_band'][1]:g})"
                f" -> r{v['round']:02d} {v['value']:g} "
                f"(band {v['band'][0]:g}..{v['band'][1]:g})"
                + (f"  [waived: {v['waived']}]" if v.get("waived")
                   else "")
            )
        return "\n".join(lines) + "\n"

    def check_pins(self, pins: list[tuple[str, float, float]]
                   ) -> list[str]:
        """Headline pins: (metric[@rNN], value, abs_tol). A metric
        with an `@rNN` suffix pins that ROUND's entry (the headline
        rows live at their measurement round — r17's 125 rps stays
        pinned even after later rounds re-measure under different
        protocols); without it the latest round is checked. Returns
        failure strings (empty = all pins hold)."""
        fails = []
        fams = self.families()
        for spec, want, tol in pins:
            metric, _, rnd_s = spec.partition("@")
            es = fams.get(metric)
            if not es:
                fails.append(f"pin {spec}: no such metric in index")
                continue
            if rnd_s:
                rnd = int(rnd_s.lstrip("r"))
                es = [e for e in es if e.round == rnd]
                if not es:
                    fails.append(
                        f"pin {spec}: metric {metric} has no "
                        f"round-{rnd} entry")
                    continue
            e = es[-1]
            if abs(e.value - want) > tol:
                fails.append(
                    f"pin {spec}: want {want:g} +-{tol:g}, "
                    f"index has {e.value:g} (r{e.round:02d}, "
                    f"{e.file})"
                )
        return fails


def _parse_pin(s: str) -> tuple[str, float, float]:
    """--pin metric[@rNN]=value[:tol]"""
    name, _, rest = s.partition("=")
    if not rest:
        raise argparse.ArgumentTypeError(
            f"pin {s!r}: expected metric[@rNN]=value[:tol]")
    val, _, tol = rest.partition(":")
    return name, float(val), float(tol) if tol else 1e-6


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m sparksched_tpu.obs.ledger",
        description="Index bench artifacts across rounds, report "
                    "trends, and fail on out-of-band regressions.")
    ap.add_argument("--root", default=".", help="repo root to scan")
    ap.add_argument("--artifacts", default="artifacts",
                    help="artifacts dir (relative to --root)")
    ap.add_argument("--bench-glob", default="BENCH_*.json",
                    help="root-level bench series glob")
    ap.add_argument("--pin", action="append", type=_parse_pin,
                    default=[], metavar="METRIC=VALUE[:TOL]",
                    help="assert a headline row is present at VALUE")
    ap.add_argument("--json", default=None,
                    help="also dump the full index as JSON here")
    ap.add_argument("--no-strict-coverage", action="store_true",
                    help="don't fail on unparseable/empty files")
    ap.add_argument("--no-verdicts", action="store_true",
                    help="report trends only, never rc 4")
    args = ap.parse_args(argv)

    from sparksched_tpu.obs.runlog import emit

    led = Ledger.scan(artifacts_dir=args.artifacts,
                      bench_glob=args.bench_glob, root=args.root)
    report = led.trend_report()
    emit(report.rstrip("\n"))

    if args.json:
        with open(args.json, "w") as fp:
            json.dump({
                "entries": [e.to_json() for e in led.entries],
                "files_ok": led.files_ok,
                "files_failed": led.files_failed,
                "verdicts": led.verdicts(),
            }, fp, indent=1)

    rc = 0
    if led.files_failed and not args.no_strict_coverage:
        emit(f"COVERAGE FAIL: {len(led.files_failed)} file(s) "
             "unindexed")
        rc = 2
    pin_fails = led.check_pins(args.pin)
    for f in pin_fails:
        emit(f"PIN FAIL: {f}")
    if pin_fails:
        rc = rc or 3
    if not args.no_verdicts:
        bad = [v for v in led.verdicts()
               if v["verdict"] == "REGRESSION"]
        for v in bad:
            emit(f"REGRESSION: {v['metric']} r{v['prev_round']:02d} "
                 f"{v['prev_value']:g} -> r{v['round']:02d} "
                 f"{v['value']:g} (outside noise band)")
        if bad:
            rc = rc or 4
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
