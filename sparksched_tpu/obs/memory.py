"""HBM memory observability: per-program byte accounting, tiled-layout
size estimation, and the lane-fit advisor.

Motivation (PERF.md "Round-3 on-chip session 1"): the round-5 flagship
bench died in XLA allocation analysis with a 19.4 GB temp
(`f32[512,154,20,3,8,16]`, a per-lane broadcast of the workload bank's
duration table) that no CPU run could see — XLA:CPU folds the
identity-select away, so tests, benches and calibration were all blind
until the chip window opened. This module makes memory a first-class
observable on three layers:

- **compile-time accounting** (`aot_memory`, `compiled_memory`): AOT
  lower/compile a program and extract `compiled.memory_analysis()`
  (argument / output / temp / generated-code bytes). Backend-true but
  backend-dependent: XLA:CPU folds the broadcast the v5e chokes on, so
  these numbers answer "what did THIS backend allocate", not "is the
  program lane-safe".
- **trace-time estimation** (`jaxpr_memory_estimate`,
  `largest_buffers`, `aval_bytes`): walk a ClosedJaxpr BEFORE backend
  folding and size every intermediate under the TPU tiled-layout model
  (minor dim padded to the 128 lane, second-minor to the 32-byte
  sublane — the 16->128 padding that turned a 2.4 GB table into
  19.4 GB). Backend-independent, so a CPU gate can veto a TPU OOM.
- **the lane-fit advisor** (`lane_fit`): trace `vmap(fn)` at two small
  lane counts, fit a per-buffer linear model bytes(B) = a + b*B, and
  evaluate any candidate lane count against an HBM budget in O(1) —
  the question bench calibration used to answer by crashing. With
  `mesh`, the budget is per DEVICE: candidates stay global lane
  counts, each evaluated at its ceil(lanes/dp) shard width against
  17.2 GB/chip — "max lanes per shard", the multi-chip scale-out's
  memory question. The
  estimate is a *lower bound* (largest single-equation working set +
  arguments + outputs + constants; real peaks add allocator slack), so
  "does not fit" is trustworthy and "fits" means "no single buffer
  blowup" — exactly the failure class the round-5 incident is in.
- **runtime telemetry** (`device_memory_stats`): `bytes_in_use` /
  `peak_bytes_in_use` from the backend allocator, for stamping bench
  rows and trainer iterations (None on backends without allocator
  stats, e.g. CPU — callers must treat the fields as optional).

`TPU_HBM_BUDGET_BYTES` defaults to the v5-lite number in PERF.md
(17.2 GB decimal); override per call for other parts.
"""

from __future__ import annotations

from typing import Any, Callable, Iterator

# the v5-lite HBM the round-5 OOM ran into (PERF.md: 19.4 GB > 17.2 GB)
TPU_HBM_BUDGET_BYTES = int(17.2e9)

# TPU tiled layout: minor dim padded to the 128-wide lane, second-minor
# to the 32-byte sublane (8 rows for 4-byte dtypes, 16 for 2-byte, 32
# for 1-byte) — the padding model behind the 16->128 (8x) inflation of
# the round-5 temp
_TPU_LANE = 128
_TPU_SUBLANE_BYTES = 32


def _ceil_to(n: int, m: int) -> int:
    return -(-n // m) * m


def _itemsize(dtype) -> int:
    import numpy as np

    try:
        return int(np.dtype(dtype).itemsize)
    except TypeError:
        # extended dtypes (typed PRNG keys): size of the uint32 block
        # behind one key ((2,) for threefry, (4,) for rbg)
        ks = getattr(getattr(dtype, "_impl", None), "key_shape", None)
        if ks is None:
            return 0
        n = 4
        for d in ks:
            n *= int(d)
        return n


def aval_bytes(aval: Any, tile_pad: bool = True) -> int:
    """Bytes of one abstract value; `tile_pad` applies the TPU tiled
    layout model (the default — this module exists to predict HBM)."""
    dtype = getattr(aval, "dtype", None)
    if dtype is None:
        return 0
    itemsize = _itemsize(dtype)
    shape = tuple(int(d) for d in getattr(aval, "shape", ()))
    if not shape:
        return itemsize
    if not tile_pad:
        n = 1
        for d in shape:
            n *= d
        return n * itemsize
    padded = list(shape)
    padded[-1] = _ceil_to(padded[-1], _TPU_LANE)
    if len(padded) >= 2:
        padded[-2] = _ceil_to(
            padded[-2], max(1, _TPU_SUBLANE_BYTES // itemsize)
        )
    n = 1
    for d in padded:
        n *= d
    return n * itemsize


def _aval_desc(aval: Any) -> str:
    import numpy as np

    try:
        name = np.dtype(aval.dtype).name
    except TypeError:
        name = str(aval.dtype)
    short = {"float32": "f32", "float64": "f64", "int32": "i32",
             "int64": "i64", "bool": "bool", "bfloat16": "bf16",
             "uint32": "u32", "float16": "f16", "int8": "i8",
             "uint8": "u8"}.get(name, name)
    return f"{short}[{','.join(str(d) for d in aval.shape)}]"


def _iter_eqns(jaxpr) -> Iterator:
    """Every equation including nested sub-jaxprs (cond branches, scan
    bodies, closed calls) — a huge temp inside a scan body is live."""
    for eqn in jaxpr.eqns:
        yield eqn
        for v in eqn.params.values():
            for sub in v if isinstance(v, (list, tuple)) else [v]:
                if hasattr(sub, "jaxpr"):
                    yield from _iter_eqns(sub.jaxpr)
                elif hasattr(sub, "eqns"):
                    yield from _iter_eqns(sub)


def _eqn_working_set(eqn, tile_pad: bool) -> int:
    """Bytes simultaneously live while one equation executes: its unique
    input and output buffers. A lower bound on the program's peak."""
    seen: set[int] = set()
    total = 0
    for v in list(eqn.invars) + list(eqn.outvars):
        aval = getattr(v, "aval", None)
        if aval is None or id(v) in seen:  # skip Literals / dupes
            continue
        seen.add(id(v))
        total += aval_bytes(aval, tile_pad)
    return total


def largest_buffers(closed, k: int = 5, tile_pad: bool = True
                    ) -> list[dict[str, Any]]:
    """Top-K largest intermediate buffers with their producing op — the
    attribution that names the offending table instead of a bare
    six-dim shape. Deduped by (shape, dtype, primitive)."""
    best: dict[tuple, dict[str, Any]] = {}
    for eqn in _iter_eqns(closed.jaxpr):
        for v in eqn.outvars:
            aval = getattr(v, "aval", None)
            if aval is None or not getattr(aval, "shape", ()):
                continue
            key = (tuple(aval.shape), str(aval.dtype),
                   eqn.primitive.name)
            if key in best:
                best[key]["count"] += 1
                continue
            best[key] = {
                "bytes": aval_bytes(aval, tile_pad),
                "shape": _aval_desc(aval),
                "op": eqn.primitive.name,
                "count": 1,
            }
    return sorted(
        best.values(), key=lambda d: d["bytes"], reverse=True
    )[:k]


def jaxpr_memory_estimate(closed, tile_pad: bool = True, top_k: int = 5
                          ) -> dict[str, Any]:
    """Backend-independent byte accounting of one traced program:
    argument/output/constant bytes, the total across intermediate
    buffers (`temp_total_bytes` — the budget-table metric: no liveness
    model, but stable and monotone in program growth), the largest
    single-equation working set, and a peak lower bound."""
    jaxpr = closed.jaxpr
    args = sum(aval_bytes(v.aval, tile_pad) for v in jaxpr.invars)
    outs = sum(aval_bytes(v.aval, tile_pad) for v in jaxpr.outvars)
    consts = sum(aval_bytes(v.aval, tile_pad) for v in jaxpr.constvars)
    temp_total = 0
    max_ws = 0
    for eqn in _iter_eqns(jaxpr):
        temp_total += sum(
            aval_bytes(v.aval, tile_pad) for v in eqn.outvars
        )
        ws = _eqn_working_set(eqn, tile_pad)
        if ws > max_ws:
            max_ws = ws
    return {
        "args_bytes": args,
        "out_bytes": outs,
        "const_bytes": consts,
        "temp_total_bytes": temp_total,
        "max_working_set_bytes": max_ws,
        # resident state + the widest single step: what must fit at once
        "peak_lower_bound_bytes": args + outs + consts + max_ws,
        "largest": largest_buffers(closed, k=top_k, tile_pad=tile_pad),
    }


# ---------------------------------------------------------------------------
# compile-time accounting (backend-true)
# ---------------------------------------------------------------------------

_MEM_ANALYSIS_FIELDS = (
    "argument_size_in_bytes",
    "output_size_in_bytes",
    "temp_size_in_bytes",
    "alias_size_in_bytes",
    "generated_code_size_in_bytes",
)


def compiled_memory(compiled) -> dict[str, int] | None:
    """`compiled.memory_analysis()` as a plain dict (None when the
    backend does not implement it)."""
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return None
    if ma is None:
        return None
    out = {}
    for f in _MEM_ANALYSIS_FIELDS:
        v = getattr(ma, f, None)
        if v is not None:
            out[f] = int(v)
    return out or None


def aot_memory(fn: Callable, *args, **kwargs) -> dict[str, Any] | None:
    """AOT lower + compile `fn` at the argument shapes and return the
    backend's memory analysis (plus which backend produced it). Returns
    None when lowering/compilation fails — callers log, not crash: a
    failed *accounting* compile must never take a bench down."""
    import jax

    try:
        compiled = jax.jit(fn).lower(*args, **kwargs).compile()
    except Exception:
        return None
    mem = compiled_memory(compiled)
    if mem is None:
        return None
    return {"backend": jax.default_backend()} | mem


def device_memory_stats(device=None) -> dict[str, int] | None:
    """Allocator stats (`bytes_in_use`, `peak_bytes_in_use`, ...) for
    one device; None on backends without them (CPU) — runtime memory
    fields are optional everywhere they are stamped."""
    import jax

    try:
        if device is None:
            device = jax.local_devices()[0]
        stats = device.memory_stats()
    except Exception:
        return None
    if not stats:
        return None
    return {k: int(v) for k, v in stats.items()
            if isinstance(v, (int, float))}


# ---------------------------------------------------------------------------
# the lane-fit advisor
# ---------------------------------------------------------------------------


def _batched_struct(tree, b: int):
    import jax

    return jax.tree_util.tree_map(
        lambda l: jax.ShapeDtypeStruct((b,) + tuple(l.shape), l.dtype),
        tree,
    )


def _trace_vmapped(fn: Callable, example_args: tuple, lanes: int):
    import jax

    batched = tuple(_batched_struct(a, lanes) for a in example_args)
    return jax.make_jaxpr(jax.vmap(fn))(*batched)


def _linear_fit(y1: int, y2: int, b1: int, b2: int
                ) -> tuple[float, float]:
    slope = (y2 - y1) / float(b2 - b1)
    return y1 - slope * b1, slope


def _mesh_dp(mesh) -> int:
    """Device count of a `mesh` argument: a Mesh, an int, or None."""
    if mesh is None:
        return 1
    if isinstance(mesh, int):
        return max(1, mesh)
    return max(1, int(getattr(mesh, "size", 1)))


def lane_fit(
    fn: Callable | None = None,
    example_args: tuple | None = None,
    candidates: tuple[int, ...] = (64, 128, 256, 512, 1024),
    budget_bytes: int = TPU_HBM_BUDGET_BYTES,
    tile_pad: bool = True,
    base_lanes: tuple[int, int] = (2, 4),
    traced: dict[int, Any] | None = None,
    tracer: Callable[[int], Any] | None = None,
    mesh=None,
) -> dict[str, Any]:
    """Sweep vmap lane counts against an HBM budget without compiling.

    `fn` is the per-lane program, `example_args` its UNBATCHED abstract
    arguments (ShapeDtypeStructs or arrays). The program is traced at
    the two `base_lanes` counts only; every buffer's bytes are fitted
    as a + b*lanes from the pair (exact for vmap's linear batching),
    then each candidate is evaluated in O(1). `traced` optionally
    provides pre-built `{lanes: ClosedJaxpr}` traces to share with
    other passes; `tracer` (lanes -> ClosedJaxpr) replaces the default
    `vmap(fn)` trace for programs that take the lane axis directly
    (e.g. the single-eval batch collectors).

    `mesh` (a `jax.sharding.Mesh`, or a bare device count) makes the
    budget PER DEVICE: candidates stay GLOBAL lane counts, but each is
    evaluated at its per-shard width ceil(lanes/dp) against
    `budget_bytes` per chip — the lane axis is batch-sharded under the
    dp mesh (parallel.py:lane_sharding), so the buffers that grow with
    lanes live ceil(B/dp) wide on every device while the bank/params
    stay replicated (the `a` intercept of each buffer's linear model).
    `max_lanes_fit` then answers "how many GLOBAL lanes fit this mesh",
    and each candidate row carries `lanes_per_device`.

    Returns `{budget_bytes, base_lanes, max_lanes_fit,
    candidates: [{lanes, est_peak_bytes, fits, top: {...}}]}` —
    `top` names the dominant buffer (shape at that lane count +
    producing op), so an over-budget row reads "select_n
    f32[512,154,20,3,8,16] = 19.4 GB", not a bare number."""
    dp = _mesh_dp(mesh)
    if tracer is None:
        assert fn is not None and example_args is not None
        tracer = lambda b: _trace_vmapped(fn, example_args, b)  # noqa: E731
    b1, b2 = base_lanes
    assert b1 != b2
    traced = dict(traced or {})
    for b in (b1, b2):
        if b not in traced:
            traced[b] = tracer(b)
    jx1, jx2 = traced[b1], traced[b2]

    def _rows(closed):
        rows = []
        for eqn in _iter_eqns(closed.jaxpr):
            rows.append((
                eqn.primitive.name,
                _eqn_working_set(eqn, tile_pad),
                eqn,
            ))
        return rows

    rows1, rows2 = _rows(jx1), _rows(jx2)
    aligned = len(rows1) == len(rows2) and all(
        a[0] == b[0] for a, b in zip(rows1, rows2)
    )
    if not aligned:
        # the two traces disagree structurally (shape-dependent Python
        # control flow in fn): fall back to tracing every candidate
        return _lane_fit_direct(
            tracer, candidates, budget_bytes, tile_pad, dp
        )

    ws_models = [
        _linear_fit(a[1], b[1], b1, b2) for a, b in zip(rows1, rows2)
    ]

    def _sum_model(vars1, vars2):
        y1 = sum(aval_bytes(v.aval, tile_pad) for v in vars1)
        y2 = sum(aval_bytes(v.aval, tile_pad) for v in vars2)
        return _linear_fit(y1, y2, b1, b2)

    arg_m = _sum_model(jx1.jaxpr.invars, jx2.jaxpr.invars)
    out_m = _sum_model(jx1.jaxpr.outvars, jx2.jaxpr.outvars)
    con_m = _sum_model(jx1.jaxpr.constvars, jx2.jaxpr.constvars)

    def _top_desc(i: int, lanes: int) -> dict[str, Any]:
        import numpy as np

        eqn = rows2[i][2]
        best = max(
            (v for v in eqn.outvars if getattr(v, "aval", None)
             is not None),
            key=lambda v: aval_bytes(v.aval, tile_pad),
            default=None,
        )
        if best is None:
            return {"op": eqn.primitive.name}
        shape = list(best.aval.shape)
        if shape and shape[0] == b2:  # lane-batched: show at `lanes`
            shape[0] = lanes
        scaled = jax_shape_struct(tuple(shape), np.dtype(best.aval.dtype))
        return {
            "op": eqn.primitive.name,
            "shape": f"{_aval_desc(best.aval).split('[')[0]}"
                     f"[{','.join(str(d) for d in shape)}]",
            "bytes": aval_bytes(scaled, tile_pad),
        }

    out_rows = []
    max_fit = 0
    for lanes in sorted(candidates):
        # per-device width: the model is linear in the LANE dimension of
        # the traced program, and under a dp mesh each device holds a
        # ceil(lanes/dp)-wide shard of every lane-batched buffer
        shard = -(-lanes // dp)
        fixed = (arg_m[0] + out_m[0] + con_m[0]
                 + (arg_m[1] + out_m[1] + con_m[1]) * shard)
        ws_vals = [a + b * shard for a, b in ws_models]
        i_top = max(range(len(ws_vals)), key=ws_vals.__getitem__)
        est = int(fixed + ws_vals[i_top])
        fits = est <= budget_bytes
        if fits:
            max_fit = max(max_fit, lanes)
        top = _top_desc(i_top, shard)
        top["working_set_bytes"] = int(ws_vals[i_top])
        row = {
            "lanes": lanes,
            "est_peak_bytes": est,
            "fits": fits,
            "top": top,
        }
        if dp > 1:
            row["lanes_per_device"] = shard
        out_rows.append(row)
    out = {
        "budget_bytes": int(budget_bytes),
        "base_lanes": list(base_lanes),
        "max_lanes_fit": max_fit,
        "candidates": out_rows,
    }
    if dp > 1:
        out["dp"] = dp
    return out


def _lane_fit_direct(tracer, candidates, budget_bytes,
                     tile_pad, dp: int = 1) -> dict[str, Any]:
    """Fallback: one trace per candidate (used only when the two-point
    linear model cannot align its traces). Under a dp mesh the trace
    runs at the candidate's per-shard width."""
    out_rows = []
    max_fit = 0
    for lanes in sorted(candidates):
        shard = -(-lanes // dp)
        jx = tracer(shard)
        est = jaxpr_memory_estimate(jx, tile_pad, top_k=1)
        peak = est["peak_lower_bound_bytes"]
        fits = peak <= budget_bytes
        if fits:
            max_fit = max(max_fit, lanes)
        top = dict(est["largest"][0]) if est["largest"] else {}
        row = {
            "lanes": lanes,
            "est_peak_bytes": int(peak),
            "fits": fits,
            "top": top,
        }
        if dp > 1:
            row["lanes_per_device"] = shard
        out_rows.append(row)
    out = {
        "budget_bytes": int(budget_bytes),
        "base_lanes": [],
        "max_lanes_fit": max_fit,
        "candidates": out_rows,
    }
    if dp > 1:
        out["dp"] = dp
    return out


def jax_shape_struct(shape: tuple, dtype):
    import jax

    return jax.ShapeDtypeStruct(shape, dtype)


def hot_set_fit(
    slot_tree,
    candidates: tuple[int, ...] = (64, 128, 256, 512, 1024, 2048),
    budget_bytes: int = TPU_HBM_BUDGET_BYTES,
    fixed_bytes: int = 0,
    tile_pad: bool = True,
    dp: int = 1,
) -> dict[str, Any]:
    """Hot-set capacity model for the paged session store (ISSUE 13) —
    the lane-fit advisor's serving analog.

    `slot_tree` is ONE session's slot as abstract leaves (arrays or
    ShapeDtypeStructs — the `LoopState` one `SessionStore` slot holds).
    The store's HBM cost is linear in the HOT capacity H: the
    [H]-stacked slot store is the only store-sized buffer the donated
    serve programs keep resident, so bytes(H) = fixed + store(H),
    where store(H) is evaluated EXACTLY per candidate (every slot leaf
    sized at leading dim H under the TPU tiled-layout model — no
    fitting, and monotone in H by construction, which the pager test
    pins). `fixed_bytes` carries the replicated constants (the
    workload bank, params) plus whatever working-set allowance the
    caller budgets for the serve program itself.

    With `dp` > 1 (the sharded store), candidates stay GLOBAL hot
    capacities but each is evaluated at its per-device shard width
    ceil(H/dp) against a per-chip budget, mirroring `lane_fit`'s mesh
    mode — the store's leading axis is `P('dp')`-sharded while the
    bank stays replicated (the fixed term).

    Returns `{budget_bytes, fixed_bytes, slot_bytes, max_hot_fit,
    candidates: [{hot, est_bytes, fits[, hot_per_device]}]}` —
    `slot_bytes` is the marginal PER-DEVICE cost of one more GLOBAL
    slot at large H (the est_bytes slope in global H, i.e. already
    divided by dp), so "how many more global sessions fit the
    per-chip budget" is one division away under any mesh."""
    leaves = [
        (tuple(int(d) for d in getattr(a, "shape", ())),
         getattr(a, "dtype", None))
        for a in _tree_leaves(slot_tree)
    ]

    def store_bytes(h: int) -> int:
        return sum(
            aval_bytes(jax_shape_struct((h,) + shape, dtype), tile_pad)
            for shape, dtype in leaves
            if dtype is not None
        )

    dp = max(1, int(dp))
    rows = []
    max_fit = 0
    for h in sorted(int(c) for c in candidates):
        shard = -(-h // dp)
        est = int(fixed_bytes) + store_bytes(shard)
        fits = est <= budget_bytes
        if fits:
            max_fit = max(max_fit, h)
        row = {"hot": h, "est_bytes": est, "fits": fits}
        if dp > 1:
            row["hot_per_device"] = shard
        rows.append(row)
    out = {
        "budget_bytes": int(budget_bytes),
        "fixed_bytes": int(fixed_bytes),
        # marginal bytes of one more GLOBAL slot (the large-H slope
        # of est_bytes, where per-leaf tile padding has amortized) —
        # computed at per-device shard widths so the division against
        # the per-chip budget yields GLOBAL sessions under any dp
        "slot_bytes": (
            store_bytes(-(-2048 // dp)) - store_bytes(-(-1024 // dp))
        ) // 1024,
        "max_hot_fit": max_fit,
        "candidates": rows,
    }
    if dp > 1:
        out["dp"] = dp
    return out


def _tree_leaves(tree):
    import jax

    return jax.tree_util.tree_leaves(tree)


def gb(n: int | float) -> float:
    """Decimal GB, the unit PERF.md and the budget table speak."""
    return round(float(n) / 1e9, 2)


def lane_fit_summary(fit: dict[str, Any]) -> dict[str, Any]:
    """Compact per-row form of a `lane_fit` report — what bench rows
    carry (the full candidate table with buffer attributions lives in
    the analysis report)."""
    worst = fit["candidates"][-1] if fit["candidates"] else {}
    top = worst.get("top", {})
    out = {
        "budget_gb": gb(fit["budget_bytes"]),
        "max_lanes_fit": fit["max_lanes_fit"],
        "candidates": [
            {
                "lanes": c["lanes"], "est_gb": gb(c["est_peak_bytes"]),
                "fits": c["fits"],
            }
            | (
                {"lanes_per_device": c["lanes_per_device"]}
                if "lanes_per_device" in c else {}
            )
            for c in fit["candidates"]
        ],
        "top": {k: top.get(k) for k in ("op", "shape") if k in top},
    }
    if "dp" in fit:
        # per-device budget: est_gb rows above are bytes PER CHIP at
        # each global lane count sharded dp ways
        out["dp"] = fit["dp"]
    return out


def memory_row_stamp(
    lane_fn: Callable | None = None,
    example_args: tuple | None = None,
    candidates: tuple[int, ...] = (512, 1024),
    budget_bytes: int = TPU_HBM_BUDGET_BYTES,
    tracer: Callable[[int], Any] | None = None,
    program: str | None = None,
    mesh=None,
) -> dict[str, Any]:
    """Best-effort `memory` block for a bench row: runtime allocator
    stats (null on backends without them — CPU) plus, when a lane
    program (or `tracer`) is given, the compact lane-fit prediction.
    With `mesh` (or a device count), the prediction is per shard
    against a per-chip budget — what a dp-sharded bench row must stamp
    (global lanes, per-device bytes). Never raises — a failed
    *accounting* step must never take a bench row down; failures land
    as a `lane_fit: {error}` field instead."""
    stats = device_memory_stats() or {}
    out: dict[str, Any] = {
        "mem_peak_bytes": stats.get("peak_bytes_in_use"),
        "mem_bytes_in_use": stats.get("bytes_in_use"),
    }
    if program is not None:
        out["program"] = program
    if lane_fn is not None or tracer is not None:
        try:
            out["lane_fit"] = lane_fit_summary(lane_fit(
                lane_fn, example_args, candidates=candidates,
                budget_bytes=budget_bytes, tracer=tracer, mesh=mesh,
            ))
        except Exception as e:
            out["lane_fit"] = {
                "error": f"{type(e).__name__}: {str(e)[:160]}"
            }
    return out
