"""Role-attributed sampling profiler of the serve/online host threads
(ISSUE 20, tentpole part 2).

The attribution plane's span decomposition (obs/critpath.py) says
WHICH segment of the request path owns the tail; this module says
WHERE IN THE CODE the host side of that segment spends its time. It
is a wall-clock sampling profiler over `sys._current_frames()` that
keys every sample to the PR-19 thread-role model (ownership.py
`ROLE_NAMES`: pump / http handler / harvester / client worker /
learner / collector): the spawn sites already name their threads
after their role, so the role of a sample is a prefix match on the
sampled thread's name — no per-thread registration, and threads that
come and go between samples (client workers, replica pumps) are still
attributed correctly.

Per role it keeps SELF-time counts keyed by the innermost frame's
`basename:function` — the question the tables answer is "what is the
pump thread actually executing when it is on-CPU-or-blocked", which
is what ROADMAP items 1-2 need to rank the host share the pipelined
front exists to hide (a pump that samples 80% in `block_until_ready`
has a device-bound tail; one that samples in `_assemble`/`device_put`
has the host share depth-D dispatch was built for).

Zero-cost-off: a profiler that is never `start()`ed costs nothing —
no thread, no signal handlers, no tracing hooks installed (sampling
is pull-based via `sys._current_frames()`, which only runs when the
sampler thread wakes). Always-on-capable: at the default 67 Hz a
sample is one dict walk over ~10 threads (~30us), <0.3% of one core;
the paired A/B in scripts_obs_demo.py holds the whole attribution
plane (this + critpath) under the 5% overhead bar.

The sampler thread is itself a role ("host-profiler", registered in
ownership.py / analysis.concurrency) so the ownership analyses cover
the profiler's own mutable state: the sample tables are single-owner
(written only by the sampler loop; `tables()` is called after
`stop()` joins, or from the main thread for a live peek — reads of
role-owned state are unchecked by design, see analysis/concurrency).
"""

from __future__ import annotations

import sys
import threading
import time
from typing import Any

from ..ownership import ROLE_NAMES, assert_owner

# role vocabulary of the sample tables: the ownership roles, plus
# buckets for the main thread, router replica pumps, and anything
# unrecognized (interpreter internals, user threads)
PROFILE_ROLES = ("main",) + ROLE_NAMES + ("serve-replica", "other")

_PREFIX_ROLES = tuple(r for r in PROFILE_ROLES
                      if r not in ("main", "other"))


def role_of_thread_name(name: str) -> str:
    """Map a thread name to its profile role (prefix match, same rule
    as ownership._role_of_thread, plus main/other buckets)."""
    if name == "MainThread":
        return "main"
    for r in _PREFIX_ROLES:
        if name == r or name.startswith(r + "-"):
            return r
    return "other"


class HostProfiler:
    """Sampling profiler producing per-role self-time tables.

    `start()` spawns the sampler thread; `stop()` joins it and (when
    a runlog is attached) emits one `hostprof` record carrying the
    tables. `tables()` renders per-role sample counts, wall-share,
    estimated self-ms, and the top-N innermost sites.
    """

    def __init__(self, *, hz: float = 67.0, runlog=None,
                 top_n: int = 6) -> None:
        # 67 Hz, not 100: a divisor-of-nothing rate so sampling does
        # not phase-lock with ms-granular timers (lingers, pollers)
        self.period_s = 1.0 / max(1e-3, float(hz))
        self.runlog = runlog
        self.top_n = max(1, int(top_n))
        # role -> {"basename:func": samples}; sampler-thread-owned
        self._counts: dict[str, dict[str, int]] = {}
        self._samples = 0
        self._elapsed_s = 0.0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._started_at: float | None = None

    # -- lifecycle -----------------------------------------------------

    def start(self) -> "HostProfiler":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._started_at = time.perf_counter()
        self._thread = threading.Thread(
            target=self._loop, name="host-profiler", daemon=True)
        self._thread.start()
        return self

    def stop(self, emit: bool = True) -> dict[str, Any]:
        """Stop sampling, join the sampler, emit the `hostprof`
        runlog record (unless `emit=False`), return the tables.
        Idempotent; a never-started profiler returns empty tables."""
        t = self._thread
        if t is not None:
            self._stop.set()
            t.join(timeout=5.0)
            self._thread = None
            if self._started_at is not None:
                self._elapsed_s += time.perf_counter() - self._started_at
                self._started_at = None
        tables = self.tables()
        if emit and self.runlog is not None and self._samples:
            self.runlog.hostprof(**tables)
        return tables

    @property
    def running(self) -> bool:
        return self._thread is not None

    # -- sampling ------------------------------------------------------

    def _loop(self) -> None:
        me = threading.get_ident()
        while not self._stop.wait(self.period_s):
            self._sample(me)

    def _sample(self, own_ident: int) -> None:
        assert_owner(self, "host-profiler")
        names = {t.ident: t.name for t in threading.enumerate()}
        for ident, frame in sys._current_frames().items():
            if ident == own_ident:
                continue
            role = role_of_thread_name(names.get(ident, "?"))
            code = frame.f_code
            site = (f"{code.co_filename.rsplit('/', 1)[-1]}"
                    f":{code.co_name}")
            table = self._counts.get(role)
            if table is None:
                table = self._counts[role] = {}
            table[site] = table.get(site, 0) + 1
        self._samples += 1

    # -- read ----------------------------------------------------------

    def tables(self) -> dict[str, Any]:
        """Per-role self-time tables. `share` is the role's fraction
        of all thread-samples; `self_ms` estimates wall self-time as
        role_samples * sampling period (per THREAD-sample, so a role
        with two live threads can exceed the elapsed wall)."""
        elapsed = self._elapsed_s
        if self._started_at is not None:
            elapsed += time.perf_counter() - self._started_at
        total = sum(sum(t.values()) for t in self._counts.values())
        roles: dict[str, Any] = {}
        for role in sorted(self._counts,
                           key=lambda r: -sum(self._counts[r].values())):
            table = self._counts[role]
            n = sum(table.values())
            top = sorted(table.items(), key=lambda kv: (-kv[1], kv[0]))
            roles[role] = {
                "samples": n,
                "share": round(n / total, 4) if total else 0.0,
                "self_ms": round(n * self.period_s * 1e3, 3),
                "top": [
                    {"site": site, "samples": c,
                     "share": round(c / n, 4)}
                    for site, c in top[:self.top_n]
                ],
            }
        return {
            "samples": self._samples,
            "hz": round(1.0 / self.period_s, 2),
            "elapsed_s": round(elapsed, 3),
            "roles": roles,
        }
