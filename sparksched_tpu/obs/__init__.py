"""Observability subsystem: on-device telemetry counters, structured
JSONL run logs, and legible device traces.

Three parts (ISSUE 2 tentpole), each usable on its own:

- `telemetry`: a small integer `Telemetry` pytree threaded (optionally)
  through `env/core.py`'s per-decision event loop and
  `env/flat_loop.py`'s micro-step engine — pure i32 adds inside jit,
  summarized on host once per iteration (`summarize`). Counts per-lane
  step types (DECIDE / FULFILL / EVENT), event pops by kind, bulk-pass
  consumption, fulfillments and commitment rounds, and the while-loop
  iteration counts from which the straggler ratio (max/mean over lanes)
  is *measured* rather than inferred from A/B steps/s pairs.
- `runlog`: a JSONL event stream per run under `artifacts/` — timed
  spans, telemetry summaries, per-iteration training stats, and JIT
  recompile events via `jax.monitoring` hooks. The default sink the
  trainer writes to (TensorBoard stays available as a mirror).
- `tracing`: named `annotate(...)` scopes (jax.named_scope +
  jax.profiler.TraceAnnotation) around the GNN eval, the env
  micro-step, the collection scatter and the PPO update, so a captured
  Perfetto trace carries those phase labels.
- `memory`: HBM byte accounting (ISSUE 5 tentpole) — compile-time
  `memory_analysis()` extraction, trace-time buffer sizing under the
  TPU tiled-layout model, the lane-fit advisor (max vmap lanes under
  an HBM budget), and runtime `device_memory_stats()` for stamping
  bench rows and trainer iterations.
- `metrics`: streaming serving metrics (ISSUE 11) — log-bucketed
  mergeable histograms (p50..p999 in O(buckets) memory, so
  million-request open-loop runs never retain samples) and a
  counter/gauge/histogram `MetricsRegistry` with Prometheus-text and
  runlog-JSONL exporters; `tracing` additionally carries the
  per-request `RequestTrace` span clock the serving front stamps
  (submit -> batch_admit -> dispatch -> device_compute ->
  scatter_back -> reply, the runlog `trace` record kind).
- `fleet` / `slo` / `ledger`: the fleet observability plane
  (ISSUE 17) — per-replica labeled scrape collector + windowed
  scoreboard (`FleetCollector`, the `/fleet` endpoint and
  `python -m sparksched_tpu.obs.fleet` CLI), declarative SLOs under
  multi-window burn-rate alerting with optional ParamBus rollback
  (`SLOMonitor`, the `alert` record kind) plus the online-loop depth
  probe (`OnlineLoopProbe`), and the cross-round perf-regression
  ledger over `artifacts/*.json` + `BENCH_*.json`
  (`python -m sparksched_tpu.obs.ledger`, the tier-1 gate).
"""

from .memory import device_memory_stats, lane_fit  # noqa: F401
from .metrics import (  # noqa: F401
    MetricsRegistry,
    StreamingHistogram,
    hist_summary,
    percentile_block,
)
from .runlog import RunLog, emit  # noqa: F401
from .slo import (  # noqa: F401
    OnlineLoopProbe,
    SLOMonitor,
    SLOSpec,
    slo_from_config,
)
from .telemetry import Telemetry, summarize, telemetry_zeros  # noqa: F401
from .tracing import RequestTrace, annotate  # noqa: F401

# PEP 562 lazy imports for the submodules that double as CLIs
# (`python -m sparksched_tpu.obs.{fleet,ledger}`) or that only the
# serving/attribution path needs: importing them eagerly here put the
# module object in sys.modules before runpy re-imported it, tripping
# the "found in sys.modules after import of package" RuntimeWarning
# (ISSUE 20 satellite). Consumers import these symbols or the
# submodules directly; both resolve identically through __getattr__.
_LAZY = {
    "FleetCollector": ("fleet", "FleetCollector"),
    "labeled_prometheus": ("fleet", "labeled_prometheus"),
    "Ledger": ("ledger", "Ledger"),
    "CritPathAnalyzer": ("critpath", "CritPathAnalyzer"),
    "SegmentProfile": ("critpath", "SegmentProfile"),
    "decompose": ("critpath", "decompose"),
    "HostProfiler": ("hostprof", "HostProfiler"),
}


def __getattr__(name: str):
    try:
        mod_name, attr = _LAZY[name]
    except KeyError:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}"
        ) from None
    import importlib

    return getattr(
        importlib.import_module(f".{mod_name}", __name__), attr
    )


def __dir__():
    return sorted(set(globals()) | set(_LAZY))
