"""Structured JSONL run log.

One file per run under `artifacts/`, one JSON object per line — the
machine-readable replacement for the trainer's ad-hoc stdout lines and
the print-based profiler reports. Every record carries `ev` (the event
kind) and `t` (unix seconds); the kinds the trainer/bench write:

- `run_start` / `run_end`: run metadata (config summary, totals)
- `span`: a timed host-side phase (`name`, `secs`, e.g. per-iteration
  collect/update)
- `scalars`: per-iteration training stats (the TensorBoard mirror —
  identical keys/values to what `add_scalar` receives)
- `telemetry`: an engine-telemetry summary (`obs.telemetry.summarize`)
- `memory`: a device-memory sample (`obs.memory.device_memory_stats`
  fields — `bytes_in_use` / `peak_bytes_in_use` — plus the optional
  `iteration`/`phase` the sample brackets)
- `latency`: a decision-latency sample from the serving path
  (ISSUE 10) — the measured percentile block (`p50_ms` / `p90_ms` /
  `p99_ms` / `mean_ms`), the `batch` width and `reps` behind it, and
  cold-start fields; `sparksched_tpu/serve/` sessions additionally
  write per-iteration `serve_*` scalars through the standard
  `scalars` record (TensorBoard-mirrored like the trainer's)
- `trace`: one served request's Dapper-style span walk (ISSUE 11) —
  the `trace_id` minted at `Ticket` creation plus per-phase offsets
  in ms from submit (`submit` -> `batch_admit` -> `dispatch` ->
  `device_compute` -> `scatter_back` -> `reply`) and `total_ms`;
  written by the instrumented `MicroBatcher`, off by default
- `metrics`: a `MetricsRegistry` snapshot (obs/metrics.py) — the
  JSONL half of the exporter pair (counters / gauges / streaming-
  histogram summaries nested under `snapshot`); the Prometheus text
  form is `MetricsRegistry.to_prometheus`
- `health`: a tripped in-JIT health sentinel (ISSUE 9) — the raw i32
  violation bitmask (`mask`), its decoded `bits` (env/health.py bit
  table), the `iteration`/`attempt` it quarantines, and the recovery
  `action` taken (rollback_retry | quarantine | gave_up)
- `recovery`: a recovery-policy outcome — rollback+retry with its
  backoff, a checkpoint fallback past a corrupt generation, or a
  gave-up marker; `chaos` records mark deliberate fault injections
  (sparksched_tpu/chaos.py) so drills are self-describing
- `params_swap`: a hot parameter swap into live serving (ISSUE 14) —
  the new `version`, the `prev_version` it replaced, the `action`
  (swap | rollback) and an optional origin/reason; written by
  `SessionStore.set_params`/`rollback_params` so every served
  decision's staleness stamp (`params_version` on `trace` records)
  can be aligned with the swap history
- `jit_compile` / `jit_compile_detail`: JIT (re)compilation events via
  `jax.monitoring` duration hooks plus the dispatch logger (the latter
  names WHICH function was traced/compiled)
- `fleet`: a fleet-collector scoreboard snapshot (ISSUE 17) — per-
  replica windowed rps/p99/occupancy/page-churn/quarantine-rate/
  params-version(+lag) rows and the fleet-aggregate window, written
  periodically by `obs.fleet.FleetCollector`
- `alert`: an SLO burn-rate breach (ISSUE 17) — the spec name, both
  window burn rates, the rule that fired, and the action taken
  (`none` | `rollback`); written by `obs.slo.SLOMonitor`
- `phase_rank`: a ranked on-device phase split (`scripts_phase_rank.py`
  as data — per-phase device-time shares per bench row)

Crash-safety: every record is flushed at write time, and open runlogs
are closed (a final `run_end` with a `teardown` reason) from an
`atexit` hook and — when the process had no handler of its own — a
chained SIGTERM handler, so a watcher-timeout-killed run keeps its
partial telemetry instead of losing the tail.

Rotation (ISSUE 11): `max_bytes` caps the active file — a write that
pushes past it renames the file to `<path>.<n>` (numbered suffix,
monotone across process restarts) and reopens `<path>` fresh with a
`rotate` continuation record, so a million-request open-loop run can
never grow one unbounded JSONL. Rotated segments are complete (every
record was flushed when written) and the crash-safety guarantees are
unchanged: teardown stamps `run_end` into the ACTIVE file and never
rotates (the signal path must not rename/reopen mid-kill).

Readers: `PERF.md` "Reading a run" documents the schema; a runlog is
greppable (`grep '"ev": "telemetry"' run.jsonl | tail -1`) and loads
with one `json.loads` per line.
"""

from __future__ import annotations

import atexit
import json
import logging
import os
import os.path as osp
import signal
import sys
import threading
import time
import weakref
from typing import Any

# sanctioned console sink: the lint tier forbids bare `print(` inside
# sparksched_tpu/ outside renderer.py, so host-loop progress lines go
# through here (stdout, line-flushed — same observable behavior as the
# print(..., flush=True) calls this replaces)


def emit(msg: str) -> None:
    sys.stdout.write(msg + "\n")
    sys.stdout.flush()


_CREATE_COUNTER = 0


def _json_safe(v: Any) -> Any:
    """Best-effort scalarization: numpy/jax scalars -> python numbers,
    everything non-serializable -> str."""
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    if isinstance(v, dict):
        return {str(k): _json_safe(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_json_safe(x) for x in v]
    try:
        import numpy as np

        if isinstance(v, np.ndarray) and v.ndim == 0:
            v = v.item()
        if isinstance(v, (np.integer,)):
            return int(v)
        if isinstance(v, (np.floating,)):
            return float(v)
    except Exception:
        pass
    if hasattr(v, "item"):
        try:
            return _json_safe(v.item())
        except Exception:
            pass
    return str(v)


class RunLog:
    """Append-only JSONL writer (thread-safe; the JIT hooks fire from
    whatever thread compiles)."""

    def __init__(self, path: str, echo: bool = False,
                 max_bytes: int | None = None) -> None:
        os.makedirs(osp.dirname(osp.abspath(path)), exist_ok=True)
        self.path = path
        self.echo = echo
        self.max_bytes = int(max_bytes) if max_bytes else None
        self._lock = threading.Lock()
        self._fp = open(path, "a")
        self._closed = False
        # resume rotation numbering past any suffixes already on disk
        # (RunLog appends; clobbering an earlier run's `.1` would break
        # the "rotated segments are complete" promise)
        self._rotations = 0
        if self.max_bytes:
            import glob as _glob

            # escape the path itself: a user-supplied runlog path with
            # glob metachars must not silently restart numbering at 0
            # (os.replace would then clobber an earlier run's segments)
            for p in _glob.glob(_glob.escape(path) + ".*"):
                tail = p[len(path) + 1:]
                if tail.isdigit():
                    self._rotations = max(self._rotations, int(tail))
        _OPEN_RUNLOGS.add(self)
        _install_teardown_hooks()

    @classmethod
    def create(cls, artifacts_dir: str, name: str | None = None,
               echo: bool = False,
               max_bytes: int | None = None) -> "RunLog":
        """Open `artifacts_dir/runlog/<name>.jsonl`. The default name
        carries pid + a process-local counter on top of the timestamp
        so two runs started within the same second (back-to-back tests,
        quick A/B scripts) never interleave into one file — RunLog
        appends, and the schema promises one run per file."""
        if name is None:
            global _CREATE_COUNTER
            _CREATE_COUNTER += 1
            name = (
                f"run-{int(time.time())}-{os.getpid()}-{_CREATE_COUNTER}"
            )
        return cls(
            osp.join(artifacts_dir, "runlog", f"{name}.jsonl"),
            echo=echo, max_bytes=max_bytes,
        )

    # -- record writers ----------------------------------------------------

    def write(self, ev: str, **fields: Any) -> None:
        # double-checked fast path: a racy True is re-verified under
        # the lock below; a racy False only skips a record on a log
        # that is closing anyway
        if self._closed:  # analysis: allow(concurrency-unlocked-shared)
            return
        rec = {"ev": ev, "t": round(time.time(), 3)}
        rec.update({k: _json_safe(v) for k, v in fields.items()})
        line = json.dumps(rec)
        with self._lock:
            if self._closed:
                return
            self._fp.write(line + "\n")
            self._fp.flush()
            # run_end must stay the active file's last record (the
            # schema promise readers and the crash-safety tests pin),
            # so the closing write never triggers a rotation
            if (self.max_bytes and ev != "run_end"
                    and self._fp.tell() >= self.max_bytes):
                self._rotate_locked()
        if self.echo:
            emit(line)

    def _rotate_locked(self) -> None:
        """Size-cap rotation (caller holds the lock): rename the full
        active file to `<path>.<n>` and reopen `<path>` with a
        `rotate` continuation record. Best-effort — a failed rename
        (read-only fs mid-run) keeps appending to the active file
        rather than losing records."""
        try:
            self._fp.close()
            self._rotations += 1
            os.replace(self.path, f"{self.path}.{self._rotations}")
            self._fp = open(self.path, "a")
            cont = {"ev": "rotate", "t": round(time.time(), 3),
                    "segment": self._rotations,
                    "prev": f"{self.path}.{self._rotations}"}
            self._fp.write(json.dumps(cont) + "\n")
            self._fp.flush()
        except OSError:
            self._fp = open(self.path, "a")

    def span(self, name: str, **fields: Any) -> "_Span":
        """Context manager timing a block; writes one `span` record with
        `secs` on exit (exception-safe — the record is written either
        way, with `error` set when the block raised)."""
        return _Span(self, name, fields)

    def span_event(self, name: str, secs: float, **fields: Any) -> None:
        """A span measured elsewhere (e.g. by `trainers.Profiler`)."""
        self.write("span", name=name, secs=round(float(secs), 4), **fields)

    def scalars(self, iteration: int, stats: dict[str, Any]) -> None:
        self.write("scalars", iteration=int(iteration), **stats)

    def telemetry(self, summary: dict[str, Any],
                  iteration: int | None = None, **fields: Any) -> None:
        if iteration is not None:
            fields["iteration"] = int(iteration)
        self.write("telemetry", summary=summary, **fields)

    def health(self, mask: int, iteration: int | None = None,
               **fields: Any) -> None:
        """A tripped health sentinel (ISSUE 9): the raw violation
        bitmask plus its decoded bit names (env/health.py bit table),
        so `grep '"ev": "health"'` reads without the table. The
        trainer adds `attempt` and the recovery `action` taken;
        recovery outcomes themselves land as `recovery` records."""
        from ..env.health import describe_mask  # host-side, no cycle

        if iteration is not None:
            fields["iteration"] = int(iteration)
        self.write(
            "health", mask=int(mask), bits=describe_mask(mask), **fields
        )

    def latency(self, stats: dict[str, Any],
                iteration: int | None = None, phase: str | None = None,
                **fields: Any) -> None:
        """A decision-latency sample (ISSUE 10 serving path): the
        percentile block the latency bench measures (`p50_ms` /
        `p90_ms` / `p99_ms` / `mean_ms`, plus `batch`, `reps`,
        cold-start fields). Keys land top-level so runlogs stay
        greppable (`grep '"ev": "latency"'`), like `memory` records."""
        if iteration is not None:
            fields["iteration"] = int(iteration)
        if phase is not None:
            fields["phase"] = phase
        self.write("latency", **(dict(stats or {}) | fields))

    def trace(self, trace_id: str, spans_ms: dict[str, float],
              **fields: Any) -> None:
        """One served request's span walk (ISSUE 11): `spans_ms` maps
        phase name -> offset in ms from submit (obs/tracing.py:
        `RequestTrace.offsets_ms`); `total_ms` is stamped from the
        `reply` offset so a grep can read tail latency without
        arithmetic."""
        total = spans_ms.get("reply")
        self.write(
            "trace", trace_id=trace_id,
            spans={k: round(float(v), 4) for k, v in spans_ms.items()},
            total_ms=None if total is None else round(float(total), 4),
            **fields,
        )

    def params_swap(self, version: int, prev_version: int,
                    action: str = "swap",
                    reason: str | None = None,
                    **fields: Any) -> None:
        """One hot parameter swap into live serving (ISSUE 14):
        versioned so staleness stamps on `trace` records and the
        trajectory buffer resolve against the swap history. `action`
        is `swap` (a learner publish) or `rollback` (the
        quarantine-style revert to the last-good version)."""
        if reason is not None:
            fields["reason"] = reason
        self.write(
            "params_swap", version=int(version),
            prev_version=int(prev_version), action=action, **fields,
        )

    def metrics(self, snapshot: dict[str, Any],
                iteration: int | None = None, **fields: Any) -> None:
        """A `MetricsRegistry.snapshot()` (obs/metrics.py) — the JSONL
        exporter: counters/gauges/histogram summaries nested under
        `snapshot` (one record per export, like `telemetry`)."""
        if iteration is not None:
            fields["iteration"] = int(iteration)
        self.write("metrics", snapshot=snapshot, **fields)

    def memory(self, stats: dict[str, Any],
               iteration: int | None = None, phase: str | None = None,
               **fields: Any) -> None:
        """A device-memory sample (`obs.memory.device_memory_stats`
        output); the allocator's keys land top-level so runlogs stay
        greppable (`grep '"ev": "memory"'`)."""
        if iteration is not None:
            fields["iteration"] = int(iteration)
        if phase is not None:
            fields["phase"] = phase
        self.write("memory", **(dict(stats or {}) | fields))

    def fleet(self, **status: Any) -> None:
        """One fleet-collector scoreboard snapshot (ISSUE 17): the
        per-replica rows (rps/p99/occupancy/page churn/quarantine
        rate/params version+lag) plus the fleet-aggregate window, as
        `obs.fleet.FleetCollector.scrape` computed them. Periodic —
        one record every `log_every` scrapes."""
        self.write("fleet", **status)

    def alert(self, slo: str, **fields: Any) -> None:
        """An SLO burn-rate alert (ISSUE 17): the spec that breached
        (`slo`), both window burn rates (`burn_long`/`burn_short`),
        the rule's windows/factor, and the `action` taken (`none` or
        `rollback` via the ParamBus/store facade). Written by
        `obs.slo.SLOMonitor` at fire time, rate-limited by its
        per-spec cooldown."""
        self.write("alert", slo=slo, **fields)

    def tail_exemplar(self, trace_id: str | None, wall_ms: float,
                      segments: dict[str, float],
                      **fields: Any) -> None:
        """One of the slowest-N requests of an attribution window
        (ISSUE 20): the critical-path segment decomposition of a
        concrete tail request (`segments` sums to `wall_ms` exactly —
        obs/critpath.py `decompose`), plus its tenant/replica/error
        and its `rank` within the window (0 = slowest). Emitted by
        `CritPathAnalyzer.flush_window`, so a p99 incident ships
        traces, not just a number."""
        self.write(
            "tail_exemplar", trace_id=trace_id,
            wall_ms=round(float(wall_ms), 4),
            segments={k: round(float(v), 4)
                      for k, v in segments.items()},
            **fields,
        )

    def hostprof(self, **tables: Any) -> None:
        """One role-attributed host-profile dump (ISSUE 20): the
        per-role self-time tables from `obs.hostprof.HostProfiler`
        (samples, share, estimated self-ms, top innermost sites per
        role). Written once at profiler `stop()`."""
        self.write("hostprof", **tables)

    def phase_rank(self, rows: list[dict[str, Any]],
                   source: str | None = None, **fields: Any) -> None:
        """A ranked on-device phase split (ISSUE 17 satellite): the
        `scripts_phase_rank.py` table as data — per-phase share of
        device time for each telemetry-stamped bench row — so chip-
        session phase splits land in the same stream the ledger and
        the fleet CLI read."""
        if source is not None:
            fields["source"] = source
        self.write("phase_rank", rows=rows, **fields)

    # -- JIT recompile hooks ----------------------------------------------

    def install_jit_hooks(self) -> None:
        """Record JIT (re)compilations into this runlog — see
        `_install_global_jit_listener`. Idempotent per process; multiple
        runlogs each receive the events while open."""
        _install_global_jit_listener()
        _ACTIVE_RUNLOGS.add(self)

    def close(self, **fields: Any) -> None:
        # double-checked fast path (idempotent close): the
        # authoritative check is write()'s locked re-test
        if self._closed:  # analysis: allow(concurrency-unlocked-shared)
            return
        self.write("run_end", **fields)
        with self._lock:
            self._closed = True
            self._fp.close()
        _ACTIVE_RUNLOGS.discard(self)
        _OPEN_RUNLOGS.discard(self)

    def _teardown(self, reason: str) -> None:
        """Signal-context close: never blocks on the writer lock. A
        SIGTERM handler runs on the main thread at the next bytecode
        boundary — possibly INSIDE a write() still holding the
        (non-reentrant) lock, mid-line; blocking would deadlock the
        process, and writing anyway would interleave into a corrupt
        line. If the lock is free, stamp run_end and close; otherwise
        leave the file exactly as the per-write flushes left it (every
        completed line already on disk, still parseable)."""
        if self._closed or not self._lock.acquire(blocking=False):
            return
        try:
            if self._closed:
                return
            try:
                rec = {"ev": "run_end", "t": round(time.time(), 3),
                       "teardown": reason}
                self._fp.write(json.dumps(rec) + "\n")
                self._fp.flush()
            finally:
                self._closed = True
                self._fp.close()
        finally:
            self._lock.release()
        _ACTIVE_RUNLOGS.discard(self)
        _OPEN_RUNLOGS.discard(self)

    def __enter__(self) -> "RunLog":
        return self

    def __exit__(self, exc_type, exc_val, exc_tb) -> None:
        self.close()


class _Span:
    def __init__(self, log: RunLog, name: str, fields: dict) -> None:
        self._log = log
        self._name = name
        self._fields = fields
        self.elapsed = 0.0

    def __enter__(self) -> "_Span":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc_val, exc_tb) -> None:
        self.elapsed = time.perf_counter() - self._t0
        fields = dict(self._fields)
        if exc_type is not None:
            fields["error"] = exc_type.__name__
        self._log.span_event(self._name, self.elapsed, **fields)


# ---------------------------------------------------------------------------
# crash-safe teardown
#
# Watcher-killed runs (`timeout -k`, chip-window handovers) must keep
# their partial telemetry. Records are already flushed per write, so
# even SIGKILL loses at most nothing; the hooks below additionally
# stamp a final `run_end` (with a `teardown` reason) on the exits a
# process can still observe: interpreter shutdown (`atexit` — covers
# normal exit, sys.exit and uncaught exceptions) and SIGTERM. The
# SIGTERM handler is installed only when the process has none of its
# own (SIG_DFL), runs only in the main thread, and re-raises the
# default disposition afterwards so exit-status semantics (rc 143 /
# `timeout` accounting) are unchanged.
# ---------------------------------------------------------------------------

_OPEN_RUNLOGS: "weakref.WeakSet[RunLog]" = weakref.WeakSet()
_ATEXIT_INSTALLED = False
_SIGTERM_INSTALLED = False


def _close_open_runlogs(reason: str, from_signal: bool = False) -> None:
    for rl in list(_OPEN_RUNLOGS):
        try:
            if from_signal:
                rl._teardown(reason)  # must not block on the lock
            else:
                rl.close(teardown=reason)
        except Exception:
            pass  # teardown must never mask the original exit


def _install_teardown_hooks() -> None:
    global _ATEXIT_INSTALLED, _SIGTERM_INSTALLED
    if not _ATEXIT_INSTALLED:
        _ATEXIT_INSTALLED = True
        atexit.register(_close_open_runlogs, "atexit")
    if _SIGTERM_INSTALLED:
        return
    if threading.current_thread() is not threading.main_thread():
        # signal.signal is main-thread-only; leave the flag unset so a
        # later RunLog created on the main thread still installs it
        return
    try:
        prev = signal.getsignal(signal.SIGTERM)
    except (ValueError, OSError):
        return
    if prev is not signal.SIG_DFL:
        # the app owns SIGTERM (or a non-Python handler is active);
        # atexit still covers clean exits — stop probing
        _SIGTERM_INSTALLED = True
        return

    def _on_sigterm(signum, frame):
        # restore the default disposition FIRST: if teardown ever
        # wedges, a second SIGTERM must still kill the process
        signal.signal(signum, signal.SIG_DFL)
        _close_open_runlogs("sigterm", from_signal=True)
        os.kill(os.getpid(), signum)

    try:
        signal.signal(signal.SIGTERM, _on_sigterm)
        _SIGTERM_INSTALLED = True
    except (ValueError, OSError):
        pass


# ---------------------------------------------------------------------------
# process-global JIT compile listener
#
# jax.monitoring listeners cannot be individually unregistered, so ONE
# listener is installed per process and fans out to the currently-open
# runlogs (a WeakSet: a garbage-collected runlog stops receiving without
# explicit teardown). The duration events name the compile PHASE
# (/jax/core/compile/...) but not the function; the dispatch logger's
# "Finished tracing + transforming <fun> ..." lines carry the name, so a
# DEBUG handler on that logger records WHICH function recompiled.
# ---------------------------------------------------------------------------

_ACTIVE_RUNLOGS: "weakref.WeakSet[RunLog]" = weakref.WeakSet()
_HOOKS_INSTALLED = False
# compiles shorter than this are not recorded: the hundreds of trivial
# broadcast/convert compiles at process start would bloat every runlog,
# while any recompile worth investigating (a shape leak, a cache miss
# mid-run) is orders of magnitude above it
JIT_MIN_SECS = float(os.environ.get("RUNLOG_JIT_MIN_SECS", "0.05"))


def _fanout(ev: str, **fields: Any) -> None:
    for rl in list(_ACTIVE_RUNLOGS):
        try:
            rl.write(ev, **fields)
        except Exception:
            pass  # a closed/broken sink must never break compilation


class _DispatchLogHandler(logging.Handler):
    def emit(self, record: logging.LogRecord) -> None:  # noqa: A003
        try:
            msg = record.getMessage()
        except Exception:
            return
        # "Finished XLA compilation of <fun> in <secs> sec" — the only
        # record that names WHICH function compiled; tracing/MLIR lines
        # are redundant with the duration events
        if not msg.startswith("Finished XLA compilation"):
            return
        try:
            secs = float(msg.rsplit(" in ", 1)[1].split()[0])
        except (IndexError, ValueError):
            secs = None
        if secs is not None and secs < JIT_MIN_SECS:
            return
        _fanout("jit_compile_detail", msg=msg, secs=secs)


def _install_global_jit_listener() -> None:
    global _HOOKS_INSTALLED
    if _HOOKS_INSTALLED:
        return
    import jax

    def _on_duration(event: str, duration: float, **kw: Any) -> None:
        if "compile" in event and float(duration) >= JIT_MIN_SECS:
            _fanout("jit_compile", event=event,
                    secs=round(float(duration), 4),
                    **{k: _json_safe(v) for k, v in kw.items()})

    jax.monitoring.record_event_duration_secs  # attr check before hook
    jax.monitoring.register_event_duration_secs_listener(_on_duration)

    # jax's per-compile "Finished ..." lines (the only place the
    # compiled FUNCTION is named) log at DEBUG; lowering the logger to
    # DEBUG would also spill every line to a basicConfig'd root logger,
    # so propagation is cut and records at the logger's previous
    # effective level (warnings) are re-emitted to root by hand.
    logger = logging.getLogger("jax._src.dispatch")
    prev_effective = logger.getEffectiveLevel()

    class _Forward(logging.Handler):
        def emit(self, record: logging.LogRecord) -> None:  # noqa: A003
            if record.levelno >= max(prev_effective, logging.WARNING):
                logging.getLogger().handle(record)

    logger.addHandler(_DispatchLogHandler(level=logging.DEBUG))
    logger.addHandler(_Forward(level=logging.DEBUG))
    logger.setLevel(logging.DEBUG)
    logger.propagate = False
    _HOOKS_INSTALLED = True
