"""Declarative SLOs with multi-window burn-rate alerting, plus the
online-loop depth probe (ISSUE 17, tentpole part 2).

**SLO monitor.** An SLO is a budgeted objective over a window (the SRE
formulation): "at most 1% of requests over 200 ms", "quarantine rate
under 5%". The naive threshold alert (p99 > bound RIGHT NOW) pages on
one bad scrape and misses slow budget bleed; the standard fix is
MULTI-WINDOW BURN RATES: burn = (bad fraction in window) / budget, and
a rule fires only when BOTH a long window and a short companion window
burn above a factor — the long window proves the budget is really
going, the short window proves it is still going (so recovered
incidents stop alerting). `DEFAULT_WINDOWS` is the classic two-rule
ladder: a fast-burn rule (60 s long / 15 s short at 2x) and a
slow-burn rule (300 s / 60 s at 1x).

Specs are declarative (`slo_from_config` reads the `serve:`/`obs:`
YAML block) over four kinds, each measured from the fleet collector's
per-scrape window (`obs/fleet.py` computes the window, this module
judges it):

- `latency`  — fraction of requests over `bound` ms vs `budget`
  (default 0.01, i.e. a p99 objective), counted from the windowed
  `StreamingHistogram` delta (`count_above`);
- `ratio`    — bad/total events vs `budget` == bound (quarantine
  rate: burn = rate / max_rate);
- `floor`    — scalar must stay >= bound (goodput floor); binary
  violation per scrape, `budget` = 0.5 (half the window may violate
  before a 1x burn), and scrapes with zero decisions carry no signal
  (an idle service is not a broken one);
- `ceiling`  — scalar must stay <= bound (params-staleness lag),
  binary like `floor`.

Alerts are `alert` runlog records. A spec named in `rollback_on` also
drives the ParamBus/SessionStore rollback facade (`rollback_params`) —
the PR-14 probation machinery, now triggerable by ANY burn-rate breach
rather than only the post-swap window. Per-spec cooldown stops a
sustained breach from re-firing every scrape.

**Online-loop depth probe.** `OnlineLoopProbe` wraps the store's
collector protocol (`add`/`on_close`, the `TrajectoryBuffer` seat) and
forwards everything to the inner collector while distilling the
online loop's health: per-decision param-lag (staleness) histogram,
swap-to-first-decision latency (how long after a `ParamBus` swap the
first decision under the new version lands — wire `bus.on_event =
probe.on_bus_event`), and per-version reward scalars (the learner's
reward trend, keyed by the params version that earned it).
"""

from __future__ import annotations

import time
from typing import Any, Callable

from .metrics import StreamingHistogram

# (long_s, short_s, factor): fire when burn(long) >= factor AND
# burn(short) >= factor. Fast-burn page + slow-burn ticket ladder.
DEFAULT_WINDOWS: tuple[tuple[float, float, float], ...] = (
    (60.0, 15.0, 2.0),
    (300.0, 60.0, 1.0),
)

_KINDS = ("latency", "ratio", "floor", "ceiling")

# the declarative config surface: `serve: {slo: {...}}` / `obs:` keys
SLO_CONFIG_KEYS = frozenset({
    "p99_ms", "p99_budget", "goodput_floor_rps", "quarantine_rate_max",
    "max_staleness", "windows", "rollback_on", "cooldown_s",
    "min_events",
})


class SLOSpec:
    """One budgeted objective. `measure(window)` extracts this spec's
    (bad, total) event increment from a collector scrape window."""

    __slots__ = ("name", "kind", "bound", "budget")

    def __init__(self, name: str, kind: str, bound: float,
                 budget: float | None = None) -> None:
        if kind not in _KINDS:
            raise ValueError(f"slo kind {kind!r} not in {_KINDS}")
        self.name = name
        self.kind = kind
        self.bound = float(bound)
        if budget is None:
            budget = (0.01 if kind == "latency"
                      else self.bound if kind == "ratio" else 0.5)
        if not 0 < budget <= 1:
            raise ValueError(
                f"slo {name}: budget must be in (0, 1], got {budget}")
        self.budget = float(budget)

    def measure(self, window: dict[str, Any]) -> tuple[float, float]:
        """(bad, total) events this window contributes. (0, 0) means
        no signal (idle window) — it dilutes nothing."""
        if self.kind == "latency":
            h: StreamingHistogram | None = window.get("latency_hist")
            if h is None or h.count == 0:
                return 0.0, 0.0
            return float(h.count_above(self.bound)), float(h.count)
        if self.kind == "ratio":
            total = float(window.get("decisions", 0))
            if total <= 0:
                return 0.0, 0.0
            return float(window.get("quarantines", 0)), total
        if self.kind == "floor":
            if float(window.get("decisions", 0)) <= 0:
                return 0.0, 0.0
            v = float(window.get("goodput_rps", 0.0))
            return (1.0 if v < self.bound else 0.0), 1.0
        # ceiling
        v = window.get("params_lag_max")
        if v is None:
            return 0.0, 0.0
        return (1.0 if float(v) > self.bound else 0.0), 1.0

    def describe(self) -> dict[str, Any]:
        return {"name": self.name, "kind": self.kind,
                "bound": self.bound, "budget": self.budget}


class SLOMonitor:
    """Burn-rate evaluation over the specs' event series. The fleet
    collector calls `ingest(window, now)` once per scrape; alerts come
    back (and land in the runlog / the rollback facade) from the same
    call — one thread, no locks, the serving-side discipline."""

    def __init__(
        self,
        specs: list[SLOSpec],
        *,
        windows: tuple[tuple[float, float, float], ...] = DEFAULT_WINDOWS,
        runlog=None,
        rollback=None,
        rollback_on: tuple[str, ...] = (),
        cooldown_s: float = 30.0,
        min_events: int = 1,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        if not specs:
            raise ValueError("SLOMonitor needs at least one SLOSpec")
        names = [s.name for s in specs]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate slo spec names: {names}")
        unknown = set(rollback_on) - set(names)
        if unknown:
            raise ValueError(
                f"rollback_on names unknown specs {sorted(unknown)}; "
                f"specs: {sorted(names)}")
        self.specs = list(specs)
        self.windows = tuple(
            (float(l), float(s), float(f)) for l, s, f in windows
        )
        if not all(l >= s > 0 for l, s, _ in self.windows):
            raise ValueError(
                f"burn windows need long >= short > 0: {self.windows}")
        self.runlog = runlog
        self.rollback = rollback
        self.rollback_on = tuple(rollback_on)
        self.cooldown_s = float(cooldown_s)
        self.min_events = int(min_events)
        self._clock = clock
        self._horizon = max(l for l, _, _ in self.windows)
        # per-spec series of (t, bad, total) increments
        self._series: dict[str, list[tuple[float, float, float]]] = {
            s.name: [] for s in self.specs
        }
        self._last_alert: dict[str, float] = {}
        # ISSUE 20: the latest scrape window's attribution block —
        # stamped on every alert fired from that window, so a
        # `rollback_on:` decision names the segment that owned the
        # tail it fired on
        self._last_attribution: dict[str, Any] | None = None
        self.stats = {"slo_windows": 0, "slo_alerts": 0,
                      "slo_rollbacks": 0}
        self.alerts: list[dict[str, Any]] = []

    # -- ingest --------------------------------------------------------

    def ingest(self, window: dict[str, Any],
               now: float | None = None) -> list[dict[str, Any]]:
        """Record one collector scrape window and evaluate every
        burn-rate rule. Returns the alerts fired (possibly empty)."""
        t = self._clock() if now is None else float(now)
        self.stats["slo_windows"] += 1
        self._last_attribution = window.get("attribution")
        for spec in self.specs:
            bad, total = spec.measure(window)
            series = self._series[spec.name]
            series.append((t, float(bad), float(total)))
            # prune beyond the longest window (keep one extra point so
            # a window never goes empty between scrapes)
            cutoff = t - self._horizon * 1.5
            while len(series) > 2 and series[0][0] < cutoff:
                series.pop(0)
        return self.evaluate(t)

    def _burn(self, name: str, now: float, win_s: float,
              budget: float) -> tuple[float, float]:
        """(burn rate, total events) over [now - win_s, now]."""
        bad = total = 0.0
        for t, b, n in reversed(self._series[name]):
            if t < now - win_s:
                break
            bad += b
            total += n
        if total <= 0:
            return 0.0, 0.0
        return (bad / total) / budget, total

    def evaluate(self, now: float) -> list[dict[str, Any]]:
        fired: list[dict[str, Any]] = []
        for spec in self.specs:
            last = self._last_alert.get(spec.name)
            if last is not None and now - last < self.cooldown_s:
                continue
            for long_s, short_s, factor in self.windows:
                burn_l, n_l = self._burn(spec.name, now, long_s,
                                         spec.budget)
                if n_l < self.min_events or burn_l < factor:
                    continue
                burn_s, n_s = self._burn(spec.name, now, short_s,
                                         spec.budget)
                if n_s <= 0 or burn_s < factor:
                    continue
                fired.append(self._fire(
                    spec, now, long_s, short_s, factor,
                    burn_l, burn_s, n_l,
                ))
                break  # one alert per spec per evaluation
        return fired

    def _fire(self, spec: SLOSpec, now: float, long_s: float,
              short_s: float, factor: float, burn_l: float,
              burn_s: float, events: float) -> dict[str, Any]:
        self._last_alert[spec.name] = now
        self.stats["slo_alerts"] += 1
        action = "none"
        rolled_to = None
        if spec.name in self.rollback_on and self.rollback is not None:
            rolled_to = self.rollback.rollback_params(
                reason=(
                    f"slo {spec.name} burn {burn_l:.2f}x/"
                    f"{burn_s:.2f}x over {long_s:g}s/{short_s:g}s "
                    f"windows (factor {factor:g})"
                )
            )
            action = "rollback"
            self.stats["slo_rollbacks"] += 1
        alert = {
            "slo": spec.name, **spec.describe(),
            "burn_long": round(burn_l, 4),
            "burn_short": round(burn_s, 4),
            "window_long_s": long_s, "window_short_s": short_s,
            "factor": factor, "events": events,
            "action": action,
        }
        if rolled_to is not None:
            alert["rolled_back_to_version"] = rolled_to
        if self._last_attribution:
            # ISSUE 20: "p99 breached" -> "and queue_wait owns it"
            alert["attribution"] = self._last_attribution
            alert["dominant_tail_segment"] = (
                self._last_attribution.get("dominant_tail_segment"))
        self.alerts.append(alert)
        if self.runlog is not None:
            self.runlog.alert(**alert)
        return alert


def slo_from_config(cfg: dict[str, Any] | None, **kw) -> SLOMonitor | None:
    """Build an SLOMonitor from the declarative `slo:` block of the
    `serve:`/`obs:` config. Unknown keys fail loudly (the config
    contract — a typoed `quarantine_rate_mx` must not silently
    disarm the alert). Returns None for an empty/absent block."""
    if not cfg:
        return None
    unknown = set(cfg) - SLO_CONFIG_KEYS
    if unknown:
        raise ValueError(
            f"unknown slo: config key(s) {sorted(unknown)}; known "
            f"keys: {sorted(SLO_CONFIG_KEYS)}")
    specs: list[SLOSpec] = []
    if cfg.get("p99_ms") is not None:
        specs.append(SLOSpec("p99_ms", "latency", cfg["p99_ms"],
                             budget=cfg.get("p99_budget")))
    if cfg.get("goodput_floor_rps") is not None:
        specs.append(SLOSpec("goodput_rps", "floor",
                             cfg["goodput_floor_rps"]))
    if cfg.get("quarantine_rate_max") is not None:
        specs.append(SLOSpec("quarantine_rate", "ratio",
                             cfg["quarantine_rate_max"]))
    if cfg.get("max_staleness") is not None:
        specs.append(SLOSpec("params_staleness", "ceiling",
                             cfg["max_staleness"]))
    if not specs:
        return None
    if cfg.get("windows") is not None:
        kw.setdefault("windows", tuple(
            tuple(w) for w in cfg["windows"]))
    if cfg.get("rollback_on") is not None:
        kw.setdefault("rollback_on", tuple(cfg["rollback_on"]))
    if cfg.get("cooldown_s") is not None:
        kw.setdefault("cooldown_s", float(cfg["cooldown_s"]))
    if cfg.get("min_events") is not None:
        kw.setdefault("min_events", int(cfg["min_events"]))
    return SLOMonitor(specs, **kw)


class OnlineLoopProbe:
    """The online-loop depth instrument, seated as the store's
    collector (`SessionStore.collector` protocol: `add(res)` +
    `on_close(sid, quarantined=)`) and forwarding to the real
    collector (a `TrajectoryBuffer`) untouched — observation, not
    interposition.

    Measures, host-side, O(1) per decision:
    - `staleness`: per-decision param lag (store's live version minus
      the version the decision was computed under);
    - `swap_latency_s`: ParamBus swap -> first decision served under
      the new version (wire `bus.on_event = probe.on_bus_event`);
    - `reward_by_version`: running reward sum/count per params
      version — the learner's per-version reward scalars.
    """

    def __init__(self, store=None, inner=None, *, metrics=None,
                 runlog=None,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.store = store
        self.inner = inner
        self.metrics = metrics
        self.runlog = runlog
        self._clock = clock
        self.staleness = StreamingHistogram(lo=0.5, hi=2 ** 20,
                                            growth=2.0)
        self.swap_latency_s = StreamingHistogram()
        self.reward_by_version: dict[int, list[float]] = {}
        self._pending_swap: tuple[int, float] | None = None
        self._max_version = 0
        self.stats = {
            "probe_decisions": 0, "probe_swaps": 0,
            "probe_first_decisions": 0, "probe_rollbacks": 0,
        }

    # -- collector protocol -------------------------------------------

    def add(self, res) -> None:
        self.stats["probe_decisions"] += 1
        ver = int(getattr(res, "params_version", 0) or 0)
        if self.store is not None:
            cur = int(self.store.stats.get("serve_param_version", ver))
        else:
            cur = max(self._max_version, ver)
        self._max_version = max(self._max_version, cur, ver)
        lag = max(0, cur - ver)
        self.staleness.add(float(lag))
        if self.metrics is not None:
            self.metrics.observe("online_staleness_lag", float(lag))
        reward = getattr(res, "reward", None)
        if reward is not None:
            slot = self.reward_by_version.setdefault(ver, [0.0, 0.0])
            slot[0] += float(reward)
            slot[1] += 1.0
        pend = self._pending_swap
        if pend is not None and ver >= pend[0]:
            dt = self._clock() - pend[1]
            self._pending_swap = None
            self.swap_latency_s.add(dt)
            self.stats["probe_first_decisions"] += 1
            if self.metrics is not None:
                self.metrics.observe("online_swap_to_first_decision_s",
                                     dt)
        if self.inner is not None:
            self.inner.add(res)

    def on_close(self, sid: int, quarantined: bool = False) -> None:
        if self.inner is not None:
            self.inner.on_close(sid, quarantined=quarantined)

    # -- ParamBus hook -------------------------------------------------

    def on_bus_event(self, event: dict[str, Any]) -> None:
        kind = event.get("event")
        if kind == "swap":
            self.note_swap(int(event["version"]))
        elif kind == "rollback":
            self._pending_swap = None
            self.stats["probe_rollbacks"] += 1

    def note_swap(self, version: int) -> None:
        self._pending_swap = (int(version), self._clock())
        self.stats["probe_swaps"] += 1

    # -- read ----------------------------------------------------------

    def summary(self) -> dict[str, Any]:
        rewards = {
            str(v): {"mean": s / n if n else 0.0, "count": int(n)}
            for v, (s, n) in sorted(self.reward_by_version.items())
        }
        return {
            **self.stats,
            "staleness": self.staleness.summary(),
            "swap_to_first_decision": self.swap_latency_s.summary("_s"),
            "reward_by_version": rewards,
        }
