"""On-device telemetry counters for both rollout engines.

A `Telemetry` is a tiny pytree of i32 scalars (one per lane when the
engine is vmapped) threaded through the hot loops as *pure adds inside
jit* — no host callbacks, no side effects, a handful of scalar ops per
iteration against loop bodies of thousands. Both engines take it as an
optional argument and are bit-identical no-ops when it is omitted
(`telemetry=None` skips the threading entirely, so the off path costs
zero).

Counter semantics per engine:

- `env/core.py` (per-decision `step`): `decide_steps` counts live step
  calls (one per policy commitment), `commit_rounds` finished rounds,
  `loop_iters` the `_resume_simulation` while-loop body iterations —
  under vmap the loop batching masks the carry for lanes whose cond is
  false, so each lane counts exactly ITS iteration count and the
  straggler tax (max/mean over lanes) is measured, not inferred.
  `event_steps` / `ev_*` count single event pops by kind;
  `bulk_relaunch_events` / `bulk_ready_events` the events consumed by
  the vectorized passes; `fulfill_steps` / `bulk_fulfill_hits` the
  one-at-a-time vs bulk-prefix fulfillments.
- `env/flat_loop.py` (micro-step engine): `decide_steps` /
  `fulfill_steps` / `event_steps` count live micro-steps by entry mode
  (the micro-step composition), `loop_iters` the events consumed per
  lane (pops + bulk passes) — the lane-imbalance quantity the flat
  engine absorbs without stalling.

Cross-engine invariant (the parity test): on a deterministic workload
the two engines process the same trajectory, so `decide_steps`, the
per-kind event totals (single pops + the bulk pass attributable to that
kind) and the fulfillment totals (`fulfill_steps + bulk_fulfill_hits`)
agree exactly.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from flax import struct

_i32 = jnp.int32


class Telemetry(struct.PyTreeNode):
    """Per-lane engine counters (i32 scalars; vmapped engines add a
    leading lane axis). See the module docstring for per-engine
    semantics."""

    decide_steps: jnp.ndarray  # policy commitments on live lanes
    fulfill_steps: jnp.ndarray  # one-at-a-time fulfillments
    event_steps: jnp.ndarray  # single event pops / EVENT micro-steps
    loop_iters: jnp.ndarray  # while-loop iters (core) / events (flat)
    ev_job_arrival: jnp.ndarray  # single pops by kind
    ev_task_finished: jnp.ndarray
    ev_exec_ready: jnp.ndarray
    bulk_relaunch_events: jnp.ndarray  # TASK_FINISHED via bulk passes
    bulk_ready_events: jnp.ndarray  # EXECUTOR_READY via bulk passes
    bulk_fulfill_hits: jnp.ndarray  # candidates via _bulk_fulfill
    commit_rounds: jnp.ndarray  # finished commitment rounds
    # --- per-phase while-iteration split (ISSUE 7) ---
    # bulk-phase iterations: micro-steps (flat) / resume-loop
    # iterations (core) whose bulk pass consumed >= 1 event — the
    # decide/fulfill/event phases' iteration counts are decide_steps /
    # fulfill_steps / event_steps; this completes the per-phase split
    bulk_passes: jnp.ndarray
    # inter-decision while-loop body iterations: `drain_to_decision`
    # (flat single-eval path) / `_resume_simulation` (core). Max/mean
    # over lanes IS the measured batch-max drain tax.
    drain_iters: jnp.ndarray
    # --- health sentinels (ISSUE 9) ---
    # i32 violation BITMASK (env/health.py bit table), OR-accumulated
    # via `orr` — not a counter. Stays 0 unless a collector runs with
    # `health=True` (the opt-in `health:` config block); the subtract/
    # summarize window math still works because bits only ever get set
    # (so a - prev == the window's newly-set bits).
    health_mask: jnp.ndarray


def telemetry_zeros() -> Telemetry:
    z = jnp.zeros((), _i32)
    return Telemetry(*([z] * len(Telemetry.__dataclass_fields__)))


def telemetry_zeros_like(batch_shape: tuple[int, ...]) -> Telemetry:
    """Zeros with a leading batch shape on every counter — the starting
    value for vmapped engines (one counter set per lane)."""
    z = jnp.zeros(batch_shape, _i32)
    return Telemetry(*([z] * len(Telemetry.__dataclass_fields__)))


def _count(x) -> bool:
    """i32-cast helper for bool increments."""
    return x.astype(_i32) if hasattr(x, "astype") else _i32(x)


def add(tm: Telemetry | None, **deltas: Any) -> Telemetry | None:
    """`tm.replace(field=field + delta, ...)` with bool deltas cast to
    i32; passes None through so call sites stay one-liners."""
    if tm is None:
        return None
    return tm.replace(
        **{k: getattr(tm, k) + _count(v) for k, v in deltas.items()}
    )


def orr(tm: Telemetry | None, **masks: Any) -> Telemetry | None:
    """Bitwise-OR accumulation for the mask-valued fields
    (`health_mask`): `tm.replace(field=field | mask, ...)`; passes None
    through like `add`."""
    if tm is None:
        return None
    return tm.replace(
        **{k: getattr(tm, k) | _count(v) for k, v in masks.items()}
    )


# ---------------------------------------------------------------------------
# host-side summary (once per iteration / bench row)
# ---------------------------------------------------------------------------


def subtract(tm: Telemetry, prev) -> Telemetry:
    """Counter delta since a `jax.device_get` snapshot `prev` (numpy
    pytree) — bench windows report the timed span, not the warmup."""
    return jax.tree_util.tree_map(lambda a, b: a - b, tm, prev)


def summarize(tm: Telemetry, prev=None) -> dict[str, Any]:
    """Host-side summary dict of a (possibly vmapped) Telemetry.

    Reports totals pooled over lanes, the micro-step composition
    (decide/fulfill/event fractions), per-kind event totals including
    the bulk passes, events and micro-steps per decision, and the
    straggler ratio max/mean over lanes of `loop_iters` — for the core
    engine that is the measured while-loop straggler tax the flat
    engine exists to remove; for the flat engine it is the event-count
    imbalance absorbed without stalling. `prev` (a `jax.device_get`
    snapshot) windows the summary to the counts since the snapshot.
    """
    import numpy as np

    t = jax.device_get(tm)
    if prev is not None:
        t = subtract(t, prev)

    def tot(x) -> int:
        return int(np.sum(np.asarray(x)))

    decide = tot(t.decide_steps)
    fulfill = tot(t.fulfill_steps)
    event = tot(t.event_steps)
    micro = decide + fulfill + event
    li = np.asarray(t.loop_iters).ravel().astype(np.float64)
    lanes = int(li.size)
    mean_li = float(li.mean()) if lanes else 0.0
    straggler = float(li.max() / mean_li) if mean_li > 0 else 1.0

    events_by_kind = {
        "job_arrival": tot(t.ev_job_arrival),
        "task_finished": tot(t.ev_task_finished)
        + tot(t.bulk_relaunch_events),
        "executor_ready": tot(t.ev_exec_ready)
        + tot(t.bulk_ready_events),
    }
    events_total = sum(events_by_kind.values())
    frac = lambda n: round(n / micro, 4) if micro else 0.0  # noqa: E731
    per_dec = lambda n: round(n / decide, 3) if decide else 0.0  # noqa: E731
    di = np.asarray(t.drain_iters).ravel().astype(np.float64)
    mean_di = float(di.mean()) if lanes else 0.0
    drain_straggler = float(di.max() / mean_di) if mean_di > 0 else 1.0
    hm = np.asarray(t.health_mask).ravel()
    health_mask = (
        int(np.bitwise_or.reduce(hm)) if hm.size else 0
    )
    from ..env.health import describe_mask  # host-side, no cycle

    return {
        "lanes": lanes,
        "decisions": decide,
        "commit_rounds": tot(t.commit_rounds),
        "micro_steps": micro,
        "composition": {
            "decide": frac(decide),
            "fulfill": frac(fulfill),
            "event": frac(event),
        },
        "events_by_kind": events_by_kind,
        "events_total": events_total,
        "events_per_decision": per_dec(events_total),
        "micro_per_decision": per_dec(micro),
        "bulk": {
            "relaunch_events": tot(t.bulk_relaunch_events),
            "ready_events": tot(t.bulk_ready_events),
            "fulfill_hits": tot(t.bulk_fulfill_hits),
        },
        "fulfillments": fulfill + tot(t.bulk_fulfill_hits),
        # per-phase while-iteration split (ISSUE 7): the engine's
        # iteration budget attributed to decide / fulfill / event /
        # bulk phases — scripts_phase_rank.py ranks these per decision
        "phase_iters": {
            "decide": decide,
            "fulfill": fulfill,
            "event": event,
            "bulk": tot(t.bulk_passes),
        },
        "drain_iters_mean": round(mean_di, 2),
        "drain_iters_max": int(di.max()) if lanes else 0,
        "drain_straggler_ratio": round(drain_straggler, 3),
        # health sentinels (ISSUE 9): the pooled violation bitmask, its
        # decoded bit names, and how many lanes tripped anything —
        # all zero/empty unless a collector ran with health=True
        "health_mask": health_mask,
        "health_bits": describe_mask(health_mask),
        "unhealthy_lanes": int((hm != 0).sum()) if hm.size else 0,
        "loop_iters_mean": round(mean_li, 2),
        "loop_iters_max": int(li.max()) if lanes else 0,
        "straggler_ratio": round(straggler, 3),
    }
