"""Streaming serving metrics: log-bucketed histograms and a small
counter/gauge/histogram registry (ISSUE 11).

The serving observability problem is cardinality: an open-loop load
run submits 10^5..10^6 requests, and retaining per-request latency
samples to compute p99 turns the measurement layer into the memory
hog. `StreamingHistogram` is the standard fix — geometric (log-spaced)
buckets, so any quantile is recoverable from O(buckets) integers with
a bounded RELATIVE error (half a bucket width, ~6% at the default
growth factor), and two histograms from different workers/windows
merge by adding counts. Count / sum / min / max are tracked exactly,
so means are exact and quantile estimates are clamped into the
observed range.

`MetricsRegistry` is the host-side instrument panel the serving front
(`serve/session.py:MicroBatcher`, `serve/loadgen.py`) writes into:
monotone counters (flush reasons, quarantines, capacity rejections),
gauges (last-observed values), and named histograms (queue depth,
batch occupancy, linger waits, per-span latencies). Two exporters:

- `to_prometheus()`: Prometheus text exposition (counters, gauges,
  cumulative `_bucket{le=...}` histogram lines ending in `+Inf`), so
  a scrape endpoint needs only to serve the string;
- `snapshot()`: a JSON-safe dict (the JSONL exporter — write it
  through `RunLog.metrics`, one `metrics` record per snapshot).

The registry is thread-safe (ISSUE 19): one registry is bumped from
the serve pump, the client worker threads, the online learner and the
fleet collector, and scraped (snapshot/to_prometheus) concurrently —
the bare dict read-modify-write in `counter()` lost increments under
that load, and a snapshot iterating while a handler bumped could see
a dict mutated mid-iteration. One registry-wide `threading.Lock`
guards the three tables; an uncontended CPython lock acquire is
~0.1us against ms-scale decides, so the <=5% instrumentation bar
holds (measured: PERF.md round 21).

`percentile_block` / `hist_summary` are the shared quantile helpers
the benches use: `percentile_block` computes the EXACT sample
percentiles (numpy) with the PERF.md round-13 latency-row keys — the
r10 artifact schema, unchanged — while `hist_summary` is the
O(buckets) companion block (`hist`) new rows stamp alongside it.
"""

from __future__ import annotations

import math
import threading
from typing import Any, Iterable

# default bucket geometry: growth 1.12 spans 1e-4 .. 1e7 (ms-scale
# latencies, but unit-agnostic) in ~224 buckets; max relative
# quantile error is half a bucket, (sqrt(1.12)-1) ~= 5.8%
DEFAULT_LO = 1e-4
DEFAULT_HI = 1e7
DEFAULT_GROWTH = 1.12

PERCENTILE_KEYS = ("p50", "p90", "p99", "p999")
_QS = {"p50": 50.0, "p90": 90.0, "p99": 99.0, "p999": 99.9}


class StreamingHistogram:
    """Mergeable log-bucketed histogram: O(buckets) memory regardless
    of sample count, quantiles within half a bucket of relative error,
    exact count/sum/min/max. Values <= 0 or < `lo` land in the
    underflow bucket (reported as `lo`), values >= `hi` in overflow
    (reported as the observed max)."""

    __slots__ = ("lo", "hi", "growth", "_log_growth", "n", "counts",
                 "count", "total", "min", "max")

    def __init__(self, lo: float = DEFAULT_LO, hi: float = DEFAULT_HI,
                 growth: float = DEFAULT_GROWTH) -> None:
        if not (0 < lo < hi and growth > 1.0):
            raise ValueError(
                f"need 0 < lo < hi and growth > 1, got lo={lo} "
                f"hi={hi} growth={growth}"
            )
        self.lo = float(lo)
        self.hi = float(hi)
        self.growth = float(growth)
        self._log_growth = math.log(self.growth)
        self.n = int(math.ceil(
            math.log(self.hi / self.lo) / self._log_growth
        ))
        # index 0 = underflow, 1..n = log buckets, n+1 = overflow
        self.counts = [0] * (self.n + 2)
        self.count = 0
        self.total = 0.0
        self.min = math.inf
        self.max = -math.inf

    # -- ingest --------------------------------------------------------

    def _index(self, v: float) -> int:
        if v < self.lo:
            return 0
        if v >= self.hi:
            return self.n + 1
        return 1 + int(math.log(v / self.lo) / self._log_growth)

    def add(self, v: float) -> None:
        v = float(v)
        self.counts[self._index(v)] += 1
        self.count += 1
        self.total += v
        if v < self.min:
            self.min = v
        if v > self.max:
            self.max = v

    def add_many(self, values: Iterable[float]) -> None:
        for v in values:
            self.add(v)

    def merge(self, other: "StreamingHistogram") -> "StreamingHistogram":
        """Add `other`'s counts into self (same bucket geometry only —
        merging differently-bucketed histograms would silently shift
        quantiles)."""
        if (self.lo, self.hi, self.growth) != (
                other.lo, other.hi, other.growth):
            raise ValueError(
                "cannot merge histograms with different bucket "
                f"geometry: {(self.lo, self.hi, self.growth)} vs "
                f"{(other.lo, other.hi, other.growth)}"
            )
        for i, c in enumerate(other.counts):
            self.counts[i] += c
        self.count += other.count
        self.total += other.total
        self.min = min(self.min, other.min)
        self.max = max(self.max, other.max)
        return self

    def copy(self) -> "StreamingHistogram":
        """Independent snapshot with the same geometry and counts —
        what the fleet collector stores per scrape so `delta` can
        recover a window's distribution later."""
        h = StreamingHistogram(self.lo, self.hi, self.growth)
        h.counts = list(self.counts)
        h.count = self.count
        h.total = self.total
        h.min = self.min
        h.max = self.max
        return h

    def delta(self, prev: "StreamingHistogram | None") -> "StreamingHistogram":
        """Windowed view: the histogram of samples added AFTER `prev`
        was snapshotted (per-bucket count subtraction, clamped at 0 so
        a reset/rolled counter degrades to the full cumulative view
        rather than going negative). min/max of the window are not
        recoverable from cumulative extremes, so the window's extremes
        are estimated from its own nonzero bucket edges, clamped into
        the cumulative [min, max]."""
        if prev is None:
            return self.copy()
        if (self.lo, self.hi, self.growth) != (
                prev.lo, prev.hi, prev.growth):
            raise ValueError(
                "cannot delta histograms with different bucket "
                f"geometry: {(self.lo, self.hi, self.growth)} vs "
                f"{(prev.lo, prev.hi, prev.growth)}"
            )
        h = StreamingHistogram(self.lo, self.hi, self.growth)
        h.counts = [max(0, a - b)
                    for a, b in zip(self.counts, prev.counts)]
        h.count = sum(h.counts)
        h.total = max(0.0, self.total - prev.total)
        if h.count:
            nz = [i for i, c in enumerate(h.counts) if c]
            lo_i, hi_i = nz[0], nz[-1]
            wmin = self.lo if lo_i == 0 else h._edge(lo_i)
            wmax = self.max if hi_i == self.n + 1 else (
                h._edge(hi_i) * self.growth
            )
            h.min = min(max(wmin, self.min), self.max)
            h.max = min(max(wmax, self.min), self.max)
        return h

    # -- read ----------------------------------------------------------

    def _edge(self, i: int) -> float:
        """Lower edge of log bucket i (1-based)."""
        return self.lo * self.growth ** (i - 1)

    def quantile(self, q: float) -> float:
        """Approximate q-quantile (q in [0, 1]): geometric midpoint of
        the bucket holding the rank, clamped to [min, max] observed."""
        if self.count == 0:
            return 0.0
        rank = max(1, int(math.ceil(q * self.count)))
        cum = 0
        for i, c in enumerate(self.counts):
            cum += c
            if cum >= rank:
                if i == 0:
                    est = self.lo
                elif i == self.n + 1:
                    est = self.max
                else:
                    est = self._edge(i) * math.sqrt(self.growth)
                return min(max(est, self.min), self.max)
        return self.max

    def count_above(self, bound: float) -> int:
        """Samples strictly in buckets whose LOWER edge is >= `bound`
        (the SLO monitor's bad-event counter: requests over the latency
        bound). Bucketed, so at most one bucket (~12% band at the
        default growth) of samples straddling `bound` is miscounted —
        the burn-rate rules tolerate that by design."""
        if self.count == 0:
            return 0
        bad = self.counts[self.n + 1]  # overflow is always above
        for i in range(1, self.n + 1):
            if self._edge(i) >= bound:
                bad += self.counts[i]
        if bound <= self.lo:
            bad += self.counts[0]
        return bad

    @property
    def mean(self) -> float:
        return self.total / self.count if self.count else 0.0

    def summary(self, suffix: str = "") -> dict[str, Any]:
        """JSON-safe summary block. `suffix` decorates the quantile
        keys (e.g. "_ms" -> p50_ms), matching the latency-row dialect."""
        out: dict[str, Any] = {
            "count": self.count,
            "mean" + suffix: round(self.mean, 4),
            "min" + suffix: round(self.min, 4) if self.count else 0.0,
            "max" + suffix: round(self.max, 4) if self.count else 0.0,
        }
        for k in PERCENTILE_KEYS:
            out[k + suffix] = round(self.quantile(_QS[k] / 100.0), 4)
        out["scheme"] = {
            "lo": self.lo, "growth": self.growth, "buckets": self.n + 2,
            "max_rel_err": round(math.sqrt(self.growth) - 1.0, 4),
        }
        return out

    def nonzero_buckets(self) -> list[tuple[float, int]]:
        """(upper-edge, count) pairs for every non-empty bucket —
        the compact serialized form."""
        out = []
        for i, c in enumerate(self.counts):
            if c == 0:
                continue
            if i == 0:
                le = self.lo
            elif i == self.n + 1:
                le = math.inf
            else:
                le = self._edge(i) * self.growth
            out.append((le, c))
        return out


class MetricsRegistry:
    """Named counters / gauges / histograms for the serving front.
    Zero-cost when absent: every instrumented call site holds
    `metrics: MetricsRegistry | None` and skips on None."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.counters: dict[str, float] = {}
        self.gauges: dict[str, float] = {}
        self.hists: dict[str, StreamingHistogram] = {}

    def counter(self, name: str, inc: float = 1) -> None:
        with self._lock:
            self.counters[name] = self.counters.get(name, 0) + inc

    def gauge(self, name: str, value: float) -> None:
        with self._lock:
            self.gauges[name] = float(value)

    def observe(self, name: str, value: float) -> None:
        with self._lock:
            h = self.hists.get(name)
            if h is None:
                h = self.hists[name] = StreamingHistogram()
            h.add(value)

    def merge(self, other: "MetricsRegistry") -> "MetricsRegistry":
        """Fold another registry in (counters add, gauges last-wins,
        histograms merge) — the multi-worker aggregation path.

        The two locks are taken SEQUENTIALLY (copy out of `other`,
        then fold into `self`), never nested — nesting two locks of
        the same class is exactly the order-inversion shape the
        concurrency pass forbids."""
        with other._lock:
            counters = dict(other.counters)
            gauges = dict(other.gauges)
            hists = []
            for k, h in other.hists.items():
                clone = StreamingHistogram(h.lo, h.hi, h.growth)
                clone.merge(h)
                hists.append((k, clone))
        with self._lock:
            for k, v in counters.items():
                self.counters[k] = self.counters.get(k, 0) + v
            self.gauges.update(gauges)
            for k, clone in hists:
                if k in self.hists:
                    self.hists[k].merge(clone)
                else:
                    self.hists[k] = clone
        return self

    # -- exporters -----------------------------------------------------

    def snapshot(self) -> dict[str, Any]:
        """JSON-safe dict — the JSONL exporter's payload (write via
        `RunLog.metrics`, one `metrics` record per snapshot)."""
        with self._lock:
            return {
                "counters": {k: self.counters[k]
                             for k in sorted(self.counters)},
                "gauges": {k: self.gauges[k]
                           for k in sorted(self.gauges)},
                "hists": {k: self.hists[k].summary()
                          for k in sorted(self.hists)},
            }

    def to_prometheus(self, prefix: str = "",
                      labels: dict[str, str] | None = None,
                      types: bool = True) -> str:
        """Prometheus text exposition format. Histogram lines are
        cumulative `_bucket{le="..."}` over the FULL fixed bucket set
        (every scrape exposes the same `le` series — a bucket
        appearing mid-run would start a new timeseries and break
        `rate()`/`histogram_quantile()` across scrapes) plus the
        mandatory `le="+Inf"`, `_sum` and `_count`.

        `labels` stamps every series with a fixed label set (the fleet
        exposition's `replica="N"` slicing — ISSUE 17); `types=False`
        suppresses the `# TYPE` header lines so labeled per-replica
        blocks can follow an already-typed merged block without
        duplicate metadata."""
        lines: list[str] = []
        lbl = ""
        if labels:
            lbl = ",".join(
                f'{k}="{v}"' for k, v in sorted(labels.items())
            )

        def _name(k: str) -> str:
            k = prefix + k
            return "".join(
                c if c.isalnum() or c == "_" else "_" for c in k
            )

        def _series(n: str, extra: str = "") -> str:
            parts = ",".join(p for p in (lbl, extra) if p)
            return f"{n}{{{parts}}}" if parts else n

        with self._lock:
            for k in sorted(self.counters):
                n = _name(k)
                if types:
                    lines.append(f"# TYPE {n} counter")
                lines.append(f"{_series(n)} {self.counters[k]:g}")
            for k in sorted(self.gauges):
                n = _name(k)
                if types:
                    lines.append(f"# TYPE {n} gauge")
                lines.append(f"{_series(n)} {self.gauges[k]:g}")
            for k in sorted(self.hists):
                h = self.hists[k]
                n = _name(k)
                if types:
                    lines.append(f"# TYPE {n} histogram")
                cum = 0
                # underflow's upper bound is `lo`, then every
                # log-bucket edge; overflow folds into the +Inf line
                for i in range(h.n + 1):
                    cum += h.counts[i]
                    le = h.lo if i == 0 else h._edge(i) * h.growth
                    edge = 'le="%g"' % le
                    lines.append(
                        f"{_series(n + '_bucket', edge)} {cum}"
                    )
                inf_edge = 'le="+Inf"'
                lines.append(
                    f"{_series(n + '_bucket', inf_edge)} {h.count}"
                )
                lines.append(f"{_series(n + '_sum')} {h.total:g}")
                lines.append(f"{_series(n + '_count')} {h.count}")
        return "\n".join(lines) + "\n"

    def export_prometheus(self, path: str, prefix: str = "") -> None:
        with open(path, "w") as fp:
            fp.write(self.to_prometheus(prefix))


def interleaved_ab(arm_off, arm_on, warmups: int = 2, reps: int = 5
                   ) -> tuple[float, float, float]:
    """The interleaved-median A/B protocol (scripts_obs_demo.py,
    PERF.md operational rules): warm both arms, then alternate timed
    reps so box-level drift hits both equally, and compare medians.
    `arm_off`/`arm_on` are zero-arg callables returning one rep's
    seconds. Returns (median_off, median_on, overhead_pct). ONE
    implementation on purpose — the <5% instrumentation bar is
    measured by this function wherever it is claimed."""
    for _ in range(warmups):
        arm_off()
        arm_on()
    offs, ons = [], []
    for _ in range(reps):
        offs.append(arm_off())
        ons.append(arm_on())
    offs.sort()
    ons.sort()
    t_off, t_on = offs[len(offs) // 2], ons[len(ons) // 2]
    return t_off, t_on, 100.0 * (t_on - t_off) / t_off


def paired_ab_pct(offs: list[float], ons: list[float]) -> float:
    """Overhead percent from PAIRED interleaved reps: the median of
    per-pair ratios (on_i / off_i - 1). For run-granularity A/Bs —
    few, expensive reps — monotone box drift moves BOTH arms of a
    pair together, so pairing cancels it, while the median-of-arms
    form (`interleaved_ab`, right for many fast reps) aliases the
    drift into whichever arm's median lands later. ONE implementation
    wherever a run-level A/B bar is claimed (the record-overhead A/Bs
    of bench_serve_scale's online arm and scripts_online_loop.py)."""
    assert len(offs) == len(ons) and offs, (len(offs), len(ons))
    ratios = sorted(
        on / off - 1.0 for off, on in zip(offs, ons)
    )
    return 100.0 * ratios[len(ratios) // 2]


# ---------------------------------------------------------------------------
# shared bench quantile helpers (ISSUE 11 satellite): the latency rows'
# percentile block — EXACT sample percentiles with the round-13 keys,
# so refactored callers (bench_decima._latency_block) emit byte-equal
# r10-schema fields — plus the streaming-histogram companion block.
# ---------------------------------------------------------------------------


def percentile_block(samples: Iterable[float], reps: int | None = None,
                     suffix: str = "_ms") -> dict[str, Any]:
    """Exact percentile block over retained samples (the PERF.md
    round-13 latency-row schema: p50/p90/p99/mean/max + reps)."""
    import numpy as np

    a = np.asarray(list(samples), dtype=np.float64)
    return {
        "p50" + suffix: round(float(np.percentile(a, 50)), 4),
        "p90" + suffix: round(float(np.percentile(a, 90)), 4),
        "p99" + suffix: round(float(np.percentile(a, 99)), 4),
        "mean" + suffix: round(float(a.mean()), 4),
        "max" + suffix: round(float(a.max()), 4),
        "reps": int(reps if reps is not None else a.size),
    }


def hist_summary(samples: Iterable[float] | StreamingHistogram,
                 suffix: str = "_ms") -> dict[str, Any]:
    """The O(buckets) `hist` block: a StreamingHistogram summary of the
    same samples (or of an already-streaming histogram), stamped NEXT
    TO the exact block so readers can check the approximation and
    million-request rows can drop the exact one."""
    if isinstance(samples, StreamingHistogram):
        return samples.summary(suffix)
    h = StreamingHistogram()
    h.add_many(samples)
    return h.summary(suffix)
