"""Fleet collector: per-replica scrape loop, windowed scoreboard, and
the labeled Prometheus exposition (ISSUE 17, tentpole part 1).

PR 16's fleet made `/metrics` a lossy merge: every replica's registry
folded into one, so a dead replica, a hot-spotted replica, or one
replica lagging a params version behind the fleet all disappear into
the aggregate. The collector keeps the per-replica axis (Monarch-style
label slicing: the `replica="N"` label IS the schema) and adds the
time axis the merge also lost — every scrape snapshots each replica's
cumulative counters + histograms, and the scoreboard reports WINDOWED
rates (deltas between scrapes, histogram bucket subtraction via
`StreamingHistogram.delta`) rather than since-boot averages.

One `FleetCollector` works against either fleet shape:

- a `serve.router.Router` (its `replica_samples()` does one `metrics`
  roundtrip per live replica, unmerged);
- any in-process `(store-like)` backend carrying `.stats` and
  optionally `.metrics` — one pseudo-replica `"0"`, so the single-
  process stack gets the same scoreboard/SLO plane for free.

Threading: `maybe_scrape()` is designed to ride the OWNER's loop (the
`ServeServer` pump calls it between polls; a bench loop calls it per
iteration) — the Router pipes and the store are single-owner by
design, so the collector never brings its own thread near them.
`start()`/`stop()` exist for backends that are safe to poll
concurrently (a remote `/fleet` URL, a fake in tests); the server
integration does NOT use them.

Each scrape: (1) per-replica windows -> scoreboard (`fleet_status()`),
(2) fleet-aggregate window -> `SLOMonitor.ingest` (alerts + optional
rollback), (3) a periodic `fleet` runlog record (every `log_every`
scrapes) so the scoreboard lands in the same JSONL stream the ledger
and `scripts_phase_rank.py` read.

CLI: `python -m sparksched_tpu.obs.fleet --url http://host:port`
scrapes a live server's `/fleet` endpoint; `--runlog FILE` renders the
latest `fleet` record from a run log instead (post-mortem mode).
"""

from __future__ import annotations

import json
import time
from typing import Any, Callable

from .critpath import SEG_HIST
from .metrics import MetricsRegistry, StreamingHistogram
from ..ownership import assert_owner

# per-decide latency source, in preference order: the device span is
# the per-call latency proxy every traced front stamps
LATENCY_HISTS = ("serve_span_device_ms", "serve_span_total_ms",
                 "serve_decide_ms")

_SCOREBOARD_FIELDS = (
    "replica", "alive", "rps", "p99_ms", "sessions", "hot",
    "inflight", "page_churn_per_s", "quarantine_rate",
    "params_version", "params_lag", "decisions",
    # ISSUE 18: the device trajectory ring's health — occupancy
    # (records parked on-device awaiting drain), drains shipped, and
    # overrun drops (nonzero = the drain cadence can't keep up with
    # this replica's decision rate)
    "ring_occ", "ring_drains", "ring_dropped",
    # ISSUE 20: the replica's dominant tail segment over the scrape
    # window (argmax of the windowed per-segment p99s — which stage
    # of the request path owns THIS replica's tail right now)
    "tail_seg",
)


def _stat(stats: dict | None, key: str, default: int = 0) -> int:
    if not stats:
        return default
    return int(stats.get(key, default))


def labeled_prometheus(samples: list[dict[str, Any]],
                       extra: "MetricsRegistry | None" = None,
                       prefix: str = "") -> str:
    """The fleet `/metrics` exposition (ISSUE 17 satellite): merged
    totals FIRST (unlabeled — byte-compatible with the PR-16 merge for
    existing scrapers), then each replica's own series stamped
    `replica="N"` (no duplicate `# TYPE` headers)."""
    merged = MetricsRegistry()
    for s in samples:
        if s.get("registry") is not None:
            merged.merge(s["registry"])
    if extra is not None:
        merged.merge(extra)
    text = merged.to_prometheus(prefix)
    for s in samples:
        reg = s.get("registry")
        if reg is not None:
            text += reg.to_prometheus(
                prefix, labels={"replica": str(s["replica"])},
                types=False,
            )
    return text


class FleetCollector:
    """Periodic per-replica scrapes -> windowed scoreboard + SLO
    ingest + `fleet` runlog records."""

    def __init__(
        self,
        backend,
        *,
        period_s: float = 1.0,
        runlog=None,
        slo=None,
        log_every: int = 1,
        latency_hists: tuple[str, ...] = LATENCY_HISTS,
        clock: Callable[[], float] = time.monotonic,
        critpath=None,
    ) -> None:
        self.backend = backend
        self.period_s = float(period_s)
        self.runlog = runlog
        self.slo = slo
        # ISSUE 20: the in-process front's attribution analyzer — its
        # joint (wall x segment) profile answers "segment mix AT a
        # quantile", which the marginal per-segment registry hists
        # cannot; behind a Router only those scraped hists exist
        self.critpath = critpath
        self.log_every = max(1, int(log_every))
        self.latency_hists = tuple(latency_hists)
        self._clock = clock
        self._prev: dict[str, dict[str, Any]] = {}
        self._last_scrape: float | None = None
        self.last_status: dict[str, Any] | None = None
        self.stats = {"collector_scrapes": 0, "collector_alerts": 0}
        self._thread = None
        self._stop_evt = None

    # -- sampling ------------------------------------------------------

    def _samples(self) -> list[dict[str, Any]]:
        if hasattr(self.backend, "replica_samples"):
            return self.backend.replica_samples()
        stats = dict(getattr(self.backend, "stats", {}) or {})
        return [{
            "replica": "0", "alive": True, "stats": stats,
            "registry": getattr(self.backend, "metrics", None),
        }]

    def _latency_hist(self, reg) -> StreamingHistogram | None:
        if reg is None:
            return None
        for name in self.latency_hists:
            h = reg.hists.get(name)
            if h is not None:
                return h
        return None

    @staticmethod
    def _seg_hists(reg) -> dict[str, StreamingHistogram]:
        """The replica's per-segment attribution histograms (ISSUE 20
        — fed by `CritPathAnalyzer` / `ServeClient._resolve`); empty
        on an unattributed replica."""
        if reg is None:
            return {}
        return {seg: h for seg, name in SEG_HIST.items()
                if (h := reg.hists.get(name)) is not None}

    # -- scrape --------------------------------------------------------

    def maybe_scrape(self, now: float | None = None
                     ) -> dict[str, Any] | None:
        """Rate-limited scrape for riding an owner loop (the server
        pump): no-op until `period_s` has elapsed."""
        t = self._clock() if now is None else float(now)
        if (self._last_scrape is not None
                and t - self._last_scrape < self.period_s):
            return None
        return self.scrape(now=t)

    def scrape(self, now: float | None = None) -> dict[str, Any]:
        assert_owner(self, "serve-pump", "fleet-collector")
        t = self._clock() if now is None else float(now)
        self._last_scrape = t
        self.stats["collector_scrapes"] += 1
        samples = self._samples()

        rows: list[dict[str, Any]] = []
        fleet_hist: StreamingHistogram | None = None
        fleet_segs: dict[str, StreamingHistogram] = {}
        fleet = {"decisions": 0.0, "quarantines": 0.0, "dt_s": 0.0,
                 "replicas_alive": 0, "replicas": len(samples)}
        max_version = max(
            (_stat(s.get("stats"), "serve_param_version")
             for s in samples if s.get("stats")), default=0,
        )
        for s in samples:
            rows.append(self._row(s, t, max_version, fleet))
            # per-replica windowed latency hists merge into the fleet
            # window (same geometry by construction)
            wh = rows[-1].pop("_window_hist", None)
            if wh is not None and wh.count:
                if fleet_hist is None:
                    fleet_hist = wh
                else:
                    fleet_hist.merge(wh)
            for seg, sh in (rows[-1].pop("_window_segs", None)
                            or {}).items():
                fh = fleet_segs.get(seg)
                if fh is None:
                    fleet_segs[seg] = sh
                else:
                    fh.merge(sh)

        dt = fleet.pop("dt_s")
        window = {
            "dt_s": dt,
            "decisions": fleet["decisions"],
            "quarantines": fleet["quarantines"],
            "goodput_rps": fleet["decisions"] / dt if dt > 0 else 0.0,
            "latency_hist": fleet_hist,
            "params_lag_max": max(
                (r["params_lag"] for r in rows
                 if r["params_lag"] is not None), default=None,
            ),
            "attribution": self._attribution(fleet_segs),
        }
        alerts: list[dict[str, Any]] = []
        if self.slo is not None:
            alerts = self.slo.ingest(window, now=t)
            self.stats["collector_alerts"] += len(alerts)

        att = window["attribution"]
        status = {
            "t": t,
            "replicas": rows,
            "fleet": {
                **fleet,
                "goodput_rps": round(window["goodput_rps"], 3),
                "window_p99_ms": (
                    round(fleet_hist.quantile(0.99), 3)
                    if fleet_hist is not None and fleet_hist.count
                    else None),
                "params_version_max": max_version,
                "tail_seg": (att or {}).get("dominant_tail_segment"),
                "attribution": att,
            },
            "alerts": alerts,
        }
        self.last_status = status
        if self.critpath is not None:
            # idle-tail exemplar shipping: the reservoir flushes on
            # the scrape cadence even when no new request arrives to
            # trigger it from the serve path
            self.critpath.maybe_flush_window()
        if (self.runlog is not None
                and self.stats["collector_scrapes"] % self.log_every
                == 0):
            self.runlog.fleet(**_json_safe(status))
        return status

    def _attribution(
        self, segs: dict[str, StreamingHistogram]
    ) -> dict[str, Any] | None:
        """The fleet window's attribution block: windowed per-segment
        p99/mean over the merged replica histograms, the dominant
        tail segment, and — when the in-process analyzer is attached
        — the joint segment mix at p50 vs p99 (cumulative, not
        windowed: the joint cells have no delta algebra)."""
        att: dict[str, Any] = {}
        if segs:
            p99 = {s: round(h.quantile(0.99), 3)
                   for s, h in segs.items() if h.count}
            att = {
                "n": max(h.count for h in segs.values()),
                "seg_p99_ms": p99,
                "seg_mean_ms": {
                    s: round(h.total / h.count, 3)
                    for s, h in segs.items() if h.count
                },
                "dominant_tail_segment": max(
                    p99.items(), key=lambda kv: kv[1])[0]
                if p99 else None,
            }
        if self.critpath is not None:
            prof = self.critpath.profile
            for q, label in ((0.5, "at_p50"), (0.99, "at_p99")):
                mix = prof.attribution_at(q)
                if mix is not None:
                    att[label] = mix
            dom = prof.dominant_segment()
            if dom is not None:
                # the joint profile's verdict beats the marginal
                # argmax (the p99 of a segment is not the segment of
                # the p99 request)
                att["dominant_tail_segment"] = dom
        return att or None

    def _row(self, s: dict[str, Any], t: float, max_version: int,
             fleet: dict[str, Any]) -> dict[str, Any]:
        rep = str(s["replica"])
        stats = s.get("stats")
        reg = s.get("registry")
        hist = self._latency_hist(reg)
        segs = self._seg_hists(reg)
        prev = self._prev.get(rep)
        cur = {
            "t": t,
            "stats": dict(stats) if stats else None,
            "hist": hist.copy() if hist is not None else None,
            "segs": {k: h.copy() for k, h in segs.items()} or None,
        }
        self._prev[rep] = cur

        row: dict[str, Any] = {
            "replica": rep, "alive": bool(s.get("alive")),
            "rps": None, "p99_ms": None,
            "sessions": _stat(stats, "serve_sessions_live"),
            "hot": _stat(stats, "serve_sessions_hot"),
            "inflight": int(reg.gauges.get("serve_inflight_depth", 0))
            if reg is not None else 0,
            "page_churn_per_s": None,
            "quarantine_rate": None,
            "params_version": _stat(stats, "serve_param_version"),
            "params_lag": (max_version
                           - _stat(stats, "serve_param_version"))
            if stats else None,
            "decisions": _stat(stats, "serve_decisions"),
            "ring_occ": _stat(stats, "serve_ring_occupancy"),
            "ring_drains": _stat(stats, "serve_ring_drains"),
            "ring_dropped": _stat(stats, "serve_ring_dropped"),
            "tail_seg": None,
            "_window_hist": None,
            "_window_segs": None,
        }
        if row["alive"]:
            fleet["replicas_alive"] += 1
        if prev is None or stats is None or prev["stats"] is None:
            return row
        dt = t - prev["t"]
        if dt <= 0:
            return row
        d_dec = _stat(stats, "serve_decisions") - _stat(
            prev["stats"], "serve_decisions")
        d_quar = _stat(stats, "serve_quarantines") - _stat(
            prev["stats"], "serve_quarantines")
        d_pages = (
            _stat(stats, "serve_page_ins")
            + _stat(stats, "serve_page_outs")
            - _stat(prev["stats"], "serve_page_ins")
            - _stat(prev["stats"], "serve_page_outs")
        )
        row["rps"] = round(max(0, d_dec) / dt, 3)
        row["page_churn_per_s"] = round(max(0, d_pages) / dt, 3)
        row["quarantine_rate"] = (
            round(max(0, d_quar) / d_dec, 4) if d_dec > 0 else 0.0)
        fleet["decisions"] += max(0, d_dec)
        fleet["quarantines"] += max(0, d_quar)
        fleet["dt_s"] = max(fleet["dt_s"], dt)
        if hist is not None:
            wh = hist.delta(prev["hist"])
            row["_window_hist"] = wh
            if wh.count:
                row["p99_ms"] = round(wh.quantile(0.99), 3)
        if segs:
            prev_segs = prev.get("segs") or {}
            wsegs: dict[str, StreamingHistogram] = {}
            for seg, h in segs.items():
                ph = prev_segs.get(seg)
                ws = h.delta(ph) if ph is not None else h.copy()
                if ws.count:
                    wsegs[seg] = ws
            if wsegs:
                row["_window_segs"] = wsegs
                seg_p99 = {seg: round(ws.quantile(0.99), 3)
                           for seg, ws in wsegs.items()}
                row["attribution"] = {"seg_p99_ms": seg_p99}
                row["tail_seg"] = max(
                    seg_p99.items(), key=lambda kv: kv[1])[0]
        return row

    def fleet_status(self) -> dict[str, Any]:
        """The scoreboard: last scrape's status (scraping first if
        none has happened yet)."""
        return self.last_status if self.last_status is not None \
            else self.scrape()

    # -- optional background loop (NOT for Router/store backends) ------

    def start(self) -> "FleetCollector":
        """Background scrape thread — only for backends that are safe
        to poll off-thread (a test fake, a remote facade). The server
        integration rides the pump thread via `maybe_scrape` instead;
        the Router pipes and the store are single-owner."""
        import threading

        if self._thread is not None:
            raise RuntimeError("collector already started")
        self._stop_evt = threading.Event()

        def _loop() -> None:
            while not self._stop_evt.wait(self.period_s):
                self.scrape()

        self._thread = threading.Thread(
            target=_loop, name="fleet-collector", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._thread is None:
            return
        self._stop_evt.set()
        self._thread.join(timeout=10.0)
        self._thread = None


def _json_safe(obj: Any) -> Any:
    if isinstance(obj, dict):
        return {k: _json_safe(v) for k, v in obj.items()
                if not str(k).startswith("_")}
    if isinstance(obj, (list, tuple)):
        return [_json_safe(v) for v in obj]
    if isinstance(obj, StreamingHistogram):
        return obj.summary()
    if isinstance(obj, (str, int, float, bool)) or obj is None:
        return obj
    return str(obj)


# ---------------------------------------------------------------------------
# scoreboard rendering + CLI
# ---------------------------------------------------------------------------


def render_status(status: dict[str, Any]) -> str:
    """Fixed-width scoreboard table (the CLI's and the docs' view)."""
    cols = _SCOREBOARD_FIELDS
    rows = [[("" if r.get(c) is None else str(r.get(c)))
             for c in cols] for r in status.get("replicas", [])]
    widths = [max(len(c), *(len(row[i]) for row in rows))
              if rows else len(c) for i, c in enumerate(cols)]
    lines = ["  ".join(c.ljust(w) for c, w in zip(cols, widths))]
    lines.append("  ".join("-" * w for w in widths))
    for row in rows:
        lines.append("  ".join(v.ljust(w)
                               for v, w in zip(row, widths)))
    fl = status.get("fleet", {})
    lines.append(
        f"fleet: alive {fl.get('replicas_alive')}/"
        f"{fl.get('replicas')}  goodput {fl.get('goodput_rps')} rps  "
        f"window p99 {fl.get('window_p99_ms')} ms  "
        f"tail seg {fl.get('tail_seg')}  "
        f"params vmax {fl.get('params_version_max')}"
    )
    for a in status.get("alerts", []):
        lines.append(
            f"ALERT {a.get('slo')}: burn {a.get('burn_long')}x/"
            f"{a.get('burn_short')}x action={a.get('action')}"
        )
    return "\n".join(lines)


def _status_from_url(url: str) -> dict[str, Any]:
    import urllib.request

    with urllib.request.urlopen(url.rstrip("/") + "/fleet",
                                timeout=10.0) as resp:
        return json.loads(resp.read().decode())


def _status_from_runlog(path: str) -> dict[str, Any] | None:
    last = None
    with open(path) as fp:
        for line in fp:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if rec.get("ev") == "fleet":
                last = rec
    return last


def main(argv: list[str] | None = None) -> int:
    import argparse

    from .runlog import emit

    ap = argparse.ArgumentParser(
        prog="python -m sparksched_tpu.obs.fleet",
        description="Render the fleet scoreboard from a live server's "
                    "/fleet endpoint or a run log's fleet records.")
    src = ap.add_mutually_exclusive_group(required=True)
    src.add_argument("--url", help="serve server base URL "
                                   "(e.g. http://127.0.0.1:8900)")
    src.add_argument("--runlog", help="JSONL run log with fleet "
                                      "records (post-mortem mode)")
    ap.add_argument("--watch", type=float, default=0.0, metavar="SEC",
                    help="re-scrape every SEC seconds until ^C")
    ap.add_argument("--json", action="store_true",
                    help="print raw JSON instead of the table")
    args = ap.parse_args(argv)

    while True:
        if args.url:
            status = _status_from_url(args.url)
        else:
            status = _status_from_runlog(args.runlog)
            if status is None:
                emit(f"[fleet] no fleet records in {args.runlog}")
                return 1
        emit(json.dumps(status) if args.json
             else render_status(status))
        if args.watch <= 0:
            return 0
        time.sleep(args.watch)


if __name__ == "__main__":
    raise SystemExit(main())
