"""AOT-compiled low-latency decision serving (ROADMAP item 3).

The latency side of the engine: persistent per-tenant cluster sessions
served through ahead-of-time-compiled, buffer-donated decision
programs, with a bounded-linger micro-batching front riding the
width-K `batch_policy` compaction. See `serve/aot.py` (the compiled
programs), `serve/session.py` (the session API), `serve/loadgen.py`
(seeded open-loop Poisson/MMPP load generation — ISSUE 11), and the
README "Serving" / "Serving at load" sections for the warmup protocol
and knobs.
"""

from .aot import (
    ServeOut,
    aot_compile,
    serve_callables,
    serve_decide_batch_fn,
    serve_decide_fn,
)
from .loadgen import generate_arrivals, run_open_loop
from .session import (
    ContinuousBatcher,
    InFlightCall,
    MicroBatcher,
    ServeResult,
    SessionError,
    SessionQuarantined,
    SessionStore,
    Ticket,
    front_from_config,
    store_from_config,
)

__all__ = [
    "ServeOut",
    "aot_compile",
    "serve_callables",
    "serve_decide_batch_fn",
    "serve_decide_fn",
    "generate_arrivals",
    "run_open_loop",
    "ContinuousBatcher",
    "InFlightCall",
    "MicroBatcher",
    "ServeResult",
    "SessionError",
    "SessionQuarantined",
    "SessionStore",
    "Ticket",
    "front_from_config",
    "store_from_config",
]
