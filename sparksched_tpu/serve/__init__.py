"""AOT-compiled low-latency decision serving (ROADMAP item 3).

The latency side of the engine: persistent per-tenant cluster sessions
served through ahead-of-time-compiled, buffer-donated decision
programs, with a bounded-linger micro-batching front riding the
width-K `batch_policy` compaction. See `serve/aot.py` (the compiled
programs), `serve/session.py` (the session API), `serve/loadgen.py`
(seeded open-loop Poisson/MMPP load generation — ISSUE 11), and the
README "Serving" / "Serving at load" sections for the warmup protocol
and knobs.

The network tier (ISSUE 16) rides on top: `serve/server.py` (the HTTP
front + `ServeClient` wire client) and `serve/router.py` (the
session-affinity multi-process replica fleet) — both lazy-imported
here so the in-process path never pays for them (zero-cost-off).
"""

from .aot import (
    ServeOut,
    aot_compile,
    serve_callables,
    serve_decide_batch_fn,
    serve_decide_fn,
)
from .loadgen import generate_arrivals, run_open_loop
from .session import (
    ContinuousBatcher,
    InFlightCall,
    MicroBatcher,
    RemoteResult,
    ServeResult,
    SessionError,
    SessionQuarantined,
    SessionStore,
    Ticket,
    front_from_config,
    store_from_config,
)

__all__ = [
    "ServeOut",
    "aot_compile",
    "serve_callables",
    "serve_decide_batch_fn",
    "serve_decide_fn",
    "generate_arrivals",
    "run_open_loop",
    "ContinuousBatcher",
    "InFlightCall",
    "MicroBatcher",
    "RemoteResult",
    "ServeResult",
    "SessionError",
    "SessionQuarantined",
    "SessionStore",
    "Ticket",
    "front_from_config",
    "store_from_config",
    # ISSUE 16 network tier (import from serve.server / serve.router;
    # named here for discoverability, lazily resolved via __getattr__)
    "ServeServer",
    "ServeClient",
    "server_from_config",
    "Router",
    "ReplicaSpec",
    "ReplicaDied",
]

_NET_EXPORTS = {
    "ServeServer": "server",
    "ServeClient": "server",
    "server_from_config": "server",
    "Router": "router",
    "ReplicaSpec": "router",
    "ReplicaDied": "router",
}


def __getattr__(name: str):
    mod = _NET_EXPORTS.get(name)
    if mod is None:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}"
        )
    import importlib

    return getattr(importlib.import_module(f".{mod}", __name__), name)
