"""Session-affinity scale-out: a router sharding sessions across N
serve-host replica processes (ISSUE 16, ROADMAP item 2's second step).

One `SessionStore` is single-threaded by contract (the donation
discipline: exactly one live reference to the device store), so
horizontal scale means PROCESSES, not threads — the reference repo's
mp.Pipe rollout-worker shape applied to serving. Each replica process
owns a full serving stack: its own donated store, its own batching
front (the ISSUE-13/15 `ContinuousBatcher`, pipelined when the config
says so), its own pager, its own `MetricsRegistry`, and the shared
persistent AOT compilation cache (`config.enable_compilation_cache`)
so replica cold-start pays a cache LOAD, not a recompile.

Affinity is structural, not a routing table lookup: a session created
on replica `i` gets the global id `lsid * n + i`, so
`replica_of(gsid) == gsid % n` for the session's whole life — a sid
can never silently migrate, which is what makes the per-session device
state (the whole point of the store) safe. Replica DEATH therefore
fails the replica's sessions (`ReplicaDied`, a `SessionError`), it
never reroutes them: the device state died with the process, and a
fresh session on another replica is a different episode — the caller
(the loadgen's rotation, a real client's retry) must decide that, not
the router.

The router speaks BOTH duck-typed serving protocols at once, so every
existing consumer works unchanged across the process boundary:

- the batching-front protocol (`submit`/`poll`/`flush`/`pending`) for
  `run_open_loop` and the HTTP front's pump loop;
- the store-facade protocol (`create`/`close`/`set_params`/
  `rollback_params`/`stats`) for session lifecycle and for
  `online.ParamBus` — `pump()` lands a learner publish on EVERY
  replica (host-side pytree broadcast over the pipes, applied by each
  replica between compiled calls: zero recompiles, the params-as-
  runtime-argument contract), and probation reads the router's
  aggregated decision/quarantine counters.

Everything here is host bookkeeping: the compiled serve programs are
byte-identical to the in-process path (each replica builds them
through the same `store_from_config`), which is the zero-cost-off
story — fleet off means this module is never imported on the serving
path.
"""

from __future__ import annotations

import importlib
import multiprocessing as mp
import os
import time
from dataclasses import dataclass, field
from typing import Any

import numpy as np

from ..obs.runlog import emit
from ..ownership import assert_owner
from .session import (
    RemoteResult,
    SessionError,
    SessionQuarantined,
)


class ReplicaDied(SessionError):
    """The replica owning this session exited: the session's device
    state is gone, so the session is FAILED — never rerouted."""


# error type names a replica may send back; anything else degrades to
# RuntimeError (the generic store failure class)
_ERROR_TYPES: dict[str, type[Exception]] = {
    "SessionError": SessionError,
    "SessionQuarantined": SessionQuarantined,
    "KeyError": SessionError,
    "ValueError": ValueError,
    "RuntimeError": RuntimeError,
}


def _rebuild_error(etype: str, msg: str) -> Exception:
    return _ERROR_TYPES.get(etype, RuntimeError)(msg)


@dataclass(frozen=True)
class ReplicaSpec:
    """Everything a replica process needs to rebuild a full serving
    stack, picklable across an mp spawn boundary. `builder` names a
    module-level callable (`"module.path:function"`) returning
    `(env_params, bank, scheduler)` — replicas REBUILD rather than
    unpickle the stack, so a seeded builder gives every replica
    bit-identical initial params (`DecimaScheduler.init_params` is
    deterministic in its seed), which is what lets a later fleet-wide
    `set_params` assume one common aval structure."""

    builder: str
    builder_kwargs: dict[str, Any] = field(default_factory=dict)
    serve_cfg: dict[str, Any] = field(default_factory=dict)
    compile_cache: bool = True
    trace: bool = False
    # jax platform FOR THE REPLICAS ("" = inherit the parent's env).
    # The chip case: one device client per chip means N replica
    # processes cannot all claim the parent's accelerator — a fleet on
    # a chip host runs its replicas on host cores (platform="cpu")
    # unless each process is given its own device slice via env.
    platform: str = ""


def resolve_builder(path: str):
    mod, sep, fn = path.partition(":")
    if not sep or not mod or not fn:
        raise ValueError(
            f"builder must be 'module.path:function', got {path!r}"
        )
    return getattr(importlib.import_module(mod), fn)


def _poison_session(store, sid: int) -> None:
    """Test hook (the chaos-tier pattern): corrupt one session's
    persistent per-job completion clock with NaN so its next decide
    trips the H_NONFINITE_TIME health sentinel — exactly the poison
    tests/test_serve.py injects in-process, made reachable across the
    process boundary so the quarantine-isolation invariant is testable
    against a real fleet."""
    import jax.numpy as jnp

    slot = int(store._slot_of[sid])
    if slot < 0:
        raise SessionError(f"session {sid} is not resident")
    g, l = divmod(slot, store.group_slots)
    st = store._stores[g]
    store._stores[g] = st.replace(
        env=st.env.replace(
            job_t_completed=st.env.job_t_completed.at[l].set(jnp.nan)
        )
    )


def _replica_main(conn, idx: int, spec: ReplicaSpec) -> None:
    """The replica process body: build the serving stack, handshake,
    then loop — drain pipe commands, pump the front, ship resolved
    tickets back. Runs until a `stop` command or pipe EOF."""
    try:
        from ..config import (
            enable_compilation_cache,
            honor_jax_platforms_env,
        )
        from ..obs.metrics import MetricsRegistry
        from .session import front_from_config, store_from_config

        if spec.platform:
            os.environ["JAX_PLATFORMS"] = spec.platform
        honor_jax_platforms_env()
        if spec.compile_cache:
            enable_compilation_cache()
        params, bank, scheduler = resolve_builder(spec.builder)(
            **spec.builder_kwargs
        )
        registry = MetricsRegistry()
        cfg = dict(spec.serve_cfg)
        # network + observability-plane keys ride the same `serve:`
        # block but belong to the router/server layer — strip before
        # the store sees them
        for k in ("host", "port", "replicas", "quota_sessions",
                  "quota_inflight", "collect", "collect_period_s",
                  "slo", "hostprof"):
            cfg.pop(k, None)
        store = store_from_config(
            cfg, params, bank, scheduler, metrics=registry,
            trace=spec.trace,
        )
        front = front_from_config(
            cfg, store, metrics=registry, trace=spec.trace,
        )
        # ISSUE 18: a ring-on replica parks drained trajectory chunks
        # (already host numpy, in stream order) in this outbox instead
        # of a local collector; the router's `ring_pump` fetches the
        # whole backlog in ONE `ring_chunks` round-trip — the batched
        # wire feed that replaces per-decision RPCs to the learner
        ring_out: list[tuple] = []
        if getattr(store, "_ring_on", False):
            store.ring_sink = ring_out.append
        conn.send(("ready", idx, {
            "capacity": store.capacity, "pid": os.getpid(),
            "front": front.front_name,
        }))
    except Exception as e:  # pragma: no cover - boot failure path
        try:
            conn.send(("boot_error", idx, type(e).__name__, str(e)))
        finally:
            conn.close()
        return

    def reply(rid: int, payload: Any) -> None:
        conn.send(("reply", rid, payload))

    def reply_err(rid: int, e: Exception) -> None:
        conn.send(("reply_err", rid, type(e).__name__, str(e)))

    tracked: dict[int, Any] = {}  # rid -> Ticket
    stop = False
    try:
        while True:
            timeout = 0.0 if (tracked or front.pending) else 0.05
            while conn.poll(timeout):
                msg = conn.recv()
                op, rid = msg[0], msg[1]
                try:
                    if op == "submit":
                        tracked[rid] = front.submit(msg[2])
                    elif op == "create":
                        reply(rid, {"sid": store.create(seed=msg[2])})
                    elif op == "close":
                        store.close(msg[2])
                        reply(rid, {"closed": msg[2]})
                    elif op == "set_params":
                        _, _, p, version, origin, reason, good = msg
                        reply(rid, {"version": store.set_params(
                            p, version=version, origin=origin,
                            reason=reason, mark_good=good,
                        )})
                    elif op == "rollback":
                        reply(rid, {
                            "version": store.rollback_params(msg[2])
                        })
                    elif op == "metrics":
                        reply(rid, (registry, dict(store.stats)))
                    elif op == "poison":
                        _poison_session(store, msg[2])
                        reply(rid, {"poisoned": msg[2]})
                    elif op == "ring_chunks":
                        # msg[2] (force) drains the device rings to
                        # the outbox first; otherwise ship whatever
                        # the normal triggers (cadence / harvest-idle
                        # / close / swap) already landed there
                        if msg[2]:
                            store.drain_ring(wait=True)
                        ents = list(ring_out)
                        ring_out.clear()
                        reply(rid, ents)
                    elif op == "stop":
                        stop = True
                        front.flush()
                        if getattr(store, "_ring_on", False):
                            store.drain_ring(wait=True)
                        reply(rid, {"stopped": idx})
                    else:
                        reply_err(rid, ValueError(
                            f"unknown replica op {op!r}"
                        ))
                except Exception as e:
                    reply_err(rid, e)
                timeout = 0.0
            front.poll()
            for rid in [r for r, t in tracked.items() if t.ready]:
                t = tracked.pop(rid)
                if t.error is not None:
                    conn.send(("result", rid, None,
                               (type(t.error).__name__, str(t.error))))
                else:
                    d = t.result.to_dict()
                    d["replica"] = idx
                    if t.trace is not None:
                        d["spans_ms"] = t.trace.offsets_ms()
                    conn.send(("result", rid, d, None))
            if stop and not tracked and not front.pending:
                return
    except (EOFError, BrokenPipeError, OSError):
        return  # router side went away: exit quietly
    finally:
        conn.close()


class _Replica:
    __slots__ = ("idx", "proc", "conn", "dead", "sessions", "info")

    def __init__(self, idx, proc, conn) -> None:
        self.idx = idx
        self.proc = proc
        self.conn = conn
        self.dead = False
        self.sessions = 0  # live sessions, the placement load signal
        self.info: dict[str, Any] = {}


class RouterTicket:
    """`Ticket`'s fleet twin: resolved by `Router.poll` when the
    owning replica ships the result (or dies)."""

    __slots__ = ("session_id", "submitted_at", "result", "error",
                 "trace")

    def __init__(self, session_id: int) -> None:
        self.session_id = session_id
        self.submitted_at = time.perf_counter()
        self.result: RemoteResult | None = None
        self.error: Exception | None = None
        self.trace = None

    @property
    def ready(self) -> bool:
        return self.result is not None or self.error is not None


class Router:
    """The session-affinity fleet front. See the module docstring for
    the protocol; construction SPAWNS `replicas` worker processes and
    blocks until every one handshakes ready (raising, and reaping the
    fleet, if any replica fails to boot)."""

    def __init__(self, spec: ReplicaSpec, replicas: int = 2, *,
                 metrics=None, runlog=None, collector=None,
                 ring_period_s: float = 0.25,
                 start_timeout_s: float = 300.0) -> None:
        if replicas < 1:
            raise ValueError(f"need >= 1 replica, got {replicas}")
        self.spec = spec
        self.n = int(replicas)
        self.metrics = metrics
        self.runlog = runlog
        # ISSUE 18: the fleet-level trajectory sink (a
        # `TrajectoryBuffer`, duck-typed `ingest_chunk`/`on_close`).
        # Every replica's ring chunks land here with session ids
        # remapped to the global space, so one learner feeds off the
        # whole fleet without per-decision RPCs.
        self.collector = collector
        self.ring_period_s = float(ring_period_s)
        self._ring_next = 0.0
        self.front_name = f"router{self.n}"
        self.params_version = 0
        self.stats: dict[str, int] = {
            "serve_decisions": 0,
            "serve_quarantines": 0,
            "serve_capacity_rejections": 0,
            "serve_param_swaps": 0,
            "serve_param_rollbacks": 0,
            "serve_param_version": 0,
            "router_replica_deaths": 0,
            "router_sessions_failed": 0,
        }
        self._rid = 0
        self._tickets: dict[int, tuple[int, RouterTicket]] = {}
        self._replies: dict[int, tuple[Any, Exception | None]] = {}
        self._reply_owner: dict[int, int] = {}
        self._sid_map: dict[int, int] = {}  # gsid -> local sid
        self._failed: set[int] = set()
        self._stopped = False
        ctx = mp.get_context("spawn")
        self._replicas: list[_Replica] = []
        try:
            for i in range(self.n):
                parent, child = ctx.Pipe()
                proc = ctx.Process(
                    target=_replica_main, args=(child, i, spec),
                    daemon=True, name=f"serve-replica-{i}",
                )
                proc.start()
                child.close()
                self._replicas.append(_Replica(i, proc, parent))
            deadline = time.monotonic() + start_timeout_s
            for r in self._replicas:
                budget = deadline - time.monotonic()
                if budget <= 0 or not r.conn.poll(budget):
                    raise RuntimeError(
                        f"replica {r.idx} did not come up within "
                        f"{start_timeout_s:g}s"
                    )
                try:
                    msg = r.conn.recv()
                except (EOFError, OSError) as e:
                    raise RuntimeError(
                        f"replica {r.idx} died during boot "
                        f"(spawned processes re-import __main__: "
                        f"run from a real script/module)"
                    ) from e
                if msg[0] != "ready":
                    raise RuntimeError(
                        f"replica {r.idx} failed to boot: "
                        f"{msg[2] if len(msg) > 2 else msg!r}: "
                        f"{msg[3] if len(msg) > 3 else ''}"
                    )
                r.info = msg[2]
        except Exception:
            self.stop(timeout_s=5.0)
            raise
        emit(
            f"[router] fleet up: {self.n} replica(s), capacity "
            f"{sum(r.info.get('capacity', 0) for r in self._replicas)}"
            f" sessions, front {self._replicas[0].info.get('front')}"
        )

    # -- plumbing ----------------------------------------------------------

    def replica_of(self, gsid: int) -> int:
        return gsid % self.n

    def _next_rid(self) -> int:
        self._rid += 1
        return self._rid

    def _send(self, r: _Replica, msg: tuple) -> None:
        try:
            r.conn.send(msg)
        except (BrokenPipeError, OSError, EOFError):
            self._mark_dead(r)
            raise ReplicaDied(
                f"replica {r.idx} died (send failed)"
            ) from None

    def _mark_dead(self, r: _Replica) -> None:
        if r.dead:
            return
        r.dead = True
        self.stats["router_replica_deaths"] += 1
        try:
            r.conn.close()
        except OSError:
            pass
        # fail everything the replica owned: in-flight tickets error,
        # its sessions join the failed set — NOT rerouted (the device
        # state died with the process; see module docstring)
        failed_sids = [g for g in self._sid_map
                       if self.replica_of(g) == r.idx]
        for g in failed_sids:
            self._failed.add(g)
            del self._sid_map[g]
        self.stats["router_sessions_failed"] += len(failed_sids)
        for rid, (owner, tk) in list(self._tickets.items()):
            if owner == r.idx:
                tk.error = ReplicaDied(
                    f"replica {r.idx} died with the request in flight"
                )
                del self._tickets[rid]
        for rid, owner in list(self._reply_owner.items()):
            if owner == r.idx:
                self._replies[rid] = (None, ReplicaDied(
                    f"replica {r.idx} died before replying"
                ))
                del self._reply_owner[rid]
        if self.metrics is not None:
            self.metrics.counter("router_replica_deaths")
        emit(
            f"[router] replica {r.idx} died; {len(failed_sids)} "
            "session(s) marked failed (sessions are never rerouted)"
        )

    def _dispatch(self, r: _Replica, msg: tuple) -> bool:
        kind, rid = msg[0], msg[1]
        if kind == "result":
            owner_tk = self._tickets.pop(rid, None)
            if owner_tk is None:
                return False
            tk = owner_tk[1]
            if msg[3] is not None:
                tk.error = _rebuild_error(*msg[3])
            else:
                tk.result = RemoteResult(msg[2])
                self.stats["serve_decisions"] += 1
                if tk.result.health_mask:
                    self.stats["serve_quarantines"] += 1
            return True
        if kind == "reply":
            self._reply_owner.pop(rid, None)
            self._replies[rid] = (msg[2], None)
            return True
        if kind == "reply_err":
            self._reply_owner.pop(rid, None)
            self._replies[rid] = (None, _rebuild_error(msg[2], msg[3]))
            return True
        return False

    def _drain(self) -> bool:
        moved = False
        for r in self._replicas:
            if r.dead:
                continue
            try:
                while r.conn.poll(0):
                    moved |= self._dispatch(r, r.conn.recv())
            except (EOFError, BrokenPipeError, OSError):
                if self._stopped:  # clean shutdown: EOF is expected
                    r.dead = True
                else:
                    self._mark_dead(r)
                moved = True
                continue
            # a replica exiting AFTER its stop-reply is a clean
            # shutdown, not a death — only an un-asked-for exit fails
            # its sessions
            if not self._stopped and not r.proc.is_alive():
                self._mark_dead(r)
                moved = True
        return moved

    def _call(self, r: _Replica, msg_tail: tuple,
              timeout_s: float = 120.0) -> Any:
        """One synchronous round-trip to a replica (create / close /
        set_params / metrics ...). Results for OTHER requests keep
        flowing while we wait — the pipes are drained, not blocked."""
        rid = self._next_rid()
        self._reply_owner[rid] = r.idx
        self._send(r, (msg_tail[0], rid, *msg_tail[1:]))
        deadline = time.monotonic() + timeout_s
        while rid not in self._replies:
            self._drain()
            if rid in self._replies:
                break
            if time.monotonic() > deadline:
                del self._reply_owner[rid]
                raise RuntimeError(
                    f"replica {r.idx} did not answer {msg_tail[0]!r} "
                    f"within {timeout_s:g}s"
                )
            time.sleep(2e-4)
        payload, err = self._replies.pop(rid)
        if err is not None:
            raise err
        return payload

    def _alive(self) -> list[_Replica]:
        return [r for r in self._replicas if not r.dead]

    # -- store facade ------------------------------------------------------

    def create(self, seed: int | None = None) -> int:
        """Place a new session on the least-loaded live replica;
        returns the GLOBAL session id (`gsid % n` names the owner for
        the session's whole life). Raises RuntimeError when the fleet
        is out of capacity — the store contract, so rotation and
        429-mapping work unchanged."""
        alive = self._alive()
        if not alive:
            self.stats["serve_capacity_rejections"] += 1
            raise RuntimeError("serve fleet has no live replicas")
        for r in sorted(alive, key=lambda r: r.sessions):
            try:
                payload = self._call(r, ("create", seed))
            except ReplicaDied:
                continue
            except RuntimeError as e:
                if "full" in str(e):
                    continue  # try the next-least-loaded replica
                raise
            lsid = payload["sid"]
            gsid = lsid * self.n + r.idx
            self._sid_map[gsid] = lsid
            self._failed.discard(gsid)
            r.sessions += 1
            return gsid
        self.stats["serve_capacity_rejections"] += 1
        if self.metrics is not None:
            self.metrics.counter("serve_capacity_rejections")
        raise RuntimeError(
            f"serve fleet full ({self.n} replicas); close sessions "
            "first"
        )

    def close(self, gsid: int) -> None:
        if gsid in self._failed:
            # the owning replica is gone: closing a failed session is
            # a no-op reclaim, not an error (the loadgen's teardown
            # closes every session it still holds)
            self._failed.discard(gsid)
            return
        lsid = self._sid_map.pop(gsid, None)
        if lsid is None:
            raise SessionError(f"unknown session {gsid}")
        r = self._replicas[self.replica_of(gsid)]
        if r.dead:
            return
        self._call(r, ("close", lsid))
        r.sessions -= 1

    def set_params(self, model_params, version: int | None = None,
                   origin: str = "swap", reason: str | None = None,
                   mark_good: bool = True) -> int:
        """Fleet-wide hot swap: broadcast the (host-materialized)
        pytree to every live replica, each of which applies it between
        compiled calls via `SessionStore.set_params` — zero recompiles
        on every member. Returns the applied version (identical across
        the fleet: the explicit `version` stamp, or each store's
        increment from a common history)."""
        import jax

        host_params = jax.device_get(model_params)
        applied = None
        for r in self._alive():
            try:
                out = self._call(r, (
                    "set_params", host_params, version, origin,
                    reason, mark_good,
                ))
            except ReplicaDied:
                continue
            applied = out["version"]
        if applied is None:
            raise RuntimeError("set_params: no live replicas")
        prev_version = self.params_version
        self.params_version = applied
        self.stats["serve_param_swaps"] += 1
        self.stats["serve_param_version"] = applied
        if self.metrics is not None:
            self.metrics.counter("serve_param_swaps")
            self.metrics.gauge("serve_param_version", applied)
        if self.runlog is not None:
            self.runlog.params_swap(
                applied, prev_version=prev_version,
                action=origin, reason=reason,
            )
        return applied

    def rollback_params(self, reason: str | None = None) -> int:
        applied = None
        for r in self._alive():
            try:
                out = self._call(r, ("rollback", reason))
            except ReplicaDied:
                continue
            applied = out["version"]
        if applied is None:
            raise RuntimeError("rollback_params: no live replicas")
        self.params_version = applied
        self.stats["serve_param_rollbacks"] += 1
        self.stats["serve_param_version"] = applied
        return applied

    def poison(self, gsid: int) -> None:
        """Test hook: trip the health sentinel on one session (see
        `_poison_session`)."""
        lsid = self._sid_map[gsid]
        self._call(self._replicas[self.replica_of(gsid)],
                   ("poison", lsid))

    def registry(self):
        """The fleet's merged `MetricsRegistry`: every live replica's
        registry folded together (counters add, histograms merge —
        the documented multi-worker aggregation path), plus the
        router's own, for one `/metrics` exposition."""
        from ..obs.metrics import MetricsRegistry

        agg = MetricsRegistry()
        for r in self._alive():
            try:
                reg, _stats = self._call(r, ("metrics",))
            except (ReplicaDied, RuntimeError):
                continue
            agg.merge(reg)
        if self.metrics is not None:
            agg.merge(self.metrics)
        return agg

    def fleet_stats(self) -> dict[str, int]:
        """Aggregated store stats across live replicas (ints summed),
        with the router's own counters riding along."""
        agg: dict[str, int] = dict(self.stats)
        for r in self._alive():
            try:
                _reg, stats = self._call(r, ("metrics",))
            except (ReplicaDied, RuntimeError):
                continue
            for k, v in stats.items():
                if isinstance(v, (int, float)):
                    agg[k] = agg.get(k, 0) + v
        return agg

    def replica_samples(self) -> list[dict[str, Any]]:
        """Per-replica labeled scrape (ISSUE 17): ONE `metrics`
        roundtrip per live replica returning each replica's OWN
        registry + store stats, unmerged — the fleet collector's and
        the labeled `/metrics` exposition's input. Dead replicas are
        reported (alive=False) rather than dropped, so the scoreboard
        shows the hole instead of silently shrinking."""
        out: list[dict[str, Any]] = []
        for r in self._replicas:
            sample: dict[str, Any] = {
                "replica": str(r.idx),
                "alive": not r.dead and r.proc.is_alive(),
                "sessions": r.sessions,
                "registry": None,
                "stats": None,
            }
            if sample["alive"]:
                try:
                    reg, stats = self._call(r, ("metrics",))
                    sample["registry"] = reg
                    sample["stats"] = stats
                except (ReplicaDied, RuntimeError):
                    sample["alive"] = False
            out.append(sample)
        return out

    # -- the fleet trajectory feed (ISSUE 18) ------------------------------

    def ring_pump(self, force: bool = False) -> int:
        """Fetch every live replica's accumulated ring chunks in ONE
        `ring_chunks` round-trip per replica and feed the fleet-level
        `collector`, remapping each chunk's whole `sid` array (and
        every close event) from the replica's local ids to the global
        space in one vectorized step — `gsid = lsid * n + idx`, the
        affinity map. `force=True` makes each replica drain its
        device rings first (the teardown / end-of-window path).
        Returns the number of records ingested. No-op without a
        collector."""
        if self.collector is None:
            return 0
        moved = 0
        for r in self._alive():
            try:
                ents = self._call(r, ("ring_chunks", bool(force)))
            except (ReplicaDied, RuntimeError):
                continue
            for ent in ents:
                if ent[0] == "chunk":
                    chunk = ent[1]
                    lsid = np.asarray(chunk.sid)
                    moved += int(lsid.shape[0])
                    self.collector.ingest_chunk(chunk.replace(
                        sid=(lsid * self.n + r.idx).astype(lsid.dtype)
                    ))
                else:  # ("close", lsid, quarantined)
                    self.collector.on_close(
                        int(ent[1]) * self.n + r.idx,
                        quarantined=bool(ent[2]),
                    )
        return moved

    def _maybe_ring_pump(self) -> None:
        """The `poll()`-cadence half: one fleet sweep per
        `ring_period_s`, so the pump loop that already drives the
        pipes ships trajectories too — no extra thread, no
        per-decision traffic."""
        if self.collector is None:
            return
        now = time.monotonic()
        if now >= self._ring_next:
            self._ring_next = now + self.ring_period_s
            self.ring_pump()

    # -- batching-front facade ---------------------------------------------

    def submit(self, gsid: int) -> RouterTicket:
        assert_owner(self, "serve-pump", "fleet-collector")
        tk = RouterTicket(gsid)
        if gsid in self._failed:
            tk.error = ReplicaDied(
                f"session {gsid}'s replica died; the session is "
                "failed, not rerouted"
            )
            return tk
        lsid = self._sid_map.get(gsid)
        if lsid is None:
            tk.error = SessionError(f"unknown session {gsid}")
            return tk
        r = self._replicas[self.replica_of(gsid)]
        if r.dead:
            tk.error = ReplicaDied(
                f"session {gsid}'s replica died; the session is "
                "failed, not rerouted"
            )
            return tk
        rid = self._next_rid()
        self._tickets[rid] = (r.idx, tk)
        try:
            self._send(r, ("submit", rid, lsid))
        except ReplicaDied:
            pass  # _mark_dead already errored the ticket
        return tk

    @property
    def pending(self) -> int:
        return len(self._tickets)

    def poll(self) -> bool:
        assert_owner(self, "serve-pump", "fleet-collector")
        moved = self._drain()
        self._maybe_ring_pump()
        return moved

    def flush(self, timeout_s: float = 120.0) -> None:
        deadline = time.monotonic() + timeout_s
        while self._tickets:
            if not self._drain():
                time.sleep(2e-4)
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"flush: {len(self._tickets)} request(s) still "
                    f"unresolved after {timeout_s:g}s"
                )

    # -- lifecycle ---------------------------------------------------------

    def stop(self, timeout_s: float = 30.0) -> None:
        """Drain and reap the fleet. Idempotent; stragglers are
        terminated."""
        if self._stopped:
            return
        self._stopped = True
        if self.collector is not None:
            try:  # last full sweep: no trajectory stranded in a ring
                self.ring_pump(force=True)
            except RuntimeError:
                pass
        for r in self._replicas:
            if r.dead or not r.proc.is_alive():
                continue
            try:
                self._call(r, ("stop",), timeout_s=timeout_s)
            except (RuntimeError, ReplicaDied):
                pass
        for r in self._replicas:
            if r.proc.is_alive():
                r.proc.join(timeout=timeout_s)
            if r.proc.is_alive():  # pragma: no cover - reap path
                r.proc.terminate()
                r.proc.join(timeout=5.0)
            try:
                r.conn.close()
            except OSError:
                pass

    def __enter__(self) -> "Router":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()
