"""AOT-compiled serve programs: the latency side of the engine.

Everything else in the repo is throughput-shaped (big-batch rollout
collection); the deployment story of the source paper — Decima
scheduling a live Spark cluster — is a request/response loop: one
cluster state arrives, one decision leaves, microseconds of budget.
This module builds that decision as an ahead-of-time-compiled XLA
executable over a persistent on-device session *store*:

- ``serve_decide``: ONE session's decision. The store (a [C]-stacked
  `LoopState`, one live cluster per tenant) is gathered at a dynamic
  slot index, the policy runs unbatched (observe -> Decima score ->
  masked sample/argmax), the decision is applied and drained to the
  next decision point (`env/flat_loop.py:apply_and_drain` — the same
  per-lane body the single-eval training collectors run, so serving
  and training cannot drift on decision semantics), and the updated
  lane is scattered back. An optional forced action (`step` in the
  session API) overrides the policy's pick under a traced select, so
  policy-decide and caller-step share one compiled program.
- ``serve_decide_batch``: up to K sessions in ONE call — gather K
  slots, ONE batched policy evaluation (`DecimaScheduler.batch_policy`
  with the width-K active-job compaction at batch level), vmapped
  apply-and-drain, scatter back. Padding slots carry index C (out of
  range): their gathers clamp, their scatters `mode="drop"`, and their
  outputs are masked by `valid`, so a partial batch mutates exactly
  the sessions it names.

Both programs DONATE the store argument (`donate_argnums=(0,)`): XLA
aliases the output store onto the input buffers, so a steady-state
decision allocates nothing store-sized — the [C] cluster states are
updated in place (`tests/test_serve.py` pins the aliasing: the donated
input is deleted and the output leaf reuses its buffer). Compilation is
`jax.jit(...).lower(...).compile()` at session-store construction:
after the warmup call there is no tracing, no dispatch-cache lookup
miss, and no recompile on the serve path (pinned via the runlog
recompile events).

The per-decision health sentinel (`env/health.py:state_health` over
the post-drain state + the span reward, ISSUE 9) rides every output:
the session layer quarantines a session whose mask is non-zero instead
of serving it again.

Since ISSUE 14 (the online learning loop) the model parameters are an
ORDINARY RUNTIME ARGUMENT of both compiled programs rather than
closure constants baked into the executable: `policy_fn` takes
`(model_params, rng, obs)` (`DecimaScheduler.serve_param_policies`),
and the compiled signature is `(store, model_params, ...)`. Swapping
to a new parameter version is therefore just passing a different
argument value of identical avals — zero retracing, zero recompiles
(pinned via the runlog jit hooks, tests/test_online.py), which is what
makes hot param swap into live serving possible at all. The optional
`record` flag (static, compile-time) makes `ServeOut` additionally
carry the decision's `StoredObs` record — the same per-decision
observation schema the training collectors scatter
(`trainers/rollout.py:store_obs`) — so served decisions can feed the
online `TrajectoryBuffer` without a second observe pass; with
`record=False` the traced program is byte-identical to the pre-record
pin (CI: the analysis registry re-measures the record-off programs).
"""

from __future__ import annotations

import time
from typing import Any, Callable

import jax
import jax.numpy as jnp
from flax import struct

from ..config import EnvParams
from ..env.flat_loop import (
    LoopState,
    TrajRing,
    _lane_done,
    apply_and_drain,
    aux_action_fields,
    ring_append,
    take_slot,
    write_slot,
)
from ..env.health import reward_health, state_health
from ..env.observe import observe
from ..obs.tracing import annotate
from ..workload.bank import WorkloadBank

_i32 = jnp.int32


class ServeOut(struct.PyTreeNode):
    """One served decision (leading [K] axis on the batch program).

    `valid` marks real (non-padding) batch slots; `decided` whether the
    lane actually recorded a decision (False for a lane whose episode
    was already over — `done` — which the session layer reports instead
    of serving). `health_mask` is the i32 sentinel bitmask
    (env/health.py bit table) over the post-drain state and the span
    reward; non-zero quarantines the session host-side."""

    stage_idx: jnp.ndarray  # i32; flat padded node index (-1 = none)
    job_idx: jnp.ndarray  # i32; padded job id
    num_exec: jnp.ndarray  # i32; 1-based executor count (env convention)
    lgprob: jnp.ndarray  # f32; log-prob of the chosen action
    decided: jnp.ndarray  # bool; lane recorded a decision
    done: jnp.ndarray  # bool; episode over after the drain
    reward: jnp.ndarray  # f32; span reward (decision -> next decision)
    dt: jnp.ndarray  # f32; sim-time advance of the span
    wall_time: jnp.ndarray  # f32; lane wall clock after the drain
    health_mask: jnp.ndarray  # i32; sentinel bitmask (0 = healthy)
    valid: jnp.ndarray  # bool; real (non-padding) slot
    # record=True programs only (ISSUE 14): the decision's StoredObs
    # record (trainers/rollout.py schema). Meaningful only where
    # `decided & valid` — padding lanes carry the clamped lane's
    # speculative view, which the host-side consumer masks out. None
    # (an empty pytree) on record-off programs, so their traced jaxpr
    # is unchanged.
    obs: Any = None


class RingRec(struct.PyTreeNode):
    """One trajectory record as stored in the device ring (ISSUE 18).

    The FULL per-decision record — everything `TrajectoryBuffer.add`
    reads off a `ServeResult` plus the reassembly stamps — so the ring
    programs' `ServeOut` can drop its `obs` payload entirely and the
    host stops materializing records per decision. `sid` (host-assigned
    session id) and `seq` (the lane's decision count after this
    decision) let the host reassemble per-session streams from a drain
    that interleaves sessions; `params_version` stamps which parameter
    version served the decision (the swap can land mid-ring, so the
    stamp must ride each record, not the drain)."""

    sid: jnp.ndarray  # i32; host-assigned session id
    seq: jnp.ndarray  # i32; lane decision count after this decision
    params_version: jnp.ndarray  # i32; param version that decided
    stage_idx: jnp.ndarray  # i32; flat padded node index
    job_idx: jnp.ndarray  # i32
    num_exec: jnp.ndarray  # i32; 1-based (env convention)
    lgprob: jnp.ndarray  # f32
    reward: jnp.ndarray  # f32
    dt: jnp.ndarray  # f32
    wall_time: jnp.ndarray  # f32
    done: jnp.ndarray  # bool; episode over after the drain
    health_mask: jnp.ndarray  # i32; sentinel bitmask (0 = healthy)
    obs: Any = None  # the decision's StoredObs record


def init_ring(R: int, params: EnvParams, state) -> TrajRing:
    """A zero-filled [R]-record `TrajRing` matching `state`'s shapes —
    what the session store allocates per slot group when
    `record=True, ring=R`. Works on a concrete or abstract `EnvState`
    (shapes are all that matter)."""
    from ..trainers.rollout import store_obs

    def rec(st):
        z = _i32(0)
        zf = jnp.float32(0.0)
        return RingRec(
            sid=z, seq=z, params_version=z, stage_idx=z, job_idx=z,
            num_exec=z, lgprob=zf, reward=zf, dt=zf, wall_time=zf,
            done=jnp.bool_(False), health_mask=z,
            obs=store_obs(observe(params, st), st),
        )

    shp = jax.eval_shape(rec, state)
    rec0 = jax.tree_util.tree_map(
        lambda a: jnp.zeros((int(R),) + tuple(a.shape), a.dtype), shp
    )
    return TrajRing(cursor=_i32(0), rec=rec0)


# engine knobs of the serve drain — the round-5 on-chip calibration
# (be=8, fulfill_bulk on, one fused cycle), the same defaults the
# single-eval collectors ship
SERVE_KNOBS: dict[str, Any] = {
    "event_bulk": True,
    "bulk_events": 8,
    "fulfill_bulk": True,
    "bulk_cycles": 1,
    "bulk_fused": True,
}


def _decide_one(
    params: EnvParams,
    bank: WorkloadBank,
    policy_fn: Callable,
    model_params: Any,
    ls: LoopState,
    key: jax.Array,
    force_stage: jnp.ndarray,
    force_nexec: jnp.ndarray,
    use_force: jnp.ndarray,
    knobs: dict[str, Any],
    record: bool = False,
) -> tuple[LoopState, ServeOut]:
    """One lane's full decision: observe -> policy (or the forced
    action under `use_force`) -> apply_and_drain -> health sentinel.
    `model_params` is the policy's parameter pytree, a runtime
    argument (the hot-swap contract — see the module docstring)."""
    k_pol, k_env = jax.random.split(key)
    env0 = ls.env
    was_done = _lane_done(env0)
    obs = observe(params, env0)
    stage_idx, num_exec, aux = policy_fn(model_params, k_pol, obs)
    lgprob, job, _ = aux_action_fields(
        aux, stage_idx, num_exec, params.max_stages
    )
    stage_idx = jnp.where(use_force, force_stage, stage_idx).astype(_i32)
    num_exec = jnp.where(use_force, force_nexec, num_exec).astype(_i32)
    job = jnp.where(
        use_force,
        jnp.where(stage_idx >= 0, stage_idx // params.max_stages, 0),
        job,
    ).astype(_i32)
    lgprob = jnp.where(use_force, 0.0, lgprob).astype(jnp.float32)
    ls2, (decided, reward, dt, reset) = apply_and_drain(
        params, bank, ls, stage_idx, num_exec, k_env,
        auto_reset=False, **knobs,
    )
    hm = state_health(ls2.env, prev=env0, resetting=reset) | reward_health(
        reward
    )
    # a lane that was already done is frozen by the engine: report it
    # rather than claim a decision happened
    rec_obs = None
    if record:
        from ..trainers.rollout import store_obs

        rec_obs = store_obs(obs, env0)
    out = ServeOut(
        stage_idx=jnp.where(decided, stage_idx, -1).astype(_i32),
        job_idx=job,
        num_exec=num_exec,
        lgprob=lgprob,
        decided=decided,
        done=_lane_done(ls2.env),
        reward=reward,
        dt=dt,
        wall_time=ls2.env.wall_time,
        health_mask=jnp.where(was_done, 0, hm).astype(_i32),
        valid=jnp.bool_(True),
        obs=rec_obs,
    )
    return ls2, out


def serve_decide_fn(
    params: EnvParams,
    bank: WorkloadBank,
    policy_fn: Callable,
    knobs: dict[str, Any] | None = None,
    shard=None,
    record: bool = False,
) -> Callable:
    """The single-session store program:
    `(store [C], model_params, slot, key, force_stage, force_nexec,
    use_force) -> (store [C], ServeOut)`. Gather one lane, decide
    unbatched, scatter back; the store argument is meant to be donated
    at compile time, while `model_params` (the policy weights) is a
    plain argument — new versions swap in with zero recompiles.
    With `shard` (a `NamedSharding` over the store's leading [C] axis,
    ISSUE 13), the store is sharding-constrained at entry and exit so
    the SPMD partitioner keeps the [C] session stack distributed over
    the `dp` mesh instead of gathering it to one device around the
    slot update — sessions are embarrassingly parallel, so the only
    cross-device traffic is the served slot itself. `record` (static,
    ISSUE 14) adds the decision's `StoredObs` to the output."""
    kn = SERVE_KNOBS | (knobs or {})

    def fn(store: LoopState, model_params, slot, key, force_stage,
           force_nexec, use_force):
        with annotate("serve/decide"):
            if shard is not None:
                store = jax.lax.with_sharding_constraint(store, shard)
            ls = take_slot(store, slot)
            ls2, out = _decide_one(
                params, bank, policy_fn, model_params, ls, key,
                force_stage, force_nexec, use_force, kn, record=record,
            )
            store2 = write_slot(store, slot, ls2)
            if shard is not None:
                store2 = jax.lax.with_sharding_constraint(store2, shard)
        return store2, out

    return fn


def serve_decide_batch_fn(
    params: EnvParams,
    bank: WorkloadBank,
    batch_policy_fn: Callable,
    batch: int,
    knobs: dict[str, Any] | None = None,
    shard=None,
    record: bool = False,
) -> Callable:
    """The micro-batched store program:
    `(store [C], model_params, slots [K], key) ->
    (store [C], ServeOut-of-[K])`.
    ONE batched policy evaluation over the K gathered sessions (the
    width-K `batch_policy` compaction is exactly a serving-batch
    primitive), vmapped apply-and-drain, scatter back. Padding slots
    carry index C: gathers clamp to a real lane whose results are then
    dropped by the `mode="drop"` scatter and masked in the output.
    `model_params` is a runtime argument (one value per compiled call,
    so every decision of a batch reads the SAME parameter version — no
    torn reads across a batch, test-pinned). `shard` (ISSUE 13)
    constrains the [C] store axis to the `dp` mesh at entry and exit;
    `record` (static, ISSUE 14) adds per-lane `StoredObs` records."""
    kn = SERVE_KNOBS | (knobs or {})
    K = int(batch)

    def fn(store: LoopState, model_params, slots, key):
        with annotate("serve/decide_batch"):
            if shard is not None:
                store = jax.lax.with_sharding_constraint(store, shard)
            C = store.mode.shape[0]
            valid = slots < C
            idx = jnp.minimum(slots, C - 1)
            ls = take_slot(store, idx)
            env0 = ls.env
            was_done = jax.vmap(_lane_done)(env0)
            k_pol, k_env = jax.random.split(key)
            obs = jax.vmap(lambda e: observe(params, e))(env0)
            stage_idx, num_exec, aux = batch_policy_fn(
                model_params, k_pol, obs
            )
            lgprob, job, _ = aux_action_fields(
                aux, stage_idx, num_exec, params.max_stages
            )
            lgprob = jnp.broadcast_to(
                jnp.asarray(lgprob, jnp.float32), stage_idx.shape
            )
            ls2, (decided, reward, dt, reset) = jax.vmap(
                lambda l, si, ne, k: apply_and_drain(
                    params, bank, l, si, ne, k, auto_reset=False, **kn
                )
            )(ls, stage_idx, num_exec, jax.random.split(k_env, K))
            hm = jax.vmap(state_health)(
                ls2.env, env0, reset
            ) | reward_health(reward)
            rec_obs = None
            if record:
                from ..trainers.rollout import store_obs

                rec_obs = jax.vmap(store_obs)(obs, env0)
            out = ServeOut(
                stage_idx=jnp.where(
                    decided & valid, stage_idx, -1
                ).astype(_i32),
                job_idx=job.astype(_i32),
                num_exec=num_exec.astype(_i32),
                lgprob=lgprob,
                decided=decided & valid,
                done=jax.vmap(_lane_done)(ls2.env),
                reward=reward,
                dt=dt,
                wall_time=ls2.env.wall_time,
                health_mask=jnp.where(
                    was_done | ~valid, 0, hm
                ).astype(_i32),
                valid=valid,
                obs=rec_obs,
            )
            # padding slots (index C) drop instead of scattering the
            # clamped lane's speculative update back over a real session
            store2 = write_slot(store, slots, ls2, drop=True)
            if shard is not None:
                store2 = jax.lax.with_sharding_constraint(store2, shard)
        return store2, out

    return fn


def serve_decide_ring_fn(
    params: EnvParams,
    bank: WorkloadBank,
    policy_fn: Callable,
    knobs: dict[str, Any] | None = None,
    shard=None,
) -> Callable:
    """The ring-recording single-session program (ISSUE 18):
    `(store [C], ring, model_params, slot, sid, pver, key, force_stage,
    force_nexec, use_force) -> (store [C], ring, ServeOut)`.
    Runs the record-on decision body, but instead of returning the
    decision's `StoredObs` to the host it appends the full `RingRec`
    (stamped with the host-passed `sid` and params version `pver`, and
    the lane's own post-decision count as `seq`) into the donated
    device ring — the returned `ServeOut` carries `obs=None`, i.e. the
    same host-visible payload as the record-OFF program, so recording
    costs the dispatch path nothing. Both `store` and `ring` are meant
    to be donated at compile time; `sid`/`pver` are ordinary i32
    runtime arguments (fixed avals — no recompiles as sessions and
    parameter versions churn)."""
    base = serve_decide_fn(params, bank, policy_fn, knobs, shard,
                           record=True)

    def fn(store: LoopState, ring: TrajRing, model_params, slot, sid,
           pver, key, force_stage, force_nexec, use_force):
        with annotate("serve/decide_ring"):
            store2, out = base(store, model_params, slot, key,
                               force_stage, force_nexec, use_force)
            rec = RingRec(
                sid=jnp.asarray(sid, _i32),
                seq=store2.decisions[slot].astype(_i32),
                params_version=jnp.asarray(pver, _i32),
                stage_idx=out.stage_idx,
                job_idx=out.job_idx,
                num_exec=out.num_exec,
                lgprob=out.lgprob,
                reward=out.reward,
                dt=out.dt,
                wall_time=out.wall_time,
                done=out.done,
                health_mask=out.health_mask,
                obs=out.obs,
            )
            ring2 = ring_append(ring, rec, out.decided)
        return store2, ring2, out.replace(obs=None)

    return fn


def serve_decide_batch_ring_fn(
    params: EnvParams,
    bank: WorkloadBank,
    batch_policy_fn: Callable,
    batch: int,
    knobs: dict[str, Any] | None = None,
    shard=None,
) -> Callable:
    """The ring-recording micro-batched program (ISSUE 18):
    `(store [C], ring, model_params, slots [K], sids [K], pver, key) ->
    (store [C], ring, ServeOut-of-[K])`.
    Record-on decision body, one masked batched ring append (padding
    and no-decision lanes drop), `ServeOut.obs=None` — the host-visible
    output matches the record-OFF batch program. `pver` is a scalar:
    every decision of a batch reads the SAME parameter version (the
    no-torn-reads contract), so one stamp broadcasts across the
    batch's ring records."""
    base = serve_decide_batch_fn(params, bank, batch_policy_fn, batch,
                                 knobs, shard, record=True)

    def fn(store: LoopState, ring: TrajRing, model_params, slots, sids,
           pver, key):
        with annotate("serve/decide_batch_ring"):
            store2, out = base(store, model_params, slots, key)
            C = store2.mode.shape[0]
            idx = jnp.minimum(slots, C - 1)
            rec = RingRec(
                sid=sids.astype(_i32),
                seq=store2.decisions[idx].astype(_i32),
                params_version=jnp.broadcast_to(
                    jnp.asarray(pver, _i32), slots.shape
                ),
                stage_idx=out.stage_idx,
                job_idx=out.job_idx,
                num_exec=out.num_exec,
                lgprob=out.lgprob,
                reward=out.reward,
                dt=out.dt,
                wall_time=out.wall_time,
                done=out.done,
                health_mask=out.health_mask,
                obs=out.obs,
            )
            ring2 = ring_append(ring, rec, out.decided)
        return store2, ring2, out.replace(obs=None)

    return fn


def aot_compile(fn: Callable, *abstract_args, donate_store: bool = True,
                donate_ring: bool = False):
    """`jax.jit(fn).lower(...).compile()` with the store (arg 0)
    donated. Returns `(compiled, secs)` — the compile wall time is the
    cold-start figure the latency bench records. The compiled
    executable bypasses the jit dispatch cache entirely: no tracing,
    no cache lookup, no recompile can happen on the warm path. With
    `donate_ring` (the ring programs, ISSUE 18) argument 1 — the
    trajectory ring — is donated too, so the in-JIT append updates the
    ring in place."""
    t0 = time.perf_counter()
    dn = (0,) if donate_store else ()
    if donate_ring:
        dn = dn + (1,)
    jitted = jax.jit(fn, donate_argnums=dn)
    compiled = jitted.lower(*abstract_args).compile()
    return compiled, time.perf_counter() - t0


def abstract_like(tree, keep_sharding: bool = False):
    """ShapeDtypeStructs of a concrete pytree — the `.lower()` argument
    spec (lowering never needs the store's values, only its shapes).
    With `keep_sharding` (the dp-sharded store, ISSUE 13), each leaf's
    concrete `.sharding` rides the struct, so the AOT lowering bakes
    the store's mesh layout into the executable — donation included —
    instead of compiling a single-device program and resharding on
    every call."""
    def one(a):
        kw = {}
        if keep_sharding and getattr(a, "sharding", None) is not None:
            kw["sharding"] = a.sharding
        return jax.ShapeDtypeStruct(
            jnp.shape(a), jnp.result_type(a), **kw
        )

    return jax.tree_util.tree_map(one, tree)


# ---------------------------------------------------------------------------
# analysis-registry builders (sparksched_tpu/analysis): the serve
# programs as (callable, abstract args) at audit shapes, so their eqn
# and temp-byte budgets are CI-pinned like the other registered
# programs. Audit store capacity / batch width are small (shapes only
# scale buffer sizes, not equation counts) but both Decima score
# branches (compact + full-width fallback) are in the audited program
# via the scaled job_bucket, matching the decima_* registry entries.
# ---------------------------------------------------------------------------

SERVE_AUDIT_CAPACITY = 8
SERVE_AUDIT_BATCH = 4
# ISSUE 15: the GROUP-shaped store program — the audit store split
# into 2 slot groups, i.e. the same serve_decide_batch function
# lowered at the [capacity/2] group width the pipelined store
# compiles. Groups are a host-side routing construct: the traced
# program must be IDENTICAL in structure to the ungrouped one (only
# buffer widths change), and the registry pin proves it stays that
# way — grouping adds zero equations, zero gathers, zero scatters.
SERVE_AUDIT_GROUPS = 2
# ISSUE 18: audit ring depth for the ring-variant record programs. Like
# capacity/batch it only scales buffer widths (the append is one masked
# scatter regardless of R), so a small ring keeps the audit cheap while
# the eqn/gather/scatter pins stay representative.
SERVE_AUDIT_RING = 16


def serve_callables(
    capacity: int = SERVE_AUDIT_CAPACITY,
    batch: int = SERVE_AUDIT_BATCH,
) -> dict[str, tuple[Callable, tuple]]:
    """`serve_decide` / `serve_decide_batch` under the shared audit
    config (analysis/jaxpr_audit.py:audit_setup), as
    (callable, abstract args)."""
    from ..analysis.jaxpr_audit import (
        _shipped_agent_kwargs,
        audit_setup,
    )
    from ..env.flat_loop import init_loop_state
    from ..schedulers.decima import DecimaScheduler

    params, bank, state = audit_setup()
    sched = DecimaScheduler(
        num_executors=params.num_executors, job_bucket=8,
        **_shipped_agent_kwargs(),
    )
    pol, bpol = sched.serve_param_policies(deterministic=True)
    key = jax.eval_shape(lambda: jax.random.PRNGKey(0))
    ls1 = jax.eval_shape(init_loop_state, state)
    store = jax.tree_util.tree_map(
        lambda l: jax.ShapeDtypeStruct(
            (capacity,) + tuple(l.shape), l.dtype
        ),
        ls1,
    )
    # the model parameters as an abstract argument (ISSUE 14: weights
    # are a runtime argument of the compiled serve programs, which is
    # the whole hot-swap mechanism)
    mp = jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(
            jnp.shape(a), jnp.result_type(a)
        ),
        sched.params,
    )
    i32 = jax.ShapeDtypeStruct((), jnp.int32)
    b = jax.ShapeDtypeStruct((), jnp.bool_)
    slots = jax.ShapeDtypeStruct((batch,), jnp.int32)
    # ISSUE 13: the dp-sharded store variant joins the registry. The
    # sharding constraint is part of the traced program (one
    # sharding_constraint eqn per store leaf at entry and exit), so
    # the audited jaxpr IS the sharded configuration — eqn counts are
    # mesh-size-invariant (the mesh is a lowering parameter, not an
    # equation), so the pin holds on the 1-device analysis CLI and the
    # 8-virtual-device test mesh alike. The mesh size is clamped to a
    # DIVISOR of the audit capacity: the [capacity]-wide store axis
    # cannot shard over more (or non-dividing) devices, and the audit
    # must trace on any host topology, not just the measured 1/8.
    import math

    from ..parallel import lane_sharding, make_mesh

    dp = math.gcd(len(jax.devices()), capacity)
    shard = lane_sharding(make_mesh(dp))
    return {
        "serve_decide": (
            serve_decide_fn(params, bank, pol),
            (store, mp, i32, key, i32, i32, b),
        ),
        "serve_decide_batch": (
            serve_decide_batch_fn(params, bank, bpol, batch),
            (store, mp, slots, key),
        ),
        "serve_decide_batch_sharded": (
            serve_decide_batch_fn(
                params, bank, bpol, batch, shard=shard
            ),
            (store, mp, slots, key),
        ),
        # ISSUE 15: the group-shaped program the pipelined store
        # compiles — serve_decide_batch at the [capacity/groups]
        # group width. Same function, smaller store axis: the pin
        # proves grouping is pure host-side routing (eqn/gather/
        # scatter counts identical to serve_decide_batch; only the
        # temp-byte budget shrinks with the store axis).
        "serve_decide_batch_group": (
            serve_decide_batch_fn(params, bank, bpol, batch),
            (
                jax.tree_util.tree_map(
                    lambda l: jax.ShapeDtypeStruct(
                        (capacity // SERVE_AUDIT_GROUPS,)
                        + tuple(l.shape[1:]),
                        l.dtype,
                    ),
                    store,
                ),
                mp, slots, key,
            ),
        ),
        # ISSUE 14: the record-on variants the online trajectory path
        # compiles (`SessionStore(record=True)`). Budgeted separately
        # so (a) the recording cost is visible and capped, and (b) the
        # record-off programs above prove the off path is structurally
        # unchanged (byte-identical re-pin).
        "serve_decide_record": (
            serve_decide_fn(params, bank, pol, record=True),
            (store, mp, i32, key, i32, i32, b),
        ),
        "serve_decide_batch_record": (
            serve_decide_batch_fn(
                params, bank, bpol, batch, record=True
            ),
            (store, mp, slots, key),
        ),
        # ISSUE 18: the ring-recording variants (`SessionStore(
        # record=True, ring=R)`) — the record body plus ONE masked ring
        # append. Budgeted separately so the append's scatter cost is
        # visible and capped, while the record-off AND plain record-on
        # pins above prove both existing paths are structurally
        # untouched by the ring machinery.
        "serve_decide_record_ring": (
            serve_decide_ring_fn(params, bank, pol),
            (
                store,
                jax.eval_shape(
                    lambda: init_ring(SERVE_AUDIT_RING, params, state)
                ),
                mp, i32, i32, i32, key, i32, i32, b,
            ),
        ),
        "serve_decide_batch_record_ring": (
            serve_decide_batch_ring_fn(params, bank, bpol, batch),
            (
                store,
                jax.eval_shape(
                    lambda: init_ring(SERVE_AUDIT_RING, params, state)
                ),
                mp, slots, slots, i32, key,
            ),
        ),
    }
