"""Open-loop load generation for the decision-serving stack
(ISSUE 11).

Every serving number before this module was CLOSED-loop: the bench
issued the next request only after the previous reply, so the measured
"latency" could never show queueing — a server that takes 10 ms per
decision looks identical at any demand. Production traffic is OPEN
loop: arrivals come from the world on their own clock, and when
offered load exceeds capacity the queue (and the tail) grows without
bound. The goodput@SLO bench (`bench_decima.bench_serve_scale`) needs
that behavior on purpose, so this generator:

- precomputes a SEEDED, deterministic arrival schedule — a list of
  (arrival_time_s, tenant) pairs — from one of two processes:
  `poisson` (exponential inter-arrivals at the offered rate) or
  `mmpp` (a 2-state Markov-modulated Poisson process: a base state
  and a burst state whose rate is `burst_factor` x base, exponential
  dwell times, parameterized so the LONG-RUN mean rate equals the
  offered rate — the bursty/heavy-tailed arrival shape the workload
  bank's schedulers will face);
- drives a `SessionStore` + `MicroBatcher` against the wall clock,
  NEVER back-pressured: a request's latency is measured from its
  SCHEDULED arrival time, so time spent waiting because the server
  (or the driving loop) was busy counts against the server, exactly
  as a queueing model demands;
- keeps per-request state O(in-flight) and the latency distribution
  in a `StreamingHistogram` (O(buckets)), so million-request runs
  don't turn the measurement layer into the memory hog; `slo_ms` is
  counted exactly during the run (good = replied within the SLO,
  measured from scheduled arrival).

Sessions: one live session per tenant; a session that finishes its
episode (or trips the health sentinel and is quarantined) is rotated
— closed and re-created with a fresh deterministic seed — so an
open-loop run can outlive any single episode. Rotation, quarantine
and capacity-rejection counts ride the summary and the shared
`MetricsRegistry`.
"""

from __future__ import annotations

import time
from typing import Any

import numpy as np

from ..obs.metrics import StreamingHistogram

ARRIVAL_PROCESSES = ("poisson", "mmpp")


def _poisson_times(rate_rps: float, n: int, rng) -> np.ndarray:
    return np.cumsum(rng.exponential(1.0 / rate_rps, size=n))


def _mmpp_times(
    rate_rps: float,
    n: int,
    rng,
    burst_factor: float,
    burst_fraction: float,
    burst_dwell_s: float,
) -> np.ndarray:
    """2-state MMPP with long-run mean rate == `rate_rps`: the chain
    spends `burst_fraction` of time in the burst state at
    `burst_factor` x the base rate. Inter-arrival draws are memoryless,
    so resampling the wait when the modulating chain switches states
    is exact, not an approximation."""
    if not 0.0 < burst_fraction < 1.0:
        raise ValueError(
            f"burst_fraction must be in (0, 1), got {burst_fraction}"
        )
    if burst_factor <= 1.0:
        raise ValueError(
            f"burst_factor must be > 1 (else use poisson), got "
            f"{burst_factor}"
        )
    base = rate_rps / (1.0 - burst_fraction
                       + burst_fraction * burst_factor)
    rates = (base, base * burst_factor)
    dwell = (
        burst_dwell_s * (1.0 - burst_fraction) / burst_fraction,
        burst_dwell_s,
    )
    out = np.empty(n, dtype=np.float64)
    t, k, state = 0.0, 0, 0
    t_switch = rng.exponential(dwell[0])
    while k < n:
        dt = rng.exponential(1.0 / rates[state])
        if t + dt >= t_switch:
            t = t_switch
            state ^= 1
            t_switch = t + rng.exponential(dwell[state])
            continue
        t += dt
        out[k] = t
        k += 1
    return out


def generate_arrivals(
    rate_rps: float,
    num_requests: int,
    num_tenants: int,
    *,
    process: str = "poisson",
    seed: int = 0,
    burst_factor: float = 8.0,
    burst_fraction: float = 0.1,
    burst_dwell_s: float = 0.5,
) -> list[tuple[float, int]]:
    """The deterministic open-loop schedule: `num_requests`
    (arrival_time_s, tenant) pairs at offered load `rate_rps` over
    `num_tenants` tenants (uniform tenant assignment). Same arguments
    => identical schedule, byte for byte — the generator is the
    experiment's seed, not a source of run-to-run noise."""
    if rate_rps <= 0 or num_requests <= 0 or num_tenants <= 0:
        raise ValueError(
            f"need positive rate/requests/tenants, got {rate_rps}/"
            f"{num_requests}/{num_tenants}"
        )
    if process not in ARRIVAL_PROCESSES:
        raise ValueError(
            f"unknown arrival process {process!r}; known: "
            f"{ARRIVAL_PROCESSES}"
        )
    rng = np.random.default_rng(seed)
    if process == "poisson":
        times = _poisson_times(rate_rps, num_requests, rng)
    else:
        times = _mmpp_times(
            rate_rps, num_requests, rng, burst_factor, burst_fraction,
            burst_dwell_s,
        )
    tenants = rng.integers(0, num_tenants, size=num_requests)
    return [(float(t), int(w)) for t, w in zip(times, tenants)]


def run_open_loop(
    store,
    batcher,
    arrivals: list[tuple[float, int]],
    *,
    slo_ms: float | None = None,
    session_seed: int = 10_000,
    keep_samples: bool = True,
    poll_sleep_s: float = 2e-4,
    on_poll=None,
) -> dict[str, Any]:
    """Drive the schedule against the wall clock and return the run
    summary. One session per tenant is created up front (rotated on
    episode end / quarantine); requests whose scheduled arrival has
    passed are submitted immediately — arrivals are never delayed by
    outstanding replies (open loop). Latency is measured from the
    SCHEDULED arrival to the harvest of the reply, in ms.

    `batcher` is either front (ISSUE 13): the driver speaks only
    `submit`/`poll`/`flush`/`pending`. Under the `ContinuousBatcher`
    the per-iteration `poll()` IS the continuous-batching engine —
    each call re-fills the width-K slot with whatever arrived while
    the previous compiled call was in flight; under the `MicroBatcher`
    it is the linger-window check. The summary records which front ran
    (`front`), so paired A/B rows are self-describing.

    Returns a dict with exact counters (`requests` scheduled ==
    `completed` served + `capacity_rejections` turned away at submit;
    `errors` and `good` partition within `completed`), the throughput
    view (`offered_rps`, `achieved_rps` = served replies/s,
    `goodput_rps` = SLO-satisfying replies per second of run), the
    latency `hist` over the served set (a StreamingHistogram;
    summarize with `.summary("_ms")`), session-rotation accounting
    (generation-guarded: a stale end-of-episode reply from a rotated
    session never closes its replacement), and —
    when `keep_samples` — the raw per-request `samples_ms` for exact
    percentiles (turn it off for million-request runs; the histogram
    alone is O(buckets)).

    `on_poll` (ISSUE 14): an optional zero-arg callable invoked once
    per driver iteration, BETWEEN compiled serve calls — the hook the
    online loop hangs `ParamBus.pump` on, so hot param swaps land
    mid-run under live traffic without the driver knowing about
    them.

    Client mode (ISSUE 16): `store` and `batcher` are duck-typed, so
    passing a `serve.server.ServeClient` as BOTH drives a remote
    server over the wire with the SAME loop — latency still clocked
    from SCHEDULED arrival, so network + queueing time counts against
    the server exactly like host time does in-process. The summary's
    `reconcile` block pins the rejection accounting either way:
    requests == served + rejected, with the per-request
    `serve_requests_rejected` counter delta equal to the summary's
    rejection count and distinct from the store's per-create
    `serve_capacity_rejections`."""
    n = len(arrivals)
    if n == 0:
        raise ValueError("empty arrival schedule")
    if getattr(batcher, "front_name", "") == "http":
        # push-based wire front: poll() is a no-op and replies are
        # resolved by the client's worker threads, so a hot 0.2 ms
        # poll loop would only steal (possibly the single) core from
        # them — in-process fronts keep the tight loop because their
        # poll() IS the batching engine
        poll_sleep_s = max(poll_sleep_s, 2e-3)
    # reconciliation baselines (ISSUE 16): the registry may be shared
    # across runs, so the double-count check below is on DELTAS
    metrics = getattr(store, "metrics", None)
    rej0 = (0 if metrics is None
            else metrics.counters.get("serve_requests_rejected", 0))
    stats = getattr(store, "stats", None)
    cap0 = (stats.get("serve_capacity_rejections", 0)
            if isinstance(stats, dict) else None)
    tenants = sorted({w for _, w in arrivals})
    sessions: dict[int, int | None] = {
        w: store.create(seed=session_seed + w) for w in tenants
    }
    # per-tenant session GENERATION: slot ids are reused by the store
    # (create() takes the first free slot, usually the one a rotation
    # just freed), so a stale done-reply can carry the same sid as the
    # fresh session — only a reply from the CURRENT generation may
    # rotate, or the second of two queued end-of-episode replies would
    # close the zero-decision replacement
    gen: dict[int, int] = {w: 0 for w in tenants}
    hist = StreamingHistogram()
    samples: list[float] | None = [] if keep_samples else None
    inflight: list[tuple[int, int, float, Any]] = []
    i = completed = errors = good = rotations = rejections = 0
    t0 = time.perf_counter()
    try:
        while i < n or inflight:
            now = time.perf_counter() - t0
            while i < n and arrivals[i][0] <= now:
                sched_t, tenant = arrivals[i]
                i += 1
                sid = sessions[tenant]
                if sid is None:
                    # tenant lost its slot to capacity exhaustion; the
                    # request is REJECTED (its own counter — never
                    # `completed`, so achieved_rps and the latency
                    # blocks describe only actually-served decisions).
                    # Mirrored into the registry per REQUEST
                    # (`serve_requests_rejected`) — distinct from the
                    # store's `serve_capacity_rejections`, which
                    # counts failed create() calls, one per rotation
                    # attempt, not turned-away traffic.
                    rejections += 1
                    m = getattr(store, "metrics", None)
                    if m is not None:
                        m.counter("serve_requests_rejected")
                    continue
                inflight.append(
                    (tenant, gen[tenant], sched_t, batcher.submit(sid))
                )
            if on_poll is not None:
                on_poll()
            batcher.poll()
            if i >= n and batcher.pending:
                # the schedule is exhausted: no co-riders are coming,
                # so drain rather than wait out the linger window
                batcher.flush()
            still: list[tuple[int, int, float, Any]] = []
            for tenant, g, sched_t, tk in inflight:
                if not tk.ready:
                    still.append((tenant, g, sched_t, tk))
                    continue
                lat_ms = ((time.perf_counter() - t0) - sched_t) * 1e3
                completed += 1
                hist.add(lat_ms)
                if samples is not None:
                    samples.append(lat_ms)
                if tk.error is not None:
                    errors += 1
                    continue
                if slo_ms is None or lat_ms <= slo_ms:
                    good += 1
                r = tk.result
                # rotate only on a CURRENT-generation reply (slot ids
                # are reused, so comparing sids is not enough): a
                # stale done-reply from the pre-rotation episode must
                # not close the replacement (or a None slot)
                if (r.done or r.health_mask) and g == gen[tenant]:
                    store.close(tk.session_id)
                    rotations += 1
                    gen[tenant] += 1
                    try:
                        sessions[tenant] = store.create(
                            seed=session_seed + tenant
                            + 1000 * rotations
                        )
                    except RuntimeError:
                        sessions[tenant] = None
            inflight = still
            if not inflight and i < n:
                dt = arrivals[i][0] - (time.perf_counter() - t0)
                if dt > 0:
                    time.sleep(min(dt, 0.01))
            elif inflight:
                time.sleep(poll_sleep_s)
    finally:
        for sid in sessions.values():
            if sid is not None:
                store.close(sid)
    makespan = time.perf_counter() - t0
    # the ISSUE-16 reconciliation pin for the PR-11 double-count
    # hazard flagged above: every scheduled request is EITHER served
    # (`completed`, which `errors`/`good` partition) or turned away
    # (`rejections`) — never both, never neither — and the per-request
    # `serve_requests_rejected` counter moves in lockstep with the
    # summary while staying DISTINCT from the store's per-create
    # `serve_capacity_rejections` (whose unit is failed create()
    # calls: rotation attempts, not turned-away traffic).
    assert completed + rejections == n, (
        f"open-loop accounting broke: {completed} served + "
        f"{rejections} rejected != {n} scheduled"
    )
    reconcile: dict[str, Any] = {
        "requests": n,
        "served": completed,
        "rejected_requests": rejections,
        "distinct_counters": True,
    }
    if metrics is not None:
        rej_delta = (
            metrics.counters.get("serve_requests_rejected", 0) - rej0
        )
        assert rej_delta == rejections, (
            f"serve_requests_rejected moved by {rej_delta} but the "
            f"run rejected {rejections} request(s) — the per-request "
            "and per-create rejection counters have been conflated"
        )
        reconcile["serve_requests_rejected"] = rej_delta
    if cap0 is not None:
        reconcile["serve_capacity_rejections"] = (
            stats.get("serve_capacity_rejections", 0) - cap0
        )
    out: dict[str, Any] = {
        "requests": n,
        "front": getattr(batcher, "front_name", "unknown"),
        "completed": completed,
        "errors": errors,
        "good": good,
        "slo_ms": slo_ms,
        "tenants": len(tenants),
        "makespan_s": round(makespan, 4),
        "offered_rps": round(n / max(arrivals[-1][0], 1e-9), 2),
        "achieved_rps": round(completed / makespan, 2),
        "goodput_rps": round(good / makespan, 2),
        "session_rotations": rotations,
        "capacity_rejections": rejections,
        "reconcile": reconcile,
        "hist": hist,
    }
    if samples is not None:
        out["samples_ms"] = samples
    return out
