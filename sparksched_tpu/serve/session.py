"""Persistent decision-serving sessions over the AOT programs.

A `SessionStore` holds one live on-device cluster (`LoopState`) per
tenant in a fixed-capacity [C]-stacked store, and serves decisions
through the two ahead-of-time-compiled programs built at construction
(`serve/aot.py`): the unbatched single-session path and the width-K
micro-batched path. The store buffer is DONATED to every serve call,
so steady-state decisions update the [C] cluster states in place —
zero store-sized allocation, zero tracing, zero recompiles after the
constructor's warmup call.

Session lifecycle (`create` / `step` / `decide` / `close`):

- `create(seed)` resets a fresh episode into a free slot and returns
  its session id. Slot writes go through a small compiled updater, not
  the serve programs.
- `decide(sid)` serves one policy decision for the session and drains
  its cluster to the next decision point (the serving unit of work);
  `step(sid, stage_idx, num_exec)` applies a CALLER-chosen action
  through the same compiled program (the forced-action select), for
  tenants that want the simulator without the policy.
- every served decision carries the in-JIT health sentinel mask
  (env/health.py, ISSUE 9): a non-zero mask QUARANTINES the session —
  it is never served again (decide/step raise `SessionQuarantined`),
  but its slot is only reclaimed by an explicit `close`. A poisoned
  cluster state must not keep emitting decisions.
- `close(sid)` frees the slot.

`MicroBatcher` is the batching front: requests accumulate until either
`max_batch` sessions are pending or the oldest request has waited
`linger_ms` (the bounded linger window), then flush as ONE compiled
width-K call; a flush of a single pending request falls back to the
unbatched AOT path (no padded batch work for a lone request). It is
deliberately synchronous — `submit` returns a `Ticket`, and `poll()`
(or a full batch) flushes — so a network front can drive it from any
event loop and the latency bench can measure it deterministically.

Observability (ISSUE 11): both layers are instrumented, OFF by
default and zero-cost off — `metrics` (an `obs.metrics.MetricsRegistry`
or None) receives the admission/occupancy view ORCA-style schedulers
need (queue depth at flush, batch K-fill, per-request linger waits,
flush reason size|linger|forced, quarantine and capacity-rejection
counters), and `trace=True` stamps a Dapper-style per-request span
walk (trace id minted at `Ticket` creation; submit -> batch_admit ->
dispatch -> device_compute -> scatter_back -> reply) emitted as
runlog `trace` records and bridged into the `annotate("serve/flush")`
named scope. All instrumentation is host-side: the compiled serve
programs are untouched (the analysis registry pins their jaxprs
byte-identical with instrumentation off).

Config surface: the top-level `serve:` YAML block
(`config.SERVE_KEYS`), validated loudly like the `health:`/`chaos:`
blocks — a typo'd knob must fail, not silently serve with defaults.
"""

from __future__ import annotations

import time
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ..config import SERVE_KEYS, EnvParams
from ..env import core
from ..env.flat_loop import init_loop_state
from ..obs.tracing import RequestTrace, annotate
from ..workload.bank import WorkloadBank
from .aot import (
    SERVE_KNOBS,
    abstract_like,
    aot_compile,
    serve_decide_batch_fn,
    serve_decide_fn,
)

_i32 = jnp.int32


class SessionError(KeyError):
    """Unknown / closed session id."""


class SessionQuarantined(RuntimeError):
    """The session's health sentinel tripped; it will not be served."""


class ServeResult:
    """Host-side view of one served decision (plain numpy scalars)."""

    __slots__ = (
        "session_id", "stage_idx", "job_idx", "num_exec", "lgprob",
        "decided", "done", "reward", "dt", "wall_time", "health_mask",
        "batched",
    )

    def __init__(self, session_id: int, out, i: int | None,
                 batched: bool) -> None:
        pick = (lambda a: a[i]) if i is not None else (lambda a: a)
        self.session_id = session_id
        self.stage_idx = int(pick(out.stage_idx))
        self.job_idx = int(pick(out.job_idx))
        self.num_exec = int(pick(out.num_exec))
        self.lgprob = float(pick(out.lgprob))
        self.decided = bool(pick(out.decided))
        self.done = bool(pick(out.done))
        self.reward = float(pick(out.reward))
        self.dt = float(pick(out.dt))
        self.wall_time = float(pick(out.wall_time))
        self.health_mask = int(pick(out.health_mask))
        self.batched = batched

    def to_dict(self) -> dict[str, Any]:
        return {k: getattr(self, k) for k in self.__slots__}


class SessionStore:
    """Fixed-capacity persistent session store over donated AOT
    programs. Not thread-safe by design: a serving front owns one
    store per worker (the donation discipline — exactly one live
    reference to the store buffer — does not compose with concurrent
    mutation)."""

    def __init__(
        self,
        params: EnvParams,
        bank: WorkloadBank,
        scheduler,
        capacity: int = 64,
        *,
        max_batch: int = 8,
        deterministic: bool = True,
        donate: bool = True,
        seed: int = 0,
        knobs: dict[str, Any] | None = None,
        runlog=None,
        tb_writer=None,
        metrics=None,
        trace: bool = False,
    ) -> None:
        if not 1 <= max_batch <= capacity:
            raise ValueError(
                f"max_batch={max_batch} must be in [1, capacity="
                f"{capacity}]"
            )
        self.params = params
        self.bank = bank
        self.capacity = int(capacity)
        self.max_batch = int(max_batch)
        self.donate = bool(donate)
        self.knobs = SERVE_KNOBS | (knobs or {})
        self._runlog = runlog
        self._tb = tb_writer
        # ISSUE 11 instrumentation — both PUBLIC and reassignable so a
        # bench can swap a fresh registry per measurement window
        # without recompiling the store. `trace=True` makes every
        # compiled call record its phase boundaries into `last_spans`
        # (dispatch / device_compute / scatter_back perf_counter
        # stamps) at the cost of one extra host sync per call.
        self.metrics = metrics
        self.trace = bool(trace)
        self.last_spans: dict[str, float] | None = None
        self._base_key = jax.random.PRNGKey(seed)
        self._calls = 0

        pol, bpol = scheduler.serve_policies(
            deterministic=deterministic
        )
        self._reset1 = jax.jit(
            lambda k: init_loop_state(core.reset(params, bank, k))
        )
        self._write_slot = jax.jit(
            lambda store, sid, ls: jax.tree_util.tree_map(
                lambda s, v: s.at[sid].set(v), store, ls
            ),
            donate_argnums=(0,) if donate else (),
        )

        # the [C] store starts as C copies of one dummy reset episode;
        # create() overwrites a slot with its own seeded reset
        ls0 = self._reset1(jax.random.fold_in(self._base_key, 2**19))
        store = jax.tree_util.tree_map(
            lambda a: jnp.broadcast_to(
                a, (self.capacity,) + a.shape
            ).copy(),
            ls0,
        )

        # ---- AOT lowering + compile (the cold start) ----
        fn1 = serve_decide_fn(params, bank, pol, self.knobs)
        fnk = serve_decide_batch_fn(
            params, bank, bpol, self.max_batch, self.knobs
        )
        st_abs = abstract_like(store)
        key = abstract_like(self._base_key)
        i32 = jax.ShapeDtypeStruct((), jnp.int32)
        b = jax.ShapeDtypeStruct((), jnp.bool_)
        slots = jax.ShapeDtypeStruct((self.max_batch,), jnp.int32)
        self._c1, secs1 = aot_compile(
            fn1, st_abs, i32, key, i32, i32, b, donate_store=donate
        )
        self._ck, secsk = aot_compile(
            fnk, st_abs, slots, key, donate_store=donate
        )
        self.compile_secs = {"decide": secs1, "decide_batch": secsk}

        # host-side slot bookkeeping
        self._live = np.zeros(self.capacity, bool)
        self._quarantined = np.zeros(self.capacity, bool)
        self.stats = {
            "serve_decisions": 0,
            "serve_batched_decisions": 0,
            "serve_batch_calls": 0,
            "serve_quarantines": 0,
            "serve_sessions_live": 0,
            "serve_capacity_rejections": 0,
        }

        # ---- warmup: one call per program, so the warm path never
        # pays a first-dispatch (executable load, buffer layout) cost.
        # Slot contents are dummies here; create() re-seeds slots.
        self._store = store
        t0 = time.perf_counter()
        self._store, _ = self._call1(
            _i32(0), _i32(-1), _i32(0), jnp.bool_(False)
        )
        self._store, _ = self._callk(
            jnp.full((self.max_batch,), self.capacity, _i32)
        )
        jax.block_until_ready(self._store.mode)
        self.warmup_secs = time.perf_counter() - t0
        # reset warmup's mutation of slot 0 back to a clean dummy
        self._store = self._write_slot(self._store, _i32(0), ls0)

    # -- compiled-call plumbing -------------------------------------------

    def _next_key(self) -> jax.Array:
        self._calls += 1
        return jax.random.fold_in(self._base_key, self._calls)

    def _call1(self, sid, fstage, fnexec, use_force):
        return self._c1(
            self._store, sid, self._next_key(), fstage, fnexec,
            use_force,
        )

    def _callk(self, slots):
        return self._ck(self._store, slots, self._next_key())

    def _served(self, call):
        """Run one compiled serve call and hand back host-side outputs.
        With `trace` on, additionally stamp the call's phase
        boundaries into `last_spans`: `dispatch` (the compiled call is
        issued), `device_compute` (its outputs are ready),
        `scatter_back` (the host holds concrete values). The off path
        is byte-identical to the uninstrumented round-13 behavior."""
        if not self.trace:
            # stale spans from a previously-traced window must never
            # merge into a later request's trace
            self.last_spans = None
            self._store, out = call()
            return jax.device_get(out)
        t_dispatch = time.perf_counter()
        self._store, out = call()
        jax.block_until_ready(out)
        t_compute = time.perf_counter()
        out = jax.device_get(out)
        t_scatter = time.perf_counter()
        self.last_spans = {
            "dispatch": t_dispatch,
            "device_compute": t_compute,
            "scatter_back": t_scatter,
        }
        return out

    # -- session lifecycle -------------------------------------------------

    def create(self, seed: int | None = None) -> int:
        """Reset a fresh episode into a free slot; returns the session
        id. Raises `RuntimeError` when the store is full."""
        free = np.flatnonzero(~self._live & ~self._quarantined)
        if free.size == 0:
            self.stats["serve_capacity_rejections"] += 1
            if self.metrics is not None:
                self.metrics.counter("serve_capacity_rejections")
            raise RuntimeError(
                f"session store full ({self.capacity} slots live or "
                "quarantined); close sessions first"
            )
        sid = int(free[0])
        k = (
            jax.random.fold_in(self._base_key, 2**20 + sid)
            if seed is None
            else jax.random.PRNGKey(seed)
        )
        self._store = self._write_slot(
            self._store, _i32(sid), self._reset1(k)
        )
        self._live[sid] = True
        self.stats["serve_sessions_live"] = int(self._live.sum())
        return sid

    def close(self, sid: int) -> None:
        self._check_sid(sid, allow_quarantined=True)
        self._live[sid] = False
        self._quarantined[sid] = False
        self.stats["serve_sessions_live"] = int(self._live.sum())

    def _check_sid(self, sid: int, allow_quarantined: bool = False
                   ) -> None:
        if not 0 <= sid < self.capacity or not self._live[sid]:
            raise SessionError(f"unknown session id {sid}")
        if self._quarantined[sid] and not allow_quarantined:
            raise SessionQuarantined(
                f"session {sid} is quarantined (health sentinel "
                "tripped); close it and create a fresh one"
            )

    def _apply_health(self, sid: int, mask: int) -> None:
        if mask == 0:
            return
        self._quarantined[sid] = True
        self.stats["serve_quarantines"] += 1
        if self.metrics is not None:
            self.metrics.counter("serve_quarantines")
        if self._runlog is not None:
            self._runlog.health(
                mask, session_id=sid, action="quarantine",
                origin="serve",
            )

    # -- serving -----------------------------------------------------------

    def decide(self, sid: int) -> ServeResult:
        """One policy decision on the unbatched AOT path."""
        self._check_sid(sid)
        out = self._served(lambda: self._call1(
            _i32(sid), _i32(-1), _i32(0), jnp.bool_(False)
        ))
        res = ServeResult(sid, out, None, batched=False)
        self._apply_health(sid, res.health_mask)
        self.stats["serve_decisions"] += 1
        return res

    def step(self, sid: int, stage_idx: int, num_exec: int
             ) -> ServeResult:
        """Apply a CALLER-chosen action (same compiled program; the
        policy's pick is overridden by the forced-action select)."""
        self._check_sid(sid)
        out = self._served(lambda: self._call1(
            _i32(sid), _i32(stage_idx), _i32(num_exec),
            jnp.bool_(True),
        ))
        res = ServeResult(sid, out, None, batched=False)
        self._apply_health(sid, res.health_mask)
        self.stats["serve_decisions"] += 1
        return res

    def decide_batch(self, sids: list[int]) -> list[ServeResult]:
        """Up to `max_batch` sessions in ONE compiled call. A single
        session falls back to the unbatched path (no padded batch work
        for a lone request)."""
        if not sids:
            return []
        if len(sids) > self.max_batch:
            raise ValueError(
                f"{len(sids)} sessions > max_batch={self.max_batch}"
            )
        for sid in sids:
            self._check_sid(sid)
        if len(set(sids)) != len(sids):
            raise ValueError("duplicate session ids in one batch")
        if len(sids) == 1:
            return [self.decide(sids[0])]
        slots = np.full(self.max_batch, self.capacity, np.int32)
        slots[: len(sids)] = sids
        out = self._served(lambda: self._callk(jnp.asarray(slots)))
        results = []
        for i, sid in enumerate(sids):
            res = ServeResult(sid, out, i, batched=True)
            self._apply_health(sid, res.health_mask)
            results.append(res)
        self.stats["serve_decisions"] += len(sids)
        self.stats["serve_batched_decisions"] += len(sids)
        self.stats["serve_batch_calls"] += 1
        return results

    # -- observability -----------------------------------------------------

    def log_stats(self, iteration: int, extra: dict[str, Any] | None
                  = None) -> None:
        """Per-iteration `serve_*` scalars: runlog JSONL + the
        TensorBoard mirror when a writer was given — the serving analog
        of the trainer's `_write_stats` (identical keys/values both
        sinks)."""
        stats = dict(self.stats) | (extra or {})
        if self._runlog is not None:
            self._runlog.scalars(iteration, stats)
        if self._tb is not None:
            for k, v in stats.items():
                self._tb.add_scalar(k, v, iteration)


class Ticket:
    """One pending micro-batch request. At flush either `result` is
    set, or `error` holds the per-request failure (a quarantined or
    closed session fails ITS ticket only — co-batched requests are
    still served). Under an instrumented front, `trace` carries the
    request's `RequestTrace` (the trace id is minted HERE, at request
    creation, so every later span hangs off one id)."""

    __slots__ = ("session_id", "submitted_at", "result", "error",
                 "trace")

    def __init__(self, session_id: int, traced: bool = False) -> None:
        self.session_id = session_id
        self.submitted_at = time.perf_counter()
        self.result: ServeResult | None = None
        self.error: Exception | None = None
        self.trace: RequestTrace | None = None
        if traced:
            self.trace = RequestTrace()
            self.trace.stamp("submit", self.submitted_at)

    @property
    def ready(self) -> bool:
        return self.result is not None or self.error is not None


class MicroBatcher:
    """Bounded-linger micro-batching front over a `SessionStore`.

    `submit(sid)` enqueues and flushes immediately when `max_batch`
    requests are pending; `poll()` flushes when the OLDEST pending
    request has waited `linger_ms` (the bounded linger window — the
    worst case a request can be delayed in exchange for batching);
    `flush()` forces. A lone pending request always takes the
    unbatched AOT path (SessionStore.decide_batch's fallback).

    Instrumentation (ISSUE 11, off by default): `metrics` receives
    queue depth at flush, batch occupancy (K-fill), per-request linger
    waits, flush-reason counters (`serve_flush_size|linger|forced`)
    and per-span latency histograms; `trace=True` mints a
    `RequestTrace` per ticket and — when `runlog` is given — emits one
    runlog `trace` record per served request, with the store-level
    device spans merged in when the store also has `trace` on."""

    def __init__(self, store: SessionStore, linger_ms: float = 1.0,
                 *, metrics=None, runlog=None, trace: bool = False
                 ) -> None:
        self.store = store
        self.linger_s = float(linger_ms) / 1e3
        self.metrics = metrics
        self.runlog = runlog
        self.trace = bool(trace)
        self._pending: list[Ticket] = []

    def submit(self, sid: int) -> Ticket:
        t = Ticket(sid, traced=self.trace)
        self._pending.append(t)
        if len(self._pending) >= self.store.max_batch:
            self.flush(reason="size")
        return t

    @property
    def pending(self) -> int:
        """Requests queued but not yet flushed — the public view
        drivers (serve/loadgen.py) use to decide an end-of-schedule
        drain, so they never couple to the queue's representation."""
        return len(self._pending)

    def poll(self) -> bool:
        """Flush if the linger window expired; True when a flush ran."""
        if not self._pending:
            return False
        waited = time.perf_counter() - self._pending[0].submitted_at
        if waited >= self.linger_s:
            self.flush(reason="linger")
            return True
        return False

    def _finish(self, t: Ticket) -> None:
        """Resolve one ticket's instrumentation: merge the store's
        device spans, stamp `reply`, emit the runlog `trace` record,
        and feed the per-span histograms."""
        m = self.metrics
        if m is not None:
            m.counter("serve_requests_total")
            if t.error is not None:
                m.counter("serve_request_errors")
        if t.trace is None:
            return
        spans = self.store.last_spans
        if t.error is None and spans is not None:
            t.trace.spans.update(spans)
        t.trace.stamp("reply")
        if m is not None:
            s = t.trace.spans
            segs = (
                ("serve_span_queue_ms", "submit", "batch_admit"),
                ("serve_span_device_ms", "dispatch", "device_compute"),
                ("serve_span_scatter_ms", "device_compute",
                 "scatter_back"),
                ("serve_span_total_ms", "submit", "reply"),
            )
            for name, a, b in segs:
                if a in s and b in s:
                    m.observe(name, (s[b] - s[a]) * 1e3)
        if self.runlog is not None:
            self.runlog.trace(
                t.trace.trace_id, t.trace.offsets_ms(),
                session_id=t.session_id,
                error=None if t.error is None
                else type(t.error).__name__,
            )

    def flush(self, reason: str = "forced") -> None:
        """Serve every pending ticket. Duplicate session ids in one
        window ride SUCCESSIVE batch calls (one session id per batch —
        decide_batch rejects duplicates, and two decisions for one
        session are sequential by definition). A request that cannot
        be served (quarantined / closed session) fails its OWN ticket
        via `Ticket.error`; the rest of the batch is still served —
        no ticket is ever left unresolved."""
        m = self.metrics
        first = True
        while self._pending:
            if m is not None:
                # the flush reason counts ONCE per flush event; the
                # admission views count per batch call so successive
                # duplicate-draining batches stay visible
                if first:
                    m.counter(f"serve_flush_{reason}")
                m.observe("serve_queue_depth", len(self._pending))
            first = False
            batch: list[Ticket] = []
            seen: set[int] = set()
            rest: list[Ticket] = []
            for t in self._pending:
                if (len(batch) < self.store.max_batch
                        and t.session_id not in seen):
                    batch.append(t)
                    seen.add(t.session_id)
                else:
                    rest.append(t)
            self._pending = rest  # each pass consumes >= 1 ticket
            now = time.perf_counter()
            for t in batch:
                if m is not None:
                    m.observe(
                        "serve_linger_wait_ms",
                        (now - t.submitted_at) * 1e3,
                    )
                if t.trace is not None:
                    t.trace.stamp("batch_admit", now)
            if m is not None:
                m.observe("serve_batch_occupancy", len(batch))
            try:
                if self.trace:
                    with annotate("serve/flush"):
                        results = self.store.decide_batch(
                            [t.session_id for t in batch]
                        )
                else:
                    results = self.store.decide_batch(
                        [t.session_id for t in batch]
                    )
            except Exception:
                # a bad session id poisons the whole batch call;
                # re-serve one by one so only the offender fails
                for t in batch:
                    try:
                        t.result = self.store.decide(t.session_id)
                    except Exception as e:
                        t.error = e
                    self._finish(t)
                continue
            for t, r in zip(batch, results):
                t.result = r
                self._finish(t)


def store_from_config(
    cfg: dict[str, Any] | None,
    params: EnvParams,
    bank: WorkloadBank,
    scheduler,
    **overrides: Any,
) -> SessionStore:
    """Build a `SessionStore` from a top-level `serve:` YAML block.
    Unknown keys fail loudly (the `health:`/`chaos:` block contract —
    config.SERVE_KEYS is the single source of truth for the surface).
    Returns the store; `linger_ms` is consumed by the caller building
    a `MicroBatcher` (it is a front knob, not a store knob)."""
    cfg = dict(cfg or {})
    unknown = set(cfg) - set(SERVE_KEYS)
    if unknown:
        raise ValueError(
            f"unknown serve: config key(s) {sorted(unknown)}; known "
            f"keys: {sorted(SERVE_KEYS)}"
        )
    kw: dict[str, Any] = {
        "capacity": int(cfg.get("capacity", 64)),
        "max_batch": int(cfg.get("max_batch", 8)),
        "deterministic": bool(cfg.get("deterministic", True)),
        "donate": bool(cfg.get("donate", True)),
        "seed": int(cfg.get("seed", 0)),
        # ISSUE 11 instrumentation keys: `trace: true` turns on the
        # per-call span stamps; `metrics: true` attaches a fresh
        # MetricsRegistry (callers needing a shared registry pass one
        # via overrides)
        "trace": bool(cfg.get("trace", False)),
    }
    if cfg.get("metrics", False):
        from ..obs.metrics import MetricsRegistry

        kw["metrics"] = MetricsRegistry()
    kw.update(overrides)
    return SessionStore(params, bank, scheduler, **kw)
